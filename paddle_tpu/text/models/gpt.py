"""GPT-style decoder-only LM — the flagship model (BASELINE configs 3/5).

Built from paddle_tpu.nn layers so the distributed strategies (TP layer
placements, ZeRO sharding specs, pipeline stages) apply uniformly. Causal
attention routes through F.scaled_dot_product_attention → pallas flash
kernel on TPU.
"""
import os

import jax
import jax.numpy as jnp

from ... import nn
from ...framework.core import Tensor, no_grad_guard
from ...nn import functional as F
from ...tensor import manipulation as M

__all__ = ['GPTConfig', 'GPTModel', 'GPTForCausalLM']


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=None,
                 max_position_embeddings=1024, dropout=0.1,
                 layer_norm_epsilon=1e-5, initializer_range=0.02,
                 use_rmsnorm=False, tie_word_embeddings=True,
                 recompute=False, num_experts=0, moe_capacity_factor=1.5,
                 fused_loss=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.dropout = dropout
        self.layer_norm_epsilon = layer_norm_epsilon
        self.initializer_range = initializer_range
        self.use_rmsnorm = use_rmsnorm
        self.tie_word_embeddings = tie_word_embeddings
        self.recompute = recompute
        # num_experts > 0 swaps each block's MLP for an expert-parallel
        # SwitchMoE (incubate/moe.py) routed over the 'ep' mesh axis
        self.num_experts = num_experts
        self.moe_capacity_factor = moe_capacity_factor
        # fused_loss=True changes the TRAINING forward contract: forward()
        # (without caches) returns the final hidden states and loss()
        # fuses head matmul + CE via F.linear_cross_entropy, never
        # materializing [batch*seq, vocab] logits (ops/fused_ce.py).
        # Decode/generate paths (caches=...) still produce logits.
        # loss() tells the two apart by the trailing dim, so the fusion
        # is only safe when vocab and hidden differ — refuse the
        # ambiguous configuration up front rather than misroute a real
        # logits tensor into the fused head at runtime.
        if fused_loss and vocab_size == hidden_size:
            raise ValueError(
                'fused_loss=True requires vocab_size != hidden_size '
                '(loss() distinguishes hidden states from logits by '
                'their trailing dimension); got both = %d' % vocab_size)
        self.fused_loss = fused_loss

    @staticmethod
    def gpt2_small():
        return GPTConfig()

    @staticmethod
    def bert_base_equiv():
        return GPTConfig(vocab_size=30522, hidden_size=768, num_layers=12,
                         num_heads=12, max_position_embeddings=512)

    @staticmethod
    def gpt3_13b():
        return GPTConfig(vocab_size=50304, hidden_size=5120, num_layers=40,
                         num_heads=40, max_position_embeddings=2048)


class GPTStaticCache:
    """Fixed-shape KV cache for decode: preallocated [B, max_len, H, Dh]
    buffers plus the current valid length. Every decode step writes via
    dynamic_update_slice and attends with a validity mask, so all steps
    share one set of shapes — per-op executables are reused across
    tokens, and the step is jit-able without retracing per token (a
    concat-growing cache changes shape every step). Inference-only: the
    buffer writes bypass the autograd tape."""

    def __init__(self, k_buf, v_buf, length, fresh=False):
        self.k = k_buf
        self.v = v_buf
        self.length = length  # scalar int32 (traced under jit)
        # python-level marker: no write has happened yet, so a multi-
        # token prefill may use the plain causal fast path (flash/
        # blockwise-eligible) instead of masked attention over the
        # zero-padded buffer
        self.fresh = fresh

    @staticmethod
    def empty(batch, max_len, num_heads, head_dim, dtype='float32'):
        import paddle_tpu as paddle
        k = paddle.zeros([batch, max_len, num_heads, head_dim], dtype)
        v = paddle.zeros([batch, max_len, num_heads, head_dim], dtype)
        return GPTStaticCache(k, v, jnp.zeros((), jnp.int32), fresh=True)


# registered as a pytree so cache stacks cross jit boundaries (the jitted
# decode step takes and returns them); `fresh` is static aux data — a
# fresh (prefill) cache and a decode cache intentionally trace differently
def _cache_flatten(c):
    return (_raw_leaf(c.k), _raw_leaf(c.v), c.length), c.fresh


def _tensor_leaf(x):
    # flatten/unflatten must round-trip jax's internal placeholder
    # leaves (e.g. ArgInfo during lower()/AOT) untouched; only real
    # arrays and tracers get the Tensor wrapper back
    return Tensor(x) if isinstance(x, jnp.ndarray) else x


def _raw_leaf(x):
    return getattr(x, '_data', x)


def _cache_unflatten(fresh, children):
    k, v, length = children
    return GPTStaticCache(_tensor_leaf(k), _tensor_leaf(v), length,
                          fresh=fresh)


jax.tree_util.register_pytree_node(GPTStaticCache, _cache_flatten,
                                   _cache_unflatten)


class GPTSlotCache:
    """Slot-batched KV cache for continuous-batching serving
    (paddle_tpu.serving): fixed [num_slots, max_len, H, Dh] buffers plus a
    PER-SLOT valid length vector [num_slots] (int32). Unlike
    GPTStaticCache's single scalar length, each slot advances
    independently — the compiled decode step keeps ONE static shape no
    matter which requests currently occupy which slots, so request
    admit/retire churn never retraces.

    Invariants (the serving engine owns them):
      - attention WRITES the new k/v at each slot's current length but
        does NOT advance `lengths` — the engine advances them after the
        full forward (every layer must write at the same pre-step
        offsets, and padded prefill tails advance by the VALID token
        count, not the chunk size);
      - buffer rows at/beyond a slot's length are garbage (padded prefill
        tails, stale rows from a retired occupant) and are never attended:
        the validity mask allows k positions <= the query's absolute
        position, which never exceeds lengths + n - 1;
      - overflow is guarded at admission (host side): a traced lengths
        vector cannot be range-checked in-program.
    """

    def __init__(self, k_buf, v_buf, lengths):
        self.k = k_buf
        self.v = v_buf
        self.lengths = lengths  # [num_slots] int32 (traced under jit)

    @staticmethod
    def empty(num_slots, max_len, num_heads, head_dim, dtype='float32'):
        import paddle_tpu as paddle
        k = paddle.zeros([num_slots, max_len, num_heads, head_dim], dtype)
        v = paddle.zeros([num_slots, max_len, num_heads, head_dim], dtype)
        return GPTSlotCache(k, v, jnp.zeros((num_slots,), jnp.int32))


def _slot_cache_flatten(c):
    return (_raw_leaf(c.k), _raw_leaf(c.v), c.lengths), None


def _slot_cache_unflatten(_, children):
    k, v, lengths = children
    return GPTSlotCache(_tensor_leaf(k), _tensor_leaf(v), lengths)


jax.tree_util.register_pytree_node(GPTSlotCache, _slot_cache_flatten,
                                   _slot_cache_unflatten)


class GPTPagedCache:
    """Block/page-granular KV cache for the paged serving engine
    (paddle_tpu/serving/paged_engine.py): per layer, a physical pool of
    `[num_pages, page_size, H, Dh]` K/V pages plus a per-sequence
    BLOCK TABLE `[B, max_blocks]` (int32 page ids) and per-sequence
    valid lengths `[B]`. A sequence's logical row j lives in pool row
    `block_tables[s, j // page_size] * page_size + j % page_size`, so
    sequences of different lengths occupy only the pages they need and
    several sequences may map leading blocks to the SAME physical page
    (prefix sharing).

    Invariants (owned by the serving engine / PagedScheduler):
      - block-table entry 0 is the reserved SCRATCH page: never handed
        to a real block, so garbage writes from frozen/retired rows land
        there (or on the row's own dead rows past its length) and are
        unreachable — shared pages are only ever FULL, immutable blocks
        strictly below every writer's length, so no real write can touch
        them;
      - like GPTSlotCache, attention writes this step's K/V at each
        row's current length but does NOT advance `lengths`; the engine
        advances them host-side after the full forward;
      - pool rows at/beyond a sequence's length are garbage and never
        attended (the validity mask allows logical positions <= the
        query's absolute position only);
      - capacity/ownership is guarded host-side at admission: a traced
        block table cannot be range-checked in-program (writes are
        clipped to the pool as a memory-safety net; a clipped write is
        by construction a garbage write).
    """

    def __init__(self, k_pool, v_pool, block_tables, lengths):
        self.k = k_pool          # [num_pages, page_size, H, Dh]
        self.v = v_pool
        self.block_tables = block_tables  # [B, max_blocks] int32
        self.lengths = lengths            # [B] int32 (traced under jit)

    @staticmethod
    def empty(num_pages, page_size, max_blocks, batch, num_heads,
              head_dim, dtype='float32'):
        import paddle_tpu as paddle
        k = paddle.zeros([num_pages, page_size, num_heads, head_dim], dtype)
        v = paddle.zeros([num_pages, page_size, num_heads, head_dim], dtype)
        return GPTPagedCache(k, v,
                             jnp.zeros((batch, max_blocks), jnp.int32),
                             jnp.zeros((batch,), jnp.int32))


def _paged_cache_flatten(c):
    return (_raw_leaf(c.k), _raw_leaf(c.v), c.block_tables, c.lengths), None


def _paged_cache_unflatten(_, children):
    k, v, bt, lengths = children
    return GPTPagedCache(_tensor_leaf(k), _tensor_leaf(v), bt, lengths)


jax.tree_util.register_pytree_node(GPTPagedCache, _paged_cache_flatten,
                                   _paged_cache_unflatten)


def _cache_get(cache, key, build, cap=8):
    """Bounded per-model compiled-executable cache: a serving loop with
    naturally varying prompt/generation shapes must not pin one XLA
    executable per distinct shape forever. Eviction happens only on a
    miss (FIFO, before insert) — a hit must never evict, least of all
    the entry being requested."""
    hit = cache.get(key)
    if hit is not None:
        return hit
    while len(cache) >= cap:
        cache.pop(next(iter(cache)))
    val = cache[key] = build()
    return val


class GPTAttention(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        self.hidden_size = config.hidden_size
        self.qkv_proj = nn.Linear(config.hidden_size, 3 * config.hidden_size)
        self.out_proj = nn.Linear(config.hidden_size, config.hidden_size)
        self.dropout = config.dropout
        # TP placement hints consumed by distributed/strategy.py
        self.qkv_proj.weight.placement = (None, 'mp')
        self.qkv_proj.bias.placement = ('mp',)
        self.out_proj.weight.placement = ('mp', None)
        # bench A/B knob, latched at construction: reading the env per
        # forward call costs in eager mode and lets a mid-process env
        # change mix layouts across traced vs eager executions
        self._qkv_split_last = os.environ.get('PADDLE_TPU_QKV_SPLIT') == 'last'

    def forward(self, x, cache=None):
        b, n = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        if self._qkv_split_last:
            # experimental A/B (bench rung): slice the packed minor axis
            # at 128-aligned offsets instead of reshaping to 5-D and
            # slicing the middle axis. The round-4 profile shows
            # ~5 ms/step of [b,n,3,h,d] layout-copy traffic on the
            # middle-axis path; whether last-axis slicing removes it is
            # measured in-window, not assumed. NOT the default: under
            # tensor parallelism the packed 2304 axis is mp-sharded and
            # q/k/v offsets straddle shard boundaries (the [3, heads, d]
            # head-axis slicing keeps each shard self-contained).
            hs = self.hidden_size
            hd = [b, n, self.num_heads, self.head_dim]
            q = M.reshape(qkv[:, :, :hs], hd)
            k = M.reshape(qkv[:, :, hs:2 * hs], hd)
            v = M.reshape(qkv[:, :, 2 * hs:], hd)
        else:
            qkv = M.reshape(qkv, [b, n, 3, self.num_heads, self.head_dim])
            q = qkv[:, :, 0]
            k = qkv[:, :, 1]
            v = qkv[:, :, 2]
        if isinstance(cache, GPTPagedCache):
            import jax
            from ...framework.core import is_grad_enabled
            if self.training and is_grad_enabled():
                raise RuntimeError(
                    'GPTPagedCache is an inference-only serving path — '
                    'call model.eval() / no_grad')
            num_pages, page = cache.k.shape[0], cache.k.shape[1]
            nb = cache.block_tables.shape[1]
            L = nb * page                       # logical capacity per row
            t = cache.lengths                   # [B] per-row write offsets
            bt = cache.block_tables             # [B, nb] physical page ids
            if not isinstance(t, jax.core.Tracer) and \
                    int(jnp.max(t)) + n > L:
                # (under jit lengths are traced; the serving engine guards
                # capacity at admission instead)
                raise ValueError(
                    'paged cache overflow: max row length %d + %d new '
                    'tokens > capacity %d' % (int(jnp.max(t)), n, L))
            # write: token i of row s sits at absolute position t[s]+i;
            # its pool row is bt[s, pos // page] * page + pos % page.
            # ONE flat scatter covers all rows; clipping keeps garbage
            # from frozen rows inside the pool (it lands on the scratch
            # page or the row's own dead rows — both unreachable, see
            # GPTPagedCache invariants)
            pos = jnp.clip(t[:, None] + jnp.arange(n)[None, :], 0, L - 1)
            rows = (jnp.take_along_axis(bt, pos // page, axis=1) * page
                    + pos % page)                                # [B, n]
            flat_shape = (num_pages * page,) + tuple(cache.k.shape[2:])
            kf = cache.k._data.reshape(flat_shape)
            vf = cache.v._data.reshape(flat_shape)
            idx = rows.reshape(-1)
            kf = kf.at[idx].set(k._data.astype(kf.dtype).reshape(
                (b * n,) + flat_shape[1:]))
            vf = vf.at[idx].set(v._data.astype(vf.dtype).reshape(
                (b * n,) + flat_shape[1:]))
            new_cache = GPTPagedCache(
                Tensor(kf.reshape(cache.k._data.shape)),
                Tensor(vf.reshape(cache.v._data.shape)), bt, t)
            # read: gather each row's logical [L] view through its block
            # table (this step's rows included — written above), then the
            # same masked attention as the slot path. The gather
            # materializes [B, L, H, Dh] activations; persistent memory
            # stays page-granular, which is where the density win lives.
            view = (bt[:, :, None] * page
                    + jnp.arange(page)[None, None, :]).reshape(b, L)
            kg = jnp.take(kf, view, axis=0)                # [B, L, H, Dh]
            vg = jnp.take(vf, view, axis=0)
            # per-row validity mask: query row i of sequence s sits at
            # absolute position t[s]+i and sees logical positions <= it
            qpos = t[:, None] + jnp.arange(n)[None, :]           # [B, n]
            allow = qpos[:, :, None] >= jnp.arange(L)[None, None, :]
            mask = Tensor(jnp.where(allow, 0.0, -1e9)[:, None].astype(
                jnp.float32))                                # [B,1,n,L]
            out = F.scaled_dot_product_attention(
                q, Tensor(kg), Tensor(vg), attn_mask=mask,
                is_causal=False, dropout_p=0.0)
            out = M.reshape(out, [b, n, self.hidden_size])
            return self.out_proj(out), new_cache
        if isinstance(cache, GPTSlotCache):
            import jax
            from ...framework.core import is_grad_enabled
            if self.training and is_grad_enabled():
                raise RuntimeError(
                    'GPTSlotCache is an inference-only serving path — '
                    'call model.eval() / no_grad')
            max_len = cache.k.shape[1]
            t = cache.lengths  # [S] per-slot write offsets
            if not isinstance(t, jax.core.Tracer) and \
                    int(jnp.max(t)) + n > max_len:
                # (under jit lengths are traced; the serving engine guards
                # capacity at admission instead)
                raise ValueError(
                    'slot cache overflow: max slot length %d + %d new '
                    'tokens > capacity %d' % (int(jnp.max(t)), n, max_len))

            # per-slot write at that slot's current length. vmap over the
            # slot axis: each slot's [max_len, H, Dh] buffer takes this
            # step's [n, H, Dh] rows at its own offset — one fused
            # scatter, same shapes every step regardless of occupancy.
            def _write(buf, new, off):
                return jax.lax.dynamic_update_slice(buf, new, (off, 0, 0))
            k_buf = jax.vmap(_write)(
                cache.k._data, k._data.astype(cache.k._data.dtype), t)
            v_buf = jax.vmap(_write)(
                cache.v._data, v._data.astype(cache.v._data.dtype), t)
            # lengths intentionally NOT advanced here: every layer must
            # write at the same pre-step offsets; the engine advances
            # them once per step (by the VALID token count for padded
            # prefill chunks)
            new_cache = GPTSlotCache(Tensor(k_buf), Tensor(v_buf), t)
            # per-slot validity mask: query row i of slot s sits at
            # absolute position t[s]+i and sees buffer slots j <= t[s]+i
            qpos = t[:, None] + jnp.arange(n)[None, :]           # [S, n]
            kpos = jnp.arange(max_len)                           # [m]
            allow = qpos[:, :, None] >= kpos[None, None, :]      # [S, n, m]
            mask = Tensor(jnp.where(allow, 0.0, -1e9)[:, None].astype(
                jnp.float32))                                    # [S,1,n,m]
            out = F.scaled_dot_product_attention(
                q, Tensor(k_buf), Tensor(v_buf), attn_mask=mask,
                is_causal=False, dropout_p=0.0)
            out = M.reshape(out, [b, n, self.hidden_size])
            return self.out_proj(out), new_cache
        if isinstance(cache, GPTStaticCache):
            import jax
            from ...framework.core import is_grad_enabled
            if self.training and is_grad_enabled():
                # the buffer writes bypass the autograd tape: training
                # through this path would silently drop the k/v grads
                raise RuntimeError(
                    'GPTStaticCache is an inference-only decode path — '
                    'call model.eval() / no_grad / generate()')
            max_len = cache.k.shape[1]
            if not isinstance(cache.length, jax.core.Tracer) and \
                    int(cache.length) + n > max_len:
                # (under jit the length is a tracer; generate() guards
                # the budget up front instead)
                raise ValueError(
                    'static cache overflow: length %d + %d new tokens > '
                    'capacity %d' % (int(cache.length), n, max_len))
            t = cache.length
            k_buf = jax.lax.dynamic_update_slice(
                cache.k._data, k._data.astype(cache.k._data.dtype),
                (0, t, 0, 0))
            v_buf = jax.lax.dynamic_update_slice(
                cache.v._data, v._data.astype(cache.v._data.dtype),
                (0, t, 0, 0))
            new_cache = GPTStaticCache(Tensor(k_buf), Tensor(v_buf), t + n)
            if cache.fresh and n > 1:
                # prefill on an untouched cache: plain causal attention
                # over the chunk itself (flash/blockwise-eligible) — the
                # masked full-buffer attention below would pay quadratic
                # cost against max_len-n empty slots
                out = F.scaled_dot_product_attention(
                    q, k, v, is_causal=True, dropout_p=0.0)
                out = M.reshape(out, [b, n, self.hidden_size])
                return self.out_proj(out), new_cache
            # validity mask over the fixed buffer: query row i (absolute
            # position t+i) sees buffer slots j <= t+i
            qpos = t + jnp.arange(n)
            kpos = jnp.arange(max_len)
            allow = qpos[:, None] >= kpos[None, :]
            mask = Tensor(jnp.where(allow, 0.0, -1e9)[None, None].astype(
                jnp.float32))
            out = F.scaled_dot_product_attention(
                q, Tensor(k_buf), Tensor(v_buf), attn_mask=mask,
                is_causal=False, dropout_p=0.0)
            out = M.reshape(out, [b, n, self.hidden_size])
            return self.out_proj(out), new_cache
        if cache is not None:
            k = M.concat([cache[0], k], axis=1)
            v = M.concat([cache[1], v], axis=1)
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=self.dropout if self.training else 0.0)
        out = M.reshape(out, [b, n, self.hidden_size])
        out = self.out_proj(out)
        if cache is not None:
            return out, (k, v)
        return out


class GPTMLP(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.fc_in = nn.Linear(config.hidden_size, config.intermediate_size)
        self.fc_out = nn.Linear(config.intermediate_size, config.hidden_size)
        self.dropout = nn.Dropout(config.dropout)
        self.fc_in.weight.placement = (None, 'mp')
        self.fc_in.bias.placement = ('mp',)
        self.fc_out.weight.placement = ('mp', None)

    def forward(self, x):
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x),
                                               approximate=True)))


class GPTBlock(nn.Layer):
    def __init__(self, config):
        super().__init__()
        Norm = nn.RMSNorm if config.use_rmsnorm else nn.LayerNorm
        self.ln_1 = Norm(config.hidden_size, config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = Norm(config.hidden_size, config.layer_norm_epsilon)
        if getattr(config, 'num_experts', 0):
            from ...incubate.moe import SwitchMoE
            self.mlp = SwitchMoE(
                config.hidden_size, config.intermediate_size,
                num_experts=config.num_experts,
                capacity_factor=config.moe_capacity_factor)
        else:
            self.mlp = GPTMLP(config)

    def forward(self, x, cache=None):
        if cache is not None:
            a, new_cache = self.attn(self.ln_1(x), cache=cache)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, new_cache
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTModel(nn.Layer):
    def __init__(self, config=None, **kwargs):
        super().__init__()
        config = config or GPTConfig(**kwargs)
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size)
        self.drop = nn.Dropout(config.dropout)
        self.h = nn.LayerList([GPTBlock(config)
                               for _ in range(config.num_layers)])
        Norm = nn.RMSNorm if config.use_rmsnorm else nn.LayerNorm
        self.ln_f = Norm(config.hidden_size, config.layer_norm_epsilon)
        self.wte.weight.placement = ('mp', None)
        self._recompute = config.recompute

    def enable_recompute(self, flag=True):
        """Per-block activation recompute (reference RecomputeOptimizer
        checkpoint segments = transformer blocks)."""
        self._recompute = flag

    def forward(self, input_ids, position_ids=None, caches=None):
        n = input_ids.shape[1]
        if position_ids is None:
            if caches is not None and isinstance(
                    caches[0], (GPTSlotCache, GPTPagedCache)):
                # serving: each slot's positions continue from ITS length
                position_ids = Tensor(
                    caches[0].lengths[:, None] + jnp.arange(n)[None, :])
            elif caches is not None:
                # decode: positions continue from the cached length
                position_ids = Tensor(
                    (caches[0].length + jnp.arange(n))[None, :])
            else:
                position_ids = Tensor(
                    jnp.arange(n, dtype=jnp.int64)[None, :])
        x = self.drop(self.wte(input_ids) + self.wpe(position_ids))
        if caches is not None:
            new_caches = []
            for block, c in zip(self.h, caches):
                x, nc = block(x, cache=c)
                new_caches.append(nc)
            return self.ln_f(x), new_caches
        from ...distributed import pipeline as pp_mod
        pp_state = pp_mod.pipeline_state()
        moe = getattr(self.config, 'num_experts', 0) > 0
        if moe and self.training and (pp_state is not None
                                      or self._recompute):
            # the aux-loss tracer would escape the checkpoint/shard_map
            # trace it was created in
            raise NotImplementedError(
                'MoE blocks do not compose with recompute or pipeline '
                'parallelism yet (aux-loss routing) — disable one of them')
        if pp_state is not None and self.training:
            # GPipe over the 'pp' mesh axis: embeddings above and ln_f/head
            # below stay replicated over pp; the block stack is the
            # pipelined region (stage params pp-sharded, ppermute rotation)
            x = pp_mod.pipeline_blocks(self.h, x, pp_state)
        elif self._recompute and self.training:
            from ...distributed.fleet.utils import recompute as _remat
            for block in self.h:
                x = _remat(block, x)
        else:
            for block in self.h:
                x = block(x)
        # collect MoE load-balancing aux losses for GPTForCausalLM.loss
        # (training only: eval perplexity must not carry the balance term)
        self._moe_aux = None
        if self.training:
            for block in self.h:
                aux = getattr(block.mlp, 'aux_loss', None)
                if aux is not None:
                    term = aux * block.mlp.aux_loss_weight
                    self._moe_aux = term if self._moe_aux is None \
                        else self._moe_aux + term
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    def __init__(self, config=None, **kwargs):
        super().__init__()
        config = config or GPTConfig(**kwargs)
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, position_ids=None, caches=None):
        if caches is not None:
            hidden, new_caches = self.gpt(input_ids, position_ids,
                                          caches=caches)
        else:
            hidden = self.gpt(input_ids, position_ids)
            if getattr(self.config, 'fused_loss', False) and self.training:
                # fused-loss TRAINING contract: the head matmul lives
                # inside loss() (F.linear_cross_entropy) — returning
                # hidden here is what makes the fusion possible. Eval
                # and decode forwards keep producing logits.
                return hidden
        if self.lm_head is None:
            logits = F.linear(hidden,
                              M.transpose(self.gpt.wte.weight, [1, 0]))
        else:
            logits = self.lm_head(hidden)
        if caches is not None:
            return logits, new_caches
        return logits

    @no_grad_guard()
    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k=0, do_sample=False, seed=0):
        """Autoregressive generation with a STATIC-shape KV cache.

        TPU-native decode: the per-token step (forward + next-token
        pick) is ONE jitted program over fixed-size cache buffers
        (GPTStaticCache, a registered pytree) updated by
        dynamic_update_slice — identical shapes every token, so XLA
        traces and compiles the step once and the loop replays the
        executable. The reference ecosystem reaches this via PaddleNLP's
        decoding; the framework here provides it natively. Greedy by
        default; do_sample=True draws from softmax(logits/temperature)
        restricted to top_k (0 = full vocab).
        """
        import jax
        model = self
        was_training = self.training
        self.eval()
        try:
            ids = input_ids if isinstance(input_ids, Tensor) \
                else Tensor(jnp.asarray(input_ids))
            if max_new_tokens <= 0:
                return Tensor(ids._data.astype(jnp.int32))
            b, n0 = ids.shape[0], ids.shape[1]
            max_len = n0 + max_new_tokens
            if max_len > self.config.max_position_embeddings:
                raise ValueError(
                    'prompt %d + max_new_tokens %d exceeds '
                    'max_position_embeddings %d' %
                    (n0, max_new_tokens, self.config.max_position_embeddings))
            dtype = self.gpt.wte.weight.dtype
            caches = [GPTStaticCache.empty(
                b, max_len, self.config.num_heads,
                self.config.hidden_size // self.config.num_heads,
                dtype=str(dtype).replace('paddle.', ''))
                for _ in self.gpt.h]
            def pick(last_logits, key):
                lg = last_logits.astype(jnp.float32)
                if not do_sample:
                    return jnp.argmax(lg, axis=-1).astype(jnp.int32)
                lg = lg / max(float(temperature), 1e-6)
                if top_k:
                    kth = jnp.sort(lg, axis=-1)[:, -int(top_k)][:, None]
                    lg = jnp.where(lg >= kth, lg, -1e30)
                return jax.random.categorical(key, lg, axis=-1).astype(
                    jnp.int32)

            from ...framework import functional as _fm
            _params = _fm.extract_params(self)
            _bufs = _fm.extract_buffers(self)

            # prefill: ONE jitted pass over the prompt seeds the caches
            # (eager prefill would dispatch every op separately — dozens
            # of round-trips on a relayed accelerator)
            pre_cache = getattr(self, '_prefill_cache', None)
            if pre_cache is None:
                pre_cache = self._prefill_cache = {}

            def _build_prefill():
                def _prefill(p, bf, cs, ids_):
                    (lg, cs2), _ = _fm.functional_call(
                        self, p, bf, args=(Tensor(ids_),),
                        kwargs={'caches': cs}, training=False)
                    return lg[:, -1], cs2
                return jax.jit(_prefill)
            pre_jit = _cache_get(pre_cache, (b, n0, max_len), _build_prefill)
            last, caches = pre_jit(_params, _bufs, caches, ids._data)

            # the whole decode is ONE compiled program: a lax.scan whose
            # body is the static-shape cached step (params/buffers/caches
            # are pytree args; GPTStaticCache is a registered node). The
            # host dispatches once per generate() call, not once per
            # token — on a relayed/tunneled accelerator the per-token
            # dispatch toll dominates cached decode, the same lesson as
            # TrainStep.multi_step for training.
            func_mod = _fm
            params, bufs = _params, _bufs

            # one compiled executable per (generation length, prompt
            # shape, sampling config) — cached on the model so repeated
            # generate() calls replay it instead of re-jitting (a fresh
            # closure every call would defeat jit's identity-keyed cache)
            cache_key = (max_new_tokens, b, n0, bool(do_sample),
                         int(top_k), float(temperature))
            decode_cache = getattr(self, '_decode_cache', None)
            if decode_cache is None:
                decode_cache = self._decode_cache = {}

            def _build_decode():
                def _decode(p, bf, cs, first, key):
                    def body(carry, _):
                        cs, tok, key = carry
                        key, sub = jax.random.split(key)
                        (lg, new_cs), _ = func_mod.functional_call(
                            self, p, bf, args=(Tensor(tok),),
                            kwargs={'caches': cs}, training=False)
                        nxt = pick(lg[:, -1], sub)
                        return (new_cs, nxt[:, None], key), nxt

                    (_, _, _), toks = jax.lax.scan(
                        body, (cs, first, key), None,
                        length=max_new_tokens - 1)
                    return toks  # [steps, b]
                return jax.jit(_decode)
            decode_jit = _cache_get(decode_cache, cache_key, _build_decode)

            key = jax.random.PRNGKey(seed)
            out = [ids._data.astype(jnp.int32)]
            key, sub = jax.random.split(key)
            nxt = pick(last, sub)[:, None]
            out.append(nxt)
            if max_new_tokens > 1:
                toks = decode_jit(params, bufs, caches, nxt, key)
                out.append(jnp.transpose(toks, (1, 0)))
            return Tensor(jnp.concatenate(out, axis=1))
        finally:
            if was_training:
                self.train()

    def enable_recompute(self, flag=True):
        self.gpt.enable_recompute(flag)

    def pp_decompose(self, loss_fn=None):
        """(pre, blocks, post) split for the 1F1B pipeline schedule
        (distributed/pipeline_1f1b.py): pre = embeddings (stage 0),
        blocks = the homogeneous transformer stack (pp-sharded), post =
        ln_f + tied/untied head + token loss (last stage). Mirrors the
        reference PipelineTrainer program split where the loss lives in
        the last section (section_worker.cc). The tied wte weight appears
        in both pre and post — its grads combine via the schedule's psum.
        loss_fn(logits, labels) overrides self.loss so the train step's
        objective is honored."""
        gpt = self.gpt
        loss_fn = loss_fn or self.loss

        def pre(ids):
            n = ids.shape[1]
            pos = Tensor(jnp.arange(n, dtype=jnp.int32)[None, :])
            return gpt.drop(gpt.wte(ids) + gpt.wpe(pos))

        def post(x, labels):
            h = gpt.ln_f(x)
            if getattr(self.config, 'fused_loss', False):
                # last pipeline stage hands the HIDDEN state to loss_fn —
                # the same fused-loss contract the non-pipelined training
                # forward has (loss_fn routes through model.loss, which
                # fuses head+CE off the hidden input). Gating on loss_fn
                # identity would silently disable the fusion for any
                # wrapper lambda around model.loss.
                return loss_fn(h, labels)
            if self.lm_head is None:
                logits = F.linear(h, M.transpose(gpt.wte.weight, [1, 0]))
            else:
                logits = self.lm_head(h)
            return loss_fn(logits, labels)

        return pre, gpt.h, post

    def loss(self, logits, labels):
        if getattr(self.config, 'fused_loss', False) and self.training and \
                logits.shape[-1] == self.config.hidden_size:
            # fused TRAINING contract: `logits` is the final HIDDEN state
            # (forward's training gate); head matmul + CE fuse in one
            # chunked op. Both gates mirror forward's, so eval-path real
            # logits never misroute here even when vocab == hidden.
            if self.lm_head is None:
                ce = F.linear_cross_entropy(
                    logits, self.gpt.wte.weight, labels,
                    transpose_weight=True)
            else:
                ce = F.linear_cross_entropy(
                    logits, self.lm_head.weight, labels)
        else:
            b, n, v = logits.shape
            ce = F.cross_entropy(M.reshape(logits, [b * n, v]),
                                 M.reshape(labels, [b * n]))
        aux = getattr(self.gpt, '_moe_aux', None)
        self.gpt._moe_aux = None  # consume once — never stale across calls
        if aux is not None:
            ce = ce + aux
        return ce

    def num_params(self):
        import numpy as np
        return int(sum(np.prod(p.shape) for p in self.parameters()))

    def flops_per_token(self, seq_len=None):
        """Approximate fwd+bwd FLOPs/token (6N + attention quadratic term).

        The quadratic term scales with the ACTUAL sequence length; pass it
        explicitly when benching seq < max_position_embeddings, otherwise
        the MFU computed from this is inflated.
        """
        c = self.config
        if seq_len is None:
            seq_len = c.max_position_embeddings
        n_params = self.num_params()
        attn = 12 * c.num_layers * c.hidden_size * int(seq_len)
        return 6 * n_params + attn
