from .bert import BertModel, BertForSequenceClassification, BertForPretraining  # noqa: F401
from .gpt import GPTModel, GPTForCausalLM, GPTConfig  # noqa: F401
