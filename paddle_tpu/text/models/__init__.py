from .bert import (BertModel, BertForSequenceClassification,  # noqa: F401
                   BertForPretraining, ErnieModel,
                   ErnieForSequenceClassification, ErnieForPretraining,
                   ernie_1_0)
from .gpt import GPTModel, GPTForCausalLM, GPTConfig  # noqa: F401
