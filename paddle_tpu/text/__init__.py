"""paddle.text parity (reference: python/paddle/text/): NLP datasets + (ours)
a transformer LM model zoo used by the benchmarks."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from .datasets import *  # noqa: F401,F403
