"""Weight regularizers (reference: python/paddle/fluid/regularizer.py)."""

__all__ = ['L1Decay', 'L2Decay']


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def _append(self, grad, param):
        import jax.numpy as jnp
        return grad + self._coeff * jnp.sign(param)


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def _append(self, grad, param):
        return grad + self._coeff * param


L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay
