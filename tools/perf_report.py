"""Offline per-step performance report.

Joins the three perf-introspection artifacts a run leaves behind into
one text report, per config:

  * dryrun/driver telemetry snapshots (`telemetry_snapshot(N)[tag]:`
    lines, or a plain snapshot JSON) -> compile counts by stage,
    recompiles after warmup, step-phase means, straggler counts, and
    the cost-model gauges (MFU / intensity / roofline bound);
  * flight-recorder dumps (flight_*.json from FlightRecorder.dump) ->
    the top recompile events with their callsite + shape-signature
    attribution, and any straggler spans the ring caught;
  * bench capture JSONL (bench.py / bench_extra.py rows) -> the
    MFU / roofline / cold-vs-warm compile table;
  * a Chrome trace (profiler *.trace.json.gz or a host-span trace from
    monitor.tracing.spans_to_chrome) -> the device roofline summary via
    tools/profile_analysis when device ops are present, else a
    host-span time breakdown.

Every section is optional: pass what the run produced.

Usage:
    python tools/perf_report.py [--snapshot FILE|-] [--flight-dir DIR]
        [--bench CAPTURE.jsonl ...] [--trace PATH] [--top N]
"""
import argparse
import glob
import json
import os
import sys
import types

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# monitor/ is stdlib-only but the package __init__ pulls in jax — load
# the subpackage without the parent (the check_metrics_snapshot pattern)
if 'paddle_tpu' not in sys.modules:
    _pkg = types.ModuleType('paddle_tpu')
    _pkg.__path__ = [os.path.join(_REPO_ROOT, 'paddle_tpu')]
    sys.modules['paddle_tpu'] = _pkg

from paddle_tpu.monitor.telemetry import parse_snapshot_lines  # noqa: E402

__all__ = ['snapshot_perf', 'flight_spans', 'flight_recompiles',
           'bench_perf_rows', 'report', 'main']

# bench row fields that form the perf table (satellite keys first).
# data_wait_frac rides on the ingest rung's throughput row: a step loop
# whose input fraction creeps up is regressing even if examples/s holds.
_BENCH_COLS = ('compile_s_cold', 'compile_s_warm', 'recompiles',
               'mfu_est', 'arithmetic_intensity', 'roofline_bound',
               'data_wait_frac')

# data_wait share of the summed phase means above which a config is
# called out as input-bound in the snapshot section
_INPUT_BOUND_FRAC = 0.25


def _sample_value(fam, **labels):
    """Scalar value of the child matching `labels` (or the unlabeled
    child) in an export.to_dict family; None when absent."""
    for s in fam.get('samples', ()):
        if dict(s.get('labels') or {}) == labels:
            return s.get('value')
    return None


def _hist_stats(fam, **labels):
    """(count, mean) of the matching histogram child; None when empty."""
    for s in fam.get('samples', ()):
        if dict(s.get('labels') or {}) == labels:
            n = int(s.get('count') or 0)
            if not n:
                return None
            return n, float(s.get('sum') or 0.0) / n
    return None


def snapshot_perf(snap):
    """The perf block of one telemetry snapshot dict: {'compiles':
    {kind: (count, mean_s)}, 'recompiles', 'steps', 'stragglers',
    'phases': {phase: (count, mean_s)}, 'mfu_est', ...} — only the keys
    the snapshot actually carries."""
    out = {}
    fam = snap.get('perf_compiles_total')
    hist = snap.get('perf_compile_seconds')
    if fam:
        compiles = {}
        for s in fam.get('samples', ()):
            kind = (s.get('labels') or {}).get('kind')
            if kind is None or not s.get('value'):
                continue
            stats = _hist_stats(hist, kind=kind) if hist else None
            compiles[kind] = (int(s['value']),
                              stats[1] if stats else None)
        if compiles:
            out['compiles'] = compiles
    fam = snap.get('perf_recompiles_total')
    if fam is not None:
        out['recompiles'] = int(_sample_value(fam) or 0)
    for key, name in (('steps', 'perf_steps_total'),
                      ('stragglers', 'perf_stragglers_total')):
        fam = snap.get(name)
        if fam is not None:
            out[key] = int(_sample_value(fam) or 0)
    hist = snap.get('perf_step_phase_seconds')
    if hist:
        phases = {}
        for s in hist.get('samples', ()):
            phase = (s.get('labels') or {}).get('phase')
            n = int(s.get('count') or 0)
            if phase and n:
                phases[phase] = (n, float(s.get('sum') or 0.0) / n)
        if phases:
            out['phases'] = phases
    for key, name in (('mfu_est', 'perf_mfu_est'),
                      ('arithmetic_intensity',
                       'perf_arithmetic_intensity'),
                      ('roofline_bound', 'perf_roofline_bound')):
        fam = snap.get(name)
        val = _sample_value(fam) if fam else None
        if val:
            out[key] = val
    return out


def flight_spans(flight_dir):
    """Every span across the dir's flight_*.json dumps, deduplicated by
    span_id (consecutive dumps of one ring overlap heavily), paired
    with its dump metadata: [(span, {'file', 'reason'})], newest dump
    first so the dedup keeps the freshest copy."""
    out, seen = [], set()
    for path in sorted(glob.glob(os.path.join(flight_dir,
                                              'flight_*.json')),
                       reverse=True):
        try:
            with open(path, errors='replace') as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        meta = {'file': os.path.basename(path),
                'reason': payload.get('reason')}
        for span in payload.get('spans', ()):
            sid = span.get('span_id')
            if sid is not None and sid in seen:
                continue
            seen.add(sid)
            out.append((span, meta))
    return out


def flight_recompiles(flight_dir):
    """All perf.recompile / perf.straggler spans across the dir's
    flight_*.json dumps, newest dump first."""
    events = []
    for span, meta in flight_spans(flight_dir):
        if span.get('name') in ('perf.recompile', 'perf.straggler'):
            events.append({'file': meta['file'],
                           'reason': meta['reason'],
                           'name': span['name'],
                           'tags': span.get('tags') or {}})
    return events


def bench_perf_rows(paths):
    """Bench capture rows carrying at least one perf field."""
    rows = []
    for path in paths:
        try:
            with open(path, errors='replace') as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict) and row.get('metric') and \
                    any(k in row for k in _BENCH_COLS):
                rows.append(row)
    return rows


def _trace_section(path, top, out):
    """Device roofline via profile_analysis when the trace has XLA ops;
    host-span breakdown otherwise."""
    from tools import profile_analysis as pa
    trace, src = pa.load_trace(path)
    ops, _ = pa.device_ops(trace)
    out.append('trace: %s' % src)
    if ops:
        rows = pa.aggregate(ops)
        busy_ms = pa.busy_us(ops) / 1e3
        out.append('  device XLA ops: %d distinct, %.1f ms busy '
                   '(interval union)' % (len(rows), busy_ms))
        ranked = sorted(rows.items(), key=lambda kv: -kv[1]['dur_us'])
        for name, r in ranked[:top]:
            out.append('  %-44s %8.2f ms  %s'
                       % (name[:44], r['dur_us'] / 1e3, r['cat'][:20]))
        return
    # host-span trace (spans_to_chrome output): group X events by name
    agg = {}
    for e in trace.get('traceEvents', ()):
        if e.get('ph') != 'X':
            continue
        cur = agg.setdefault(e.get('name', '?'), [0, 0.0])
        cur[0] += 1
        cur[1] += float(e.get('dur') or 0.0)
    if not agg:
        out.append('  (no X events in trace)')
        return
    out.append('  host spans (no device lanes in this trace):')
    for name, (n, dur_us) in sorted(agg.items(),
                                    key=lambda kv: -kv[1][1])[:top]:
        out.append('  %-44s %6d x %10.2f ms total'
                   % (name[:44], n, dur_us / 1e3))


def report(snap_text=None, flight_dir=None, bench_paths=(), trace=None,
           top=10):
    """Assemble the full text report (list of lines)."""
    out = []
    if snap_text:
        snaps = parse_snapshot_lines(snap_text)
        if not snaps:
            # a bare snapshot JSON (export.to_dict) instead of lines
            try:
                snaps = {'': json.loads(snap_text)}
            except ValueError:
                snaps = {}
        for tag in sorted(snaps):
            perf = snapshot_perf(snaps[tag])
            out.append('config %s:' % (tag or '(unlabeled)'))
            if not perf:
                out.append('  no perf families in snapshot')
                continue
            for kind, (n, mean) in sorted(
                    perf.get('compiles', {}).items()):
                out.append('  compiles[%s]: %d%s'
                           % (kind, n, '' if mean is None
                              else ' (mean %.3fs)' % mean))
            if 'recompiles' in perf:
                flag = '  <-- steady state violated' \
                    if perf['recompiles'] else ''
                out.append('  recompiles after warmup: %d%s'
                           % (perf['recompiles'], flag))
            if 'steps' in perf:
                out.append('  steps: %d  stragglers: %d'
                           % (perf['steps'], perf.get('stragglers', 0)))
            phases = perf.get('phases', {})
            step_mean = sum(m for _, m in phases.values())
            for phase, (n, mean) in sorted(phases.items()):
                flag = ''
                if phase == 'data_wait' and step_mean > 0 and \
                        mean / step_mean >= _INPUT_BOUND_FRAC:
                    flag = ('  <-- input-bound (%d%% of step)'
                            % round(100 * mean / step_mean))
                out.append('  phase %-14s mean %.6fs over %d steps%s'
                           % (phase, mean, n, flag))
            if 'mfu_est' in perf:
                out.append('  mfu_est: %.4f' % perf['mfu_est'])
            if 'arithmetic_intensity' in perf:
                out.append('  arithmetic_intensity: %.2f flops/byte'
                           % perf['arithmetic_intensity'])
            if 'roofline_bound' in perf:
                out.append('  roofline_bound: %s'
                           % ('compute' if perf['roofline_bound'] >= 1.0
                              else 'bandwidth'))
    if flight_dir:
        events = flight_recompiles(flight_dir)
        out.append('flight dumps (%s): %d perf events'
                   % (flight_dir, len(events)))
        for ev in events[:top]:
            tags = ev['tags']
            if ev['name'] == 'perf.recompile':
                out.append('  recompile %.3fs at %s'
                           % (float(tags.get('duration_s') or 0.0),
                              tags.get('callsite', '?')))
                if tags.get('signature'):
                    out.append('    signature: %s'
                               % str(tags['signature'])[:120])
            else:
                out.append('  straggler total=%ss median=%ss (step %s)'
                           % (tags.get('total_s'), tags.get('median_s'),
                              tags.get('step')))
    rows = bench_perf_rows(bench_paths)
    if rows:
        out.append('bench perf table (%d rows):' % len(rows))
        hdr = ('metric',) + _BENCH_COLS
        out.append('  ' + '  '.join('%-14s' % h for h in hdr))
        for row in rows:
            cells = ['%-14s' % str(row['metric'])[:40]]
            for k in _BENCH_COLS:
                cells.append('%-14s' % ('' if row.get(k) is None
                                        else row[k]))
            out.append('  ' + '  '.join(cells).rstrip())
    if trace:
        _trace_section(trace, top, out)
    if not out:
        out.append('nothing to report: pass --snapshot, --flight-dir, '
                   '--bench and/or --trace')
    return out


def _load_snapshot_text(arg):
    if arg == '-':
        return sys.stdin.read()
    with open(arg, errors='replace') as f:
        text = f.read()
    # driver captures are JSON with the raw output under 'tail'
    if arg.endswith('.json') and '"tail"' in text[:200000]:
        try:
            return json.loads(text).get('tail', text)
        except ValueError:
            pass
    return text


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--snapshot',
                    help="telemetry_snapshot text / driver capture "
                         "JSON / plain snapshot JSON, or '-' (stdin)")
    ap.add_argument('--flight-dir',
                    help='directory of FlightRecorder flight_*.json')
    ap.add_argument('--bench', action='append', default=[],
                    help='bench capture JSONL (repeatable)')
    ap.add_argument('--trace',
                    help='Chrome trace: profiler dir/file or a '
                         'spans_to_chrome JSON')
    ap.add_argument('--top', type=int, default=10)
    args = ap.parse_args(argv)

    snap_text = _load_snapshot_text(args.snapshot) if args.snapshot \
        else None
    for line in report(snap_text=snap_text, flight_dir=args.flight_dir,
                       bench_paths=args.bench, trace=args.trace,
                       top=args.top):
        print(line)
    return 0


if __name__ == '__main__':
    sys.exit(main())
