"""Offline plan-artifact report + integrity gate.

Renders the sharding autotuner's content-addressed plan artifacts
(distributed/auto_parallel/tuner.py, `plan_<key>.json`) as a human
report — per-boundary chosen spec, the full candidate table with the
score breakdown (involuntary-reshard bytes / HLO collective bytes /
analytic ideal step time), and the content key with the config it
derives from — and gates their integrity the way the engines'
PADDLE_TPU_PLAN_STRICT=1 mode would: a stored key that does not
re-derive from its stored config, or an unsupported plan version, is a
finding.

Speaks the gate_common protocol (exit 0 clean, 1 findings, 2 nothing
to check) so CI can point it at a committed plan directory.

Usage:
    python tools/plan_report.py PLAN.json [PLAN.json ...]
    python tools/plan_report.py --plan-dir DIR   # every plan_*.json
"""
import argparse
import glob
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# the tuner imports jax; an offline report must not grab a TPU
os.environ.setdefault('JAX_PLATFORMS', 'cpu')

from tools import gate_common  # noqa: E402

__all__ = ['check_artifact', 'render', 'main']


def _fmt_score(score):
    parts = ['involuntary=%dB' % int(score.get('involuntary_bytes', 0)),
             'collectives=%dB/%d' % (int(score.get('collective_bytes', 0)),
                                     int(score.get('collective_count', 0)))]
    if score.get('ideal_step_s') is not None:
        parts.append('ideal=%.3gs' % float(score['ideal_step_s']))
    return ' '.join(parts)


def _fmt_spec(spec):
    if spec is None:
        return '<planner default>'
    return 'P(%s)' % ', '.join(
        '(%s)' % ','.join(e) if isinstance(e, list)
        else {None: 'None'}.get(e, repr(e)) for e in spec)


def check_artifact(art, path, tuner):
    """Integrity findings for one loaded artifact (empty == sound)."""
    try:
        tuner.verify_artifact(art)
    except tuner.PlanKeyError as e:
        return [{'path': path, 'key': art.get('key'), 'error': str(e)}]
    return []


def render(art, path, out):
    cfg = art.get('config') or {}
    mesh = ' '.join('%s=%s' % kv for kv in sorted(
        (cfg.get('mesh') or {}).items()))
    out.write('plan %s  (%s)\n' % (art.get('key'), path))
    out.write('  config: mesh[%s] axis=%s batch_axes=%s jaxlib=%s '
              'model=%s\n'
              % (mesh, cfg.get('axis'),
                 ','.join(cfg.get('batch_axes') or ()) or '-',
                 cfg.get('jaxlib'), cfg.get('model')))
    if art.get('probe_compiles') is not None:
        out.write('  search: %s probe compiles, final %s\n'
                  % (art['probe_compiles'],
                     _fmt_score(art.get('final_score') or {})))
    for b, d in sorted((art.get('boundaries') or {}).items()):
        out.write('  %-8s -> %-28s %s\n'
                  % (b, _fmt_spec(d.get('spec')),
                     _fmt_score(d.get('score') or {})))
        for t in d.get('candidates') or ():
            if not t.get('chosen'):
                out.write('  %-8s    %-28s %s\n'
                          % ('', _fmt_spec(t.get('spec')),
                             _fmt_score(t.get('score') or {})))
    out.write('\n')


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('plans', nargs='*', help='plan artifact JSON files')
    ap.add_argument('--plan-dir', default=None,
                    help='report every plan_*.json in this directory '
                         '(default: $PADDLE_TPU_PLAN_DIR when set)')
    args = ap.parse_args(argv)

    from paddle_tpu.distributed.auto_parallel import tuner

    paths = list(args.plans)
    dirpath = args.plan_dir or (os.environ.get('PADDLE_TPU_PLAN_DIR')
                                if not paths else None)
    if dirpath:
        paths += sorted(glob.glob(os.path.join(dirpath, 'plan_*.json')))
    if not paths:
        return gate_common.nothing_to_check('no plan artifacts given')

    findings, reported = [], 0
    for path in paths:
        try:
            art = tuner.load_plan(path)
        except (ValueError, OSError) as e:
            findings.append({'path': path, 'error': 'unreadable: %s' % e})
            continue
        findings.extend(check_artifact(art, path, tuner))
        render(art, path, sys.stdout)
        reported += 1
    return gate_common.finish(findings, {'plans': reported})


if __name__ == '__main__':
    sys.exit(main())
