#!/usr/bin/env python
"""Roofline analysis of a captured TPU profile (jax.profiler trace).

Usage: python tools/profile_analysis.py [docs/tpu_profile_r4] [--top N]

Reads the newest `*.trace.json.gz` under the given profile dir (written
by jax.profiler.start_trace via PADDLE_TPU_BENCH_PROFILE / the warmer's
auto-profile pass) and prints, per XLA op aggregated over steps:

  - time/step, roofline-ideal time (max of flops/peak, bytes/bw), and
    the achieved fraction;
  - totals: program flops vs the 6N model, program HBM bytes, and
    whether the step is compute- or bandwidth-bound;
  - the top byte movers — the list that names the next fusion target
    (this is how the round-4 fused-CE and native-dtype-matmul levers
    were found; see docs/PERF_NOTES_r4.md).

Peak numbers default to v5e (197 TFLOP/s bf16, 819 GB/s HBM); override
with --peak-tflops / --hbm-gbs for other TPU generations.

Reference counterpart: the op-benchmark harness family
(/root/reference/paddle/fluid/operators/benchmark/op_tester.cc) — this
is the XLA-profile-driven equivalent: measure the compiled program,
attribute time to ops, rank by headroom.
"""
import argparse
import collections
import glob
import gzip
import json
import os
import sys


def _read_json(path):
    """Chrome-trace JSON, gzip or plain, judged by content not suffix."""
    with open(path, 'rb') as f:
        magic = f.read(2)
    opener = gzip.open if magic == b'\x1f\x8b' else open
    with opener(path, 'rb') as f:
        return json.load(f)


def load_trace(profile_dir):
    """Newest trace under a profile dir — or a trace file given
    directly. Accepts the profiler's *.trace.json.gz and plain *.json
    Chrome traces (monitor.tracing.spans_to_chrome output), so the
    offline tools can join host-span dumps with device profiles."""
    if os.path.isfile(profile_dir):
        return _read_json(profile_dir), profile_dir
    paths = sorted(glob.glob(os.path.join(
        profile_dir, '**', '*.trace.json.gz'), recursive=True))
    if not paths:
        paths = sorted(p for p in glob.glob(os.path.join(
            profile_dir, '**', '*.json'), recursive=True)
            if p.endswith('.json') and 'trace' in os.path.basename(p))
    if not paths:
        raise SystemExit('no *.trace.json.gz (or *trace*.json) under %s'
                         % profile_dir)
    return _read_json(paths[-1]), paths[-1]


def device_ops(trace):
    """XLA-op duration events from the device pid's 'XLA Ops' lane."""
    tids = {}
    device_pids = set()
    for e in trace['traceEvents']:
        if e.get('ph') != 'M':
            continue
        if e.get('name') == 'process_name' and '/device:' in str(
                e.get('args', {}).get('name', '')):
            device_pids.add(e['pid'])
        if e.get('name') == 'thread_name':
            tids[(e['pid'], e['tid'])] = e['args'].get('name')
    ops, n_modules = [], 0
    for e in trace['traceEvents']:
        if e.get('ph') != 'X' or e['pid'] not in device_pids:
            continue
        lane = tids.get((e['pid'], e['tid']))
        if lane == 'XLA Ops':
            ops.append(e)
        elif lane == 'XLA Modules':
            n_modules += 1
    return ops, n_modules


def busy_us(ops):
    """Union of the device-op time intervals per (pid, tid), in us.

    A plain sum of durations double-counts nested ops (a while/scan op's
    slice covers its body ops, which appear as their own events), which
    inflated the r5 summary's 'on-chip op time' to ~2x the measured
    step. The interval union is the actual busy time."""
    lanes = {}
    for e in ops:
        lanes.setdefault((e['pid'], e.get('tid')), []).append(
            (float(e['ts']), float(e['ts']) + float(e['dur'])))
    total = 0.0
    for spans in lanes.values():
        spans.sort()
        cur_s, cur_e = spans[0]
        for s, t in spans[1:]:
            if s > cur_e:
                total += cur_e - cur_s
                cur_s, cur_e = s, t
            else:
                cur_e = max(cur_e, t)
        total += cur_e - cur_s
    return total


def aggregate(ops):
    rows = {}
    for e in ops:
        a = e.get('args', {})
        r = rows.setdefault(e['name'], dict(
            dur_us=0.0, n=0,
            flops=float(a.get('model_flops', 0) or 0),
            bytes=float(a.get('bytes_accessed', 0) or 0),
            cat=a.get('hlo_category', ''),
            ln=a.get('long_name', '')))
        r['dur_us'] += e['dur']
        r['n'] += 1
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('profile_dir', nargs='?', default='docs/tpu_profile_r4')
    ap.add_argument('--top', type=int, default=15)
    ap.add_argument('--steps', type=int, default=0,
                    help='profiled steps (default: inferred from the '
                         'most-frequent op count)')
    ap.add_argument('--peak-tflops', type=float, default=197.0)
    ap.add_argument('--hbm-gbs', type=float, default=819.0)
    ap.add_argument('--model-gflops', type=float, default=0.0,
                    help='model flops per step (e.g. 6N*batch*seq) for '
                         'the MFU line')
    args = ap.parse_args()

    trace, path = load_trace(args.profile_dir)
    ops, n_modules = device_ops(trace)
    if not ops:
        raise SystemExit('no device XLA-op events in %s' % path)
    rows = aggregate(ops)

    steps = args.steps
    if not steps:
        # each per-step op repeats once per step; the modal count is
        # robust against setup/one-off modules in the same trace
        counts = collections.Counter(r['n'] for r in rows.values())
        steps = counts.most_common(1)[0][0]
    peak = args.peak_tflops * 1e12
    bw = args.hbm_gbs * 1e9

    tot_ms = busy_us(ops) / 1e3 / steps
    tot_flops = sum(r['flops'] * r['n'] for r in rows.values()) / steps
    tot_bytes = sum(r['bytes'] * r['n'] for r in rows.values()) / steps
    print('trace: %s' % path)
    print('steps inferred: %d   on-chip busy time: %.1f ms/step '
          '(interval union; nested ops not double-counted)' %
          (steps, tot_ms))
    print('program flops/step: %.3e  -> %.1f ms at %.0f TFLOP/s' %
          (tot_flops, tot_flops / peak * 1e3, args.peak_tflops))
    print('program bytes/step: %.3e  -> %.1f ms at %.0f GB/s' %
          (tot_bytes, tot_bytes / bw * 1e3, args.hbm_gbs))
    bound = 'BANDWIDTH' if tot_bytes / bw > tot_flops / peak else 'COMPUTE'
    print('the step is %s-bound; achieved %.0f GB/s, %.1f TFLOP/s' %
          (bound, tot_bytes / (tot_ms / 1e3) / 1e9,
           tot_flops / (tot_ms / 1e3) / 1e12))
    if args.model_gflops:
        print('MFU vs --model-gflops: %.1f%%' %
              (100 * args.model_gflops * 1e9 / (tot_ms / 1e3) / peak))

    print('\ntop %d ops by time:' % args.top)
    print('%-40s %7s %7s %5s  %s' % ('op', 'ms/st', 'ideal', 'eff', 'category'))
    for k, r in sorted(rows.items(), key=lambda kv: -kv[1]['dur_us'])[:args.top]:
        ms = r['dur_us'] / 1e3 / steps
        ideal = max(r['flops'] / peak, r['bytes'] / bw) * 1e3
        eff = (ideal / ms * 100) if ms else 0
        print('%-40s %7.2f %7.2f %4.0f%%  %s' % (k[:40], ms, ideal, eff,
                                                 r['cat'][:24]))

    print('\ntop %d byte movers (the fusion-target list):' % args.top)
    for k, r in sorted(rows.items(),
                       key=lambda kv: -kv[1]['bytes'] * kv[1]['n'])[:args.top]:
        gb = r['bytes'] * r['n'] / steps / 1e9
        print('%-40s %6.2f GB/step  %s' % (k[:40], gb, r['ln'][:80]))
    return 0


if __name__ == '__main__':
    sys.exit(main())
