"""Shared graftlint core: modules, findings, suppressions, baseline.

A ``Project`` is the parsed universe the checkers see — every module's
AST plus raw source, loaded once, so whole-program checkers (the
idempotency table join, the cross-module lock graph) are cheap. A
``Finding`` fingerprints on (rule, path, symbol, message) — NOT the line
number — so the committed baseline survives unrelated edits that shift
code up or down a file.

Suppression forms (see docs/static_analysis.md):

  x = float(t)   # graftlint: disable=retrace-host-sync  <reason>
  # graftlint: disable-file=lock-guard-write  <reason>        (anywhere)

Rule ``all`` matches every rule. Suppressions are deliberate, local and
reviewable; the baseline exists only to pin pre-existing findings when a
new rule lands (``--fix-baseline``), never to wave through new code.
"""
import ast
import hashlib
import json
import os
import re

__all__ = ['Finding', 'Module', 'Project', 'Checker', 'load_baseline',
           'write_baseline', 'apply_baseline', 'run_checkers',
           'DEFAULT_BASELINE', 'REPO_ROOT']

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, 'tools', 'graftlint_baseline.json')

_SUPPRESS_RE = re.compile(
    r'#\s*graftlint:\s*(?P<scope>disable|disable-file)='
    r'(?P<rules>[a-z0-9,\-]+|all)')


class Finding:
    """One rule violation at one site."""

    __slots__ = ('rule', 'path', 'line', 'col', 'message', 'symbol')

    def __init__(self, rule, path, line, message, symbol='', col=0):
        self.rule = rule
        self.path = path          # repo-relative, '/'-separated
        self.line = int(line)
        self.col = int(col)
        self.message = message
        self.symbol = symbol      # enclosing qualname ('Class.method')

    def fingerprint(self):
        """Line-number-free identity for the baseline."""
        key = '%s|%s|%s|%s' % (self.rule, self.path, self.symbol,
                               self.message)
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def to_dict(self):
        return {'rule': self.rule, 'path': self.path, 'line': self.line,
                'symbol': self.symbol, 'message': self.message,
                'fingerprint': self.fingerprint()}

    def __repr__(self):
        return '%s:%d: [%s] %s' % (self.path, self.line, self.rule,
                                   self.message)


class Module:
    """One parsed source file plus its suppression tables."""

    def __init__(self, path, relpath, source):
        self.path = path
        self.relpath = relpath.replace(os.sep, '/')
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.modname = self.relpath[:-3].replace('/', '.') \
            if self.relpath.endswith('.py') else self.relpath
        if self.modname.endswith('.__init__'):
            self.modname = self.modname[:-len('.__init__')]
        self._line_suppress = {}   # lineno -> set of rules
        self._file_suppress = set()
        for i, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = set(m.group('rules').split(','))
            if m.group('scope') == 'disable-file':
                self._file_suppress |= rules
            else:
                self._line_suppress.setdefault(i, set()).update(rules)

    def suppressed(self, rule, line):
        rules = self._line_suppress.get(line, ())
        return ('all' in self._file_suppress or rule in self._file_suppress
                or 'all' in rules or rule in rules)

    def qualname_at(self, node):
        """Enclosing Class.method qualname of `node` (best effort via a
        parent walk — cheap because modules are small)."""
        chain = []
        self._qual_walk(self.tree, node, chain)
        return '.'.join(chain)

    def _qual_walk(self, root, target, chain):
        for child in ast.iter_child_nodes(root):
            if child is target or any(n is target
                                      for n in ast.walk(child)):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    chain.append(child.name)
                self._qual_walk(child, target, chain)
                return


class Project:
    """Every module the checkers see, loaded and parsed once."""

    def __init__(self, modules):
        self.modules = list(modules)
        self.by_modname = {m.modname: m for m in self.modules}

    @classmethod
    def load(cls, paths, root=None, exclude=('__pycache__',)):
        root = root or REPO_ROOT
        files = []
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(root, p)
            if os.path.isfile(ap):
                files.append(ap)
                continue
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in dirnames if d not in exclude]
                for fn in sorted(filenames):
                    if fn.endswith('.py'):
                        files.append(os.path.join(dirpath, fn))
        modules = []
        for f in sorted(set(files)):
            rel = os.path.relpath(f, root)
            with open(f, errors='replace') as fh:
                src = fh.read()
            try:
                modules.append(Module(f, rel, src))
            except SyntaxError:
                # non-importable scraps (fixtures for other tools) are
                # not lintable; skip rather than crash the whole run
                continue
        return cls(modules)


class Checker:
    """Base checker: subclasses set `name`, `RULES` ({rule: doc}) and
    implement check(project) -> [Finding]. Helpers stamp suppression-
    aware findings."""

    name = None
    RULES = {}

    def check(self, project):
        raise NotImplementedError

    def finding(self, module, node, rule, message, out):
        """Append a Finding for `node` unless suppressed at its line."""
        line = getattr(node, 'lineno', 0)
        if module.suppressed(rule, line):
            return
        out.append(Finding(rule, module.relpath, line, message,
                           symbol=module.qualname_at(node),
                           col=getattr(node, 'col_offset', 0)))


# -- baseline ---------------------------------------------------------------

def load_baseline(path=DEFAULT_BASELINE):
    """{fingerprint: count} plus the context entries for humans."""
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        data = json.load(fh)
    return {fp: entry for fp, entry in data.get('findings', {}).items()}


def write_baseline(findings, path=DEFAULT_BASELINE):
    """Pin `findings` as the accepted pre-existing set."""
    table = {}
    for f in findings:
        fp = f.fingerprint()
        entry = table.get(fp)
        if entry is None:
            entry = table[fp] = dict(f.to_dict(), count=0)
            del entry['fingerprint']
        entry['count'] += 1
    payload = {'comment': 'graftlint accepted pre-existing findings; '
                          'regenerate with --fix-baseline',
               'findings': {fp: table[fp] for fp in sorted(table)}}
    with open(path, 'w') as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write('\n')
    return path


def apply_baseline(findings, baseline):
    """Split into (new, pinned): each fingerprint absorbs up to its
    baselined count; anything beyond is new."""
    remaining = {fp: int(entry.get('count', 1))
                 for fp, entry in baseline.items()}
    new, pinned = [], []
    for f in findings:
        fp = f.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            pinned.append(f)
        else:
            new.append(f)
    return new, pinned


def run_checkers(project, checkers, rules=None):
    """All findings from `checkers` over `project`, sorted by site.
    `rules`: optional iterable restricting which rule ids may fire."""
    allowed = set(rules) if rules else None
    out = []
    for checker in checkers:
        for f in checker.check(project):
            if allowed is None or f.rule in allowed:
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out
