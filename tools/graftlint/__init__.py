"""graftlint: repo-native static analysis (stdlib ``ast``, no new deps).

The runtime guardrails (CompileWatchdog, chaos injection, the schema
gate) catch invariant violations after they execute; graftlint rejects
the same bug classes at lint time, the way the reference framework's
operator registry and IR verification reject bad programs before they
run. Four checkers, one shared visitor/finding/suppression core:

- ``retrace``     — host-sync and retrace hazards inside jit-reachable
                    functions (the static complement to the watchdog);
- ``locks``       — lock-acquisition-order cycles and lock-guarded
                    attributes written outside any ``with`` block;
- ``idempotency`` — every op retried through ResilientChannel.call must
                    be declared retry-safe at its server registration
                    (whole-program, resolved across modules);
- ``metrics``     — metric families two-way against the committed schema
                    baseline, label arity at ``.labels()`` sites, and
                    tracer spans that can leak.

Run: ``python -m tools.graftlint paddle_tpu tools``; see
docs/static_analysis.md for the rule catalog and suppression format.
"""
from .core import (Finding, Module, Project, Checker, load_baseline,
                   write_baseline, apply_baseline, run_checkers,
                   DEFAULT_BASELINE)
from .checkers import all_checkers

__all__ = ['Finding', 'Module', 'Project', 'Checker', 'load_baseline',
           'write_baseline', 'apply_baseline', 'run_checkers',
           'all_checkers', 'DEFAULT_BASELINE']
