"""graftlint command line: the lint gate, speaking the gate_common
protocol (exit 0 = clean, 1 = unsuppressed findings, 2 = nothing to
lint). Usage:

    python -m tools.graftlint paddle_tpu tools
    python -m tools.graftlint --rules lock-guard-write serving/
    python -m tools.graftlint --fix-baseline paddle_tpu tools
    python -m tools.graftlint --list-rules
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from tools import gate_common
from tools.graftlint.core import (Project, load_baseline, write_baseline,
                                  apply_baseline, run_checkers,
                                  DEFAULT_BASELINE, REPO_ROOT)
from tools.graftlint.checkers import all_checkers


def build_parser():
    p = argparse.ArgumentParser(
        prog='graftlint',
        description='repo-native static analysis (retrace hazards, lock '
                    'discipline, RPC idempotency, metric/span hygiene)')
    p.add_argument('paths', nargs='*', default=[],
                   help='files or directories to lint (repo-relative)')
    p.add_argument('--baseline', default=DEFAULT_BASELINE,
                   help='baseline file pinning accepted pre-existing '
                        'findings')
    p.add_argument('--no-baseline', action='store_true',
                   help='report every finding, pinned or not')
    p.add_argument('--fix-baseline', action='store_true',
                   help='rewrite the baseline to pin all current findings')
    p.add_argument('--rules', default='',
                   help='comma-separated rule ids to run (default: all)')
    p.add_argument('--list-rules', action='store_true',
                   help='print the rule catalog and exit')
    p.add_argument('--json', action='store_true',
                   help='machine output only (suppress human lines)')
    return p


def main(argv=None, stream=None):
    args = build_parser().parse_args(argv)
    stream = stream if stream is not None else sys.stdout
    checkers = all_checkers()

    if args.list_rules:
        for checker in checkers:
            for rule, doc in sorted(checker.RULES.items()):
                print('%-26s %s' % (rule, doc), file=stream)
        return gate_common.OK

    if not args.paths:
        return gate_common.nothing_to_check('no paths given', stream=stream)
    project = Project.load(args.paths, root=REPO_ROOT)
    if not project.modules:
        return gate_common.nothing_to_check(
            'no python modules under %s' % ' '.join(args.paths),
            stream=stream)

    rules = [r for r in args.rules.split(',') if r] or None
    findings = run_checkers(project, checkers, rules=rules)

    if args.fix_baseline:
        path = write_baseline(findings, args.baseline)
        gate_common.emit({'ok': True, 'baseline': os.path.relpath(
            path, REPO_ROOT), 'pinned': len(findings)}, stream=stream)
        return gate_common.OK

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, pinned = apply_baseline(findings, baseline)

    if not args.json:
        for f in new:
            print(str(f), file=sys.stderr)
    return gate_common.finish(
        [f.to_dict() for f in new],
        summary={'modules': len(project.modules),
                 'findings': len(findings), 'pinned': len(pinned)},
        stream=stream)


if __name__ == '__main__':
    sys.exit(main())
