"""Atomic-write discipline checker for checkpoint artifacts.

The repo has exactly one sanctioned durable-artifact writer:
``paddle_tpu/framework/io_save.py`` (temp file + fsync + ``os.replace``
+ CRC32 manifest sidecar, with chaos fault hooks at the torn-write
points). A checkpoint written any other way can be torn by a preempted
pod — and, with no manifest, ``CheckpointManager.restore_latest`` has no
way to know it is torn.

Rule:

- atomic-write — a checkpoint-flavored artifact is written through a
  raw mechanism outside io_save: ``open(..., 'w'/'wb'/'a'...)``,
  ``pickle.dump``/``np.save``/``np.savez``, or a hand-rolled
  ``os.rename``/``os.replace`` commit, where the call's argument
  subtree carries checkpoint evidence (a string constant or an
  identifier mentioning ckpt / checkpoint / pdparams / pdopt / snap).

Evidence is deliberately lexical: the checker only fires where the code
itself says it is writing a checkpoint. Generic ``open(path, 'w')``
helpers stay quiet — naming the artifact is what creates the duty to
write it atomically.
"""
import ast

from ..core import Checker

# the sanctioned writer itself (and only it) may touch these primitives
# on checkpoint paths
EXEMPT_MODULES = ('paddle_tpu.framework.io_save',)

KEYWORDS = ('ckpt', 'checkpoint', 'pdparams', 'pdopt', 'snap')

_RAW_DUMPERS = {'dump': ('pickle',), 'save': ('np', 'numpy'),
                'savez': ('np', 'numpy'), 'savez_compressed': ('np',
                                                               'numpy')}


def _mentions_checkpoint(node):
    """True when any string constant or identifier under `node` names a
    checkpoint-ish artifact."""
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            text = n.value.lower()
        elif isinstance(n, ast.Name):
            text = n.id.lower()
        elif isinstance(n, ast.Attribute):
            text = n.attr.lower()
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.arg)):
            text = (n.name if hasattr(n, 'name') else n.arg).lower()
        else:
            continue
        if any(k in text for k in KEYWORDS):
            return True
    return False


def _args_mention_checkpoint(call):
    return any(_mentions_checkpoint(a) for a in call.args) or \
        any(_mentions_checkpoint(kw.value) for kw in call.keywords)


def _write_mode(call):
    """The mode string of an open() call when it writes, else None."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == 'mode':
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str) \
            and any(c in mode.value for c in 'wax+'):
        return mode.value
    return None


class AtomicWriteChecker(Checker):
    name = 'atomic_write'
    RULES = {
        'atomic-write': 'checkpoint artifact written without the '
                        'io_save atomic writer (temp+fsync+rename+'
                        'manifest)',
    }

    def check(self, project):
        out = []
        for module in project.modules:
            if module.modname in EXEMPT_MODULES:
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._raw_write(node)
                if hit and _args_mention_checkpoint(node):
                    self.finding(
                        module, node, 'atomic-write',
                        '%s writes a checkpoint artifact raw — a '
                        'preempted writer tears it and no manifest '
                        'marks it torn; route it through '
                        'framework.io_save.save' % hit, out)
        return out

    @staticmethod
    def _raw_write(call):
        """Human-readable label of the raw write mechanism, or None."""
        f = call.func
        if isinstance(f, ast.Name) and f.id == 'open':
            mode = _write_mode(call)
            return "open(..., %r)" % mode if mode else None
        if isinstance(f, ast.Attribute):
            base = f.value.id if isinstance(f.value, ast.Name) else None
            if base == 'os' and f.attr in ('rename', 'replace'):
                return 'os.%s' % f.attr
            allowed = _RAW_DUMPERS.get(f.attr)
            if allowed and base in allowed:
                return '%s.%s' % (base, f.attr)
        return None
