"""Wide-event schema checker.

The committed field list (tools/request_event_baseline.json) is the
contract wide-event consumers parse against (request_report, the
/requests route, downstream log pipelines); code and baseline must
agree BOTH ways:

- event-unknown-field — code declares a field in a REQUEST_EVENT_FIELDS
  table, or passes a keyword to an event log's ``emit(...)``, that the
  baseline does not list (a typo'd emission site would otherwise raise
  only at runtime — and only when that code path runs);
- event-stale-field   — the baseline lists a field no
  REQUEST_EVENT_FIELDS table declares any more (only checked when the
  project includes the events module, so fixture runs don't drown in
  repo-wide noise).

Emission sites are found by receiver shape, mirroring the metrics
checker's family tracking: ``emit`` called on a name assigned from
``RequestLog(...)`` / ``default_request_log()`` / an ``.events``
attribute, or directly on an ``.events`` attribute.
"""
import ast
import json
import os

from ..core import Checker, Finding, REPO_ROOT

DEFAULT_BASELINE = os.path.join(REPO_ROOT, 'tools',
                                'request_event_baseline.json')
ANCHOR_MODULE = 'paddle_tpu.monitor.events'

_LOG_MAKERS = ('RequestLog', 'default_request_log')


def _declared_fields(module):
    """[(field, node)] from every REQUEST_EVENT_FIELDS assignment in the
    module — entries are (name, help) tuples; the name is element 0."""
    out = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if 'REQUEST_EVENT_FIELDS' not in names:
            continue
        if not isinstance(node.value, (ast.Tuple, ast.List)):
            continue
        for entry in node.value.elts:
            if not isinstance(entry, (ast.Tuple, ast.List)) \
                    or not entry.elts:
                continue
            head = entry.elts[0]
            if isinstance(head, ast.Constant) \
                    and isinstance(head.value, str):
                out.append((head.value, entry))
    return out


def _event_receivers(module):
    """Names bound to a request log within the module: assigned from a
    RequestLog constructor / default_request_log() / an `.events`
    attribute (the caching convention every emission site follows)."""
    recv = set()
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        bound = False
        if isinstance(v, ast.Call):
            f = v.func
            callee = f.id if isinstance(f, ast.Name) else \
                f.attr if isinstance(f, ast.Attribute) else None
            bound = callee in _LOG_MAKERS
        elif isinstance(v, ast.Attribute) and v.attr == 'events':
            bound = True
        if not bound:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                recv.add(tgt.id)
            elif (isinstance(tgt, ast.Attribute)
                  and isinstance(tgt.value, ast.Name)
                  and tgt.value.id == 'self'):
                recv.add('self.' + tgt.attr)
    return recv


def _emit_sites(module):
    """[(kwargs, node)] for ``<event receiver>.emit(...)`` calls."""
    recv = _event_receivers(module)
    sites = []
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == 'emit'):
            continue
        obj = node.func.value
        key = None
        if isinstance(obj, ast.Name):
            key = obj.id
        elif isinstance(obj, ast.Attribute):
            if isinstance(obj.value, ast.Name) and obj.value.id == 'self':
                key = 'self.' + obj.attr
            if obj.attr == 'events':
                key = key if key in recv else '@events'
        if key == '@events' or key in recv:
            kwargs = [kw.arg for kw in node.keywords
                      if kw.arg is not None]
            sites.append((kwargs, node))
    return sites


class EventsChecker(Checker):
    name = 'events'
    RULES = {
        'event-unknown-field': 'code declares or emits a wide-event '
                               'field missing from the baseline',
        'event-stale-field': 'the baseline lists a wide-event field no '
                             'code declares',
    }

    def __init__(self, baseline_path=DEFAULT_BASELINE):
        self.baseline_path = baseline_path

    def _load_baseline(self):
        if not os.path.exists(self.baseline_path):
            return None
        with open(self.baseline_path) as fh:
            data = json.load(fh)
        fields = data.get('fields', data) if isinstance(data, dict) \
            else data
        return set(fields)

    def check(self, project):
        out = []
        baseline = self._load_baseline()
        if baseline is None:
            return out
        rel = os.path.relpath(self.baseline_path, REPO_ROOT)
        declared = set()
        for module in project.modules:
            for field, node in _declared_fields(module):
                declared.add(field)
                if field not in baseline:
                    self.finding(
                        module, node, 'event-unknown-field',
                        "wide-event field '%s' is not in %s; update the "
                        'baseline when the schema change is intentional'
                        % (field, rel), out)
            for kwargs, node in _emit_sites(module):
                for kw in kwargs:
                    if kw not in baseline:
                        self.finding(
                            module, node, 'event-unknown-field',
                            "emit(...) passes field '%s' which is not "
                            'in %s; RequestLog.emit would raise at '
                            'runtime' % (kw, rel), out)
        if ANCHOR_MODULE in project.by_modname:
            for field in sorted(baseline - declared):
                out.append(Finding(
                    'event-stale-field', rel.replace(os.sep, '/'), 1,
                    "baseline lists wide-event field '%s' but no "
                    'REQUEST_EVENT_FIELDS table declares it' % field,
                    symbol=field))
        return out
