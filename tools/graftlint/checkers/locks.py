"""Lock-discipline checker: acquisition-order cycles and unguarded writes.

Lock discovery is per class (``self._lock = threading.Lock()/RLock()/
Condition()``) plus module-level locks (``_TRACE_LOCK = threading.Lock()``).
Within each method a lexical walk tracks the ordered set of locks held
(``with self._lock:`` nesting); the repo's ``*_locked`` method-name
convention (caller holds the class's primary lock) is honoured, and
private methods whose every same-class call site holds lock L are
treated as entered with L held (small fixpoint).

Rules:

- lock-order-cycle — ``with A: ... with B:`` here and ``with B: ...
  with A:`` elsewhere; deadlock when both paths race;
- lock-guard-write — an attribute written under the class lock in one
  method and written bare in another (the classic lost-update /
  torn-read race).

``acquisition_order(project)`` exposes the derived edges so the runtime
witness (paddle_tpu/testing/lockwatch.py) can assert the same order at
execution time.
"""
import ast

from ..core import Checker

_LOCK_CTORS = {'Lock', 'RLock', 'Condition', 'Semaphore', 'BoundedSemaphore'}
_LOCKISH_ATTRS = ('_lock', '_cv', '_mu', '_mutex', '_cond')


def _is_lock_ctor(node):
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in _LOCK_CTORS
    if isinstance(f, ast.Attribute):
        return f.attr in _LOCK_CTORS
    return False


def _self_attr(node):
    """'attr' when node is ``self.attr`` else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == 'self'):
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, module, node):
        self.module = module
        self.node = node
        self.name = node.name
        self.methods = {m.name: m for m in node.body
                        if isinstance(m, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
        self.locks = set()          # attr names holding lock objects
        for m in self.methods.values():
            for sub in ast.walk(m):
                if isinstance(sub, ast.Assign) and _is_lock_ctor(sub.value):
                    for tgt in sub.targets:
                        attr = _self_attr(tgt)
                        if attr:
                            self.locks.add(attr)
        # fallback: `with self.X:` on a lock-ish name counts as a lock
        # even when the assignment lives in a helper we didn't see
        for m in self.methods.values():
            for sub in ast.walk(m):
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        attr = _self_attr(item.context_expr)
                        if attr and (attr in _LOCKISH_ATTRS
                                     or attr.endswith('lock')):
                            self.locks.add(attr)

    def primary_lock(self):
        if '_lock' in self.locks:
            return '_lock'
        return sorted(self.locks)[0] if self.locks else None

    def lock_id(self, attr):
        return '%s:%s.%s' % (self.module.modname, self.name, attr)


class _MethodWalk:
    """One lexical pass over a method with an ordered held-lock list."""

    def __init__(self, cls, method, entry_held, module_locks, collect):
        self.cls = cls
        self.method = method
        self.module_locks = module_locks   # {name: lock_id}
        self.collect = collect             # final pass sink or None
        self.calls = []                    # (callee_name, frozenset(held))
        self.entry_held = list(entry_held)

    def lock_id_of(self, expr):
        attr = _self_attr(expr)
        if attr is not None and attr in self.cls.locks:
            return self.cls.lock_id(attr)
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return self.module_locks[expr.id]
        return None

    def run(self):
        self._walk(self.method.body, self.entry_held)

    def _walk(self, stmts, held):
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                acquired = []
                for item in stmt.items:
                    lid = self.lock_id_of(item.context_expr)
                    if lid is None:
                        continue
                    if self.collect is not None:
                        for h in held + acquired:
                            if h != lid:
                                self.collect.edge(h, lid, self.cls.module,
                                                  item.context_expr)
                    acquired.append(lid)
                self._scan_exprs([i.context_expr for i in stmt.items], held)
                self._walk(stmt.body, held + acquired)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested defs (thread targets, callbacks) run on their
                # own schedule: walk them as a separate bare-entry
                # context, not under the lexically-held locks
                sub = _MethodWalk(self.cls, stmt, [], self.module_locks,
                                  self.collect)
                sub.run()
                self.calls.extend(sub.calls)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                self._scan_exprs([stmt.test], held)
            elif isinstance(stmt, ast.For):
                self._scan_exprs([stmt.iter], held)
            elif isinstance(stmt, ast.Try):
                pass
            else:
                self._scan_exprs([stmt], held)
            for field in ('body', 'orelse', 'finalbody'):
                sub = getattr(stmt, field, None)
                if sub:
                    self._walk(sub, held)
            for handler in getattr(stmt, 'handlers', None) or []:
                self._walk(handler.body, held)

    def _scan_exprs(self, nodes, held):
        held_fs = frozenset(held)
        for root in nodes:
            for sub in ast.walk(root):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    continue
                if isinstance(sub, (ast.Assign, ast.AugAssign,
                                    ast.AnnAssign)):
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for tgt in targets:
                        attr = _self_attr(tgt)
                        if attr and attr not in self.cls.locks:
                            if self.collect is not None:
                                self.collect.write(
                                    self.cls, self.method.name, attr,
                                    held_fs, tgt)
                elif isinstance(sub, ast.Call):
                    callee = _self_attr(sub.func)
                    if callee and callee in self.cls.methods:
                        self.calls.append((callee, held_fs))


class _Collector:
    def __init__(self):
        self.edges = {}    # (a, b) -> (module, node)
        self.writes = []   # (cls, method_name, attr, held_fs, node)

    def edge(self, a, b, module, node):
        self.edges.setdefault((a, b), (module, node))

    def write(self, cls, method_name, attr, held_fs, node):
        self.writes.append((cls, method_name, attr, held_fs, node))


def _entry_held_map(cls, module_locks):
    """Fixpoint: {method_name: set(lock_ids)} held on entry."""
    entry = {}
    primary = cls.primary_lock()
    for name in cls.methods:
        if name.endswith('_locked') and primary:
            entry[name] = {cls.lock_id(primary)}
        else:
            entry[name] = set()
    for _ in range(3):
        call_held = {}   # callee -> list of frozensets
        for name, method in cls.methods.items():
            walk = _MethodWalk(cls, method, entry[name], module_locks, None)
            walk.run()
            for callee, held in walk.calls:
                call_held.setdefault(callee, []).append(held)
        changed = False
        for name in cls.methods:
            if not name.startswith('_') or name.startswith('__'):
                continue   # public API: assume bare entry
            sites = call_held.get(name)
            if not sites:
                continue
            common = set(sites[0])
            for s in sites[1:]:
                common &= s
            new = entry[name] | common
            if new != entry[name]:
                entry[name] = new
                changed = True
        if not changed:
            break
    return entry


def acquisition_order(project):
    """[(lock_a, lock_b, relpath, lineno)] derived acquisition edges."""
    collect = _run(project)
    return sorted((a, b, mod.relpath, node.lineno)
                  for (a, b), (mod, node) in collect.edges.items())


def _run(project):
    collect = _Collector()
    for module in project.modules:
        module_locks = {}
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        module_locks[tgt.id] = '%s:%s' % (module.modname,
                                                          tgt.id)
        for node in module.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            cls = _ClassInfo(module, node)
            if not cls.locks:
                continue
            entry = _entry_held_map(cls, module_locks)
            for name, method in cls.methods.items():
                _MethodWalk(cls, method, entry[name], module_locks,
                            collect).run()
    return collect


class LockChecker(Checker):
    name = 'locks'
    RULES = {
        'lock-order-cycle': 'two code paths acquire the same pair of locks '
                            'in opposite orders',
        'lock-guard-write': 'attribute written under the class lock in one '
                            'method and bare in another',
    }

    def check(self, project):
        collect = _run(project)
        self.order_edges = sorted(collect.edges)
        out = []

        # -- cycles ---------------------------------------------------------
        adj = {}
        for a, b in collect.edges:
            adj.setdefault(a, set()).add(b)

        def reachable(src, dst):
            seen, stack = set(), [src]
            while stack:
                n = stack.pop()
                if n == dst:
                    return True
                if n in seen:
                    continue
                seen.add(n)
                stack.extend(adj.get(n, ()))
            return False

        reported = set()
        for (a, b), (module, node) in sorted(collect.edges.items()):
            if a != b and reachable(b, a):
                key = frozenset((a, b))
                if key in reported:
                    continue
                reported.add(key)
                self.finding(
                    module, node, 'lock-order-cycle',
                    'acquisition-order cycle: %s held while acquiring %s '
                    'here, but a path also orders %s before %s' % (a, b,
                                                                   b, a),
                    out)

        # -- guarded writes -------------------------------------------------
        guarded = {}   # (module, class, attr) -> set(lock_ids)
        for cls, method_name, attr, held, node in collect.writes:
            if method_name == '__init__' or not held:
                continue
            guarded.setdefault((cls.module.modname, cls.name, attr),
                               set()).update(held)
        for cls, method_name, attr, held, node in collect.writes:
            if method_name == '__init__' or held:
                continue
            locks = guarded.get((cls.module.modname, cls.name, attr))
            if not locks:
                continue
            self.finding(
                cls.module, node, 'lock-guard-write',
                'self.%s is written under %s elsewhere but written here '
                'without it (in %s.%s)' % (attr, '/'.join(sorted(locks)),
                                           cls.name, method_name),
                out)
        return out
