"""Idempotency checker for retried RPC ops (whole-program).

ResilientChannel.call retries by default (``idempotent=True``): a resend
of a non-idempotent op double-applies it (``push`` re-accumulates,
``add_edges`` duplicates edges). The contract this checker enforces:
every op name a client sends through ``.call``/``._call`` must be
declared in an ``OP_SEMANTICS`` table at its server module, with one of

- ``idempotent``     — safe to retry (pure reads, set-style writes);
- ``accumulating``   — an accumulating push; the CLIENT must disable
                       retries (``idempotent=False``) at every send;
- ``conditional``    — retry safety depends on the payload; the client
                       must compute the ``idempotent=`` kwarg (a literal
                       ``True`` is a lie waiting to happen);
- ``non_idempotent`` — never retried; client must send with
                       ``idempotent=False`` or use ``call_once``.

The join is cross-module: tables live in embedding_service.py /
graph_service.py, send sites live wherever clients are written. Rules:

- idem-undeclared-op      — op sent through a retrying channel but
                            declared in no OP_SEMANTICS table;
- idem-retry-unsafe       — op declared accumulating/non_idempotent but
                            sent with retries enabled;
- idem-conditional-literal — op declared conditional but the send passes
                            a constant ``idempotent=``;
- idem-unknown-op         — server dispatch handles an op missing from
                            its module's OP_SEMANTICS table, or the
                            table declares an op the handler never
                            dispatches (stale entry).
"""
import ast

from ..core import Checker

SEMANTICS = ('idempotent', 'accumulating', 'conditional', 'non_idempotent')


def _dict_op_literal(node):
    """The 'op' value when node is a dict literal with a constant op."""
    if not isinstance(node, ast.Dict):
        return None
    for k, v in zip(node.keys, node.values):
        if (isinstance(k, ast.Constant) and k.value == 'op'
                and isinstance(v, ast.Constant)
                and isinstance(v.value, str)):
            return v.value
    return None


def _op_semantics_tables(project):
    """{op: (semantics, modname)} merged across every module's
    OP_SEMANTICS dict, plus per-module tables for the two-way check."""
    merged, per_module = {}, {}
    for module in project.modules:
        for node in module.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if 'OP_SEMANTICS' not in names:
                continue
            if not isinstance(node.value, ast.Dict):
                continue
            table = {}
            for k, v in zip(node.value.keys, node.value.values):
                if (isinstance(k, ast.Constant) and isinstance(v, ast.Constant)
                        and isinstance(k.value, str)):
                    table[k.value] = str(v.value)
            per_module[module.modname] = (module, node, table)
            for op, sem in table.items():
                merged.setdefault(op, (sem, module.modname))
    return merged, per_module


def _dispatched_ops(module):
    """Op literals the module's server handler dispatches on: string
    constants compared against a name/subscript called 'op'."""
    ops = {}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        if not any(_is_op_ref(s) for s in sides):
            continue
        for s in sides:
            if isinstance(s, ast.Constant) and isinstance(s.value, str):
                ops.setdefault(s.value, node)
            elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
                for e in s.elts:
                    if (isinstance(e, ast.Constant)
                            and isinstance(e.value, str)):
                        ops.setdefault(e.value, node)
    return ops


def _is_op_ref(node):
    if isinstance(node, ast.Name) and node.id == 'op':
        return True
    if (isinstance(node, ast.Subscript)
            and isinstance(node.slice, ast.Constant)
            and node.slice.value == 'op'):
        return True
    return False


class IdempotencyChecker(Checker):
    name = 'idempotency'
    RULES = {
        'idem-undeclared-op': 'op sent through a retrying channel but not '
                              'declared in any OP_SEMANTICS table',
        'idem-retry-unsafe': 'op declared accumulating/non_idempotent sent '
                             'with retries enabled',
        'idem-conditional-literal': 'op declared conditional sent with a '
                                    'constant idempotent= kwarg',
        'idem-unknown-op': 'server dispatch and OP_SEMANTICS table '
                           'disagree (two-way)',
    }

    def check(self, project):
        out = []
        declared, per_module = _op_semantics_tables(project)

        # -- server side: table <-> dispatch, both directions ---------------
        for modname, (module, table_node, table) in per_module.items():
            dispatched = _dispatched_ops(module)
            for op, sem in sorted(table.items()):
                if sem not in SEMANTICS:
                    self.finding(
                        module, table_node, 'idem-unknown-op',
                        "OP_SEMANTICS['%s'] = '%s' is not one of %s"
                        % (op, sem, '/'.join(SEMANTICS)), out)
                if op not in dispatched:
                    self.finding(
                        module, table_node, 'idem-unknown-op',
                        "OP_SEMANTICS declares '%s' but the handler never "
                        'dispatches it (stale entry)' % op, out)
            for op, node in sorted(dispatched.items()):
                if op not in table:
                    self.finding(
                        module, node, 'idem-unknown-op',
                        "handler dispatches op '%s' but OP_SEMANTICS does "
                        'not declare its retry semantics' % op, out)

        # -- client side: every retried send joins against the tables -------
        for module in project.modules:
            self._scan_sends(module, declared, out)
        return out

    def _scan_sends(self, module, declared, out):
        for fn in [n for n in ast.walk(module.tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
            # local `msg = {'op': ...}` bindings visible to later sends
            msg_ops = {}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    op = _dict_op_literal(node.value)
                    if op:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                msg_ops[tgt.id] = op
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if not (isinstance(f, ast.Attribute)
                        and f.attr in ('call', '_call')):
                    continue
                op = None
                for arg in node.args:
                    op = _dict_op_literal(arg)
                    if op is None and isinstance(arg, ast.Name):
                        op = msg_ops.get(arg.id)
                    if op:
                        break
                if op is None:
                    continue
                self._judge_send(module, node, op, declared, out)

    def _judge_send(self, module, node, op, declared, out):
        idem_kw = None
        for kw in node.keywords:
            if kw.arg == 'idempotent':
                idem_kw = kw.value
        if isinstance(idem_kw, ast.Constant):
            retries = bool(idem_kw.value)
            literal = True
        elif idem_kw is None:
            retries = True      # channel default
            literal = True
        else:
            retries = True      # computed: assume it can be True
            literal = False
        if op not in declared:
            self.finding(
                module, node, 'idem-undeclared-op',
                "op '%s' is sent through a retrying channel but no "
                'OP_SEMANTICS table declares its retry semantics' % op,
                out)
            return
        sem = declared[op][0]
        if sem in ('accumulating', 'non_idempotent') and retries and literal:
            self.finding(
                module, node, 'idem-retry-unsafe',
                "op '%s' is declared %s in %s but sent with retries "
                'enabled; pass idempotent=False or use call_once'
                % (op, sem, declared[op][1]), out)
        elif sem == 'conditional' and literal:
            self.finding(
                module, node, 'idem-conditional-literal',
                "op '%s' is declared conditional in %s but sent with a "
                'constant (or defaulted) idempotent=; compute it from '
                'the payload' % (op, declared[op][1]), out)
