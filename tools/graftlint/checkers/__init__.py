from .retrace import RetraceChecker
from .locks import LockChecker
from .idempotency import IdempotencyChecker
from .metrics import MetricsChecker
from .atomic_write import AtomicWriteChecker

__all__ = ['RetraceChecker', 'LockChecker', 'IdempotencyChecker',
           'MetricsChecker', 'AtomicWriteChecker', 'all_checkers']


def all_checkers():
    """Fresh instances of every registered checker."""
    return [RetraceChecker(), LockChecker(), IdempotencyChecker(),
            MetricsChecker(), AtomicWriteChecker()]
