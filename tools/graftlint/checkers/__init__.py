from .retrace import RetraceChecker
from .locks import LockChecker
from .idempotency import IdempotencyChecker
from .metrics import MetricsChecker

__all__ = ['RetraceChecker', 'LockChecker', 'IdempotencyChecker',
           'MetricsChecker', 'all_checkers']


def all_checkers():
    """Fresh instances of every registered checker."""
    return [RetraceChecker(), LockChecker(), IdempotencyChecker(),
            MetricsChecker()]
