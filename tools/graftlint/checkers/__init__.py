from .retrace import RetraceChecker
from .locks import LockChecker
from .idempotency import IdempotencyChecker
from .metrics import MetricsChecker
from .atomic_write import AtomicWriteChecker
from .events import EventsChecker

__all__ = ['RetraceChecker', 'LockChecker', 'IdempotencyChecker',
           'MetricsChecker', 'AtomicWriteChecker', 'EventsChecker',
           'all_checkers']


def all_checkers():
    """Fresh instances of every registered checker."""
    return [RetraceChecker(), LockChecker(), IdempotencyChecker(),
            MetricsChecker(), AtomicWriteChecker(), EventsChecker()]
