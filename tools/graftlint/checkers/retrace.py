"""Retrace / host-sync hazard checker.

Static complement to the CompileWatchdog (PR 9): the watchdog notices a
steady-state recompile *after* it burned a compile; this checker flags
the code shapes that cause them before the test suite ever runs.

Entry points are jit-reachable functions, discovered three ways:

- ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorated defs,
- ``jax.jit(fn, ...)`` call sites where ``fn`` resolves to a local def
  or a ``self._method`` of the enclosing class,
- inner defs of a jit function (``lax.scan`` bodies and friends), whose
  parameters are traced by construction.

Within an entry point the non-static parameters are the taint roots
(``static_argnums``/``static_argnames`` are honoured); taint propagates
through simple assignments and same-module calls whose arguments carry
taint. Rules:

- retrace-branch     — Python ``if``/``while``/ternary/``assert``/loop
                       bound on a traced value (concretization error or
                       per-value retrace);
- retrace-host-sync  — ``float()``/``int()``/``bool()``/``np.asarray()``
                       /``.item()``/``.tolist()`` on a traced value
                       (blocks dispatch, syncs the device);
- retrace-format     — f-string / ``format()`` / ``str()`` of a traced
                       value (implicit host sync for logging);
- retrace-set-iter   — iterating a ``set``/``dict`` where order feeds
                       shapes or argument order (nondeterministic cache
                       keys across processes).
"""
import ast

from ..core import Checker

_COERCIONS = {'float', 'int', 'bool'}
_NP_COERCIONS = {'asarray', 'array', 'asanyarray'}
_SYNC_METHODS = {'item', 'tolist', 'numpy'}
_ORDER_SINKS = {'reshape', 'stack', 'concatenate', 'zip'}


def _is_jit_expr(node):
    """True for ``jax.jit`` / ``jit`` / ``pjit`` expression nodes."""
    if isinstance(node, ast.Attribute):
        return node.attr in ('jit', 'pjit')
    if isinstance(node, ast.Name):
        return node.id in ('jit', 'pjit')
    return False


def _jit_static_names(call, func_node):
    """Parameter names excluded from tracing by static_argnums/argnames
    of a ``jax.jit(...)`` Call (or None when not a Call)."""
    static = set()
    if not isinstance(call, ast.Call):
        return static
    args = [a.arg for a in func_node.args.posonlyargs + func_node.args.args]
    for kw in call.keywords:
        val = kw.value
        if kw.arg == 'static_argnums':
            nums = []
            if isinstance(val, ast.Constant) and isinstance(val.value, int):
                nums = [val.value]
            elif isinstance(val, (ast.Tuple, ast.List)):
                nums = [e.value for e in val.elts
                        if isinstance(e, ast.Constant)]
            for n in nums:
                if isinstance(n, int) and 0 <= n < len(args):
                    static.add(args[n])
        elif kw.arg == 'static_argnames':
            if isinstance(val, ast.Constant) and isinstance(val.value, str):
                static.add(val.value)
            elif isinstance(val, (ast.Tuple, ast.List)):
                static.update(e.value for e in val.elts
                              if isinstance(e, ast.Constant))
    return static


def _local_defs(module):
    """{name: FunctionDef} for defs visible by bare name anywhere in the
    module (module level AND nested — jit entry points are commonly
    `jax.jit(pure_step)` on a closure-local def), plus
    {('ClassName', name): FunctionDef} for methods."""
    flat, methods = {}, {}
    for node in module.tree.body:
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods[(node.name, sub.name)] = sub
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            flat.setdefault(node.name, node)
    return flat, methods


def _find_entries(module, flat, methods):
    """[(func_node, static_param_names)] jit-entry functions."""
    entries = []
    seen = set()

    def add(fn, static):
        if fn is not None and id(fn) not in seen:
            seen.add(id(fn))
            entries.append((fn, static))

    class_of = {}
    for (cls, name), fn in methods.items():
        class_of[id(fn)] = cls

    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _is_jit_expr(target):
                    add(node, _jit_static_names(dec, node))
                elif (isinstance(dec, ast.Call)
                      and isinstance(dec.func, (ast.Name, ast.Attribute))
                      and getattr(dec.func, 'id',
                                  getattr(dec.func, 'attr', '')) == 'partial'
                      and dec.args and _is_jit_expr(dec.args[0])):
                    add(node, _jit_static_names(dec, node))
        elif isinstance(node, ast.Call) and _is_jit_expr(node.func):
            if not node.args:
                continue
            fn_expr = node.args[0]
            target = None
            if isinstance(fn_expr, ast.Name):
                target = flat.get(fn_expr.id)
            elif (isinstance(fn_expr, ast.Attribute)
                  and isinstance(fn_expr.value, ast.Name)
                  and fn_expr.value.id == 'self'):
                # jax.jit(self._decode_fn): resolve within any class that
                # defines the method — module-local, best effort
                for (cls, name), fn in methods.items():
                    if name == fn_expr.attr:
                        target = fn
                        break
            if target is not None:
                add(target, _jit_static_names(node, target))
    return entries


def _expr_names(node):
    names = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            names.add(n.id)
    return names


class _FnScan(ast.NodeVisitor):
    """Walk one jit-reachable function with a tainted-name set."""

    def __init__(self, checker, module, fn, tainted, out, queue):
        self.checker = checker
        self.module = module
        self.fn = fn
        self.tainted = set(tainted)
        self.setish = set()        # names bound to set()/dict.keys() etc.
        self.out = out
        self.queue = queue         # callee worklist: (fn_node, tainted)

    def hot(self, node):
        return bool(_expr_names(node) & self.tainted)

    def hot_test(self, node):
        """Like hot(), but ignores trace-STABLE uses of traced values:
        identity/membership comparisons (`x is not None`, `k in ref`)
        and introspection calls (isinstance/hasattr/len...) never
        concretize a tracer, so branching on them is fine."""
        stable = set()
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Compare)
                    and all(isinstance(op, (ast.Is, ast.IsNot, ast.In,
                                            ast.NotIn))
                            for op in sub.ops)):
                stable.update(id(n) for n in ast.walk(sub))
            elif (isinstance(sub, ast.Call)
                  and isinstance(sub.func, ast.Name)
                  and sub.func.id in ('isinstance', 'hasattr', 'callable',
                                      'len', 'getattr', 'type', 'id')):
                stable.update(id(n) for n in ast.walk(sub))
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
                    and sub.id in self.tainted and id(sub) not in stable):
                return True
        return False

    def run(self):
        for stmt in self.fn.body:
            self.visit(stmt)

    # -- taint propagation --------------------------------------------------

    def visit_Assign(self, node):
        self.generic_visit(node)
        hot = self.hot(node.value)
        setish = self._is_setish(node.value)
        for tgt in node.targets:
            for n in ast.walk(tgt):
                if isinstance(n, ast.Name):
                    if hot:
                        self.tainted.add(n.id)
                    else:
                        self.tainted.discard(n.id)
                    if setish:
                        self.setish.add(n.id)
                    else:
                        self.setish.discard(n.id)

    def _is_setish(self, node):
        # dict views are NOT here: python dicts iterate in insertion
        # order, which is trace-stable — only set hash order varies
        # across processes
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id in ('set', 'frozenset'):
                return True
        return False

    # -- rules --------------------------------------------------------------

    def _branch(self, test, what):
        if self.hot_test(test):
            self.checker.finding(
                self.module, test, 'retrace-branch',
                'python %s on traced value (%s) inside jit-reachable '
                '%s; use lax.cond/lax.select or hoist to host'
                % (what, ', '.join(sorted(_expr_names(test)
                                          & self.tainted)), self.fn.name),
                self.out)

    def visit_If(self, node):
        self._branch(node.test, 'branch')
        self.generic_visit(node)

    def visit_While(self, node):
        self._branch(node.test, 'loop condition')
        self.generic_visit(node)

    def visit_IfExp(self, node):
        self._branch(node.test, 'ternary')
        self.generic_visit(node)

    def visit_Assert(self, node):
        self._branch(node.test, 'assert')
        self.generic_visit(node)

    def visit_For(self, node):
        it = node.iter
        if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == 'range' and self.hot_test(it)):
            self._branch(it, 'loop bound')
        self._check_set_iter(it)
        # loop variable inherits iterable's taint — but dict KEYS are
        # static strings in a pytree, only the values are tracers
        if self.hot(it):
            view = (it.func.attr
                    if isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Attribute) else None)
            tgts = [n for n in ast.walk(node.target)
                    if isinstance(n, ast.Name)]
            if view == 'keys':
                tgts = []
            elif view == 'items' and isinstance(node.target, ast.Tuple) \
                    and len(node.target.elts) == 2:
                tgts = [n for n in ast.walk(node.target.elts[1])
                        if isinstance(n, ast.Name)]
            for n in tgts:
                self.tainted.add(n.id)
        self.generic_visit(node)

    def _check_set_iter(self, it):
        setish = self._is_setish(it) or (isinstance(it, ast.Name)
                                         and it.id in self.setish)
        if setish:
            self.checker.finding(
                self.module, it, 'retrace-set-iter',
                'iteration over a set inside jit-reachable %s: hash '
                'order is process-dependent and feeds the trace; sort it '
                'first' % self.fn.name, self.out)

    def visit_Call(self, node):
        f = node.func
        # float(x) / int(x) / bool(x) on a traced value
        if (isinstance(f, ast.Name) and f.id in _COERCIONS
                and node.args and self.hot(node.args[0])):
            self.checker.finding(
                self.module, node, 'retrace-host-sync',
                '%s() on traced value inside jit-reachable %s forces a '
                'host sync / concretization' % (f.id, self.fn.name),
                self.out)
        # np.asarray(x) and friends
        elif (isinstance(f, ast.Attribute) and f.attr in _NP_COERCIONS
              and isinstance(f.value, ast.Name)
              and f.value.id in ('np', 'numpy')
              and node.args and self.hot(node.args[0])):
            self.checker.finding(
                self.module, node, 'retrace-host-sync',
                'np.%s() on traced value inside jit-reachable %s pulls '
                'the array to host' % (f.attr, self.fn.name), self.out)
        # x.item() / x.tolist() / x.numpy()
        elif (isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS
              and self.hot(f.value)):
            self.checker.finding(
                self.module, node, 'retrace-host-sync',
                '.%s() on traced value inside jit-reachable %s forces a '
                'host sync' % (f.attr, self.fn.name), self.out)
        # str(x) / format(x) of traced value
        elif (isinstance(f, ast.Name) and f.id in ('str', 'format', 'repr')
              and node.args and self.hot(node.args[0])):
            self.checker.finding(
                self.module, node, 'retrace-format',
                '%s() of traced value inside jit-reachable %s implies a '
                'host sync' % (f.id, self.fn.name), self.out)
        else:
            self._propagate_call(node)
        self.generic_visit(node)

    def visit_JoinedStr(self, node):
        for v in node.values:
            if isinstance(v, ast.FormattedValue) and self.hot(v.value):
                self.checker.finding(
                    self.module, v.value, 'retrace-format',
                    'f-string formats traced value inside jit-reachable '
                    '%s (implicit host sync)' % self.fn.name, self.out)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        # inner def (lax.scan body etc.): params are traced by construction
        params = {a.arg for a in node.args.posonlyargs + node.args.args
                  if a.arg not in ('self', 'cls')}
        self.queue.append((node, params))
        # don't descend — the queued scan covers it

    visit_AsyncFunctionDef = visit_FunctionDef

    def _propagate_call(self, node):
        """Queue same-module callees whose arguments carry taint."""
        f = node.func
        callee = None
        if isinstance(f, ast.Name):
            callee = self.checker._flat.get(f.id)
        elif (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
              and f.value.id == 'self'):
            for (cls, name), fn in self.checker._methods.items():
                if name == f.attr:
                    callee = fn
                    break
        if callee is None:
            return
        params = [a.arg for a in callee.args.posonlyargs + callee.args.args]
        if params and params[0] in ('self', 'cls'):
            params = params[1:]
        hot_params = set()
        for i, arg in enumerate(node.args):
            if i < len(params) and self.hot(arg):
                hot_params.add(params[i])
        for kw in node.keywords:
            if kw.arg in params and self.hot(kw.value):
                hot_params.add(kw.arg)
        if hot_params:
            self.queue.append((callee, hot_params))


class RetraceChecker(Checker):
    name = 'retrace'
    RULES = {
        'retrace-branch': 'python control flow on a traced value inside a '
                          'jit-reachable function',
        'retrace-host-sync': 'float()/int()/np.asarray()/.item() coercion '
                             'of a traced value',
        'retrace-format': 'f-string/str()/format() of a traced value',
        'retrace-set-iter': 'set (hash-order) iteration feeding a trace',
    }

    def check(self, project):
        out = []
        for module in project.modules:
            self._flat, self._methods = _local_defs(module)
            entries = _find_entries(module, self._flat, self._methods)
            queue = []
            for fn, static in entries:
                params = {a.arg for a in
                          fn.args.posonlyargs + fn.args.args
                          if a.arg not in ('self', 'cls')} - set(static)
                queue.append((fn, params))
            scanned = {}
            while queue:
                fn, tainted = queue.pop()
                key = id(fn)
                prev = scanned.get(key)
                if prev is not None and tainted <= prev:
                    continue
                scanned[key] = (prev or set()) | set(tainted)
                _FnScan(self, module, fn, scanned[key], out, queue).run()
        # a re-scan of the same function with a larger taint set repeats
        # its findings; collapse exact duplicates
        uniq, seen = [], set()
        for f in out:
            k = (f.rule, f.path, f.line, f.col, f.message)
            if k not in seen:
                seen.add(k)
                uniq.append(f)
        return uniq
