"""Metric-schema and span-hygiene checker.

The committed schema (tools/metrics_schema_baseline.json) is the
contract consumers scrape against; code and schema must agree BOTH
ways:

- metric-unknown-family — code registers a family (``registry.counter/
  gauge/histogram('name', ...)`` or a ``*_FAMILIES`` table entry) whose
  name is not in the schema baseline;
- metric-stale-family   — the baseline lists a family no code registers
  any more (only checked when the project includes the telemetry module,
  so fixture runs don't drown in repo-wide noise);
- metric-label-arity    — a ``fam.labels(...)`` call passes a different
  number of label values than the family declared (registry raises at
  runtime; this catches it at lint time);
- span-no-cm            — ``tracer.start_span()/server_span()`` result
  discarded or bound to a local that is never entered/finished/escaped
  (the span leaks open and poisons the flight recorder).
"""
import ast
import json
import os
import re

from ..core import Checker, Finding, REPO_ROOT

DEFAULT_SCHEMA = os.path.join(REPO_ROOT, 'tools',
                              'metrics_schema_baseline.json')
ANCHOR_MODULE = 'paddle_tpu.monitor.telemetry'

_NAME_RE = re.compile(r'^[a-z][a-z0-9_]*_[a-z0-9_]*$')
_REG_METHODS = ('counter', 'gauge', 'histogram')
_SPAN_OPENERS = ('start_span', 'server_span')


def _str_tuple(node):
    """('a', 'b') when node is a tuple/list of str constants, else None."""
    if isinstance(node, (ast.Tuple, ast.List)):
        elts = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
        return tuple(e.value for e in node.elts)
    return None


def _registration_sites(module):
    """[(name, labels_or_None, node)] family registrations in a module:
    registry method calls plus *_FAMILIES table entries."""
    sites = []
    for node in ast.walk(module.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _REG_METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and _NAME_RE.match(node.args[0].value)):
            labels = ()
            for kw in node.keywords:
                if kw.arg in ('labels', 'labelnames'):
                    labels = _str_tuple(kw.value)
            for arg in node.args[1:]:
                got = _str_tuple(arg)
                if got is not None:
                    labels = got
            sites.append((node.args[0].value, labels, node))
        elif isinstance(node, ast.Assign):
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if not any(n.endswith('_FAMILIES') for n in names):
                continue
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                continue
            for entry in node.value.elts:
                if not isinstance(entry, (ast.Tuple, ast.List)):
                    continue
                # entries are (kind, name, help[, labels]): the family
                # name is the first metric-shaped string that is not a
                # registry kind keyword
                fam, at = None, 0
                for i, e in enumerate(entry.elts):
                    if (isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                            and e.value not in _REG_METHODS
                            and _NAME_RE.match(e.value)):
                        fam, at = e.value, i
                        break
                if fam is None:
                    continue
                labels = ()
                for e in entry.elts[at + 1:]:
                    got = _str_tuple(e)
                    if got is not None:
                        labels = got
                sites.append((fam, labels, entry))
    return sites


def _parent_map(tree):
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


class MetricsChecker(Checker):
    name = 'metrics'
    RULES = {
        'metric-unknown-family': 'code registers a metric family missing '
                                 'from the schema baseline',
        'metric-stale-family': 'the schema baseline lists a family no code '
                               'registers',
        'metric-label-arity': '.labels(...) call disagrees with the '
                              'declared label set',
        'span-no-cm': 'tracer span opened without context manager, finish, '
                      'or escape',
    }

    def __init__(self, schema_path=DEFAULT_SCHEMA):
        self.schema_path = schema_path

    def _load_schema(self):
        if not os.path.exists(self.schema_path):
            return {}
        with open(self.schema_path) as fh:
            data = json.load(fh)
        fams = data.get('families', data)
        out = {}
        for name, entry in fams.items():
            labels = tuple(entry.get('labels', ())) \
                if isinstance(entry, dict) else ()
            out[name] = labels
        return out

    def check(self, project):
        out = []
        schema = self._load_schema()
        registered = {}                  # name -> (labels, module, node)
        for module in project.modules:
            for name, labels, node in _registration_sites(module):
                registered.setdefault(name, (labels, module, node))
                if name not in schema:
                    self.finding(
                        module, node, 'metric-unknown-family',
                        "metric family '%s' is not in %s; add it via "
                        'tools/check_metrics_snapshot.py --write-baseline '
                        'after registering it in the dryrun schema'
                        % (name, os.path.relpath(self.schema_path,
                                                 REPO_ROOT)), out)
                elif labels is not None and tuple(labels) != schema[name]:
                    self.finding(
                        module, node, 'metric-label-arity',
                        "metric family '%s' declares labels %r but the "
                        'schema baseline says %r'
                        % (name, tuple(labels), schema[name]), out)
            self._check_label_calls(module, registered, schema, out)
            self._check_spans(module, out)

        if ANCHOR_MODULE in project.by_modname:
            rel = os.path.relpath(self.schema_path, REPO_ROOT)
            for name in sorted(set(schema) - set(registered)):
                out.append(Finding(
                    'metric-stale-family', rel.replace(os.sep, '/'), 1,
                    "schema baseline lists '%s' but no code registers it"
                    % name, symbol=name))
        return out

    # -- label arity at .labels() sites -------------------------------------

    def _check_label_calls(self, module, registered, schema, out):
        # map local/self names to family names within this module
        fam_of = {}
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            fam = None
            v = node.value
            if (isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Attribute)
                    and v.func.attr in _REG_METHODS
                    and v.args and isinstance(v.args[0], ast.Constant)
                    and isinstance(v.args[0].value, str)):
                fam = v.args[0].value
            elif (isinstance(v, ast.Subscript)
                  and isinstance(v.slice, ast.Constant)
                  and isinstance(v.slice.value, str)
                  and _NAME_RE.match(str(v.slice.value))):
                fam = v.slice.value
            if fam is None:
                continue
            for tgt in node.targets:
                key = self._ref_key(tgt)
                if key:
                    fam_of[key] = fam
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == 'labels'):
                continue
            key = self._ref_key(node.func.value)
            fam = fam_of.get(key)
            if fam is None:
                continue
            declared = schema.get(fam)
            if declared is None and fam in registered:
                declared = registered[fam][0]
            if declared is None:
                continue
            got = len(node.args) + len(node.keywords)
            if got != len(declared):
                self.finding(
                    module, node, 'metric-label-arity',
                    ".labels() on '%s' passes %d value(s) but the family "
                    'declares %d label(s) %r'
                    % (fam, got, len(declared), tuple(declared)), out)

    def _ref_key(self, node):
        if isinstance(node, ast.Name):
            return node.id
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == 'self'):
            return 'self.' + node.attr
        return None

    # -- span hygiene --------------------------------------------------------

    def _check_spans(self, module, out):
        parents = _parent_map(module.tree)
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SPAN_OPENERS):
                continue
            parent = parents.get(node)
            if isinstance(parent, ast.withitem):
                continue
            if isinstance(parent, ast.Expr):
                self.finding(
                    module, node, 'span-no-cm',
                    '%s() result discarded: the span is opened and can '
                    'never be finished; use `with` or keep a handle'
                    % node.func.attr, out)
                continue
            if (isinstance(parent, ast.Assign) and len(parent.targets) == 1
                    and isinstance(parent.targets[0], ast.Name)):
                name = parent.targets[0].id
                fn = self._enclosing_fn(parents, node)
                if fn is not None and not self._name_escapes(fn, name,
                                                             parent):
                    self.finding(
                        module, node, 'span-no-cm',
                        "span bound to '%s' is never entered, finished, "
                        'or handed off; it leaks open' % name, out)

    def _enclosing_fn(self, parents, node):
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parents.get(cur)
        return None

    def _name_escapes(self, fn, name, binding):
        """True when `name` is used anywhere beyond its binding statement
        (entered, finished, returned, passed along, re-stored...)."""
        binding_names = {id(n) for t in binding.targets
                         for n in ast.walk(t)}
        for node in ast.walk(fn):
            if (isinstance(node, ast.Name) and node.id == name
                    and id(node) not in binding_names):
                return True
        return False
