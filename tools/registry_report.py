"""Multi-model serving gate: registry metrics joined with per-model
wide events (monitor/events.py + REGISTRY_FAMILIES in telemetry.py).

Inputs:

    --jsonl   FILE     a RequestLog sink (repeatable) — the per-model
                       wide events of the run under review;
    --rollout FILE     the JSON summary a `ServingGateway.rollout()`
                       returned (the bench writes it next to its rows),
                       optionally extended with the replay's
                       'requests' / 'completed' counts;
    --metrics FILE     a monitor export.to_dict() JSON snapshot — the
                       registry_* families are read out of it.

The gate asks the two questions a hot-swap must answer:

  * **Did the rollout lose requests?** `completed < requests` in the
    rollout summary (or any wide event for the swapped model with a
    non-ok outcome when --model is given) is a finding — the whole
    point of drain-never-kill weight swaps is completed_ratio == 1.0.
  * **Did the warm bring-up miss the compile cache?** `cache_misses >
    0` in the rollout summary means the new version recompiled instead
    of hitting the content-fingerprint-keyed persistent cache — a
    finding, because a recompiling rollout stalls the pool for the
    compile time it was designed to avoid.

Metrics cross-checks (when --metrics is given): evictions counted while
registry_evictions_deferred_total stayed zero AND in-flight refcounts
were claimed is fine; what the gate flags is a negative residency gauge
or resident bytes above --byte-budget — both impossible states that
mean the paging accounting broke.

Exit codes (tools/gate_common): 0 ok, 1 findings, 2 nothing to check.
"""
import argparse
import json
import os
import sys
import types

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# monitor/ is stdlib-only but the package __init__ pulls in jax: load
# the subpackage without executing the parent (request_report's pattern)
if 'paddle_tpu' not in sys.modules:
    _pkg = types.ModuleType('paddle_tpu')
    _pkg.__path__ = [os.path.join(_REPO_ROOT, 'paddle_tpu')]
    sys.modules['paddle_tpu'] = _pkg

from tools import gate_common  # noqa: E402
from tools.request_report import (load_events,  # noqa: E402
                                  rollup_by_model)

__all__ = ['registry_values', 'check', 'main']


def registry_values(metrics_doc):
    """{metric_name: scalar or {label_tuple: scalar}} for the
    registry_* families of an export.to_dict() snapshot. Histograms
    reduce to their sample count (the gate only needs 'how many loads
    were observed')."""
    out = {}
    for name, fam in (metrics_doc or {}).items():
        if not name.startswith('registry_'):
            continue
        samples = fam.get('samples') or ()
        vals = {}
        for s in samples:
            labels = tuple(sorted((s.get('labels') or {}).items()))
            vals[labels] = (s['count'] if 'count' in s
                            else s.get('value', 0))
        if list(vals) == [()]:
            out[name] = vals[()]
        else:
            out[name] = vals
    return out


def check(events, rollout=None, metrics=None, model=None,
          byte_budget=None):
    """Pure gate: findings list (empty == pass)."""
    findings = []
    if rollout:
        req = rollout.get('requests')
        done = rollout.get('completed')
        if req is not None and done is not None and done < req:
            findings.append({
                'problem': 'rollout_lost_requests',
                'model': rollout.get('model'),
                'from_version': rollout.get('from_version'),
                'to_version': rollout.get('to_version'),
                'requests': req, 'completed': done,
                'note': 'a zero-downtime rollout must complete every '
                        'in-flight and queued request (drain-never-kill '
                        'applied to weights)'})
        if int(rollout.get('cache_misses') or 0) > 0:
            findings.append({
                'problem': 'rollout_compile_cache_miss',
                'model': rollout.get('model'),
                'to_version': rollout.get('to_version'),
                'cache_misses': int(rollout['cache_misses']),
                'cache_hits': int(rollout.get('cache_hits') or 0),
                'note': 'warm bring-up recompiled — the new version '
                        'should hit the persistent compile cache (same '
                        'program shapes, new weights)'})
    if model is not None:
        for ev in events:
            if ev.get('model') == model and \
                    ev.get('outcome') not in (None, 'ok'):
                findings.append({
                    'problem': 'model_request_not_ok',
                    'model': model,
                    'request_id': ev.get('request_id'),
                    'outcome': ev.get('outcome')})
    vals = registry_values(metrics)
    resident = vals.get('registry_resident_bytes')
    if isinstance(resident, (int, float)):
        if resident < 0:
            findings.append({'problem': 'negative_resident_bytes',
                             'registry_resident_bytes': resident})
        elif byte_budget is not None and resident > byte_budget:
            findings.append({
                'problem': 'resident_bytes_over_budget',
                'registry_resident_bytes': resident,
                'byte_budget': byte_budget,
                'note': 'weight paging must hold the residency gauge '
                        'at or under the configured byte budget'})
    n_models = vals.get('registry_models_resident')
    if isinstance(n_models, (int, float)) and n_models < 0:
        findings.append({'problem': 'negative_models_resident',
                         'registry_models_resident': n_models})
    return findings


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--jsonl', action='append', default=[],
                    help='RequestLog JSONL sink (repeatable)')
    ap.add_argument('--rollout',
                    help='rollout summary JSON (gateway.rollout() '
                         'return value, + optional requests/completed)')
    ap.add_argument('--metrics',
                    help='export.to_dict() JSON snapshot to cross-check '
                         'registry_* families')
    ap.add_argument('--model',
                    help='gate: fail on any non-ok wide event for this '
                         'model (the swapped one)')
    ap.add_argument('--byte-budget', type=int,
                    help='gate: registry_resident_bytes must not '
                         'exceed this')
    args = ap.parse_args(argv)

    events, skipped = load_events(args.jsonl, ())
    rollout = metrics = None
    if args.rollout:
        with open(args.rollout, errors='replace') as f:
            rollout = json.load(f)
    if args.metrics:
        with open(args.metrics, errors='replace') as f:
            metrics = json.load(f)
    if not events and rollout is None and metrics is None:
        return gate_common.nothing_to_check(
            'no wide events, rollout summary or metrics snapshot',
            skipped=skipped)

    findings = check(events, rollout=rollout, metrics=metrics,
                     model=args.model, byte_budget=args.byte_budget)
    summary = {'events': len(events), 'skipped_lines': skipped,
               'models': rollup_by_model(events)}
    if rollout is not None:
        summary['rollout'] = rollout
    if metrics is not None:
        summary['registry_metrics'] = registry_values(metrics)
    return gate_common.finish(findings, summary)


if __name__ == '__main__':
    sys.exit(main())
