#!/bin/bash
# Probe the axon TPU every 3 minutes; log transitions; on an up-window,
# fire tools/tpu_warmer.py (lockfile-serialized) so the persistent compile
# cache + an in-window bench number get captured without supervision.
REPO="$(cd "$(dirname "$0")/.." && pwd)"
while true; do
  if timeout 90 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu'" 2>/dev/null; then
    echo "$(date -u +%H:%M:%S) TPU OK" >> /tmp/tpu_probe.log
    nohup python "$REPO/tools/tpu_warmer.py" >> /tmp/tpu_warmer.out 2>&1 &
  else
    echo "$(date -u +%H:%M:%S) TPU DOWN" >> /tmp/tpu_probe.log
  fi
  sleep 180
done
