"""Metrics-schema gate over dryrun telemetry snapshots.

The driver's dryrun prints one `telemetry_snapshot(N)[tag]: {json}` line
per config (__graft_entry__, same pattern as sharding_audit). This tool
re-parses those lines and diffs the METRIC SCHEMA — metric names, types,
and label keys — against a committed baseline
(tools/metrics_schema_baseline.json), failing when an instrumented
metric silently disappears or changes shape. Values are deliberately
ignored: loss and RSS move run to run; the instrumentation's existence
must not.

Inputs (one of):
    --new  MULTICHIP_rNN.json   a driver capture ({..., 'tail': ...})
    --text FILE|-               raw driver output (or stdin)

Rules:
  * every baseline metric must appear in the new run's union schema,
    with the same type and label keys (missing/changed -> exit 1);
  * NEW metrics pass with a note — add them to the baseline via
    --write-baseline once they are intentional;
  * no telemetry lines / no baseline -> exit 2 (nothing to compare).

Same shape as tools/check_sharding_regression.py so CI wires both the
same way.
"""
import argparse
import json
import os
import sys
import types

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# paddle_tpu/monitor is stdlib-only, but the paddle_tpu package __init__
# pulls in jax (seconds per invocation). CI calls this gate per capture,
# so load the subpackage without executing the parent __init__.
if 'paddle_tpu' not in sys.modules:
    _pkg = types.ModuleType('paddle_tpu')
    _pkg.__path__ = [os.path.join(_REPO_ROOT, 'paddle_tpu')]
    sys.modules['paddle_tpu'] = _pkg

from paddle_tpu.monitor import schema_of  # noqa: E402
from paddle_tpu.monitor.telemetry import parse_snapshot_lines  # noqa: E402
from tools import gate_common  # noqa: E402

__all__ = ['union_schema', 'check', 'main']

DEFAULT_BASELINE = os.path.join(_REPO_ROOT, 'tools',
                                'metrics_schema_baseline.json')


def union_schema(text):
    """Union {metric: {'type', 'labels'}} across every config's
    telemetry snapshot in the captured text (plus per-tag schemas)."""
    per_tag = {tag: schema_of(snap)
               for tag, snap in parse_snapshot_lines(text).items()}
    union = {}
    for schema in per_tag.values():
        union.update(schema)
    return union, per_tag


def check(text, baseline):
    """Pure gate: list of findings (empty == pass)."""
    union, per_tag = union_schema(text)
    findings = []
    for name in sorted(baseline):
        want = baseline[name]
        got = union.get(name)
        if got is None:
            findings.append({'metric': name, 'problem': 'missing',
                             'note': 'instrumented metric disappeared '
                                     'from the dryrun telemetry'})
        elif got != want:
            findings.append({'metric': name, 'problem': 'schema_changed',
                             'baseline': want, 'new': got})
    return findings


def _load_text(args):
    if args.new:
        with open(args.new, errors='replace') as f:
            return json.load(f).get('tail', '')
    if args.text == '-':
        return sys.stdin.read()
    with open(args.text, errors='replace') as f:
        return f.read()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument('--new', help='driver capture JSON (MULTICHIP_r*.json)')
    src.add_argument('--text', help="raw driver output file, or '-' (stdin)")
    ap.add_argument('--baseline', default=DEFAULT_BASELINE,
                    help='schema baseline JSON (default: %(default)s)')
    ap.add_argument('--write-baseline', action='store_true',
                    help='write the new union schema to --baseline and '
                         'exit 0')
    args = ap.parse_args(argv)

    text = _load_text(args)
    union, per_tag = union_schema(text)
    if not union:
        return gate_common.nothing_to_check(
            'no telemetry_snapshot lines found')

    if args.write_baseline:
        with open(args.baseline, 'w') as f:
            json.dump(union, f, indent=2, sort_keys=True)
            f.write('\n')
        gate_common.emit({'wrote': args.baseline, 'metrics': len(union)})
        return gate_common.OK

    if not os.path.exists(args.baseline):
        return gate_common.nothing_to_check('no baseline schema')
    with open(args.baseline) as f:
        baseline = json.load(f)

    findings = check(text, baseline)
    extra = sorted(set(union) - set(baseline))
    return gate_common.finish(findings, {
        'regressions': 0, 'metrics_seen': len(union),
        'configs': sorted(per_tag),
        'tracing_families': sum(
            1 for n in union if n.startswith('trace_')),
        'gateway_families': sum(
            1 for n in union if n.startswith('gateway_')),
        'new_unbaselined': extra})


if __name__ == '__main__':
    sys.exit(main())
