"""Perf-regression gate over bench capture logs.

Compares a NEW capture log (JSONL rows as written by bench.py children,
tools/tpu_warmer.py, or bench_extra.py) against the stored best and
FAILS (exit 1) when any same-config metric regresses more than the
threshold (default 10%). Reference counterpart:
tools/check_op_benchmark_result.py, which gates op microbenchmark PRs
the same way — compare same-case logs, alarm past a ratio.

"Same config" means: same metric AND same effective replay environment.
Rows are canonicalized through bench._capture_replay_env +
bench._effective_env, so a legacy row with unstated knobs and a new row
spelling out today's defaults still land in the same bucket (the whole
point of those helpers), plus the auxiliary workload fields
(num_slots/new_tokens/... for the serving and decode rungs).

Only trustworthy rows participate: real-TPU, non-degraded, non-suspect,
no error field — the same eligibility rule as bench._best_capture.

Usage:
    python tools/check_bench_regression.py --new NEW.jsonl \
        [--baseline BEST.jsonl ...] [--threshold 0.10]

With no --baseline, the repo's in-window logs (bench._inwindow_log_paths)
are the stored best. Exit codes: 0 ok, 1 regression, 2 nothing to check.
"""
import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from tools import gate_common  # noqa: E402

# auxiliary config fields that distinguish otherwise same-env rows
# (bench_extra rungs vary these, not the knob env). The paged-serving
# rung adds page_size/spec_k/workload: a spec-on row must never land in
# a spec-off row's regression bucket. `tenant` keys the mixed-tenant
# gateway rung's per-tenant TTFT rows — premium and batch latencies are
# different contracts and must gate separately. `transport`/`n_procs`
# key the serving-fabric rung: in-proc and socket-transport rows are
# different regimes (the process boundary is the measured cost).
_AUX_CONFIG = ('replicas', 'kill_at', 'policy',
               'num_slots', 'new_tokens', 'prompt_len', 'image_size',
               'trace', 'model', 'n_models', 'swap_at', 'scan_steps',
               'page_size', 'spec_k', 'workload', 'tenant',
               'transport', 'n_procs')

__all__ = ['eligible', 'config_key', 'higher_is_better', 'expand_derived',
           'check', 'main']

# row fields that gate as first-class metrics of their own. Synthesized
# as pseudo-rows ('<metric>_compile_s_cold', unit 's') rather than added
# to _AUX_CONFIG: an aux field would bucket-split every existing config
# and orphan the stored bests. compile_cache_hit_rate (unit 'ratio')
# regresses DOWNWARD like throughput — a warmed persistent cache losing
# its hits is exactly the cold-start regression this column exists for.
_DERIVED_KEYS = ('compile_s_cold', 'compile_s_warm',
                 'compile_cache_hit_rate')
_DERIVED_UNITS = {'compile_cache_hit_rate': 'ratio'}


def eligible(row, trust_degraded=False):
    """bench._best_capture's trust rule: real-TPU, clean, measured.
    `trust_degraded` relaxes the platform/degraded half — the
    compile-cache rungs are measured on CPU (XLA compile + persistent
    cache behave identically there) and gate via an explicit
    --trust-degraded invocation against their own committed baseline,
    never against the real-TPU bests."""
    if not (not row.get('suspect')
            and 'error' not in row
            and isinstance(row.get('value'), (int, float))
            and row.get('metric')):
        return False
    if trust_degraded:
        return True
    return row.get('platform', 'tpu') == 'tpu' and not row.get('degraded')


def config_key(row):
    """Canonical same-config identity for a capture row."""
    import bench
    env = bench._effective_env(bench._capture_replay_env(row))
    aux = tuple((k, row[k]) for k in _AUX_CONFIG if k in row)
    return (row['metric'],) + aux + tuple(sorted(env.items()))


def higher_is_better(row):
    """Throughput-style metrics regress DOWN; latency-style and
    compile-time metrics regress UP. hit_rate is checked first: cache
    hit rates are higher-is-better even though 'compile' is in the
    metric name."""
    text = '%s %s' % (row.get('metric', ''), row.get('unit', ''))
    if 'hit_rate' in text:
        return True
    if 'completed_ratio' in text:
        # QoS rung: premium requests finishing is the whole contract
        return True
    if 'shed_rate' in text:
        # QoS rung: more shedding on the same workload = policy or
        # capacity regression, even though shedding itself is by design
        return False
    if 'mttr' in text:
        # recovery time: a faster supervisor is a better supervisor
        return False
    if 'ttft' in text:
        # time-to-first-token (incl. the per-tenant columns): latency
        return False
    if 'divergence' in text or 'rel_err' in text:
        # sim-vs-real calibration error (capacity_sim_ttft_divergence):
        # a better-calibrated simulator diverges LESS
        return False
    if 'min_replicas' in text:
        # capacity answer: fewer replicas for the same SLO is better
        return False
    if 'data_wait' in text:
        # ingest rung: fraction of step wall blocked on input — the
        # number the async prefetcher exists to drive to zero
        return False
    if 'examples_per_sec' in text:
        # ingest throughput (explicit so a future unit rename can't
        # flip it into the latency default)
        return True
    return not ('ms' in text.split() or 'latency' in text
                or text.endswith('_ms') or 'compile' in text)


def expand_derived(rows):
    """rows + pseudo-rows for the derived gate keys: a row carrying
    compile_s_cold/compile_s_warm also gates those values under
    '<metric>_compile_s_cold' (unit 's'). mfu_est and the roofline
    fields stay informational — analytic estimates, not measurements."""
    out = list(rows)
    for row in rows:
        if not row.get('metric') or 'error' in row:
            continue
        for key in _DERIVED_KEYS:
            val = row.get(key)
            if isinstance(val, (int, float)):
                derived = dict(row)
                derived['metric'] = '%s_%s' % (row['metric'], key)
                derived['value'] = float(val)
                derived['unit'] = _DERIVED_UNITS.get(key, 's')
                out.append(derived)
    return out


def check(new_rows, baseline_rows, threshold=0.10, trust_degraded=False):
    """Pure gate: list of regression findings (empty == pass).

    For every config present in BOTH logs, the best new value must not
    be worse than the stored best by more than `threshold`. Configs only
    one side knows are skipped — a new rung has no best yet, and a
    retired rung must not block forever.
    """
    def best_by_config(rows):
        best = {}
        for row in rows:
            if not eligible(row, trust_degraded=trust_degraded):
                continue
            key = config_key(row)
            cur = best.get(key)
            if cur is None:
                best[key] = row
            elif higher_is_better(row) == (row['value'] > cur['value']):
                best[key] = row
        return best

    stored = best_by_config(expand_derived(baseline_rows))
    fresh = best_by_config(expand_derived(new_rows))
    findings = []
    for key, old in sorted(stored.items()):
        new = fresh.get(key)
        if new is None:
            continue
        hib = higher_is_better(old)
        ratio = (new['value'] / old['value']) if old['value'] else 1.0
        regressed = (ratio < 1.0 - threshold) if hib \
            else (ratio > 1.0 + threshold)
        if regressed:
            findings.append({
                'metric': old['metric'],
                'stored_best': old['value'],
                'new_best': new['value'],
                'ratio': round(ratio, 4),
                'threshold': threshold,
                'direction': 'down' if hib else 'up',
                'stored_label': old.get('label'),
                'new_label': new.get('label'),
            })
    return findings


def _load_jsonl(path):
    rows = []
    with open(path, errors='replace') as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                continue
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--new', required=True, help='new capture JSONL')
    ap.add_argument('--baseline', action='append', default=[],
                    help='stored-best JSONL (repeatable; default: the '
                         'repo in-window logs)')
    ap.add_argument('--threshold', type=float, default=0.10,
                    help='allowed fractional regression (default 0.10)')
    ap.add_argument('--trust-degraded', action='store_true',
                    help='admit non-TPU/degraded rows (compile-cache CPU '
                         'rungs gating against their own baseline)')
    args = ap.parse_args(argv)

    baselines = args.baseline
    if not baselines:
        import bench
        baselines = [p for p in bench._inwindow_log_paths()
                     if os.path.exists(p)]
    new_rows = _load_jsonl(args.new)
    base_rows = [r for p in baselines for r in _load_jsonl(p)]
    if not new_rows or not base_rows:
        return gate_common.nothing_to_check(
            'nothing to compare (new=%d baseline=%d eligible rows '
            'pre-filter)' % (len(new_rows), len(base_rows)))
    findings = check(new_rows, base_rows, threshold=args.threshold,
                     trust_degraded=args.trust_degraded)
    return gate_common.finish(
        findings, {'regressions': 0, 'threshold': args.threshold})


if __name__ == '__main__':
    sys.exit(main())
