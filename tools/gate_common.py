"""Shared exit-code / JSON-output convention for the repo's CI gates.

Every gate in tools/ (check_bench_regression, check_sharding_regression,
check_metrics_snapshot, graftlint) speaks the same protocol so CI wires
them identically:

  exit 0  (OK)      — checked something, no findings; last stdout line is
                      a JSON summary with ``"ok": true``;
  exit 1  (FAIL)    — findings; one JSON line per finding, each carrying
                      ``"regression": true`` (the grep-able marker);
  exit 2  (NOTHING) — nothing to compare/analyze (missing baseline, empty
                      input); a JSON note with ``"checked": 0``.

``finish()`` is the whole protocol: hand it the findings and the summary
fields and return its result from main(). Gates stay pure (their check()
functions return finding lists) and the I/O convention lives here once.
"""
import json
import sys

__all__ = ['OK', 'FAIL', 'NOTHING', 'emit', 'nothing_to_check', 'finish']

OK = 0
FAIL = 1
NOTHING = 2


def emit(obj, stream=None):
    """One JSON object per line on stdout (machine-parseable, append-safe)."""
    print(json.dumps(obj), file=stream if stream is not None else sys.stdout)


def nothing_to_check(note, stream=None, **extra):
    """Report an empty comparison and return the NOTHING exit code."""
    emit(dict({'checked': 0, 'note': note}, **extra), stream=stream)
    return NOTHING


def finish(findings, summary=None, stream=None):
    """Print findings (each marked ``regression: true``) or the ok-summary,
    and return the exit code for main()."""
    for f in findings:
        emit(dict(f, regression=True), stream=stream)
    if not findings:
        emit(dict(summary or {}, ok=True), stream=stream)
        return OK
    return FAIL
