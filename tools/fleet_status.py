"""Unified fleet status: one offline surface over the telemetry plane.

Joins the three artifacts the fleet leaves behind:

  * fleet snapshots — `fleet_snapshot(N)[tag]: {...}` dryrun lines, or
    a saved `/fleet` JSON body (curl it off any MetricsServer with a
    FleetCollector attached) -> per-target liveness table + the merged
    headline counters;
  * alert state — a saved `/alerts` JSON body -> per-rule state table
    (firing rules first) with fire/resolve counts;
  * per-process flight dumps — one `name=DIR` pair per process ->
    combined Chrome trace with one process lane per name, so a fleet
    incident reads as aligned timelines in Perfetto (epoch-based span
    timestamps need no offset bookkeeping — see tracing.spans_to_chrome).

Every section is optional: pass what the deployment produced.

Usage:
    python tools/fleet_status.py [--fleet FILE|-] [--alerts FILE]
        [--flight NAME=DIR ...] [--chrome-out TRACE.json]
"""
import argparse
import json
import os
import sys
import types

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)
_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

# monitor/ is stdlib-only but the package __init__ pulls in jax — load
# the subpackage without the parent (the check_metrics_snapshot pattern)
if 'paddle_tpu' not in sys.modules:
    _pkg = types.ModuleType('paddle_tpu')
    _pkg.__path__ = [os.path.join(_REPO_ROOT, 'paddle_tpu')]
    sys.modules['paddle_tpu'] = _pkg

from paddle_tpu.monitor.federation import FLEET_LINE_RE  # noqa: E402
from paddle_tpu.monitor.tracing import spans_to_chrome   # noqa: E402
from perf_report import flight_spans                     # noqa: E402

__all__ = ['parse_fleet_text', 'fleet_section', 'alerts_section',
           'flight_section', 'report', 'main']


def parse_fleet_text(text):
    """{tag: fleet status dict}. Accepts either captured dryrun output
    (fleet_snapshot lines, later duplicates of a tag win) or a single
    raw /fleet JSON body (keyed under tag '')."""
    out = {}
    for line in (text or '').splitlines():
        m = FLEET_LINE_RE.search(line)
        if not m:
            continue
        try:
            out[m.group('tag')] = json.loads(m.group('json'))
        except ValueError:
            continue
    if not out:
        try:
            body = json.loads(text)
        except ValueError:
            return {}
        if isinstance(body, dict) and 'targets' in body:
            out[''] = body
    return out


def _fmt_val(v):
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return ('%.6g' % v) if isinstance(v, float) else str(v)


def fleet_section(status, tag=''):
    """Text lines for one fleet status dict: liveness table, then the
    merged counter totals (the 'how much did the FLEET do' headline)."""
    out = []
    targets = status.get('targets') or {}
    out.append('fleet%s: %d/%d targets up'
               % ((' %s' % tag) if tag else '',
                  status.get('up', 0), len(targets)))
    for inst in sorted(targets):
        t = targets[inst]
        state = 'up' if t.get('up') else (
            'down (stale data held)' if t.get('stale') else 'down (no data)')
        line = ('  %-20s %-22s scrapes=%d errors=%d'
                % (inst, state, t.get('scrapes', 0), t.get('errors', 0)))
        if t.get('staleness_s') is not None:
            line += ' age=%.1fs' % t['staleness_s']
        if not t.get('up') and t.get('last_error'):
            line += '  [%s]' % t['last_error']
        out.append(line)
    merged = status.get('merged') or {}
    counters = []
    for name in sorted(merged):
        fam = merged[name]
        if fam.get('type') != 'counter':
            continue
        total = sum(float(s.get('value') or 0.0)
                    for s in fam.get('samples', ()))
        if total:
            counters.append((name, total))
    if counters:
        out.append('  merged counters:')
        for name, total in counters:
            out.append('    %-40s %s' % (name, _fmt_val(total)))
    return out


def alerts_section(body):
    """Text lines for an /alerts JSON body, firing rules first."""
    out = []
    firing = body.get('firing') or []
    out.append('alerts: %d firing%s'
               % (len(firing), (' (%s)' % ', '.join(firing))
                  if firing else ''))
    entries = body.get('alerts') or []
    order = {'firing': 0, 'pending': 1}
    for e in sorted(entries, key=lambda e: (
            order.get(e.get('state'), 2), e.get('rule', {}).get('name', ''))):
        rule = e.get('rule') or {}
        line = ('  %-24s %-8s fired=%d resolved=%d'
                % (rule.get('name', '?'), e.get('state', '?'),
                   e.get('fired_count', 0), e.get('resolved_count', 0)))
        if e.get('value') is not None:
            line += ' value=%s' % _fmt_val(e['value'])
        if rule.get('metric'):
            line += '  [%s]' % rule['metric']
        out.append(line)
    return out


def flight_section(named_dirs, chrome_out=None):
    """Join per-process flight dumps into one Chrome trace with a lane
    per process. `named_dirs` is [(name, dir)]; pids are assigned by
    position so lanes are stable across re-runs."""
    out, events = [], []
    for pid, (name, d) in enumerate(named_dirs, start=1):
        spans = [s for s, _meta in flight_spans(d)]
        out.append('flight %s (%s): %d spans' % (name, d, len(spans)))
        events.extend(spans_to_chrome(spans, pid=pid,
                                      process_name=name)['traceEvents'])
    if chrome_out and events:
        with open(chrome_out, 'w') as f:
            json.dump({'traceEvents': events}, f)
        out.append('chrome trace: %s (%d events)'
                   % (chrome_out, len(events)))
    return out


def report(fleet_text=None, alerts_text=None, named_dirs=(),
           chrome_out=None):
    out = []
    if fleet_text:
        snaps = parse_fleet_text(fleet_text)
        for tag in sorted(snaps):
            out.extend(fleet_section(snaps[tag], tag=tag))
    if alerts_text:
        try:
            body = json.loads(alerts_text)
        except ValueError:
            body = None
        if isinstance(body, dict):
            out.extend(alerts_section(body))
        else:
            out.append('alerts: unparseable body')
    if named_dirs:
        out.extend(flight_section(named_dirs, chrome_out=chrome_out))
    if not out:
        out.append('nothing to report: pass --fleet, --alerts, '
                   'or --flight NAME=DIR')
    return out


def _read(arg):
    if arg == '-':
        return sys.stdin.read()
    with open(arg, errors='replace') as f:
        return f.read()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--fleet',
                    help='dryrun output with fleet_snapshot lines, a '
                         'saved /fleet JSON body, or - for stdin')
    ap.add_argument('--alerts', help='saved /alerts JSON body')
    ap.add_argument('--flight', action='append', default=[],
                    metavar='NAME=DIR',
                    help='per-process flight-dump dir; repeatable')
    ap.add_argument('--chrome-out',
                    help='write the combined multi-lane Chrome trace '
                         'here')
    args = ap.parse_args(argv)
    named = []
    for spec in args.flight:
        name, sep, d = spec.partition('=')
        if not sep:
            ap.error('--flight wants NAME=DIR, got %r' % spec)
        named.append((name, d))
    for line in report(
            fleet_text=_read(args.fleet) if args.fleet else None,
            alerts_text=_read(args.alerts) if args.alerts else None,
            named_dirs=named, chrome_out=args.chrome_out):
        print(line)
    return 0


if __name__ == '__main__':
    sys.exit(main())
