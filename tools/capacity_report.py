"""Capacity-planning report: join a trace, a simulator run, and
(optionally) a real replay's wide events; gate on sim-vs-real TTFT
divergence.

Inputs (all offline — no jax, no gateway):

    --trace FILE        a Trace JSONL (workload.Trace.to_jsonl), a
                        recorded RequestLog sink, or captured dryrun
                        request_event lines — anything
                        capacity.workload.load_trace ingests;
    --spec FILE / --spec-inline JSON
                        a WorkloadSpec to generate the trace from
                        (deterministic: same spec+seed, same trace);
    --real FILE         wide-event JSONL of a real run of the SAME
                        trace (a RequestLog sink), repeatable;
    --sim FILE          wide-event JSONL of a simulator run
                        (SimResult.to_events dumped one per line),
                        repeatable. When absent and a trace is given,
                        --simulate runs the discrete-event simulator
                        here, with --prefill-chunk-s/--decode-burst-s
                        or --calibrate (fit the service model from the
                        --real events, then simulate).

Report: overall + per-tenant TTFT p50/p99 sim-vs-real divergence
(K-S statistic, relative errors), the simulator summary, and — with
--sweep — the replica-count sweep and its minimum-replica answer for
--slo-ms. --qos-policy (repeatable JSON, a capacity.qos.QosPolicy
to_dict blob with an optional "name" key) runs each admission policy
over the trace at --replicas and reports shed rate plus per-priority
TTFT tails and SLO verdicts side by side — the million-request policy
sweep, offline.

Gate (tools/gate_common protocol, like check_bench_regression): a
sim-vs-real comparison whose p50 or p99 relative error exceeds
--max-p50-err/--max-p99-err (or K-S over --max-ks, when given) is a
finding -> exit 1. No inputs -> exit 2; otherwise 0 with a summary.
"""
import argparse
import json
import os
import sys
import types

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# capacity/ and monitor/ avoid jax at import time, but the paddle_tpu
# package __init__ pulls it in: load the subpackages without executing
# the parent (request_report's pattern).
if 'paddle_tpu' not in sys.modules:
    _pkg = types.ModuleType('paddle_tpu')
    _pkg.__path__ = [os.path.join(_REPO_ROOT, 'paddle_tpu')]
    sys.modules['paddle_tpu'] = _pkg

from paddle_tpu.capacity import simulator, workload  # noqa: E402
from tools import gate_common  # noqa: E402
from tools.request_report import load_events  # noqa: E402

__all__ = ['check_divergence', 'main']


def check_divergence(cmp, max_p50_err, max_p99_err, max_ks=None):
    """Pure gate over compare_events() output: findings (empty == pass).
    Per-tenant entries marked 'skipped' never gate — small samples make
    percentile error meaningless."""
    findings = []
    rows = [('overall', cmp['overall'])]
    rows += sorted(cmp.get('tenants', {}).items())
    for name, div in rows:
        if 'skipped' in div:
            continue
        over = []
        if div['p50_rel_err'] > max_p50_err:
            over.append(('p50_rel_err', div['p50_rel_err'], max_p50_err))
        if div['p99_rel_err'] > max_p99_err:
            over.append(('p99_rel_err', div['p99_rel_err'], max_p99_err))
        if max_ks is not None and div['ks'] > max_ks:
            over.append(('ks', div['ks'], max_ks))
        for what, got, limit in over:
            findings.append({'problem': 'ttft_divergence', 'scope': name,
                             'stat': what, 'value': round(got, 4),
                             'threshold': limit,
                             'sim_p50_s': div['sim_p50_s'],
                             'real_p50_s': div['real_p50_s'],
                             'sim_p99_s': div['sim_p99_s'],
                             'real_p99_s': div['real_p99_s']})
    return findings


def _load_trace(args):
    if args.spec_inline:
        return workload.generate(
            workload.WorkloadSpec.from_dict(json.loads(args.spec_inline)))
    if args.spec:
        with open(args.spec) as f:
            return workload.generate(
                workload.WorkloadSpec.from_dict(json.load(f)))
    if args.trace:
        return workload.load_trace(path=args.trace)
    return None


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--trace', help='trace JSONL / recorded wide events')
    ap.add_argument('--spec', help='WorkloadSpec JSON file to generate')
    ap.add_argument('--spec-inline', help='WorkloadSpec JSON literal')
    ap.add_argument('--real', action='append', default=[],
                    help='real run wide-event JSONL (repeatable)')
    ap.add_argument('--sim', action='append', default=[],
                    help='simulated run wide-event JSONL (repeatable)')
    ap.add_argument('--simulate', action='store_true',
                    help='run the simulator on the trace here')
    ap.add_argument('--calibrate', action='store_true',
                    help='fit the service model from --real events '
                         '(implies --simulate)')
    ap.add_argument('--prefill-chunk-s', type=float, default=0.002)
    ap.add_argument('--decode-burst-s', type=float, default=0.004)
    ap.add_argument('--prefill-chunk', type=int, default=32)
    ap.add_argument('--decode-block', type=int, default=8)
    ap.add_argument('--num-slots', type=int, default=8)
    ap.add_argument('--replicas', type=int, default=1,
                    help='simulated replica count (default %(default)s)')
    ap.add_argument('--router', default='least_loaded',
                    choices=('least_loaded', 'round_robin'))
    ap.add_argument('--sweep', help='comma list of replica counts to '
                                    'sweep, e.g. 1,2,4,8')
    ap.add_argument('--qos-policy', action='append', default=[],
                    help='admission policy JSON to sweep (repeatable): '
                         'a QosPolicy.to_dict blob, optional "name" key '
                         'labels the result row')
    ap.add_argument('--slo-ms', type=float, default=1000.0,
                    help='TTFT SLO for the sweep (default %(default)s)')
    ap.add_argument('--percentile', type=float, default=99.0,
                    help='sweep tail percentile (default %(default)s)')
    ap.add_argument('--max-p50-err', type=float, default=0.5,
                    help='gate: max sim-vs-real TTFT p50 relative error')
    ap.add_argument('--max-p99-err', type=float, default=0.5,
                    help='gate: max sim-vs-real TTFT p99 relative error')
    ap.add_argument('--max-ks', type=float, default=None,
                    help='gate: max K-S statistic (ungated by default '
                         '— CI timing noise shifts whole distributions)')
    args = ap.parse_args(argv)

    trace = _load_trace(args)
    real_events, skipped = load_events(args.real, ())
    sim_events, s2 = load_events(args.sim, ())
    skipped += s2

    summary = {'skipped_lines': skipped}
    if trace is not None:
        summary['trace'] = {'requests': len(trace),
                            'duration_s': round(trace.duration_s, 3),
                            'spec_hash': trace.spec_hash,
                            'tenants': trace.tenant_mix()}

    if (args.simulate or args.calibrate or args.sweep
            or args.qos_policy) and trace is None:
        return gate_common.nothing_to_check(
            'simulation requested but no trace/spec given')

    policies = []
    for i, blob in enumerate(args.qos_policy):
        d = json.loads(blob)
        if not isinstance(d, dict):
            raise SystemExit('--qos-policy must be a JSON object, got: %r'
                             % (blob,))
        policies.append((d.pop('name', 'policy%d' % i), d))

    model = None
    if args.calibrate:
        if not real_events:
            return gate_common.nothing_to_check(
                '--calibrate needs --real events to fit from')
        model = simulator.ServiceModel.from_events(
            real_events, prefill_chunk=args.prefill_chunk,
            decode_block=args.decode_block, num_slots=args.num_slots,
            trace=trace, replicas=args.replicas, router=args.router)
    elif args.simulate or args.sweep or policies:
        model = simulator.ServiceModel(
            args.prefill_chunk_s, args.decode_burst_s,
            prefill_chunk=args.prefill_chunk,
            decode_block=args.decode_block, num_slots=args.num_slots)
    if model is not None:
        summary['service_model'] = model.to_dict()

    if (args.simulate or args.calibrate) and not sim_events:
        res = simulator.simulate(trace, model, replicas=args.replicas,
                                 router=args.router)
        summary['sim'] = res.summary(slo_ttft_s=args.slo_ms / 1e3)
        sim_events = res.to_events()

    if args.sweep:
        counts = [int(c) for c in args.sweep.split(',') if c.strip()]
        summary['sweep'] = simulator.sweep_replicas(
            trace, model, counts=counts, slo_ttft_s=args.slo_ms / 1e3,
            percentile=args.percentile)

    if policies:
        summary['qos_sweep'] = simulator.sweep_qos(
            trace, model, policies, replicas=args.replicas,
            slo_ttft_s=args.slo_ms / 1e3, percentile=args.percentile,
            router=args.router)

    findings = []
    if sim_events and real_events:
        cmp = simulator.compare_events(sim_events, real_events)
        summary['divergence'] = cmp
        findings = check_divergence(cmp, args.max_p50_err,
                                    args.max_p99_err, max_ks=args.max_ks)
    elif not sim_events and not real_events \
            and 'sweep' not in summary and 'qos_sweep' not in summary:
        return gate_common.nothing_to_check(
            'no simulated or real events to compare '
            '(give --trace/--spec with --simulate, or --sim/--real '
            'files)')

    return gate_common.finish(findings, summary)


if __name__ == '__main__':
    sys.exit(main())
