"""Sharding-regression gate over MULTICHIP dryrun captures.

Compares a NEW multichip capture (the {n_devices, rc, ok, tail} JSON
the driver stores as MULTICHIP_rNN.json) against a stored baseline
capture and FAILS (exit 1) when the new run's sharding audit shows
involuntary-reshard events the baseline did not have — the same spirit
as tools/check_bench_regression.py, but the metric is "GSPMD last-
resort replications" instead of throughput.

Events are read from BOTH encodings a capture tail can carry:

  * `sharding_audit(N)[tag]: {json}` lines — what __graft_entry__'s
    dryrun prints per config since the auto_parallel subsystem landed
    (events keyed per config label);
  * raw `spmd_partitioner` warning lines — what pre-audit captures
    (e.g. MULTICHIP_r05.json) contain, parsed by the same
    auto_parallel parser the test suite pins against fixtures. Raw
    events are unlabeled and shared across configs.

An event "is in the baseline" if its identity key (opcode, dtype,
shape, op_name, source/target shardings — HLO value numbering
excluded) appears under the same config label or among the baseline's
raw events. Baseline events missing from the new run are fine (that is
the fix landing); new ones fail with a diff.

Usage:
    python tools/check_sharding_regression.py --new MULTICHIP_r06.json \
        [--baseline MULTICHIP_r05.json]

With no --baseline, the newest MULTICHIP_r*.json in the repo root
other than --new is used. Exit codes: 0 ok, 1 new involuntary-reshard
events, 2 nothing to compare.
"""
import argparse
import glob
import json
import os
import re
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from paddle_tpu.distributed.auto_parallel import (  # noqa: E402
    ShardingAuditReport, parse_spmd_warnings)
from tools import gate_common  # noqa: E402

__all__ = ['extract_events', 'check', 'main']

_AUDIT_LINE = re.compile(r'sharding_audit\(\d+\)\[(?P<tag>[^\]]*)\]:\s*'
                         r'(?P<json>\{.*\})\s*$')
_RAW_LABEL = '_raw'


def extract_events(tail):
    """{label: [ShardingEvent]} from a capture tail (both encodings)."""
    out = {}
    for line in (tail or '').splitlines():
        m = _AUDIT_LINE.search(line)
        if not m:
            continue
        try:
            rep = ShardingAuditReport.from_dict(json.loads(m.group('json')))
        except ValueError:
            continue
        out.setdefault(m.group('tag'), []).extend(rep.events)
    raw = parse_spmd_warnings(tail)
    if raw:
        out.setdefault(_RAW_LABEL, []).extend(raw)
    return out


def check(new_tail, baseline_tail):
    """Pure gate: list of regression findings (empty == pass)."""
    new_by_label = extract_events(new_tail)
    base_by_label = extract_events(baseline_tail)
    base_raw = {e.key() for e in base_by_label.get(_RAW_LABEL, ())}
    findings = []
    for label, events in sorted(new_by_label.items()):
        known = {e.key() for e in base_by_label.get(label, ())} | base_raw
        if label == _RAW_LABEL:
            # raw lines are unlabeled: compare against everything stored
            known = {e.key() for evs in base_by_label.values()
                     for e in evs}
        for e in events:
            if e.key() in known:
                continue
            findings.append({
                'config': label,
                'event': e.to_dict(),
                'note': 'involuntary reshard not present in baseline',
            })
    return findings


def _load_tail(path):
    with open(path, errors='replace') as f:
        cap = json.load(f)
    return cap.get('tail', '')


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--new', required=True, help='new MULTICHIP capture')
    ap.add_argument('--baseline', default=None,
                    help='stored capture (default: newest MULTICHIP_r*.json '
                         'in the repo root other than --new)')
    args = ap.parse_args(argv)

    baseline = args.baseline
    if baseline is None:
        cands = sorted(glob.glob(os.path.join(_REPO_ROOT,
                                              'MULTICHIP_r*.json')))
        cands = [p for p in cands
                 if os.path.abspath(p) != os.path.abspath(args.new)]
        baseline = cands[-1] if cands else None
    if baseline is None or not os.path.exists(baseline):
        return gate_common.nothing_to_check('no baseline capture')
    new_tail = _load_tail(args.new)
    base_tail = _load_tail(baseline)
    n_new = sum(len(v) for v in extract_events(new_tail).values())
    findings = check(new_tail, base_tail)
    return gate_common.finish(findings, {
        'regressions': 0, 'events_seen': n_new,
        'baseline': os.path.basename(baseline)})


if __name__ == '__main__':
    sys.exit(main())
