"""Request-centric report over wide-event logs (monitor/events.py).

Input is the canonical per-request record stream, from either side of
the serving stack:

    --jsonl  FILE        a RequestLog sink (one JSON event per line);
    --text   FILE|-      captured driver/bench output containing
                         `request_event(N)[tag]: {json}` lines (the
                         dryrun surface), or stdin.

Both may repeat; events concatenate. The report:

  * top-N slowest requests (by TTFT, falling back to total latency when
    a request never produced a token), each with the trace_id to pull
    from tail retention / the /requests route;
  * per-tenant rollups — requests, tokens, TTFT p50/p99, summed KV
    page·seconds — the attribution table "which tenant held the pool";
  * optional joins: --flight-dump / --chrome-trace files are scanned
    for span trace_ids so each slow request shows whether its span tree
    was actually retained somewhere on disk.

Gate mode (tools/gate_common protocol, like check_bench_regression):

  * --slo-ms X       : any request whose TTFT exceeds X ms is a finding;
  * --kv-integral X  : the per-request kv_page_seconds must sum to the
    allocator's pool-occupancy integral X within --kv-tol relative
    error (slot engine: exact by construction; paged + prefix sharing
    legitimately exceeds it — pass the paged pool's own integral only
    when sharing is off). Mismatch is a finding.

No events -> exit 2; findings -> exit 1; otherwise 0 with a summary.
"""
import argparse
import json
import os
import sys
import types

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# monitor/ is stdlib-only but the package __init__ pulls in jax: load
# the subpackage without executing the parent (check_metrics_snapshot's
# pattern).
if 'paddle_tpu' not in sys.modules:
    _pkg = types.ModuleType('paddle_tpu')
    _pkg.__path__ = [os.path.join(_REPO_ROOT, 'paddle_tpu')]
    sys.modules['paddle_tpu'] = _pkg

from paddle_tpu.monitor.events import (FIELD_NAMES,  # noqa: E402
                                       parse_event_lines)
from tools import gate_common  # noqa: E402

__all__ = ['load_events', 'rollup_by_tenant', 'rollup_by_model',
           'slowest', 'check', 'main']


def _percentile(values, q):
    """serving.metrics.percentile re-stated (that module sits behind the
    jax-importing serving package): linear interpolation, numpy-free."""
    if not values:
        return None
    xs = sorted(values)
    if len(xs) == 1:
        return xs[0]
    pos = (len(xs) - 1) * (q / 100.0)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    frac = pos - lo
    return xs[lo] * (1.0 - frac) + xs[hi] * frac


def load_events(jsonl_paths=(), texts=()):
    """Wide events from sink files and/or captured text, in input order.
    Lines that don't parse (torn writes, interleaved logs) are skipped
    and counted, never fatal."""
    events, skipped = [], 0
    for path in jsonl_paths:
        with open(path, errors='replace') as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    skipped += 1
                    continue
                if isinstance(ev, dict) and 'request_id' in ev:
                    events.append(ev)
                else:
                    skipped += 1
    for text in texts:
        events.extend(ev for _, ev in parse_event_lines(text))
    return events, skipped


def _ttft_s(ev):
    a, f = ev.get('arrival_t'), ev.get('first_token_t')
    if a is None or f is None:
        return None
    return f - a


def _latency_s(ev):
    a, f = ev.get('arrival_t'), ev.get('finish_t')
    if a is None or f is None:
        return None
    return f - a


def slowest(events, n=10):
    """Top-n by TTFT (total latency when no token was ever produced),
    newest-schema fields only — unknown keys ride along untouched."""
    def key(ev):
        t = _ttft_s(ev)
        return t if t is not None else (_latency_s(ev) or 0.0)
    ranked = sorted(events, key=key, reverse=True)[:n]
    return [{'request_id': ev.get('request_id'),
             'tenant': ev.get('tenant'),
             'trace_id': ev.get('trace_id'),
             'ttft_ms': None if _ttft_s(ev) is None
             else _ttft_s(ev) * 1e3,
             'latency_ms': None if _latency_s(ev) is None
             else _latency_s(ev) * 1e3,
             'failovers': ev.get('failovers'),
             'outcome': ev.get('outcome')} for ev in ranked]


def rollup_by_tenant(events):
    """{tenant: {requests, tokens, ttft_p50_ms, ttft_p99_ms,
    kv_page_seconds, failovers, rejected, preempted, errors}} — the
    attribution table. QoS outcomes get their own columns: a shed
    request (outcome='rejected') or a preemption-budget kill
    (outcome='preempted') is policy doing its job, not an engine error,
    and capacity review needs them countable per tenant."""
    by = {}
    for ev in events:
        t = ev.get('tenant') or 'default'
        row = by.setdefault(t, {'requests': 0, 'tokens': 0,
                                'kv_page_seconds': 0.0, 'failovers': 0,
                                'rejected': 0, 'preempted': 0,
                                'errors': 0, '_ttfts': []})
        row['requests'] += 1
        row['tokens'] += int(ev.get('output_tokens') or 0)
        row['kv_page_seconds'] += float(ev.get('kv_page_seconds') or 0.0)
        row['failovers'] += int(ev.get('failovers') or 0)
        outcome = ev.get('outcome')
        if outcome == 'rejected':
            row['rejected'] += 1
        elif outcome == 'preempted':
            row['preempted'] += 1
        elif outcome not in (None, 'ok'):
            row['errors'] += 1
        ttft = _ttft_s(ev)
        if ttft is not None:
            row['_ttfts'].append(ttft)
    for row in by.values():
        ttfts = row.pop('_ttfts')
        row['ttft_p50_ms'] = (None if not ttfts
                              else _percentile(ttfts, 50) * 1e3)
        row['ttft_p99_ms'] = (None if not ttfts
                              else _percentile(ttfts, 99) * 1e3)
    return by


def rollup_by_model(events):
    """{model: {requests, tokens, ttft_p50_ms, ttft_p99_ms, failovers,
    rejected, errors}} — the multi-model attribution table. Events
    without a model field (single-model deployments, pre-schema logs)
    fold under '(none)': they are unattributed, not a named model."""
    by = {}
    for ev in events:
        m = ev.get('model') or '(none)'
        row = by.setdefault(m, {'requests': 0, 'tokens': 0,
                                'failovers': 0, 'rejected': 0,
                                'errors': 0, '_ttfts': []})
        row['requests'] += 1
        row['tokens'] += int(ev.get('output_tokens') or 0)
        row['failovers'] += int(ev.get('failovers') or 0)
        outcome = ev.get('outcome')
        if outcome == 'rejected':
            row['rejected'] += 1
        elif outcome not in (None, 'ok', 'preempted'):
            row['errors'] += 1
        ttft = _ttft_s(ev)
        if ttft is not None:
            row['_ttfts'].append(ttft)
    for row in by.values():
        ttfts = row.pop('_ttfts')
        row['ttft_p50_ms'] = (None if not ttfts
                              else _percentile(ttfts, 50) * 1e3)
        row['ttft_p99_ms'] = (None if not ttfts
                              else _percentile(ttfts, 99) * 1e3)
    return by


def _trace_ids_in_file(path):
    """Every trace_id mentioned in a flight dump ({'spans': [...]}) or a
    Chrome trace ({'traceEvents': [...]}, ids under args)."""
    with open(path, errors='replace') as f:
        try:
            doc = json.load(f)
        except ValueError:
            return set()
    ids = set()
    for span in doc.get('spans') or ():
        if span.get('trace_id'):
            ids.add(span['trace_id'])
    for ev in doc.get('traceEvents') or ():
        tid = (ev.get('args') or {}).get('trace_id')
        if tid:
            ids.add(tid)
    return ids


def check(events, slo_ms=None, kv_integral=None, kv_tol=1e-6):
    """Pure gate: findings list (empty == pass)."""
    findings = []
    if slo_ms is not None:
        for ev in events:
            ttft = _ttft_s(ev)
            if ttft is not None and ttft * 1e3 > slo_ms:
                findings.append({
                    'problem': 'ttft_over_slo',
                    'request_id': ev.get('request_id'),
                    'tenant': ev.get('tenant'),
                    'trace_id': ev.get('trace_id'),
                    'ttft_ms': ttft * 1e3, 'slo_ms': slo_ms})
    if kv_integral is not None:
        total = sum(float(ev.get('kv_page_seconds') or 0.0)
                    for ev in events)
        denom = max(abs(kv_integral), 1e-12)
        if abs(total - kv_integral) / denom > kv_tol:
            findings.append({
                'problem': 'kv_attribution_mismatch',
                'sum_per_request': total,
                'pool_integral': kv_integral,
                'relative_error': abs(total - kv_integral) / denom,
                'note': 'per-request kv_page_seconds must sum to the '
                        'allocator pool-occupancy integral (slot '
                        'engine: exact; paged + prefix sharing may '
                        'legitimately exceed — do not gate that case)'})
    return findings


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--jsonl', action='append', default=[],
                    help='RequestLog JSONL sink (repeatable)')
    ap.add_argument('--text', action='append', default=[],
                    help="driver/bench capture with request_event "
                         "lines, or '-' (repeatable)")
    ap.add_argument('--top', type=int, default=10,
                    help='slowest requests to list (default %(default)s)')
    ap.add_argument('--tenant', help='restrict the report to one tenant')
    ap.add_argument('--model', help='restrict the report to one model')
    ap.add_argument('--flight-dump', action='append', default=[],
                    help='flight-recorder dump JSON to join by trace_id')
    ap.add_argument('--chrome-trace', action='append', default=[],
                    help='Chrome-trace JSON to join by trace_id')
    ap.add_argument('--slo-ms', type=float,
                    help='gate: fail on any TTFT over this many ms')
    ap.add_argument('--kv-integral', type=float,
                    help='gate: allocator pool-occupancy integral the '
                         'per-request kv_page_seconds must sum to')
    ap.add_argument('--kv-tol', type=float, default=1e-6,
                    help='relative tolerance for --kv-integral '
                         '(default %(default)s)')
    args = ap.parse_args(argv)

    texts = []
    for t in args.text:
        texts.append(sys.stdin.read() if t == '-'
                     else open(t, errors='replace').read())
    events, skipped = load_events(args.jsonl, texts)
    if args.tenant:
        events = [e for e in events if e.get('tenant') == args.tenant]
    if args.model:
        events = [e for e in events if e.get('model') == args.model]
    if not events:
        return gate_common.nothing_to_check('no wide events found',
                                            skipped=skipped)

    known = set()
    for path in list(args.flight_dump) + list(args.chrome_trace):
        known |= _trace_ids_in_file(path)
    top = slowest(events, args.top)
    if known:
        for row in top:
            row['trace_on_disk'] = row['trace_id'] in known

    findings = check(events, slo_ms=args.slo_ms,
                     kv_integral=args.kv_integral, kv_tol=args.kv_tol)
    return gate_common.finish(findings, {
        'events': len(events), 'skipped_lines': skipped,
        'fields': list(FIELD_NAMES),
        'tenants': rollup_by_tenant(events),
        'models': rollup_by_model(events),
        'slowest': top,
        'joined_trace_ids': len(known)})


if __name__ == '__main__':
    sys.exit(main())
