#!/usr/bin/env python
"""Opportunistic TPU bench runner + compile-cache warmer.

The axon TPU pool wedges for hours at a time (memory: every backend touch
must live in a child process with a hard timeout). This script is invoked
by the probe loop (tools/tpu_probe.sh) the moment a probe sees the pool
up. It then:

1. runs the SAME bench.py child configs the driver's end-of-round bench
   ladder uses — with the repo-local persistent compilation cache enabled
   (bench.py `_enable_persistent_cache`), so every XLA executable compiled
   in this up-window is a warm artifact for the driver's later run even if
   the pool wedges again in between;
2. records every result (+ timestamp + config label) to
   docs/bench_inwindow_r4.jsonl for PERF_NOTES;
3. compares configs (scan-K device loop vs single dispatch, flash vs
   blockwise vs quadratic attention) so the ladder ordering in bench.py
   can be tuned from data.

A lockfile serializes warmers (probe fires every ~3 min; a warm run takes
longer). Never touches the backend in-process.
"""
import fcntl
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, 'bench.py')
OUT = os.environ.get(
    'PADDLE_TPU_BENCH_INWINDOW_LOG',
    os.path.join(REPO, 'docs', 'bench_inwindow_r4.jsonl'))
LOCK = '/tmp/tpu_warmer.lock'

# config ladder: label -> extra env. Ordered so the most valuable
# measurement (the expected driver rung) lands first in case the window
# closes mid-run.
CONFIGS = [
    # round-4 session-3 ladder: the fused head+CE lever (ops/fused_ce.py)
    # first — it is the one unmeasured-on-TPU change; everything after
    # re-captures the proven rungs. bench.py defaults PADDLE_TPU_FUSED_CE
    # on, so the non-fused rungs set it to '0' explicitly.
    ('fused_flash_scan8', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8'}),
    ('fused_flash_plain', {}),
    ('flash_scan8', {'PADDLE_TPU_FUSED_CE': '0',
                     'PADDLE_TPU_BENCH_SCAN_STEPS': '8'}),
    ('fused_flash_disabled_scan8', {'PADDLE_TPU_FLASH_DISABLE': '1',
                                    'PADDLE_TPU_FLASH_STRICT': '0',
                                    'PADDLE_TPU_BENCH_SCAN_STEPS': '8'}),
    ('fused_flash_scan8_b64', {'PADDLE_TPU_BENCH_BATCH': '64',
                               'PADDLE_TPU_BENCH_SCAN_STEPS': '8'}),
    ('fused_ce_chunk2048_scan8', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
                                  'PADDLE_TPU_FUSED_CE_CHUNK': '2048'}),
    ('fused_ce_chunk8192_scan8', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
                                  'PADDLE_TPU_FUSED_CE_CHUNK': '8192'}),
    # long-context with the full stack: flash + fused CE
    ('fused_flash_seq2048_b8_scan4', {'PADDLE_TPU_BENCH_SEQ': '2048',
                                      'PADDLE_TPU_BENCH_BATCH': '8',
                                      'PADDLE_TPU_BENCH_SCAN_STEPS': '4'}),
    ('fused_flash_seq8192_b2_scan2', {'PADDLE_TPU_BENCH_SEQ': '8192',
                                      'PADDLE_TPU_BENCH_BATCH': '2',
                                      'PADDLE_TPU_BENCH_SCAN_STEPS': '2'}),
    # A/B: last-axis qkv split (layout-copy hypothesis from the r4
    # profile — ~5 ms/step of [b,n,3,h,d] copies on the default path)
    ('fused_flash_scan8_qkvlast', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
                                   'PADDLE_TPU_QKV_SPLIT': 'last'}),
    # the remaining driver-ladder fallback rungs (bench.py): warm their
    # caches too, and keep refreshing r4's best plain capture
    ('flash_plain', {'PADDLE_TPU_FUSED_CE': '0'}),
    ('flash_disabled_plain', {'PADDLE_TPU_FUSED_CE': '0',
                              'PADDLE_TPU_FLASH_DISABLE': '1',
                              'PADDLE_TPU_FLASH_STRICT': '0'}),
    # flash kernel block-size sweep (kernels read PADDLE_TPU_FLASH_BLOCK_*
    # at import; each bench child re-imports): defaults are 256/512
    ('fused_flash_scan8_bq128_bk128', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
                                       'PADDLE_TPU_FLASH_BLOCK_Q': '128',
                                       'PADDLE_TPU_FLASH_BLOCK_K': '128'}),
    ('fused_flash_scan8_bq512_bk512', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
                                       'PADDLE_TPU_FLASH_BLOCK_Q': '512',
                                       'PADDLE_TPU_FLASH_BLOCK_K': '512'}),
]


def log(msg):
    line = '%s %s' % (time.strftime('%H:%M:%S'), msg)
    print(line, flush=True)
    with open('/tmp/tpu_warmer.log', 'a') as f:
        f.write(line + '\n')


def _json_lines(stdout):
    out = []
    for line in (stdout or '').strip().splitlines():
        line = line.strip()
        if line.startswith('{'):
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def run_child(label, extra_env, timeout=1500):
    env = dict(os.environ)
    env['PADDLE_TPU_BENCH_CHILD'] = '1'
    env.update(extra_env)
    t0 = time.time()
    try:
        proc = subprocess.run([sys.executable, BENCH], capture_output=True,
                              text=True, env=env, timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, 'timeout>%ds' % timeout, time.time() - t0
    entries = _json_lines(proc.stdout)
    if entries:
        return entries[-1], None, time.time() - t0
    return None, 'rc=%d: %s' % (proc.returncode,
                                (proc.stderr or '')[-300:]), time.time() - t0


def record(label, result, err, wall):
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    entry = {'ts': time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime()),
             'label': label, 'wall_s': round(wall, 1)}
    if result is not None:
        entry.update(result)
    else:
        entry['error'] = err
    with open(OUT, 'a') as f:
        f.write(json.dumps(entry) + '\n')


def probe_tpu(timeout=90):
    src = "import jax; assert jax.devices()[0].platform == 'tpu'"
    try:
        return subprocess.run([sys.executable, '-c', src],
                              timeout=timeout).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    lock = open(LOCK, 'w')
    try:
        fcntl.flock(lock, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        log('another warmer holds the lock; exiting')
        return
    if not probe_tpu():
        log('TPU not up at warmer start; exiting')
        return
    log('TPU up — warming')
    best = None
    for label, extra in CONFIGS:
        result, err, wall = run_child(label, extra)
        record(label, result, err, wall)
        if result is not None:
            log('%s: %.1fms/step mfu=%.4f (%.0fs)' % (
                label, result.get('step_ms', -1), result.get('mfu', 0),
                wall))
            if best is None or result.get('mfu', 0) > best[1].get('mfu', 0):
                best = (label, result, extra)
        else:
            log('%s: FAILED %s (%.0fs)' % (label, err, wall))
            # if the pool wedged mid-window, stop burning child timeouts
            if not probe_tpu():
                log('pool went down mid-window; stopping')
                break
    # window still open after the ladder: capture an on-chip profile of
    # the best rung — the data that tells WHERE the remaining MFU gap is
    # (XLA schedule vs attention vs dispatch), which no step-time number
    # can. Written under docs/ so it survives for analysis.
    if best is not None and probe_tpu():
        label, _, extra = best
        pdir = os.path.join(REPO, 'docs', 'tpu_profile_r4')
        prof_env = dict(extra, PADDLE_TPU_BENCH_PROFILE=pdir,
                        PADDLE_TPU_BENCH_STEPS='8',
                        PADDLE_TPU_BENCH_WARMUP='4')
        result, err, wall = run_child('profile_' + label, prof_env)
        record('profile_' + label, result, err, wall)
        log('profile(%s): %s (%.0fs)' % (
            label, 'ok -> %s' % pdir if result is not None else err, wall))
        if result is not None:
            # self-documenting window: roofline summary of the fresh
            # trace lands next to the profile for post-hoc analysis
            try:
                proc = subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, 'tools', 'profile_analysis.py'),
                     pdir], capture_output=True, text=True, timeout=120)
                if proc.returncode != 0:
                    log('profile summary failed rc=%d: %s'
                        % (proc.returncode, (proc.stderr or '')[-300:]))
                else:
                    out_path = os.path.join(REPO, 'docs',
                                            'profile_summary_r4.txt')
                    with open(out_path, 'w') as f:
                        f.write('rung: %s\n%s' % (label, proc.stdout))
                    log('profile summary -> %s' % out_path)
            except Exception as e:
                log('profile summary failed: %r' % (e,))
    # BASELINE configs 2/4 (ResNet train throughput, YOLO inference):
    # bench_extra prints one JSON line per config
    if probe_tpu():
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(REPO, 'bench_extra.py')],
                capture_output=True, text=True, timeout=1800)
            entries = _json_lines(proc.stdout)
            wall = time.time() - t0
            if not entries:
                record('bench_extra', None,
                       'rc=%d: %s' % (proc.returncode,
                                      (proc.stderr or '')[-300:]), wall)
                log('bench_extra: no JSON output (rc=%d)' % proc.returncode)
            for entry in entries:
                # wall is the whole two-config process; per-row timing is
                # not observable from outside, so mark it as shared
                record(entry.get('metric', 'bench_extra'),
                       dict(entry, wall_shared=True), None, wall)
                log('extra %s: %s' % (entry.get('metric'),
                                      entry.get('value')))
        except subprocess.TimeoutExpired:
            record('bench_extra', None, 'timeout>1800s', time.time() - t0)
            log('bench_extra timed out')
    log('warmer done')


if __name__ == '__main__':
    main()
