#!/usr/bin/env python
"""Opportunistic TPU bench runner + compile-cache warmer (round 5).

The axon TPU pool wedges for hours at a time (memory: every backend touch
must live in a child process with a hard timeout). This script is invoked
by the probe loop (tools/tpu_probe.sh) the moment a probe sees the pool
up. It then:

1. snapshots the repo at HEAD into /tmp and runs every bench child from
   the snapshot — a half-edited working tree can no longer poison a
   window (r4 lost a rung to a mid-edit import error), and every number
   is attributable to a commit (recorded as `git_rev`);
2. runs the SAME bench.py child configs the driver's end-of-round bench
   ladder uses — with the repo-local persistent compilation cache
   (PADDLE_TPU_CACHE_DIR pins it to the REAL repo's .jax_cache), so every
   XLA executable compiled in this up-window is a warm artifact for the
   driver's later run even if the pool wedges again in between;
3. records every result (+ timestamp + config label) to
   docs/bench_inwindow_r5.jsonl in the real repo;
4. re-runs the first successful rung as a CANARY every few rungs and at
   window end: if a canary reads >15% below the window's reference
   canary, every sample since the last good canary is rewritten with
   `suspect: true` — a mid-window pool degradation can no longer leave
   plausible-but-throttled numbers unmarked (the r4 12:06 problem);
5. runs bench_extra (ResNet / YOLO batch-1+8 / scan decode) EARLY —
   BASELINE configs 2 and 4 have the thinnest evidence, so they must not
   be the first casualties of a short window.

A lockfile serializes warmers (probe fires every ~3 min; a warm run takes
longer). Never touches the backend in-process.
"""
import fcntl
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.environ.get(
    'PADDLE_TPU_BENCH_INWINDOW_LOG',
    os.path.join(REPO, 'docs', 'bench_inwindow_r5.jsonl'))
LOCK = '/tmp/tpu_warmer.lock'
SNAP = '/tmp/paddle_tpu_warm_snapshot'

CANARY_DRIFT = 0.15      # >15% below the window reference => suspect
CANARY_EVERY = 4         # re-run the canary after every N ladder rungs

# config ladder: label -> extra env, grouped in priority phases.
# Phase A: the headline rungs. As of f6b6242 the code DEFAULTS equal the
# measured in-window optimum (fused CE x flash 512/512 x fused single-
# tile backward), so `fused_flash_scan8_qkvlast` IS the winner config —
# 101.8 ms/step, 53.4% 6N-MFU on v5e. Phase B: BASELINE configs 2/4 +
# decode via bench_extra. Phase C: fallbacks, sweeps, long-context.
PHASE_A = [
    ('fused_flash_scan8', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8'}),
    # the qkv layout copies (~5 ms/step, r4 profile fusion.825 family)
    # are the next known byte mover after fused-CE+flash — the A/B
    # belongs in the must-measure phase, not the tail
    ('fused_flash_scan8_qkvlast', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
                                   'PADDLE_TPU_QKV_SPLIT': 'last'}),
    ('fused_flash_plain', {}),
    ('flash_scan8', {'PADDLE_TPU_FUSED_CE': '0',
                     'PADDLE_TPU_BENCH_SCAN_STEPS': '8'}),
    ('fused_flash_disabled_scan8', {'PADDLE_TPU_FLASH_DISABLE': '1',
                                    'PADDLE_TPU_FLASH_STRICT': '0',
                                    'PADDLE_TPU_BENCH_SCAN_STEPS': '8'}),
]
PHASE_C = [
    ('fused_flash_scan8_b64', {'PADDLE_TPU_BENCH_BATCH': '64',
                               'PADDLE_TPU_BENCH_SCAN_STEPS': '8'}),
    ('fused_ce_chunk2048_scan8', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
                                  'PADDLE_TPU_FUSED_CE_CHUNK': '2048'}),
    ('fused_ce_chunk8192_scan8', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
                                  'PADDLE_TPU_FUSED_CE_CHUNK': '8192'}),
    # single-chunk: no f32 dw-accumulator read-modify-write passes at
    # all, at the cost of one 2 GB transient f32 logits tile — the
    # other end of the chunk tradeoff curve
    ('fused_ce_chunk16384_scan8', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
                                   'PADDLE_TPU_FUSED_CE_CHUNK': '16384'}),
    # long-context ladder: 2k/4k/8k with the full stack; each seq also
    # gets the pure-XLA blockwise fallback rung so a flash limit at that
    # scale still yields an honest measured number (VERDICT r4 #5)
    ('fused_flash_seq2048_b8_scan4', {'PADDLE_TPU_BENCH_SEQ': '2048',
                                      'PADDLE_TPU_BENCH_BATCH': '8',
                                      'PADDLE_TPU_BENCH_SCAN_STEPS': '4'}),
    ('fused_flash_seq4096_b4_scan2', {'PADDLE_TPU_BENCH_SEQ': '4096',
                                      'PADDLE_TPU_BENCH_BATCH': '4',
                                      'PADDLE_TPU_BENCH_SCAN_STEPS': '2'}),
    ('fused_flash_seq8192_b2_scan2', {'PADDLE_TPU_BENCH_SEQ': '8192',
                                      'PADDLE_TPU_BENCH_BATCH': '2',
                                      'PADDLE_TPU_BENCH_SCAN_STEPS': '2'}),
    ('fused_blockwise_seq8192_b2_scan2', {
        'PADDLE_TPU_BENCH_SEQ': '8192',
        'PADDLE_TPU_BENCH_BATCH': '2',
        'PADDLE_TPU_BENCH_SCAN_STEPS': '2',
        'PADDLE_TPU_FLASH_DISABLE': '1',
        'PADDLE_TPU_FLASH_STRICT': '0',
        'PADDLE_TPU_ATTN_IMPL': 'blockwise'}),
    ('fused_blockwise_seq4096_b4_scan2', {
        'PADDLE_TPU_BENCH_SEQ': '4096',
        'PADDLE_TPU_BENCH_BATCH': '4',
        'PADDLE_TPU_BENCH_SCAN_STEPS': '2',
        'PADDLE_TPU_FLASH_DISABLE': '1',
        'PADDLE_TPU_FLASH_STRICT': '0',
        'PADDLE_TPU_ATTN_IMPL': 'blockwise'}),
    # remaining driver-ladder fallback rungs: warm their caches and keep
    # refreshing r4's best plain capture
    ('flash_plain', {'PADDLE_TPU_FUSED_CE': '0'}),
    ('flash_disabled_plain', {'PADDLE_TPU_FUSED_CE': '0',
                              'PADDLE_TPU_FLASH_DISABLE': '1',
                              'PADDLE_TPU_FLASH_STRICT': '0'}),
    # flash kernel block-size sweep (kernels read PADDLE_TPU_FLASH_BLOCK_*
    # at import; each bench child re-imports): defaults are 512/512 as of
    # r5, so sweep the smaller references
    ('fused_flash_scan8_bq128_bk128', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
                                       'PADDLE_TPU_FLASH_BLOCK_Q': '128',
                                       'PADDLE_TPU_FLASH_BLOCK_K': '128'}),
    ('fused_flash_scan8_bq256_bk512', {'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
                                       'PADDLE_TPU_FLASH_BLOCK_Q': '256',
                                       'PADDLE_TPU_FLASH_BLOCK_K': '512'}),
    # fused-backward A/B reference (the winner minus one lever)
    ('fused_flash_scan8_qkvlast_twopassbwd', {
        'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
        'PADDLE_TPU_QKV_SPLIT': 'last',
        'PADDLE_TPU_FLASH_FUSED_BWD': '0'}),
]


def _load_custom_ladder():
    """PADDLE_TPU_WARMER_LADDER=<path.json> replaces the built-in ladder.

    Schema: {"phase_a": [[label, {env}], ...], "phase_c": [...],
    "skip_extras": bool}. Lets an in-window iteration fire a handful of
    targeted rungs (e.g. combinations of knobs that just won their A/Bs)
    without paying for the whole default ladder again.
    """
    global PHASE_A, PHASE_C, SKIP_EXTRAS
    path = os.environ.get('PADDLE_TPU_WARMER_LADDER')
    if not path:
        return
    with open(path) as f:
        spec = json.load(f)
    def _env_str(v):
        # env-safe strings: a natural JSON spec writes ints and bools,
        # and the knob consumers compare against '1'/'0' (str(True)
        # would silently read as off)
        if isinstance(v, bool):
            return '1' if v else '0'
        return str(v)

    PHASE_A = [(l, {k: _env_str(v) for k, v in e.items()})
               for l, e in spec.get('phase_a', [])]
    PHASE_C = [(l, {k: _env_str(v) for k, v in e.items()})
               for l, e in spec.get('phase_c', [])]
    SKIP_EXTRAS = bool(spec.get('skip_extras', False))


SKIP_EXTRAS = False


def log(msg):
    line = '%s %s' % (time.strftime('%H:%M:%S'), msg)
    print(line, flush=True)
    with open('/tmp/tpu_warmer.log', 'a') as f:
        f.write(line + '\n')


def _snapshot_repo():
    """Export HEAD into SNAP; return (snap_dir, rev) or (REPO, None)."""
    try:
        rev = subprocess.run(['git', '-C', REPO, 'rev-parse', '--short',
                              'HEAD'], capture_output=True, text=True,
                             timeout=30).stdout.strip()
        if os.path.isdir(SNAP):
            shutil.rmtree(SNAP)
        os.makedirs(SNAP)
        ar = subprocess.run(['git', '-C', REPO, 'archive', 'HEAD'],
                            capture_output=True, timeout=120)
        if ar.returncode != 0:
            raise RuntimeError(ar.stderr[-200:])
        subprocess.run(['tar', '-x', '-C', SNAP], input=ar.stdout,
                       timeout=120, check=True)
        return SNAP, rev
    except Exception as e:
        log('snapshot failed (%r) — running from the live tree' % (e,))
        return REPO, None


class Recorder(object):
    """Append jsonl entries; support retro-tagging a line range."""

    def __init__(self, path):
        self.path = path
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self.lines = []          # indexes (in this run) -> file line no
        with open(path, 'a'):
            pass
        with open(path) as f:
            self.base = sum(1 for _ in f)

    def record(self, label, result, err, wall, rev=None):
        entry = {'ts': time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime()),
                 'label': label, 'wall_s': round(wall, 1)}
        if rev:
            entry['git_rev'] = rev
        if result is not None:
            entry.update(result)
        else:
            entry['error'] = err
        with open(self.path, 'a') as f:
            f.write(json.dumps(entry) + '\n')
        self.lines.append(self.base + len(self.lines))
        return len(self.lines) - 1

    def mark_suspect(self, first_idx, reason, end_idx=None):
        """Rewrite rows [first_idx:end_idx) of THIS run with
        suspect: true (end_idx None = through the latest row)."""
        if end_idx is None:
            end_idx = len(self.lines)
        tag = [self.lines[i] for i in range(first_idx, end_idx)]
        if not tag:
            return
        with open(self.path) as f:
            rows = f.readlines()
        for ln in tag:
            if ln >= len(rows):
                continue
            try:
                e = json.loads(rows[ln])
            except ValueError:
                continue
            e['suspect'] = True
            e['suspect_reason'] = reason
            rows[ln] = json.dumps(e) + '\n'
        tmp = self.path + '.tmp'
        with open(tmp, 'w') as f:
            f.writelines(rows)
        os.replace(tmp, self.path)


def _json_lines(stdout):
    out = []
    for line in (stdout or '').strip().splitlines():
        line = line.strip()
        if line.startswith('{'):
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def run_child(script, extra_env, timeout=1500, snap=REPO):
    env = dict(os.environ)
    env['PADDLE_TPU_BENCH_CHILD'] = '1'
    # the cache must live in the REAL repo so later driver runs hit it
    env.setdefault('PADDLE_TPU_CACHE_DIR', os.path.join(REPO, '.jax_cache'))
    env.update(extra_env)
    t0 = time.time()
    try:
        proc = subprocess.run([sys.executable, os.path.join(snap, script)],
                              capture_output=True, text=True, env=env,
                              timeout=timeout, cwd=snap)
    except subprocess.TimeoutExpired as te:
        # salvage rows the child already printed (bench_extra emits one
        # json line per config as it goes — a timeout on the LAST config
        # must not discard the earlier measurements)
        partial = te.stdout
        if isinstance(partial, bytes):
            partial = partial.decode('utf-8', 'replace')
        entries = _json_lines(partial)
        if entries:
            log('%s timed out >%ds; salvaged %d already-printed rows'
                % (script, timeout, len(entries)))
            # the window log must distinguish "config was cut off" from
            # "config never ran": record the timeout as its own row
            # alongside the salvaged measurements
            entries.append({'metric': '%s_timeout' % script,
                            'error': 'timeout>%ds' % timeout})
            return entries, None, time.time() - t0
        return None, 'timeout>%ds' % timeout, time.time() - t0
    entries = _json_lines(proc.stdout)
    if entries:
        return entries, None, time.time() - t0
    return None, 'rc=%d: %s' % (proc.returncode,
                                (proc.stderr or '')[-300:]), time.time() - t0


def probe_tpu(timeout=90):
    src = "import jax; assert jax.devices()[0].platform == 'tpu'"
    try:
        return subprocess.run([sys.executable, '-c', src],
                              timeout=timeout).returncode == 0
    except subprocess.TimeoutExpired:
        return False


class Warmer(object):
    def __init__(self):
        self.snap, self.rev = _snapshot_repo()
        self.rec = Recorder(OUT)
        self.best = None           # (label, result, extra)
        self.canary = None         # (label, extra)
        self.canary_ref = None     # reference mfu for drift checks
        self.last_good_idx = 0     # first row index not yet vouched
        self.tainted = False       # a drifted/failed canary with no
        #                            healthy canary since: nothing after
        #                            it may be vouched retroactively
        self.rungs_since_canary = 0

    def bench_rung(self, label, extra, timeout=1500):
        entries, err, wall = run_child('bench.py', extra, timeout,
                                       self.snap)
        # a timeout-salvaged list ends with a synthetic error row; the
        # rung's RESULT is the last real measurement
        good = [e for e in (entries or []) if 'error' not in e]
        result = good[-1] if good else None
        if result is None and err is None and entries:
            err = entries[-1].get('error', 'timeout')
        idx = self.rec.record(label, result, err, wall, self.rev)
        if result is not None:
            log('%s: %.1fms/step mfu=%.4f (%.0fs)' % (
                label, result.get('step_ms', -1), result.get('mfu', 0),
                wall))
            if self.best is None or result.get('mfu_6n', 0) > \
                    self.best[1].get('mfu_6n', 0):
                self.best = (label, result, extra)
        else:
            log('%s: FAILED %s (%.0fs)' % (label, err, wall))
        return result, idx

    def maybe_canary(self, force=False):
        """Re-run the reference rung; retro-tag on drift."""
        if self.canary is None:
            return True
        self.rungs_since_canary += 1
        if not force and self.rungs_since_canary < CANARY_EVERY:
            return True
        self.rungs_since_canary = 0
        label, extra = self.canary
        result, idx = self.bench_rung('canary_' + label, extra)
        if result is None:
            # a failed canary is itself a strong degradation signal
            self.rec.mark_suspect(self.last_good_idx,
                                  'canary %s failed' % label)
            self.tainted = True
            return False
        mfu = result.get('mfu_6n', 0)
        if self.canary_ref and mfu < (1 - CANARY_DRIFT) * self.canary_ref:
            reason = 'canary %.4f < %.4f ref -15%%' % (mfu, self.canary_ref)
            log('CANARY DRIFT: ' + reason)
            self.rec.mark_suspect(self.last_good_idx, reason)
            self.tainted = True
            return False
        if self.tainted:
            # a drift happened since the last healthy canary: rows
            # measured in between sit next to a confirmed-throttled
            # reading and can NOT be vouched retroactively — tag them
            # (idempotent for already-tagged rows), excluding this
            # healthy canary row itself
            self.rec.mark_suspect(self.last_good_idx,
                                  'between drifted and healthy canary',
                                  end_idx=idx)
            self.tainted = False
        # window healthy from here: later rows vouch against this point
        self.last_good_idx = idx + 1
        return True

    def run(self):
        log('TPU up — warming (rev %s)' % (self.rev or 'dirty-tree'))
        # Phase A: headline rungs; the first success becomes the canary
        for label, extra in PHASE_A:
            result, idx = self.bench_rung(label, extra)
            if result is not None and self.canary is None:
                self.canary = (label, extra)
                self.canary_ref = result.get('mfu_6n', 0)
                self.last_good_idx = idx + 1
            if result is None and not probe_tpu():
                log('pool went down mid-window; stopping')
                return
        # Phase B: BASELINE configs 2/4 + decode (thinnest evidence) —
        # behind a fresh probe: a wedged pool must cost a 90s probe, not
        # the 1800s bench_extra child timeout
        if SKIP_EXTRAS:
            pass
        elif probe_tpu():
            self.extras()
        else:
            log('pool went down before extras; stopping')
            return
        if not self.maybe_canary(force=True):
            if not probe_tpu():
                log('pool went down; stopping')
                return
        # Phase C: sweeps, long context, fallbacks
        for label, extra in PHASE_C:
            result, _ = self.bench_rung(label, extra)
            if result is None and not probe_tpu():
                log('pool went down mid-window; stopping')
                return
            if not self.maybe_canary() and not probe_tpu():
                log('pool went down at canary; stopping')
                return
        self.profile_best()
        # end-of-window canary: vouch for (or flag) the tail samples
        self.maybe_canary(force=True)

    def extras(self):
        # every window also captures an on-chip decode trace: decode sits
        # at ~20% of its weight-streaming roofline and the step-level
        # tok/s numbers cannot say why — the trace names the byte movers
        dec_pdir = os.path.join(REPO, 'docs', 'tpu_profile_r5_decode')
        # fresh dir per window: a failed capture must not let the
        # summarizer re-read LAST window's newest trace stamped with the
        # current rev (and the multi-MB blobs must not accumulate)
        if os.path.isdir(dec_pdir):
            shutil.rmtree(dec_pdir, ignore_errors=True)
        entries, err, wall = run_child(
            'bench_extra.py',
            {'PADDLE_TPU_BENCH_PROFILE_DECODE': dec_pdir}, 1800,
            self.snap)
        if entries is None:
            self.rec.record('bench_extra', None, err, wall, self.rev)
            log('bench_extra: %s' % err)
            return
        for entry in entries:
            # wall covers the whole multi-config process; per-row timing
            # is not observable from outside, so mark it shared
            self.rec.record(entry.get('metric', 'bench_extra'),
                            dict(entry, wall_shared=True), None, wall,
                            self.rev)
            log('extra %s: %s' % (entry.get('metric'), entry.get('value')))
        self._summarize_profile(dec_pdir, 'profile_summary_r5_decode.txt',
                                'gpt_decode b8 (128 new tokens)')

    def _summarize_profile(self, pdir, out_name, rung_label):
        if not os.path.isdir(pdir):
            return
        try:
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, 'tools', 'profile_analysis.py'), pdir],
                capture_output=True, text=True, timeout=120)
            if proc.returncode != 0:
                log('profile summary (%s) failed rc=%d: %s'
                    % (out_name, proc.returncode, (proc.stderr or '')[-300:]))
                return
            out_path = os.path.join(REPO, 'docs', out_name)
            with open(out_path, 'w') as f:
                f.write('rung: %s (rev %s)\n%s'
                        % (rung_label, self.rev, proc.stdout))
            log('profile summary -> %s' % out_path)
        except Exception as e:
            log('profile summary (%s) failed: %r' % (out_name, e))

    def profile_best(self):
        """Capture an on-chip profile of the best rung — the data that
        tells WHERE the remaining MFU gap is, which no step-time number
        can. Raw xplane blobs live under docs/tpu_profile_r5 (gitignored);
        the committed evidence is the roofline summary text."""
        if self.best is None or not probe_tpu():
            return
        label, _, extra = self.best
        pdir = os.path.join(REPO, 'docs', 'tpu_profile_r5')
        prof_env = dict(extra, PADDLE_TPU_BENCH_PROFILE=pdir,
                        PADDLE_TPU_BENCH_STEPS='8',
                        PADDLE_TPU_BENCH_WARMUP='4')
        entries, err, wall = run_child('bench.py', prof_env, 1500,
                                       self.snap)
        good = [e for e in (entries or []) if 'error' not in e]
        result = good[-1] if good else None
        self.rec.record('profile_' + label, result, err, wall, self.rev)
        log('profile(%s): %s (%.0fs)' % (
            label, 'ok -> %s' % pdir if result is not None else err, wall))
        if result is None:
            return
        self._summarize_profile(pdir, 'profile_summary_r5.txt', label)


def main():
    _load_custom_ladder()
    lock = open(LOCK, 'w')
    try:
        fcntl.flock(lock, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        log('another warmer holds the lock; exiting')
        return
    if not probe_tpu():
        log('TPU not up at warmer start; exiting')
        return
    Warmer().run()
    log('warmer done')


if __name__ == '__main__':
    main()
