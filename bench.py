"""Benchmark: BERT-base-equivalent causal-LM training throughput on 1 chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Metric: samples/sec/chip on a BERT-base-sized (110M-param-class) transformer
training step (fwd+bwd+AdamW), seq 512, bf16 compute — BASELINE.json
config-3 family. vs_baseline is measured MFU vs the 50% north-star target
(reference publishes no absolute numbers; BASELINE.md).

Robustness contract (VERDICT r1 item 1): this script NEVER exits non-zero
and ALWAYS prints a JSON line. Every backend touch happens in a child
process with a hard timeout, so a TPU backend-init crash OR HANG cannot
take down the parent; the parent probes with staged backoff, then falls
back to a CPU run tagged {"degraded": true}.

Note: the CPU fallback selects the platform via
jax.config.update('jax_platforms', 'cpu') INSIDE the child — the
JAX_PLATFORMS env var routes through the axon backend shim and can hang.
"""
import json
import os
import subprocess
import sys
import time

_CHILD_ENV = 'PADDLE_TPU_BENCH_CHILD'       # '1' => run the measurement
_PLATFORM_ENV = 'PADDLE_TPU_BENCH_PLATFORM'  # 'cpu' => force CPU backend

# the north-star target (BASELINE.md config 3): vs_baseline = mfu_6n / this,
# used identically for the live run and any attached TPU capture
_BASELINE_MFU = 0.50

_PROBE_SRC = (
    "import jax\n"
    "print('PLATFORM=' + jax.devices()[0].platform)\n"
)

# A trivial Mosaic kernel: decides whether flash attempts are even worth
# their child timeout. The axon relay's remote Pallas compile service can
# wedge (hang, not error) — when THIS hangs, every pallas_call will, so
# the ladder should jump straight to the flash-disabled rung instead of
# burning 2x1500s on doomed children.
_PALLAS_PROBE_SRC = (
    "import jax, jax.numpy as jnp\n"
    "from jax.experimental import pallas as pl\n"
    "def k(x_ref, o_ref):\n"
    "    o_ref[...] = x_ref[...] * 2.0\n"
    "x = jnp.ones((256, 256), jnp.float32)\n"
    "y = pl.pallas_call(\n"
    "    k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)\n"
    "assert float(y[0, 0]) == 2.0\n"
    "print('PALLAS=ok')\n"
)


def _probe_pallas(timeout=None):
    """True iff a trivial pallas_call compiles+runs on the backend."""
    if timeout is None:
        timeout = int(os.environ.get('PADDLE_TPU_BENCH_PALLAS_PROBE_TIMEOUT',
                                     300))
    try:
        proc = subprocess.run([sys.executable, '-c', _PALLAS_PROBE_SRC],
                              capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, 'pallas probe hung (>%ds)' % timeout
    if 'PALLAS=ok' in proc.stdout:
        return True, None
    return False, 'pallas probe rc=%d: %s' % (proc.returncode,
                                              (proc.stderr or '')[-400:])


def _enable_persistent_cache():
    """Point jax's persistent compilation cache at a repo-local dir.

    The axon pool wedges for hours; when it is up, every compiled
    executable lands here so a later bench run (e.g. the driver's
    end-of-round one) skips XLA compilation entirely — a warm window
    survives a wedged one. See tools/tpu_warmer.py. One configuration
    path repo-wide (framework/compile_cache.py): PADDLE_TPU_CACHE_DIR
    keeps working, and the module's hit/miss tallies feed the
    compile_cache_hit_rate bench column.
    """
    from paddle_tpu.framework import compile_cache
    return compile_cache.configure()


def _run_measurement():
    """Child-process body: the actual benchmark. Prints one JSON line."""
    import jax
    if os.environ.get(_PLATFORM_ENV):
        jax.config.update('jax_platforms', os.environ[_PLATFORM_ENV])
    _enable_persistent_cache()

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.framework import functional as func_mod

    paddle.seed(0)
    platform = jax.devices()[0].platform
    on_tpu = platform == 'tpu'
    # seq override: long-context rungs (blockwise attention) ride the
    # same harness — the warmer measures seq 2048/8192 variants
    seq = int(os.environ.get('PADDLE_TPU_BENCH_SEQ', 512))
    # fused head+CE (ops/fused_ce.py): never materializes [B*S, vocab]
    # logits — the profile-measured ~13ms/step of vocab-tensor HBM
    # traffic (docs/PERF_NOTES_r4.md)
    fused_ce = os.environ.get('PADDLE_TPU_FUSED_CE', '1') != '0'
    if on_tpu:
        # fail loudly if the Pallas flash kernel cannot run on the chip:
        # a silent jnp fallback would invalidate the number. Since r3 the
        # strict check covers SHAPE fallbacks too (flash_attention._supported
        # raises) and the jaxpr assertion below proves the pallas_call is in
        # the measured program.
        os.environ.setdefault('PADDLE_TPU_FLASH_STRICT', '1')
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=seq,
                        dropout=0.0, fused_loss=fused_ce)
        batch = int(os.environ.get('PADDLE_TPU_BENCH_BATCH', 32))
        steps = int(os.environ.get('PADDLE_TPU_BENCH_STEPS', 30))
    else:  # CPU smoke fallback keeps the harness runnable anywhere
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=128,
                        dropout=0.0, fused_loss=fused_ce)
        seq = 128
        batch = 4
        steps = 3

    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return model.loss(logits, labels)

    remat = os.environ.get('PADDLE_TPU_BENCH_REMAT', '0') == '1'
    step = func_mod.TrainStep(model, loss_fn, opt, remat=remat)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    flash_in_program = False
    if on_tpu:
        # the measured program must contain the Pallas flash kernel —
        # combined with strict mode (any fallback raises) this makes a
        # "flash" number that didn't run flash impossible. The
        # FLASH_DISABLE retry path reports flash_in_program=false.
        jaxpr = step.trace_jaxpr(ids, labels)
        flash_in_program = 'pallas_call' in jaxpr
        if not flash_in_program and \
                os.environ.get('PADDLE_TPU_FLASH_DISABLE') != '1':
            raise RuntimeError('flash pallas_call absent from the step jaxpr')

    # device training loop: K steps per dispatch via lax.scan
    # (TrainStep.multi_step). The tunnel charges a per-dispatch toll —
    # scanning K steps inside one XLA program amortizes it K-fold.
    scan_k = int(os.environ.get('PADDLE_TPU_BENCH_SCAN_STEPS', '0'))

    # warmup/compile. The axon tunnel's dispatch path ramps over the first
    # ~tens of steps (fresh-process step times start 4-10x higher than
    # steady state), so warm until the measured window sees steady state.
    # The CompileWatchdog arms after warmup: a recompile inside the
    # measured window invalidates the number, and now gets reported.
    from paddle_tpu.monitor.perf import CompileWatchdog, costmodel
    wd = CompileWatchdog(strict=False, name='bench')
    warmup = int(os.environ.get('PADDLE_TPU_BENCH_WARMUP',
                                15 if on_tpu else 1))
    if scan_k > 1:
        import numpy as _np
        ids_k = paddle.to_tensor(_np.broadcast_to(
            ids.numpy(), (scan_k,) + tuple(ids.shape)).copy())
        labels_k = paddle.to_tensor(_np.broadcast_to(
            labels.numpy(), (scan_k,) + tuple(labels.shape)).copy())
        t_cold = time.time()
        losses = step.multi_step(ids_k, labels_k)
        _ = losses.numpy()
        compile_s_cold = time.time() - t_cold
        # the relay's dispatch path ramps over the first dispatches, not
        # steps — warm at least 3 dispatches regardless of K
        for _ in range(max(3, -(-warmup // scan_k))):
            losses = step.multi_step(ids_k, labels_k)
        _ = losses.numpy()
    else:
        t_cold = time.time()
        loss = step(ids, labels)
        _ = loss.numpy()
        compile_s_cold = time.time() - t_cold
        for _ in range(warmup):
            loss = step(ids, labels)
        _ = loss.numpy()
    wd.declare_warmup('bench warmup done')

    profile_dir = os.environ.get('PADDLE_TPU_BENCH_PROFILE')
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    # per-dispatch variance view (costs one host fetch per dispatch —
    # ~77ms each through the relay — so it is opt-in; the headline number
    # keeps the single end-of-loop fetch)
    per_dispatch = os.environ.get('PADDLE_TPU_BENCH_PER_DISPATCH') == '1'
    dispatch_ms = []
    t0 = last = time.time()
    if scan_k > 1:
        n_dispatch = max(1, steps // scan_k)
        for _ in range(n_dispatch):
            losses = step.multi_step(ids_k, labels_k)
            if per_dispatch:
                _ = losses.numpy()
                now = time.time()
                dispatch_ms.append(round(1000 * (now - last), 2))
                last = now
        _ = losses.numpy()
        steps = scan_k * n_dispatch
    else:
        for _ in range(steps):
            loss = step(ids, labels)
            if per_dispatch:
                _ = loss.numpy()
                now = time.time()
                dispatch_ms.append(round(1000 * (now - last), 2))
                last = now
        _ = loss.numpy()
    dt = time.time() - t0
    if profile_dir:
        jax.profiler.stop_trace()
    recompiles = wd.recompiles
    wd.close()
    # persistent-cache effectiveness of THIS process's compiles: 1.0 on
    # a fully warmed cache (the cold-start rung), ~0 on a fresh one
    from paddle_tpu.framework import compile_cache
    cache_hit_rate = compile_cache.hit_rate()

    # cost-model block: analytic FLOPs/bytes of the single-step program
    # (per-step numbers even under scan), plus a warm compile time — the
    # second lower+compile resolves through the compilation cache, so it
    # measures the cache-hit path, not XLA
    perf_est = None
    compile_s_warm = None
    try:
        compiled = step.compiled_executable(ids, labels)
        t_warm = time.time()
        step.compiled_executable(ids, labels)
        compile_s_warm = time.time() - t_warm
        perf_est = costmodel.estimate(compiled, step_seconds=dt / steps)
    except Exception:
        pass

    samples_per_sec = batch * steps / dt
    n_params = model.num_params()
    # MFU counts the model's actual matmul flops: 6N per token PLUS the
    # attention quadratic term (12*L*h*s per token) — the PaLM-appendix-B
    # convention. mfu_6n (params-only) is reported alongside for
    # comparability with earlier rounds' captures.
    flops_per_step = float(model.flops_per_token(seq)) * batch * seq
    flops_6n_per_step = 6.0 * n_params * batch * seq
    # v5e peak bf16 ~197 TFLOP/s/chip; CPU value meaningless but reported
    peak = 197e12 if on_tpu else 1e12
    mfu = flops_per_step * steps / dt / peak
    mfu_6n = flops_6n_per_step * steps / dt / peak

    print(json.dumps({
        'metric': 'bert_base_lm_train_samples_per_sec_per_chip',
        'value': round(samples_per_sec, 3),
        'unit': 'samples/sec/chip',
        # vs_baseline stays in the 6N convention every earlier capture
        # used — the conservative number; 'mfu' (with attention flops,
        # PaLM convention) is reported alongside
        'vs_baseline': round(mfu_6n / _BASELINE_MFU, 4),
        'mfu': round(mfu, 4),
        'mfu_6n': round(mfu_6n, 4),
        'step_ms': round(1000.0 * dt / steps, 2),
        'batch': batch,
        'seq': seq,
        'flash_in_program': flash_in_program,
        'fused_ce': fused_ce,
        'scan_steps': scan_k,
        'attn_impl': os.environ.get('PADDLE_TPU_ATTN_IMPL', 'auto'),
        'qkv_split': os.environ.get('PADDLE_TPU_QKV_SPLIT', 'headaxis'),
        'fused_ce_chunk': _fce_chunk(),
        # effective flash knobs from the ONE defaults table (the same
        # resolve() the kernel module latches at import)
        **{'flash_%s' % k: v for k, v in _flash_knobs().items()},
        **({'blockwise_block': int(os.environ['PADDLE_TPU_BLOCKWISE_BLOCK'])}
           if 'PADDLE_TPU_BLOCKWISE_BLOCK' in os.environ else {}),
        'platform': platform,
        'degraded': not on_tpu,
        'compile_s_cold': round(compile_s_cold, 3),
        **({'compile_s_warm': round(compile_s_warm, 3)}
           if compile_s_warm is not None else {}),
        'recompiles': recompiles,
        **({'compile_cache_hit_rate': round(cache_hit_rate, 4)}
           if cache_hit_rate is not None else {}),
        **({'mfu_est': round(perf_est['mfu_est'], 4),
            'arithmetic_intensity':
                round(perf_est['arithmetic_intensity'], 2),
            'roofline_bound': perf_est['roofline_bound']}
           if perf_est and 'mfu_est' in perf_est else {}),
        **({'dispatch_ms': dispatch_ms} if dispatch_ms else {}),
    }))


def _flash_defaults_mod():
    """Load ops/flash_defaults.py WITHOUT importing the paddle_tpu
    package: the parent process must never trigger the package's jax
    import (backend touches belong in children with timeouts)."""
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'paddle_tpu', 'ops', 'flash_defaults.py')
    spec = importlib.util.spec_from_file_location('_flash_defaults', path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _flash_knobs():
    return _flash_defaults_mod().resolve()


def _fce_chunk():
    try:
        from paddle_tpu.ops.fused_ce import env_chunk_rows
        return env_chunk_rows()
    except Exception:
        return None


def _capture_replay_env(entry):
    """Map a warmer capture row back to the FULL child env that produced
    it, every knob pinned in BOTH directions — a stray operator env var
    (FLASH_DISABLE=1, QKV_SPLIT=last, ...) in the driver's environment
    must not leak into a 'verbatim' replay. Pure function (unit-tested)."""
    env = {
        'PADDLE_TPU_BENCH_SCAN_STEPS':
            str(int(entry.get('scan_steps') or 0)),
        'PADDLE_TPU_FUSED_CE': '1' if entry.get('fused_ce') else '0',
        'PADDLE_TPU_QKV_SPLIT': str(entry.get('qkv_split') or 'headaxis'),
        'PADDLE_TPU_ATTN_IMPL': str(entry.get('attn_impl') or 'auto'),
        # rows from before a knob existed must replay at the value that
        # era's code actually used, NOT today's default — legacy fwd
        # blocks were 256/512, the legacy long path reused the fwd
        # blocks, and the legacy router was '> 4096' (= today's
        # '>= 4097')
        'PADDLE_TPU_FLASH_BLOCK_Q':
            str(int(entry.get('flash_block_q') or 256)),
        'PADDLE_TPU_FLASH_BLOCK_K':
            str(int(entry.get('flash_block_k') or 512)),
        'PADDLE_TPU_FLASH_BLOCK_Q_BWD':
            str(int(entry.get('flash_block_q_bwd')
                    or entry.get('flash_block_q') or 256)),
        'PADDLE_TPU_FLASH_BLOCK_K_BWD':
            str(int(entry.get('flash_block_k_bwd')
                    or entry.get('flash_block_k') or 512)),
        'PADDLE_TPU_FLASH_BLOCK_Q_LONG':
            str(int(entry.get('flash_block_q_long')
                    or entry.get('flash_block_q') or 256)),
        'PADDLE_TPU_FLASH_BLOCK_K_LONG':
            str(int(entry.get('flash_block_k_long')
                    or entry.get('flash_block_k') or 512)),
        'PADDLE_TPU_FLASH_LONG_SEQ':
            str(int(entry.get('flash_long_seq') or 4097)),
        # rows predating the fused-backward kernel ran the two-pass path
        'PADDLE_TPU_FLASH_FUSED_BWD':
            '1' if entry.get('flash_fused_bwd') else '0',
    }
    if entry.get('flash_in_program'):
        env['PADDLE_TPU_FLASH_DISABLE'] = '0'
        env['PADDLE_TPU_FLASH_STRICT'] = '1'
    else:
        env['PADDLE_TPU_FLASH_DISABLE'] = '1'
        env['PADDLE_TPU_FLASH_STRICT'] = '0'
    chunk = entry.get('fused_ce_chunk')
    if chunk and entry.get('fused_ce'):
        env['PADDLE_TPU_FUSED_CE_CHUNK'] = str(int(chunk))
    if entry.get('blockwise_block'):
        env['PADDLE_TPU_BLOCKWISE_BLOCK'] = \
            str(int(entry['blockwise_block']))
    if entry.get('batch'):
        env['PADDLE_TPU_BENCH_BATCH'] = str(int(entry['batch']))
    if entry.get('seq'):
        env['PADDLE_TPU_BENCH_SEQ'] = str(int(entry['seq']))
    return env


# the TPU child's effective defaults for every replayable knob — used to
# compare ladder entries and replay envs as COMPLETE configs, so two env
# dicts that differ only in unstated defaults still compare equal
_KNOB_DEFAULTS = {
    'PADDLE_TPU_BENCH_SCAN_STEPS': '0',
    'PADDLE_TPU_FUSED_CE': '1',
    'PADDLE_TPU_FUSED_CE_CHUNK': '4096',
    'PADDLE_TPU_QKV_SPLIT': 'headaxis',
    'PADDLE_TPU_ATTN_IMPL': 'auto',
    # flash knobs: one source of truth (ops/flash_defaults.py)
    **{'PADDLE_TPU_FLASH_%s' % k.upper(): str(v)
       for k, v in (lambda d: {
           'BLOCK_Q': d.BLOCK_Q, 'BLOCK_K': d.BLOCK_K,
           'BLOCK_Q_BWD': d.BLOCK_Q, 'BLOCK_K_BWD': d.BLOCK_K,
           'BLOCK_Q_LONG': d.BLOCK_Q_LONG, 'BLOCK_K_LONG': d.BLOCK_K_LONG,
           'LONG_SEQ': d.LONG_SEQ,
           'FUSED_BWD': '1' if d.FUSED_BWD else '0',
           })(_flash_defaults_mod()).items()},
    'PADDLE_TPU_FLASH_DISABLE': '0',
    'PADDLE_TPU_FLASH_STRICT': '1',
    'PADDLE_TPU_BENCH_BATCH': '32',
    'PADDLE_TPU_BENCH_SEQ': '512',
}


def _effective_env(extra):
    """Complete a partial child-env dict with the knob defaults."""
    eff = dict(_KNOB_DEFAULTS)
    eff.update(extra or {})
    # the bwd blocks inherit the (possibly overridden) fwd blocks when
    # unset — mirror the kernel's env contract so two spellings of the
    # same effective config compare equal
    if 'PADDLE_TPU_FLASH_BLOCK_Q_BWD' not in (extra or {}):
        eff['PADDLE_TPU_FLASH_BLOCK_Q_BWD'] = eff['PADDLE_TPU_FLASH_BLOCK_Q']
    if 'PADDLE_TPU_FLASH_BLOCK_K_BWD' not in (extra or {}):
        eff['PADDLE_TPU_FLASH_BLOCK_K_BWD'] = eff['PADDLE_TPU_FLASH_BLOCK_K']
    return eff


def _best_capture(headline_seq=None):
    """Best non-suspect real-TPU capture row across the in-window logs
    (6N-convention ranking). With headline_seq set, only rows measured
    at that sequence length qualify — the driver's replay must stay the
    module-contract workload (seq-512 BERT-base); a long-context rung
    topping the window must not silently become the headline number."""
    best = None
    for path in _inwindow_log_paths():
        try:
            f = open(path, errors='replace')
        except OSError:
            continue
        with f:
            for line in f:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                mfu = e.get('mfu_6n', e.get('mfu'))
                if e.get('platform') == 'tpu' and not e.get('degraded') \
                        and not e.get('suspect') \
                        and isinstance(mfu, (int, float)):
                    if headline_seq is not None and \
                            e.get('seq') != headline_seq:
                        continue
                    if best is None or mfu > best.get(
                            'mfu_6n', best.get('mfu')):
                        best = e
    return best


def _probe_backend(timeout=None):
    """Ask a child what the default backend is, failing FAST.

    With no explicit `timeout` this runs one SHORT attempt (default 30s,
    PADDLE_TPU_BENCH_PROBE_SHORT_TIMEOUT) and, only if that attempt
    fails, exactly one LONG retry (default 240s,
    PADDLE_TPU_BENCH_PROBE_TIMEOUT). A healthy backend answers in
    seconds, so the short probe decides almost every run; a hung tunnel
    now costs 30s + 240s instead of the previous three serial 240s
    probes. PADDLE_TPU_BENCH_FAST_PROBE=1 keeps its meaning — short
    attempt only, no retry. An explicit `timeout` is a single bounded
    attempt. Callers see the same (platform, err) contract either way;
    a probe that never succeeds still yields the degraded-CPU run.
    """
    if timeout is not None:
        return _probe_backend_once(timeout)
    short = int(os.environ.get('PADDLE_TPU_BENCH_PROBE_SHORT_TIMEOUT', 30))
    platform, err = _probe_backend_once(short)
    if (platform is not None
            or os.environ.get('PADDLE_TPU_BENCH_FAST_PROBE') == '1'):
        return platform, err
    retry = int(os.environ.get('PADDLE_TPU_BENCH_PROBE_TIMEOUT', 240))
    platform, err2 = _probe_backend_once(retry)
    if platform is not None:
        return platform, None
    return None, 'short probe: %s; long retry: %s' % (err, err2)


def _probe_backend_once(timeout):
    try:
        proc = subprocess.run([sys.executable, '-c', _PROBE_SRC],
                              capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, 'backend probe hung (>%ds)' % timeout
    for line in proc.stdout.splitlines():
        if line.startswith('PLATFORM='):
            return line.split('=', 1)[1].strip(), None
    return None, 'probe rc=%d: %s' % (proc.returncode,
                                      (proc.stderr or '')[-500:])


def _spawn_child(extra_env=None, timeout=1500):
    """Run the measurement in a child; return (json dict | None, err)."""
    env = dict(os.environ)
    env[_CHILD_ENV] = '1'
    if extra_env:
        env.update(extra_env)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, text=True, env=env, timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, 'child timed out after %ds' % timeout
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith('{'):
            try:
                return json.loads(line), None
            except (json.JSONDecodeError, ValueError):
                continue
    tail = (proc.stderr or proc.stdout or '')[-800:]
    return None, 'child rc=%d: %s' % (proc.returncode, tail)


def _inwindow_log_paths():
    """The warmer's in-window logs (tools/tpu_warmer.py writes the
    current round's; earlier rounds' files remain valid capture sources
    until a newer window beats them). Override with
    PADDLE_TPU_BENCH_INWINDOW_LOG."""
    override = os.environ.get('PADDLE_TPU_BENCH_INWINDOW_LOG')
    if override:
        return [override]
    docs = os.path.join(os.path.dirname(os.path.abspath(__file__)), 'docs')
    return [os.path.join(docs, 'bench_inwindow_r5.jsonl'),
            os.path.join(docs, 'bench_inwindow_r4.jsonl')]


def _attach_tpu_capture(result):
    """Attach the round's best warmer-captured REAL-TPU measurement
    (platform 'tpu', not degraded) to ANY result: a degraded run carries
    it as the round's genuine TPU evidence, a live run carries it for
    comparison (the warmer may have measured a better rung). Its
    presence does NOT imply degradation — check result['degraded'].
    Purely opportunistic: ANY failure reading the log must not cost the
    real measured number."""
    try:
        # _best_capture carries the ranking rules (6N convention,
        # suspect/degraded exclusion) for BOTH the attached evidence and
        # the replay rung — one copy, no drift. The attachment stays
        # unfiltered by workload (it is labeled with its own batch/seq).
        best = _best_capture()
        if best is not None:
            keep = ('ts', 'label', 'mfu', 'mfu_6n', 'step_ms', 'value',
                    'unit', 'batch', 'seq', 'scan_steps', 'attn_impl',
                    'fused_ce', 'fused_ce_chunk', 'qkv_split',
                    'flash_in_program', 'flash_block_q', 'flash_block_k',
                    'flash_block_q_bwd', 'flash_block_k_bwd',
                    'flash_block_q_long', 'flash_block_k_long',
                    'flash_long_seq', 'flash_fused_bwd', 'git_rev',
                    'platform')
            cap = {k: best[k] for k in keep if k in best}
            # the capture carries its OWN vs_baseline (6N convention /
            # the 50% north star) — the top-level vs_baseline belongs to
            # the possibly-degraded live run and must not be read as the
            # TPU number's ratio
            mfu6 = best.get('mfu_6n', best.get('mfu'))
            if isinstance(mfu6, (int, float)):
                cap['vs_baseline'] = round(mfu6 / _BASELINE_MFU, 4)
            result['last_tpu_capture'] = cap
    except Exception:
        pass


def _fallback_json(errors):
    print(json.dumps({
        'metric': 'bert_base_lm_train_samples_per_sec_per_chip',
        'value': 0.0,
        'unit': 'samples/sec/chip',
        'vs_baseline': 0.0,
        'degraded': True,
        'error': '; '.join(errors)[-2000:],
    }))


def main():
    if os.environ.get(_CHILD_ENV) == '1':
        _run_measurement()
        return

    errors = []
    try:
        _orchestrate(errors)
    except BaseException as e:  # the contract: ALWAYS print a JSON line
        errors.append('orchestrator: %r' % (e,))
        _fallback_json(errors)


def _orchestrate(errors):
    # 1) bounded backend probe; the short-then-long staging lives inside
    #    _probe_backend so a hung tunnel fails fast instead of eating
    #    three serial full-length timeouts
    platform, err = _probe_backend()
    if platform is None:
        errors.append('probe: %s' % err)

    # 2) measured run on the probed (real) backend; the retry disables
    #    the Pallas flash kernel so a kernel-compile failure still yields
    #    an honest number (flash_in_program=false distinguishes it)
    if platform is not None:
        # best-first from the round-5 in-window measurements
        # (docs/bench_inwindow_r5.jsonl): the head rung is the measured
        # optimum — fused CE + flash 512/512 + fused single-tile
        # backward (all code defaults) + the qkv last-axis split (safe
        # single-chip; not a default because under tensor parallelism
        # q/k/v offsets would straddle mp shards), scan8 amortizing the
        # tunnel's dispatch toll. Then the default-knob rung, then
        # without fused CE, then flash off.
        ladder = (({'PADDLE_TPU_BENCH_SCAN_STEPS': '8',
                    'PADDLE_TPU_QKV_SPLIT': 'last'},
                   'fused_flash_scan8_qkvlast'),
                  ({'PADDLE_TPU_BENCH_SCAN_STEPS': '8'}, 'fused_flash_scan8'),
                  (None, 'fused_flash_plain'),
                  ({'PADDLE_TPU_FUSED_CE': '0',
                    'PADDLE_TPU_BENCH_SCAN_STEPS': '8'}, 'flash_scan8'),
                  ({'PADDLE_TPU_FUSED_CE': '0'}, 'flash_plain'),
                  ({'PADDLE_TPU_FUSED_CE': '0',
                    'PADDLE_TPU_FLASH_DISABLE': '1',
                    'PADDLE_TPU_FLASH_STRICT': '0'}, 'flash_disabled'))
        pallas_ok = True
        if platform == 'tpu':
            pallas_ok, perr = _probe_pallas()
            if not pallas_ok:
                errors.append(perr)
                # flash rungs are doomed; go straight to the XLA path,
                # fused-first, with non-fused fallbacks. Derived from the
                # safe rung so the flash-disable contract stays in one
                # place.
                off = dict(ladder[-1][0])
                del off['PADDLE_TPU_FUSED_CE']
                fscan8 = dict(off, PADDLE_TPU_BENCH_SCAN_STEPS='8')
                scan8 = dict(fscan8, PADDLE_TPU_FUSED_CE='0')
                plain = dict(off, PADDLE_TPU_FUSED_CE='0')
                ladder = ((fscan8, 'fused_flash_disabled_scan8'),
                          (dict(off), 'fused_flash_disabled'),
                          (scan8, 'flash_disabled_scan8'),
                          (plain, 'flash_disabled'))
        # self-tuning head rung: replay the best warmer-measured config
        # verbatim (the warmer explored the A/Bs; the driver's bench
        # should not re-guess). Headline workload only (seq 512 —
        # module contract); skipped when it needs flash and the pallas
        # probe just failed; ladder entries that resolve to the same
        # effective config are dropped so a hang can't burn two child
        # timeouts on one doomed config.
        best = _best_capture(headline_seq=512)
        head_extra = None
        if best is not None:
            renv = _capture_replay_env(best)
            if pallas_ok or renv.get('PADDLE_TPU_FLASH_DISABLE') == '1':
                # the fixed ladder's head may encode a NEWER optimum than
                # the best logged capture (kernel improvements land
                # between windows): when the configs differ, run BOTH and
                # report the faster — one extra ~75s child at round end
                # buys never reporting a stale number
                if ladder and _effective_env(ladder[0][0]) !=                         _effective_env(renv):
                    head_extra = ladder[0]
                ladder = tuple(
                    (extra, label) for extra, label in ladder
                    if _effective_env(extra) != _effective_env(renv))
                ladder = ((renv, 'best_inwindow_replay'),) + ladder
        for attempt, (extra, label) in enumerate(ladder):
            result, err = _spawn_child(extra_env=extra)
            if result is not None:
                if label:
                    result['retry'] = label
                if label == 'best_inwindow_replay' and head_extra                         is not None:
                    h_res, h_err = _spawn_child(extra_env=head_extra[0])
                    if h_res is not None and h_res.get('mfu_6n', 0) >                             result.get('mfu_6n', 0):
                        h_res['retry'] = head_extra[1]
                        result = h_res
                    elif h_res is None:
                        errors.append('head rung: %s' % h_err)
                # context either way: a degraded result carries the
                # round's best REAL capture as its evidence; a live TPU
                # result carries it for comparison (the warmer may have
                # measured a better rung than the one that ran here)
                _attach_tpu_capture(result)
                print(json.dumps(result))
                return
            errors.append('run %d: %s' % (attempt, err))

    # 3) CPU fallback — a degraded number beats no number
    result, err = _spawn_child(extra_env={_PLATFORM_ENV: 'cpu'},
                               timeout=900)
    if result is not None:
        result['degraded'] = True
        result['error'] = '; '.join(errors)[-1500:]
        # the pool wedged at bench time, but the opportunistic warmer may
        # have captured real TPU runs earlier in the round — attach the
        # best one, labeled with its own timestamp, so the round's
        # recorded artifact carries the genuine TPU evidence
        _attach_tpu_capture(result)
        print(json.dumps(result))
        return
    errors.append('cpu fallback: %s' % err)

    # 4) last resort: still emit a JSON line, never exit non-zero
    _fallback_json(errors)


if __name__ == '__main__':
    main()
    sys.exit(0)
