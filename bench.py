"""Benchmark: BERT-base-equivalent causal-LM training throughput on 1 chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric: samples/sec/chip on a BERT-base-sized (110M-param-class) transformer
training step (fwd+bwd+AdamW), seq 512, bf16 activations — BASELINE.json
config-3 family. vs_baseline is measured MFU vs the 50% north-star target
(reference publishes no absolute numbers; BASELINE.md).
"""
import json
import time

import numpy as np


def main():
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.framework import functional as func_mod

    paddle.seed(0)
    on_tpu = jax.devices()[0].platform == 'tpu'
    seq = 512
    if on_tpu:
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=seq,
                        dropout=0.0)
        batch = 16
        steps = 20
    else:  # CPU smoke fallback keeps the harness runnable anywhere
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=128, dropout=0.0)
        seq = 128
        batch = 4
        steps = 3

    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        return model.loss(logits, labels)

    step = func_mod.TrainStep(model, loss_fn, opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    # warmup/compile
    step(ids, labels)
    step(ids, labels)

    t0 = time.time()
    for _ in range(steps):
        loss = step(ids, labels)
    _ = loss.numpy()
    dt = time.time() - t0

    samples_per_sec = batch * steps / dt
    n_params = model.num_params()
    flops_per_step = 6.0 * n_params * batch * seq
    achieved = flops_per_step * steps / dt
    # v5e peak bf16 ~197 TFLOP/s/chip; CPU value meaningless but reported
    peak = 197e12 if on_tpu else 1e12
    mfu = achieved / peak

    print(json.dumps({
        'metric': 'bert_base_lm_train_samples_per_sec_per_chip',
        'value': round(samples_per_sec, 3),
        'unit': 'samples/sec/chip',
        'vs_baseline': round(mfu / 0.50, 4),
    }))


if __name__ == '__main__':
    main()
