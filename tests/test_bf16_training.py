"""bf16 MODEL training regression tests (VERDICT r4 weak #1 / next #2).

Round 4 shipped a conv backward that crashed every bf16 conv model's
TrainStep (`lax.conv_general_dilated requires arguments to have the same
dtypes, got bfloat16, float32` — the astype cotangent arriving f32 at the
conv transpose), which is exactly what killed the in-window
`bench_resnet` rung twice and left BASELINE config 2 with no number.
These are the missing tests: a full conv+BN+pool model's jitted train
step in bf16, including the verbatim bench_resnet repro shape.

Reference analog: the vision-zoo train smoke tests
(python/paddle/vision/models/resnet.py + tests/test_vision_models.py
family) — which the reference runs in fp32/amp, and this repo must also
hold under pure-bf16 params (the TPU bench configuration).
"""
import pytest
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.framework.functional import TrainStep


def _step_model(model, batch, size, classes=10, steps=2):
    opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                    parameters=model.parameters())
    ce = nn.CrossEntropyLoss()
    step = TrainStep(model, lambda lo, la: ce(lo, la), opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(
        rng.randn(batch, 3, size, size).astype(np.float32)
    ).astype('bfloat16')
    y = paddle.to_tensor(rng.randint(0, classes, (batch,)).astype(np.int64))
    return [float(step(x, y).numpy()) for _ in range(steps)]


def test_bf16_convnet_trainstep():
    """Conv2D+BN+ReLU+pool+Linear — the minimal surface of the r4 crash."""
    paddle.seed(0)
    model = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1),
        nn.BatchNorm2D(8),
        nn.ReLU(),
        nn.MaxPool2D(2, 2),
        nn.Conv2D(8, 16, 3, stride=2, padding=1, groups=2),
        nn.ReLU(),
        nn.AdaptiveAvgPool2D(1),
        nn.Flatten(),
        nn.Linear(16, 10),
    )
    model.bfloat16()
    losses = _step_model(model, batch=4, size=16, steps=3)
    assert all(np.isfinite(l) for l in losses), losses
    # params must STAY bf16 (the r3/r4 silent-upcast lesson)
    for p in model.parameters():
        assert str(p.dtype) in ('bfloat16', 'paddle.bfloat16'), \
            (p.name if hasattr(p, 'name') else '?', p.dtype)


@pytest.mark.slow
def test_bf16_resnet18_trainstep():
    """The verbatim VERDICT repro: resnet18().bfloat16() + TrainStep +
    bf16 input — r4's code crashed in the VJP before this test existed."""
    from paddle_tpu.vision.models import resnet18
    paddle.seed(0)
    model = resnet18()
    model.bfloat16()
    losses = _step_model(model, batch=2, size=32, classes=1000, steps=2)
    assert all(np.isfinite(l) for l in losses), losses


def test_bf16_conv_eval_matches_f32():
    """bf16 conv forward stays within bf16 tolerance of f32 (the fix
    removed preferred_element_type — on the MXU accumulation is f32
    either way, so this guards the numerics claim behind that)."""
    import paddle_tpu.nn.functional as F
    rng = np.random.RandomState(1)
    x = rng.standard_normal((2, 3, 12, 12)).astype(np.float32)
    w = rng.standard_normal((5, 3, 3, 3)).astype(np.float32)
    ref = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w)).numpy()
    out = F.conv2d(paddle.to_tensor(x).astype('bfloat16'),
                   paddle.to_tensor(w).astype('bfloat16'))
    assert str(out.dtype) in ('bfloat16', 'paddle.bfloat16')
    np.testing.assert_allclose(out.astype('float32').numpy(), ref,
                               rtol=0.05, atol=0.05)
