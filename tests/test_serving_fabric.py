"""Serving fabric tests (paddle_tpu/serving/fabric/).

The fabric's load-bearing contracts:

  1. wire protocol failure taxonomy — malformed frame, oversized frame,
     mid-frame drop — every case yields a TYPED error (or a clean
     close), never a hung handler thread;
  2. exactly-once across the process boundary — a duplicate-delivered
     (client, seq) submit returns the SAME req_id and admits once;
  3. the gateway's failover / drain / rollout machinery works UNCHANGED
     through SocketReplica: killing a worker mid-burst still completes
     100% of requests with token parity, rollout() through socket
     replicas loses zero requests, and each request gets exactly one
     wide event carrying its cross-replica history;
  4. artifact distribution verifies what it pulls: corrupted payload or
     corrupted CRC manifest -> ArtifactVerifyError, never weights-
     silently-wrong;
  5. the prefix directory routes shared-prefix prompts to the replica
     that already holds their pages.

Fast tests run ReplicaWorker in-process over real localhost sockets
with jax-free stub engines; the slow chaos test SIGKILLs a real spawned
worker process mid-burst.
"""
import json
import os
import socket
import struct
import threading
import time
import urllib.error
import urllib.request

import pytest

from paddle_tpu.distributed.resilience import (FrameDecodeError,
                                               FrameTooLargeError)
from paddle_tpu.framework import io_save
from paddle_tpu.monitor import FleetCollector, MetricRegistry, to_dict
from paddle_tpu.monitor import events as _events
from paddle_tpu.serving import ServingGateway
from paddle_tpu.serving.fabric import (ArtifactClient, ArtifactServer,
                                       ArtifactVerifyError, MAX_FRAME,
                                       PrefixAffinityRouter,
                                       PrefixDirectory, ReplicaWorker,
                                       SocketReplica, recv_frame,
                                       send_frame)
from paddle_tpu.serving.fabric.transport import (DRAINING, READY, STOPPED)
from paddle_tpu.serving.registry import ModelHost, ModelRegistry

MNT = 6


# ---- jax-free stub engines -------------------------------------------


class _StubReq:
    def __init__(self, rid, prompt, max_new_tokens):
        self.id = rid
        self.prompt = list(prompt)
        self.tokens = []
        self.done = False
        self.outcome = None
        self.max_new = int(max_new_tokens)
        self._admit_t = time.monotonic()
        self._arrival_t = self._admit_t
        self._prefill_chunks = 1
        self._prefix_hit = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self.kv_page_seconds = 0.0


def _expected(prompt, n):
    """The stub's deterministic output: a pure function of the prompt,
    so failover to a fresh engine reproduces it exactly."""
    return [(prompt[-1] + i + 1) % 997 for i in range(n)]


class EchoEngine:
    """Engine-contract stub: each step() appends one deterministic
    token per in-flight request. Jax-free, so in-proc worker tests are
    milliseconds."""

    num_slots = 4

    class _Sched:
        def __init__(self, eng):
            self._eng = eng

        @property
        def queue(self):
            return [r for r in self._eng._reqs if not r.done]

        @property
        def pending(self):
            return len(self.queue)

    def __init__(self, step_delay=0.0005):
        self.scheduler = EchoEngine._Sched(self)
        self._reqs = []
        self._lock = threading.Lock()
        self._ids = 0
        self._down = False
        self.submits = 0
        self._delay = step_delay

    def add_request(self, prompt, max_new_tokens=MNT, emit_event=True,
                    **kw):
        with self._lock:
            if self._down:
                raise RuntimeError('engine is shut down')
            if not prompt:
                raise ValueError('empty prompt')
            self._ids += 1
            self.submits += 1
            r = _StubReq(self._ids, prompt, max_new_tokens)
            self._reqs.append(r)
            return r

    def step(self):
        with self._lock:
            for r in self._reqs:
                if r.done:
                    continue
                r.tokens.append(_expected(r.prompt, r.max_new)
                                [len(r.tokens)])
                if len(r.tokens) >= r.max_new:
                    r.done = True
                    r.outcome = 'ok'
            self._reqs = [r for r in self._reqs if not r.done]
        if self._delay:
            time.sleep(self._delay)
        return 1

    def shutdown(self):
        with self._lock:
            self._down = True


# ---- helpers ----------------------------------------------------------


def _hard_kill(worker):
    """The in-proc stand-in for SIGKILL: the TCP server and every live
    connection vanish without a goodbye; the drive thread stops."""
    with worker._lock:
        worker._stopping = True
        worker._cv.notify_all()
    worker._srv.shutdown()
    worker._srv.server_close()
    for conn in list(worker._srv.live_connections):
        try:
            conn.close()
        except OSError:
            pass
    worker._metrics.stop()


def _raw_conn(worker):
    host, port = worker.endpoint.rsplit(':', 1)
    return socket.create_connection((host, int(port)), timeout=5)


@pytest.fixture
def worker():
    w = ReplicaWorker(EchoEngine()).start()
    yield w
    w.stop()


@pytest.fixture
def worker_pair():
    ws = [ReplicaWorker(EchoEngine()).start() for _ in range(2)]
    yield ws
    for w in ws:
        try:
            w.stop()
        except Exception:
            pass


def _fabric_gateway(workers, **kw):
    kw.setdefault('registry', MetricRegistry())
    gw = ServingGateway(None, replicas=0, **kw)
    for w in workers:
        gw.adopt_replica(
            SocketReplica(w.endpoint, metrics_url=w.metrics_url,
                          poll_interval=0.001).connect())
    return gw


def _counter(gw, name, labels=None):
    fam = gw.registry.get(name)
    if labels is None:
        return fam.value()
    return fam.labels(*labels).value()


# ---- wire protocol edge cases ----------------------------------------


def test_frame_roundtrip_and_clean_eof():
    a, b = socket.socketpair()
    try:
        send_frame(a, {'op': 'ping', 'n': [1, 2, 3]})
        assert recv_frame(b) == {'op': 'ping', 'n': [1, 2, 3]}
        a.close()
        assert recv_frame(b) is None       # EOF at a frame boundary
    finally:
        b.close()


def test_malformed_frame_is_typed_decode_error():
    a, b = socket.socketpair()
    try:
        payload = b'\xff\xfenot json at all'
        a.sendall(struct.pack('>Q', len(payload)) + payload)
        with pytest.raises(FrameDecodeError):
            recv_frame(b)
        # non-encodable object on the SEND side is the same typed error
        with pytest.raises(FrameDecodeError):
            send_frame(a, {'op': object()})
    finally:
        a.close()
        b.close()


def test_oversized_frame_refused_before_allocation():
    a, b = socket.socketpair()
    try:
        # a corrupted header declaring an absurd length must be refused
        # without trying to buffer it
        a.sendall(struct.pack('>Q', MAX_FRAME + 1))
        with pytest.raises(FrameTooLargeError):
            recv_frame(b)
        with pytest.raises(FrameTooLargeError):
            send_frame(a, {'blob': 'x' * 64}, max_frame=16)
    finally:
        a.close()
        b.close()


def test_mid_frame_drop_is_connection_error():
    a, b = socket.socketpair()
    a.sendall(struct.pack('>Q', 100) + b'only ten b')
    a.close()
    with pytest.raises(ConnectionError):
        recv_frame(b)
    b.close()
    # ... and mid-header
    a, b = socket.socketpair()
    a.sendall(b'\x00\x00\x00')
    a.close()
    with pytest.raises(ConnectionError):
        recv_frame(b)
    b.close()


# ---- worker wire behavior --------------------------------------------


def test_worker_replies_typed_error_on_malformed_frame(worker):
    s = _raw_conn(worker)
    try:
        payload = b'{broken'
        s.sendall(struct.pack('>Q', len(payload)) + payload)
        out = recv_frame(s)
        assert out['error_type'] == 'FrameDecodeError'
    finally:
        s.close()
    # the worker is not hung: a fresh connection still serves
    s = _raw_conn(worker)
    try:
        send_frame(s, {'op': 'ping'})
        assert recv_frame(s)['ok'] is True
    finally:
        s.close()


def test_worker_replies_typed_error_on_oversized_frame(worker):
    s = _raw_conn(worker)
    try:
        s.sendall(struct.pack('>Q', MAX_FRAME + 1))
        out = recv_frame(s)
        assert out['error_type'] == 'FrameTooLargeError'
    finally:
        s.close()
    s = _raw_conn(worker)
    try:
        send_frame(s, {'op': 'status'})
        assert recv_frame(s)['ok'] is True
    finally:
        s.close()


def test_worker_survives_mid_frame_drop(worker):
    s = _raw_conn(worker)
    s.sendall(struct.pack('>Q', 5000) + b'partial')
    s.close()                       # drop mid-frame
    s = _raw_conn(worker)
    try:
        send_frame(s, {'op': 'ping'})
        assert recv_frame(s)['ok'] is True
    finally:
        s.close()


def test_duplicate_submit_dedups_on_client_seq(worker):
    msg = {'op': 'submit', 'client': 'c1', 'seq': 1, 'prompt': [5],
           'sampling': {'max_new_tokens': 2}}
    s = _raw_conn(worker)
    try:
        send_frame(s, msg)
        r1 = recv_frame(s)
        assert not r1.get('dup')
        # duplicate delivery (e.g. a retried send): same req_id, no
        # second admission
        send_frame(s, msg)
        r2 = recv_frame(s)
        assert r2['req_id'] == r1['req_id']
        assert r2['dup'] is True
        assert worker.engine.submits == 1
        # a STALE seq is a protocol error, typed
        send_frame(s, dict(msg, seq=0))
        r3 = recv_frame(s)
        assert r3['error_type'] == 'ValueError'
    finally:
        s.close()


def test_poll_unknown_request_is_typed_not_hung(worker):
    s = _raw_conn(worker)
    try:
        send_frame(s, {'op': 'poll', 'reqs': {'999': 0}, 'ack': []})
        out = recv_frame(s)
        assert out['reqs']['999']['unknown'] is True
        assert out['reqs']['999']['outcome'] == 'error'
    finally:
        s.close()


def test_worker_readyz_flips_503_on_drain(worker):
    with urllib.request.urlopen(worker.metrics_url + '/readyz',
                                timeout=5) as resp:
        assert resp.status == 200
    s = _raw_conn(worker)
    try:
        send_frame(s, {'op': 'drain'})
        assert recv_frame(s)['state'] == DRAINING
    finally:
        s.close()
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(worker.metrics_url + '/readyz', timeout=5)
    assert ei.value.code == 503
    # drained empty -> terminal rung, while the TCP server stays up
    deadline = time.monotonic() + 5
    while worker.state != STOPPED and time.monotonic() < deadline:
        time.sleep(0.01)
    assert worker.state == STOPPED
    # ... and a drained worker refuses new admissions, typed
    s = _raw_conn(worker)
    try:
        send_frame(s, {'op': 'submit', 'prompt': [1], 'sampling': {}})
        assert recv_frame(s)['error_type'] == 'RuntimeError'
    finally:
        s.close()


# ---- gateway over sockets --------------------------------------------


def test_socket_gateway_parity_and_one_wide_event_per_request(
        worker_pair):
    log = _events.RequestLog()
    prev = _events.set_default_request_log(log)
    try:
        gw = _fabric_gateway(worker_pair)
        prompts = [[3 + i, 7 + i] for i in range(8)]
        out = gw.generate(prompts, max_new_tokens=MNT)
        gw.shutdown()
    finally:
        _events.set_default_request_log(prev)
    assert out == [_expected(p, MNT) for p in prompts]
    routed = [_counter(gw, 'gateway_route_total', (str(i),))
              for i in range(2)]
    assert sum(routed) == len(prompts)
    assert all(v > 0 for v in routed), routed
    evs = log.events()
    assert len(evs) == len(prompts)      # exactly one per request
    assert all(len(e['replicas']) == 1 for e in evs)
    assert all(e['outcome'] == 'ok' for e in evs)


def test_socket_gateway_failover_chaos_oracle():
    """Kill one worker mid-burst (server + live sockets vanish): every
    request completes, the victim's in-flight work is re-placed, tokens
    are exactly the no-fault outputs, and wide events carry the
    two-replica history."""
    # slower stub decode: the kill window must be wide enough that the
    # victim reliably holds in-flight work when it dies
    workers = [ReplicaWorker(EchoEngine(step_delay=0.01)).start()
               for _ in range(2)]
    log = _events.RequestLog()
    prev = _events.set_default_request_log(log)
    try:
        gw = _fabric_gateway(workers)
        gw.start()
        prompts = [[11 + i] for i in range(10)]
        reqs = [gw.submit(p, max_new_tokens=24) for p in prompts]
        # wait until both replicas hold in-flight work, then kill one
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with gw._lock:
                if all(len(r.assigned) > 0 for r in gw.pool):
                    break
            time.sleep(0.002)
        victim = gw.pool[0]
        n_victim = len(victim.assigned)
        assert n_victim > 0
        _hard_kill(workers[0])
        for r in reqs:
            assert r.wait(timeout=30), 'request %d never finished' % r.id
        gw.shutdown()
    finally:
        _events.set_default_request_log(prev)
        for w in workers:
            try:
                w.stop()
            except Exception:
                pass
    # completed_ratio == 1.0 with exact token parity
    assert all(r.done for r in reqs)
    assert [r.tokens for r in reqs] == \
        [_expected(p, 24) for p in prompts]
    # every request in flight on the victim AT KILL TIME failed over
    # exactly once (a poll may have collected a finisher between the
    # in-flight snapshot and the kill, hence <=)
    fo = _counter(gw, 'gateway_failover_total')
    assert 1 <= fo <= n_victim
    evs = log.events()
    assert len(evs) == len(prompts)
    failed_over = [e for e in evs if len(e['replicas']) == 2]
    assert len(failed_over) == fo
    assert all(e['replicas'] == [0, 1] for e in failed_over)
    assert all(e['outcome'] == 'ok' for e in evs)


def test_socket_gateway_inadmissible_raises_not_failover(worker_pair):
    gw = _fabric_gateway(worker_pair)
    with pytest.raises(ValueError):
        gw.submit([], max_new_tokens=2)     # EchoEngine rejects empty
    assert _counter(gw, 'gateway_failover_total') == 0
    assert gw.replicas_alive == 2
    gw.shutdown()


# ---- fleet federation: worker processes as scrape targets -------------


def test_fleet_scrapes_worker_url_stale_not_wrong(worker_pair):
    meta = MetricRegistry()
    fc = FleetCollector(registry=meta, clock=time.monotonic)
    gw = _fabric_gateway(worker_pair)
    gw.attach_fleet(fc)
    assert sorted(t.instance for t in fc.targets()) == \
        ['gw-replica-0', 'gw-replica-1']
    assert fc.scrape() == {'ok': 2, 'down': 0}
    up = {s['labels']['instance']: s['value']
          for s in to_dict(meta)['fleet_target_up']['samples']}
    assert up == {'gw-replica-0': 1.0, 'gw-replica-1': 1.0}

    # SIGKILL-equivalent: the worker's HTTP endpoint vanishes. The
    # collector degrades to stale-not-wrong: up -> 0, last snapshot
    # retained, the survivor still scrapes clean.
    _hard_kill(worker_pair[0])
    assert fc.scrape() == {'ok': 1, 'down': 1}
    st = fc.fleet_status()
    assert st['targets']['gw-replica-0']['up'] is False
    assert st['targets']['gw-replica-0']['stale'] is True
    assert st['targets']['gw-replica-1']['up'] is True
    up = {s['labels']['instance']: s['value']
          for s in to_dict(meta)['fleet_target_up']['samples']}
    assert up == {'gw-replica-0': 0.0, 'gw-replica-1': 1.0}
    gw.shutdown()


# ---- rollout through socket replicas ----------------------------------


class _HostStubEngine:
    """test_model_registry's stub, trimmed: emits the serving VERSION
    digit so tests can tell which weights answered."""

    max_len = 128
    num_slots = 4
    spec_k = 0
    trace_counts = {'prefill': 1, 'decode': 1}

    def __init__(self, entry):
        from paddle_tpu.serving.metrics import ServingMetrics
        self.entry = entry
        self.metrics = ServingMetrics()
        self._reqs = []

    class _Sched:
        def __init__(self, eng):
            self._eng = eng

        @property
        def pending(self):
            return sum(1 for r in self._eng._reqs if not r.done)

        @property
        def queue(self):
            return tuple(r for r in self._eng._reqs if not r.done)

    @property
    def scheduler(self):
        return _HostStubEngine._Sched(self)

    def enqueue(self, req):
        if req._arrival_t is None:
            req._arrival_t = self.metrics.now()
        self._reqs.append(req)
        return req

    def step(self):
        from paddle_tpu.serving.scheduler import DONE
        for r in self._reqs:
            if not r.done:
                r.tokens.extend([int(self.entry.version[-1])]
                                * r.max_new_tokens)
                r.state = DONE
                r.outcome = 'ok'
                r._finished.set()
        return self.scheduler.pending

    def generate(self, prompts, max_new_tokens=2, emit_event=True):
        return [[1] * max_new_tokens for _ in prompts]

    def shutdown(self):
        pass

    def rebind_perf(self, registry):
        pass


def _publish_zoo(root):
    reg = ModelRegistry(root=str(root))
    reg.publish('alpha', 'v1', {'w': [1.0] * 64})
    reg.publish('alpha', 'v2', {'w': [2.0] * 64})
    return reg


@pytest.fixture
def host_worker_pair(tmp_path):
    ws = []
    for i in range(2):
        reg = _publish_zoo(tmp_path / ('w%d' % i))
        host = ModelHost(reg, lambda entry: _HostStubEngine(entry))
        ws.append(ReplicaWorker(host).start())
    yield ws
    for w in ws:
        try:
            w.stop()
        except Exception:
            pass


def test_rollout_through_socket_replicas_zero_loss(host_worker_pair):
    gw = _fabric_gateway(host_worker_pair)
    before = [gw.submit([1, 2], max_new_tokens=4, model='alpha')
              for _ in range(6)]
    gw.run()
    summary = gw.rollout('alpha', 'v2')
    after = [gw.submit([3], max_new_tokens=4, model='alpha')
             for _ in range(4)]
    gw.run()
    gw.shutdown()
    assert all(r.done and r.error is None for r in before + after)
    assert summary['model'] == 'alpha'
    assert summary['from_version'] == 'v1'
    assert summary['to_version'] == 'v2'
    assert summary['replicas'] == [0, 1]
    # pre-swap served by v1, post-swap by v2 — in BOTH worker processes
    assert all(r.tokens == [1] * 4 for r in before)
    assert all(r.tokens == [2] * 4 for r in after)
    for w in host_worker_pair:
        assert w.engine.registry.serving_version('alpha') == 'v2'


def test_rollout_pulls_missing_artifact_over_fabric(tmp_path):
    """A worker whose local registry lacks the target version pulls it
    from the gateway's ArtifactServer during rollout_prepare, verified
    end to end."""
    src = _publish_zoo(tmp_path / 'src')
    art = ArtifactServer(src).start()
    local = ModelRegistry(root=str(tmp_path / 'w0'))
    local.publish('alpha', 'v1', {'w': [1.0] * 64})   # v2 is MISSING
    host = ModelHost(local, lambda entry: _HostStubEngine(entry))
    client = ArtifactClient(art.endpoint, str(tmp_path / 'cache'))
    w = ReplicaWorker(host, artifact_client=client).start()
    try:
        gw = _fabric_gateway([w])
        r = gw.submit([1], max_new_tokens=2, model='alpha')
        gw.run()
        assert r.tokens == [1, 1]
        assert ('alpha', 'v2') not in local
        summary = gw.rollout('alpha', 'v2')
        assert summary['to_version'] == 'v2'
        # the pull registered a verified local copy
        assert ('alpha', 'v2') in local
        assert local.entry('alpha', 'v2').fingerprint == \
            src.entry('alpha', 'v2').fingerprint
        r2 = gw.submit([1], max_new_tokens=2, model='alpha')
        gw.run()
        assert r2.tokens == [2, 2]
        gw.shutdown()
    finally:
        w.stop()
        art.stop()


# ---- artifact verification -------------------------------------------


def test_artifact_pull_roundtrip_and_fingerprint(tmp_path):
    src = _publish_zoo(tmp_path / 'src')
    art = ArtifactServer(src).start()
    try:
        dst = ModelRegistry(root=str(tmp_path / 'dst'))
        client = ArtifactClient(art.endpoint, str(tmp_path / 'cache'))
        entry = client.ensure(dst, 'alpha', 'v1')
        assert entry.fingerprint == src.entry('alpha', 'v1').fingerprint
        assert ('alpha', 'v1') in dst
        # idempotent: a second ensure is a catalog hit, not a re-pull
        again = client.ensure(dst, 'alpha', 'v1')
        assert again.path == entry.path
    finally:
        art.stop()


def _corrupt(path, at=-3):
    with open(path, 'rb') as f:
        blob = bytearray(f.read())
    blob[at] ^= 0xFF
    with open(path, 'wb') as f:
        f.write(bytes(blob))


def test_corrupted_artifact_payload_typed_reject(tmp_path):
    src = _publish_zoo(tmp_path / 'src')
    # corrupt the PAYLOAD, leave the CRC manifest intact: the content
    # fingerprint (a manifest hash) still matches, so the per-chunk CRC
    # verification at register() is what must catch it
    _corrupt(src.entry('alpha', 'v1').path)
    art = ArtifactServer(src).start()
    try:
        dst = ModelRegistry(root=str(tmp_path / 'dst'))
        client = ArtifactClient(art.endpoint, str(tmp_path / 'cache'))
        with pytest.raises(ArtifactVerifyError):
            client.ensure(dst, 'alpha', 'v1')
        assert ('alpha', 'v1') not in dst    # reject, not register
    finally:
        art.stop()


def test_corrupted_manifest_typed_reject(tmp_path):
    src = _publish_zoo(tmp_path / 'src')
    # corrupt the CRC manifest sidecar: the pulled fingerprint no
    # longer matches the cataloged one
    _corrupt(io_save.manifest_path(src.entry('alpha', 'v1').path))
    art = ArtifactServer(src).start()
    try:
        dst = ModelRegistry(root=str(tmp_path / 'dst'))
        client = ArtifactClient(art.endpoint, str(tmp_path / 'cache'))
        with pytest.raises(ArtifactVerifyError):
            client.ensure(dst, 'alpha', 'v1')
        assert ('alpha', 'v1') not in dst
    finally:
        art.stop()


def test_artifact_fetch_refuses_path_traversal(tmp_path):
    src = _publish_zoo(tmp_path / 'src')
    art = ArtifactServer(src).start()
    try:
        s = socket.create_connection(
            ('127.0.0.1', art.port), timeout=5)
        send_frame(s, {'op': 'fetch', 'model': 'alpha', 'version': 'v1',
                       'file': '../../etc/passwd', 'offset': 0})
        out = recv_frame(s)
        assert 'error' in out
        s.close()
    finally:
        art.stop()


# ---- prefix directory + affinity routing ------------------------------


def test_prefix_directory_depths_and_lru():
    d = PrefixDirectory(page_size=4, capacity=8)
    shared = list(range(16))
    d.observe(shared + [100], replica_index=1)
    # 16 shared tokens + tail -> 4 full blocks on replica 1
    assert d.depths(shared + [200]) == {1: 4}
    # a different prefix diverges at block 0: no hint
    assert d.depths([9, 9, 9, 9, 9]) == {}
    # shorter than a page (plus the never-covered last token): nothing
    assert d.depths([1, 2, 3, 4]) == {}
    # latest writer wins
    d.observe(shared + [101], replica_index=0)
    assert d.depths(shared + [200]) == {0: 4}
    # LRU capacity: flooding with unrelated chains evicts the oldest
    for i in range(8):
        d.observe([50 + i] * 5, replica_index=1)
    assert len(d) <= 8


def test_prefix_affinity_router_orders_by_depth_then_load():
    class _Rep:
        def __init__(self, index, load):
            self.index = index
            self._load = load

        def routable(self):
            return True

        def load(self):
            return self._load

    class _Gw:
        def __init__(self, prompt):
            self.prompt = prompt

    pool = [_Rep(0, 0.0), _Rep(1, 5.0), _Rep(2, 1.0)]
    r = PrefixAffinityRouter(page_size=4)
    shared = list(range(12))
    # cold directory: pure least-loaded order
    assert [x.index for x in
            r.candidates_for_request(pool, _Gw(shared + [7]))] == [0, 2, 1]
    # replica 1 served this prefix: it ranks first DESPITE max load
    r.note_placement(shared + [7], 1)
    assert [x.index for x in
            r.candidates_for_request(pool, _Gw(shared + [8]))] == [1, 0, 2]
    # unrelated prompt still routes by load
    assert [x.index for x in
            r.candidates_for_request(pool, _Gw([99] * 13))] == [0, 2, 1]


def test_prefix_affinity_gateway_keeps_shared_prefix_together(
        worker_pair):
    gw = _fabric_gateway(worker_pair,
                         router=PrefixAffinityRouter(page_size=4))
    shared = [7] * 16
    first = gw.submit(shared + [1], max_new_tokens=2)
    gw.run()
    warm = first.replica_history[0]
    rest = [gw.submit(shared + [2 + i], max_new_tokens=2)
            for i in range(5)]
    gw.run()
    gw.shutdown()
    assert all(r.done for r in [first] + rest)
    # every shared-prefix request landed on the warm replica
    assert all(r.replica_history == [warm] for r in rest)
    assert _counter(gw, 'gateway_route_total', (str(warm),)) == 6.0


# ---- predictor-zoo presets -------------------------------------------


def test_presets_build_deterministic_models(tmp_path):
    import numpy as np
    from paddle_tpu.serving.fabric import (PRESETS, build_engine, preset,
                                           publish_preset)
    from paddle_tpu.serving.fabric.presets import build_model, host_factory
    assert set(PRESETS) >= {'gpt-nano', 'gpt-nano-paged', 'gpt-micro'}
    with pytest.raises(KeyError):
        preset('gpt-colossal')
    # the preset seed pins the weights: two builds agree exactly
    sd1 = {k: np.asarray(v)
           for k, v in build_model('gpt-nano').state_dict().items()}
    sd2 = {k: np.asarray(v)
           for k, v in build_model('gpt-nano').state_dict().items()}
    assert sd1.keys() == sd2.keys()
    assert all(np.array_equal(sd1[k], sd2[k]) for k in sd1)
    # engine round trip + publish/host_factory serve the same weights
    eng = build_engine('gpt-nano')
    ref = eng.generate([[5, 6, 7]], max_new_tokens=4)
    eng.shutdown()
    reg = ModelRegistry(root=str(tmp_path))
    entry = publish_preset(reg, 'gpt-nano')
    assert entry.meta['preset'] == 'gpt-nano'
    eng2 = host_factory()(reg.entry('gpt-nano', 'v0'))
    assert eng2.generate([[5, 6, 7]], max_new_tokens=4) == ref
    eng2.shutdown()


# ---- the real process boundary (slow) ---------------------------------


@pytest.mark.slow
def test_fabric_chaos_sigkill_worker_midburst_token_parity():
    """THE acceptance test: two real worker processes behind the
    gateway, a Poisson burst, SIGKILL one worker mid-burst. Every
    request completes, the delivered tokens are EXACTLY the
    single-engine reference, and each request's single wide event
    carries its cross-process replica history."""
    import numpy as np
    from paddle_tpu.serving.fabric import build_engine, spawn_worker
    rng = np.random.RandomState(11)
    prompts = [[int(t) for t in rng.randint(0, 211, n)]
               for n in (3, 9, 5, 12, 4, 7, 6, 10, 8, 5)]
    ref_eng = build_engine('gpt-nano')
    reference = ref_eng.generate(prompts, max_new_tokens=8)
    ref_eng.shutdown()

    handles = [spawn_worker(preset='gpt-nano') for _ in range(2)]
    log = _events.RequestLog()
    prev = _events.set_default_request_log(log)
    meta = MetricRegistry()
    fc = FleetCollector(registry=meta, clock=time.monotonic)
    try:
        gw = ServingGateway(None, replicas=0, registry=MetricRegistry())
        for h in handles:
            gw.adopt_replica(
                SocketReplica(h.endpoint, metrics_url=h.metrics_url,
                              poll_interval=0.002).connect())
        gw.attach_fleet(fc)
        gw.start()
        reqs = []
        for i, p in enumerate(prompts):
            reqs.append(gw.submit(p, max_new_tokens=8))
            if i == len(prompts) // 2:
                handles[0].kill()            # SIGKILL, no goodbye
            time.sleep(float(rng.exponential(0.05)))
        for r in reqs:
            assert r.wait(timeout=300), \
                'request %d never completed' % r.id
        gw.shutdown()
    finally:
        _events.set_default_request_log(prev)
        for h in handles:
            h.cleanup()

    # completed_ratio == 1.0, exact token parity with one engine
    assert all(r.done for r in reqs)
    assert [r.tokens for r in reqs] == reference
    evs = log.events()
    assert len(evs) == len(prompts)          # exactly one per request
    assert all(e['outcome'] == 'ok' for e in evs)
    crossed = [e for e in evs if len(e['replicas']) > 1]
    victims = [r for r in reqs if len(r.replica_history) > 1]
    assert len(crossed) == len(victims)
    assert all(set(e['replicas']) == {0, 1} for e in crossed)
    # stale-not-wrong federation after the SIGKILL
    fc.scrape()
    st = fc.fleet_status()
    assert st['targets']['gw-replica-0']['up'] is False


@pytest.mark.slow
def test_spawn_worker_pulls_artifacts_by_fingerprint(tmp_path):
    """Worker bring-up from nothing but (model, version, fingerprint):
    the spawned process pulls the preset checkpoint from the
    ArtifactServer, CRC-verifies it, and serves the same tokens as a
    locally built engine."""
    from paddle_tpu.serving.fabric import (build_engine, publish_preset,
                                           spawn_worker)
    reg = ModelRegistry(root=str(tmp_path / 'src'))
    entry = publish_preset(reg, 'gpt-nano')
    art = ArtifactServer(reg).start()
    h = None
    try:
        h = spawn_worker(artifacts=art.endpoint,
                         cache=str(tmp_path / 'wcache'),
                         model='gpt-nano', version='v0',
                         fingerprint=entry.fingerprint)
        gw = ServingGateway(None, replicas=0, registry=MetricRegistry())
        gw.adopt_replica(SocketReplica(h.endpoint,
                                       metrics_url=h.metrics_url).connect())
        out = gw.generate([[5, 6, 7]], max_new_tokens=4)
        gw.shutdown()
        eng = build_engine('gpt-nano')
        assert out == eng.generate([[5, 6, 7]], max_new_tokens=4)
        eng.shutdown()
    finally:
        if h is not None:
            h.cleanup()
        art.stop()
