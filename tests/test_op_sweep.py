"""Broad OpTest sweep (VERDICT r2 weak #8: reference has ~1,122 op-test
files over the op_test.py harness; this drives a wide op table through
check_output and — for differentiable ops — analytic-vs-numeric
check_grad, the same contract at sweep scale).

Each entry: (name, paddle fn over Tensors, numpy reference, input specs,
attrs, grad). Input spec: shape tuple or ('int', shape, hi).
"""
import numpy as np
import pytest
from scipy.special import gammaln

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import tensor as T

from op_test import OpTest


def _mk(spec, rng):
    if isinstance(spec, tuple) and spec and spec[0] == 'int':
        _, shape, hi = spec
        return rng.randint(0, hi, shape).astype(np.int32)
    if isinstance(spec, tuple) and spec and spec[0] == 'pos':
        return (rng.rand(*spec[1]).astype(np.float32) + 0.1)
    if isinstance(spec, tuple) and spec and spec[0] == 'unit':
        return (rng.rand(*spec[1]).astype(np.float32) * 1.6 - 0.8)
    return rng.randn(*spec).astype(np.float32)


def _softplus_np(x):
    return np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0)


SWEEP = [
    # name, fn, ref, input specs, attrs, check_grad?
    ('abs', paddle.abs, np.abs, [(3, 4)], {}, False),
    ('exp', paddle.exp, np.exp, [(3, 4)], {}, True),
    ('log', paddle.log, np.log, [('pos', (3, 4))], {}, True),
    ('log2', paddle.log2, np.log2, [('pos', (3, 4))], {}, True),
    ('log1p', paddle.log1p, np.log1p, [('pos', (3, 4))], {}, True),
    ('sqrt', paddle.sqrt, np.sqrt, [('pos', (3, 4))], {}, True),
    ('rsqrt', paddle.rsqrt, lambda x: 1 / np.sqrt(x),
     [('pos', (3, 4))], {}, True),
    ('sin', paddle.sin, np.sin, [(3, 4)], {}, True),
    ('cos', paddle.cos, np.cos, [(3, 4)], {}, True),
    ('tan', paddle.tan, np.tan, [(2, 3)], {}, True),
    ('asin', paddle.asin, np.arcsin,
     [('unit', (2, 3))], {}, False),
    ('atan', paddle.atan, np.arctan, [(3, 4)], {}, True),
    ('sinh', paddle.sinh, np.sinh, [(3, 4)], {}, True),
    ('cosh', paddle.cosh, np.cosh, [(3, 4)], {}, True),
    ('tanh', paddle.tanh, np.tanh, [(3, 4)], {}, True),
    ('erf', paddle.erf, None, [(3, 4)], {}, True),
    ('floor', paddle.floor, np.floor, [(3, 4)], {}, False),
    ('ceil', paddle.ceil, np.ceil, [(3, 4)], {}, False),
    ('round', paddle.round, np.round, [(3, 4)], {}, False),
    ('sign', paddle.sign, np.sign, [(3, 4)], {}, False),
    ('square', paddle.square, np.square, [(3, 4)], {}, True),
    ('reciprocal', paddle.reciprocal, lambda x: 1 / x,
     [('pos', (3, 4))], {}, True),
    ('sigmoid', F.sigmoid, lambda x: 1 / (1 + np.exp(-x)),
     [(3, 4)], {}, True),
    ('softplus', F.softplus, _softplus_np, [(3, 4)], {}, True),
    ('relu', F.relu, lambda x: np.maximum(x, 0), [(3, 4)], {}, False),
    ('gelu_exact', F.gelu, None, [(3, 4)], {}, True),
    ('hardswish', F.hardswish,
     lambda x: x * np.clip(x + 3, 0, 6) / 6, [(3, 4)], {}, False),
    ('elu', F.elu,
     lambda x: np.where(x > 0, x, np.exp(x) - 1), [(3, 4)], {}, False),
    ('add', paddle.add, np.add, [(3, 4), (3, 4)], {}, True),
    ('subtract', paddle.subtract, np.subtract, [(3, 4), (3, 4)], {}, True),
    ('multiply', paddle.multiply, np.multiply, [(3, 4), (3, 4)], {}, True),
    ('divide', paddle.divide, np.divide,
     [(3, 4), ('pos', (3, 4))], {}, True),
    ('maximum', paddle.maximum, np.maximum, [(3, 4), (3, 4)], {}, False),
    ('minimum', paddle.minimum, np.minimum, [(3, 4), (3, 4)], {}, False),
    ('fmax', paddle.fmax, np.fmax, [(3, 4), (3, 4)], {}, False),
    ('pow', paddle.pow, lambda x, y: x ** y,
     [('pos', (3, 4)), ('pos', (3, 4))], {}, True),
    ('floor_divide', lambda x, y: paddle.floor_divide(x, paddle.add(
        y, paddle.to_tensor(np.ones((3, 4), np.int32)))),
     lambda x, y: np.floor_divide(x, y + 1),
     [('int', (3, 4), 20), ('int', (3, 4), 5)], {}, False),
    ('mod', paddle.mod, np.mod,
     [('pos', (3, 4)), ('pos', (3, 4))], {}, False),
    ('matmul', T.matmul, np.matmul, [(3, 5), (5, 4)], {}, True),
    ('bmm', T.bmm, np.matmul, [(2, 3, 5), (2, 5, 4)], {}, True),
    ('dot', T.dot, lambda x, y: np.sum(x * y, -1), [(6,), (6,)], {}, True),
    ('trace', T.trace,
     lambda x: np.trace(x), [(4, 4)], {}, True),
    ('cumsum', T.cumsum, lambda x, axis=None: np.cumsum(x, axis),
     [(3, 4)], {'axis': 1}, True),
    ('cumprod', T.cumprod, lambda x, dim=None: np.cumprod(x, dim),
     [('pos', (3, 4))], {'dim': 1}, True),
    ('logsumexp', T.logsumexp,
     lambda x, axis=None: np.log(np.sum(np.exp(x), axis)),
     [(3, 4)], {'axis': 1}, True),
    ('lerp', T.lerp,
     lambda x, y, w: x + w * (y - x), [(3, 4), (3, 4), (3, 4)], {}, True),
    ('clip', T.clip, lambda x, min=None, max=None: np.clip(x, min, max),
     [(3, 4)], {'min': -0.5, 'max': 0.5}, False),
    ('kron', paddle.kron, np.kron, [(2, 3), (3, 2)], {}, True),
    ('outer', paddle.outer, np.outer, [(4,), (5,)], {}, True),
    ('inner', paddle.inner, np.inner, [(3, 4), (5, 4)], {}, True),
    ('norm_fro', lambda x: T.norm(x, 'fro'),
     lambda x: np.linalg.norm(x), [(3, 4)], {}, True),
    ('dist_2', T.dist,
     lambda x, y, p=2: np.linalg.norm((x - y).ravel(), ord=p),
     [(3, 4), (3, 4)], {}, True),
    ('det', T.det, np.linalg.det, [(3, 3)], {}, False),
    ('inv', T.inv, np.linalg.inv, [(3, 3)], {}, False),
    ('cross', lambda x, y: T.cross(x, y, axis=-1),
     lambda x, y: np.cross(x, y), [(4, 3), (4, 3)], {}, False),
    ('stanh', T.stanh,
     lambda x, scale_a=0.67, scale_b=1.7159:
     scale_b * np.tanh(scale_a * x), [(3, 4)], {}, True),
    ('diagonal', T.diagonal,
     lambda x: np.diagonal(x, 0, 0, 1), [(4, 4)], {}, False),
    ('flip', lambda x: paddle.flip(x, axis=[0]),
     lambda x: np.flip(x, 0), [(3, 4)], {}, False),
    ('roll', lambda x: paddle.roll(x, 2, axis=1),
     lambda x: np.roll(x, 2, 1), [(3, 4)], {}, False),
    ('tril', paddle.tril, np.tril, [(4, 4)], {}, False),
    ('triu', paddle.triu, np.triu, [(4, 4)], {}, False),
    ('softmax', lambda x: F.softmax(x, axis=-1),
     lambda x: np.exp(x - x.max(-1, keepdims=True)) /
     np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True),
     [(3, 5)], {}, True),
    ('log_softmax', lambda x: F.log_softmax(x, axis=-1),
     None, [(3, 5)], {}, True),
    ('mean_axis', lambda x: paddle.mean(x, axis=1),
     lambda x: np.mean(x, 1), [(3, 4)], {}, True),
    ('sum_axis', lambda x: paddle.sum(x, axis=0),
     lambda x: np.sum(x, 0), [(3, 4)], {}, True),
    ('prod', lambda x: paddle.prod(x, axis=1),
     lambda x: np.prod(x, 1), [('pos', (3, 4))], {}, True),
    ('amax', lambda x: paddle.amax(x, axis=1),
     lambda x: np.max(x, 1), [(3, 4)], {}, False),
    ('amin', lambda x: paddle.amin(x, axis=1),
     lambda x: np.min(x, 1), [(3, 4)], {}, False),
]


def _run_sweep_case(case):
    name, fn, ref, specs, attrs, grad = case
    import zlib
    rng = np.random.RandomState(zlib.crc32(name.encode()) % (2 ** 31))

    class _T(OpTest):
        pass

    _T.fn = staticmethod(fn)
    _T.inputs = {'x%d' % i: _mk(s, rng) for i, s in enumerate(specs)}
    _T.attrs = attrs
    if ref is None:
        # no independent numpy formula: check self-consistency under jit
        # + grads only
        import jax
        t = _T()
        tensors, out = t._run(stop_gradient=False)
        assert np.all(np.isfinite(out.numpy()))
    else:
        _T.ref = staticmethod(ref)
        t = _T()
        t.check_output()
    if grad:
        float_names = [k for k, v in t.inputs.items()
                       if np.issubdtype(np.asarray(v).dtype, np.floating)]
        if float_names:
            t.check_grad(float_names)


@pytest.mark.parametrize('case', SWEEP, ids=[c[0] for c in SWEEP])
def test_op_sweep(case):
    _run_sweep_case(case)


def test_metric_auc_matches_rank_formula():
    """auc op vs the Mann-Whitney rank AUC (operators/metrics/auc_op.cc)."""
    from paddle_tpu import metric
    rng = np.random.RandomState(5)
    n = 1000
    scores = rng.rand(n).astype(np.float32)
    labels = (scores + 0.4 * rng.randn(n) > 0.5).astype(np.float32)
    a = float(metric.auc(paddle.to_tensor(scores[:, None]),
                         paddle.to_tensor(labels[:, None])).numpy())
    order = np.argsort(scores)
    ranks = np.empty(n)
    ranks[order] = np.arange(1, n + 1)
    n1 = labels.sum()
    n0 = n - n1
    ref = (ranks[labels == 1].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)
    assert abs(a - ref) < 0.01


def test_static_accuracy_and_auc():
    from paddle_tpu import static
    rng = np.random.RandomState(6)
    logits = rng.randn(32, 5).astype(np.float32)
    labels = rng.randint(0, 5, (32, 1)).astype(np.int64)
    acc = static.accuracy(paddle.to_tensor(logits),
                          paddle.to_tensor(labels), k=1)
    ref = (np.argmax(logits, -1) == labels[:, 0]).mean()
    np.testing.assert_allclose(float(acc.numpy()), ref, atol=1e-6)
    out = static.auc(paddle.to_tensor(rng.rand(32, 1).astype(np.float32)),
                     paddle.to_tensor((rng.rand(32, 1) > 0.5)
                                      .astype(np.float32)))
    assert 0.0 <= float(out[0].numpy()) <= 1.0


def _seg_ids():
    return np.array([0, 0, 1, 1, 1, 3], np.int32)


SWEEP2 = [
    ('atan2', paddle.atan2, np.arctan2, [(3, 4), (3, 4)], {}, True),
    ('trunc', paddle.trunc, np.trunc, [(3, 4)], {}, False),
    ('expm1', paddle.expm1, np.expm1, [(3, 4)], {}, True),
    ('lgamma', paddle.lgamma, gammaln, [('pos', (3, 4))], {}, True),
    ('nanmean', paddle.nanmean, np.nanmean, [(3, 4)], {}, False),
    ('nansum', paddle.nansum, np.nansum, [(3, 4)], {}, False),
    ('diff', paddle.diff, lambda x: np.diff(x), [(3, 6)], {}, True),
    ('heaviside', paddle.heaviside, np.heaviside,
     [(3, 4), (3, 4)], {}, False),
    ('median', paddle.median, np.median, [(3, 5)], {}, False),
    ('frac', paddle.frac, lambda x: x - np.trunc(x), [(3, 4)], {}, True),
    ('deg2rad', paddle.deg2rad, np.deg2rad, [(3, 4)], {}, True),
    ('rad2deg', paddle.rad2deg, np.rad2deg, [(3, 4)], {}, True),
    ('rot90', paddle.rot90, lambda x: np.rot90(x), [(3, 4)], {}, True),
    # round-3 tranche ops through the same harness
    ('rank_loss',
     lambda t, l, r: paddle.static.nn.rank_loss(t, l, r),
     lambda t, l, r: np.log1p(np.exp(-np.abs(l - r)))
     + np.maximum(l - r, 0) - t * (l - r),
     [('int', (6, 1), 2), (6, 1), (6, 1)], {}, False),
    ('cvm_strip',
     lambda x, c: paddle.static.nn.cvm(x, c, use_cvm=False),
     lambda x, c: x[:, 2:], [('pos', (4, 6)), ('pos', (4, 2))], {}, True),
    ('temporal_shift',
     lambda x: F.temporal_shift(x, seg_num=2, shift_ratio=0.25),
     None, [(4, 8, 2, 2)], {}, True),
    ('segment_sum',
     lambda d: paddle.incubate.segment_sum(
         d, paddle.to_tensor(_seg_ids())),
     lambda d: np.stack([d[_seg_ids() == i].sum(0) if (_seg_ids() == i).any()
                         else np.zeros(d.shape[1:], d.dtype)
                         for i in range(4)]),
     [(6, 3)], {}, True),
    ('segment_max',
     lambda d: paddle.incubate.segment_max(
         d, paddle.to_tensor(_seg_ids())),
     lambda d: np.stack([d[_seg_ids() == i].max(0) if (_seg_ids() == i).any()
                         else np.zeros(d.shape[1:], d.dtype)
                         for i in range(4)]),
     [(6, 3)], {}, True),
    ('max_unpool2d_grad',
     lambda x: F.max_unpool2d(*F.max_pool2d(x, 2, 2, return_mask=True),
                              kernel_size=2, stride=2),
     None, [(2, 2, 4, 4)], {}, True),
]


@pytest.mark.parametrize('case', SWEEP2, ids=[c[0] for c in SWEEP2])
def test_op_sweep2(case):
    _run_sweep_case(case)
