"""Text dataset loader tests: build miniature archives in the reference's
standard on-disk layouts, then drive the real parsers (zero-egress analog
of the reference's download-then-parse tests)."""
import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest


def _add_text(tf, name, text):
    data = text.encode()
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


@pytest.fixture(scope='module')
def imdb_tgz(tmp_path_factory):
    d = tmp_path_factory.mktemp('imdb')
    path = d / 'aclImdb_v1.tar.gz'
    reviews = {
        'train/pos/0_9.txt': 'a great great movie truly great',
        'train/pos/1_8.txt': 'great fun and a great cast',
        'train/neg/0_2.txt': 'a terrible terrible movie truly terrible',
        'train/neg/1_1.txt': 'terrible plot and terrible acting',
        'test/pos/0_10.txt': 'great stuff',
        'test/neg/0_1.txt': 'terrible stuff',
    }
    with tarfile.open(path, 'w:gz') as tf:
        for name, text in reviews.items():
            _add_text(tf, 'aclImdb/' + name, text)
    return str(path)


def test_imdb_parsing_and_word_dict(imdb_tgz):
    from paddle_tpu.text.datasets import Imdb
    ds = Imdb(data_file=imdb_tgz, mode='train', cutoff=2)
    # words with freq > 2 in train: 'great'(5), 'terrible'(5), 'a'(3)
    assert set(ds.word_idx) == {'great', 'terrible', 'a', '<unk>'}
    # ids ordered by (-freq, word): great/terrible (5) before a (3)
    assert ds.word_idx['a'] == 2
    assert len(ds) == 4
    # first samples are pos (label 0), then neg (label 1)
    labels = [int(ds[i][1]) for i in range(4)]
    assert labels == [0, 0, 1, 1]
    ids, label = ds[0]
    assert ids.dtype == np.int64
    test = Imdb(data_file=imdb_tgz, mode='test', cutoff=2)
    assert len(test) == 2


def test_uci_housing_and_legacy_reader(tmp_path):
    rng = np.random.RandomState(0)
    raw = np.hstack([rng.standard_normal((50, 13)),
                     rng.uniform(10, 50, (50, 1))])
    f = tmp_path / 'housing.data'
    np.savetxt(f, raw)
    from paddle_tpu.text.datasets import UCIHousing
    tr = UCIHousing(data_file=str(f), mode='train')
    te = UCIHousing(data_file=str(f), mode='test')
    assert len(tr) == 40 and len(te) == 10
    x, y = tr[0]
    assert x.shape == (13,) and y.shape == (1,)

    from paddle_tpu import dataset as legacy
    reader = legacy.uci_housing.train(data_file=str(f))
    rows = list(reader())
    assert len(rows) == 40 and rows[0][0].shape == (13,)


@pytest.fixture(scope='module')
def ml_zip(tmp_path_factory):
    d = tmp_path_factory.mktemp('ml')
    path = d / 'ml-1m.zip'
    movies = ("1::Toy Story (1995)::Animation|Comedy\n"
              "2::Jumanji (1995)::Adventure\n")
    users = ("1::M::25::12::55117\n"
             "2::F::35::7::55105\n")
    ratings = ("1::1::5::978300760\n"
               "1::2::3::978302109\n"
               "2::1::4::978301968\n"
               "2::2::2::978300275\n")
    with zipfile.ZipFile(path, 'w') as zf:
        zf.writestr('ml-1m/movies.dat', movies)
        zf.writestr('ml-1m/users.dat', users)
        zf.writestr('ml-1m/ratings.dat', ratings)
    return str(path)


def test_movielens(ml_zip):
    from paddle_tpu.text.datasets import Movielens
    tr = Movielens(data_file=ml_zip, mode='train', test_ratio=0.25,
                   rand_seed=1)
    te = Movielens(data_file=ml_zip, mode='test', test_ratio=0.25,
                   rand_seed=1)
    assert len(tr) + len(te) == 4
    row = (tr if len(tr) else te)[0]
    # [uid, gender, age, job, mid, [categories], [title ids], rating]
    assert isinstance(row[5], list) and isinstance(row[6], list)
    assert isinstance(row[-1], float)


@pytest.fixture(scope='module')
def wmt14_tgz(tmp_path_factory):
    d = tmp_path_factory.mktemp('wmt14')
    path = d / 'wmt14.tgz'
    with tarfile.open(path, 'w:gz') as tf:
        _add_text(tf, 'wmt14/train/part0.src',
                  'hello world\ngood morning\n')
        _add_text(tf, 'wmt14/train/part0.trg',
                  'bonjour monde\nbon matin\n')
        _add_text(tf, 'wmt14/test/part0.src', 'hello\n')
        _add_text(tf, 'wmt14/test/part0.trg', 'bonjour\n')
        _add_text(tf, 'wmt14/train.dict.src',
                  'hello\nworld\ngood\nmorning\n')
        _add_text(tf, 'wmt14/train.dict.trg',
                  'bonjour\nmonde\nbon\nmatin\n')
    return str(path)


def test_wmt14(wmt14_tgz):
    from paddle_tpu.text.datasets import WMT14
    ds = WMT14(data_file=wmt14_tgz, mode='train')
    assert len(ds) == 2
    src, trg, trg_next = ds[0]
    assert ds.src_dict['<s>'] == 0 and ds.src_dict['<e>'] == 1
    # trg starts with <s>, trg_next ends with <e>
    assert trg[0] == ds.trg_dict['<s>']
    assert trg_next[-1] == ds.trg_dict['<e>']
    assert len(trg) == len(trg_next)
    test = WMT14(data_file=wmt14_tgz, mode='test')
    assert len(test) == 1


@pytest.fixture(scope='module')
def wmt16_tgz(tmp_path_factory):
    d = tmp_path_factory.mktemp('wmt16')
    path = d / 'wmt16.tar.gz'
    with tarfile.open(path, 'w:gz') as tf:
        _add_text(tf, 'wmt16/train',
                  'a red house\tein rotes haus\n'
                  'the cat\tdie katze\n')
        _add_text(tf, 'wmt16/test', 'a house\tein haus\n')
        _add_text(tf, 'wmt16/vocab_en.txt', 'a\nred\nhouse\nthe\ncat\n')
        _add_text(tf, 'wmt16/vocab_de.txt',
                  'ein\nrotes\nhaus\ndie\nkatze\n')
    return str(path)


def test_wmt16(wmt16_tgz):
    from paddle_tpu.text.datasets import WMT16
    ds = WMT16(data_file=wmt16_tgz, mode='train', lang='en')
    assert len(ds) == 2
    src, trg, trg_next = ds[1]
    assert [int(i) for i in src] == [ds.src_dict['the'],
                                     ds.src_dict['cat']]
    assert int(trg[0]) == ds.trg_dict['<s>']
    assert int(trg_next[-1]) == ds.trg_dict['<e>']


@pytest.fixture(scope='module')
def conll_tgz(tmp_path_factory):
    d = tmp_path_factory.mktemp('conll')
    path = d / 'conll05st-tests.tar.gz'
    words = 'The\ncat\nsat\n\n'
    props = '-\t*\n-\t*\nsat\t(V*)\n\n'
    with tarfile.open(path, 'w:gz') as tf:
        _add_text(tf, 'conll05st-release/test.wsj/words/test.wsj.words.gz',
                  '')
        _add_text(tf, 'conll05st-release/test.wsj/props/test.wsj.props.gz',
                  '')
    # rewrite with real gzipped members
    with tarfile.open(path, 'w:gz') as tf:
        for name, txt in (
                ('conll05st-release/test.wsj/words/test.wsj.words.gz',
                 words),
                ('conll05st-release/test.wsj/props/test.wsj.props.gz',
                 props)):
            data = gzip.compress(txt.encode())
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return str(path)


def test_conll05(conll_tgz, tmp_path):
    from paddle_tpu.text.datasets import Conll05st
    wd = tmp_path / 'words.dict'
    wd.write_text('the\ncat\nsat\n<unk>\n')
    vd = tmp_path / 'verbs.dict'
    vd.write_text('sat\n')
    ld = tmp_path / 'labels.dict'
    ld.write_text('O\nB-V\nI-V\n')
    ds = Conll05st(data_file=conll_tgz, word_dict_file=str(wd),
                   verb_dict_file=str(vd), target_dict_file=str(ld))
    assert len(ds) == 1
    sample = ds[0]
    word_ids = sample[0]
    labels = sample[-1]
    mark = sample[-2]
    assert list(word_ids) == [0, 1, 2]
    assert list(mark) == [0, 0, 1]       # predicate position
    assert list(labels) == [0, 0, 1]     # O O B-V


def test_missing_archive_raises():
    from paddle_tpu.text.datasets import Imdb
    with pytest.raises(FileNotFoundError):
        Imdb(data_file='/nonexistent/imdb.tgz')


def test_wmt16_lang_de_swaps_columns(wmt16_tgz):
    from paddle_tpu.text.datasets import WMT16
    ds = WMT16(data_file=wmt16_tgz, mode='train', lang='de')
    # source must now be the GERMAN column against the German vocab
    src, trg, trg_next = ds[1]
    assert [int(i) for i in src] == [ds.src_dict['die'],
                                     ds.src_dict['katze']]
    assert [int(i) for i in trg[1:]] == [ds.trg_dict['the'],
                                         ds.trg_dict['cat']]


def test_conll05_lemma_predicate(tmp_path):
    # props column 0 holds the LEMMA ('sit'), surface word is 'sat':
    # the predicate position must come from the B-V label column
    import tarfile as tl
    path = tmp_path / 'conll05st-tests.tar.gz'
    words = 'The\ncat\nsat\n\n'
    props = '-\t*\n-\t*\nsit\t(V*)\n\n'
    with tl.open(path, 'w:gz') as tf:
        for name, txt in (
                ('conll05st-release/test.wsj/words/test.wsj.words.gz',
                 words),
                ('conll05st-release/test.wsj/props/test.wsj.props.gz',
                 props)):
            data = gzip.compress(txt.encode())
            info = tl.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    from paddle_tpu.text.datasets import Conll05st
    ds = Conll05st(data_file=str(path))
    sample = ds[0]
    mark = sample[-2]
    assert list(mark) == [0, 0, 1]  # position of B-V, not of the lemma
    # no dict files: auto ids must be deterministic (first-seen order)
    word_ids = sample[0]
    assert list(word_ids) == [0, 1, 2]


def test_legacy_imdb_honors_word_idx(imdb_tgz):
    from paddle_tpu import dataset as legacy
    custom = {'great': 7, 'terrible': 9, '<unk>': 0}
    reader = legacy.imdb.train(custom, data_file=imdb_tgz)
    rows = list(reader())
    ids = np.concatenate([r[0] for r in rows])
    assert set(np.unique(ids)) <= {0, 7, 9}
