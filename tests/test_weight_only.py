"""Weight-only int8 serving-path quantization (slim.weight_only).

Reference counterpart: the inference engine's post-training int8 paths
(trt_int8_calibrator.cc, api/mkldnn_quantizer.cc) — quantize a TRAINED
model for serving. Tested like the slim QDQ suite: numerics stay close,
the swap respects structure (sharing, exclusion), and the decode path
runs end-to-end through the quantized model.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.slim import WeightOnlyLinear, quantize_weight_only


def test_weight_only_linear_numerics():
    paddle.seed(3)
    lin = nn.Linear(64, 48)
    q = WeightOnlyLinear(lin)
    q.eval()
    x = paddle.to_tensor(
        np.random.RandomState(0).standard_normal((16, 64)).astype(np.float32))
    ref = lin(x).numpy()
    got = q(x).numpy()
    # per-channel symmetric int8 weight error is ~0.4% RMS of the weight
    # scale; the matmul carries it through proportionally (individual
    # outputs near zero can have large RELATIVE error — normalize by the
    # output RMS, not per element)
    nrmse = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert nrmse < 0.02
    # and the quantization is real: int8 storage, not fake-quant
    assert str(q.qweight.dtype).endswith('int8')
    assert q.qweight.shape == [64, 48]
    assert q.weight_scale.shape == [48]


def test_weight_only_linear_refuses_training():
    lin = nn.Linear(8, 8)
    q = WeightOnlyLinear(lin)
    q.train()
    x = paddle.to_tensor(np.zeros((2, 8), np.float32))
    with pytest.raises(RuntimeError):
        q(x)


def test_quantize_weight_only_swaps_and_excludes():
    paddle.seed(5)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                          nn.Sequential(nn.Linear(16, 16)), nn.Linear(16, 4))
    n = quantize_weight_only(
        model, exclude=lambda name, layer: layer._out_features == 4)
    assert n == 2
    assert isinstance(model[0], WeightOnlyLinear)
    assert isinstance(model[2][0], WeightOnlyLinear)
    assert type(model[3]) is nn.Linear  # excluded head stays fp


def test_quantize_weight_only_preserves_sharing():
    class TwoPath(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(8, 8)
            self.b = self.a

        def forward(self, x):
            return self.a(x) + self.b(x)

    model = TwoPath()
    n = quantize_weight_only(model)
    assert n == 1
    assert model.a is model.b
    assert isinstance(model.a, WeightOnlyLinear)


def test_exclude_one_alias_keeps_shared_layer_fp():
    """Excluding ANY alias of a shared Linear keeps every alias in full
    precision — a partial swap would silently break the sharing."""
    class TwoPath(nn.Layer):
        def __init__(self):
            super().__init__()
            self.proj = nn.Linear(8, 8)
            self.head = self.proj

        def forward(self, x):
            return self.proj(x) + self.head(x)

    model = TwoPath()
    n = quantize_weight_only(
        model, exclude=lambda name, layer: name.endswith('head'))
    assert n == 0
    assert model.proj is model.head
    assert type(model.proj) is nn.Linear


def test_bare_root_linear_raises():
    """A root-level nn.Linear cannot be swapped in place (the caller's
    reference IS the layer) — the old behavior silently returned 0."""
    lin = nn.Linear(8, 8)
    with pytest.raises(ValueError, match='WeightOnlyLinear'):
        quantize_weight_only(lin)
    assert type(lin) is nn.Linear  # untouched by the failed call


def test_bare_root_linear_excluded_is_noop():
    lin = nn.Linear(8, 8)
    n = quantize_weight_only(lin, exclude=lambda name, layer: True)
    assert n == 0
    assert type(lin) is nn.Linear


def test_quantized_mlp_forward_close():
    paddle.seed(11)
    model = nn.Sequential(nn.Linear(32, 64), nn.GELU(), nn.Linear(64, 10))
    model.eval()
    x = paddle.to_tensor(
        np.random.RandomState(1).standard_normal((8, 32)).astype(np.float32))
    ref = model(x).numpy()
    quantize_weight_only(model)
    got = model(x).numpy()
    assert np.mean(np.abs(got - ref)) / (np.mean(np.abs(ref)) + 1e-9) < 0.03


def test_gpt_decode_through_weight_only():
    """generate() end-to-end on a quantized GPT: the int8 buffers must
    cross the functional_call/jit boundary (they are Layer buffers) and
    the scan decode must compile with them as carried constants."""
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 128, (2, 8)).astype(np.int32))
    ref_out = model.generate(prompt, max_new_tokens=12)

    n = quantize_weight_only(model)
    assert n == 2 * 4  # qkv_proj, out_proj, fc_in, fc_out per block
    out = model.generate(prompt, max_new_tokens=12)
    assert out.shape == ref_out.shape
    assert out.numpy().dtype == np.int32
    # greedy decode over a random tiny model can legitimately diverge
    # after a few tokens; the prompt echo + first steps should agree
    assert np.array_equal(out.numpy()[:, :9], ref_out.numpy()[:, :9])


def test_weight_only_state_dict_roundtrip(tmp_path):
    paddle.seed(4)
    model = nn.Sequential(nn.Linear(8, 8))
    quantize_weight_only(model)
    model.eval()
    x = paddle.to_tensor(np.ones((2, 8), np.float32))
    ref = model(x).numpy()
    path = str(tmp_path / 'wq.pdparams')
    paddle.save(model.state_dict(), path)

    paddle.seed(9)  # different init
    model2 = nn.Sequential(nn.Linear(8, 8))
    quantize_weight_only(model2)
    model2.set_state_dict(paddle.load(path))
    model2.eval()
    assert np.allclose(model2(x).numpy(), ref)
