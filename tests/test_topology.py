"""Topology: DCN-aware device ordering (docs/dcn_multislice.md; the
TPU-native analog of the reference's NVLink-vs-IB ring hierarchy,
nccl_helper.h:190)."""
import collections

import numpy as np

from paddle_tpu.distributed.topology import _AXES, _dcn_aware_order

Stub = collections.namedtuple('Stub', ['slice_index', 'process_index', 'id'])


def _stub_devices(n_slices=2, per_slice=4, shuffled_seed=7):
    devs = [Stub(s, s, s * per_slice + i)
            for s in range(n_slices) for i in range(per_slice)]
    rng = np.random.RandomState(shuffled_seed)
    order = rng.permutation(len(devs))
    return [devs[i] for i in order]


def test_dcn_aware_device_order():
    """2 slices x 4 chips, dp outermost over slices: after ordering +
    the topology reshape, every inner-axes block is slice-pure and only
    dp groups mix slices."""
    devs = _dcn_aware_order(_stub_devices())
    # sorted: slice-major
    assert [d.slice_index for d in devs] == [0] * 4 + [1] * 4
    # the topology reshape: dp=2 outermost, mp=4 innermost
    shape = {a: 1 for a in _AXES}
    shape['dp'], shape['mp'] = 2, 4
    arr = np.empty(len(devs), dtype=object)
    arr[:] = devs
    mesh = arr.reshape(tuple(shape[a] for a in _AXES))
    # every mp group (fixed dp index) lives inside ONE slice => ICI
    for dp in range(2):
        grp = mesh[dp].reshape(-1)
        assert len({d.slice_index for d in grp}) == 1, grp
    # every dp group (fixed mp index) spans both slices => DCN, amortized
    flat = mesh.reshape(2, 4)
    for mp in range(4):
        assert {d.slice_index for d in flat[:, mp]} == {0, 1}


def test_single_slice_order_is_stable():
    devs = [Stub(0, 0, i) for i in range(8)]
    assert _dcn_aware_order(devs) == devs
