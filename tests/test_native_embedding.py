"""Native C++ sparse-embedding table (native/embedding_table.cc) vs the
python EmbeddingTable contract (reference common_sparse_table.cc shard
semantics: on-demand init, optimizer on push, drop-push-to-missing,
delta path, save/load)."""
import threading

import numpy as np
import pytest

native_mod = pytest.importorskip('paddle_tpu.native.embedding_table')
NativeEmbeddingTable = native_mod.NativeEmbeddingTable


def test_pull_inits_and_sgd_push():
    t = NativeEmbeddingTable(4, init_scale=0.1, optimizer='sgd', lr=0.5)
    ids = np.asarray([7, 3, 7, 900000000000])
    rows = t.pull(ids)
    assert rows.shape == (4, 4)
    assert (np.abs(rows) <= 0.1).all()
    np.testing.assert_array_equal(rows[0], rows[2])  # same id, same row
    assert len(t) == 3

    g = np.ones((4, 4), np.float32)
    t.push(ids, g)
    # id 7 got TWO gradient applications (appears twice in the batch)
    after = t.pull(np.asarray([7, 3]))
    np.testing.assert_allclose(after[0], rows[0] - 0.5 * 2, rtol=1e-6)
    np.testing.assert_allclose(after[1], rows[1] - 0.5, rtol=1e-6)

    # push to an id never pulled is dropped, not created
    t.push(np.asarray([12345]), np.ones((1, 4), np.float32))
    assert len(t) == 3


def test_adagrad_matches_formula():
    t = NativeEmbeddingTable(3, initializer='zeros', optimizer='adagrad',
                             lr=0.1, eps=1e-6)
    ids = np.asarray([1])
    r0 = t.pull(ids)[0]
    np.testing.assert_array_equal(r0, 0)
    g1 = np.asarray([[1.0, 2.0, 4.0]], np.float32)
    t.push(ids, g1)
    acc = g1[0] ** 2
    want = -0.1 * g1[0] / (np.sqrt(acc) + 1e-6)
    np.testing.assert_allclose(t.pull(ids)[0], want, rtol=1e-5)
    g2 = np.asarray([[2.0, 2.0, 2.0]], np.float32)
    t.push(ids, g2)
    acc += g2[0] ** 2
    want = want - 0.1 * g2[0] / (np.sqrt(acc) + 1e-6)
    np.testing.assert_allclose(t.pull(ids)[0], want, rtol=1e-5)


def test_push_delta_and_save_load(tmp_path):
    t = NativeEmbeddingTable(2, initializer='zeros', optimizer='adagrad')
    ids = np.asarray([10, 20])
    t.pull(ids)
    t.push(ids, np.ones((2, 2), np.float32))
    t.push_delta(ids, np.asarray([[5.0, 5.0], [7.0, 7.0]], np.float32))
    before = t.pull(ids)
    t.save(str(tmp_path))

    t2 = NativeEmbeddingTable(2, initializer='zeros', optimizer='adagrad')
    t2.load(str(tmp_path))
    assert len(t2) == 2
    np.testing.assert_allclose(t2.pull(ids), before)
    # adagrad accumulator survived the round trip: another push moves
    # both tables identically
    g = np.full((2, 2), 3.0, np.float32)
    t.push(ids, g)
    t2.push(ids, g)
    np.testing.assert_allclose(t2.pull(ids), t.pull(ids), rtol=1e-6)


def test_deterministic_init_across_instances():
    a = NativeEmbeddingTable(8, seed=42)
    b = NativeEmbeddingTable(8, seed=42)
    ids = np.asarray([5, 17, 5000])
    # arrival order must not matter (splitmix64 per-id init)
    np.testing.assert_array_equal(a.pull(ids), b.pull(ids[::-1])[::-1])
    c = NativeEmbeddingTable(8, seed=43)
    assert not np.array_equal(a.pull(ids), c.pull(ids))


def test_threaded_pull_push_consistency():
    t = NativeEmbeddingTable(4, initializer='zeros', optimizer='sgd', lr=1.0)
    ids = np.arange(64)
    t.pull(ids)
    n_threads, per = 8, 50

    def worker():
        g = np.ones((len(ids), 4), np.float32)
        for _ in range(per):
            t.push(ids, g)
    ts = [threading.Thread(target=worker) for _ in range(n_threads)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    # every push is applied under the table mutex: total = -lr * n * per
    np.testing.assert_allclose(t.pull(ids),
                               -float(n_threads * per), rtol=1e-6)


def test_served_native_table_round_trip():
    """NativeEmbeddingTable hosted by EmbeddingServer via backend='native',
    pulled/pushed through the client wire path."""
    from paddle_tpu.distributed.ps.embedding_service import (
        EmbeddingServer, EmbeddingClient)
    srv = EmbeddingServer()
    srv.create_table(0, 4, backend='native', optimizer='sgd', lr=0.5,
                     initializer='zeros')
    srv.start()
    try:
        c = EmbeddingClient(endpoints=[srv.endpoint])
        ids = np.asarray([3, 9])
        rows = c.pull_sparse(0, ids) if hasattr(c, 'pull_sparse') else \
            c.pull(0, ids)
        np.testing.assert_array_equal(rows, 0)
        (c.push_sparse if hasattr(c, 'push_sparse') else c.push)(
            0, ids, np.ones((2, 4), np.float32))
        rows = (c.pull_sparse if hasattr(c, 'pull_sparse') else c.pull)(
            0, ids)
        np.testing.assert_allclose(rows, -0.5)
    finally:
        srv.stop()


def test_native_beats_python_table_throughput():
    """Informational: batched C++ pull/push vs the python dict loop on an
    identical workload (printed, not asserted — CI boxes vary)."""
    import time
    from paddle_tpu.distributed.ps.embedding_service import EmbeddingTable
    dim, n = 16, 20000
    ids = np.random.RandomState(0).randint(0, 10 * n, n)
    g = np.ones((n, dim), np.float32)

    nat = NativeEmbeddingTable(dim, initializer='zeros')
    t0 = time.perf_counter()
    nat.pull(ids)
    nat.push(ids, g)
    t_nat = time.perf_counter() - t0

    py = EmbeddingTable(dim, initializer='zeros')
    t0 = time.perf_counter()
    py.pull(ids)
    py.push(ids, g)
    t_py = time.perf_counter() - t0
    print('native %.1f ms vs python %.1f ms (%.1fx)' %
          (t_nat * 1e3, t_py * 1e3, t_py / max(t_nat, 1e-9)))
    assert len(nat) == len(py)


def test_load_replaces_and_rejects_optimizer_mismatch(tmp_path):
    t = NativeEmbeddingTable(2, initializer='zeros', optimizer='sgd')
    t.pull(np.asarray([1, 2]))
    t.save(str(tmp_path))

    warm = NativeEmbeddingTable(2, optimizer='sgd')
    warm.pull(np.asarray([99]))          # pre-load row must not survive
    warm.load(str(tmp_path))
    assert len(warm) == 2
    assert (warm.pull(np.asarray([99]), create=False) == 0).all()

    other = NativeEmbeddingTable(2, optimizer='adagrad')
    with pytest.raises(ValueError, match='sgd'):
        other.load(str(tmp_path))

    from paddle_tpu.distributed.ps.embedding_service import (
        EmbeddingServer, EmbeddingTable)
    srv = EmbeddingServer()
    try:
        with pytest.raises(ValueError, match='not both'):
            srv.create_table(0, 2, table_class=EmbeddingTable,
                             backend='native')
    finally:
        # never started serving: shutdown() would block; just close
        srv._srv.server_close()
