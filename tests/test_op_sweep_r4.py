"""Round-4 OpTest tranche (VERDICT r3 item 6): extend the numeric-grad
sweep across the remaining differentiable tensor/* + comparison/
manipulation/linalg surface, converting name-complete into
behavior-complete — the reference op_test.py:270 contract at sweep scale.

Adds a bf16 consistency pass for the MXU-relevant families: every op in
_BF16_SWEEP runs on bf16 inputs and must stay within bf16 tolerance of
its f32 result (TPU-native dtype contract).
"""
import numpy as np
import pytest
from scipy import special as sps

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from test_op_sweep import _mk, _run_sweep_case


def _sym(a):
    return a @ a.T + 3 * np.eye(a.shape[0], dtype=a.dtype)


def _p_sym(a):
    return paddle.matmul(a, a, transpose_y=True) + \
        3 * paddle.eye(a.shape[0])


_IDS3 = np.array([2, 0, 1], np.int32)


SWEEP4 = [
    # --- unary math ---------------------------------------------------------
    ('acos', paddle.acos, np.arccos, [('unit', (3, 4))], {}, True),
    ('acosh', lambda x: paddle.acosh(x + 1.5),
     lambda x: np.arccosh(x + 1.5), [('pos', (3, 4))], {}, True),
    ('asinh', paddle.asinh, np.arcsinh, [(3, 4)], {}, True),
    ('atanh', paddle.atanh, np.arctanh, [('unit', (3, 4))], {}, True),
    ('digamma', paddle.digamma, sps.psi, [('pos', (3, 4))], {}, False),
    ('erfinv', paddle.erfinv, sps.erfinv, [('unit', (3, 4))], {}, True),
    ('i0', paddle.i0, sps.i0, [(3, 4)], {}, False),
    ('neg', paddle.neg, np.negative, [(3, 4)], {}, True),
    ('log10', paddle.log10, np.log10, [('pos', (3, 4))], {}, True),
    ('nan_to_num', paddle.nan_to_num, np.nan_to_num, [(3, 4)], {}, False),
    ('conj_real', paddle.conj, np.conj, [(3, 4)], {}, True),
    ('real_of_real', paddle.real, np.real, [(3, 4)], {}, True),
    # --- binary math --------------------------------------------------------
    ('remainder', paddle.remainder, np.remainder,
     [(3, 4), ('pos', (3, 4))], {}, False),
    ('floor_mod', paddle.floor_mod, np.remainder,
     [(3, 4), ('pos', (3, 4))], {}, False),
    ('copysign', paddle.copysign, np.copysign, [(3, 4), (3, 4)], {}, False),
    ('hypot', paddle.hypot, np.hypot, [(3, 4), (3, 4)], {}, True),
    ('logaddexp', paddle.logaddexp, np.logaddexp, [(3, 4), (3, 4)], {},
     True),
    ('nextafter', paddle.nextafter, np.nextafter, [(3, 4), (3, 4)], {},
     False),
    ('fmin', paddle.fmin, np.fmin, [(3, 4), (3, 4)], {}, False),
    ('gcd', paddle.gcd, np.gcd,
     [('int', (3, 4), 20), ('int', (3, 4), 20)], {}, False),
    ('lcm', paddle.lcm, np.lcm,
     [('int', (3, 4), 9), ('int', (3, 4), 9)], {}, False),
    ('ldexp', paddle.ldexp, np.ldexp,
     [(3, 4), ('int', (3, 4), 4)], {}, False),
    # --- logical / comparison ----------------------------------------------
    ('logical_and', paddle.logical_and, np.logical_and,
     [('int', (3, 4), 2), ('int', (3, 4), 2)], {}, False),
    ('logical_or', paddle.logical_or, np.logical_or,
     [('int', (3, 4), 2), ('int', (3, 4), 2)], {}, False),
    ('logical_xor', paddle.logical_xor, np.logical_xor,
     [('int', (3, 4), 2), ('int', (3, 4), 2)], {}, False),
    ('logical_not', paddle.logical_not, np.logical_not,
     [('int', (3, 4), 2)], {}, False),
    ('bitwise_and', paddle.bitwise_and, np.bitwise_and,
     [('int', (3, 4), 16), ('int', (3, 4), 16)], {}, False),
    ('bitwise_or', paddle.bitwise_or, np.bitwise_or,
     [('int', (3, 4), 16), ('int', (3, 4), 16)], {}, False),
    ('bitwise_xor', paddle.bitwise_xor, np.bitwise_xor,
     [('int', (3, 4), 16), ('int', (3, 4), 16)], {}, False),
    ('bitwise_not', paddle.bitwise_not, np.bitwise_not,
     [('int', (3, 4), 16)], {}, False),
    ('equal', paddle.equal, np.equal,
     [('int', (3, 4), 3), ('int', (3, 4), 3)], {}, False),
    ('not_equal', paddle.not_equal, np.not_equal,
     [('int', (3, 4), 3), ('int', (3, 4), 3)], {}, False),
    ('greater_than', paddle.greater_than, np.greater,
     [(3, 4), (3, 4)], {}, False),
    ('greater_equal', paddle.greater_equal, np.greater_equal,
     [(3, 4), (3, 4)], {}, False),
    ('less_than', paddle.less_than, np.less, [(3, 4), (3, 4)], {}, False),
    ('less_equal', paddle.less_equal, np.less_equal,
     [(3, 4), (3, 4)], {}, False),
    ('isclose', paddle.isclose, np.isclose, [(3, 4), (3, 4)], {}, False),
    ('isfinite', paddle.isfinite, np.isfinite, [(3, 4)], {}, False),
    ('isnan', paddle.isnan, np.isnan, [(3, 4)], {}, False),
    ('isinf', paddle.isinf, np.isinf, [(3, 4)], {}, False),
    # --- reductions ---------------------------------------------------------
    ('sum_axis', lambda x: paddle.sum(x, axis=1),
     lambda x: np.sum(x, 1), [(3, 4)], {}, True),
    ('max_axis', lambda x: paddle.max(x, axis=0),
     lambda x: np.max(x, 0), [(3, 4)], {}, False),
    ('min_axis', lambda x: paddle.min(x, axis=1),
     lambda x: np.min(x, 1), [(3, 4)], {}, False),
    ('std', paddle.std, lambda x: np.std(x, ddof=1), [(3, 4)], {}, True),
    ('var', paddle.var, lambda x: np.var(x, ddof=1), [(3, 4)], {}, True),
    ('norm_fro', paddle.norm, lambda x: np.linalg.norm(x),
     [(3, 4)], {}, True),
    ('dist_l2', paddle.dist,
     lambda x, y: np.linalg.norm((x - y).ravel()),
     [(3, 4), (3, 4)], {}, True),
    ('count_nonzero', paddle.count_nonzero,
     lambda x: np.count_nonzero(x), [('int', (3, 4), 2)], {}, False),
    ('quantile', lambda x: paddle.quantile(x, 0.5),
     lambda x: np.quantile(x, 0.5), [(3, 5)], {}, False),
    ('nanmedian', paddle.nanmedian, np.nanmedian, [(3, 5)], {}, False),
    ('kthvalue', lambda x: paddle.kthvalue(x, 2, axis=1)[0],
     lambda x: np.sort(x, 1)[:, 1], [(3, 5)], {}, False),
    ('mode', lambda x: paddle.mode(x, axis=1)[0],
     lambda x: np.sort(x, 1)[:, 0],  # distinct floats: smallest wins ties
     [(3, 5)], {}, False),
    ('cummax', lambda x: paddle.cummax(x, axis=1)[0],
     lambda x: np.maximum.accumulate(x, 1), [(3, 5)], {}, False),
    ('cummin', lambda x: paddle.cummin(x, axis=1)[0],
     lambda x: np.minimum.accumulate(x, 1), [(3, 5)], {}, False),
    ('logcumsumexp', getattr(paddle, 'logcumsumexp', None),
     lambda x: np.log(np.cumsum(np.exp(x), 1)),
     [(3, 5)], {'axis': 1}, True) if hasattr(paddle, 'logcumsumexp')
    else None,
    ('numel', lambda x: paddle.numel(x), lambda x: np.asarray(x.size),
     [(3, 4)], {}, False),
    # --- manipulation -------------------------------------------------------
    ('reshape', lambda x: paddle.reshape(x, [4, 3]),
     lambda x: x.reshape(4, 3), [(3, 4)], {}, True),
    ('flatten', paddle.flatten, lambda x: x.reshape(-1),
     [(3, 2, 2)], {}, True),
    ('flatten_axis', lambda x: paddle.flatten(x, start_axis=1),
     lambda x: x.reshape(x.shape[0], -1), [(3, 2, 2)], {}, True),
    ('squeeze', lambda x: paddle.squeeze(x, axis=1),
     lambda x: x.squeeze(1), [(3, 1, 4)], {}, True),
    ('unsqueeze', lambda x: paddle.unsqueeze(x, axis=1),
     lambda x: x[:, None], [(3, 4)], {}, True),
    ('transpose', lambda x: paddle.transpose(x, [1, 0]),
     lambda x: x.T, [(3, 4)], {}, True),
    ('moveaxis', lambda x: paddle.moveaxis(x, 0, 2),
     lambda x: np.moveaxis(x, 0, 2), [(2, 3, 4)], {}, True),
    ('tile', lambda x: paddle.tile(x, [2, 3]),
     lambda x: np.tile(x, (2, 3)), [(3, 4)], {}, True),
    ('broadcast_to', lambda x: paddle.broadcast_to(x, [5, 3, 4]),
     lambda x: np.broadcast_to(x, (5, 3, 4)), [(3, 4)], {}, True),
    ('expand', lambda x: paddle.expand(x, [5, 3, 4]),
     lambda x: np.broadcast_to(x, (5, 3, 4)), [(3, 4)], {}, True),
    ('concat2', lambda x, y: paddle.concat([x, y], axis=1),
     lambda x, y: np.concatenate([x, y], 1),
     [(3, 4), (3, 2)], {}, True),
    ('stack2', lambda x, y: paddle.stack([x, y], axis=0),
     lambda x, y: np.stack([x, y]), [(3, 4), (3, 4)], {}, True),
    ('unstack', lambda x: paddle.unstack(x, axis=0),
     lambda x: [x[i] for i in range(x.shape[0])], [(3, 4)], {}, False),
    ('unbind', lambda x: paddle.unbind(x, axis=1),
     lambda x: [x[:, i] for i in range(x.shape[1])], [(3, 2)], {}, False),
    ('split', lambda x: paddle.split(x, 2, axis=1),
     lambda x: np.split(x, 2, 1), [(3, 4)], {}, False),
    ('chunk', lambda x: paddle.chunk(x, 2, axis=1),
     lambda x: np.split(x, 2, 1), [(3, 4)], {}, False),
    ('gather', lambda x: paddle.gather(x, paddle.to_tensor(_IDS3), axis=0),
     lambda x: x[_IDS3], [(3, 4)], {}, True),
    ('gather_nd',
     lambda x: paddle.gather_nd(x, paddle.to_tensor(
         np.array([[0, 1], [2, 3]], np.int32))),
     lambda x: x[[0, 2], [1, 3]], [(3, 4)], {}, True),
    ('index_select',
     lambda x: paddle.index_select(x, paddle.to_tensor(_IDS3), axis=1),
     lambda x: x[:, _IDS3], [(3, 4)], {}, True),
    ('index_sample',
     lambda x: paddle.index_sample(x, paddle.to_tensor(
         np.array([[0, 2], [1, 3], [2, 0]], np.int32))),
     lambda x: np.take_along_axis(
         x, np.array([[0, 2], [1, 3], [2, 0]]), 1), [(3, 4)], {}, True),
    ('take_along_axis',
     lambda x: paddle.take_along_axis(x, paddle.to_tensor(
         np.array([[0], [1], [2]], np.int64)), axis=1),
     lambda x: np.take_along_axis(
         x, np.array([[0], [1], [2]]), 1), [(3, 4)], {}, True),
    ('put_along_axis',
     lambda x: paddle.put_along_axis(x, paddle.to_tensor(
         np.array([[0], [1], [2]], np.int64)),
         paddle.to_tensor(np.float32(9.0)), axis=1),
     None, [(3, 4)], {}, False),
    ('take', lambda x: paddle.take(x, paddle.to_tensor(
        np.array([0, 5, 11], np.int32))),
     lambda x: x.ravel()[[0, 5, 11]], [(3, 4)], {}, True),
    ('scatter',
     lambda x, u: paddle.scatter(x, paddle.to_tensor(
         np.array([1, 0], np.int32)), u),
     None, [(3, 4), (2, 4)], {}, False),
    ('scatter_nd_add',
     lambda x, u: paddle.scatter_nd_add(x, paddle.to_tensor(
         np.array([[1], [0]], np.int32)), u),
     None, [(3, 4), (2, 4)], {}, False),
    ('slice_op',
     lambda x: paddle.slice(x, axes=[0, 1], starts=[0, 1], ends=[2, 3]),
     lambda x: x[0:2, 1:3], [(3, 4)], {}, True),
    ('strided_slice',
     lambda x: paddle.strided_slice(x, axes=[1], starts=[0], ends=[4],
                                    strides=[2]),
     lambda x: x[:, 0:4:2], [(3, 4)], {}, True),
    ('crop', lambda x: paddle.crop(x, shape=[2, 2], offsets=[1, 1]),
     lambda x: x[1:3, 1:3], [(3, 4)], {}, True),
    ('repeat_interleave',
     lambda x: paddle.repeat_interleave(x, 2, axis=1),
     lambda x: np.repeat(x, 2, 1), [(3, 4)], {}, True),
    ('searchsorted',
     lambda s, v: paddle.searchsorted(s, v),
     lambda s, v: np.stack([np.searchsorted(s[i], v[i])
                            for i in range(s.shape[0])]),
     [(2, 5), (2, 3)], {}, False),
    ('sort_axis', lambda x: paddle.sort(x, axis=1),
     lambda x: np.sort(x, 1), [(3, 5)], {}, True),
    ('argsort', lambda x: paddle.argsort(x, axis=1),
     lambda x: np.argsort(x, 1, kind='stable'), [(3, 5)], {}, False),
    ('topk', lambda x: paddle.topk(x, 2, axis=1)[0],
     lambda x: np.sort(x, 1)[:, ::-1][:, :2], [(3, 5)], {}, False),
    ('masked_select',
     lambda x: paddle.masked_select(x, paddle.to_tensor(_MASK34)),
     lambda x: x[_MASK34], [(3, 4)], {}, False),
    ('where_op',
     lambda x, y: paddle.where(paddle.to_tensor(_MASK34), x, y),
     lambda x, y: np.where(_MASK34, x, y), [(3, 4), (3, 4)], {}, True),
    ('multiplex',
     lambda a, b: paddle.multiplex(
         [a, b], paddle.to_tensor(np.array([[0], [1], [0]], np.int32))),
     lambda a, b: np.stack([a[0], b[1], a[2]]), [(3, 4), (3, 4)], {},
     False),
    ('diag_vec', paddle.diag, np.diag, [(4,)], {}, True),
    ('diagflat', paddle.diagflat, np.diagflat, [(3,)], {}, True),
    ('meshgrid',
     lambda x, y: paddle.meshgrid(x, y),
     lambda x, y: np.meshgrid(x, y, indexing='ij'), [(3,), (4,)], {},
     False),
    ('t_2d', paddle.t, lambda x: x.T, [(3, 4)], {}, True),
    ('as_complex_real',
     lambda x: paddle.real(paddle.as_complex(x)),
     lambda x: x[..., 0], [(3, 4, 2)], {}, True),
    # --- matmul family ------------------------------------------------------
    ('mm', paddle.mm, np.matmul, [(3, 4), (4, 5)], {}, True),
    ('mv', paddle.mv, np.matmul, [(3, 4), (4,)], {}, True),
    ('addmm',
     lambda inp, a, b: paddle.addmm(inp, a, b, beta=0.5, alpha=2.0),
     lambda inp, a, b: 0.5 * inp + 2.0 * (a @ b),
     [(3, 5), (3, 4), (4, 5)], {}, True),
    ('multi_dot', lambda a, b, c: paddle.linalg.multi_dot([a, b, c]),
     lambda a, b, c: a @ b @ c, [(2, 3), (3, 4), (4, 2)], {}, True),
    ('tensordot', lambda a, b: paddle.tensordot(a, b, axes=1),
     lambda a, b: np.tensordot(a, b, 1), [(3, 4), (4, 5)], {}, True),
    ('einsum_ij',
     lambda a, b: paddle.einsum('ij,jk->ik', a, b),
     lambda a, b: a @ b, [(3, 4), (4, 5)], {}, True),
    ('add_n', lambda a, b: paddle.add_n([a, b]),
     lambda a, b: a + b, [(3, 4), (3, 4)], {}, True),
    # --- linalg -------------------------------------------------------------
    ('inverse', lambda a: paddle.inverse(_p_sym(a)),
     lambda a: np.linalg.inv(_sym(a)), [(4, 4)], {}, True),
    ('cholesky', lambda a: paddle.linalg.cholesky(_p_sym(a)),
     lambda a: np.linalg.cholesky(_sym(a)), [(4, 4)], {}, True),
    ('cholesky_solve',
     lambda a, b: paddle.linalg.cholesky_solve(
         b, paddle.linalg.cholesky(_p_sym(a))),
     lambda a, b: np.linalg.solve(_sym(a), b), [(4, 4), (4, 2)], {},
     False),
    ('solve', lambda a, b: paddle.linalg.solve(_p_sym(a), b),
     lambda a, b: np.linalg.solve(_sym(a), b), [(4, 4), (4, 2)], {},
     True),
    ('triangular_solve',
     lambda a, b: paddle.linalg.triangular_solve(
         paddle.tril(a) + 3 * paddle.eye(4), b, upper=False),
     lambda a, b: np.linalg.solve(np.tril(a) + 3 * np.eye(4), b),
     [(4, 4), (4, 2)], {}, True),
    ('matrix_power', lambda a: paddle.linalg.matrix_power(a, 3),
     lambda a: np.linalg.matrix_power(a, 3), [(4, 4)], {}, True),
    ('slogdet', lambda a: paddle.linalg.slogdet(_p_sym(a))[1],
     lambda a: np.linalg.slogdet(_sym(a))[1], [(4, 4)], {}, True),
    ('svdvals', lambda a: paddle.linalg.svd(a)[1],
     lambda a: np.linalg.svd(a, compute_uv=False), [(4, 3)], {}, False),
    ('qr_reconstruct', lambda a: paddle.matmul(*paddle.linalg.qr(a)),
     lambda a: a, [(4, 3)], {}, True),
    ('eigvalsh', lambda a: paddle.linalg.eigvalsh(_p_sym(a)),
     lambda a: np.linalg.eigvalsh(_sym(a)), [(4, 4)], {}, False),
    ('eigh_vals', lambda a: paddle.linalg.eigh(_p_sym(a))[0],
     lambda a: np.linalg.eigvalsh(_sym(a)), [(4, 4)], {}, False),
    ('lstsq', lambda a, b: paddle.linalg.lstsq(a, b)[0],
     lambda a, b: np.linalg.lstsq(a, b, rcond=None)[0],
     [(5, 3), (5, 2)], {}, False),
    ('pinv', paddle.linalg.pinv, np.linalg.pinv, [(4, 3)], {}, False),
    ('matrix_rank', lambda a: paddle.linalg.matrix_rank(_p_sym(a)),
     lambda a: np.asarray(np.linalg.matrix_rank(_sym(a))),
     [(4, 4)], {}, False),
    ('histogram',
     lambda x: paddle.histogram(x, bins=5, min=-2, max=2),
     lambda x: np.histogram(x, bins=5, range=(-2, 2))[0],
     [(3, 4)], {}, False),
    ('bincount', paddle.bincount, np.bincount,
     [('int', (10,), 5)], {}, False),
    ('cov', lambda x: paddle.linalg.cov(x),
     lambda x: np.cov(x), [(3, 6)], {}, False),
    ('corrcoef', lambda x: paddle.linalg.corrcoef(x),
     lambda x: np.corrcoef(x), [(3, 6)], {}, False),
    # --- creation (vs numpy) ------------------------------------------------
    ('linspace', lambda: paddle.linspace(0, 1, 7),
     lambda: np.linspace(0, 1, 7, dtype=np.float32), [], {}, False),
    ('logspace', lambda: paddle.logspace(0, 2, 5),
     lambda: np.logspace(0, 2, 5, dtype=np.float32), [], {}, False),
    ('arange_op', lambda: paddle.arange(1, 10, 2),
     lambda: np.arange(1, 10, 2), [], {}, False),
    ('eye_op', lambda: paddle.eye(3, 4), lambda: np.eye(3, 4),
     [], {}, False),
    ('full_op', lambda: paddle.full([2, 3], 2.5),
     lambda: np.full((2, 3), 2.5, np.float32), [], {}, False),
    ('tril_indices', lambda: paddle.tril_indices(3, 3, 0),
     lambda: np.stack(np.tril_indices(3, 0, 3)), [], {}, False),
    ('triu_indices', lambda: paddle.triu_indices(3, 3, 0),
     lambda: np.stack(np.triu_indices(3, 0, 3)), [], {}, False),
    ('ones_like_op', paddle.ones_like, np.ones_like, [(3, 4)], {}, False),
    ('zeros_like_op', paddle.zeros_like, np.zeros_like,
     [(3, 4)], {}, False),
    ('full_like_op', lambda x: paddle.full_like(x, 7.0),
     lambda x: np.full_like(x, 7.0), [(3, 4)], {}, False),
    ('diag_embed_like', lambda x: paddle.diag(x, offset=1),
     lambda x: np.diag(x, 1), [(4,)], {}, False),
    # --- misc ---------------------------------------------------------------
    ('clip', lambda x: paddle.clip(x, -0.5, 0.5),
     lambda x: np.clip(x, -0.5, 0.5), [(3, 4)], {}, True),
    ('increment', lambda x: paddle.increment(x, 2.0),
     lambda x: x + 2.0, [(1,)], {}, False),
    ('cast_i32', lambda x: paddle.cast(x, 'int32'),
     lambda x: x.astype(np.int32), [('pos', (3, 4))], {}, False),
    ('shard_index',
     lambda x: paddle.shard_index(x, index_num=20, nshards=2, shard_id=0),
     lambda x: np.where(x < 10, x, -1), [('int', (4, 1), 20)], {}, False),
    ('unique_sorted', lambda x: paddle.unique(x),
     lambda x: np.unique(x), [('int', (10,), 4)], {}, False),
    ('nonzero_op', lambda x: paddle.nonzero(x),
     lambda x: np.stack(np.nonzero(x), 1), [('int', (3, 4), 2)], {},
     False),
]
SWEEP4 = [c for c in SWEEP4 if c is not None]

_MASK34 = (np.arange(12).reshape(3, 4) % 3 == 0)


@pytest.mark.parametrize('case', SWEEP4, ids=[c[0] for c in SWEEP4])
def test_op_sweep_r4(case):
    name, fn, ref, specs, attrs, grad = case
    if fn is None:
        pytest.skip('op absent')
    if not specs:
        # creation ops: direct compare
        out = fn()
        outs = out if isinstance(out, (list, tuple)) else [out]
        refs = ref()
        refs = refs if isinstance(refs, (list, tuple)) else [refs]
        for o, r in zip(outs, refs):
            np.testing.assert_allclose(np.asarray(o.numpy(), np.float64),
                                       np.asarray(r, np.float64),
                                       rtol=1e-5, atol=1e-5)
        return
    _run_sweep_case(case)


# -- bf16 consistency: MXU-relevant families run in the TPU-native dtype ----

_BF16_SWEEP = [
    ('matmul', lambda x, y: paddle.matmul(x, y), [(8, 16), (16, 8)]),
    ('mm', paddle.mm, [(8, 16), (16, 8)]),
    ('add', paddle.add, [(8, 16), (8, 16)]),
    ('multiply', paddle.multiply, [(8, 16), (8, 16)]),
    ('softmax', lambda x: F.softmax(x, axis=-1), [(8, 16)]),
    ('gelu', F.gelu, [(8, 16)]),
    ('relu', F.relu, [(8, 16)]),
    ('tanh', paddle.tanh, [(8, 16)]),
    ('sigmoid', F.sigmoid, [(8, 16)]),
    ('layer_norm_fn',
     lambda x, w, b: F.layer_norm(x, (16,), weight=None, bias=None),
     [(8, 16), (16,), (16,)]),
    ('mean', paddle.mean, [(8, 16)]),
    ('sum', paddle.sum, [(8, 16)]),
    ('exp', paddle.exp, [(4, 8)]),
    ('log', lambda x: paddle.log(paddle.abs(x) + 1.0), [(4, 8)]),
    ('transpose', lambda x: paddle.transpose(x, [1, 0]), [(8, 16)]),
    ('concat', lambda x, y: paddle.concat([x, y], axis=1),
     [(4, 8), (4, 8)]),
    ('cross_entropy_logits',
     lambda x: F.log_softmax(x, axis=-1), [(8, 16)]),
]


@pytest.mark.parametrize('case', _BF16_SWEEP, ids=[c[0] for c in _BF16_SWEEP])
def test_bf16_consistency(case):
    """f(x.bf16) must track f(x.f32) within bf16 resolution — every op a
    TPU training step touches must be usable in the MXU-native dtype."""
    name, fn, specs = case
    rng = np.random.RandomState(11)
    f32 = [rng.randn(*s).astype(np.float32) for s in specs]
    out32 = fn(*[paddle.to_tensor(a) for a in f32])
    out16 = fn(*[paddle.to_tensor(a).astype('bfloat16') for a in f32])
    o32 = out32[0] if isinstance(out32, (list, tuple)) else out32
    o16 = out16[0] if isinstance(out16, (list, tuple)) else out16
    assert 'bfloat16' in str(o16.dtype)
    np.testing.assert_allclose(
        np.asarray(o16.astype('float32').numpy(), np.float64),
        np.asarray(o32.numpy(), np.float64), rtol=0.05, atol=0.05,
        err_msg='bf16 drift for %s' % name)
