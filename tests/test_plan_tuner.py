"""Sharding autotuner: content-addressed plan artifacts, strict key
resolution, the cfg5 search pin, and the persistent-compile-cache /
CompileWatchdog composition.

The pure layers (keys, spec codec, scoring, artifact round-trip,
resolution) are tested without compiling; the search itself runs ONCE
per module on the cfg5 mesh (pp2 x sharding4 — the config whose
involuntary reshards the whole subsystem exists to eliminate) and two
tests share the artifact.
"""
import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed import auto_parallel as ap
from paddle_tpu.distributed.auto_parallel import tuner
from paddle_tpu.distributed.auto_parallel.planner import _U

# one REAL involuntary-reshard warning (the r05 dialect) so score_report
# is fixture-tested against the text the auditor actually parses
WARN_LINE = (
    'W0802 18:00:41.692990    3516 spmd_partitioner.cc:652] [SPMD] '
    'Involuntary full rematerialization. The compiler cannot go from '
    'sharding {devices=[4,1]0,2,1,3} to {devices=[1,2,2]<=[2,2]T(1,0) '
    'last_tile_dim_replicate} efficiently for HLO operation %squeeze.67 '
    '= f32[128,128]{1,0} copy(%squeeze.66), sharding={devices=[4,1]'
    '0,2,1,3}, metadata={op_name="while/body/squeeze" stack_frame_id=99}'
    '. As the last resort, SPMD will replicate the tensor and then '
    'partition it to obtain the target sharding, which is inefficient.')


def _mesh_pp_sharding():
    dev = np.array(jax.devices()[:8]).reshape(1, 2, 4)
    return Mesh(dev, ('dp', 'pp', 'sharding'))


def _toy_artifact(model=None):
    """A hand-built artifact for the cfg5 mesh carrying the analytic
    planner's own specs — key-compatible with resolve_plan's live key
    (same mesh sizes, axis, batch axes, jaxlib, fingerprint)."""
    boundaries = {
        'micro': {'spec': [None, ['sharding']],
                  'score': {'involuntary_bytes': 0}},
        'stacked': {'spec': ['pp'], 'score': {'involuntary_bytes': 0}},
        'batch': {'spec': [['sharding']],
                  'score': {'involuntary_bytes': 0}},
    }
    return tuner.build_artifact({'dp': 1, 'pp': 2, 'sharding': 4}, 'pp',
                                ('sharding',), boundaries,
                                model_fingerprint=model)


# ---------------- keys + codec (pure) ----------------

def test_key_of_config_is_content_addressed():
    base = tuner.current_config({'dp': 1, 'pp': 2, 'sharding': 4}, 'pp',
                                ('sharding',))
    assert tuner.key_of_config(base) == tuner.key_of_config(dict(base))
    assert len(tuner.key_of_config(base)) == 16
    for mutate in (lambda c: c['mesh'].update(sharding=8),
                   lambda c: c.update(axis='mp'),
                   lambda c: c.update(batch_axes=['dp']),
                   lambda c: c.update(jaxlib='9.9.9'),
                   lambda c: c.update(model='gpt-13b')):
        other = json.loads(json.dumps(base))
        mutate(other)
        assert tuner.key_of_config(other) != tuner.key_of_config(base)


def test_entry_codec_roundtrip():
    entries = (None, ('dp', 'sharding'), 'pp', _U)
    enc = tuner.encode_entries(entries)
    assert enc == [None, ['dp', 'sharding'], 'pp', '*']
    assert tuner.decode_entries(enc) == entries
    assert tuner.encode_entries(None) is None
    assert tuner.decode_entries(None) is None


# ---------------- scoring (fixture-driven, no compile) ----------------

def test_score_report_and_key_ordering():
    dirty = tuner.score_report(ap.audit_from_text(WARN_LINE, label='d'))
    clean = tuner.score_report(ap.audit_from_text('all quiet', label='c'))
    assert dirty['involuntary_bytes'] >= 128 * 128 * 4
    assert clean['involuntary_bytes'] == 0
    assert tuner.score_key(clean) < tuner.score_key(dirty)
    # involuntary bytes dominate any collective traffic...
    loud = dict(clean, collective_bytes=10 ** 9)
    assert tuner.score_key(loud) < tuner.score_key(dirty)
    # ...and collective bytes dominate the analytic tiebreaker
    slow = dict(clean, ideal_step_s=99.0)
    assert tuner.score_key(slow) < tuner.score_key(loud)


# ---------------- artifact round-trip + verification ----------------

def test_artifact_roundtrip_byte_identical(tmp_path):
    art = _toy_artifact()
    blob = tuner.dump_plan(art)
    path = tuner.save_plan(art, str(tmp_path))
    assert os.path.basename(path) == 'plan_%s.json' % art['key']
    with open(path) as f:
        assert f.read() == blob
    reloaded = tuner.load_plan(path)
    assert tuner.dump_plan(reloaded) == blob          # emit == re-emit
    assert tuner.verify_artifact(reloaded) is reloaded
    # saving the reload writes the identical file again
    assert tuner.save_plan(reloaded, str(tmp_path)) == path
    with open(path) as f:
        assert f.read() == blob


def test_verify_artifact_rejections():
    art = _toy_artifact()
    with pytest.raises(tuner.PlanKeyError, match='version'):
        tuner.verify_artifact(dict(art, version=99))
    with pytest.raises(tuner.PlanKeyError, match='re-derive'):
        tuner.verify_artifact(dict(art, key='deadbeefdeadbeef'))
    with pytest.raises(tuner.PlanKeyError, match='stale'):
        tuner.verify_artifact(art, expect_key='0' * 16)
    assert tuner.verify_artifact(art, expect_key=art['key']) is art


# ---------------- resolution (engines' plan source) ----------------

def test_resolve_plan_loads_matching_artifact(tmp_path, monkeypatch):
    art = _toy_artifact()
    tuner.save_plan(art, str(tmp_path))
    monkeypatch.setenv('PADDLE_TPU_PLAN_DIR', str(tmp_path))
    mesh = _mesh_pp_sharding()
    plan = tuner.resolve_plan(mesh, 'pp')
    assert isinstance(plan, tuner.TunedPlan)
    assert plan.key == art['key']
    micro = plan.micro_spec((2, 4, 64, 128))
    assert micro[0] is None and micro[1] == ('sharding',)
    # the planner's shape guards survive the artifact
    assert plan.micro_spec((2, 3, 64)) is None
    # the engines' call-site helper resolves the same artifact
    from paddle_tpu.distributed.pipeline import make_pp_state
    st = make_pp_state(mesh, n_stages=2)
    assert isinstance(tuner.resolve_plan_for_state(st), tuner.TunedPlan)
    assert tuner.resolve_plan_for_state(None) is None


def test_resolve_plan_stale_key_strict_vs_fallback(tmp_path, monkeypatch):
    # the dir holds a plan for ANOTHER config (different fingerprint)
    tuner.save_plan(_toy_artifact(model='other-model'), str(tmp_path))
    monkeypatch.setenv('PADDLE_TPU_PLAN_DIR', str(tmp_path))
    mesh = _mesh_pp_sharding()
    plan = tuner.resolve_plan(mesh, 'pp')      # non-strict: fall back
    assert plan is not None
    assert not isinstance(plan, tuner.TunedPlan)
    monkeypatch.setenv('PADDLE_TPU_PLAN_STRICT', '1')
    with pytest.raises(tuner.PlanKeyError, match='stale artifacts'):
        tuner.resolve_plan(mesh, 'pp')


def test_resolve_plan_corrupt_artifact_strict_vs_fallback(
        tmp_path, monkeypatch):
    art = _toy_artifact()
    path = tuner.save_plan(art, str(tmp_path))
    # corrupt IN PLACE at the live key's path: stored key no longer
    # re-derives from the stored config
    with open(path, 'w') as f:
        f.write(tuner.dump_plan(dict(art, key='deadbeefdeadbeef')))
    monkeypatch.setenv('PADDLE_TPU_PLAN_DIR', str(tmp_path))
    mesh = _mesh_pp_sharding()
    plan = tuner.resolve_plan(mesh, 'pp')
    assert not isinstance(plan, tuner.TunedPlan)
    monkeypatch.setenv('PADDLE_TPU_PLAN_STRICT', '1')
    with pytest.raises(tuner.PlanKeyError):
        tuner.resolve_plan(mesh, 'pp')


# ---------------- the cfg5 search pin (compiles: 5 + 1) ----------------

@pytest.fixture(scope='module')
def cfg5_artifact():
    return tuner.tune_pipeline(_mesh_pp_sharding(), axis='pp')


def test_tuner_cfg5_reproduces_or_beats_planner(cfg5_artifact):
    art = cfg5_artifact
    assert art is not None and art['key']
    assert art['probe_compiles'] == 5
    bounds = art['boundaries']
    assert set(bounds) == set(tuner.BOUNDARIES)
    # the planner's micro pin (the r05 fix) is rediscovered by search:
    # GSPMD's transposed guess scores involuntary bytes, the time-axis
    # layout scores none
    assert bounds['micro']['spec'] == [None, ['sharding']]
    micro_cands = {json.dumps(t['spec']): t['score']
                   for t in bounds['micro']['candidates']}
    assert micro_cands[json.dumps([['sharding'], None])][
        'involuntary_bytes'] > 0
    for b in tuner.BOUNDARIES:
        chosen = bounds[b]['score']
        planner = bounds[b]['candidates'][0]['score']  # index 0 = planner
        assert chosen['involuntary_bytes'] == 0
        assert tuner.score_key(chosen) <= tuner.score_key(planner)


def test_tuned_plan_probe_compiles_clean(cfg5_artifact):
    mesh = _mesh_pp_sharding()
    plan = tuner.plan_from_artifact(cfg5_artifact, mesh)
    assert isinstance(plan, tuner.TunedPlan)
    fn, args = tuner.default_probe(plan)
    rep = ap.assert_no_involuntary_resharding(fn, args=args,
                                              label='tuned-cfg5')
    assert rep.passed
    assert plan.describe()['plan_key'] == cfg5_artifact['key']


# -------- persistent cache x watchdog composition (satellite fix) -------

def test_cache_hit_after_warmup_is_not_a_recompile(tmp_path):
    """The satellite-6 regression pin: jax fires the backend-compile
    duration event even when the persistent cache served the
    executable, so a cache-hit reload after declare_warmup() used to
    trip the watchdog. strict=True makes a misclassification raise
    RecompileError right here."""
    from paddle_tpu.framework import compile_cache
    from paddle_tpu import monitor

    x = jnp.arange(8.0)
    jnp.multiply(x, 1.0).block_until_ready()   # aux compiles out of the way
    if compile_cache.configure(str(tmp_path / 'cc')) is None:
        pytest.skip('jaxlib rejects the compilation-cache knobs')
    reg = monitor.MetricRegistry()
    wd = monitor.CompileWatchdog(registry=reg, strict=True, name='cc')
    try:
        jax.jit(lambda x: x * 2.0 + 1.0)(x).block_until_ready()  # miss
        wd.declare_warmup('cache-hit test')
        # an IDENTICAL program under a fresh jit wrapper: the in-memory
        # jit cache can't serve it, the persistent cache does
        jax.jit(lambda x: x * 2.0 + 1.0)(x).block_until_ready()
        assert wd.recompiles == 0
        assert reg.get('perf_recompiles_total').value() == 0
        assert reg.get('perf_persistent_cache_hits_total').value() >= 1
        assert reg.get('perf_persistent_cache_misses_total').value() >= 1
    finally:
        wd.close()
        compile_cache.disable()


def test_compile_cache_configure_idempotent(tmp_path):
    from paddle_tpu.framework import compile_cache
    d = str(tmp_path / 'cc2')
    try:
        got = compile_cache.configure(d)
        if got is None:
            pytest.skip('jaxlib rejects the compilation-cache knobs')
        assert got == d and compile_cache.enabled()
        assert compile_cache.cache_dir() == d
        assert compile_cache.configure(d) == d     # repeat: no-op
        s = compile_cache.stats()
        assert set(s) == {'hits', 'misses'}
    finally:
        compile_cache.disable()
        assert not compile_cache.enabled()
