"""Auto-checkpoint epoch-resume (reference:
fluid/incubate/checkpoint/auto_checkpoint.py TrainEpochRange:265 —
snapshot per epoch, resume at the last one after a crash)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.auto_checkpoint import TrainEpochRange


def _setup(seed=0):
    paddle.seed(seed)
    model = nn.Linear(4, 2)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model.parameters())
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, 2).astype(np.float32))
    return model, opt, x, y


def _train_one(model, opt, x, y):
    loss = F.mse_loss(model(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss.numpy())


def test_resume_after_crash(tmp_path):
    ckpt = str(tmp_path)

    # run 1: "crashes" after epoch 2 (epochs 0,1,2 complete + snapshot)
    model, opt, x, y = _setup()
    seen = []
    for epoch in TrainEpochRange(10, 'job1', checkpoint_dir=ckpt,
                                 model=model, optimizer=opt):
        seen.append(epoch)
        _train_one(model, opt, x, y)
        if epoch == 2:
            break
    # the break skipped epoch 2's save hook; epochs 0 and 1 are on disk
    assert seen == [0, 1, 2]
    w_after_crash = None

    # run 2: fresh objects, resume from the last snapshot (epoch 1)
    model2, opt2, x, y = _setup(seed=99)  # different init to prove restore
    r = TrainEpochRange(5, 'job1', checkpoint_dir=ckpt,
                        model=model2, optimizer=opt2)
    assert r.restored_epoch == 1
    seen2 = [e for e in r]
    assert seen2 == [2, 3, 4]

    # run 3: everything finished; nothing left to iterate
    model3, opt3, x, y = _setup()
    r3 = TrainEpochRange(5, 'job1', checkpoint_dir=ckpt,
                         model=model3, optimizer=opt3)
    assert [e for e in r3] == []


def test_restored_state_matches_saved(tmp_path):
    model, opt, x, y = _setup(seed=3)
    r = TrainEpochRange(3, 'job2', checkpoint_dir=str(tmp_path),
                        model=model, optimizer=opt)
    for epoch in r:
        _train_one(model, opt, x, y)
    w_saved = model.weight.numpy().copy()
    step_saved = opt.state_dict()['step']

    model2, opt2, _, _ = _setup(seed=123)
    r2 = TrainEpochRange(3, 'job2', checkpoint_dir=str(tmp_path),
                         model=model2, optimizer=opt2)
    np.testing.assert_array_equal(model2.weight.numpy(), w_saved)
    import jax.numpy as jnp
    assert int(jnp.asarray(opt2._step_count)) == int(
        jnp.asarray(step_saved._data if hasattr(step_saved, '_data')
                    else step_saved))


def test_keep_last_prunes_old_snapshots(tmp_path):
    model, opt, x, y = _setup()
    r = TrainEpochRange(8, 'job3', checkpoint_dir=str(tmp_path),
                        model=model, optimizer=opt, keep_last=2)
    for epoch in r:
        pass
    import os
    files = sorted(os.listdir(os.path.join(str(tmp_path), 'job3')))
    # each snapshot = data file + CRC32 manifest sidecar; pruning removes
    # both for evicted epochs
    assert files == ['epoch_6.ckpt', 'epoch_6.ckpt.manifest',
                     'epoch_7.ckpt', 'epoch_7.ckpt.manifest']
