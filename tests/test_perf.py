"""Performance-introspection tests (paddle_tpu/monitor/perf/).

The load-bearing assertions:
  1. the recompile ORACLE: one injected retrace after the warmup
     barrier produces exactly one perf_recompiles_total increment,
     attributed to this file's callsite and the offending abstract
     shapes, plus exactly one flight dump — and raises under strict;
  2. serving steady state: a full paged-engine burst ends armed with
     ZERO recompiles (the engine design's core invariant, now watched);
  3. the step timeline's phase arithmetic under a fake clock (sum of
     phases == wall, remainder lands in 'other', straggler detection
     fires against the rolling median) — sleep-free;
  4. the cost model reproduces exact analytic FLOPs on a known matmul
     and classifies it on the roofline;
  5. the disabled path stays near-free and records nothing.

All tests run CPU-only (conftest pins jax_platforms=cpu) and without
sleeps; the watchdog listener is process-global, so every test pairs
construction with close().
"""
import gc
import glob
import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.monitor import MetricRegistry, set_default_registry
from paddle_tpu.monitor.perf import (COMPILE_EVENTS, CompileWatchdog,
                                     PHASES, RecompileError, StepTimeline,
                                     costmodel)
from paddle_tpu.monitor.runtime import jax_cache_entries
from paddle_tpu.monitor.telemetry import PERF_FAMILIES
from paddle_tpu.monitor.tracing import FlightRecorder, Tracer

REPO = __file__.rsplit('/tests/', 1)[0]


def _fresh_fn():
    """A never-before-jitted function (fresh closure -> fresh jit cache
    entry, so every call here genuinely compiles)."""
    salt = np.float32(np.random.rand())

    def f(x):
        return (x * salt).sum()
    return f


def _watchdog(tmp_path=None, **kw):
    """Watchdog + private registry + private tracer whose flight ring
    dumps (cooldown 0) into tmp_path when given."""
    reg = MetricRegistry()
    rec = FlightRecorder(dump_dir=str(tmp_path) if tmp_path else None,
                         cooldown=0.0, registry=reg)
    tracer = Tracer(recorder=rec, registry=reg)
    wd = CompileWatchdog(registry=reg, tracer=tracer, **kw)
    return wd, reg, tracer


# -- the recompile oracle ----------------------------------------------------

def test_recompile_oracle_attribution_and_flight_dump(tmp_path):
    wd, reg, _ = _watchdog(tmp_path, strict=False, name='oracle')
    try:
        if not wd.active:
            pytest.skip('jax.monitoring listeners unavailable')
        # numpy inputs: jnp.zeros would itself fire an eager compile
        # event per new shape and pollute the exact counts below
        f = jax.jit(_fresh_fn())
        f(np.zeros((4, 16), np.float32)).block_until_ready()
        assert wd.counts['compile'] >= 1
        assert wd.counts['trace'] >= 1
        wd.declare_warmup('oracle warm')
        assert wd.armed
        before = wd.counts['compile']

        f(np.zeros((4, 32), np.float32)).block_until_ready()  # RETRACE

        assert wd.counts['compile'] == before + 1
        assert wd.recompiles == 1
        assert reg.get('perf_recompiles_total').value() == 1.0
        rec = wd.records[-1]
        assert rec['after_warmup'] == 'oracle warm'
        assert 'test_perf' in rec['callsite']       # charged to US
        assert 'float32[4,32]' in rec['signature']  # the offending avals
        dumps = glob.glob(str(tmp_path / 'flight_recompile_*.json'))
        assert len(dumps) == 1                      # exactly one dump
        with open(dumps[0]) as fh:
            spans = json.load(fh)['spans']
        hits = [s for s in spans if s.get('name') == 'perf.recompile']
        assert len(hits) == 1
        assert hits[0]['tags']['signature'] == rec['signature']
    finally:
        wd.close()
    assert not wd.active


def test_strict_mode_raises_out_of_the_dispatch():
    wd, reg, _ = _watchdog(strict=True)
    try:
        if not wd.active:
            pytest.skip('jax.monitoring listeners unavailable')
        f = jax.jit(_fresh_fn())
        f(np.ones((2, 2), np.float32)).block_until_ready()
        wd.declare_warmup('strict warm')
        with pytest.raises(RecompileError, match='strict warm'):
            f(np.ones((2, 3), np.float32))
        assert wd.recompiles == 1
        # suspended(): deliberate compiles inside a warm window are fine
        with wd.suspended():
            f(np.ones((2, 4), np.float32)).block_until_ready()
        assert wd.recompiles == 1
        assert wd.armed                              # re-armed on exit
    finally:
        wd.close()


def test_owner_filter_ignores_other_objects_compiles():
    """Replica A's armed watchdog must not be tripped by a compile on a
    stack that never touches A (the gateway multi-replica hazard)."""
    class Owner:
        def compile_something(self, f, x):
            return f(x).block_until_ready()

    a, b = Owner(), Owner()
    wd, reg, _ = _watchdog(strict=False, owner=a)
    try:
        if not wd.active:
            pytest.skip('jax.monitoring listeners unavailable')
        wd.declare_warmup('owner warm')
        b.compile_something(jax.jit(_fresh_fn()),
                            np.ones((3, 3), np.float32))
        assert wd.recompiles == 0                    # b's compile: ignored
        a.compile_something(jax.jit(_fresh_fn()),
                            np.ones((3, 3), np.float32))
        assert wd.recompiles == 1                    # a's compile: charged
    finally:
        wd.close()


def test_watchdog_counts_cross_check_runtime_sampler():
    """The watchdog's event counts and the RuntimeSampler's trace-cache
    gauge watch the same phenomenon: a fresh jit compile must move
    BOTH."""
    wd, reg, _ = _watchdog()
    try:
        if not wd.active:
            pytest.skip('jax.monitoring listeners unavailable')
        # census entries die with their (weakly-referenced) functions, so
        # a GC pass inside the window can drop more entries than the
        # fresh compile adds when a long suite ran first. Collect before
        # EACH read so both censuses count only live entries, and keep a
        # strong ref to the jitted fn so its entries are alive at read 2.
        f = jax.jit(_fresh_fn())
        gc.collect()
        entries0 = jax_cache_entries()
        assert entries0 is not None and entries0 >= 0
        c0 = wd.counts['compile']
        f(np.ones((5,), np.float32)).block_until_ready()
        assert wd.counts['compile'] == c0 + 1
        gc.collect()
        assert jax_cache_entries() > entries0
    finally:
        wd.close()


def test_close_is_idempotent_and_no_events_after():
    wd, reg, _ = _watchdog()
    active = wd.active
    wd.close()
    wd.close()
    assert not wd.active
    if active:
        c0 = dict(wd.counts)
        jax.jit(_fresh_fn())(np.ones((7,), np.float32)) \
            .block_until_ready()
        assert wd.counts == c0


# -- serving steady state ----------------------------------------------------

def test_paged_engine_steady_state_zero_recompiles():
    import paddle_tpu as paddle
    from paddle_tpu.serving import PagedContinuousBatchingEngine
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    reg = MetricRegistry()
    prev = set_default_registry(reg)
    try:
        cfg = GPTConfig(vocab_size=211, hidden_size=64, num_layers=2,
                        num_heads=4, max_position_embeddings=128,
                        dropout=0.0)
        paddle.seed(7)
        m = GPTForCausalLM(cfg)
        m.eval()
        eng = PagedContinuousBatchingEngine(m, num_seqs=4, max_len=48,
                                            page_size=8, prefill_chunk=8,
                                            decode_block=2)
        assert eng.perf.registry is reg
        assert not eng.perf.armed
        rng = np.random.RandomState(0)
        prompts = [[int(t) for t in rng.randint(0, 211, n)]
                   for n in (4, 7, 5, 9, 6, 8)]
        for p in prompts:
            eng.add_request(p, max_new_tokens=8)
        eng.run()
        # every program traced -> the engine armed itself mid-run...
        assert eng.perf.armed
        assert 'steady state' in eng.perf.warmup_label
        # ...and the burst stayed retrace-free
        assert eng.perf.recompiles == 0
        assert reg.get('perf_recompiles_total').value() == 0.0
        assert eng.compiled_sizes() == {'prefill': 1, 'decode': 1,
                                        'verify': 0}
        # the timeline saw the decode bursts, split into real phases
        assert eng.timeline.steps > 0
        assert float(reg.get('perf_steps_total').value()) == \
            eng.timeline.steps
        summary = eng.timeline.summary()
        assert summary['host_dispatch']['count'] > 0
        assert summary['device_block']['count'] > 0
        # cost model over the stashed decode args: flat trace counts
        # (the lowering must hit the jaxpr cache, not retrace)
        est = eng.perf_estimate(bursts=eng.timeline.steps,
                                wall_seconds=1.0)
        assert est is not None
        assert est['flops'] > 0
        assert est['roofline_bound'] in ('compute', 'bandwidth')
        assert est['compile_s_warm'] >= 0.0
        assert 'mfu_est' in est
        assert eng.compiled_sizes()['decode'] == 1   # still 1: no retrace
        assert eng.perf.recompiles == 0
        eng.shutdown()
        assert not eng.perf.active
    finally:
        set_default_registry(prev)


def test_spec_engine_perf_estimate_prices_the_verify_program():
    """Under speculation the plain decode program never dispatches; the
    cost model must price the verify forward instead of returning
    None."""
    import paddle_tpu as paddle
    from paddle_tpu.serving import PagedContinuousBatchingEngine
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=211, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128,
                    dropout=0.0)
    paddle.seed(7)
    m = GPTForCausalLM(cfg)
    m.eval()
    eng = PagedContinuousBatchingEngine(m, num_seqs=2, max_len=48,
                                        page_size=8, prefill_chunk=8,
                                        decode_block=2, spec_k=3)
    try:
        eng.generate([[1, 2, 3, 4], [5, 6, 7]], max_new_tokens=6)
        assert eng._decode_args is None          # decode never ran
        est = eng.perf_estimate(bursts=eng.timeline.steps,
                                wall_seconds=0.5)
        assert est is not None
        assert est['flops'] > 0
        assert est['roofline_bound'] in ('compute', 'bandwidth')
        assert 'mfu_est' in est
        assert eng.compiled_sizes()['verify'] == 1   # no retrace
    finally:
        eng.shutdown()


def test_engine_rebind_perf_moves_registry_and_owner():
    import paddle_tpu as paddle
    from paddle_tpu.serving import ContinuousBatchingEngine
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=211, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128,
                    dropout=0.0)
    paddle.seed(7)
    m = GPTForCausalLM(cfg)
    m.eval()
    eng = ContinuousBatchingEngine(m, num_slots=2, max_len=32,
                                   prefill_chunk=8, decode_block=2)
    try:
        old_wd = eng.perf
        reg = MetricRegistry()
        eng.rebind_perf(reg)
        assert not old_wd.active          # old listener unregistered
        assert eng.perf.registry is reg
        assert eng.timeline.registry is reg
        assert eng.perf.owner is eng
        assert not eng.perf.armed
    finally:
        eng.shutdown()


# -- step timeline -----------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def test_timeline_phase_sum_and_other_remainder():
    clock = FakeClock()
    reg = MetricRegistry()
    tl = StepTimeline(registry=reg, tracer=Tracer(registry=reg),
                      clock=clock)
    with tl.phase('data_wait'):
        clock.tick(0.25)
    with tl.phase('host_dispatch'):
        clock.tick(0.05)
    with tl.phase('device_block'):
        clock.tick(0.50)
    out = tl.end_step(wall_seconds=1.0)
    assert out['data_wait'] == pytest.approx(0.25)
    assert out['host_dispatch'] == pytest.approx(0.05)
    assert out['device_block'] == pytest.approx(0.50)
    assert out['other'] == pytest.approx(0.20)       # wall - phases
    assert out['total'] == pytest.approx(1.0)
    assert sum(out[p] for p in PHASES) == pytest.approx(out['total'])
    assert tl.steps == 1
    # the histograms saw exactly these observations
    count, total = reg.get('perf_step_phase_seconds') \
        .labels('device_block').value()
    assert count == 1 and total == pytest.approx(0.50)
    with pytest.raises(ValueError):
        tl.record('warp_drive', 1.0)
    assert tl.end_step() is None                     # nothing recorded


def test_timeline_straggler_detection_and_percentiles():
    clock = FakeClock()
    reg = MetricRegistry()
    tl = StepTimeline(registry=reg, tracer=Tracer(registry=reg),
                      clock=clock, straggler_factor=2.0, min_history=8)
    for _ in range(8):
        with tl.phase('device_block'):
            clock.tick(0.1)
        assert not tl.end_step()['straggler']
    assert tl.percentile(50) == pytest.approx(0.1)
    # 3x the median: flagged, counted, and visible in the registry
    with tl.phase('device_block'):
        clock.tick(0.3)
    assert tl.end_step()['straggler']
    assert tl.stragglers == 1
    assert reg.get('perf_stragglers_total').value() == 1.0
    # discard() drops a dangling partial step (epoch-end data_wait)
    with tl.phase('data_wait'):
        clock.tick(5.0)
    tl.discard()
    assert tl.end_step() is None
    assert tl.steps == 9


def test_timeline_disabled_path_records_nothing_and_stays_cheap():
    tl = StepTimeline(registry=MetricRegistry())
    tl.enabled = False
    with tl.phase('device_block'):
        pass
    tl.record('device_block', 1.0)
    assert tl.end_step(wall_seconds=9.9) is None
    assert tl.steps == 0
    # generous bound: 20k disabled phase entries must be trivially fast
    t0 = time.monotonic()
    for _ in range(20000):
        with tl.phase('host_dispatch'):
            pass
    assert time.monotonic() - t0 < 2.0


# -- cost model --------------------------------------------------------------

def test_cost_model_exact_flops_on_known_matmul():
    a = jnp.zeros((64, 128), jnp.float32)
    b = jnp.zeros((128, 32), jnp.float32)
    est = costmodel.estimate(lambda x, y: x @ y, args=(a, b),
                             step_seconds=0.001)
    if est is None:
        pytest.skip('backend exposes no cost analysis')
    assert est['flops'] == 2.0 * 64 * 128 * 32       # 524288 exactly
    assert est['bytes_accessed'] > 0
    assert est['arithmetic_intensity'] == pytest.approx(
        est['flops'] / est['bytes_accessed'])
    assert est['roofline_bound'] in ('compute', 'bandwidth')
    assert est['ideal_step_s'] > 0
    assert est['mfu_est'] == pytest.approx(
        est['flops'] / 0.001 / est['peak_flops'])
    assert 0 < est['roofline_frac'] <= 1.0 or est['roofline_frac'] >= 0


def test_cost_model_roofline_classification():
    # intensity 1000 on a ridge of 197e12/819e9 ~ 240 -> compute-bound
    r = costmodel.roofline(1000.0e9, 1.0e9, platform='tpu')
    assert r['roofline_bound'] == 'compute'
    assert r['ridge_intensity'] == pytest.approx(197e12 / 819e9)
    # intensity 1 -> far under any ridge -> bandwidth-bound
    r = costmodel.roofline(1.0e9, 1.0e9, platform='tpu')
    assert r['roofline_bound'] == 'bandwidth'
    assert r['ideal_step_s'] == pytest.approx(1.0e9 / 819e9)
    # overrides beat the table
    r = costmodel.roofline(10.0, 1.0, platform='anything',
                           peak_flops=20.0, peak_bandwidth=1.0)
    assert r['ideal_step_s'] == pytest.approx(1.0)


def test_cost_model_record_publishes_gauges():
    reg = MetricRegistry()
    est = {'mfu_est': 0.37, 'arithmetic_intensity': 120.5,
           'roofline_bound': 'bandwidth'}
    costmodel.record(est, registry=reg)
    assert reg.get('perf_mfu_est').value() == pytest.approx(0.37)
    assert reg.get('perf_arithmetic_intensity').value() == \
        pytest.approx(120.5)
    assert reg.get('perf_roofline_bound').value() == 0.0
    assert costmodel.record(None, registry=reg) is None


# -- Model.fit / summary_perf wiring -----------------------------------------

def _tiny_model():
    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 4), nn.Linear(4, 1))
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.SGD(learning_rate=0.01,
                                       parameters=net.parameters()),
        loss=nn.MSELoss())
    return model


def test_model_fit_wires_timeline_and_watchdog():
    from paddle_tpu.io import Dataset

    class DS(Dataset):
        def __len__(self):
            return 12

        def __getitem__(self, i):
            return (np.full((8,), i, np.float32),
                    np.zeros((1,), np.float32))

    reg = MetricRegistry()
    prev = set_default_registry(reg)
    try:
        model = _tiny_model()
        model.fit(DS(), batch_size=4, epochs=2, verbose=0, shuffle=False)
        # the fit loop finalized one timeline step per batch
        steps = reg.get('perf_steps_total').value()
        assert steps == 6                            # 3 batches x 2 epochs
        count, _ = reg.get('perf_step_phase_seconds') \
            .labels('data_wait').value()
        assert count == 6
        # epoch 1 re-ran the SAME shapes: zero post-warmup recompiles
        assert reg.get('perf_recompiles_total').value() == 0.0
        assert model._perf_timeline is None          # cleaned up
    finally:
        set_default_registry(prev)


def test_model_summary_perf_reports_cost_model():
    import paddle_tpu as paddle
    reg = MetricRegistry()
    model = _tiny_model()
    x = paddle.to_tensor(np.random.rand(4, 8).astype('float32'))
    y = paddle.to_tensor(np.random.rand(4, 1).astype('float32'))
    est = model.summary_perf([x], [y], step_seconds=0.01, registry=reg)
    if est is None:
        pytest.skip('backend exposes no cost analysis')
    assert est['flops'] > 0
    assert est['roofline_bound'] in ('compute', 'bandwidth')
    assert est['mfu_est'] > 0
    assert reg.get('perf_mfu_est').value() == pytest.approx(
        est['mfu_est'])


# -- schema + tooling --------------------------------------------------------

def test_perf_families_are_in_the_committed_baseline():
    with open(os.path.join(REPO, 'tools',
                           'metrics_schema_baseline.json')) as fh:
        baseline = json.load(fh)
    for kind, name, _doc, labels in PERF_FAMILIES:
        assert name in baseline, name
        assert baseline[name]['type'] == kind
        assert tuple(baseline[name].get('labels', [])) == labels
    assert len(COMPILE_EVENTS) == 3


def test_perf_report_cli_joins_snapshot_flight_and_bench(tmp_path):
    from paddle_tpu.monitor import telemetry
    # a snapshot with live perf counters folded in
    reg = MetricRegistry()
    wd = CompileWatchdog(registry=reg,
                         tracer=Tracer(registry=reg))
    wd.enabled = False                      # no live listening needed
    wd._on_event('/jax/core/compile/backend_compile_duration', 1.25)
    tl = StepTimeline(registry=reg, tracer=Tracer(registry=reg),
                      clock=FakeClock())
    tl.record('device_block', 0.5)
    tl.end_step()
    wd.close()
    treg = telemetry.dryrun_registry(0.5, 1.0, batch=4, registry=reg)
    snap = tmp_path / 'snap.txt'
    snap.write_text(telemetry.snapshot_line(treg, 8, '[perf]') + '\n')
    # a flight dump carrying one recompile span
    rec = FlightRecorder(dump_dir=str(tmp_path), cooldown=0.0,
                         registry=reg)
    rec.record({'name': 'perf.recompile', 'start': 1.0, 'duration': 0.2,
                'tags': {'duration_s': 0.2, 'callsite': 'x.py:1:f',
                         'signature': 'float32[2,2]'}})
    rec.dump('recompile')
    # a bench row carrying the perf fields
    bench_path = tmp_path / 'cap.jsonl'
    bench_path.write_text(json.dumps(
        {'metric': 'serving_cb_tokens_per_sec', 'value': 100.0,
         'compile_s_cold': 3.2, 'compile_s_warm': 0.1, 'recompiles': 0,
         'mfu_est': 0.21, 'roofline_bound': 'bandwidth'}) + '\n')

    import importlib.util
    spec = importlib.util.spec_from_file_location(
        '_perf_report', os.path.join(REPO, 'tools', 'perf_report.py'))
    pr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pr)
    lines = pr.report(snap_text=snap.read_text(),
                      flight_dir=str(tmp_path),
                      bench_paths=[str(bench_path)])
    text = '\n'.join(lines)
    assert 'config perf' in text
    assert 'compiles[compile]: 1 (mean 1.250s)' in text
    assert 'phase device_block' in text
    assert 'recompile 0.200s at x.py:1:f' in text
    assert 'signature: float32[2,2]' in text
    assert 'serving_cb_tokens_per_sec' in text and '0.21' in text
