"""Multi-replica serving gateway tests (paddle_tpu/serving/gateway/).

The load-bearing assertions from the gateway's contract:
  1. routing/failover/drain never buy availability with output drift —
     whatever the pool does internally, delivered tokens are IDENTICAL
     to a single engine's greedy run (seeded determinism + the
     delivered-token ledger give exactly-once delivery);
  2. chaos-oracle failover (the test_resilience.py discipline): a
     replica partitioned mid-burst yields EXACTLY as many
     gateway_failover_total increments as it had in-flight non-finished
     requests, and 100% of requests still complete;
  3. the autoscaler is a pure function of (clock, observations) —
     sustained burn scales up, sustained idle scales down, flapping and
     cooldown suppress everything else.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.monitor.registry import MetricRegistry
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                PagedContinuousBatchingEngine,
                                ServingGateway)
from paddle_tpu.serving.gateway import (AutoscalePolicy, LeastLoadedRouter,
                                        RoundRobinRouter, slo_burn_rate)
from paddle_tpu.serving.gateway.replica import DEAD, DRAINING, STOPPED
from paddle_tpu.testing import chaos
from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

MNT = 8          # max_new_tokens everywhere: keeps the suite fast


@pytest.fixture(scope='module')
def model():
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=211, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope='module')
def prompts():
    rng = np.random.RandomState(3)
    return [[int(t) for t in rng.randint(0, 211, n)]
            for n in (3, 17, 7, 12, 5, 21, 9, 4, 14, 6)]


@pytest.fixture(scope='module')
def reference(model, prompts):
    """Single-engine greedy outputs — the parity oracle."""
    eng = ContinuousBatchingEngine(model, num_slots=2, max_len=32,
                                   prefill_chunk=8, decode_block=2)
    return eng.generate(prompts, max_new_tokens=MNT)


def _slot_factory(model):
    return lambda: ContinuousBatchingEngine(
        model, num_slots=2, max_len=32, prefill_chunk=8, decode_block=2)


def _paged_factory(model):
    return lambda: PagedContinuousBatchingEngine(
        model, num_seqs=2, max_len=32, page_size=8, prefill_chunk=8,
        decode_block=2)


def _gw(model, factory=None, **kw):
    kw.setdefault('registry', MetricRegistry())
    return ServingGateway(factory or _slot_factory(model), **kw)


def _counter(gw, name, labels=None):
    fam = gw.registry.get(name)
    if labels is None:
        return fam.value()
    return fam.labels(*labels).value()


# ---- routing ----------------------------------------------------------


def test_least_loaded_spreads_and_parity(model, prompts, reference):
    """Sync drive: the router spreads a burst across both replicas on
    their live queue/occupancy gauges, and delivered tokens match the
    single-engine run exactly."""
    gw = _gw(model, replicas=2)
    out = gw.generate(prompts, max_new_tokens=MNT)
    assert out == reference
    routed = [_counter(gw, 'gateway_route_total', (str(i),))
              for i in range(2)]
    assert sum(routed) == len(prompts)
    assert all(v > 0 for v in routed), routed
    assert _counter(gw, 'gateway_requests_completed_total') == len(prompts)
    assert _counter(gw, 'gateway_failover_total') == 0
    assert gw.report()['pending'] == 0


def test_round_robin_router(model, prompts, reference):
    gw = _gw(model, replicas=2, router=RoundRobinRouter())
    out = gw.generate(prompts[:4], max_new_tokens=MNT)
    assert out == reference[:4]
    routed = [_counter(gw, 'gateway_route_total', (str(i),))
              for i in range(2)]
    assert routed == [2.0, 2.0]


def test_paged_replicas_parity(model, prompts, reference):
    """The gateway fronts paged engines through the same contract."""
    gw = _gw(model, factory=_paged_factory(model), replicas=2)
    assert gw.generate(prompts[:6], max_new_tokens=MNT) == reference[:6]


def test_inadmissible_request_raises_not_failover(model):
    """The engines' front-door guard propagates to the submit() caller;
    it must never be mistaken for a transport failure."""
    gw = _gw(model, replicas=2)
    with pytest.raises(ValueError, match='max_len'):
        gw.submit(list(range(1, 30)), max_new_tokens=MNT)  # 29+8-1 > 32
    assert _counter(gw, 'gateway_requests_total') == 0
    assert _counter(gw, 'gateway_failover_total') == 0
    assert all(r.routable() for r in gw.pool)


# ---- failover ---------------------------------------------------------


@pytest.mark.chaos
def test_partition_failover_exact_oracle(model, prompts, reference):
    """THE acceptance test: a Poisson-arrival burst over 2 replicas,
    one partitioned mid-burst. Every request completes, outputs are
    token-identical to the single-engine run, and the failover counter
    equals EXACTLY the partitioned replica's in-flight non-finished
    count at the moment of loss (chaos-oracle style)."""
    gw = _gw(model, replicas=2)
    # seeded Poisson arrival process, quantised to engine steps
    gaps = np.random.RandomState(5).exponential(1.0, size=len(prompts))
    arrival_step = np.floor(np.cumsum(gaps) / 1.5).astype(int)
    kill_at = len(prompts) // 2
    reqs, expected, fault = [], None, None
    ctx = None
    try:
        i = k = 0
        while i < len(prompts) or any(not r.done for r in reqs):
            while i < len(prompts) and arrival_step[i] <= k:
                if i == kill_at:
                    ctx = chaos.partition(gw.pool[1].endpoint)
                    fault = ctx.__enter__()
                    # the oracle: in-flight non-finished on replica 1
                    # the instant the partition lands
                    expected = len([g for g in gw.pool[1].assigned
                                    if len(g.tokens) < MNT])
                reqs.append(gw.submit(prompts[i], max_new_tokens=MNT))
                i += 1
            gw.step()
            k += 1
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)

    assert expected is not None and expected > 0
    assert all(r.done for r in reqs)                    # 100% complete
    assert [r.tokens for r in reqs] == reference        # exact parity
    assert _counter(gw, 'gateway_failover_total') == expected
    # every failover is a re-placement in some request's history
    assert sum(len(r.replica_history) - 1 for r in reqs) == expected
    assert fault.fired >= 1
    assert len(gw.failover_log) == 1
    assert gw.failover_log[0]['replica'] == 1
    assert len(gw.failover_log[0]['requests']) == expected
    # the dead replica is fenced: breaker open, never routable again
    rep = gw.pool[1]
    assert rep.state == DEAD
    assert not rep.routable()
    assert gw.registry.get('gateway_replica_state').labels('1').value() \
        == 2.0
    assert _counter(gw, 'gateway_replicas') == 1
    # no chaos leaked into the next test
    assert chaos.active_faults() == 0


@pytest.mark.chaos
def test_partition_at_submission_retries_elsewhere(model, prompts,
                                                   reference):
    """A partition hit at submit time (no in-flight work yet) is a
    retry, not a failover: the walk places the request on the live
    replica in the same call."""
    gw = _gw(model, replicas=2)
    with chaos.partition(gw.pool[1].endpoint):
        reqs = [gw.submit(p, max_new_tokens=MNT) for p in prompts[:4]]
        gw.run()
    assert [r.tokens for r in reqs] == reference[:4]
    assert _counter(gw, 'gateway_retries_total') == 1.0
    assert _counter(gw, 'gateway_failover_total') == 0
    assert all(r.replica_history == [0] for r in reqs)
    assert gw.pool[1].state == DEAD


def test_kill_replica_threaded_parity(model, prompts, reference):
    """Driver-thread mode: kill a replica while its driver is mid-
    flight; every request completes with exact parity."""
    gw = _gw(model, replicas=2).start()
    try:
        reqs = [gw.submit(p, max_new_tokens=MNT) for p in prompts]
        gw.kill_replica(1)
        for r in reqs:
            assert r.wait(120), r
        assert [r.tokens for r in reqs] == reference
        assert len(gw.failover_log) == 1
        assert gw.failover_log[0]['replica'] == 1
    finally:
        gw.shutdown()
    assert gw.report()['completed'] == len(prompts)


# ---- drain ------------------------------------------------------------


def test_drain_finishes_in_flight_without_failover(model, prompts,
                                                   reference):
    """Graceful drain: the draining replica stops taking NEW work but
    its in-flight requests finish in place (no re-admission)."""
    gw = _gw(model, replicas=2)
    first = [gw.submit(p, max_new_tokens=MNT) for p in prompts[:4]]
    gw.step()
    drained = gw.drain_replica(1)
    assert drained.state == DRAINING
    assert not drained.ready()
    later = [gw.submit(p, max_new_tokens=MNT) for p in prompts[4:]]
    gw.run()
    assert [r.tokens for r in first + later] == reference
    assert _counter(gw, 'gateway_failover_total') == 0
    # nothing submitted after the drain landed on replica 1
    assert all(r.replica_history == [0] for r in later)
    # the drained replica ran dry and stopped
    assert drained.state == STOPPED


def test_replica_readyz_flips_on_drain(model):
    """Satellite integration: a replica's MetricsServer serves 200 on
    /readyz while READY and 503 once draining — with /healthz at 200
    throughout (drain must not look like death to the kubelet)."""
    import json
    import urllib.error
    import urllib.request
    gw = _gw(model, replicas=1)
    rep = gw.pool[0]
    with rep.metrics_server() as srv:
        ok = urllib.request.urlopen(srv.url + '/readyz', timeout=5)
        assert ok.status == 200
        assert json.loads(ok.read().decode())['status'] == 'ready'
        gw.drain_replica(0)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + '/readyz', timeout=5)
        assert ei.value.code == 503
        assert json.loads(ei.value.read().decode())['status'] == 'draining'
        assert urllib.request.urlopen(srv.url + '/healthz',
                                      timeout=5).status == 200
        # the replica's own serving gauges are on this scrape endpoint
        body = urllib.request.urlopen(srv.url + '/metrics',
                                      timeout=5).read().decode()
        assert 'serving_queue_depth' in body


def test_gateway_shutdown_drains_all(model, prompts, reference):
    gw = _gw(model, replicas=2).start()
    reqs = [gw.submit(p, max_new_tokens=MNT) for p in prompts[:6]]
    gw.shutdown()
    assert all(r.done for r in reqs)
    assert [r.tokens for r in reqs] == reference[:6]
    assert all(r.state == STOPPED for r in gw.pool)
    with pytest.raises(Exception):
        # drained engines refuse new work end to end
        gw.pool[0].engine.add_request([1, 2], max_new_tokens=2)


def test_streaming_through_gateway(model, prompts, reference):
    gw = _gw(model, replicas=2).start()
    try:
        req = gw.submit(prompts[0], max_new_tokens=MNT, stream=True)
        got = list(req.stream())
    finally:
        gw.shutdown()
    assert got == reference[0]
    assert req.done


# ---- autoscaler: pure policy with an injectable clock -----------------


def test_slo_burn_rate_windows():
    samples = [(0.0, 0.1), (5.0, 0.9), (10.0, 0.9), (15.0, 0.1)]
    assert slo_burn_rate(samples, 15.0, 0.5, 30.0) == 0.5
    assert slo_burn_rate(samples, 15.0, 0.5, 6.0) == \
        pytest.approx(1.0 / 2.0)      # only t=10,15 in window
    assert slo_burn_rate([], 0.0, 0.5, 30.0) == 0.0
    assert slo_burn_rate(samples, 100.0, 0.5, 10.0) == 0.0


def test_policy_sustained_burn_scales_up():
    pol = AutoscalePolicy(slo_ttft_s=0.5, sustain_s=3.0, cooldown_s=10.0)
    assert pol.decide(0.0, 0.9, 0.9, 4, 2).delta == 0    # just started
    assert pol.decide(1.0, 0.9, 0.9, 4, 2).delta == 0
    d = pol.decide(3.0, 0.9, 0.9, 4, 2)
    assert d.delta == +1 and 'burn' in d.reason
    # immediately after acting: sustain restarts, then cooldown holds
    assert pol.decide(4.0, 0.9, 0.9, 4, 3).delta == 0
    d2 = pol.decide(7.0, 0.9, 0.9, 4, 3)
    assert d2.delta == 0 and 'cooling' in d2.reason
    # cooldown elapsed + still burning -> acts again
    assert pol.decide(13.0, 0.9, 0.9, 4, 3).delta == +1


def test_policy_sustained_idle_scales_down_to_min():
    pol = AutoscalePolicy(slo_ttft_s=0.5, min_replicas=1, sustain_s=2.0,
                          cooldown_s=0.0)
    assert pol.decide(0.0, 0.0, 0.0, 0, 2).delta == 0
    d = pol.decide(2.0, 0.0, 0.0, 0, 2)
    assert d.delta == -1 and 'idle' in d.reason
    # at the floor: idle forever never goes below min_replicas
    assert pol.decide(4.0, 0.0, 0.0, 0, 1).delta == 0
    assert pol.decide(9.0, 0.0, 0.0, 0, 1).delta == 0


def test_policy_flapping_suppressed_by_hysteresis():
    """A burn signal that toggles faster than sustain_s never acts; a
    pool oscillating hot/idle around an action is pinned by cooldown."""
    pol = AutoscalePolicy(slo_ttft_s=0.5, sustain_s=3.0, cooldown_s=20.0)
    for t in range(0, 12, 2):
        burn = 0.9 if (t // 2) % 2 == 0 else 0.0   # toggles every 2 s
        assert pol.decide(float(t), burn, 0.5, 1, 2).delta == 0
    # sustained burn finally acts...
    for t in (12.0, 14.0, 15.0):
        d = pol.decide(t, 0.9, 0.9, 4, 2)
    assert d.delta == +1
    # ...then a hard swing to idle within cooldown cannot flap it back
    for t in (16.0, 17.0, 18.0, 19.0, 20.0):
        assert pol.decide(t, 0.0, 0.0, 0, 3).delta == 0
    assert pol.decide(35.0, 0.0, 0.0, 0, 3).delta == -1


def test_policy_respects_max_replicas():
    pol = AutoscalePolicy(slo_ttft_s=0.5, max_replicas=2, sustain_s=0.0,
                          cooldown_s=0.0)
    d = pol.decide(0.0, 1.0, 1.0, 9, 2)
    assert d.delta == 0 and 'max_replicas' in d.reason


def test_policy_validates_bounds():
    with pytest.raises(ValueError, match='min_replicas'):
        AutoscalePolicy(slo_ttft_s=0.5, min_replicas=0)
    with pytest.raises(ValueError, match='min_replicas'):
        AutoscalePolicy(slo_ttft_s=0.5, min_replicas=4, max_replicas=2)


def test_autoscale_tick_grows_and_drains_pool(model):
    """Gateway integration on a fake clock: sustained burn builds a new
    replica from the factory; sustained idle drains the least-loaded
    one (never kills it)."""
    clock = {'t': 0.0}
    gw = _gw(model, replicas=1, clock=lambda: clock['t'],
             autoscaler=AutoscalePolicy(slo_ttft_s=0.5, sustain_s=2.0,
                                        cooldown_s=5.0, window_s=60.0,
                                        max_replicas=2))
    # synthetic TTFT samples breaching the SLO
    for t in (1.0, 2.0, 3.0):
        gw._ttfts.append((t, 2.0))
    clock['t'] = 4.0
    assert gw.autoscale_tick().delta == 0        # burn timer starts
    clock['t'] = 6.5
    d = gw.autoscale_tick()
    assert d.delta == +1
    assert len(gw.pool) == 2
    assert gw.pool[1].routable()                 # new replica takes work
    assert gw.registry.get('gateway_scale_events_total') \
        .labels('up').value() == 1.0
    assert _counter(gw, 'gateway_slo_burn_rate') == 1.0
    # burn clears, samples age out of the window -> sustained idle
    gw._ttfts.clear()
    clock['t'] = 20.0
    assert gw.autoscale_tick().delta == 0        # idle timer starts
    clock['t'] = 23.0
    d = gw.autoscale_tick()
    assert d.delta == -1
    assert gw.registry.get('gateway_scale_events_total') \
        .labels('down').value() == 1.0
    states = sorted(r.state for r in gw.pool)
    assert DRAINING in states                    # drained, not killed
    gw.run()                                     # runs dry -> stopped
    assert sorted(r.state for r in gw.pool)[-1] == STOPPED


# ---- threaded soak ----------------------------------------------------


def test_threaded_concurrent_submitters(model, prompts, reference):
    """Several caller threads submit concurrently against driver
    threads; everything completes with exact parity."""
    gw = _gw(model, replicas=2).start()
    results = {}
    try:
        def client(base):
            for j, p in enumerate(prompts[base::2]):
                r = gw.submit(p, max_new_tokens=MNT)
                assert r.wait(120)
                results[base + 2 * j] = r.tokens
        ts = [threading.Thread(target=client, args=(b,)) for b in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(180)
        assert not any(t.is_alive() for t in ts)
    finally:
        gw.shutdown()
    assert [results[i] for i in range(len(prompts))] == reference


@pytest.mark.slow
def test_predictor_decode_gateway(model, prompts, tmp_path):
    """The fleet front door reached the inference API: a jit.save'd
    causal LM round-trips into a gateway whose pooled output matches
    the live model's generate()."""
    path = str(tmp_path / 'gpt_lm')
    paddle.jit.save(model, path)
    from paddle_tpu import inference
    pred = inference.create_predictor(inference.Config(path))
    gw = pred.decode_gateway(replicas=2, registry=MetricRegistry(),
                             num_slots=2, max_len=64, prefill_chunk=8,
                             decode_block=4)
    got = gw.generate(prompts[:3], max_new_tokens=6)
    expect = [[int(t) for t in model.generate(
        paddle.to_tensor([p]), max_new_tokens=6).numpy()[0][len(p):]]
        for p in prompts[:3]]
    assert got == expect
    assert len(gw.pool) == 2
