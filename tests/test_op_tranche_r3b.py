"""Round-3 op tranche: fluid-era losses/CTR ops, CRF, beam-search
backtrace, segment pools, max-unpool, temporal shift — each checked
against an independent numpy reference (reference ops cited per-op in
the implementations)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import static


def test_rank_loss():
    rng = np.random.RandomState(0)
    t = rng.randint(0, 2, (8, 1)).astype(np.float32)
    left = rng.randn(8, 1).astype(np.float32)
    right = rng.randn(8, 1).astype(np.float32)
    got = static.nn.rank_loss(paddle.to_tensor(t), paddle.to_tensor(left),
                              paddle.to_tensor(right)).numpy()
    o = left - right
    want = np.log1p(np.exp(-np.abs(o))) + np.maximum(o, 0) - t * o
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_bpr_loss():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 6).astype(np.float32)
    y = rng.randint(0, 6, (4, 1))
    got = static.nn.bpr_loss(paddle.to_tensor(x),
                             paddle.to_tensor(y)).numpy()
    want = np.zeros((4, 1), np.float32)
    for i in range(4):
        acc = []
        for j in range(6):
            if j == y[i, 0]:
                continue
            d = x[i, y[i, 0]] - x[i, j]
            acc.append(np.log(1.0 / (1.0 + np.exp(-d))))
        want[i, 0] = -np.mean(acc)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_center_loss_updates_centers():
    rng = np.random.RandomState(2)
    x = rng.randn(6, 4).astype(np.float32)
    y = np.array([0, 1, 0, 2, 1, 0])
    loss, centers = static.nn.center_loss(
        paddle.to_tensor(x), paddle.to_tensor(y), num_classes=3, alpha=0.5)
    want = 0.5 * (x ** 2).sum(1, keepdims=True)  # centers start at zero
    np.testing.assert_allclose(loss.numpy(), want, rtol=1e-5)
    c = centers.numpy()
    # class 0 has 3 members; update = -alpha * sum(0 - x_i) / (1 + 3)
    np.testing.assert_allclose(
        c[0], 0.5 * x[y == 0].sum(0) / 4.0, rtol=1e-5)
    assert np.abs(c).sum() > 0


def test_cvm():
    rng = np.random.RandomState(3)
    x = rng.rand(5, 6).astype(np.float32)
    show_click = np.abs(rng.rand(5, 2).astype(np.float32)) * 10
    got = static.nn.cvm(paddle.to_tensor(x),
                        paddle.to_tensor(show_click), use_cvm=True).numpy()
    np.testing.assert_allclose(got[:, 0], np.log(show_click[:, 0] + 1),
                               rtol=1e-5)
    np.testing.assert_allclose(
        got[:, 1], np.log(show_click[:, 1] + 1) - np.log(show_click[:, 0] + 1),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got[:, 2:], x[:, 2:])
    stripped = static.nn.cvm(paddle.to_tensor(x),
                             paddle.to_tensor(show_click),
                             use_cvm=False).numpy()
    np.testing.assert_allclose(stripped, x[:, 2:])


def test_pad_constant_like_and_im2sequence():
    x = paddle.to_tensor(np.zeros((3, 5), np.float32))
    y = paddle.to_tensor(np.ones((2, 3), np.float32))
    got = static.nn.pad_constant_like(x, y, pad_value=7.0).numpy()
    assert got.shape == (3, 5)
    assert got[2, 4] == 7.0 and got[1, 2] == 1.0

    img = paddle.to_tensor(
        np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    seq = static.nn.im2sequence(img, filter_size=2, stride=2).numpy()
    assert seq.shape == (4, 4)
    np.testing.assert_allclose(seq[0], [0, 1, 4, 5])
    np.testing.assert_allclose(seq[3], [10, 11, 14, 15])


def test_row_conv_shapes_and_lookahead():
    x = paddle.to_tensor(np.eye(4, dtype=np.float32).reshape(1, 4, 4))
    out = static.nn.row_conv(x, future_context_size=1)
    got = out.numpy()[0]
    # uniform weights 1/2: out[t] = (x[t] + x[t+1]) / 2
    want = 0.5 * (np.eye(4) + np.vstack([np.eye(4)[1:], np.zeros(4)]))
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=1e-5)


def test_sample_logits():
    rng = np.random.RandomState(4)
    x = rng.randn(3, 50).astype(np.float32)
    y = rng.randint(0, 50, (3, 1))
    out, lbl = static.nn.sample_logits(paddle.to_tensor(x),
                                       paddle.to_tensor(y), num_samples=10)
    assert tuple(out.shape) == (3, 11)
    assert lbl.numpy().tolist() == [[0], [0], [0]]
    k = 50.0
    q = np.log((y + 2.0) / (y + 1.0)) / np.log(k + 1.0)
    want_true = np.take_along_axis(x, y, axis=1) - np.log(q)
    np.testing.assert_allclose(out.numpy()[:, :1], want_true, rtol=1e-4)


def _np_crf_nll(em, trans, lab, lens):
    b, l, k = em.shape
    start, stop, tr = trans[0], trans[1], trans[2:]
    out = np.zeros((b, 1), np.float64)
    for i in range(b):
        n = lens[i]
        # brute-force logZ over all paths
        paths = [[t] for t in range(k)]
        for _ in range(n - 1):
            paths = [p + [t] for p in paths for t in range(k)]
        scores = []
        for p in paths:
            s = start[p[0]] + stop[p[-1]] + sum(em[i, t, p[t]]
                                                for t in range(n))
            s += sum(tr[p[t], p[t + 1]] for t in range(n - 1))
            scores.append(s)
        logz = np.log(np.sum(np.exp(np.asarray(scores) -
                                    max(scores)))) + max(scores)
        g = lab[i, :n]
        gold = start[g[0]] + stop[g[-1]] + sum(em[i, t, g[t]]
                                               for t in range(n))
        gold += sum(tr[g[t], g[t + 1]] for t in range(n - 1))
        out[i, 0] = logz - gold
    return out


def test_linear_chain_crf_and_decoding():
    rng = np.random.RandomState(5)
    b, l, k = 3, 4, 3
    em = rng.randn(b, l, k).astype(np.float32)
    trans = rng.randn(k + 2, k).astype(np.float32) * 0.3
    lab = rng.randint(0, k, (b, l))
    lens = np.array([4, 3, 2], np.int32)

    cost, _t = static.nn.linear_chain_crf(
        paddle.to_tensor(em), paddle.to_tensor(lab),
        transition=paddle.to_tensor(trans),
        length=paddle.to_tensor(lens))
    want = _np_crf_nll(em.astype(np.float64), trans.astype(np.float64),
                       lab, lens)
    np.testing.assert_allclose(cost.numpy(), want, rtol=1e-4, atol=1e-4)

    path = static.nn.crf_decoding(paddle.to_tensor(em),
                                  paddle.to_tensor(trans),
                                  length=paddle.to_tensor(lens)).numpy()
    # brute-force viterbi per sequence
    start, stop, tr = trans[0], trans[1], trans[2:]
    for i in range(b):
        n = lens[i]
        best, best_s = None, -np.inf
        paths = [[t] for t in range(k)]
        for _ in range(n - 1):
            paths = [p + [t] for p in paths for t in range(k)]
        for p in paths:
            s = start[p[0]] + stop[p[-1]] + sum(em[i, t, p[t]]
                                                for t in range(n))
            s += sum(tr[p[t], p[t + 1]] for t in range(n - 1))
            if s > best_s:
                best_s, best = s, p
        assert path[i, :n].tolist() == best
        assert (path[i, n:] == 0).all()


def test_gather_tree():
    # beam=2 toy: reference semantics from gather_tree_op.cc unit test
    ids = np.array([[[2, 2]], [[6, 1]], [[3, 9]]], np.int64)  # [T=3,B=1,W=2]
    parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], np.int64)
    got = F.gather_tree(paddle.to_tensor(ids),
                        paddle.to_tensor(parents)).numpy()
    # walk: final step tokens [3, 9]; parents [0,1] -> step1 tokens
    # slot0<-parent0: 6 ... slot1<-parent1: 1; then their parents [1, 0]
    want = np.array([[[2, 2]], [[6, 1]], [[3, 9]]], np.int64)
    assert got.shape == (3, 1, 2)
    np.testing.assert_array_equal(got[2], want[2])
    np.testing.assert_array_equal(got[1], [[6, 1]])
    np.testing.assert_array_equal(got[0], [[2, 2]])


def test_gather_tree_relinks_crossed_beams():
    # crossed parents force re-linking: slot 0's history comes from slot 1
    ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], np.int64)
    parents = np.array([[[0, 0]], [[1, 1]], [[1, 0]]], np.int64)
    got = F.gather_tree(paddle.to_tensor(ids),
                        paddle.to_tensor(parents)).numpy()
    # slot0 final token 5, parent 1 -> time1 token 4, its parent 1 -> 2
    np.testing.assert_array_equal(got[:, 0, 0], [2, 4, 5])
    # slot1 final token 6, parent 0 -> time1 token 3, its parent 1 -> 2
    np.testing.assert_array_equal(got[:, 0, 1], [2, 3, 6])


def test_segment_pools():
    data = np.array([[1., 2.], [3., 4.], [10., 20.]], np.float32)
    ids = np.array([0, 0, 1])
    d, i = paddle.to_tensor(data), paddle.to_tensor(ids)
    np.testing.assert_allclose(paddle.incubate.segment_sum(d, i).numpy(),
                               [[4., 6.], [10., 20.]])
    np.testing.assert_allclose(paddle.incubate.segment_mean(d, i).numpy(),
                               [[2., 3.], [10., 20.]])
    np.testing.assert_allclose(paddle.incubate.segment_max(d, i).numpy(),
                               [[3., 4.], [10., 20.]])
    np.testing.assert_allclose(paddle.incubate.segment_min(d, i).numpy(),
                               [[1., 2.], [10., 20.]])


def test_max_pool_mask_and_unpool_roundtrip():
    rng = np.random.RandomState(6)
    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    xt = paddle.to_tensor(x)
    out, mask = F.max_pool2d(xt, kernel_size=2, stride=2, return_mask=True)
    # mask must hold the true argmax flat indices
    for n in range(2):
        for c in range(3):
            for oh in range(3):
                for ow in range(3):
                    win = x[n, c, oh * 2:oh * 2 + 2, ow * 2:ow * 2 + 2]
                    fi = int(mask.numpy()[n, c, oh, ow])
                    assert x[n, c, fi // 6, fi % 6] == win.max()
    un = F.max_unpool2d(out, mask, kernel_size=2, stride=2)
    assert tuple(un.shape) == (2, 3, 6, 6)
    # unpooled tensor holds each max at its original position, zeros else
    got = un.numpy()
    assert np.count_nonzero(got) <= 2 * 3 * 9
    np.testing.assert_allclose(got.max(axis=(2, 3)),
                               out.numpy().max(axis=(2, 3)))

    layer = paddle.nn.MaxUnPool2D(kernel_size=2, stride=2)
    np.testing.assert_allclose(layer(out, mask).numpy(), got)


def test_temporal_shift():
    x = np.arange(2 * 4 * 4 * 1 * 1, dtype=np.float32).reshape(8, 4, 1, 1)
    got = F.temporal_shift(paddle.to_tensor(x), seg_num=4,
                           shift_ratio=0.25).numpy()
    v = x.reshape(2, 4, 4, 1, 1)
    want = np.zeros_like(v)
    # reference semantics: channel group 0 reads x[t-1], group 1 reads
    # x[t+1], rest identity (temporal_shift_op.h)
    want[:, 1:, 0:1] = v[:, :-1, 0:1]
    want[:, :-1, 1:2] = v[:, 1:, 1:2]
    want[:, :, 2:] = v[:, :, 2:]
    np.testing.assert_allclose(got, want.reshape(8, 4, 1, 1))


def test_fluid_aliases():
    x = paddle.to_tensor(np.random.RandomState(7)
                         .randn(2, 4, 4, 4).astype(np.float32))
    assert tuple(static.nn.lrn(x).shape) == (2, 4, 4, 4)
    y = static.nn.space_to_depth(x, 2)
    assert tuple(y.shape) == (2, 16, 2, 2)
    r = static.nn.reverse(paddle.to_tensor(
        np.arange(4, dtype=np.float32)), [0])
    np.testing.assert_allclose(r.numpy(), [3, 2, 1, 0])
    a = paddle.to_tensor(np.ones((2, 3), np.float32))
    cs = static.nn.cos_sim(a, a)
    assert tuple(cs.shape) == (2, 1)  # fluid returns [N, 1]
    assert cs.numpy().max() <= 1.0 + 1e-6


def test_crf_grads_flow():
    rng = np.random.RandomState(8)
    em = paddle.to_tensor(rng.randn(2, 3, 4).astype(np.float32),
                          stop_gradient=False)
    trans = paddle.to_tensor((rng.randn(6, 4) * 0.1).astype(np.float32),
                             stop_gradient=False)
    lab = paddle.to_tensor(rng.randint(0, 4, (2, 3)))
    cost, _ = static.nn.linear_chain_crf(em, lab, transition=trans)
    cost.sum().backward()
    assert em.grad is not None and np.isfinite(em.grad.numpy()).all()
    assert trans.grad is not None and np.isfinite(trans.grad.numpy()).all()

    # default transition is a trainable Parameter
    em2 = paddle.to_tensor(rng.randn(2, 3, 4).astype(np.float32),
                           stop_gradient=False)
    cost2, t2 = static.nn.linear_chain_crf(em2, lab)
    assert not t2.stop_gradient
    cost2.sum().backward()
    assert t2.grad is not None

    # crf_decoding(label=...) marks CORRECT tags with 1 (reference
    # crf_decoding_op.h)
    path = static.nn.crf_decoding(em2, t2)
    marks = static.nn.crf_decoding(em2, t2, label=path)
    assert (marks.numpy() == 1).all()
