"""Third OpTest sweep wave: the remaining differentiable nn.functional
tail (activations, losses, pooling, norms, conv family, shape ops) vs
independent numpy references with numeric-grad checks — extending
test_op_sweep.py / test_op_sweep_r4.py toward full surface coverage
(reference bar: unittests/op_test.py:270 OpTest over ~1,122 op files).

References are written from the ops' canonical/documented semantics
(paddle 2.1 docs conventions: NCHW layouts, paddle arg orders), NOT from
this repo's implementations.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from test_op_sweep import _mk, _run_sweep_case, _softplus_np as _softplus


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _log_softmax(x, axis=-1):
    m = x.max(axis=axis, keepdims=True)
    return x - m - np.log(np.exp(x - m).sum(axis=axis, keepdims=True))


# -- conv family loops (canonical cross-correlation, NCHW) -------------------

def _conv1d_np(x, w, b):
    n, cin, l = x.shape
    co, _, k = w.shape
    lo = l - k + 1
    out = np.zeros((n, co, lo), np.float32)
    for i in range(lo):
        out[:, :, i] = np.tensordot(x[:, :, i:i + k], w,
                                    axes=([1, 2], [1, 2]))
    return out + b.reshape(1, -1, 1)


def _conv2d_np(x, w, b):
    n, cin, h, wd = x.shape
    co, _, kh, kw = w.shape
    ho, wo = h - kh + 1, wd - kw + 1
    out = np.zeros((n, co, ho, wo), np.float32)
    for i in range(ho):
        for j in range(wo):
            patch = x[:, :, i:i + kh, j:j + kw]
            out[:, :, i, j] = np.tensordot(patch, w,
                                           axes=([1, 2, 3], [1, 2, 3]))
    return out + b.reshape(1, -1, 1, 1)


def _conv3d_np(x, w):
    n, cin, dd, h, wd = x.shape
    co, _, kd, kh, kw = w.shape
    do, ho, wo = dd - kd + 1, h - kh + 1, wd - kw + 1
    out = np.zeros((n, co, do, ho, wo), np.float32)
    for z in range(do):
        for i in range(ho):
            for j in range(wo):
                patch = x[:, :, z:z + kd, i:i + kh, j:j + kw]
                out[:, :, z, i, j] = np.tensordot(
                    patch, w, axes=([1, 2, 3, 4], [1, 2, 3, 4]))
    return out


def _conv1dT_np(x, w, stride=1):
    # paddle conv1d_transpose weight: [cin, cout, k]
    n, cin, l = x.shape
    _, co, k = w.shape
    lo = (l - 1) * stride + k
    out = np.zeros((n, co, lo), np.float32)
    for i in range(l):
        out[:, :, i * stride:i * stride + k] += np.einsum(
            'nc,cok->nok', x[:, :, i], w)
    return out


def _conv2dT_np(x, w, stride=1):
    # paddle conv2d_transpose weight: [cin, cout, kh, kw]
    n, cin, h, wd = x.shape
    _, co, kh, kw = w.shape
    ho, wo = (h - 1) * stride + kh, (wd - 1) * stride + kw
    out = np.zeros((n, co, ho, wo), np.float32)
    for i in range(h):
        for j in range(wd):
            out[:, :, i * stride:i * stride + kh,
                j * stride:j * stride + kw] += np.einsum(
                    'nc,cokl->nokl', x[:, :, i, j], w)
    return out


def _unfold_np(x, k):
    # im2col, channel-major (c, ki, kj) row layout, L = ho*wo cols
    n, c, h, w = x.shape
    ho, wo = h - k + 1, w - k + 1
    cols = np.zeros((n, c, k * k, ho * wo), np.float32)
    for i in range(k):
        for j in range(k):
            cols[:, :, i * k + j] = x[:, :, i:i + ho, j:j + wo].reshape(
                n, c, -1)
    return cols.reshape(n, c * k * k, ho * wo)


def _fold_np(x, out_hw, k):
    n, ckk, l = x.shape
    c = ckk // (k * k)
    ho, wo = out_hw[0] - k + 1, out_hw[1] - k + 1
    cols = x.reshape(n, c, k, k, ho, wo)
    out = np.zeros((n, c, out_hw[0], out_hw[1]), np.float32)
    for i in range(k):
        for j in range(k):
            out[:, :, i:i + ho, j:j + wo] += cols[:, :, i, j]
    return out


# -- pooling refs ------------------------------------------------------------

def _avg_pool2d_np(x, k):
    n, c, h, w = x.shape
    return x.reshape(n, c, h // k, k, w // k, k).mean(axis=(3, 5))


def _max_pool2d_np(x, k):
    n, c, h, w = x.shape
    return x.reshape(n, c, h // k, k, w // k, k).max(axis=(3, 5))


def _group_norm_np(x, w, b, groups, eps=1e-5):
    n, c, h, wd = x.shape
    xg = x.reshape(n, groups, -1)
    mu = xg.mean(axis=2, keepdims=True)
    var = xg.var(axis=2, keepdims=True)
    xn = ((xg - mu) / np.sqrt(var + eps)).reshape(n, c, h, wd)
    return xn * w.reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1)


_PM1 = lambda l: 2.0 * l - 1.0   # {0,1} int spec -> {-1,+1} labels

_BN_MEAN = np.array([0.1, -0.2, 0.3], np.float32)
_BN_VAR = np.array([1.1, 0.9, 1.3], np.float32)


SWEEP5 = [
    # --- activations -------------------------------------------------------
    ('celu', lambda x: F.celu(x, alpha=1.2),
     lambda x: np.maximum(x, 0) + np.minimum(1.2 * np.expm1(x / 1.2), 0),
     [(3, 4)], {}, True),
    ('mish', F.mish, lambda x: x * np.tanh(_softplus(x)), [(3, 4)], {}, True),
    ('silu', F.silu, lambda x: x * _sigmoid(x), [(3, 4)], {}, True),
    ('selu', F.selu,
     lambda x: 1.0507009873554805 * np.where(
         x > 0, x, 1.6732632423543772 * np.expm1(x)), [(3, 4)], {}, False),
    ('relu6', F.relu6, lambda x: np.clip(x, 0, 6), [(3, 4)], {}, False),
    ('softshrink', lambda x: F.softshrink(x, threshold=0.5),
     lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0.0)),
     [(3, 4)], {}, False),
    ('hardshrink', lambda x: F.hardshrink(x, threshold=0.5),
     lambda x: np.where(np.abs(x) > 0.5, x, 0.0), [(3, 4)], {}, False),
    ('tanhshrink', F.tanhshrink, lambda x: x - np.tanh(x), [(3, 4)], {},
     True),
    ('thresholded_relu', lambda x: F.thresholded_relu(x, threshold=1.0),
     lambda x: np.where(x > 1.0, x, 0.0), [(3, 4)], {}, False),
    ('hardsigmoid', F.hardsigmoid,
     lambda x: np.clip(x / 6.0 + 0.5, 0.0, 1.0), [(3, 4)], {}, False),
    ('hardtanh', F.hardtanh, lambda x: np.clip(x, -1, 1), [(3, 4)], {},
     False),
    ('leaky_relu', lambda x: F.leaky_relu(x, negative_slope=0.01),
     lambda x: np.where(x >= 0, x, 0.01 * x), [(3, 4)], {}, False),
    ('log_sigmoid', F.log_sigmoid, lambda x: -_softplus(-x), [(3, 4)], {},
     True),
    ('softsign', F.softsign, lambda x: x / (1 + np.abs(x)), [(3, 4)], {},
     True),
    ('swish', F.swish, lambda x: x * _sigmoid(x), [(3, 4)], {}, True),
    ('maxout', lambda x: F.maxout(x, groups=2, axis=1),
     lambda x: x.reshape(2, 2, 2, 3, 4).max(axis=2), [(2, 4, 3, 4)], {},
     False),
    ('prelu', lambda x, w: F.prelu(x, w),
     lambda x, w: np.where(x >= 0, x, w.reshape(1, -1, 1, 1) * x),
     [(2, 3, 4, 4), ('pos', (3,))], {}, False),
    ('glu', lambda x: F.glu(x, axis=-1),
     lambda x: x[..., :3] * _sigmoid(x[..., 3:]), [(2, 4, 6)], {}, True),
    # --- losses ------------------------------------------------------------
    ('l1_loss', F.l1_loss, lambda x, y: np.mean(np.abs(x - y)),
     [(3, 4), (3, 4)], {}, False),
    ('mse_loss', F.mse_loss, lambda x, y: np.mean((x - y) ** 2),
     [(3, 4), (3, 4)], {}, True),
    ('smooth_l1_loss', F.smooth_l1_loss,
     lambda x, y: np.mean(np.where(np.abs(x - y) < 1.0,
                                   0.5 * (x - y) ** 2,
                                   np.abs(x - y) - 0.5)),
     [(3, 4), (3, 4)], {}, False),
    ('kl_div', lambda x, y: F.kl_div(x, paddle.nn.functional.softmax(y)),
     lambda x, y: np.mean(
         np.exp(y) / np.exp(y).sum(-1, keepdims=True) *
         (np.log(np.exp(y) / np.exp(y).sum(-1, keepdims=True)) - x)),
     [(3, 4), (3, 4)], {}, True),
    ('nll_loss',
     lambda x, l: F.nll_loss(paddle.nn.functional.log_softmax(x), l),
     lambda x, l: -np.mean(
         _log_softmax(x)[np.arange(len(l)), l.astype(int)]),
     [(6, 5), ('int', (6,), 5)], {}, True),
    ('binary_cross_entropy',
     lambda x, y: F.binary_cross_entropy(paddle.nn.functional.sigmoid(x),
                                         y),
     lambda x, y: -np.mean(y * np.log(_sigmoid(x)) +
                           (1 - y) * np.log(1 - _sigmoid(x))),
     [(3, 4), ('unit', (3, 4))], {}, True),
    ('bce_with_logits', F.binary_cross_entropy_with_logits,
     lambda x, y: np.mean((1 - y) * x + _softplus(-x)),
     [(3, 4), ('unit', (3, 4))], {}, True),
    ('soft_margin_loss',
     lambda x, l: F.soft_margin_loss(x, paddle.to_tensor(2.0) * l - 1.0),
     lambda x, l: np.mean(np.log1p(np.exp(-_PM1(l) * x))),
     [(3, 4), ('int', (3, 4), 2)], {}, True),
    ('margin_ranking_loss',
     lambda a, b, l: F.margin_ranking_loss(
         a, b, paddle.to_tensor(2.0) * l - 1.0, margin=0.1),
     lambda a, b, l: np.mean(np.maximum(0.0, -_PM1(l) * (a - b) + 0.1)),
     [(3, 4), (3, 4), ('int', (3, 4), 2)], {}, False),
    ('hinge_embedding_loss',
     lambda x, l: F.hinge_embedding_loss(
         x, paddle.to_tensor(2.0) * l - 1.0),
     lambda x, l: np.mean(np.where(_PM1(l) == 1.0, x,
                                   np.maximum(0.0, 1.0 - x))),
     [(3, 4), ('int', (3, 4), 2)], {}, False),
    ('cosine_embedding_loss',
     lambda a, b, l: F.cosine_embedding_loss(
         a, b, paddle.to_tensor(2.0) * l - 1.0, margin=0.1),
     lambda a, b, l: np.mean(np.where(
         _PM1(l) == 1,
         1 - (a * b).sum(-1) / (np.linalg.norm(a, axis=-1) *
                                np.linalg.norm(b, axis=-1)),
         np.maximum(0.0, (a * b).sum(-1) /
                    (np.linalg.norm(a, axis=-1) *
                     np.linalg.norm(b, axis=-1)) - 0.1))),
     [(4, 6), (4, 6), ('int', (4,), 2)], {}, False),
    ('triplet_margin_loss', F.triplet_margin_loss,
     lambda a, p, n: np.mean(np.maximum(
         np.linalg.norm(a - p, axis=-1) -
         np.linalg.norm(a - n, axis=-1) + 1.0, 0.0)),
     [(4, 6), (4, 6), (4, 6)], {}, False),
    ('multi_label_soft_margin', F.multi_label_soft_margin_loss,
     lambda x, y: np.mean(
         np.mean(-(y * np.log(_sigmoid(x)) +
                   (1 - y) * np.log(_sigmoid(-x))), axis=-1)),
     [(3, 5), ('int', (3, 5), 2)], {}, True),
    ('square_error_cost', F.square_error_cost,
     lambda x, y: (x - y) ** 2, [(3, 4), (3, 4)], {}, True),
    ('dice_loss',
     lambda x, l: F.dice_loss(paddle.nn.functional.softmax(x), l),
     lambda x, l: np.mean(1.0 - (
         2 * np.take_along_axis(
             np.exp(x) / np.exp(x).sum(-1, keepdims=True), l, -1
         ).squeeze(-1).sum(-1) + 1e-5) / (
             (np.exp(x) / np.exp(x).sum(-1, keepdims=True)).sum((1, 2)) +
             l.shape[1] + 1e-5)),
     [(2, 6, 3), ('int', (2, 6, 1), 3)], {}, True),
    ('label_smooth', F.label_smooth,
     lambda x: 0.9 * x + 0.1 / 4, [('unit', (3, 4))], {}, True),
    ('softmax_with_cross_entropy', F.softmax_with_cross_entropy,
     lambda x, l: -np.take_along_axis(_log_softmax(x), l, -1),
     [(5, 6), ('int', (5, 1), 6)], {}, True),
    # --- pooling -----------------------------------------------------------
    ('avg_pool1d', lambda x: F.avg_pool1d(x, 2, stride=2),
     lambda x: x.reshape(2, 3, 4, 2).mean(-1), [(2, 3, 8)], {}, True),
    ('avg_pool2d', lambda x: F.avg_pool2d(x, 2, stride=2),
     lambda x: _avg_pool2d_np(x, 2), [(2, 3, 4, 6)], {}, True),
    ('avg_pool3d', lambda x: F.avg_pool3d(x, 2, stride=2),
     lambda x: x.reshape(1, 2, 2, 2, 2, 2, 2, 2).mean(axis=(3, 5, 7)),
     [(1, 2, 4, 4, 4)], {}, True),
    ('max_pool1d', lambda x: F.max_pool1d(x, 2, stride=2),
     lambda x: x.reshape(2, 3, 4, 2).max(-1), [(2, 3, 8)], {}, False),
    ('max_pool2d', lambda x: F.max_pool2d(x, 2, stride=2),
     lambda x: _max_pool2d_np(x, 2), [(2, 3, 4, 6)], {}, False),
    ('max_pool3d', lambda x: F.max_pool3d(x, 2, stride=2),
     lambda x: x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7)),
     [(1, 2, 4, 4, 4)], {}, False),
    ('adaptive_avg_pool1d', lambda x: F.adaptive_avg_pool1d(x, 2),
     lambda x: x.reshape(2, 3, 2, 4).mean(-1), [(2, 3, 8)], {}, True),
    ('adaptive_avg_pool2d', lambda x: F.adaptive_avg_pool2d(x, 2),
     lambda x: x.reshape(2, 3, 2, 2, 2, 3).mean(axis=(3, 5)),
     [(2, 3, 4, 6)], {}, True),
    ('adaptive_avg_pool3d', lambda x: F.adaptive_avg_pool3d(x, 1),
     lambda x: x.mean(axis=(2, 3, 4), keepdims=True), [(1, 2, 4, 4, 4)],
     {}, True),
    ('adaptive_max_pool2d', lambda x: F.adaptive_max_pool2d(x, 2),
     lambda x: x.reshape(2, 3, 2, 2, 2, 3).max(axis=(3, 5)),
     [(2, 3, 4, 6)], {}, False),
    # --- norms -------------------------------------------------------------
    ('layer_norm_affine',
     lambda x, w, b: F.layer_norm(x, (6,), weight=w, bias=b),
     lambda x, w, b: (x - x.mean(-1, keepdims=True)) /
     np.sqrt(x.var(-1, keepdims=True) + 1e-5) * w + b,
     [(4, 6), (6,), (6,)], {}, True),
    ('group_norm',
     lambda x, w, b: F.group_norm(x, 2, weight=w, bias=b),
     lambda x, w, b: _group_norm_np(x, w, b, 2),
     [(2, 4, 3, 3), (4,), (4,)], {}, True),
    ('instance_norm',
     lambda x, w, b: F.instance_norm(x, weight=w, bias=b),
     lambda x, w, b: (x - x.mean((2, 3), keepdims=True)) /
     np.sqrt(x.var((2, 3), keepdims=True) + 1e-5) *
     w.reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1),
     [(2, 3, 4, 4), (3,), (3,)], {}, True),
    ('batch_norm_eval',
     lambda x, w, b: F.batch_norm(
         x, paddle.to_tensor(_BN_MEAN), paddle.to_tensor(_BN_VAR),
         weight=w, bias=b, training=False),
     lambda x, w, b: (x - _BN_MEAN.reshape(1, -1, 1, 1)) /
     np.sqrt(_BN_VAR.reshape(1, -1, 1, 1) + 1e-5) *
     w.reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1),
     [(2, 3, 4, 4), (3,), (3,)], {}, True),
    ('local_response_norm',
     lambda x: F.local_response_norm(x, 3, alpha=0.1, beta=0.75, k=1.0),
     None, [(2, 5, 4, 4)], {}, True),
    ('normalize', lambda x: F.normalize(x, axis=-1),
     lambda x: x / np.maximum(np.linalg.norm(x, axis=-1, keepdims=True),
                              1e-12),
     [(3, 6)], {}, True),
    # --- conv family -------------------------------------------------------
    ('conv1d', F.conv1d, _conv1d_np,
     [(2, 3, 8), (4, 3, 3), (4,)], {}, True),
    ('conv2d', F.conv2d, _conv2d_np,
     [(2, 3, 6, 6), (4, 3, 3, 3), (4,)], {}, True),
    ('conv3d', lambda x, w: F.conv3d(x, w), _conv3d_np,
     [(1, 2, 4, 4, 4), (3, 2, 2, 2, 2)], {}, True),
    ('conv1d_transpose',
     lambda x, w: F.conv1d_transpose(x, w, stride=2),
     lambda x, w: _conv1dT_np(x, w, 2),
     [(2, 3, 5), (3, 4, 3)], {}, True),
    ('conv2d_transpose',
     lambda x, w: F.conv2d_transpose(x, w, stride=2),
     lambda x, w: _conv2dT_np(x, w, 2),
     [(1, 3, 4, 4), (3, 2, 3, 3)], {}, True),
    ('unfold', lambda x: F.unfold(x, 2),
     lambda x: _unfold_np(x, 2), [(2, 3, 4, 5)], {}, True),
    ('fold', lambda x: F.fold(x, (4, 5), 2),
     lambda x: _fold_np(x, (4, 5), 2), [(2, 12, 12)], {}, True),
    ('bilinear', F.bilinear,
     lambda x1, x2, w, b: np.einsum('bi,oij,bj->bo', x1, w, x2) + b,
     [(4, 3), (4, 5), (2, 3, 5), (1, 2)], {}, True),
    ('embedding', lambda ids, w: F.embedding(ids, w),
     lambda ids, w: w[ids.astype(int)],
     [('int', (3, 4), 6), (6, 5)], {}, True),
    ('cosine_similarity', lambda a, b: F.cosine_similarity(a, b, axis=-1),
     lambda a, b: (a * b).sum(-1) / (np.linalg.norm(a, axis=-1) *
                                     np.linalg.norm(b, axis=-1)),
     [(3, 6), (3, 6)], {}, True),
    # --- shape / layout ----------------------------------------------------
    ('one_hot', lambda l: F.one_hot(l, 5),
     lambda l: np.eye(5, dtype=np.float32)[l.astype(int)],
     [('int', (3, 4), 5)], {}, False),
    ('diag_embed', F.diag_embed,
     lambda x: np.stack([np.diag(r) for r in x]), [(3, 4)], {}, True),
    ('pad_nchw', lambda x: F.pad(x, [1, 2, 0, 1]),
     lambda x: np.pad(x, [(0, 0), (0, 0), (0, 1), (1, 2)]),
     [(2, 3, 4, 4)], {}, True),
    ('zeropad2d', lambda x: F.zeropad2d(x, [1, 2, 3, 4]),
     lambda x: np.pad(x, [(0, 0), (0, 0), (3, 4), (1, 2)]),
     [(2, 3, 4, 4)], {}, True),
    ('pixel_shuffle', lambda x: F.pixel_shuffle(x, 2),
     lambda x: x.reshape(1, 2, 2, 2, 3, 3).transpose(
         0, 1, 4, 2, 5, 3).reshape(1, 2, 6, 6),
     [(1, 8, 3, 3)], {}, True),
    ('pixel_unshuffle', lambda x: F.pixel_unshuffle(x, 2),
     lambda x: x.reshape(1, 2, 3, 2, 3, 2).transpose(
         0, 1, 3, 5, 2, 4).reshape(1, 8, 3, 3),
     [(1, 2, 6, 6)], {}, True),
    ('channel_shuffle', lambda x: F.channel_shuffle(x, 2),
     lambda x: x.reshape(1, 2, 3, 4, 4).transpose(0, 2, 1, 3, 4).reshape(
         1, 6, 4, 4),
     [(1, 6, 4, 4)], {}, True),
    # --- tensor namespace tail ---------------------------------------------
    ('einsum_matmul', lambda x, y: paddle.einsum('ij,jk->ik', x, y),
     lambda x, y: x @ y, [(3, 4), (4, 5)], {}, True),
    ('norm_fro', lambda x: paddle.norm(x),
     lambda x: np.sqrt((x ** 2).sum()), [(3, 4)], {}, True),
    ('dist_l2', lambda x, y: paddle.dist(x, y),
     lambda x, y: np.sqrt(((x - y) ** 2).sum()), [(3, 4), (3, 4)], {},
     True),
    ('diag_vec', paddle.diag, np.diag, [(5,)], {}, True),
    ('t', paddle.t, np.transpose, [(3, 4)], {}, True),
    ('where_select',
     lambda c, x, y: paddle.where(c.astype('bool'), x, y),
     lambda c, x, y: np.where(c.astype(bool), x, y),
     [('int', (3, 4), 2), (3, 4), (3, 4)], {}, True),
    ('scale_op', lambda x: paddle.scale(x, scale=2.5, bias=1.5),
     lambda x: 2.5 * x + 1.5, [(3, 4)], {}, True),
    ('stack_op', lambda x, y: paddle.stack([x, y], axis=1),
     lambda x, y: np.stack([x, y], axis=1), [(3, 4), (3, 4)], {}, True),
    ('max_reduce', lambda x: paddle.max(x, axis=1),
     lambda x: x.max(axis=1), [(3, 4)], {}, False),
    ('min_reduce', lambda x: paddle.min(x, axis=1),
     lambda x: x.min(axis=1), [(3, 4)], {}, False),
    ('sort_op', lambda x: paddle.sort(x, axis=-1),
     lambda x: np.sort(x, axis=-1), [(3, 4)], {}, False),
    ('expand_as', lambda x, y: paddle.expand_as(x, y),
     lambda x, y: np.broadcast_to(x, y.shape), [(1, 4), (3, 4)], {},
     False),
    ('crop_tensor',
     lambda x: paddle.crop_tensor(x, shape=[2, 2], offsets=[1, 1]),
     lambda x: x[1:3, 1:3], [(4, 5)], {}, True),
    ('atleast_2d', paddle.atleast_2d,
     lambda x: np.atleast_2d(x), [(4,)], {}, True),
]


@pytest.mark.parametrize('case', SWEEP5, ids=[c[0] for c in SWEEP5])
def test_op_sweep_r5(case):
    _run_sweep_case(case)


# -- tranche 2: linalg / manipulation / reduction tail ----------------------

def _lu_ref_check(x):
    # lu returns (LU-packed, pivots[, info]); validate by reconstruction
    out = paddle.lu(paddle.to_tensor(x))
    packed = out[0].numpy()
    pivots = out[1].numpy()
    n = x.shape[-1]
    l = np.tril(packed, -1) + np.eye(n, dtype=packed.dtype)
    u = np.triu(packed)
    perm = np.arange(n)
    for i, pv in enumerate(pivots.astype(int)):
        # paddle/LAPACK pivots are 1-based row swaps
        j = pv - 1
        perm[[i, j]] = perm[[j, i]]
    recon = np.zeros_like(x)
    recon[perm] = (l @ u)
    np.testing.assert_allclose(recon, x, rtol=1e-4, atol=1e-4)


SWEEP5B = [
    ('angle', paddle.angle, np.angle, [(3, 4)], {}, False),
    ('nanmean',
     lambda x: paddle.nanmean(paddle.where(x > 0, x,
                                           paddle.to_tensor(np.nan))),
     lambda x: np.nanmean(np.where(x > 0, x, np.nan)), [(3, 4)], {},
     False),
    ('nansum',
     lambda x: paddle.nansum(paddle.where(x > 0, x,
                                          paddle.to_tensor(np.nan))),
     lambda x: np.nansum(np.where(x > 0, x, np.nan)), [(3, 4)], {},
     False),
    ('triangular_solve',
     lambda a, b: paddle.linalg.triangular_solve(
         paddle.tril(a) + paddle.to_tensor(
             4.0 * np.eye(4, dtype=np.float32)), b, upper=False),
     lambda a, b: np.linalg.solve(
         np.tril(a) + 4 * np.eye(4, dtype=np.float32), b),
     [(4, 4), (4, 2)], {}, True),
]
@pytest.mark.parametrize('case', SWEEP5B, ids=[c[0] for c in SWEEP5B])
def test_op_sweep_r5b(case):
    _run_sweep_case(case)


def test_put_along_axis_matches_numpy():
    rng = np.random.RandomState(9)
    x = rng.randn(3, 4).astype(np.float32)
    # per-row-UNIQUE indices: duplicate-index scatter-set ordering is
    # unspecified in XLA, so a duplicated column would make the expected
    # result backend-dependent
    idx = np.stack([rng.permutation(4)[:2] for _ in range(3)]).astype(
        np.int64)
    v = rng.randn(3, 2).astype(np.float32)
    out = paddle.put_along_axis(paddle.to_tensor(x), paddle.to_tensor(idx),
                                paddle.to_tensor(v), axis=1)
    ref = x.copy()
    np.put_along_axis(ref, idx, v, 1)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)


def test_eigh_reconstructs():
    rng = np.random.RandomState(11)
    a = rng.randn(5, 5).astype(np.float32)
    sym = (a + a.T) / 2
    w, v = paddle.linalg.eigh(paddle.to_tensor(sym))
    w, v = w.numpy(), v.numpy()
    np.testing.assert_allclose(v @ np.diag(w) @ v.T, sym, rtol=1e-3,
                               atol=1e-4)


def test_lu_reconstructs():
    rng = np.random.RandomState(12)
    x = rng.randn(5, 5).astype(np.float32) + 3 * np.eye(5, dtype=np.float32)
    _lu_ref_check(x)


def test_broadcast_tensors_values():
    a_np = np.arange(4, dtype=np.float32).reshape(1, 4)
    b_np = 10.0 * np.arange(3, dtype=np.float32).reshape(3, 1)
    oa, ob = paddle.broadcast_tensors([paddle.to_tensor(a_np),
                                       paddle.to_tensor(b_np)])
    ra, rb = np.broadcast_arrays(a_np, b_np)
    np.testing.assert_array_equal(oa.numpy(), ra)
    np.testing.assert_array_equal(ob.numpy(), rb)


def test_unique_consecutive_matches_numpy():
    x = np.array([1, 1, 2, 2, 2, 3, 1, 1], np.int32)
    out = paddle.unique_consecutive(paddle.to_tensor(x))
    out = out[0] if isinstance(out, (list, tuple)) else out
    ref = np.array([1, 2, 3, 1], np.int32)
    np.testing.assert_array_equal(np.asarray(out.numpy()), ref)
