"""Sparse-scale datapoint: SsdSparseTable at 10M rows x dim 64.

The claim under test (reference table/ssd_sparse_table.cc over rocksdb):
the two-tier table holds a vocabulary ~100x larger than the hot set
with bounded resident memory — the hot dict stays at `max_mem_rows`
and everything else lives in the sqlite cold tier ON DISK, while
pull/push keep a usable throughput. All-hot would need
10M * 64 * 4B = 2.56 GB for values alone; the capped run must stay
far under that.

Slow-marked (several minutes of single-row demotions); tier-1 runs
with -m 'not slow'. Run directly:

    JAX_PLATFORMS=cpu python -m pytest tests/test_sparse_scale.py -m slow -s
"""
import os
import time

import numpy as np
import pytest

from paddle_tpu.distributed.ps.tables import SsdSparseTable

ROWS = 10_000_000
DIM = 64
HOT_ROWS = 100_000           # 1% of the vocabulary
BATCH = 50_000
# generous RSS ceiling: hot tier (~50 MB) + sqlite page cache + interp
# noise. Uncapped, values alone exceed 2.56 GB — the assertion fails
# loudly if demotion ever stops evicting.
RSS_DELTA_CAP = 1.2 * 2 ** 30
MIN_ROWS_PER_SEC = 2_000     # loaded-CI floor, ~15x under measured


def _rss_bytes():
    with open('/proc/self/statm') as f:
        return int(f.read().split()[1]) * os.sysconf('SC_PAGE_SIZE')


@pytest.mark.slow
def test_ssd_sparse_table_10m_rows_capped_ram(tmp_path):
    rss0 = _rss_bytes()
    table = SsdSparseTable(dim=DIM, max_mem_rows=HOT_ROWS,
                           db_path=str(tmp_path / 'cold.db'),
                           optimizer='sgd', lr=0.1)

    # ---- populate: pull materializes rows, overflow demotes to disk ----
    t0 = time.time()
    for start in range(0, ROWS, BATCH):
        ids = np.arange(start, start + BATCH, dtype=np.int64)
        out = table.pull(ids)
        assert out.shape == (BATCH, DIM)
        assert table.mem_rows() <= HOT_ROWS  # cap holds at every step
    pull_s = time.time() - t0
    pull_rate = ROWS / pull_s

    assert len(table) == ROWS
    assert table.mem_rows() == HOT_ROWS
    assert table.disk_rows() == ROWS - HOT_ROWS
    db_bytes = os.path.getsize(str(tmp_path / 'cold.db'))
    # the cold tier really is on disk, not hidden in the page cache
    assert db_bytes >= (ROWS - HOT_ROWS) * DIM * 4

    rss_delta = _rss_bytes() - rss0
    assert rss_delta < RSS_DELTA_CAP, (
        'resident growth %.2f GB exceeds cap %.2f GB (demotion broken?)'
        % (rss_delta / 2 ** 30, RSS_DELTA_CAP / 2 ** 30))

    # ---- push throughput: hot hits and cold promotions ----
    grads = np.ones((BATCH, DIM), np.float32)
    hot_ids = np.arange(ROWS - BATCH, ROWS, dtype=np.int64)
    t0 = time.time()
    table.push(hot_ids, grads)
    hot_rate = BATCH / (time.time() - t0)

    cold_ids = np.arange(0, BATCH, dtype=np.int64)
    t0 = time.time()
    table.push(cold_ids, grads)
    cold_rate = BATCH / (time.time() - t0)
    assert table.mem_rows() <= HOT_ROWS

    # pushed rows actually moved (sgd lr=0.1 on grad 1.0 => -0.1 shift)
    before_like = table.pull(np.arange(BATCH, 2 * BATCH, dtype=np.int64))
    after = table.pull(cold_ids)
    shift = float(np.mean(before_like) - np.mean(after))
    assert abs(shift - 0.1) < 0.01

    print('\nssd_sparse_scale: rows=%d dim=%d hot=%d | pull %.0f rows/s '
          '| push hot %.0f rows/s, cold-promote %.0f rows/s | '
          'rss +%.0f MB, db %.0f MB'
          % (ROWS, DIM, HOT_ROWS, pull_rate, hot_rate, cold_rate,
             rss_delta / 2 ** 20, db_bytes / 2 ** 20))
    for rate in (pull_rate, hot_rate, cold_rate):
        assert rate > MIN_ROWS_PER_SEC


@pytest.mark.slow
def test_native_embedding_table_10m_rows():
    """The all-in-RAM half of the datapoint: the C++ arena
    (native/embedding_table.cc) holds the full 10M x 64 vocabulary
    (~2.6 GB of values) and its pull/push rates bound what the sqlite
    tiering costs relative to a flat table."""
    from paddle_tpu.native.embedding_table import NativeEmbeddingTable

    try:
        table = NativeEmbeddingTable(dim=DIM, optimizer='sgd', lr=0.1)
    except OSError as e:
        pytest.skip('native embedding table unavailable: %s' % e)

    rss0 = _rss_bytes()
    t0 = time.time()
    for start in range(0, ROWS, BATCH):
        ids = np.arange(start, start + BATCH, dtype=np.int64)
        out = table.pull(ids)
        assert out.shape == (BATCH, DIM)
    pull_rate = ROWS / (time.time() - t0)
    assert len(table) == ROWS

    grads = np.ones((BATCH, DIM), np.float32)
    ids = np.arange(0, BATCH, dtype=np.int64)
    t0 = time.time()
    table.push(ids, grads)
    push_rate = BATCH / (time.time() - t0)

    rss_delta = _rss_bytes() - rss0
    # values alone are ROWS*DIM*4 = 2.56 GB; the arena (hash + slots
    # bookkeeping) must stay within ~3x of that, i.e. no duplication
    # bug quietly doubling the footprint
    assert rss_delta < 3 * ROWS * DIM * 4

    print('\nnative_embedding_scale: rows=%d dim=%d | pull %.0f rows/s '
          '| push %.0f rows/s | rss +%.0f MB'
          % (ROWS, DIM, pull_rate, push_rate, rss_delta / 2 ** 20))
    for rate in (pull_rate, push_rate):
        assert rate > MIN_ROWS_PER_SEC
