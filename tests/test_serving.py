"""Continuous-batching serving engine tests (paddle_tpu/serving/).

The two load-bearing assertions from the engine's contract:
  1. greedy tokens through the engine are IDENTICAL to sequential
     model.generate() for mixed-length prompts — continuous batching
     must not buy throughput with output drift; the paged engine must
     hold the same bar with prefix sharing and speculative decoding on;
  2. the compiled program set is FIXED and traces once per program
     across an arbitrary admit/retire workload — churn must never
     retrace (two programs for the slot engine, at most four overall
     for the paged engine).
"""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                PagedContinuousBatchingEngine, Scheduler,
                                ServingMetrics, SlotAllocator)
from paddle_tpu.serving.metrics import percentile
from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM


@pytest.fixture(scope='module')
def model():
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=211, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope='module')
def prompts():
    rng = np.random.RandomState(3)
    # >= 8 mixed lengths, deliberately non-monotonic so admission order
    # and slot layout differ from length order
    return [[int(t) for t in rng.randint(0, 211, n)]
            for n in (3, 17, 7, 12, 5, 21, 9, 4, 14, 6)]


def _sequential(model, prompt, mnt, **kw):
    out = model.generate(paddle.to_tensor([prompt]), max_new_tokens=mnt,
                         **kw)
    return [int(t) for t in out.numpy()[0][len(prompt):]]


@pytest.mark.slow
def test_greedy_parity_and_zero_retrace(model, prompts):
    """The acceptance bar: token-identical to generate() for mixed
    lengths with slots << requests (forces admit/retire churn), and the
    compiled-program count stays at one prefill + one decode."""
    mnt = 11
    expect = [_sequential(model, p, mnt) for p in prompts]
    eng = ContinuousBatchingEngine(model, num_slots=3, max_len=64,
                                   prefill_chunk=8, decode_block=4)
    got = eng.generate(prompts, max_new_tokens=mnt)
    assert got == expect
    assert eng.compiled_sizes() == {'prefill': 1, 'decode': 1}
    # every slot cycled through several occupants
    assert eng.allocator.in_use == 0
    assert eng.scheduler.pending == 0


@pytest.mark.slow
def test_sampling_stream_parity(model, prompts):
    """Per-request PRNG streams mirror generate(): same seed, same
    temperature/top-k, same sampled tokens."""
    mnt = 8
    kw = dict(do_sample=True, temperature=0.8, top_k=5, seed=42)
    expect = [_sequential(model, p, mnt, **kw) for p in prompts[:4]]
    eng = ContinuousBatchingEngine(model, num_slots=2, max_len=64,
                                   prefill_chunk=8, decode_block=4)
    got = eng.generate(prompts[:4], max_new_tokens=mnt, **kw)
    assert got == expect


@pytest.mark.slow
def test_per_request_sampling_params(model, prompts):
    """Requests with DIFFERENT sampling configs share the batch; each
    must match its own sequential run (the vectorized pick must not mix
    rows)."""
    specs = [dict(do_sample=False),
             dict(do_sample=True, temperature=0.7, top_k=3, seed=1),
             dict(do_sample=True, temperature=1.3, top_k=0, seed=9),
             dict(do_sample=False)]
    mnt = 7
    expect = [_sequential(model, p, mnt, **kw)
              for p, kw in zip(prompts, specs)]
    eng = ContinuousBatchingEngine(model, num_slots=4, max_len=64,
                                   prefill_chunk=8, decode_block=4)
    reqs = [eng.add_request(p, max_new_tokens=mnt, **kw)
            for p, kw in zip(prompts, specs)]
    eng.run()
    assert [r.tokens for r in reqs] == expect


@pytest.mark.slow
def test_slot_reuse_no_crosstalk(model, prompts):
    """A slot's next occupant sees none of the previous one: running the
    same workload at 2 slots (heavy reuse) and at 8 slots (no reuse)
    yields identical outputs."""
    mnt = 6
    outs = []
    for slots in (2, 8):
        eng = ContinuousBatchingEngine(model, num_slots=slots, max_len=64,
                                       prefill_chunk=8, decode_block=4)
        outs.append(eng.generate(prompts[:8], max_new_tokens=mnt))
    assert outs[0] == outs[1]


@pytest.mark.slow
def test_varied_budgets_and_immediate_finish(model, prompts):
    """max_new_tokens=1 finishes at prefill; longer budgets coexist in
    the same burst and each stops exactly at its own budget."""
    budgets = [1, 3, 9, 2]
    eng = ContinuousBatchingEngine(model, num_slots=4, max_len=64,
                                   prefill_chunk=8, decode_block=4)
    reqs = [eng.add_request(p, max_new_tokens=b)
            for p, b in zip(prompts, budgets)]
    eng.run()
    for req, b, p in zip(reqs, budgets, prompts):
        assert len(req.tokens) == b
        assert req.tokens == _sequential(model, p, b)


def test_stream_yields_all_tokens(model, prompts):
    eng = ContinuousBatchingEngine(model, num_slots=2, max_len=64,
                                   prefill_chunk=8, decode_block=4)
    req = eng.add_request(prompts[0], max_new_tokens=9, stream=True)
    streamed = list(eng.stream(req))
    assert streamed == req.tokens
    assert streamed == _sequential(model, prompts[0], 9)


@pytest.mark.slow
def test_thread_safe_front_door(model, prompts):
    """Several threads submit and drive concurrently; every request
    still matches its sequential run (the lock serializes steps, the
    outputs prove no cross-talk)."""
    mnt = 5
    expect = [_sequential(model, p, mnt) for p in prompts[:6]]
    eng = ContinuousBatchingEngine(model, num_slots=3, max_len=64,
                                   prefill_chunk=8, decode_block=4)
    results = [None] * 3
    def worker(i):
        results[i] = eng.generate(prompts[2 * i:2 * i + 2],
                                  max_new_tokens=mnt)
    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    got = [tok for pair in results for tok in pair]
    assert got == expect
    assert eng.compiled_sizes() == {'prefill': 1, 'decode': 1}


def test_admission_validation(model):
    eng = ContinuousBatchingEngine(model, num_slots=2, max_len=32,
                                   prefill_chunk=8, decode_block=2)
    with pytest.raises(ValueError, match='empty prompt'):
        eng.add_request([], max_new_tokens=4)
    with pytest.raises(ValueError, match='max_new_tokens'):
        eng.add_request([1, 2], max_new_tokens=0)
    with pytest.raises(ValueError, match='cache rows'):
        eng.add_request(list(range(30)), max_new_tokens=8)   # 30+8-1 > 32
    # prompt + budget fit but the PADDED last prefill chunk would not
    # (26 pads to 32 > 30): a clamped write would silently corrupt rows
    eng30 = ContinuousBatchingEngine(model, num_slots=2, max_len=30,
                                     prefill_chunk=8, decode_block=2)
    with pytest.raises(ValueError, match='cache rows'):
        eng30.add_request(list(range(26)), max_new_tokens=2)
    # capacity errors must not wedge the queue for valid requests
    req = eng.add_request([1, 2, 3], max_new_tokens=2)
    eng.run()
    assert len(req.tokens) == 2


@pytest.mark.parametrize('make', [
    lambda m: ContinuousBatchingEngine(m, num_slots=2, max_len=32,
                                       prefill_chunk=8, decode_block=2),
    lambda m: PagedContinuousBatchingEngine(m, num_seqs=2, max_len=32,
                                            page_size=8, prefill_chunk=8,
                                            decode_block=2),
], ids=['slot', 'paged'])
def test_front_door_rejects_unservable_worst_case(model, make):
    """Both engines share the _EngineBase submission-time guard: a
    request whose worst case (prompt + budget - 1) exceeds max_len gets
    a clear ValueError naming max_len at add_request, instead of
    wedging the queue head forever."""
    eng = make(model)
    with pytest.raises(ValueError, match='max_len=32'):
        eng.add_request(list(range(1, 20)), max_new_tokens=20)  # 38 > 32
    # the guard is exact: worst case == max_len still admits and runs
    req = eng.add_request(list(range(1, 20)), max_new_tokens=14)  # == 32
    eng.run()
    assert len(req.tokens) == 14
    assert eng.scheduler.pending == 0


def test_engine_cap_exceeds_model_positions(model):
    with pytest.raises(ValueError, match='max_position_embeddings'):
        ContinuousBatchingEngine(model, num_slots=2, max_len=4096)


def test_slot_allocator():
    a = SlotAllocator(3)
    s0, s1 = a.alloc('r0'), a.alloc('r1')
    assert (s0, s1) == (0, 1)           # lowest-first, deterministic
    a.free(s0)
    assert a.alloc('r2') == 0           # reuse the lowest freed slot
    assert a.in_use == 2 and a.available == 1
    assert a.occupancy == pytest.approx(2 / 3)
    assert a.owner_of(1) == 'r1'
    with pytest.raises(ValueError):
        a.free(2)                       # never allocated
    assert a.alloc('r3') == 2
    assert a.alloc('r4') is None        # full


def test_scheduler_chunk_plan():
    from paddle_tpu.serving.scheduler import Request
    a = SlotAllocator(2)
    s = Scheduler(a, max_len=32, prefill_chunk=8)
    r = Request(list(range(1, 12)), max_new_tokens=4)   # 11 tokens
    s.submit(r)
    assert s.admit() == [(0, r)]
    plan = s.prefill_plan()
    assert len(plan) == 1
    req, start, ids, valid, final = plan[0]
    assert (start, valid, final) == (0, 8, False)
    assert ids == list(range(1, 9))
    s.mark_prefilled(req, 8)
    req, start, ids, valid, final = s.prefill_plan()[0]
    assert (start, valid, final) == (8, 3, True)
    assert ids == [9, 10, 11, 0, 0, 0, 0, 0]            # zero-padded to C


def test_metrics_report():
    t = [0.0]
    m = ServingMetrics(clock=lambda: t[0])
    m.on_arrival('a')
    t[0] = 0.5
    m.on_tokens('a', 1)            # ttft 0.5s
    t[0] = 0.9
    m.on_tokens('a', 4)            # 0.4s burst over 4 tokens
    m.on_step(2, 4)
    m.on_step(4, 4)
    rep = m.report()
    assert rep['tokens'] == 5
    assert rep['tok_per_s'] == pytest.approx(5 / 0.9)
    assert rep['ttft_p50_ms'] == pytest.approx(500.0)
    assert rep['occupancy_mean'] == pytest.approx(0.75)
    assert rep['latency_p99_ms'] <= 500.0
    assert percentile([], 50) is None
    assert percentile([3.0], 99) == 3.0
    assert percentile([1.0, 2.0], 50) == pytest.approx(1.5)


def test_percentile_is_linear_interpolation_not_nearest_rank():
    """The docstring/behavior contract: linear interpolation between
    closest ranks (numpy's default method). Nearest-rank would return a
    member of the input for every q; interpolation doesn't."""
    # empty and singleton
    assert percentile([], 0) is None
    assert percentile([], 100) is None
    assert percentile([7.0], 0) == 7.0
    assert percentile([7.0], 50) == 7.0
    assert percentile([7.0], 100) == 7.0
    # q = 0 / 100 are exact extremes regardless of order
    xs = [5.0, 1.0, 3.0]
    assert percentile(xs, 0) == 1.0
    assert percentile(xs, 100) == 5.0
    # two elements: q interpolates linearly between them
    assert percentile([1.0, 3.0], 0) == 1.0
    assert percentile([1.0, 3.0], 25) == pytest.approx(1.5)
    assert percentile([1.0, 3.0], 50) == pytest.approx(2.0)
    assert percentile([1.0, 3.0], 75) == pytest.approx(2.5)
    assert percentile([1.0, 3.0], 100) == 3.0
    # parity with numpy's default ('linear') on a bigger sample —
    # including a q where nearest-rank and interpolation disagree
    rng = np.random.RandomState(0)
    vals = rng.rand(17).tolist()
    for q in (0, 10, 33.3, 50, 90, 99, 100):
        assert percentile(vals, q) == pytest.approx(
            float(np.percentile(vals, q)))
    assert percentile([1.0, 2.0, 4.0], 75) == pytest.approx(3.0)  # not 2/4


@pytest.mark.slow
def test_paged_greedy_parity_and_bounded_compilation(model, prompts):
    """The paged acceptance bar: token-identical to generate() with
    sequences << requests (page/slot churn), the program set stays at
    the fixed prefill/decode pair, and every page returns to the free
    list or the prefix cache when the workload drains."""
    mnt = 11
    expect = [_sequential(model, p, mnt) for p in prompts]
    eng = PagedContinuousBatchingEngine(model, num_seqs=3, max_len=64,
                                        page_size=8, prefill_chunk=8,
                                        decode_block=4)
    got = eng.generate(prompts, max_new_tokens=mnt)
    assert got == expect
    assert eng.compiled_sizes() == {'prefill': 1, 'decode': 1, 'verify': 0}
    assert eng.allocator.in_use == 0
    assert eng.scheduler.pending == 0
    # only prefix-cache references may outlive the requests
    assert eng.pages.in_use == len(eng.prefix)


def test_paged_prefix_sharing_parity_and_reduced_prefill(model):
    """Requests sharing a system prompt hit the prefix cache (> 0 hit
    rate), skip the shared blocks' prefill (fewer prefilled tokens than
    a cache-off engine on the same workload) and still match
    sequential generate() token-for-token."""
    rng = np.random.RandomState(11)
    system = [int(t) for t in rng.randint(0, 211, 16)]
    prompts = [system + [int(t) for t in rng.randint(0, 211, 3)]
               for _ in range(6)]
    mnt = 8
    expect = [_sequential(model, p, mnt) for p in prompts]
    kw = dict(num_seqs=2, max_len=64, page_size=8, prefill_chunk=8,
              decode_block=4)
    shared = PagedContinuousBatchingEngine(model, **kw)
    got = shared.generate(prompts, max_new_tokens=mnt)
    assert got == expect
    rep = shared.metrics.report()
    assert rep['prefix_hits'] > 0
    assert rep['prefix_hit_rate'] > 0
    cold = PagedContinuousBatchingEngine(model, prefix_cache=False, **kw)
    assert cold.generate(prompts, max_new_tokens=mnt) == expect
    cold_rep = cold.metrics.report()
    assert cold_rep['prefix_hits'] == 0
    # the hit-rate win is real work not done: strictly fewer prompt
    # tokens went through the prefill program
    assert rep['prefill_tokens'] < cold_rep['prefill_tokens']


def test_paged_spec_decode_parity(model, prompts):
    """Draft-and-verify emits the exact greedy sequence (the accept rule
    only keeps drafts equal to the model's own argmax picks), reports
    its acceptance counters, and the overall program set stays within
    the four-program bound."""
    mnt = 11
    expect = [_sequential(model, p, mnt) for p in prompts[:6]]
    eng = PagedContinuousBatchingEngine(model, num_seqs=3, max_len=64,
                                        page_size=8, prefill_chunk=8,
                                        decode_block=4, spec_k=3)
    got = eng.generate(prompts[:6], max_new_tokens=mnt)
    assert got == expect
    rep = eng.metrics.report()
    assert rep['spec_proposed'] > 0
    assert 0.0 <= rep['spec_accept_rate'] <= 1.0
    traces = eng.compiled_sizes()
    assert traces == {'prefill': 1, 'decode': 0, 'verify': 1}
    assert sum(1 for v in traces.values() if v) <= 4
    # greedy-only: the accept rule compares against argmax picks
    with pytest.raises(ValueError, match='greedy-only'):
        eng.add_request(prompts[0], max_new_tokens=4, do_sample=True)


def test_paged_sampling_stream_parity(model, prompts):
    """With spec off, the paged engine serves sampled requests through
    the same per-request PRNG stream as generate() — page indirection
    must not perturb logits or key order."""
    mnt = 8
    kw = dict(do_sample=True, temperature=0.8, top_k=5, seed=42)
    expect = [_sequential(model, p, mnt, **kw) for p in prompts[:4]]
    eng = PagedContinuousBatchingEngine(model, num_seqs=2, max_len=64,
                                        page_size=8, prefill_chunk=8,
                                        decode_block=4)
    got = eng.generate(prompts[:4], max_new_tokens=mnt, **kw)
    assert got == expect


@pytest.mark.slow
def test_predictor_decode_engine(model, prompts, tmp_path):
    """The serving front door reached the inference API: a jit.save'd
    causal LM round-trips into an engine whose output matches the live
    model's generate()."""
    path = str(tmp_path / 'gpt_lm')
    paddle.jit.save(model, path)
    from paddle_tpu import inference
    pred = inference.create_predictor(inference.Config(path))
    eng = pred.decode_engine(num_slots=2, max_len=64, prefill_chunk=8,
                             decode_block=4)
    got = eng.generate(prompts[:3], max_new_tokens=6)
    assert got == [_sequential(model, p, 6) for p in prompts[:3]]
    # and the paged variant through the same door
    paged = pred.decode_engine(num_slots=2, max_len=64, prefill_chunk=8,
                               decode_block=4, paged=True, page_size=8)
    assert paged.generate(prompts[:3], max_new_tokens=6) == got
    with pytest.raises(TypeError, match='paged=True'):
        pred.decode_engine(page_size=8)


def test_predictor_decode_engine_rejects_non_lm(tmp_path):
    from paddle_tpu import nn
    m = nn.Sequential(nn.Linear(4, 4))
    m.eval()
    path = str(tmp_path / 'mlp')
    paddle.jit.save(m, path)
    from paddle_tpu import inference
    pred = inference.create_predictor(inference.Config(path))
    with pytest.raises(TypeError, match='causal-LM'):
        pred.decode_engine()
