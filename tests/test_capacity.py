"""Capacity subsystem: workload determinism, trace loaders, the
discrete-event simulator, replay through a real in-proc gateway, and
the sim-vs-real calibration gate (ISSUE 16)."""
import json
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.capacity import simulator, workload


def _poisson_spec(n=64, mean_gap=0.01, seed=0, **kw):
    base = dict(requests=n, seed=seed, vocab_size=512,
                arrival={'process': 'poisson', 'mean_gap_s': mean_gap},
                lengths={'dist': 'ladder', 'lens': [8, 16, 24, 32]},
                output={'dist': 'fixed', 'len': 16})
    base.update(kw)
    return workload.WorkloadSpec(**base)


MODEL = simulator.ServiceModel(prefill_chunk_s=0.002, decode_burst_s=0.004)


# ---------------------------------------------------------------------------
# workload generation


def test_same_spec_same_seed_is_byte_identical():
    a = workload.generate(_poisson_spec())
    b = workload.generate(_poisson_spec())
    assert a.to_jsonl() == b.to_jsonl()
    assert a.prompts() == b.prompts()
    assert a.spec_hash == b.spec_hash


def test_different_seed_different_trace():
    a = workload.generate(_poisson_spec(seed=0))
    b = workload.generate(_poisson_spec(seed=1))
    assert a.to_jsonl() != b.to_jsonl()
    assert a.spec_hash != b.spec_hash  # seed is part of the spec


def test_poisson_matches_retired_bench_generator():
    # the exact formula bench_extra._poisson_arrivals used; stored bench
    # bests depend on this stream staying bit-identical
    gaps = np.random.RandomState(0).exponential(0.01, size=64)
    ref = np.concatenate([[0.0], np.cumsum(gaps)[:-1]])
    tr = workload.generate(_poisson_spec(n=64, mean_gap=0.01))
    assert np.array_equal(tr.arrival, ref)


def test_ladder_prompts_match_retired_bench_generator():
    lens = [8, 16, 24, 32]
    rng = np.random.RandomState(0)
    ref = [[int(t) for t in rng.randint(0, 512, lens[i % 4])]
           for i in range(16)]
    tr = workload.generate(_poisson_spec(n=16))
    assert tr.prompts() == ref


def test_shared_prefix_prompts_match_retired_paged_generator():
    rng = np.random.RandomState(0)
    system = [int(t) for t in rng.randint(0, 512, 32)]
    tails = [4, 8, 12, 16]
    ref = [system + [int(t) for t in rng.randint(0, 512, tails[i % 4])]
           for i in range(12)]
    tr = workload.generate(_poisson_spec(
        n=12, arrival={'process': 'burst'},
        lengths={'dist': 'ladder', 'lens': tails},
        prefix={'len': 32, 'groups': 1, 'prob': 1.0}))
    assert tr.prompts() == ref
    assert tr.arrivals() == [0.0] * 12


def test_heavy_tail_and_diurnal_shapes():
    tr = workload.generate(workload.WorkloadSpec(
        requests=2000, seed=3,
        arrival={'process': 'diurnal', 'mean_gap_s': 0.01,
                 'period_s': 5.0, 'peak_to_trough': 4.0},
        lengths={'dist': 'zipf', 'a': 1.5, 'min': 4, 'max': 512},
        output={'dist': 'lognormal', 'median': 16, 'sigma': 0.7,
                'min': 1, 'max': 128},
        tenants={'mode': 'zipf', 'count': 10, 'a': 1.5}))
    assert len(tr) == 2000
    assert (np.diff(tr.arrival) >= 0).all()
    assert tr.prompt_len.min() >= 4 and tr.prompt_len.max() <= 512
    assert tr.new_tokens.min() >= 1 and tr.new_tokens.max() <= 128
    # zipf tenancy is skewed: the top tenant dominates
    mix = tr.tenant_mix()
    assert max(mix.values()) > 2000 / 10


def test_weighted_tenants_and_burst_rider():
    tr = workload.generate(workload.WorkloadSpec(
        requests=500, seed=1, vocab_size=512,
        arrival={'process': 'poisson', 'mean_gap_s': 0.01,
                 'burst': {'prob': 0.1, 'size': 4, 'jitter_s': 1e-4}},
        lengths={'dist': 'fixed', 'len': 16},
        output={'dist': 'fixed', 'len': 8},
        tenants={'mode': 'weighted', 'tenants': [
            {'name': 'big', 'weight': 9}, {'name': 'small', 'weight': 1}]}))
    assert (np.diff(tr.arrival) >= 0).all()
    mix = tr.tenant_mix()
    assert mix['big'] > mix['small']


# ---------------------------------------------------------------------------
# trace serialization + loaders


def test_jsonl_roundtrip_preserves_everything():
    tr = workload.generate(_poisson_spec(
        n=32, tenants={'mode': 'round_robin', 'tenants': [
            {'name': 'a'}, {'name': 'b'}]}))
    back = workload.Trace.from_jsonl(tr.to_jsonl())
    assert back.to_jsonl() == tr.to_jsonl()
    assert back.tenants() == tr.tenants()
    assert np.array_equal(back.arrival, tr.arrival)


def test_trace_from_wide_events_preserves_order_and_mix():
    # recorded events arrive in completion order, not arrival order —
    # the loader must re-sort and rebase
    events = [
        {'request_id': 'r2', 'arrival_t': 107.0, 'tenant': 'b',
         'prompt_tokens': 8, 'output_tokens': 4, 'finish_t': 110.0},
        {'request_id': 'r0', 'arrival_t': 100.5, 'tenant': 'a',
         'prompt_tokens': 16, 'output_tokens': 8, 'finish_t': 109.0},
        {'request_id': 'r1', 'arrival_t': 103.0, 'tenant': 'a',
         'prompt_tokens': 4, 'output_tokens': 2, 'finish_t': 104.0},
    ]
    tr = workload.trace_from_events(events)
    assert tr.arrivals() == [0.0, 2.5, 6.5]
    assert tr.tenants() == ['a', 'a', 'b']
    assert tr.tenant_mix() == {'a': 2, 'b': 1}
    assert list(tr.prompt_len) == [16, 4, 8]


def test_load_trace_reads_sink_jsonl_and_trace_jsonl(tmp_path):
    tr = workload.generate(_poisson_spec(n=8))
    p = tmp_path / 'trace.jsonl'
    p.write_text(tr.to_jsonl())
    back = workload.load_trace(path=str(p))
    assert back.to_jsonl() == tr.to_jsonl()

    sink = tmp_path / 'sink.jsonl'
    sink.write_text('\n'.join(json.dumps(
        {'request_id': 'r%d' % i, 'arrival_t': 50.0 + i * 0.25,
         'tenant': 't', 'prompt_tokens': 4, 'output_tokens': 2,
         'finish_t': 51.0 + i * 0.25}) for i in range(5)) + '\n')
    loaded = workload.load_trace(path=str(sink))
    assert len(loaded) == 5
    assert loaded.arrivals()[0] == 0.0


# ---------------------------------------------------------------------------
# simulator


def test_simulator_more_replicas_non_increasing_p99():
    tr = workload.generate(_poisson_spec(n=400, mean_gap=0.002))
    p99s = []
    for c in (1, 2, 4, 8):
        res = simulator.simulate(tr, MODEL, replicas=c,
                                 router='round_robin')
        assert (res.finish > 0).all()
        p99s.append(res.ttft_percentiles((99,))[99])
    assert all(a >= b - 1e-9 for a, b in zip(p99s, p99s[1:])), p99s


def test_sweep_reports_min_replicas():
    tr = workload.generate(_poisson_spec(n=400, mean_gap=0.002))
    sweep = simulator.sweep_replicas(tr, MODEL, counts=(1, 2, 4, 8),
                                     slo_ttft_s=0.05)
    assert sweep['min_replicas'] is not None
    first_ok = next(p['replicas'] for p in sweep['points']
                    if p['meets_slo'])
    assert sweep['min_replicas'] == first_ok
    # unreachable SLO -> explicit None, not a wrong answer
    none_sweep = simulator.sweep_replicas(tr, MODEL, counts=(1,),
                                          slo_ttft_s=1e-9)
    assert none_sweep['min_replicas'] is None


def test_simulator_failover_reroutes_and_finishes():
    tr = workload.generate(_poisson_spec(n=200, mean_gap=0.002,
                                         output={'dist': 'fixed',
                                                 'len': 32}))
    res = simulator.simulate(tr, MODEL, replicas=3,
                             kill_at={1: tr.duration_s / 2})
    assert res.failovers.sum() > 0
    assert (res.finish > 0).all()


def test_simulator_autoscaler_policy_scales_up():
    from paddle_tpu.serving.gateway.autoscaler import AutoscalePolicy
    tr = workload.generate(_poisson_spec(
        n=2000, mean_gap=0.002,
        lengths={'dist': 'fixed', 'len': 64},
        output={'dist': 'fixed', 'len': 16}))
    pol = AutoscalePolicy(slo_ttft_s=0.02, min_replicas=1,
                          max_replicas=8, sustain_s=0.5, cooldown_s=1.0,
                          window_s=5.0)
    flat = simulator.simulate(tr, MODEL, replicas=1)
    scaled = simulator.simulate(tr, MODEL, replicas=1, policy=pol)
    assert scaled.max_replicas > 1
    assert (scaled.ttft_percentiles((99,))[99]
            < flat.ttft_percentiles((99,))[99])


def test_simulator_prefix_cache_hits_speed_up():
    spec = _poisson_spec(n=200, mean_gap=0.002,
                         lengths={'dist': 'fixed', 'len': 8},
                         prefix={'len': 64, 'groups': 2, 'prob': 1.0})
    tr = workload.generate(spec)
    res = simulator.simulate(tr, MODEL, replicas=1)
    assert res.prefix_hits.sum() > 0
    # a cold-cache run of the same load (prefix structure stripped)
    cold = workload.Trace(tr.arrival, tr.prompt_len, tr.new_tokens,
                          tr.tenant_id, tr.tenant_names,
                          np.full(len(tr), -1), np.zeros(len(tr)),
                          meta=tr.meta)
    res_cold = simulator.simulate(cold, MODEL, replicas=1)
    assert res.ttft_percentiles((99,))[99] \
        < res_cold.ttft_percentiles((99,))[99]


def test_sim_events_speak_the_wide_schema():
    from paddle_tpu.monitor.events import FIELD_NAMES
    tr = workload.generate(_poisson_spec(n=16))
    ev = simulator.simulate(tr, MODEL, replicas=1).to_events()
    assert len(ev) == 16
    assert set(ev[0]) == set(FIELD_NAMES)
    assert all(e['first_token_t'] >= e['admit_t'] >= e['arrival_t']
               for e in ev)


def test_ks_statistic_and_divergence():
    assert simulator.ks_statistic([1, 2, 3], [1, 2, 3]) == 0.0
    assert simulator.ks_statistic([0, 0, 0], [1, 1, 1]) == 1.0
    div = simulator.ttft_divergence([0.1] * 10, [0.2] * 10)
    assert div['p50_rel_err'] == pytest.approx(0.5)
    with pytest.raises(ValueError):
        simulator.ttft_divergence([], [0.1])


def test_compare_events_per_tenant_skips_small_samples():
    def ev(tenant, ttft, i):
        return {'request_id': i, 'tenant': tenant, 'arrival_t': 0.0,
                'first_token_t': ttft}
    sim = [ev('a', 0.1, i) for i in range(5)] + [ev('b', 0.1, 'x')]
    real = [ev('a', 0.1, i) for i in range(5)] + [ev('b', 0.1, 'y')]
    cmp = simulator.compare_events(sim, real)
    assert cmp['overall']['p50_rel_err'] == 0.0
    assert 'skipped' in cmp['tenants']['b']
    assert cmp['tenants']['a']['ks'] == 0.0


def test_service_model_from_roofline_and_bench_rows():
    m = simulator.ServiceModel.from_roofline(1e8, 2e8, platform='cpu')
    assert m.prefill_chunk_s > 0 and m.decode_burst_s > 0
    rows = [{'metric': 'serving_cb_tokens_per_sec', 'value': 1000.0,
             'num_slots': 8}]
    m2 = simulator.ServiceModel.from_bench_rows(rows)
    assert m2.decode_burst_s == pytest.approx(8 * 8 / 1000.0)
    with pytest.raises(ValueError):
        simulator.ServiceModel.from_bench_rows([])


@pytest.mark.slow
def test_million_request_sweep_is_fast():
    tr = workload.generate(workload.WorkloadSpec(
        requests=1000000, seed=0,
        arrival={'process': 'poisson', 'mean_gap_s': 0.0005},
        lengths={'dist': 'zipf', 'a': 1.8, 'min': 8, 'max': 256},
        output={'dist': 'fixed', 'len': 16}))
    sweep = simulator.sweep_replicas(tr, MODEL, counts=(16, 32),
                                     slo_ttft_s=0.25)
    assert sweep['min_replicas'] is not None
    assert sum(p['sim_wall_s'] for p in sweep['points']) < 60.0


# ---------------------------------------------------------------------------
# replay through the real in-proc gateway + calibration


def _tiny_engine_factory():
    import paddle_tpu as paddle
    from paddle_tpu.serving import ContinuousBatchingEngine
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return lambda: ContinuousBatchingEngine(
        model, num_slots=4, max_len=48, prefill_chunk=8, decode_block=4)


def test_replay_roundtrip_preserves_order_and_tenants():
    from paddle_tpu.capacity.replay import measure
    spec = workload.WorkloadSpec(
        requests=6, seed=0, vocab_size=128,
        arrival={'process': 'poisson', 'mean_gap_s': 0.005},
        lengths={'dist': 'ladder', 'lens': [4, 8]},
        output={'dist': 'fixed', 'len': 8},
        tenants={'mode': 'round_robin', 'tenants': [
            {'name': 'premium'}, {'name': 'batch'}]})
    tr = workload.generate(spec)
    events, res = measure(_tiny_engine_factory(), tr, replicas=1,
                          timeout=120)
    assert res.completed == len(tr)
    assert len(events) == len(tr)
    # arrival order and tenant mix survive the trip through the gateway
    evs = sorted(events, key=lambda e: e['arrival_t'])
    assert [e['tenant'] for e in evs] == tr.tenants()
    got_mix = {}
    for e in events:
        got_mix[e['tenant']] = got_mix.get(e['tenant'], 0) + 1
    assert got_mix == tr.tenant_mix()
    # and the recorded run loads back as a Trace in arrival order
    back = workload.trace_from_events(events)
    assert len(back) == len(tr)
    assert list(back.prompt_len) == [len(p) for p in tr.prompts()]


def test_sim_vs_real_calibration_small_poisson_burst():
    from paddle_tpu.capacity.replay import measure
    spec = workload.WorkloadSpec(
        requests=10, seed=0, vocab_size=128,
        arrival={'process': 'poisson', 'mean_gap_s': 0.01},
        lengths={'dist': 'ladder', 'lens': [4, 8, 12]},
        output={'dist': 'fixed', 'len': 12})
    tr = workload.generate(spec)
    events, _ = measure(_tiny_engine_factory(), tr, replicas=1,
                        timeout=120)
    model = simulator.ServiceModel.from_events(
        events, prefill_chunk=8, decode_block=4, num_slots=4,
        trace=tr, replicas=1)
    res = simulator.simulate(tr, model, replicas=1)
    div = simulator.ttft_divergence(
        res.ttft(), simulator.ttfts_of_events(events))
    # committed thresholds (tools/capacity_report.py defaults): CI boxes
    # are noisy, but the calibrated simulator must stay in the ballpark
    assert div['p50_rel_err'] <= 0.5, div
    assert div['p99_rel_err'] <= 0.5, div


# ---------------------------------------------------------------------------
# the offline gate CLI


def _run_report(*args):
    return subprocess.run(
        [sys.executable, 'tools/capacity_report.py'] + list(args),
        capture_output=True, text=True)


def test_capacity_report_protocol(tmp_path):
    tr = workload.generate(_poisson_spec(n=50))
    tp = tmp_path / 'trace.jsonl'
    tp.write_text(tr.to_jsonl())
    real = tmp_path / 'real.jsonl'
    res = simulator.simulate(tr, MODEL, replicas=1)
    real.write_text('\n'.join(json.dumps(e) for e in res.to_events()))

    ok = _run_report('--trace', str(tp), '--simulate',
                     '--prefill-chunk-s', '0.002',
                     '--decode-burst-s', '0.004', '--real', str(real))
    assert ok.returncode == 0, ok.stdout + ok.stderr
    out = json.loads(ok.stdout.splitlines()[-1])
    assert out['ok'] and out['divergence']['overall']['ks'] == 0.0

    bad = _run_report('--trace', str(tp), '--simulate',
                      '--prefill-chunk-s', '0.05',
                      '--decode-burst-s', '0.1', '--real', str(real))
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert any(json.loads(l).get('problem') == 'ttft_divergence'
               for l in bad.stdout.splitlines() if l.startswith('{'))

    nothing = _run_report()
    assert nothing.returncode == 2

    sweep = _run_report('--trace', str(tp), '--sweep', '1,2,4',
                        '--slo-ms', '100',
                        '--prefill-chunk-s', '0.002',
                        '--decode-burst-s', '0.004')
    assert sweep.returncode == 0, sweep.stdout + sweep.stderr
    out = json.loads(sweep.stdout.splitlines()[-1])
    assert out['sweep']['min_replicas'] is not None
