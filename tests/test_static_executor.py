"""static.Program record/replay (VERDICT r2 weak #6 + item 9): feeding
fresh values after build returns fresh fetches — the reference
ProgramDesc+Executor contract (executor.cc:166 Run, naive_executor.cc:38)
— and save/load_inference_model round-trips an executable artifact.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import static


def _build_program():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data('x', [4, 8], 'float32')
        lin = nn.Linear(8, 3)
        y = lin(x)
        out = paddle.nn.functional.relu(y)
    return prog, x, out, lin


def test_executor_replays_fresh_feeds():
    paddle.seed(11)
    prog, x, out, lin = _build_program()
    exe = static.Executor()

    rng = np.random.RandomState(0)
    f1 = rng.randn(4, 8).astype(np.float32)
    f2 = rng.randn(4, 8).astype(np.float32)

    r1 = exe.run(prog, feed={'x': f1}, fetch_list=[out])[0]
    r2 = exe.run(prog, feed={'x': f2}, fetch_list=[out])[0]

    w = lin.weight.numpy()
    b = lin.bias.numpy()
    np.testing.assert_allclose(r1, np.maximum(f1 @ w + b, 0), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(r2, np.maximum(f2 @ w + b, 0), rtol=1e-5,
                               atol=1e-5)
    assert not np.allclose(r1, r2)  # the old stale-fetch bug would equal


def test_executor_raises_on_unrecorded_program():
    # building OUTSIDE program_guard records nothing; feeding then must
    # raise, not silently return stale build-time values
    prog = static.Program()
    x = static.data('x', [2, 2], 'float32')  # goes to default program
    prog._feed_vars['x'] = x
    lin = nn.Linear(2, 2)
    y = lin(x)
    exe = static.Executor()
    with pytest.raises(RuntimeError, match='program_guard'):
        exe.run(prog, feed={'x': np.ones((2, 2), np.float32)},
                fetch_list=[y])


def test_save_load_inference_model_roundtrip(tmp_path):
    paddle.seed(5)
    prog, x, out, lin = _build_program()
    exe = static.Executor()
    rng = np.random.RandomState(1)
    feed = rng.randn(4, 8).astype(np.float32)
    exe.run(prog, feed={'x': feed}, fetch_list=[out])

    path = str(tmp_path / 'infer')
    x.name = 'x'
    static.save_inference_model(path, [x], [out], exe, program=prog)

    prog2, feed_names, fetch_targets = static.load_inference_model(path, exe)
    assert feed_names == ['x']
    got = exe.run(prog2, feed={'x': feed}, fetch_list=fetch_targets)[0]
    w, b = lin.weight.numpy(), lin.bias.numpy()
    np.testing.assert_allclose(got, np.maximum(feed @ w + b, 0),
                               rtol=1e-5, atol=1e-5)


def test_fluid_era_static_surface(tmp_path):
    """append_backward / gradients / scopes / py_func / serialize
    round-trip (reference fluid Executor-world APIs)."""
    import jax.numpy as jnp
    import paddle_tpu.nn.functional as F

    # append_backward returns (param, grad) pairs off the tape
    paddle.seed(0)
    lin = nn.Linear(3, 2)
    x = paddle.to_tensor(np.ones((4, 3), np.float32))
    loss = F.mse_loss(lin(x), paddle.to_tensor(np.zeros((4, 2), np.float32)))
    pairs = static.append_backward(loss)
    names = {id(p) for p, g in pairs}
    assert id(lin.weight) in names and id(lin.bias) in names
    for p, g in pairs:
        assert g is not None and g.shape == p.shape

    # gradients() delegates to autograd.grad
    a = paddle.to_tensor(np.asarray([2.0], np.float32), stop_gradient=False)
    b = a * a
    (ga,) = static.gradients([b], [a])
    np.testing.assert_allclose(ga.numpy(), [4.0])

    # scope machinery
    sc = static.Scope()
    with static.scope_guard(sc):
        v = static.create_global_var([2], 1.5, 'float32', name='gv')
        assert static.global_scope().find_var('gv') is v
    assert static.global_scope().find_var('gv') is None

    # py_func wraps a host callable as an op
    xt = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
    out_t = paddle.to_tensor(np.zeros(2, np.float32))
    res = static.py_func(lambda arr: arr * 3.0, xt, out_t)
    np.testing.assert_allclose(res.numpy(), [3.0, 6.0])

    # serialize/deserialize a recorded program
    prog = static.Program()
    with static.program_guard(prog):
        inp = static.data('x', [2, 3], 'float32')
        lin2 = nn.Linear(3, 2)
        out = lin2(inp)
    blob = static.serialize_program([inp], [out], program=prog)
    loaded = static.deserialize_program(blob)
    exe = static.Executor()
    feed = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    got = exe.run(loaded, feed={'x': feed}, fetch_list=[0])[0]
    np.testing.assert_allclose(got, feed @ lin2.weight.numpy()
                               + lin2.bias.numpy(), rtol=1e-5, atol=1e-5)

    # normalize_program returns the pruned executable form
    np_prog = static.normalize_program(prog, [inp], [out])
    got2 = exe.run(np_prog, feed={'x': feed}, fetch_list=[0])[0]
    np.testing.assert_allclose(got2, got, rtol=1e-6)


def test_static_nn_control_flow():
    """cond/case/switch_case/while_loop over lax control flow
    (reference fluid/layers/control_flow.py); cond grads flow to leaves
    of BOTH branches via the record-and-replay tape operands."""
    import paddle_tpu.nn.functional as F

    x = paddle.to_tensor(np.asarray([2.0], np.float32),
                         stop_gradient=False)
    out = static.nn.cond(paddle.to_tensor(True), lambda: x * 3,
                         lambda: x * 5)
    out.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0])
    x.clear_grad()
    out2 = static.nn.cond(paddle.to_tensor(False), lambda: x * 3,
                          lambda: x * 5)
    out2.backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    assert float(out2.numpy()[0]) == 10.0

    r = static.nn.switch_case(
        paddle.to_tensor(1),
        {0: lambda: paddle.to_tensor(np.float32(10.)),
         1: lambda: paddle.to_tensor(np.float32(20.))})
    assert float(r.numpy()) == 20.0

    i = paddle.to_tensor(np.asarray(0, np.int32))
    s = paddle.to_tensor(np.asarray(0.0, np.float32))
    iv, sv = static.nn.while_loop(lambda i, s: i < 5,
                                  lambda i, s: [i + 1, s + 2.0], [i, s])
    assert int(iv.numpy()) == 5 and float(sv.numpy()) == 10.0

    c = static.nn.case(
        [(paddle.to_tensor(False), lambda: paddle.to_tensor(np.float32(1.))),
         (paddle.to_tensor(True), lambda: paddle.to_tensor(np.float32(2.)))],
        default=lambda: paddle.to_tensor(np.float32(3.)))
    assert float(c.numpy()) == 2.0

    import pytest as _pytest
    with _pytest.raises(NotImplementedError, match='sequence'):
        static.nn.sequence_pool(None, 'sum')


def test_static_nn_cond_list_outputs_and_switch_grads():
    """cond branches may return nested lists (reference cond contract);
    switch_case differentiates through the tape like cond; empty
    branch_fns raise a clear ValueError."""
    import pytest as _pytest

    x = paddle.to_tensor(np.asarray([2.0], np.float32),
                         stop_gradient=False)
    a, b = static.nn.cond(paddle.to_tensor(True),
                          lambda: [x * 3, x * 7],
                          lambda: [x * 5, x * 9])
    np.testing.assert_allclose(a.numpy(), [6.0])
    np.testing.assert_allclose(b.numpy(), [14.0])
    (a + b).backward()
    np.testing.assert_allclose(x.grad.numpy(), [10.0])

    x.clear_grad()
    r = static.nn.switch_case(
        paddle.to_tensor(1),
        {0: lambda: x * 10, 1: lambda: x * 20})
    r.backward()
    np.testing.assert_allclose(r.numpy(), [40.0])
    np.testing.assert_allclose(x.grad.numpy(), [20.0])

    with _pytest.raises(ValueError, match='at least one'):
        static.nn.switch_case(paddle.to_tensor(0), [])


def test_static_nn_cond_structure_checks():
    """Branch-structure mismatches raise; negative switch keys raise;
    leafless branches (side-effect-only, None return) pass through."""
    import pytest as _pytest

    x = paddle.to_tensor(np.asarray([2.0], np.float32))
    with _pytest.raises(TypeError, match='same structure'):
        static.nn.cond(paddle.to_tensor(True),
                       lambda: [x * 3, x * 7],
                       lambda: [x * 5, [x * 9]])
    with _pytest.raises(ValueError, match='non-negative'):
        static.nn.switch_case(paddle.to_tensor(0),
                              {-1: lambda: x, 0: lambda: x * 2})
    assert static.nn.cond(paddle.to_tensor(True),
                          lambda: None, lambda: None) is None
