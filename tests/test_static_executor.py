"""static.Program record/replay (VERDICT r2 weak #6 + item 9): feeding
fresh values after build returns fresh fetches — the reference
ProgramDesc+Executor contract (executor.cc:166 Run, naive_executor.cc:38)
— and save/load_inference_model round-trips an executable artifact.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import static


def _build_program():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data('x', [4, 8], 'float32')
        lin = nn.Linear(8, 3)
        y = lin(x)
        out = paddle.nn.functional.relu(y)
    return prog, x, out, lin


def test_executor_replays_fresh_feeds():
    paddle.seed(11)
    prog, x, out, lin = _build_program()
    exe = static.Executor()

    rng = np.random.RandomState(0)
    f1 = rng.randn(4, 8).astype(np.float32)
    f2 = rng.randn(4, 8).astype(np.float32)

    r1 = exe.run(prog, feed={'x': f1}, fetch_list=[out])[0]
    r2 = exe.run(prog, feed={'x': f2}, fetch_list=[out])[0]

    w = lin.weight.numpy()
    b = lin.bias.numpy()
    np.testing.assert_allclose(r1, np.maximum(f1 @ w + b, 0), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(r2, np.maximum(f2 @ w + b, 0), rtol=1e-5,
                               atol=1e-5)
    assert not np.allclose(r1, r2)  # the old stale-fetch bug would equal


def test_executor_raises_on_unrecorded_program():
    # building OUTSIDE program_guard records nothing; feeding then must
    # raise, not silently return stale build-time values
    prog = static.Program()
    x = static.data('x', [2, 2], 'float32')  # goes to default program
    prog._feed_vars['x'] = x
    lin = nn.Linear(2, 2)
    y = lin(x)
    exe = static.Executor()
    with pytest.raises(RuntimeError, match='program_guard'):
        exe.run(prog, feed={'x': np.ones((2, 2), np.float32)},
                fetch_list=[y])


def test_save_load_inference_model_roundtrip(tmp_path):
    paddle.seed(5)
    prog, x, out, lin = _build_program()
    exe = static.Executor()
    rng = np.random.RandomState(1)
    feed = rng.randn(4, 8).astype(np.float32)
    exe.run(prog, feed={'x': feed}, fetch_list=[out])

    path = str(tmp_path / 'infer')
    x.name = 'x'
    static.save_inference_model(path, [x], [out], exe, program=prog)

    prog2, feed_names, fetch_targets = static.load_inference_model(path, exe)
    assert feed_names == ['x']
    got = exe.run(prog2, feed={'x': feed}, fetch_list=fetch_targets)[0]
    w, b = lin.weight.numpy(), lin.bias.numpy()
    np.testing.assert_allclose(got, np.maximum(feed @ w + b, 0),
                               rtol=1e-5, atol=1e-5)
