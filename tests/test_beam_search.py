"""BeamSearchDecoder + dynamic_decode (reference fluid/layers/rnn.py),
checked against brute-force enumeration on a deterministic toy cell."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.framework.core import Tensor


class _TableCell(nn.Layer):
    """Logits depend only on the previous token: logits = table[token].
    Makes the sequence distribution a simple Markov chain we can
    enumerate exactly."""

    def __init__(self, table):
        super().__init__()
        self._table = np.asarray(table, np.float32)

    def forward(self, inputs, states):
        tok = np.asarray(inputs.numpy()).astype(np.int64)   # [B*W]
        return Tensor(self._table[tok]), states


def _brute_force_best(table, start, end, steps, beam):
    """Exact top sequence by total log-prob over all token paths."""
    from itertools import product
    logp = np.log(np.exp(table) / np.exp(table).sum(-1, keepdims=True))
    vocab = table.shape[1]
    best, best_s = None, -np.inf
    for path in product(range(vocab), repeat=steps):
        s, prev, alive = 0.0, start, True
        for t in path:
            if not alive:
                if t != end:
                    s = -np.inf
                    break
                continue
            s += logp[prev, t]
            prev = t
            if t == end:
                alive = False
        if s > best_s:
            best_s, best = s, path
    return list(best), best_s


def test_beam_search_finds_optimal_markov_path():
    rng = np.random.RandomState(0)
    vocab, steps = 5, 4
    table = rng.randn(vocab, vocab).astype(np.float32)
    start, end = 0, vocab - 1
    cell = _TableCell(table)
    dec = nn.BeamSearchDecoder(cell, start_token=start, end_token=end,
                               beam_size=vocab * vocab)  # wide enough: exact
    init = Tensor(np.zeros((1, 2), np.float32))          # dummy state [B=1]
    preds, _ = nn.dynamic_decode(dec, inits=init, max_step_num=steps)
    got = preds.numpy()[0, :, 0].tolist()                # best beam
    want, _ = _brute_force_best(table, start, end, steps, None)
    # compare up to (and including) the first end token
    if end in want:
        want = want[:want.index(end) + 1]
    assert got[:len(want)] == want


def test_beam_search_batch_and_finished_semantics():
    vocab = 4
    # token 3 = end; from token 0 the argmax chain is 1 -> 2 -> 3(end)
    table = np.full((vocab, vocab), -5.0, np.float32)
    table[0, 1] = 5.0
    table[1, 2] = 5.0
    table[2, 3] = 5.0
    table[3, 3] = 5.0
    cell = _TableCell(table)
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=3, beam_size=2)
    init = Tensor(np.zeros((3, 2), np.float32))          # batch of 3
    preds, states, lengths = nn.dynamic_decode(dec, inits=init,
                                               max_step_num=10,
                                               return_length=True)
    out = preds.numpy()
    assert out.shape[0] == 3 and out.shape[2] == 2
    # every batch row's best beam decodes 1, 2, 3 then stops (end emitted)
    for b in range(3):
        assert out[b, :3, 0].tolist() == [1, 2, 3]
    # loop exited on all-finished before max_step_num (the runner-up
    # beam may wander a few extra steps before it emits end)
    assert out.shape[1] < 10
    # the best beam's length froze at 3 tokens (1, 2, end)
    assert (np.asarray(lengths.numpy())[:, 0] == 3).all()


def test_beam_search_lstm_shapes():
    """End-to-end with a real LSTMCell + projection; checks shape
    contract and that tile_beam_merge expands initial states."""
    paddle.seed(0)
    hidden, vocab, beam, batch = 16, 12, 3, 2
    cell = nn.LSTMCell(8, hidden)
    proj = nn.Linear(hidden, vocab)
    emb = nn.Embedding(vocab, 8)
    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=2,
                               beam_size=beam,
                               embedding_fn=emb,
                               output_fn=proj)
    h = Tensor(np.zeros((batch, hidden), np.float32))
    c = Tensor(np.zeros((batch, hidden), np.float32))
    preds, _ = nn.dynamic_decode(dec, inits=(h, c), max_step_num=5)
    out = preds.numpy()
    assert out.shape[0] == batch and out.shape[2] == beam
    assert out.shape[1] <= 5
    assert (out >= 0).all() and (out < vocab).all()


def test_dynamic_decode_guards_and_attention_dropout():
    """max_step_num=0 raises; attention dropout actually drops (review
    regression: dropout_p was silently ignored on the reference path)."""
    import pytest
    import paddle_tpu.nn.functional as F

    cell = _TableCell(np.zeros((3, 3), np.float32))
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=2, beam_size=1)
    with pytest.raises(ValueError, match='max_step_num'):
        nn.dynamic_decode(dec, inits=Tensor(np.zeros((1, 2), np.float32)),
                          max_step_num=0)

    paddle.seed(7)
    q = Tensor(np.random.RandomState(0).randn(2, 8, 2, 4).astype(np.float32))
    no_drop = F.scaled_dot_product_attention(q, q, q).numpy()
    paddle.seed(7)
    dropped = F.scaled_dot_product_attention(q, q, q, dropout_p=0.5,
                                             training=True).numpy()
    assert not np.allclose(no_drop, dropped)
    # eval mode ignores dropout
    paddle.seed(7)
    eval_out = F.scaled_dot_product_attention(q, q, q, dropout_p=0.5,
                                              training=False).numpy()
    np.testing.assert_allclose(eval_out, no_drop)
