"""GPT autoregressive generation over the static-shape KV cache.

Parity bar: greedy cached decode must reproduce argmax over repeated
FULL forwards exactly (the cache is an optimization, never a semantics
change). The static cache keeps every decode step the same shape, so
per-op executables are reused across tokens.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.text.models import GPTConfig, GPTForCausalLM


def _model(**kw):
    paddle.seed(1)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=32, dropout=0.0,
                    **kw)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.mark.slow
def test_greedy_generate_matches_full_forward():
    m = _model()
    rng = np.random.RandomState(0)
    prompt = paddle.to_tensor(rng.randint(0, 64, (2, 5)).astype(np.int32))
    out = m.generate(prompt, max_new_tokens=6)
    assert tuple(out.shape) == (2, 11)

    ids = prompt.numpy().astype(np.int32)
    for _ in range(6):
        logits = m(paddle.to_tensor(ids)).numpy()
        nxt = logits[:, -1].argmax(-1).astype(np.int32)[:, None]
        ids = np.concatenate([ids, nxt], axis=1)
    np.testing.assert_array_equal(out.numpy(), ids)


@pytest.mark.slow
def test_sampling_deterministic_and_in_topk():
    m = _model()
    rng = np.random.RandomState(2)
    prompt = paddle.to_tensor(rng.randint(0, 64, (1, 4)).astype(np.int32))
    s1 = m.generate(prompt, max_new_tokens=5, do_sample=True, top_k=4,
                    seed=7)
    s2 = m.generate(prompt, max_new_tokens=5, do_sample=True, top_k=4,
                    seed=7)
    np.testing.assert_array_equal(s1.numpy(), s2.numpy())
    s3 = m.generate(prompt, max_new_tokens=5, do_sample=True, top_k=4,
                    seed=8)
    assert s3.numpy().shape == s1.numpy().shape

    # every sampled token must be inside the step's top-k set
    ids = prompt.numpy().astype(np.int32)
    gen = s1.numpy()[:, 4:]
    for i in range(gen.shape[1]):
        logits = m(paddle.to_tensor(ids)).numpy()[:, -1]
        topk = np.argsort(logits[0])[-4:]
        assert gen[0, i] in topk
        ids = np.concatenate([ids, gen[:, i:i + 1]], axis=1)


def test_generate_respects_position_limit():
    m = _model()
    prompt = paddle.to_tensor(np.zeros((1, 30), np.int32))
    with pytest.raises(ValueError, match='max_position_embeddings'):
        m.generate(prompt, max_new_tokens=10)


def test_generate_training_mode_restored():
    m = _model()
    m.train()
    prompt = paddle.to_tensor(np.zeros((1, 3), np.int32))
    m.generate(prompt, max_new_tokens=2)
    assert m.training


def test_static_cache_overflow_raises():
    from paddle_tpu.text.models.gpt import GPTStaticCache
    m = _model()
    caches = [GPTStaticCache.empty(1, 4, 2, 16) for _ in range(2)]
    ids = paddle.to_tensor(np.zeros((1, 3), np.int32))
    _, caches = m(ids, caches=caches)
    with pytest.raises(ValueError, match='overflow'):
        m(paddle.to_tensor(np.zeros((1, 2), np.int32)), caches=caches)


def test_static_cache_rejects_grad_mode():
    from paddle_tpu.text.models.gpt import GPTStaticCache
    m = _model()
    m.train()
    caches = [GPTStaticCache.empty(1, 8, 2, 16) for _ in range(2)]
    ids = paddle.to_tensor(np.zeros((1, 3), np.int32))
    with pytest.raises(RuntimeError, match='inference-only'):
        m(ids, caches=caches)


def test_generate_zero_tokens_returns_prompt():
    m = _model()
    prompt = paddle.to_tensor(np.zeros((1, 3), np.int32))
    out = m.generate(prompt, max_new_tokens=0)
    np.testing.assert_array_equal(out.numpy(), prompt.numpy())


def test_qkv_split_last_is_bitwise_identical(monkeypatch):
    """PADDLE_TPU_QKV_SPLIT=last picks the same q/k/v channels as the
    default 5-D-reshape path — the flat [3*h*d] axis maps identically
    ([i3, ih, id] <-> i3*h*d + ih*d + id) so outputs must match exactly."""
    import paddle_tpu as paddle
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    cfg = dict(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
               max_position_embeddings=16, dropout=0.0)
    ids = np.random.RandomState(0).randint(0, 97, (2, 16)).astype(np.int32)

    # the reference must take the DEFAULT path even if a shell left the
    # A/B knob exported — otherwise the test compares last vs last
    monkeypatch.delenv('PADDLE_TPU_QKV_SPLIT', raising=False)
    paddle.seed(0)
    ref = GPTForCausalLM(GPTConfig(**cfg))
    out_ref = ref(paddle.to_tensor(ids)).numpy()

    monkeypatch.setenv('PADDLE_TPU_QKV_SPLIT', 'last')
    paddle.seed(0)
    alt = GPTForCausalLM(GPTConfig(**cfg))
    out_alt = alt(paddle.to_tensor(ids)).numpy()
    np.testing.assert_array_equal(out_ref, out_alt)
