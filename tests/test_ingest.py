"""Streaming ingestion plane (ISSUE 18): shard format round-trip and
corruption detection, canonical interleave arithmetic, reproducible
window shuffle, async==sync pipeline determinism, checkpointable
cursors with fingerprint guards, multi-worker DataLoader ordering, the
Model.fit integration, and the perf_report/bench surfacing."""
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', 'tools'))

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.data import (IngestCursor, IngestPipeline, ShardCorruptError,
                             ShardInterleave, ShardReader, ShardWriter,
                             list_shards, read_index, shards, window_shuffle,
                             write_shards)
from paddle_tpu.hapi.callbacks import Callback
from paddle_tpu.hapi.model import Model
from paddle_tpu.io import DataLoader, Dataset

_REPO = os.path.join(os.path.dirname(__file__), '..')


# -- shard format ------------------------------------------------------------

def test_shard_writer_reader_roundtrip(tmp_path):
    path = str(tmp_path / 'a.shard')
    recs = [b'rec-%d' % i * (i % 3 + 1) for i in range(37)]
    with ShardWriter(path, index_stride=8) as w:
        for r in recs:
            w.append(r)
    reader = ShardReader(path)
    assert len(reader) == 37
    assert list(reader) == recs
    assert reader.read(0) == recs[0]
    assert reader.read(36) == recs[36]
    # strided seek: iter_from lands mid-shard without scanning from 0
    assert list(reader.iter_from(20)) == recs[20:]
    idx = read_index(path, verify=True)      # CRC agrees with the bytes
    assert idx['records'] == 37
    assert idx['payload_bytes'] == sum(len(r) for r in recs)


def test_shard_random_access_at(tmp_path):
    path = str(tmp_path / 'a.shard')
    with ShardWriter(path, index_stride=4) as w:
        for i in range(21):
            w.append(b'x%d' % i)
    reader = ShardReader(path)
    # at() through the persistent handle, out of order
    for i in (20, 0, 13, 7, 13):
        assert reader.at(i) == b'x%d' % i
    with pytest.raises(IndexError):
        reader.at(21)
    reader.close()
    reader.close()                            # idempotent


def test_shard_publish_is_atomic(tmp_path):
    path = str(tmp_path / 'b.shard')
    w = ShardWriter(path)
    w.append(b'one')
    assert not os.path.exists(path)           # nothing visible pre-close
    w.abort()
    assert list(tmp_path.iterdir()) == []     # abort leaves no droppings
    # an exception inside the context manager aborts, not publishes
    with pytest.raises(RuntimeError):
        with ShardWriter(path) as w2:
            w2.append(b'two')
            raise RuntimeError('writer died')
    assert list(tmp_path.iterdir()) == []


def test_corruption_detection(tmp_path):
    path = str(tmp_path / 'c.shard')
    with ShardWriter(path) as w:
        for i in range(10):
            w.append(b'payload-%d' % i)
    # truncation flips the CRC
    with open(path, 'r+b') as f:
        f.truncate(os.path.getsize(path) - 3)
    with pytest.raises(ShardCorruptError):
        read_index(path, verify=True)
    # a shard without its sidecar is invisible to discovery and refused
    # by the reader (writer died between data and index publish)
    os.remove(path + '.idx')
    assert list_shards(str(tmp_path)) == []
    with pytest.raises(ShardCorruptError):
        ShardReader(path)


def test_write_shards_roundtrips_through_interleave(tmp_path):
    xs = [np.float32(i) for i in range(23)]
    paths = write_shards(xs, str(tmp_path), 4)
    assert paths == list_shards(str(tmp_path))
    # write_shards distributes record-level round robin — exactly the
    # canonical interleave order — so the merged stream is the original
    back = [shards.decode_sample(p) for p in ShardInterleave(paths)]
    assert back == xs


# -- canonical interleave arithmetic -----------------------------------------

def _naive_interleave(counts):
    """(shard, record) pairs in record-level round-robin order."""
    out = []
    for r in range(max(counts)):
        for s, c in enumerate(counts):
            if c > r:
                out.append((s, r))
    return out


def test_interleave_locate_matches_naive_simulation():
    for counts in ([5, 3, 7], [1, 1, 1, 1], [4], [6, 0, 2, 9, 1]):
        order = _naive_interleave(counts)
        assert shards.interleave_total(counts) == len(order)
        for p, expect in enumerate(order):
            assert shards.interleave_locate(counts, p) == expect
    with pytest.raises(IndexError):
        shards.interleave_locate([2, 2], 4)


def _uneven_shards(tmp_path, counts=(9, 4, 13, 1)):
    paths = []
    for s, c in enumerate(counts):
        p = str(tmp_path / ('u-%d.shard' % s))
        with ShardWriter(p, index_stride=4) as w:
            for r in range(c):
                w.append(b'%d:%d' % (s, r))
        paths.append(p)
    return paths


def test_interleave_seek_and_threads_match_canonical(tmp_path):
    paths = _uneven_shards(tmp_path)
    trace = []
    canonical = list(ShardInterleave(paths, trace=trace))
    counts = [len(ShardReader(p)) for p in paths]
    assert trace == _naive_interleave(counts)
    # seek to any stream position == suffix of the canonical stream
    for start in (0, 1, 7, 13, 26, len(canonical) - 1, len(canonical)):
        assert list(ShardInterleave(paths, start=start)) \
            == canonical[start:]
    # reader threads race on IO but the merged order never moves
    for k in (1, 2, 3):
        assert list(ShardInterleave(paths, reader_threads=k,
                                    queue_records=4)) == canonical


# -- window shuffle ----------------------------------------------------------

def test_window_shuffle_reproducible_per_seed_epoch():
    items = list(range(50))
    W = 16

    def run(seed, epoch, start=0):
        stream = iter(items[(start // W) * W:])
        return list(window_shuffle(stream, len(items), W, seed, epoch,
                                   start=start))

    a = run(3, 0)
    assert a == run(3, 0)                     # same coordinates, same order
    assert sorted(a) == items                 # a permutation, nothing lost
    assert a != items                         # and actually shuffled
    assert run(3, 1) != a                     # epoch reshuffles
    assert run(4, 0) != a                     # seed reshuffles
    # shuffle radius is bounded by the window
    for pos, v in enumerate(a):
        assert abs(pos - items.index(v)) < W
    # mid-window resume: the suffix of the full stream, exactly
    for start in (1, 15, 16, 23, 49):
        assert run(3, 0, start=start) == a[start:]


def test_window_shuffle_zero_window_is_passthrough():
    items = list(range(10))
    assert list(window_shuffle(iter(items), 10, 0, 1, 0)) == items


# -- IngestPipeline ----------------------------------------------------------

def _sample_shards(tmp_path, n=48, dim=3, n_shards=4):
    rng = np.random.RandomState(7)
    xs = rng.randn(n, dim).astype(np.float32)
    paths = write_shards(list(xs), str(tmp_path), n_shards)
    return paths, xs


def _collect(pipe):
    return [np.asarray(b) for b in pipe]


def test_pipeline_async_equals_sync_equals_threaded(tmp_path):
    paths, xs = _sample_shards(tmp_path)
    kw = dict(batch_size=4, shuffle_window=16, seed=5, device_put=False)
    sync = _collect(IngestPipeline(paths, prefetch=0, **kw))
    async_ = _collect(IngestPipeline(paths, prefetch=2, **kw))
    threaded = _collect(IngestPipeline(paths, prefetch=2,
                                       reader_threads=2, **kw))
    assert len(sync) == 12
    for a, b, c in zip(sync, async_, threaded):
        assert np.array_equal(a, b) and np.array_equal(a, c)
    # shuffled stream covers the data exactly once
    flat = np.concatenate(sync).reshape(-1, xs.shape[1])
    assert np.array_equal(np.sort(flat, axis=0), np.sort(xs, axis=0))


def test_pipeline_epoch_advance_reshuffles(tmp_path):
    paths, _ = _sample_shards(tmp_path)
    pipe = IngestPipeline(paths, batch_size=4, shuffle_window=16,
                          device_put=False, prefetch=0)
    e0 = _collect(pipe)
    assert pipe.epoch == 1                    # full epoch advances
    assert pipe.last_epoch_stats['records'] == 48
    assert pipe.last_epoch_stats['batches'] == 12
    e1 = _collect(pipe)
    assert not all(np.array_equal(a, b) for a, b in zip(e0, e1))
    # set_epoch pins the shuffle (evaluation replays)
    pipe.set_epoch(0)
    assert all(np.array_equal(a, b) for a, b in zip(e0, _collect(pipe)))


def test_pipeline_len_and_drop_last(tmp_path):
    paths = write_shards([np.float32(i) for i in range(10)],
                         str(tmp_path), 2)
    keep = IngestPipeline(paths, batch_size=4, device_put=False)
    drop = IngestPipeline(paths, batch_size=4, drop_last=True,
                          device_put=False)
    assert len(keep) == 3 and len(drop) == 2
    got = _collect(keep)
    assert [g.shape[0] for g in got] == [4, 4, 2]
    assert [g.shape[0] for g in _collect(drop)] == [4, 4]


def test_cursor_midepoch_resume_bit_identical(tmp_path):
    """Kill mid-epoch with a LIVE shuffle buffer (position not window
    aligned): the resumed pipeline must deliver the remaining batches
    bit-identically AND touch the underlying shard records in exactly
    the reference run's order from the resumed window on."""
    paths, _ = _sample_shards(tmp_path)
    W, bs = 16, 4
    kw = dict(batch_size=bs, shuffle_window=W, seed=5, device_put=False)

    ref_trace = []
    ref = _collect(IngestPipeline(paths, record_trace=ref_trace,
                                  prefetch=2, **kw))

    pipe_a = IngestPipeline(paths, prefetch=2, **kw)
    it = iter(pipe_a)
    got = [np.asarray(next(it)) for _ in range(7)]   # 28 records: window 1
    cur = pipe_a.cursor()
    it.close()                                       # consumer dies here
    assert (cur.records, cur.batches) == (28, 7)
    assert cur.rng_state is not None                 # live window state

    # fresh process-state pipeline, cursor round-tripped through a dict
    resumed_trace = []
    pipe_b = IngestPipeline(paths, record_trace=resumed_trace,
                            prefetch=2, **kw)
    pipe_b.restore(IngestCursor.from_state(cur.to_state()))
    rest = _collect(pipe_b)
    assert len(got) + len(rest) == len(ref)
    for a, b in zip(got + rest, ref):
        assert np.array_equal(a, b)
    # record-access log: the resumed reader seeks to the window start
    # (28 // 16 * 16 = 16) and replays the reference order exactly
    assert resumed_trace == ref_trace[16:]
    # the resumed epoch completes and rolls over like an uninterrupted one
    assert pipe_b.epoch == 1
    assert pipe_b.last_epoch_stats['records'] == 48 - 28


def test_cursor_fingerprint_guard(tmp_path):
    paths_a, _ = _sample_shards(tmp_path / 'a')
    # same shard names but a different record count: the fingerprint
    # (basename:count per shard) must refuse the cursor
    paths_b = write_shards([np.float32(i) for i in range(40)],
                           str(tmp_path / 'b'), 4)
    pipe_a = IngestPipeline(paths_a, batch_size=4)
    cur = pipe_a.cursor()
    other = IngestPipeline(paths_b, batch_size=4)
    with pytest.raises(ValueError, match='fingerprint'):
        other.restore(cur)
    with pytest.raises(ValueError, match='out of range'):
        pipe_a.restore(IngestCursor(records=49,
                                    fingerprint=pipe_a.fingerprint()))


def test_pipeline_backpressure_and_counters(tmp_path):
    from paddle_tpu.monitor import export
    from paddle_tpu.monitor.registry import MetricRegistry
    paths, _ = _sample_shards(tmp_path)
    reg = MetricRegistry()
    pipe = IngestPipeline(paths, batch_size=4, prefetch=2,
                          device_put=False, registry=reg)
    list(pipe)
    snap = export.to_dict(reg)

    def val(name):
        return snap[name]['samples'][0]['value']
    assert val('ingest_records_total') == 48
    assert val('ingest_batches_total') == 12
    assert val('ingest_epochs_total') == 1
    assert val('ingest_examples_per_second') > 0
    assert val('ingest_wait_seconds_total') >= 0


# -- multi-worker DataLoader (satellite) -------------------------------------

class _SquareData(Dataset):
    def __init__(self, n=23):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.float32(i * i)


def _loader_values(**kw):
    loader = DataLoader(_SquareData(), **kw)
    return [np.asarray(b).ravel().tolist() for b in loader]


def test_multiworker_preserves_batch_order():
    """The reorder thread must yield batches in sampler order no matter
    which worker finishes first."""
    base = _loader_values(batch_size=4, shuffle=False, num_workers=0)
    multi = _loader_values(batch_size=4, shuffle=False, num_workers=2)
    assert multi == base
    assert multi[-1] == [np.float32(20 * 20), np.float32(21 * 21),
                         np.float32(22 * 22)]     # tail batch kept


def test_multiworker_shuffle_matches_single_process():
    """The shuffle permutation is drawn in the main process: the same
    seed must give the same batch stream at any worker count."""
    np.random.seed(123)
    single = _loader_values(batch_size=4, shuffle=True, num_workers=0)
    np.random.seed(123)
    multi = _loader_values(batch_size=4, shuffle=True, num_workers=2)
    assert multi == single


def test_multiworker_drop_last():
    vals = _loader_values(batch_size=4, shuffle=False, num_workers=2,
                          drop_last=True)
    assert len(vals) == 5
    assert all(len(v) == 4 for v in vals)


# -- Model.fit integration ---------------------------------------------------

def test_model_fit_accepts_pipeline(tmp_path):
    rng = np.random.RandomState(11)
    xs = rng.randn(48, 4).astype(np.float32)
    ys = (xs.sum(axis=1, keepdims=True) * 0.5).astype(np.float32)
    paths = write_shards([(x, y) for x, y in zip(xs, ys)],
                         str(tmp_path), 3)
    pipe = IngestPipeline(paths, batch_size=8, shuffle_window=16, seed=2)

    paddle.seed(9)
    net = nn.Linear(4, 1)
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(
        learning_rate=0.05, parameters=net.parameters()),
        loss=nn.MSELoss())

    class _Count(Callback):
        steps = 0
        tl = None

        def on_train_batch_end(self, step, logs=None):
            _Count.steps += 1
            _Count.tl = m._perf_timeline    # fit clears it on exit

    m.fit(pipe, epochs=2, verbose=0, callbacks=[_Count()])
    assert _Count.steps == 2 * len(pipe) == 12
    assert pipe.last_epoch_stats is not None
    # fit charged data_wait from the pipeline's measured queue-wait
    summary = _Count.tl.summary()
    assert summary.get('data_wait', {}).get('count', 0) >= 12


# -- perf_report surfacing (satellite) ---------------------------------------

def test_perf_report_flags_input_bound_phase():
    import perf_report

    def snap(wait_sum):
        return json.dumps({'perf_step_phase_seconds': {'samples': [
            {'labels': {'phase': 'data_wait'}, 'count': 10,
             'sum': wait_sum},
            {'labels': {'phase': 'device_block'}, 'count': 10,
             'sum': 3.0},
        ]}})

    starved = '\n'.join(perf_report.report(snap_text=snap(4.0)))
    assert 'input-bound' in starved
    healthy = '\n'.join(perf_report.report(snap_text=snap(0.05)))
    assert 'input-bound' not in healthy


def test_perf_report_bench_table_carries_data_wait_frac():
    import perf_report
    path = os.path.join(_REPO, 'docs', 'bench_ingest_cpu.jsonl')
    lines = perf_report.report(bench_paths=[path])
    table = '\n'.join(lines)
    assert 'data_wait_frac' in table
    assert 'ingest_examples_per_sec' in table


# -- the bench rung itself (slow: excluded from tier-1) ----------------------

@pytest.mark.slow
def test_bench_ingest_rung_beats_sync_baseline():
    import bench_extra
    rows = bench_extra.bench_ingest(on_tpu=False)
    by = {r['metric']: r for r in rows}
    eps = by['ingest_examples_per_sec']
    frac = by['ingest_data_wait_frac']
    # loose bounds: the committed capture pins the real numbers; this
    # rung just proves the mechanism still works on a noisy 1-core box
    assert eps['speedup_vs_dataloader'] > 1.5
    assert frac['value'] < 0.5
    assert frac['value'] < frac['dataloader_data_wait_frac']
