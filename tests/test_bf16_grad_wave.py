"""bf16 BACKWARD sweep for the families the r4 op sweeps only covered
forward (VERDICT r4 weak #7 / next #7): conv, pool, norm, interp.

The r4 native-dtype audit shipped a conv backward that CRASHED for bf16
models (f32 cotangent meeting bf16 operands in the conv transpose) —
and no test noticed, because the bf16 pass was forward-only. This wave
runs every case's backward on bf16 activations and compares the
analytic grads against the f32 analytic grads of the same case
(finite differences are noise at bf16 resolution; the f32 tape is the
reference — the reference repo's op_accuracy_white_list pattern:
python/paddle/fluid/tests/unittests/white_list/op_accuracy_white_list.py,
looser thresholds for low-precision ops rather than skipped checks).

Every case therefore asserts two things:
  1. the bf16 backward RUNS (the r4 regression class), and
  2. its grads stay within bf16 tolerance of the f32 grads.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _distinct(shape, lo=-2.0, hi=2.0, seed=0):
    """Values that stay pairwise-distinct AFTER bf16 rounding — max-pool
    ties would otherwise route grads differently between the f32 and
    bf16 runs."""
    n = int(np.prod(shape))
    grid = np.linspace(lo, hi, max(n, 2), dtype=np.float32)
    return np.random.RandomState(seed).permutation(grid)[:n].reshape(shape)


def _smooth(shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).standard_normal(shape)
            .astype(np.float32) * scale)


def _grads(fn, inputs, cast_bf16):
    """Run fn over float leaves (optionally cast to bf16 before the op),
    sum-backward, return {name: grad ndarray}. Leaves stay f32 so the
    two runs' grads are directly comparable; the cast puts every op —
    forward AND backward — on bf16 arrays, the regression surface."""
    ts = {}
    for k, v in inputs.items():
        ts[k] = paddle.to_tensor(
            v, stop_gradient=not np.issubdtype(v.dtype, np.floating))
    args = {k: (t.astype('bfloat16')
                if cast_bf16 and not t.stop_gradient else t)
            for k, t in ts.items()}
    out = fn(**args)
    if isinstance(out, (list, tuple)):
        out = out[0]
    # weighted sum, not plain sum: for mean-subtracting ops (batch_norm
    # et al.) the x-grad of a plain sum is analytically ~0 and the
    # comparison would be rounding noise against rounding noise
    r = paddle.to_tensor(np.random.RandomState(123)
                         .standard_normal(tuple(out.shape))
                         .astype(np.float32))
    (out.astype('float32') * r).sum().backward()
    return {k: t.grad.numpy().astype(np.float64)
            for k, t in ts.items() if not t.stop_gradient}


def _check(fn, inputs, rtol=0.1, atol_frac=0.04):
    g32 = _grads(fn, inputs, cast_bf16=False)
    g16 = _grads(fn, inputs, cast_bf16=True)
    assert set(g16) == set(g32) and g32, 'no float grads flowed'
    for k in g32:
        scale = np.abs(g32[k]).max() + 1e-6
        np.testing.assert_allclose(
            g16[k], g32[k], rtol=rtol, atol=atol_frac * scale,
            err_msg='bf16 grad diverged from f32 for input %r' % k)


# each case: (name, fn(**tensors), {input: ndarray}, per-case tol overrides)
CASES = [
    # --- conv: the family that shipped broken in r4 --------------------
    ('conv1d', lambda x, w: F.conv1d(x, w),
     {'x': _smooth((2, 3, 12)), 'w': _smooth((4, 3, 3), 1)}, {}),
    ('conv2d', lambda x, w: F.conv2d(x, w),
     {'x': _smooth((2, 3, 10, 10)), 'w': _smooth((4, 3, 3, 3), 1)}, {}),
    ('conv2d_bias', lambda x, w, b: F.conv2d(x, w, bias=b),
     {'x': _smooth((2, 3, 8, 8)), 'w': _smooth((4, 3, 3, 3), 1),
      'b': _smooth((4,), 2)}, {}),
    ('conv2d_stride2_pad1', lambda x, w: F.conv2d(x, w, stride=2, padding=1),
     {'x': _smooth((2, 3, 9, 9)), 'w': _smooth((4, 3, 3, 3), 1)}, {}),
    ('conv2d_dilation2', lambda x, w: F.conv2d(x, w, dilation=2),
     {'x': _smooth((1, 2, 12, 12)), 'w': _smooth((3, 2, 3, 3), 1)}, {}),
    ('conv2d_groups2', lambda x, w: F.conv2d(x, w, groups=2),
     {'x': _smooth((2, 4, 8, 8)), 'w': _smooth((6, 2, 3, 3), 1)}, {}),
    ('conv2d_depthwise', lambda x, w: F.conv2d(x, w, groups=4),
     {'x': _smooth((2, 4, 8, 8)), 'w': _smooth((4, 1, 3, 3), 1)}, {}),
    ('conv2d_nhwc',
     lambda x, w: F.conv2d(x, w, data_format='NHWC'),
     {'x': _smooth((2, 8, 8, 3)), 'w': _smooth((4, 3, 3, 3), 1)}, {}),
    ('conv2d_same',
     lambda x, w: F.conv2d(x, w, padding='SAME'),
     {'x': _smooth((2, 3, 8, 8)), 'w': _smooth((4, 3, 3, 3), 1)}, {}),
    ('conv3d', lambda x, w: F.conv3d(x, w),
     {'x': _smooth((1, 2, 6, 6, 6)), 'w': _smooth((3, 2, 3, 3, 3), 1)}, {}),
    ('conv1d_transpose', lambda x, w: F.conv1d_transpose(x, w),
     {'x': _smooth((2, 4, 10)), 'w': _smooth((4, 3, 3), 1)}, {}),
    ('conv2d_transpose', lambda x, w: F.conv2d_transpose(x, w),
     {'x': _smooth((2, 4, 7, 7)), 'w': _smooth((4, 3, 3, 3), 1)}, {}),
    ('conv2d_transpose_s2op1',
     lambda x, w: F.conv2d_transpose(x, w, stride=2, padding=1,
                                     output_padding=1),
     {'x': _smooth((1, 4, 6, 6)), 'w': _smooth((4, 3, 3, 3), 1)}, {}),
    ('conv3d_transpose', lambda x, w: F.conv3d_transpose(x, w),
     {'x': _smooth((1, 3, 5, 5, 5)), 'w': _smooth((3, 2, 3, 3, 3), 1)}, {}),
    # --- pooling -------------------------------------------------------
    ('max_pool1d', lambda x: F.max_pool1d(x, 2, 2),
     {'x': _distinct((2, 3, 12))}, {}),
    ('max_pool2d', lambda x: F.max_pool2d(x, 2, 2),
     {'x': _distinct((2, 3, 8, 8))}, {}),
    ('max_pool2d_k3s2p1', lambda x: F.max_pool2d(x, 3, 2, padding=1),
     {'x': _distinct((2, 2, 9, 9))}, {}),
    ('max_pool3d', lambda x: F.max_pool3d(x, 2, 2),
     {'x': _distinct((1, 2, 6, 6, 6))}, {}),
    ('avg_pool1d', lambda x: F.avg_pool1d(x, 2, 2),
     {'x': _smooth((2, 3, 12))}, {}),
    ('avg_pool2d', lambda x: F.avg_pool2d(x, 2, 2),
     {'x': _smooth((2, 3, 8, 8))}, {}),
    ('avg_pool2d_pad', lambda x: F.avg_pool2d(x, 3, 2, padding=1),
     {'x': _smooth((2, 2, 9, 9))}, {}),
    ('avg_pool3d', lambda x: F.avg_pool3d(x, 2, 2),
     {'x': _smooth((1, 2, 6, 6, 6))}, {}),
    ('adaptive_avg_pool1d', lambda x: F.adaptive_avg_pool1d(x, 4),
     {'x': _smooth((2, 3, 12))}, {}),
    ('adaptive_avg_pool2d', lambda x: F.adaptive_avg_pool2d(x, 3),
     {'x': _smooth((2, 3, 9, 9))}, {}),
    ('adaptive_max_pool2d', lambda x: F.adaptive_max_pool2d(x, 2),
     {'x': _distinct((1, 2, 8, 8))}, {}),
    # --- norms (training-mode statistics) ------------------------------
    ('batch_norm',
     lambda x, w, b: F.batch_norm(
         x, paddle.zeros([3]), paddle.ones([3]), weight=w, bias=b,
         training=True),
     {'x': _smooth((4, 3, 6, 6)), 'w': _smooth((3,), 1, 0.5),
      'b': _smooth((3,), 2, 0.5)}, {}),
    ('batch_norm_nhwc',
     lambda x, w, b: F.batch_norm(
         x, paddle.zeros([3]), paddle.ones([3]), weight=w, bias=b,
         training=True, data_format='NHWC'),
     {'x': _smooth((4, 6, 6, 3)), 'w': _smooth((3,), 1, 0.5),
      'b': _smooth((3,), 2, 0.5)}, {}),
    ('layer_norm',
     lambda x, w, b: F.layer_norm(x, 16, weight=w, bias=b),
     {'x': _smooth((4, 6, 16)), 'w': _smooth((16,), 1, 0.5),
      'b': _smooth((16,), 2, 0.5)}, {}),
    ('group_norm',
     lambda x, w, b: F.group_norm(x, 2, weight=w, bias=b),
     {'x': _smooth((2, 4, 6, 6)), 'w': _smooth((4,), 1, 0.5),
      'b': _smooth((4,), 2, 0.5)}, {}),
    ('instance_norm', lambda x: F.instance_norm(x),
     {'x': _smooth((2, 3, 6, 6))}, {}),
    ('local_response_norm', lambda x: F.local_response_norm(x, 3),
     {'x': _smooth((2, 4, 6, 6))}, {}),
    ('normalize', lambda x: F.normalize(x, axis=1),
     {'x': _smooth((4, 8))}, {}),
    # --- interpolate / upsample ----------------------------------------
    ('interp_nearest_x2',
     lambda x: F.interpolate(x, scale_factor=2, mode='nearest'),
     {'x': _smooth((2, 3, 6, 6))}, {}),
    ('interp_bilinear_size',
     lambda x: F.interpolate(x, size=(9, 9), mode='bilinear'),
     {'x': _smooth((2, 3, 6, 6))}, {}),
    ('interp_bilinear_corners',
     lambda x: F.interpolate(x, size=(11, 11), mode='bilinear',
                             align_corners=True),
     {'x': _smooth((2, 3, 6, 6))}, {}),
    ('interp_trilinear',
     lambda x: F.interpolate(x, scale_factor=2, mode='trilinear'),
     {'x': _smooth((1, 2, 4, 4, 4))}, {}),
    ('interp_down_bilinear',
     lambda x: F.interpolate(x, size=(4, 4), mode='bilinear'),
     {'x': _smooth((2, 3, 8, 8))}, {}),
    # --- MXU partners the conv regression travels with ------------------
    ('linear', lambda x, w, b: F.linear(x, w, b),
     {'x': _smooth((4, 16)), 'w': _smooth((16, 8), 1),
      'b': _smooth((8,), 2)}, {}),
    ('matmul', lambda x, y: paddle.matmul(x, y),
     {'x': _smooth((4, 12)), 'y': _smooth((12, 6), 1)}, {}),
    ('matmul_bcast', lambda x, y: paddle.matmul(x, y),
     {'x': _smooth((2, 4, 8)), 'y': _smooth((8, 5), 1)}, {}),
    ('embedding_path',
     lambda ids, w: F.embedding(ids, w),
     {'ids': np.array([[0, 2], [3, 1]], np.int64),
      'w': _smooth((5, 6), 1)}, {}),
    ('softmax_ce',
     lambda x: F.cross_entropy(x, paddle.to_tensor(
         np.array([1, 0, 3, 2], np.int64))),
     {'x': _smooth((4, 6))}, {'rtol': 0.15, 'atol_frac': 0.06}),
    ('pad_reflect',
     lambda x: F.pad(x, [1, 1, 1, 1], mode='reflect'),
     {'x': _smooth((1, 2, 6, 6))}, {}),
]


@pytest.mark.parametrize('name,fn,inputs,tol',
                         CASES, ids=[c[0] for c in CASES])
def test_bf16_grad(name, fn, inputs, tol):
    _check(fn, inputs, **tol)


def test_wave_size():
    # the VERDICT r4 bar: a bf16 grad wave of >= 40 cases
    assert len(CASES) >= 40, len(CASES)
