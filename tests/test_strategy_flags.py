"""Strategy flags must transform the program (VERDICT r1 item 3).

Modeled on the reference's meta-optimizer tests
(test_fleet_amp_meta_optimizer.py etc.): set a DistributedStrategy flag,
build the fleet step, and assert on the transformed program — here the
jaxpr instead of the rewritten ProgramDesc — plus loss-parity runs.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.sp import disable_sequence_parallel
from paddle_tpu.text.models import GPTConfig, GPTForCausalLM


def _model(seed=0, **kw):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=32, dropout=0.0,
                    **kw)
    return GPTForCausalLM(cfg)


def _batch(b=8, s=32, vocab=128):
    rng = np.random.RandomState(7)
    ids = paddle.to_tensor(rng.randint(0, vocab, (b, s)).astype(np.int32))
    lbl = paddle.to_tensor(rng.randint(0, vocab, (b, s)).astype(np.int32))
    return ids, lbl


def _fleet_step(model, strategy):
    fleet.init(is_collective=True, strategy=strategy)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    return fleet.fleet_train_step(
        model, lambda lg, lb: model.loss(lg, lb), opt, strategy=strategy)


@pytest.fixture(autouse=True)
def _sp_cleanup():
    yield
    disable_sequence_parallel()


def _dp_strategy(**hybrid):
    s = fleet.DistributedStrategy()
    cfg = {'dp_degree': 8, 'mp_degree': 1, 'pp_degree': 1,
           'sharding_degree': 1, 'sp_degree': 1}
    cfg.update(hybrid)
    s.hybrid_configs = cfg
    return s


def test_amp_flag_changes_jaxpr_and_trains():
    ids, lbl = _batch()
    base = _fleet_step(_model(), _dp_strategy())
    base_jaxpr = base.trace_jaxpr(ids, lbl)
    assert 'bf16' not in base_jaxpr

    s = _dp_strategy()
    s.amp = True
    model = _model()
    step = _fleet_step(model, s)
    amp_jaxpr = step.trace_jaxpr(ids, lbl)
    assert 'bf16' in amp_jaxpr  # compute happens in bfloat16
    # master params stay fp32 and the step still trains
    loss0 = float(step(ids, lbl).numpy())
    loss1 = float(step(ids, lbl).numpy())
    assert np.isfinite(loss0) and np.isfinite(loss1)
    assert loss1 < loss0
    p = next(iter(model.parameters()))
    assert str(p._data.dtype) == 'float32'


@pytest.mark.slow
def test_recompute_flag_changes_jaxpr_and_matches():
    ids, lbl = _batch()

    m0 = _model(seed=11)
    base = _fleet_step(m0, _dp_strategy())
    base_jaxpr = base.trace_jaxpr(ids, lbl)
    base_losses = [float(base(ids, lbl).numpy()) for _ in range(2)]

    s = _dp_strategy()
    s.recompute = True
    m1 = _model(seed=11)
    step = _fleet_step(m1, s)
    jaxpr = step.trace_jaxpr(ids, lbl)
    # jax.vjp partial-evaluates the checkpoint during tracing, so remat
    # manifests as the forward matmuls re-appearing in the backward —
    # strictly more dot_generals than the store-activations program
    assert jaxpr.count('dot_general') > base_jaxpr.count('dot_general')
    losses = [float(step(ids, lbl).numpy()) for _ in range(2)]
    # recompute changes memory, not math
    np.testing.assert_allclose(losses, base_losses, rtol=2e-4)


def test_recompute_plain_model_falls_back_to_global_remat():
    """Models without enable_recompute get whole-forward remat."""
    import paddle_tpu.nn as nn

    class Tiny(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 32)
            self.fc2 = nn.Linear(32, 4)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    paddle.seed(3)
    model = Tiny()
    s = _dp_strategy()
    s.recompute = True
    fleet.init(is_collective=True, strategy=s)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    import paddle_tpu.nn.functional as F
    step = fleet.fleet_train_step(
        model, lambda out, lb: F.cross_entropy(out, lb), opt, strategy=s)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (8,)).astype(np.int64))

    paddle.seed(3)
    base_model = Tiny()
    s0 = _dp_strategy()
    fleet.init(is_collective=True, strategy=s0)
    opt0 = paddle.optimizer.SGD(learning_rate=0.1,
                                parameters=base_model.parameters())
    base = fleet.fleet_train_step(
        base_model, lambda out, lb: F.cross_entropy(out, lb), opt0,
        strategy=s0)
    assert step.trace_jaxpr(x, y).count('dot_general') > \
        base.trace_jaxpr(x, y).count('dot_general')
    assert np.isfinite(float(step(x, y).numpy()))


@pytest.mark.slow
def test_fp16_amp_dynamic_loss_scaling():
    """pure-fp16 engages loss scaling; finite steps advance the growth
    counter and training proceeds on fp32 master weights."""
    s = _dp_strategy()
    s.amp = True
    s.amp_configs['use_pure_fp16'] = True
    s.amp_configs['use_bf16'] = False
    s.amp_configs['init_loss_scaling'] = 1024.0
    model = _model()
    step = _fleet_step(model, s)
    ids, lbl = _batch()
    jaxpr = step.trace_jaxpr(ids, lbl)
    assert 'f16' in jaxpr and 'is_finite' in jaxpr
    l0 = float(step(ids, lbl).numpy())
    l1 = float(step(ids, lbl).numpy())
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0
    assert float(step._ls_scale) == 1024.0  # finite: scale held
    assert int(step._ls_growth) == 2        # growth counter advanced

    # overflow path: the default 65536 scale overflows fp16 intermediates
    # on this model — the update is SKIPPED and the scale halves
    s2 = _dp_strategy()
    s2.amp = True
    s2.amp_configs['use_pure_fp16'] = True
    s2.amp_configs['use_bf16'] = False
    m2 = _model()
    step2 = _fleet_step(m2, s2)
    before = np.array(next(iter(m2.parameters()))._data)
    step2(ids, lbl)
    after = np.array(next(iter(m2.parameters()))._data)
    if float(step2._ls_scale) < 65536.0:   # overflow detected
        np.testing.assert_array_equal(before, after)


@pytest.mark.slow
def test_sp_with_dropout_builds_and_steps():
    """r3 raised at build time; since r4 sp composes with dropout via
    sp-aware folded keys (full coverage: tests/test_dropout_parallel.py)."""
    s = _dp_strategy(dp_degree=2, sp_degree=4)
    s.sequence_parallel = True
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=32, dropout=0.1)
    model = GPTForCausalLM(cfg)
    fleet.init(is_collective=True, strategy=s)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = fleet.fleet_train_step(model, lambda lg, lb: model.loss(lg, lb),
                                  opt, strategy=s)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 128, (8, 32)).astype(np.int32))
    assert np.isfinite(float(step(ids, ids).numpy()))


def test_recompute_propagates_buffer_updates():
    """BN running stats inside a recompute segment must still update."""
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.fleet.utils import recompute

    paddle.seed(0)
    seg = nn.Sequential(nn.Linear(8, 8), nn.BatchNorm1D(8))
    seg.train()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 8).astype(np.float32) * 3 + 1,
                         stop_gradient=False)
    before = np.array(seg[1]._mean.numpy())
    out = recompute(seg, x)
    after = np.array(seg[1]._mean.numpy())
    assert not np.allclose(before, after)
    # and gradients flow to the segment's params
    out.sum().backward()
    assert seg[0].weight.grad is not None


def test_sp_context_scoped_to_step():
    """After building an sp fleet step, plain eval attention is unchanged."""
    from paddle_tpu.distributed.sp import sequence_parallel_state
    ids, lbl = _batch(b=8, s=32)
    s = _dp_strategy(dp_degree=2, sp_degree=4)
    s.sequence_parallel = True
    model = _model(seed=5)
    step = _fleet_step(model, s)
    step(ids, lbl)
    assert sequence_parallel_state() is None
    # eval with a seq length NOT divisible by sp=4 — would crash if the
    # sp context leaked out of the step
    model.eval()
    odd = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 128, (2, 7)).astype(np.int32))
    out = model(odd)
    assert out.shape == [2, 7, 128]


@pytest.mark.parametrize('mode', ['ring', 'ulysses'])
@pytest.mark.slow
def test_sequence_parallel_matches_dp(mode):
    """sp=4 GPT losses match the pure-dp run (VERDICT item 3 'done' bar)."""
    ids, lbl = _batch(b=8, s=32)

    m_ref = _model(seed=5)
    ref = _fleet_step(m_ref, _dp_strategy())
    ref_losses = [float(ref(ids, lbl).numpy()) for _ in range(3)]

    s = _dp_strategy(dp_degree=2, sp_degree=4)
    s.sequence_parallel = True
    s.sequence_parallel_configs['mode'] = mode
    m_sp = _model(seed=5)
    step = _fleet_step(m_sp, s)
    jaxpr = step.trace_jaxpr(ids, lbl)
    assert 'ppermute' in jaxpr or 'all_to_all' in jaxpr
    sp_losses = [float(step(ids, lbl).numpy()) for _ in range(3)]
    np.testing.assert_allclose(sp_losses, ref_losses, rtol=2e-4, atol=2e-5)
