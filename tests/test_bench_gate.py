"""Perf-regression gate tests (tools/check_bench_regression.py).

The gate's contract, proven with a deliberate-regression fixture: a new
capture of the SAME effective config that is >10% worse than the stored
best must fail the check, and a capture at (or near) the stored best
must pass. Also exercised against the repo's real in-window logs:
self-comparison is by construction regression-free.
"""
import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', 'tools'))

import bench
import check_bench_regression as gate

_REPO = os.path.join(os.path.dirname(__file__), '..')


def _row(value, metric='train_tokens_per_sec', **over):
    row = {'metric': metric, 'value': value, 'unit': 'tokens/sec',
           'platform': 'tpu', 'label': over.pop('label', 'fixture'),
           'batch': 8, 'seq': 512, 'scan_steps': 2, 'fused_ce': True,
           'attn_impl': 'flash', 'qkv_split': False}
    row.update(over)
    return row


def test_fails_on_deliberate_regression():
    best = [_row(1000.0, label='stored_best')]
    regressed = [_row(850.0, label='regressed')]       # -15% > 10% bar
    findings = gate.check(regressed, best)
    assert len(findings) == 1
    f = findings[0]
    assert f['direction'] == 'down'
    assert f['ratio'] == pytest.approx(0.85)
    assert f['stored_best'] == 1000.0 and f['new_best'] == 850.0


def test_passes_on_stored_best_and_within_threshold():
    best = [_row(1000.0)]
    assert gate.check(best, best) == []                # identical capture
    assert gate.check([_row(920.0)], best) == []       # -8% inside bar
    assert gate.check([_row(1100.0)], best) == []      # improvement


def test_effective_config_matching_not_literal():
    """A legacy row that omits knob fields and a new row spelling out the
    same defaults are ONE config: the key goes through bench's
    _capture_replay_env + _effective_env canonicalization."""
    legacy = {'metric': 'train_tokens_per_sec', 'value': 1000.0,
              'unit': 'tokens/sec', 'platform': 'tpu', 'batch': 8,
              'seq': 512}
    same = dict(legacy, value=800.0)
    assert gate.config_key(legacy) == gate.config_key(same)
    assert len(gate.check([same], [legacy])) == 1      # -20% caught
    # a DIFFERENT config (other seq) never compares against this best
    other = dict(legacy, value=100.0, seq=1024)
    assert gate.config_key(other) != gate.config_key(legacy)
    assert gate.check([other], [legacy]) == []


def test_untrusted_rows_are_ignored():
    best = [_row(1000.0)]
    for bad in (_row(10.0, degraded=True),
                _row(10.0, suspect=True),
                _row(10.0, platform='cpu'),
                _row(10.0, error='oom'),
                _row('nan')):
        assert not gate.eligible(bad)
        assert gate.check([bad], best) == []
    # and an untrusted stored row can't masquerade as the best
    assert gate.check([_row(500.0)],
                      [_row(10000.0, suspect=True), _row(520.0)]) == []


def test_latency_metrics_regress_upward():
    best = [_row(12.0, metric='decode_step_latency', unit='ms')]
    assert not gate.higher_is_better(best[0])
    assert gate.check([_row(14.0, metric='decode_step_latency',
                            unit='ms')], best)         # +17% slower
    assert gate.check([_row(11.0, metric='decode_step_latency',
                            unit='ms')], best) == []   # faster is fine


def test_compile_seconds_gate_as_derived_rows():
    """A row carrying compile_s_cold/compile_s_warm spawns pseudo-rows
    ('<metric>_compile_s_cold', unit 's') that regress UPWARD, without
    bucket-splitting the carrier row's own config."""
    best = [_row(1000.0, compile_s_cold=8.0, compile_s_warm=0.5)]
    derived = gate.expand_derived(best)
    metrics = sorted(r['metric'] for r in derived)
    assert 'train_tokens_per_sec_compile_s_cold' in metrics
    assert 'train_tokens_per_sec_compile_s_warm' in metrics
    cold = next(r for r in derived
                if r['metric'].endswith('_compile_s_cold'))
    assert cold['value'] == 8.0 and cold['unit'] == 's'
    assert not gate.higher_is_better(cold)             # time regresses UP
    # same throughput, 50% slower cold compile -> exactly one finding,
    # and it is the derived compile row, not the carrier
    slow = [_row(1000.0, compile_s_cold=12.0, compile_s_warm=0.5)]
    findings = gate.check(slow, best)
    assert len(findings) == 1
    assert findings[0]['metric'] == 'train_tokens_per_sec_compile_s_cold'
    assert findings[0]['direction'] == 'up'
    # faster compiles and mfu_est passengers never trip the gate
    fast = [_row(1000.0, compile_s_cold=4.0, compile_s_warm=0.4,
                 mfu_est=0.31, roofline_bound='compute')]
    assert gate.check(fast, best) == []


def test_cache_hit_rate_gates_as_higher_is_better():
    """compile_cache_hit_rate contains 'compile' but must NOT inherit
    the compile-time direction: a warmed persistent cache losing its
    hits is a downward regression, like throughput."""
    best = [_row(1000.0, compile_s_cold=8.0, compile_cache_hit_rate=0.9)]
    derived = gate.expand_derived(best)
    hr = next(r for r in derived if r['metric'].endswith('_hit_rate'))
    assert hr['unit'] == 'ratio' and hr['value'] == 0.9
    assert gate.higher_is_better(hr)
    dropped = [_row(1000.0, compile_s_cold=8.0,
                    compile_cache_hit_rate=0.5)]
    findings = gate.check(dropped, best)
    assert len(findings) == 1
    assert findings[0]['metric'] == \
        'train_tokens_per_sec_compile_cache_hit_rate'
    assert findings[0]['direction'] == 'down'
    improved = [_row(1000.0, compile_s_cold=8.0,
                     compile_cache_hit_rate=0.95)]
    assert gate.check(improved, best) == []


def test_supervisor_mttr_gates_lower_is_better():
    """supervisor_mttr_seconds (bench_extra's elastic-recovery rung)
    regresses UP: a supervisor that takes longer to bring a killed
    shard back is a worse supervisor, regardless of the generic
    throughput default."""
    row = {'metric': 'supervisor_mttr_seconds', 'unit': 's',
           'value': 0.08}
    assert not gate.higher_is_better(row)
    best = [dict(row, platform='tpu', degraded=False)]
    slower = [dict(row, value=0.5, platform='tpu', degraded=False)]
    findings = gate.check(slower, best)
    assert len(findings) == 1 and findings[0]['direction'] == 'up'
    faster = [dict(row, value=0.02, platform='tpu', degraded=False)]
    assert gate.check(faster, best) == []


def test_capacity_divergence_gates_lower_is_better():
    """bench_capacity_calibration's rows regress UP: a simulator whose
    TTFT distribution drifts further from the measured gateway
    (capacity_sim_ttft_divergence, rel_err) is a worse simulator, and a
    sweep that suddenly needs more replicas for the same pinned service
    model (capacity_sweep_min_replicas) is a capacity regression."""
    div = {'metric': 'capacity_sim_ttft_divergence', 'unit': 'rel_err',
           'value': 0.3}
    assert not gate.higher_is_better(div)
    best = [dict(div, platform='tpu', degraded=False)]
    worse = [dict(div, value=0.6, platform='tpu', degraded=False)]
    findings = gate.check(worse, best)
    assert len(findings) == 1 and findings[0]['direction'] == 'up'
    better = [dict(div, value=0.1, platform='tpu', degraded=False)]
    assert gate.check(better, best) == []

    rep = {'metric': 'capacity_sweep_min_replicas', 'unit': 'replicas',
           'value': 16}
    assert not gate.higher_is_better(rep)
    best = [dict(rep, platform='tpu', degraded=False)]
    more = [dict(rep, value=32, platform='tpu', degraded=False)]
    findings = gate.check(more, best)
    assert len(findings) == 1 and findings[0]['direction'] == 'up'
    fewer = [dict(rep, value=8, platform='tpu', degraded=False)]
    assert gate.check(fewer, best) == []


def test_trust_degraded_admits_cpu_rows():
    """The compile-cache rungs are measured on CPU: invisible to the
    default gate (they must never displace real-TPU bests), gated
    against their own baseline under --trust-degraded. Suspect and
    errored rows stay out even when trusted."""
    cpu_best = [_row(100.0, platform='cpu', degraded=True)]
    cpu_new = [_row(80.0, platform='cpu', degraded=True)]
    assert not gate.eligible(cpu_new[0])
    assert gate.check(cpu_new, cpu_best) == []
    findings = gate.check(cpu_new, cpu_best, trust_degraded=True)
    assert len(findings) == 1 and findings[0]['direction'] == 'down'
    assert not gate.eligible(_row(10.0, suspect=True), trust_degraded=True)
    assert not gate.eligible(_row(10.0, error='x'), trust_degraded=True)


def test_cli_trust_degraded_flag(tmp_path):
    best_p = tmp_path / 'best.jsonl'
    new_p = tmp_path / 'new.jsonl'
    best_p.write_text(json.dumps(_row(100.0, platform='cpu')) + '\n')
    new_p.write_text(json.dumps(_row(50.0, platform='cpu')) + '\n')
    script = os.path.join(_REPO, 'tools', 'check_bench_regression.py')
    base = [sys.executable, script, '--new', str(new_p),
            '--baseline', str(best_p)]
    # default: CPU rows are ineligible on both sides -> no findings
    assert subprocess.run(base, capture_output=True,
                          cwd=_REPO).returncode == 0
    # trusted: the -50% regression is caught
    r = subprocess.run(base + ['--trust-degraded'], capture_output=True,
                       text=True, cwd=_REPO)
    assert r.returncode == 1, r.stderr
    assert json.loads(r.stdout.strip().splitlines()[0])['regression']


def test_aux_workload_fields_split_configs():
    """Serving-rung rows at different slot counts are different configs
    even though their knob env is identical."""
    b8 = _row(300.0, metric='serving_tokens_per_sec', num_slots=8)
    b32 = _row(900.0, metric='serving_tokens_per_sec', num_slots=32)
    new8 = _row(280.0, metric='serving_tokens_per_sec', num_slots=8)
    assert gate.config_key(b8) != gate.config_key(b32)
    assert gate.check([new8], [b8, b32]) == []         # -7%: ok vs its own


def test_cli_exit_codes(tmp_path):
    best_p = tmp_path / 'best.jsonl'
    new_ok = tmp_path / 'ok.jsonl'
    new_bad = tmp_path / 'bad.jsonl'
    best_p.write_text(json.dumps(_row(1000.0)) + '\n')
    new_ok.write_text(json.dumps(_row(990.0)) + '\n')
    new_bad.write_text(json.dumps(_row(500.0)) + '\n')
    script = os.path.join(_REPO, 'tools', 'check_bench_regression.py')

    def run(new):
        return subprocess.run(
            [sys.executable, script, '--new', str(new),
             '--baseline', str(best_p)],
            capture_output=True, text=True, cwd=_REPO)

    ok = run(new_ok)
    assert ok.returncode == 0, ok.stderr
    assert json.loads(ok.stdout.strip().splitlines()[-1])['ok'] is True
    bad = run(new_bad)
    assert bad.returncode == 1, bad.stderr
    finding = json.loads(bad.stdout.strip().splitlines()[0])
    assert finding['regression'] and finding['ratio'] == pytest.approx(0.5)
    empty = tmp_path / 'empty.jsonl'
    empty.write_text('')
    assert run(empty).returncode == 2                  # nothing to check


def test_repo_cache_rows_pin_cold_start_win():
    """The committed CPU cache demonstration (docs/bench_cache_cpu.jsonl,
    measured cold-process via PADDLE_TPU_BENCH_CHILD=1 with
    PADDLE_TPU_CACHE_DIR at a fresh dir, then again at the warmed dir):
    the warm run compiles >=3x faster at full persistent-cache hit rate
    on both measured configs, the rows are invisible to the default
    (TPU-only) gate, and the file self-gates under --trust-degraded."""
    path = os.path.join(_REPO, 'docs', 'bench_cache_cpu.jsonl')
    rows = gate._load_jsonl(path)
    assert rows, 'missing committed cache bench rows'
    assert all(gate.eligible(r, trust_degraded=True) for r in rows)
    assert not any(gate.eligible(r) for r in rows)
    by_label = {r['label']: r for r in rows}
    for cfg in ('plain', 'scan2'):
        cold = by_label['cache_cold_%s' % cfg]
        warm = by_label['cache_warm_%s' % cfg]
        assert warm['compile_cache_hit_rate'] > 0
        assert warm['recompiles'] == 0
        assert cold['compile_s_cold'] >= 3 * warm['compile_s_cold']
    assert gate.check(rows, rows, trust_degraded=True) == []


def test_ingest_metric_directions():
    """The ingest rung's two gated metrics regress in opposite
    directions: examples/s down, data_wait_frac up."""
    eps = _row(50000.0, metric='ingest_examples_per_sec',
               unit='examples/sec')
    frac = _row(0.05, metric='ingest_data_wait_frac', unit='ratio')
    assert gate.higher_is_better(eps)
    assert not gate.higher_is_better(frac)
    slower = [_row(30000.0, metric='ingest_examples_per_sec',
                   unit='examples/sec')]
    assert gate.check(slower, [eps])           # -40% throughput fails
    starved = [_row(0.2, metric='ingest_data_wait_frac', unit='ratio')]
    assert gate.check(starved, [frac])         # 4x more waiting fails
    better = [_row(0.04, metric='ingest_data_wait_frac', unit='ratio')]
    assert gate.check(better, [frac]) == []    # less waiting passes


def test_repo_ingest_rows_pin_async_win():
    """The committed CPU ingest capture (docs/bench_ingest_cpu.jsonl,
    measured by bench_extra.bench_ingest against a synchronous
    random-access DataLoader over the same disk-resident shards): the
    async pipeline holds >=2x throughput with near-zero data_wait, the
    rows are invisible to the default (TPU-only) gate, and the file
    self-gates under --trust-degraded."""
    path = os.path.join(_REPO, 'docs', 'bench_ingest_cpu.jsonl')
    rows = gate._load_jsonl(path)
    assert rows, 'missing committed ingest bench rows'
    assert all(gate.eligible(r, trust_degraded=True) for r in rows)
    assert not any(gate.eligible(r) for r in rows)
    by_metric = {r['metric']: r for r in rows}
    eps = by_metric['ingest_examples_per_sec']
    frac = by_metric['ingest_data_wait_frac']
    assert eps['speedup_vs_dataloader'] >= 2.0
    assert eps['speedup_vs_pipeline_sync'] > 1.0
    assert frac['value'] <= 0.15               # near-zero async data_wait
    assert frac['value'] < frac['pipeline_sync_data_wait_frac']
    assert frac['value'] < frac['dataloader_data_wait_frac']
    # the frac also rides the throughput row for perf_report's table
    assert eps['data_wait_frac'] == frac['value']
    assert gate.check(rows, rows, trust_degraded=True) == []


def test_repo_stored_best_passes_gate():
    """In-suite rung: the stored in-window logs, replayed as a 'new'
    capture against themselves, must pass — if this fails the stored
    best itself is internally inconsistent."""
    paths = [p for p in bench._inwindow_log_paths() if os.path.exists(p)]
    if not paths:
        pytest.skip('no stored in-window capture logs in repo')
    rows = []
    for p in paths:
        rows.extend(gate._load_jsonl(p))
    assert any(gate.eligible(r) for r in rows)
    assert gate.check(rows, rows) == []
