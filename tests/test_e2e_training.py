"""End-to-end training tests (reference pattern: tests/book/
test_recognize_digits.py — small real models to a loss threshold +
save/load round trip)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.vision.models import LeNet
from paddle_tpu.vision.datasets import FakeData
from paddle_tpu.io import DataLoader
from paddle_tpu.framework.functional import TrainStep


@pytest.mark.slow
def test_lenet_eager_convergence():
    paddle.seed(42)
    model = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()
    ds = FakeData(num_samples=256, image_shape=(1, 28, 28), num_classes=10)
    loader = DataLoader(ds, batch_size=64, shuffle=True)
    losses = []
    for epoch in range(8):
        for img, label in loader:
            loss = loss_fn(model(img), label)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
    assert losses[-1] < 1.0, 'did not converge: %s' % losses[-5:]


def test_trainstep_matches_eager_exactly():
    def build():
        paddle.seed(7)
        m = LeNet()
        o = paddle.optimizer.Adam(learning_rate=1e-3,
                                  parameters=m.parameters())
        return m, o

    rng = np.random.RandomState(0)
    img = rng.standard_normal((8, 1, 28, 28)).astype(np.float32)
    lab = rng.randint(0, 10, 8)
    loss_fn = nn.CrossEntropyLoss()

    m1, o1 = build()
    for _ in range(3):
        l1 = loss_fn(m1(paddle.to_tensor(img)), paddle.to_tensor(lab))
        l1.backward()
        o1.step()
        o1.clear_grad()

    m2, o2 = build()
    step = TrainStep(m2, loss_fn, o2)
    for _ in range(3):
        l2 = step(paddle.to_tensor(img), paddle.to_tensor(lab))

    np.testing.assert_allclose(float(l1.numpy()), float(l2.numpy()),
                               rtol=1e-4)
    for (n1, p1), (n2, p2) in zip(m1.named_parameters(),
                                  m2.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), atol=1e-5,
                                   err_msg=n1)


def test_trainstep_overfits_fast():
    paddle.seed(0)
    model = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=3e-3,
                                parameters=model.parameters())
    step = TrainStep(model, nn.CrossEntropyLoss(), opt)
    rng = np.random.RandomState(0)
    img = paddle.to_tensor(rng.standard_normal((32, 1, 28, 28)).astype(np.float32))
    lab = paddle.to_tensor(rng.randint(0, 10, 32))
    for _ in range(80):
        loss = step(img, lab)
    assert float(loss.numpy()) < 0.05


def test_save_load_roundtrip(tmp_path):
    model = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    path = str(tmp_path / 'ckpt')
    paddle.save(model.state_dict(), path + '.pdparams')
    paddle.save(opt.state_dict(), path + '.pdopt')

    model2 = LeNet()
    model2.set_state_dict(paddle.load(path + '.pdparams'))
    x = paddle.randn([2, 1, 28, 28])
    model.eval()
    model2.eval()
    np.testing.assert_allclose(model(x).numpy(), model2(x).numpy(),
                               rtol=1e-6)


def test_hapi_model_fit():
    paddle.seed(1)
    net = LeNet()
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=2e-3,
                                        parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy())
    train_ds = FakeData(num_samples=128, image_shape=(1, 28, 28))
    val_ds = FakeData(num_samples=64, image_shape=(1, 28, 28), mode='test')
    model.fit(train_ds, val_ds, batch_size=32, epochs=2, verbose=0)
    res = model.evaluate(val_ds, batch_size=32, verbose=0)
    assert 'loss' in res
    preds = model.predict(val_ds, batch_size=32)
    assert len(preds) > 0


def test_jit_to_static_layer():
    paddle.seed(3)
    model = LeNet()
    model.eval()
    x = paddle.randn([2, 1, 28, 28])
    ref = model(x).numpy()
    static_model = paddle.jit.to_static(model)
    out = static_model(x)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_to_static_training_grad():
    paddle.seed(4)
    layer = nn.Linear(4, 2)

    @paddle.jit.to_static
    def fwd(x):
        return layer(x)

    x = paddle.randn([3, 4])
    out = fwd(x)
    loss = out.sum()
    loss.backward()
    assert layer.weight.grad is not None
    ref_grad = np.ones((3, 2), np.float32)
    np.testing.assert_allclose(layer.weight.grad.numpy(),
                               x.numpy().T @ ref_grad, rtol=1e-4)


def test_jit_save_load(tmp_path):
    model = LeNet()
    model.eval()
    path = str(tmp_path / 'lenet')
    from paddle_tpu.static import InputSpec
    paddle.jit.save(model, path, input_spec=[InputSpec([1, 1, 28, 28])])
    assert os.path.exists(path + '.pdiparams')
    loaded = paddle.jit.load(path)
    x = paddle.randn([2, 1, 28, 28])
    np.testing.assert_allclose(loaded(x).numpy(), model(x).numpy(),
                               rtol=1e-5)


def test_dataloader_multiworker():
    ds = FakeData(num_samples=64, image_shape=(1, 8, 8))
    loader = DataLoader(ds, batch_size=16, num_workers=2)
    batches = list(loader)
    assert len(batches) == 4
    img, lab = batches[0]
    assert img.shape == [16, 1, 8, 8]
    # deterministic order matches single-worker
    loader0 = DataLoader(ds, batch_size=16, num_workers=0)
    img0, lab0 = next(iter(loader0))
    np.testing.assert_allclose(img.numpy(), img0.numpy())


def test_amp_autocast_eager():
    with paddle.amp.auto_cast(enable=True, dtype='bfloat16'):
        x = paddle.randn([4, 4])
        y = paddle.randn([4, 4])
        z = paddle.matmul(x, y)
    assert z.dtype == 'bfloat16'
    w = paddle.matmul(x, y)
    assert w.dtype == 'float32'


def test_reduce_lr_on_plateau_callback():
    """paddle.callbacks.ReduceLROnPlateau halves the lr after `patience`
    stagnant evals (reference hapi/callbacks.py:956); also pins the
    paddle.callbacks / paddle.device namespaces."""
    import numpy as np
    import paddle_tpu as paddle

    assert callable(paddle.device.set_device)
    cb = paddle.callbacks.ReduceLROnPlateau(monitor='loss', factor=0.5,
                                            patience=2, verbose=0)

    class _FakeOpt:
        def __init__(self):
            self._lr = 1.0

        def get_lr(self):
            return self._lr

        def set_lr(self, v):
            self._lr = v

    class _FakeModel:
        pass

    m = _FakeModel()
    m._optimizer = _FakeOpt()
    cb.set_model(m)
    cb.on_eval_end({'loss': 1.0})   # best
    cb.on_eval_end({'loss': 1.0})   # wait 1
    assert m._optimizer.get_lr() == 1.0
    cb.on_eval_end({'loss': 1.0})   # wait 2 -> reduce
    assert np.isclose(m._optimizer.get_lr(), 0.5)
    cb.on_eval_end({'loss': 0.2})   # improvement resets
    cb.on_eval_end({'loss': 0.2})
    cb.on_eval_end({'loss': 0.2})
    assert np.isclose(m._optimizer.get_lr(), 0.25)


def test_load_reference_format_pdparams(tmp_path):
    """A checkpoint written the reference way — a plain pickled dict of
    numpy arrays (python/paddle/framework/io.py paddle.save) with
    paddle-structured key names — loads via paddle.load +
    set_state_dict, so users can migrate existing .pdparams files."""
    import pickle
    import numpy as np
    import paddle_tpu as paddle

    src = paddle.vision.models.LeNet()
    ref_ckpt = {k: np.asarray(v.numpy()) for k, v in
                src.state_dict().items()}
    path = str(tmp_path / 'model.pdparams')
    with open(path, 'wb') as f:
        pickle.dump(ref_ckpt, f, protocol=2)   # plain pickle, no wrapper

    dst = paddle.vision.models.LeNet()
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 1, 28, 28).astype(np.float32))
    before = dst(x).numpy()
    dst.set_state_dict(paddle.load(path))
    np.testing.assert_allclose(dst(x).numpy(), src(x).numpy(), rtol=1e-6)
    assert not np.allclose(before, src(x).numpy())


def test_summary_records_output_shapes(capsys):
    import paddle_tpu as paddle
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                               paddle.nn.Linear(16, 4))
    info = paddle.summary(net, (2, 8))
    out = capsys.readouterr().out
    assert '[2, 16]' in out and '[2, 4]' in out
    assert info['total_params'] == 8 * 16 + 16 + 16 * 4 + 4
    # no probe: still works, shapes dashed
    info2 = paddle.summary(net)
    assert info2 == info
    # dynamic batch dims map to 1 (reference _check_shape)
    paddle.summary(net, (None, 8))
    paddle.summary(net, (-1, 8))
    # per-layer eval state survives the probe
    net[1].eval()
    net.training = True
    paddle.summary(net, (2, 8))
    assert net[1].training is False and net.training is True
