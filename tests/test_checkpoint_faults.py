"""Checkpoint-writer fault coverage (ISSUE 14 satellites 1+2).

io_save's atomic writer exposes two named crash points — 'pre_rename'
(payload still in the temp file) and 'pre_manifest' (payload renamed,
manifest sidecar missing) — and CheckpointManager.restore_latest must
fall back to the previous intact snapshot for BOTH torn states. The
AsyncCheckpointer's non-orbax fallback must honor orbax's contract:
save() returns immediately, wait_until_finished() blocks and re-raises
a writer error.
"""
import os
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.checkpoint import (AsyncCheckpointer,
                                               CheckpointManager)
from paddle_tpu.framework import io_save
from paddle_tpu.testing import chaos


def _state(step):
    return {'step': step, 'w': np.full(8, step, np.float32)}


def _assert_restored(mgr, step):
    got_step, got = mgr.restore_latest()
    assert got_step == step
    np.testing.assert_array_equal(got['w'], np.full(8, step, np.float32))


@pytest.mark.parametrize('point,torn_file_present', [
    ('pre_rename', False),    # temp file only; target path untouched
    ('pre_manifest', True),   # data renamed in; manifest never written
])
def test_restore_falls_back_past_torn_save(tmp_path, point,
                                           torn_file_present):
    """A writer killed at either crash point must cost exactly one
    checkpoint interval: restore_latest lands on the previous snapshot,
    never on the torn one and never on (None, None)."""
    mgr = CheckpointManager(str(tmp_path), keep_last=3)
    mgr.save(1, _state(1))
    mgr.save(2, _state(2))
    with chaos.crash_io_save(point, path_substr='step_3') as fault:
        with pytest.raises(chaos.WriterKilled):
            mgr.save(3, _state(3))
    assert fault.fired == 1

    torn = os.path.join(str(tmp_path), 'step_3.ckpt')
    assert os.path.exists(torn) == torn_file_present
    assert not os.path.exists(io_save.manifest_path(torn))
    if torn_file_present:
        # manifest-less manager snapshot == writer died mid-commit: the
        # strict verify must refuse it even though the bytes are whole
        assert not io_save.verify_checkpoint(torn, require_manifest=True)
    _assert_restored(mgr, 2)

    # the torn state is not sticky: the next save commits normally and
    # becomes the restore target
    mgr.save(4, _state(4))
    _assert_restored(mgr, 4)


def test_keep_last_below_one_refused():
    """keep_last=0 used to prune NOTHING (steps()[:-0] == []); now it is
    a loud constructor error, as is any negative value."""
    for bad in (0, -1):
        with pytest.raises(ValueError, match='keep_last'):
            CheckpointManager('/tmp/never-created', keep_last=bad)


def test_keep_last_one_keeps_exactly_the_newest(tmp_path):
    """The smallest legal retention: after N saves only the newest
    snapshot (data + manifest sidecar, nothing else) remains."""
    mgr = CheckpointManager(str(tmp_path), keep_last=1)
    for step in range(1, 5):
        mgr.save(step, _state(step))
    assert sorted(os.listdir(str(tmp_path))) == \
        ['step_4.ckpt', 'step_4.ckpt.manifest']
    _assert_restored(mgr, 4)


def _fallback_checkpointer():
    ac = AsyncCheckpointer()
    # force the thread fallback even when orbax is importable — the
    # fallback path is what this file is proving
    ac._ocp = None
    ac._ckpt = None
    return ac


def test_async_fallback_save_returns_before_write_finishes(tmp_path,
                                                           monkeypatch):
    """Orbax contract: save() must NOT block on the write. Proven
    deterministically by gating the underlying io_save.save on an event
    the test holds closed until after save() has returned."""
    release = threading.Event()
    real_save = io_save.save

    def gated_save(obj, path, **kw):
        assert release.wait(10), 'writer never released'
        return real_save(obj, path, **kw)

    monkeypatch.setattr(io_save, 'save', gated_save)
    ac = _fallback_checkpointer()
    target = str(tmp_path / 'ckpt')
    ac.save(target, {'w': np.arange(4, dtype=np.float32)})
    # back in the caller while the writer is still parked on the event
    assert not os.path.exists(target + '.fallback.pdparams')
    release.set()
    ac.wait_until_finished()
    assert os.path.exists(target + '.fallback.pdparams')
    got = ac.restore(target)
    np.testing.assert_array_equal(got['w'],
                                  np.arange(4, dtype=np.float32))


def test_async_fallback_reraises_writer_error_on_wait(tmp_path):
    """A writer that dies in the background must surface at
    wait_until_finished(), exactly once — orbax raises there too, and a
    swallowed error would let the trainer believe the snapshot exists."""
    ac = _fallback_checkpointer()
    target = str(tmp_path / 'ckpt')
    ac.save(target, {'bad': lambda: None})      # unpicklable payload
    with pytest.raises(Exception) as exc_info:
        ac.wait_until_finished()
    assert 'pickle' in repr(exc_info.value).lower()
    # error is consumed: the checkpointer is reusable afterwards
    ac.wait_until_finished()
    ac.save(target, _state(7))
    ac.wait_until_finished()
    np.testing.assert_array_equal(ac.restore(target)['w'],
                                  np.full(8, 7, np.float32))


def test_async_fallback_restore_waits_for_inflight_save(tmp_path,
                                                        monkeypatch):
    """restore() right after save() must see the just-saved state, not
    ENOENT: it joins the in-flight writer first."""
    started = threading.Event()
    real_save = io_save.save

    def slow_save(obj, path, **kw):
        started.set()
        return real_save(obj, path, **kw)

    monkeypatch.setattr(io_save, 'save', slow_save)
    ac = _fallback_checkpointer()
    target = str(tmp_path / 'ckpt')
    ac.save(target, _state(5))
    assert started.wait(10)
    got = ac.restore(target)                    # no explicit wait
    np.testing.assert_array_equal(got['w'], np.full(8, 5, np.float32))


def test_no_leaked_io_save_faults():
    assert chaos.active_faults() == 0
