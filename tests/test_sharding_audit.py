"""auto_parallel subsystem: warning parser (fixture-driven, no
compilation), auditor end-to-end, planner specs, and the HLO pin for
the MULTICHIP r05 config-5 fix.

The parser fixtures are the REAL tail of MULTICHIP_r05.json — the
capture whose three spmd_partitioner.cc:652 warnings this subsystem
exists to eliminate — so the detector is regression-tested against the
exact text the regression gate must keep recognizing.
"""
import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed import auto_parallel as ap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_R05 = os.path.join(_REPO, 'MULTICHIP_r05.json')

# the r05 capture tail, embedded verbatim so the fixture test survives
# the stored file advancing to r06+ (which SHOULD go clean)
R05_TAIL = r'''devices=[1,2,2]<=[2,2]T(1,0) last_tile_dim_replicate} efficiently for HLO operation %squeeze.63 = f32[32,512]{1,0} copy(%squeeze.62), sharding={devices=[4,1]0,2,1,3}, metadata={op_name="while/body/closed_call/while/body/squeeze" stack_frame_id=99}. As the last resort, SPMD will replicate the tensor and then partition it to obtain the target sharding, which is inefficient.
W0802 18:00:41.692990    3516 spmd_partitioner.cc:652] [SPMD] Involuntary full rematerialization. The compiler cannot go from sharding {devices=[4,1]0,2,1,3} to {devices=[1,2,2]<=[2,2]T(1,0) last_tile_dim_replicate} efficiently for HLO operation %squeeze.67 = f32[128,128]{1,0} copy(%squeeze.66), sharding={devices=[4,1]0,2,1,3}, metadata={op_name="while/body/closed_call/while/body/squeeze" stack_frame_id=99}. As the last resort, SPMD will replicate the tensor and then partition it to obtain the target sharding, which is inefficient.
W0802 18:00:41.878208    3516 spmd_partitioner.cc:652] [SPMD] Involuntary full rematerialization. The compiler cannot go from sharding {devices=[1,2,4]<=[8] last_tile_dim_replicate} to {devices=[4,1,2]<=[2,4]T(1,0) last_tile_dim_replicate} efficiently for HLO operation %all-reduce = f32[512,64]{1,0} all-reduce(%dynamic-slice), channel_id=257, replica_groups=[2,4]<=[8], use_global_device_ids=true, to_apply=%region_121.125.clone.1.clone, sharding={devices=[1,2,4]<=[8] last_tile_dim_replicate}. As the last resort, SPMD will replicate the tensor and then partition it to obtain the target sharding, which is inefficient.
dryrun_multichip(8)[pp/sharding3 cfg5]: pp=2 sharding=4 loss=6.4444'''

# the OTHER warning dialect (older XLA, spmd_partitioner.cc:613,
# E-level) — what the locally-installed jaxlib emits
OLD_DIALECT_LINE = (
    'E0805 04:10:00.000000   999 spmd_partitioner.cc:613] [spmd] '
    'Involuntary full rematerialization. The compiler was not able to go '
    'from sharding {devices=[4,1,2]<=[2,4]T(1,0) last_tile_dim_replicate} '
    'to {devices=[1,2,4]<=[8] last_tile_dim_replicate} without doing a '
    'full rematerialization of the tensor for HLO operation: %copy.1 = '
    'f32[32,512]{1,0} copy(f32[32,512]{1,0} %reshape.0), '
    'sharding={devices=[1,2,4]<=[8] last_tile_dim_replicate}, '
    'metadata={op_name="jit(f)/jit(main)/while/body/sharding_constraint" '
    'source_file="/tmp/repro.py" source_line=18}. You probably want to '
    'enrich the sharding annotations to prevent this from happening.')

CLEAN_TAIL = ('dryrun_multichip(8)[dp/mp/sharding fused-ce]: loss=6.45\n'
              'dryrun_multichip(8)[pp/sharding3 cfg5]: pp=2 sharding=4 '
              'loss=6.4444\n')


# ---------------- parser fixtures (no compilation) ----------------

def test_parser_r05_tail_finds_all_three_events():
    evs = ap.parse_spmd_warnings(R05_TAIL)
    assert len(evs) == 3
    # the tail-truncated first line still yields an event (dst + op)
    assert evs[0].src_sharding is None
    assert evs[0].shape == [32, 512]
    assert evs[0].dst_sharding == \
        'devices=[1,2,2]<=[2,2]T(1,0) last_tile_dim_replicate'
    assert evs[0].op_name == 'while/body/closed_call/while/body/squeeze'
    # full squeeze line: both shardings, opcode, stack frame
    assert evs[1].op == 'squeeze.67'
    assert evs[1].opcode == 'copy'
    assert evs[1].shape == [128, 128]
    assert evs[1].src_sharding == 'devices=[4,1]0,2,1,3'
    assert evs[1].stack_frame_id == 99
    assert evs[1].bytes == 128 * 128 * 4
    # the all-reduce line has no metadata= section at all
    assert evs[2].op == 'all-reduce'
    assert evs[2].op_name is None
    assert evs[2].shape == [512, 64]
    assert evs[2].bytes == 512 * 64 * 4


def test_parser_r05_stored_file_still_matches_embedded_fixture():
    """Guard: if the stored capture is still r05-era (3 warnings), the
    parser must see exactly them; once the capture goes clean this test
    asserts the parser agrees it is clean."""
    with open(_R05) as f:
        tail = json.load(f)['tail']
    evs = ap.parse_spmd_warnings(tail)
    assert len(evs) in (0, 3)
    if evs:
        assert {tuple(e.shape) for e in evs} == \
            {(32, 512), (128, 128), (512, 64)}


def test_parser_old_dialect_line():
    evs = ap.parse_spmd_warnings(OLD_DIALECT_LINE)
    assert len(evs) == 1
    e = evs[0]
    assert e.opcode == 'copy'
    assert e.shape == [32, 512]
    assert e.src_sharding == \
        'devices=[4,1,2]<=[2,4]T(1,0) last_tile_dim_replicate'
    assert e.source_file == '/tmp/repro.py'
    assert e.source_line == 18
    assert 'sharding_constraint' in e.op_name


def test_parser_clean_tail_is_clean():
    assert ap.parse_spmd_warnings(CLEAN_TAIL) == []
    rep = ap.audit_from_text(CLEAN_TAIL, label='clean')
    assert rep.passed and rep.involuntary_bytes == 0


def test_event_key_ignores_hlo_value_numbering():
    evs = ap.parse_spmd_warnings(R05_TAIL)
    renum = R05_TAIL.replace('squeeze.67', 'squeeze.123')
    evs2 = ap.parse_spmd_warnings(renum)
    assert [e.key() for e in evs] == [e.key() for e in evs2]


def test_report_roundtrips_through_dict():
    rep = ap.audit_from_text(R05_TAIL, label='r05')
    rep2 = ap.ShardingAuditReport.from_dict(rep.to_dict())
    assert [e.key() for e in rep2.events] == [e.key() for e in rep.events]
    assert rep2.involuntary_bytes == rep.involuntary_bytes


def test_hlo_collective_stats():
    hlo = '\n'.join([
        '%all-reduce.1 = f32[512,64]{1,0} all-reduce(f32[512,64]{1,0} %x)',
        '%ag = f32[128,128]{1,0} all-gather(f32[32,128]{1,0} %y)',
        '%cp = f32[4,64]{1,0} collective-permute(f32[4,64]{1,0} %z)',
        '%add = f32[4,64]{1,0} add(%cp, %cp)',
    ])
    stats = ap.parse_hlo_collectives(hlo)
    assert stats['all-reduce'] == {'count': 1, 'bytes': 512 * 64 * 4}
    assert stats['all-gather']['count'] == 1
    assert stats['collective-permute']['count'] == 1
    assert 'add' not in stats


# ---------------- auditor end-to-end (compiles) ----------------

def _mesh_ab():
    dev = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(dev, ('a', 'b'))


def test_auditor_detects_involuntary_reshard():
    """A program whose while-body demands a transposed retiling of the
    same tensor MUST trip the partitioner's last-resort path — and the
    auditor must see it through the fd-level capture."""
    mesh = _mesh_ab()
    w = jax.device_put(jnp.ones((16, 128, 512), jnp.float32),
                       NamedSharding(mesh, P(None, 'b', None)))

    def bad(w):
        def body(c, i):
            s = lax.dynamic_index_in_dim(w, i, 0, keepdims=False)
            s = lax.with_sharding_constraint(
                s, NamedSharding(mesh, P('b', None)))
            s = jnp.tanh(s)
            s = lax.with_sharding_constraint(
                s, NamedSharding(mesh, P(None, 'a')))
            return c + s.sum(), None
        out, _ = lax.scan(body, 0.0, jnp.arange(16))
        return out

    rep = ap.audit_callable(bad, args=(w,), label='bad')
    assert not rep.passed
    assert any(e.shape == [32, 512] for e in rep.events)
    assert rep.involuntary_bytes >= 32 * 512 * 4
    with pytest.raises(AssertionError):
        ap.assert_no_involuntary_resharding(bad, args=(w,))


def test_auditor_clean_program_passes():
    mesh = _mesh_ab()
    w = jax.device_put(jnp.ones((16, 128, 512), jnp.float32),
                       NamedSharding(mesh, P(None, 'b', None)))

    def good(w):
        def body(c, i):
            s = lax.dynamic_index_in_dim(w, i, 0, keepdims=False)
            s = lax.with_sharding_constraint(
                s, NamedSharding(mesh, P('b', None)))
            return c + jnp.tanh(s).sum(), None
        out, _ = lax.scan(body, 0.0, jnp.arange(16))
        return out

    rep = ap.assert_no_involuntary_resharding(good, args=(w,))
    assert rep.passed
    # a real compile happened: the optimized HLO was parsed
    assert isinstance(rep.collectives, dict)


# ---------------- planner ----------------

def _mesh_pp_sharding():
    dev = np.array(jax.devices()[:8]).reshape(1, 2, 4)
    return Mesh(dev, ('dp', 'pp', 'sharding'))


def test_planner_specs_and_trivial_meshes():
    mesh = _mesh_pp_sharding()
    plan = ap.plan_pipeline(mesh, 'pp')
    assert plan is not None
    assert plan.batch_axes == ('sharding',)
    assert plan.batch_div == 4
    micro = plan.micro_spec((2, 4, 64, 128))
    assert micro is not None and micro[0] is None
    assert micro[1] == ('sharding',)
    # indivisible microbatch rows -> no constraint rather than a bad one
    assert plan.micro_spec((2, 3, 64)) is None
    st = plan.stacked_spec((2, 2, 128, 128))
    assert st is not None and st[0] == 'pp'
    # wrong leading dim (not the pp extent) -> refuse
    assert plan.stacked_spec((3, 2, 128)) is None
    # pure-pp mesh: nothing to plan
    dev = np.array(jax.devices()[:2])
    assert ap.plan_pipeline(Mesh(dev, ('pp',)), 'pp') is None
    # no pp axis at all
    dev = np.array(jax.devices()[:4])
    assert ap.plan_pipeline(Mesh(dev, ('dp',)), 'pp') is None


def test_planner_state_helper():
    from paddle_tpu.distributed.pipeline import make_pp_state
    mesh = _mesh_pp_sharding()
    st = make_pp_state(mesh, n_stages=2)
    assert ap.plan_for_state(st) is not None
    assert ap.plan_for_state(None) is None


# -------- the cfg5 HLO pin: planner boundaries stay warning-free ------

def test_cfg5_analog_boundaries_compile_clean():
    """Pure-auto analog of the config-5 (pp2 x ZeRO-sharding4) region:
    batch sharded over ('dp','sharding') reshaped to microbatches, a
    while loop dynamic-slicing stacked ZeRO-tiled stage weights — the
    exact producer/consumer structure whose unpinned version produced
    the three r05 involuntary-reshard warnings. With the planner's
    boundary constraints the compile must be CLEAN, and the loop body
    must keep collective-permute-free access to the microbatch stream
    (regression pin for the fixed transitions)."""
    mesh = _mesh_pp_sharding()
    plan = ap.plan_pipeline(mesh, 'pp')
    x = jax.device_put(jnp.ones((8, 64, 128), jnp.float32),
                       NamedSharding(mesh, P(('dp', 'sharding'))))
    w = jax.device_put(
        jnp.ones((2, 2, 128, 128), jnp.float32),
        NamedSharding(mesh, P(None, None, 'sharding', None)))

    def f(x, w):
        micro = plan.constrain_micro(x.reshape((2, 4) + x.shape[1:]))
        wts = plan.constrain_stacked({'w': w})['w']

        def tick(carry, t):
            def layer(c, j):
                lw = lax.dynamic_index_in_dim(
                    lax.dynamic_index_in_dim(wts, t % 2, 0,
                                             keepdims=False),
                    j, 0, keepdims=False)
                return jnp.tanh(c @ lw), None
            y, _ = lax.scan(layer, micro[t % 2], jnp.arange(2))
            return carry + y.sum(), None
        out, _ = lax.scan(tick, 0.0, jnp.arange(3))
        return out

    rep = ap.assert_no_involuntary_resharding(f, args=(x, w),
                                              label='cfg5-analog')
    # pinned transitions: stage weights stay tiled (the all-gather that
    # feeds the matmul is voluntary and appears as a real collective),
    # and nothing in the body needed replicate-then-repartition
    assert rep.passed


# ---------------- regression gate (tools/) ----------------

def _gate():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        'check_sharding_regression',
        os.path.join(_REPO, 'tools', 'check_sharding_regression.py'))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


AUDIT_CLEAN = ('sharding_audit(8)[pp/sharding3 cfg5]: {"ok":true,'
               '"n_events":0,"involuntary_bytes":0,"events":[],'
               '"collectives":{}}\n')
AUDIT_BAD = ('sharding_audit(8)[pp/sharding3 cfg5]: {"ok":false,'
             '"n_events":1,"involuntary_bytes":4096,"events":['
             '{"kind":"involuntary-full-rematerialization","opcode":"copy",'
             '"dtype":"f32","shape":[32,32],"bytes":4096,'
             '"src_sharding":"devices=[4,1]","dst_sharding":"devices=[1,4]",'
             '"op_name":"while/body/new_thing"}],"collectives":{}}\n')


def test_gate_clean_vs_r05_passes():
    gate = _gate()
    assert gate.check(AUDIT_CLEAN, R05_TAIL) == []


def test_gate_new_event_fails_with_diff():
    gate = _gate()
    findings = gate.check(AUDIT_BAD, R05_TAIL)
    assert len(findings) == 1
    assert findings[0]['config'] == 'pp/sharding3 cfg5'
    assert findings[0]['event']['op_name'] == 'while/body/new_thing'


def test_gate_raw_baseline_covers_same_raw_events():
    gate = _gate()
    # a new capture still in the raw-warning format, identical events:
    # not a regression (value numbering differences must not matter)
    renum = R05_TAIL.replace('squeeze.67', 'squeeze.91')
    assert gate.check(renum, R05_TAIL) == []


def test_gate_extract_reads_both_encodings():
    gate = _gate()
    by_label = gate.extract_events(AUDIT_BAD + R05_TAIL)
    assert len(by_label['pp/sharding3 cfg5']) == 1
    assert len(by_label['_raw']) == 3


@pytest.mark.skipif(not hasattr(jax, 'shard_map'),
                    reason='partial-auto shard_map needs the modern '
                           'jax.shard_map API (the installed 0.4.x line '
                           'lowers axis_index under partial-auto to an '
                           'unpartitionable PartitionId)')
def test_cfg5_full_train_step_audits_clean():
    """The REAL config-5 step (pp2 x sharding3, fused loss) compiles
    with zero involuntary-reshard warnings — the acceptance criterion,
    runnable wherever the modern shard_map API exists (the MULTICHIP
    driver environment)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        'dp_degree': 1, 'mp_degree': 1, 'pp_degree': 2,
        'sharding_degree': 4, 'sp_degree': 1, 'ep_degree': 1}
    strategy.sharding = True
    strategy.sharding_configs.update({'stage': 3})
    fleet.init(is_collective=True, strategy=strategy)

    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=512, hidden_size=128, num_layers=4, num_heads=4,
        max_position_embeddings=64, fused_loss=True))
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = fleet.fleet_train_step(model, lambda lg, lb: model.loss(lg, lb),
                                  opt, strategy=strategy)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 512, (8, 64)).astype(np.int32))
    lbl = paddle.to_tensor(rng.randint(0, 512, (8, 64)).astype(np.int32))
    rep = ap.audit_train_step(step, ids, lbl, label='cfg5')
    assert rep.passed, rep.summary()
