"""tools/profile_analysis.py parses a real captured TPU trace.

The committed round-4 profile (docs/tpu_profile_r4) is the fixture: the
tool must load it, attribute device time to XLA ops, infer the step
count, and produce the roofline totals the perf notes cite.
"""
import os

import pytest

import tools.profile_analysis as pa

_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'docs', 'tpu_profile_r4')


@pytest.mark.skipif(not os.path.isdir(_DIR), reason='no committed profile')
def test_parses_committed_profile():
    trace, path = pa.load_trace(_DIR)
    ops, n_modules = pa.device_ops(trace)
    assert ops, 'no device ops found'
    rows = pa.aggregate(ops)
    # the bench profiled 8 steps; the modal op count must agree
    import collections
    steps = collections.Counter(r['n'] for r in rows.values()).most_common(
        1)[0][0]
    assert steps == 8
    tot_ms = sum(r['dur_us'] for r in rows.values()) / 1e3 / steps
    # the captured flash_disabled_plain rung ran ~129 ms/step on-chip
    assert 100 < tot_ms < 160, tot_ms
    tot_bytes = sum(r['bytes'] * r['n'] for r in rows.values()) / steps
    assert tot_bytes > 5e10  # the step moves tens of GB — sanity
