"""tools/profile_analysis.py contract tests.

Two tiers:
- a synthetic trace fixture (always runs, hardware-free): exercises
  load_trace / device_ops / aggregate end-to-end on the exact
  trace-viewer JSON shape jax.profiler writes;
- a captured on-TPU profile, when one exists locally (docs/tpu_profile_r5
  is written by the warmer's auto-profile pass; the raw blobs are
  gitignored per the r4 advisor, so CI machines skip this tier).
"""
import glob
import gzip
import json
import os

import pytest

import tools.profile_analysis as pa

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# first profile dir (newest round first) that holds a trace, else None —
# single source of truth for both the skip condition and the test body
_CAPTURED_DIR = next(
    (os.path.join(_ROOT, 'docs', d)
     for d in ('tpu_profile_r5', 'tpu_profile_r4')
     if glob.glob(os.path.join(_ROOT, 'docs', d, '**', '*.trace.json.gz'),
                  recursive=True)),
    None)


def _synthetic_trace(tmp_path, steps=8, step_us=1000.0):
    """A minimal trace-viewer JSON mirroring jax.profiler's layout: a
    device pid with 'XLA Ops' / 'XLA Modules' lanes plus a host pid that
    must be ignored."""
    dev, host = 7, 3
    events = [
        {'ph': 'M', 'pid': dev, 'name': 'process_name',
         'args': {'name': '/device:TPU:0'}},
        {'ph': 'M', 'pid': dev, 'tid': 1, 'name': 'thread_name',
         'args': {'name': 'XLA Ops'}},
        {'ph': 'M', 'pid': dev, 'tid': 2, 'name': 'thread_name',
         'args': {'name': 'XLA Modules'}},
        {'ph': 'M', 'pid': host, 'name': 'process_name',
         'args': {'name': 'host worker'}},
        {'ph': 'M', 'pid': host, 'tid': 1, 'name': 'thread_name',
         'args': {'name': 'XLA Ops'}},  # host lane: must not be counted
    ]
    for s in range(steps):
        t0 = s * step_us
        events.append({'ph': 'X', 'pid': dev, 'tid': 2, 'ts': t0,
                       'dur': step_us, 'name': 'jit_train_step'})
        # one matmul-ish op (flops-heavy) + one copy (bytes-heavy)
        events.append({'ph': 'X', 'pid': dev, 'tid': 1, 'ts': t0,
                       'dur': 600.0, 'name': 'fusion.1',
                       'args': {'model_flops': 2.4e11,
                                'bytes_accessed': 1e7,
                                'hlo_category': 'convolution fusion',
                                'long_name': '%fusion.1 = bf16[...]'}})
        events.append({'ph': 'X', 'pid': dev, 'tid': 1, 'ts': t0 + 600,
                       'dur': 400.0, 'name': 'copy.2',
                       'args': {'model_flops': 0,
                                'bytes_accessed': 3.2e8,
                                'hlo_category': 'copy',
                                'long_name': '%copy.2 = f32[...]'}})
        # host-lane noise with the same name: ignored by device_ops
        events.append({'ph': 'X', 'pid': host, 'tid': 1, 'ts': t0,
                       'dur': 5000.0, 'name': 'fusion.1', 'args': {}})
    pdir = tmp_path / 'prof' / 'plugins' / 'profile' / 'run1'
    pdir.mkdir(parents=True)
    with gzip.open(str(pdir / 'vm.trace.json.gz'), 'wt') as f:
        json.dump({'traceEvents': events}, f)
    return str(tmp_path / 'prof')


def test_busy_time_interval_union(tmp_path):
    # a while/scan parent op's slice covers its body ops; the busy-time
    # union must count that wall span once, not parent + children
    dev = 7
    events = [
        {'ph': 'M', 'pid': dev, 'name': 'process_name',
         'args': {'name': '/device:TPU:0'}},
        {'ph': 'M', 'pid': dev, 'tid': 1, 'name': 'thread_name',
         'args': {'name': 'XLA Ops'}},
        # parent covering [0, 1000)
        {'ph': 'X', 'pid': dev, 'tid': 1, 'ts': 0.0, 'dur': 1000.0,
         'name': 'while.1', 'args': {}},
        # children nested inside the parent's span
        {'ph': 'X', 'pid': dev, 'tid': 1, 'ts': 0.0, 'dur': 600.0,
         'name': 'fusion.a', 'args': {}},
        {'ph': 'X', 'pid': dev, 'tid': 1, 'ts': 600.0, 'dur': 300.0,
         'name': 'fusion.b', 'args': {}},
        # a disjoint op after an idle gap: [1500, 1700)
        {'ph': 'X', 'pid': dev, 'tid': 1, 'ts': 1500.0, 'dur': 200.0,
         'name': 'copy.z', 'args': {}},
    ]
    ops, _ = pa.device_ops({'traceEvents': events})
    assert sum(e['dur'] for e in ops) == pytest.approx(2100.0)  # naive
    assert pa.busy_us(ops) == pytest.approx(1200.0)             # union


def test_synthetic_trace_roundtrip(tmp_path):
    pdir = _synthetic_trace(tmp_path)
    trace, path = pa.load_trace(pdir)
    assert path.endswith('.trace.json.gz')
    ops, n_modules = pa.device_ops(trace)
    # 8 steps x 2 device ops; the 8 host events must be excluded
    assert len(ops) == 16
    assert n_modules == 8
    rows = pa.aggregate(ops)
    assert set(rows) == {'fusion.1', 'copy.2'}
    f = rows['fusion.1']
    assert f['n'] == 8 and f['dur_us'] == pytest.approx(4800.0)
    assert f['flops'] == pytest.approx(2.4e11)
    assert f['cat'] == 'convolution fusion'
    c = rows['copy.2']
    assert c['bytes'] == pytest.approx(3.2e8)
    # per-step totals: (600+400) us
    steps = 8
    tot_ms = sum(r['dur_us'] for r in rows.values()) / 1e3 / steps
    assert tot_ms == pytest.approx(1.0)


@pytest.mark.skipif(_CAPTURED_DIR is None,
                    reason='no locally captured profile (raw blobs are '
                           'gitignored; the warmer writes them in-window)')
def test_parses_captured_profile():
    trace, _ = pa.load_trace(_CAPTURED_DIR)
    ops, _ = pa.device_ops(trace)
    assert ops, 'no device ops found'
    rows = pa.aggregate(ops)
    import collections
    steps = collections.Counter(r['n'] for r in rows.values()).most_common(
        1)[0][0]
    # the warmer profiles multiple steps: step inference must detect the
    # repetition, not collapse to 1 (which would inflate every per-step
    # total this tool reports)
    assert steps >= 2
    tot_ms = sum(r['dur_us'] for r in rows.values()) / 1e3 / steps
    assert tot_ms > 10, tot_ms
    tot_bytes = sum(r['bytes'] * r['n'] for r in rows.values()) / steps
    # a real BERT-base training step moves tens of GB
    assert tot_bytes > 1e10
