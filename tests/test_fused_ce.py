"""Fused linear+CE (ops/fused_ce.py, F.linear_cross_entropy).

Parity bar: the chunked custom_vjp must match the straight path
(head matmul -> F.cross_entropy) in value AND in every gradient
(dx, dw, db) — f32 tight, bf16 loose — including ignored labels,
non-divisible row counts, and the tied-embedding transposed-weight
layout. Then end-to-end: a GPTForCausalLM(fused_loss=True) TrainStep
must track the non-fused model parameter-for-parameter.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.fused_ce import linear_cross_entropy_arrays


def _naive(x, w, labels, bias, ignore_index):
    logits = (x @ w).astype(jnp.float32)
    if bias is not None:
        logits = logits + bias
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    safe = jnp.clip(labels, 0, w.shape[1] - 1)
    picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
    valid = labels != ignore_index
    per = jnp.where(valid, lse - picked, 0.0)
    denom = jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
    return (per.sum() / denom).astype(x.dtype)


@pytest.mark.parametrize('rows,chunk', [(64, 16), (60, 16), (64, 64),
                                        (7, 100)])
@pytest.mark.parametrize('with_bias', [False, True])
def test_matches_naive_f32(rows, chunk, with_bias):
    rng = np.random.RandomState(0)
    d, v = 24, 97
    x = jnp.asarray(rng.randn(rows, d), jnp.float32)
    w = jnp.asarray(rng.randn(d, v) * 0.1, jnp.float32)
    b = jnp.asarray(rng.randn(v) * 0.1, jnp.float32) if with_bias else None
    labels = jnp.asarray(rng.randint(0, v, rows), jnp.int32)

    args = (x, w, labels, b)
    loss = linear_cross_entropy_arrays(*args, -100, chunk)
    ref = _naive(*args, -100)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-6)

    diff = (0, 1) if b is None else (0, 1, 3)
    gf = jax.grad(lambda *a: linear_cross_entropy_arrays(*a, -100, chunk),
                  argnums=diff)(*args)
    gr = jax.grad(lambda *a: _naive(*a, -100), argnums=diff)(*args)
    for gi, ri in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(gi), np.asarray(ri),
                                   rtol=2e-5, atol=2e-6)


def test_ignore_index_rows_contribute_nothing():
    rng = np.random.RandomState(1)
    rows, d, v = 32, 16, 50
    x = jnp.asarray(rng.randn(rows, d), jnp.float32)
    w = jnp.asarray(rng.randn(d, v) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.randint(0, v, rows), jnp.int32)
    labels = labels.at[::3].set(-100)

    loss = linear_cross_entropy_arrays(x, w, labels, None, -100, 8)
    ref = _naive(x, w, labels, None, -100)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-6)

    # ignored rows must get exactly zero dx
    dx = jax.grad(lambda a: linear_cross_entropy_arrays(
        a, w, labels, None, -100, 8))(x)
    assert float(jnp.abs(dx[::3]).max()) == 0.0
    assert float(jnp.abs(dx[1::3]).max()) > 0.0


def test_all_rows_ignored_is_finite():
    x = jnp.ones((8, 4), jnp.float32)
    w = jnp.ones((4, 9), jnp.float32)
    labels = jnp.full((8,), -100, jnp.int32)
    loss = linear_cross_entropy_arrays(x, w, labels, None, -100, 4)
    assert float(loss) == 0.0
    dx = jax.grad(lambda a: linear_cross_entropy_arrays(
        a, w, labels, None, -100, 4))(x)
    assert float(jnp.abs(dx).max()) == 0.0


def test_bf16_matches_naive_bf16():
    rng = np.random.RandomState(2)
    rows, d, v = 128, 32, 211
    x = jnp.asarray(rng.randn(rows, d), jnp.bfloat16)
    w = jnp.asarray(rng.randn(d, v) * 0.05, jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, v, rows), jnp.int32)

    loss = linear_cross_entropy_arrays(x, w, labels, None, -100, 32)
    ref = _naive(x, w, labels, None, -100)
    np.testing.assert_allclose(float(loss), float(ref), rtol=2e-2)
    gf = jax.grad(lambda a, b: linear_cross_entropy_arrays(
        a, b, labels, None, -100, 32), argnums=(0, 1))(x, w)
    gr = jax.grad(lambda a, b: _naive(a, b, labels, None, -100),
                  argnums=(0, 1))(x, w)
    for gi, ri in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(gi, np.float32),
                                   np.asarray(ri, np.float32),
                                   rtol=0.1, atol=5e-4)


def test_functional_transpose_weight_eager_backward():
    """Tensor-level API with the tied-embedding [vocab, d] layout."""
    import paddle_tpu as paddle
    from paddle_tpu.nn import functional as F

    rng = np.random.RandomState(3)
    b, n, d, v = 2, 6, 8, 31
    x = paddle.to_tensor(rng.randn(b, n, d).astype(np.float32),
                         stop_gradient=False)
    wt = paddle.to_tensor(rng.randn(v, d).astype(np.float32) * 0.1,
                          stop_gradient=False)
    labels = paddle.to_tensor(rng.randint(0, v, (b, n)).astype(np.int64))

    loss = F.linear_cross_entropy(x, wt, labels, transpose_weight=True,
                                  chunk_rows=5)
    loss.backward()

    xa, wa = jnp.asarray(x.numpy()), jnp.asarray(wt.numpy())
    la = jnp.asarray(labels.numpy().reshape(-1), jnp.int32)
    ref_fn = lambda a, ww: _naive(a.reshape(-1, d), ww.T, la, None, -100)
    ref = ref_fn(xa, wa)
    np.testing.assert_allclose(float(loss.numpy()), float(ref), rtol=1e-6)
    gx, gw = jax.grad(ref_fn, argnums=(0, 1))(xa, wa)
    np.testing.assert_allclose(x.grad.numpy(), np.asarray(gx),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(wt.grad.numpy(), np.asarray(gw),
                               rtol=2e-5, atol=2e-6)


def _train_steps(fused, steps=3, optimizer='momentum'):
    import paddle_tpu as paddle
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.framework import functional as func_mod

    paddle.seed(0)
    cfg = dict(vocab_size=211, hidden_size=32, num_layers=2, num_heads=4,
               max_position_embeddings=16, dropout=0.0)
    model = GPTForCausalLM(GPTConfig(fused_loss=fused, **cfg))
    if optimizer == 'momentum':
        # linear in the grads: parity stays tight. Adam's m/sqrt(v)
        # amplifies f32 reassociation noise on near-zero grads into
        # sign-flipped whole-lr updates, so it cannot hold a tight
        # param-parity bar even between two bit-different-but-correct
        # implementations.
        opt = paddle.optimizer.Momentum(learning_rate=1e-2, momentum=0.9,
                                        parameters=model.parameters())
    else:
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
    step = func_mod.TrainStep(model, model.loss, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 211, (2, 16)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, 211, (2, 16)).astype(np.int32))
    losses = [float(step(ids, labels).numpy()) for _ in range(steps)]
    params = {k: np.asarray(v) for k, v in
              func_mod.extract_params(model).items()}
    return losses, params


def test_gpt_fused_loss_trains_identically():
    """fused_loss=True must track the straight model step-for-step —
    including the tied wte.weight, whose head-side grad only flows if the
    loss runs inside the TrainStep parameter binding."""
    l0, p0 = _train_steps(fused=False)
    l1, p1 = _train_steps(fused=True)
    np.testing.assert_allclose(l0, l1, rtol=1e-5)
    assert p0.keys() == p1.keys()
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], rtol=1e-4, atol=1e-6,
                                    err_msg=k)


@pytest.mark.slow
def test_gpt_fused_loss_adamw_loss_trajectory():
    l0, _ = _train_steps(fused=False, steps=3, optimizer='adamw')
    l1, _ = _train_steps(fused=True, steps=3, optimizer='adamw')
    np.testing.assert_allclose(l0, l1, rtol=1e-4)


def test_fused_loss_under_shardmap_dp():
    """ShardMapDPStep must run the loss inside the parameter binding too:
    with fused_loss the tied wte head-grad otherwise silently vanishes
    (same hazard TrainStep's post_fn closes)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.meta_optimizers import ShardMapDPStep
    from paddle_tpu.framework.functional import extract_params
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    cfg = dict(vocab_size=97, hidden_size=16, num_layers=1, num_heads=2,
               max_position_embeddings=8, dropout=0.0)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 97, (8, 8)).astype(np.int32)
    lab = rng.randint(0, 97, (8, 8)).astype(np.int32)

    results = {}
    for fused in (False, True):
        paddle.seed(0)
        m = GPTForCausalLM(GPTConfig(fused_loss=fused, **cfg))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        step = ShardMapDPStep(m, lambda o, l: m.loss(o, l), opt,
                              mode='dense')
        loss = float(step(paddle.to_tensor(ids),
                          paddle.to_tensor(lab)).numpy())
        results[fused] = (loss, {k: np.asarray(v) for k, v in
                                 extract_params(m).items()})
    l0, p0 = results[False]
    l1, p1 = results[True]
    np.testing.assert_allclose(l0, l1, rtol=1e-5)
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], rtol=1e-4, atol=1e-6,
                                    err_msg=k)
    # and the tied weight actually moved (grads were not dropped)
    paddle.seed(0)
    init = np.asarray(GPTForCausalLM(GPTConfig(**cfg)).gpt.wte.weight
                      .numpy())
    assert np.abs(p1['gpt.wte.weight'] - init).max() > 1e-6


def _fleet_losses(fused, strategy_kwargs, steps=2, schedule=None,
                  layers=2, opt_cls='adamw', **train_kw):
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    paddle.seed(11)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=layers,
                    num_heads=4, max_position_embeddings=32, dropout=0.0,
                    fused_loss=fused)
    model = GPTForCausalLM(cfg)
    s = fleet.DistributedStrategy()
    hybrid = {'dp_degree': 8, 'mp_degree': 1, 'pp_degree': 1,
              'sharding_degree': 1, 'sp_degree': 1}
    hybrid.update(strategy_kwargs)
    s.hybrid_configs = hybrid
    if schedule is not None:
        s.pipeline = True
        s.pipeline_configs['schedule_mode'] = schedule
    fleet.init(is_collective=True, strategy=s)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = fleet.fleet_train_step(
        model, lambda lg, lb: model.loss(lg, lb), opt, strategy=s,
        **train_kw)
    rng = np.random.RandomState(3)
    ids = paddle.to_tensor(rng.randint(0, 128, (8, 32)).astype(np.int32))
    lbl = paddle.to_tensor(rng.randint(0, 128, (8, 32)).astype(np.int32))
    return [float(step(ids, lbl).numpy()) for _ in range(steps)]


# the pp schedules hit XLA:CPU's SPMD partitioner gap ("UNIMPLEMENTED:
# PartitionId instruction is not supported for SPMD partitioning");
# real-TPU runs are unaffected
_CPU_NO_PARTITION_ID = pytest.mark.skipif(
    jax.default_backend() == 'cpu',
    reason='XLA:CPU SPMD partitioner lacks PartitionId (UNIMPLEMENTED); '
           'runs on TPU')


@pytest.mark.parametrize('name,kw', [
    pytest.param('1f1b_pp2',
                 dict(strategy_kwargs={'dp_degree': 4, 'pp_degree': 2},
                      schedule='1F1B', layers=4),
                 marks=_CPU_NO_PARTITION_ID),
    pytest.param('gpipe_pp2',
                 dict(strategy_kwargs={'dp_degree': 4, 'pp_degree': 2},
                      schedule='GPipe', layers=4),
                 marks=_CPU_NO_PARTITION_ID),
    ('sp4', dict(strategy_kwargs={'dp_degree': 2, 'sp_degree': 4})),
])
def test_fused_loss_composes_with_schedules(name, kw):
    """fused_loss under pp (1F1B fused last stage, GPipe) and sp must
    train to the same losses as the straight non-fused model."""
    ref = _fleet_losses(False, **kw)
    got = _fleet_losses(True, **kw)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5,
                               err_msg=name)


@pytest.mark.slow
def test_fused_loss_with_remat_and_grad_merge():
    """jax.checkpoint over the custom_vjp + k-step accumulation."""
    import paddle_tpu as paddle
    from paddle_tpu.framework import functional as func_mod
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    rng = np.random.RandomState(5)
    ids = paddle.to_tensor(rng.randint(0, 97, (4, 16)).astype(np.int32))
    lbl = paddle.to_tensor(rng.randint(0, 97, (4, 16)).astype(np.int32))
    losses = {}
    for fused in (False, True):
        paddle.seed(7)
        m = GPTForCausalLM(GPTConfig(
            vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
            max_position_embeddings=16, dropout=0.0, fused_loss=fused))
        opt = paddle.optimizer.Momentum(learning_rate=1e-2, momentum=0.9,
                                        parameters=m.parameters())
        step = func_mod.TrainStep(m, m.loss, opt, remat=True, k_steps=2)
        losses[fused] = [float(step(ids, lbl).numpy()) for _ in range(4)]
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)


def test_fused_step_program_has_no_full_logits(monkeypatch):
    """Program-transform assertion: the fused TrainStep's jaxpr must not
    contain ANY [rows, vocab]-shaped array — fwd, residual, or backward —
    only [chunk, vocab] tiles. (The non-fused step's jaxpr shows several
    full-size ones; that is the traffic the op exists to remove.)"""
    import re
    import paddle_tpu as paddle
    monkeypatch.setenv('PADDLE_TPU_FUSED_CE_CHUNK', '64')
    from paddle_tpu.framework import functional as func_mod
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    vocab, hidden, b, s = 1024, 32, 4, 64  # rows=256, vocab >> hidden
    rows = b * s
    ids = np.zeros((b, s), np.int32)

    def jaxpr_for(fused):
        paddle.seed(0)
        m = GPTForCausalLM(GPTConfig(
            vocab_size=vocab, hidden_size=hidden, num_layers=1,
            num_heads=2, max_position_embeddings=s, dropout=0.0,
            fused_loss=fused))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        step = func_mod.TrainStep(m, m.loss, opt)
        return step.trace_jaxpr(paddle.to_tensor(ids),
                                paddle.to_tensor(ids))

    full = re.compile(r'(?:f32|bf16|f16)\[%d,%d\]' % (rows, vocab))
    assert full.search(jaxpr_for(False)), 'sanity: plain path has them'
    fused_jaxpr = jaxpr_for(True)
    assert not full.search(fused_jaxpr), \
        'fused step still materializes [rows, vocab]'
    # the embedding table grad [vocab, hidden] must still exist (tied
    # weight trains) — the fusion removes activations, not param grads
    assert re.search(r'f32\[%d,%d\]' % (vocab, hidden), fused_jaxpr)


@pytest.mark.slow
def test_bert_fused_mlm_matches_plain():
    """BertForPretraining(fused_mlm=True): same losses/params as the
    straight MLM path, with ~85% ignore_index labels (the MLM shape)."""
    import paddle_tpu as paddle
    from paddle_tpu.framework import functional as func_mod
    from paddle_tpu.text.models.bert import BertForPretraining

    rng = np.random.RandomState(0)
    b, s, v = 4, 16, 211
    ids = rng.randint(0, v, (b, s)).astype(np.int32)
    mlm_lab = rng.randint(0, v, (b, s)).astype(np.int32)
    mlm_lab[rng.rand(b, s) > 0.15] = -100  # only masked positions count
    nsp_lab = rng.randint(0, 2, (b,)).astype(np.int64)

    results = {}
    for fused in (False, True):
        paddle.seed(0)
        m = BertForPretraining(
            fused_mlm=fused, vocab_size=v, hidden_size=32,
            num_hidden_layers=2, num_attention_heads=4,
            intermediate_size=64, max_position_embeddings=16,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
        opt = paddle.optimizer.Momentum(learning_rate=1e-2, momentum=0.9,
                                        parameters=m.parameters())
        step = func_mod.TrainStep(
            m, lambda mo, no, ml, nl: m.loss(mo, no, ml, nl), opt)
        losses = [float(step((paddle.to_tensor(ids),),
                             (paddle.to_tensor(mlm_lab),
                              paddle.to_tensor(nsp_lab))).numpy())
                  for _ in range(3)]
        results[fused] = (losses, {k: np.asarray(p) for k, p in
                                   func_mod.extract_params(m).items()})
    l0, p0 = results[False]
    l1, p1 = results[True]
    np.testing.assert_allclose(l1, l0, rtol=1e-5)
    for k in p0:
        np.testing.assert_allclose(p1[k], p0[k], rtol=1e-4, atol=1e-6,
                                    err_msg=k)


def test_gpt_fused_loss_generate_unaffected():
    """generate() (cache path) still produces logits under fused_loss."""
    import paddle_tpu as paddle
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = dict(vocab_size=64, hidden_size=16, num_layers=1, num_heads=2,
               max_position_embeddings=24, dropout=0.0)
    m_f = GPTForCausalLM(GPTConfig(fused_loss=True, **cfg))
    paddle.seed(0)
    m_p = GPTForCausalLM(GPTConfig(fused_loss=False, **cfg))
    ids = np.random.RandomState(0).randint(0, 64, (1, 4)).astype(np.int32)
    out_f = m_f.generate(paddle.to_tensor(ids), max_new_tokens=6)
    out_p = m_p.generate(paddle.to_tensor(ids), max_new_tokens=6)
    np.testing.assert_array_equal(out_f.numpy(), out_p.numpy())
