"""Flash-attention Pallas kernels: correctness of forward AND backward vs
the jnp reference, via the Pallas interpreter on CPU (hardware-free), for
head_dim 64 (BERT/GPT-base reality — VERDICT r2 item 3) and 128.

Reference parity target: operators/fused/ attention kernels; test style:
OpTest check_output/check_grad (numeric-vs-analytic).
"""
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops import flash_attention as fa


def _mk(b=1, h=2, n=256, m=None, d=64, dtype=np.float32, seed=0):
    m = m or n
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, h, n, d).astype(dtype) * 0.3)
    k = jnp.asarray(rng.randn(b, h, m, d).astype(dtype) * 0.3)
    v = jnp.asarray(rng.randn(b, h, m, d).astype(dtype) * 0.3)
    return q, k, v


@pytest.fixture(autouse=True)
def _interpret_strict(monkeypatch):
    # interpreter mode => the pallas path really runs on CPU; strict =>
    # any fallback to the jnp reference fails the test
    monkeypatch.setenv('PADDLE_TPU_FLASH_INTERPRET', '1')
    monkeypatch.setenv('PADDLE_TPU_FLASH_STRICT', '1')


@pytest.mark.parametrize('d', [64, 128])
@pytest.mark.parametrize('causal', [False, True])
def test_forward_matches_reference(d, causal):
    q, k, v = _mk(d=d)
    scale = 1.0 / np.sqrt(d)
    out = fa.flash_attention_bhnd(q, k, v, causal=causal)
    ref = fa._ref_bhnd(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize('d', [64, 128])
@pytest.mark.parametrize('causal', [False, True])
def test_backward_matches_reference(d, causal):
    q, k, v = _mk(d=d, n=256)
    scale = 1.0 / np.sqrt(d)

    def f_flash(q, k, v):
        return jnp.sum(fa.flash_attention_bhnd(q, k, v, causal=causal) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(fa._ref_bhnd(q, k, v, causal, scale) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, 'qkv'):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg='d%s/causal=%s grad %s'
                                           % (d, causal, name))


def test_cross_attention_shapes():
    # decode-style: n != m
    q, k, v = _mk(n=256, m=512)
    out = fa.flash_attention_bhnd(q, k, v, causal=False)
    ref = fa._ref_bhnd(q, k, v, False, 1.0 / np.sqrt(64))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_strict_mode_raises_on_shape_fallback():
    # head_dim 80 cannot run the kernel; strict mode must raise, NOT
    # silently return the jnp reference (VERDICT r2 weak #3)
    q, k, v = _mk(d=80)
    with pytest.raises(RuntimeError, match='head_dim'):
        fa.flash_attention_bhnd(q, k, v)


def test_nonstrict_shape_fallback_still_works(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_FLASH_STRICT', '0')
    q, k, v = _mk(d=80)
    out = fa.flash_attention_bhnd(q, k, v)
    ref = fa._ref_bhnd(q, k, v, False, 1.0 / np.sqrt(80))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_bf16_forward_close():
    q, k, v = _mk(d=64)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    out = fa.flash_attention_bhnd(qb, kb, vb, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = fa._ref_bhnd(q, k, v, True, 1.0 / np.sqrt(64))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)


def test_bf16_grads_close():
    """bf16 operands route the backward kernels' matmuls through the
    native-dtype + f32-accumulation path (_mm_f32); grads must track the
    f32 reference within bf16 resolution."""
    q, k, v = _mk(d=64)

    def loss_flash(qq, kk, vv):
        return jnp.sum(fa.flash_attention_bhnd(
            qq, kk, vv, causal=True).astype(jnp.float32) ** 2)

    def loss_ref(qq, kk, vv):
        return jnp.sum(fa._ref_bhnd(qq, kk, vv, True,
                                    1.0 / np.sqrt(64)) ** 2)

    gb = jax.grad(loss_flash, argnums=(0, 1, 2))(
        *(t.astype(jnp.bfloat16) for t in (q, k, v)))
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gbi, gri in zip(gb, gr):
        assert gbi.dtype == jnp.bfloat16
        denom = max(float(jnp.abs(gri).max()), 1e-6)
        rel = float(jnp.abs(gbi.astype(jnp.float32) - gri).max()) / denom
        assert rel < 0.1, rel


@pytest.mark.parametrize('causal', [False, True])
def test_ring_flash_matches_jnp_ring(causal):
    """ring_flash_attention (Pallas blocks + ppermute + LSE merge, ring
    backward with rotating dk/dv accumulators) vs the jnp ring and the
    single-device reference — forward AND grads (SURVEY §5.7)."""
    import jax
    from jax.sharding import Mesh
    from paddle_tpu.ops import ring_attention as ra

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ('sp',))
    b, n, h, d = 2, 512, 2, 64   # 128 tokens/shard
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, n, h, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(b, n, h, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(b, n, h, d).astype(np.float32) * 0.3)

    def loss_flash(q, k, v):
        return jnp.sum(
            ra.ring_flash_attention_sharded(q, k, v, mesh,
                                            causal=causal) ** 2)

    def loss_ref(q, k, v):
        from paddle_tpu.ops.flash_attention import _ref_bhnd
        qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
        o = _ref_bhnd(qt, kt, vt, causal, d ** -0.5)
        return jnp.sum(jnp.swapaxes(o, 1, 2) ** 2)

    out = ra.ring_flash_attention_sharded(q, k, v, mesh, causal=causal)
    from paddle_tpu.ops.flash_attention import _ref_bhnd
    ref = jnp.swapaxes(_ref_bhnd(jnp.swapaxes(q, 1, 2),
                                 jnp.swapaxes(k, 1, 2),
                                 jnp.swapaxes(v, 1, 2),
                                 causal, d ** -0.5), 1, 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, 'qkv'):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg='grad %s causal=%s'
                                           % (name, causal))


@pytest.mark.parametrize('causal', [False, True])
def test_long_path_forward_matches_reference(causal):
    """The long-seq kernels (KV walk as a sequential grid dim + VMEM
    scratch carry — the r5 fix for the 8k scoped-vmem OOM,
    docs/bench_inwindow_r4.jsonl 11:58) vs the jnp reference. FORCE_LONG
    exercises them at a CPU-interpretable size with multiple kv blocks."""
    os.environ['PADDLE_TPU_FLASH_FORCE_LONG'] = '1'
    try:
        q, k, v = _mk(n=1024, d=64)
        scale = 1.0 / np.sqrt(64)
        out = fa.flash_attention_bhnd(q, k, v, causal=causal)
        ref = fa._ref_bhnd(q, k, v, causal, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        os.environ.pop('PADDLE_TPU_FLASH_FORCE_LONG', None)


@pytest.mark.parametrize('causal', [False, True])
def test_long_path_backward_matches_reference(causal):
    os.environ['PADDLE_TPU_FLASH_FORCE_LONG'] = '1'
    try:
        q, k, v = _mk(n=1024, d=64)
        scale = 1.0 / np.sqrt(64)

        def f_flash(q, k, v):
            return jnp.sum(
                fa.flash_attention_bhnd(q, k, v, causal=causal) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(fa._ref_bhnd(q, k, v, causal, scale) ** 2)

        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr, name in zip(g_flash, g_ref, 'qkv'):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gr), rtol=2e-4, atol=2e-4,
                err_msg='long-path grad %s causal=%s' % (name, causal))
    finally:
        os.environ.pop('PADDLE_TPU_FLASH_FORCE_LONG', None)


def test_long_path_auto_threshold():
    """seq > PADDLE_TPU_FLASH_LONG_SEQ routes to the long kernels
    automatically (the 8k bench rung path); short seqs keep the proven
    short-seq kernels."""
    assert not fa._use_long_path(512, 512)
    assert fa._use_long_path(8192, 8192)
    assert fa._use_long_path(512, 8192)


def test_supported_gate_checks_the_dispatched_paths_blocks():
    # seq 4608 routes to the LONG path (>= 4096); the preferred KV block
    # (1024) doesn't divide it, so the kernels must CLAMP to 512/512 —
    # not truncate the KV walk, and not reject a shape the kernel can
    # serve (it ran at 256/512 before the wide defaults)
    assert fa._long_blocks(4608, 4608) == (512, 512)
    q = jnp.zeros((1, 1, 4608, 64), jnp.float32)
    assert fa._supported(q, q, q) is None
    # preferred blocks used when they fit
    assert fa._long_blocks(8192, 8192) == (512, 1024)
    q = jnp.zeros((1, 1, 8192, 64), jnp.float32)
    assert fa._supported(q, q, q) is None
    # a shape no power-of-two block >= 128 tiles: rejected, with the
    # long-path reason (4616 = 8 x 577 passes the %8 granularity check)
    assert fa._long_blocks(4616, 4616) is None
    q = jnp.zeros((1, 1, 4616, 64), jnp.float32)
    reason = fa._supported(q, q, q)
    assert reason is not None and 'tileable' in reason
    # the standard path still validates against its own blocks
    q = jnp.zeros((1, 1, 512, 64), jnp.float32)
    assert fa._supported(q, q, q) is None
    # n == 768 divides 256 but not the preferred 512 q block: the
    # standard path must clamp (as it did when 256 WAS the default),
    # not reject
    assert fa._std_blocks(768, 1024) == (256, 512)
    q = jnp.zeros((1, 1, 768, 64), jnp.float32)
    k = jnp.zeros((1, 1, 1024, 64), jnp.float32)
    assert fa._supported(q, k, k) is None
    # short-q cross-attention over a long KV: q runs as a single block
    q = jnp.zeros((1, 1, 64, 64), jnp.float32)
    k = jnp.zeros((1, 1, 8192, 64), jnp.float32)
    assert fa._long_blocks(64, 8192) == (64, 1024)
    assert fa._supported(q, k, k) is None


def test_long_path_short_q_cross_attention_parity(monkeypatch):
    # q shorter than the 128 lane tile over a longer KV, forced onto the
    # long path: single-block q, clamped KV walk
    monkeypatch.setenv('PADDLE_TPU_FLASH_FORCE_LONG', '1')
    import numpy as _np
    rng = _np.random.RandomState(11)
    q = jnp.asarray(rng.randn(1, 2, 64, 64), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 640, 64), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 640, 64), jnp.float32)
    out = fa.flash_attention_bhnd(q, k, v)
    ref = fa._ref_bhnd(q, k, v, False, 1.0 / np.sqrt(64))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_long_path_clamped_blocks_parity(monkeypatch):
    # force the long path onto a seq where the preferred 512/1024 blocks
    # don't divide (640 -> clamps to bq=128, bk=640): outputs and grads
    # must match the reference exactly like the aligned case
    monkeypatch.setenv('PADDLE_TPU_FLASH_FORCE_LONG', '1')
    import numpy as _np
    rng = _np.random.RandomState(7)
    q = jnp.asarray(rng.randn(1, 2, 640, 64), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 640, 64), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 640, 64), jnp.float32)
    assert fa._long_blocks(640, 640) == (128, 640)
    scale = 1.0 / np.sqrt(64)

    def f(q, k, v):
        return (fa.flash_attention_bhnd(q, k, v, causal=True) ** 2).sum()

    def ref(q, k, v):
        return (fa._ref_bhnd(q, k, v, True, scale) ** 2).sum()

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize('causal', [False, True])
def test_fused_bwd_matches_two_pass(monkeypatch, causal):
    # seq 256 <= the 512/512 default blocks: one tile covers the score
    # matrix, so the fused single-kernel backward dispatches. Its grads
    # must match the two-pass kernels bit-for-bit in intent (same math,
    # same f32 accumulation) — tight tolerance, not reference-loose
    q, k, v = _mk(n=256)

    def loss(q, k, v):
        return (fa.flash_attention_bhnd(q, k, v, causal=causal) ** 2).sum()

    fused = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv('PADDLE_TPU_FLASH_FUSED_BWD', '0')
    twopass = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(fused, twopass):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


def test_fused_bwd_not_dispatched_multi_block(monkeypatch):
    # seq 1024 at 512/512 blocks: two tiles -> the two-pass kernels must
    # run (the fused kernel has no inter-block accumulation). Pin the
    # gate directly: a wrongly-dispatched fused kernel computes the
    # same numbers (parity can't catch it), so make dispatch itself
    # the assertion
    def boom(*a, **kw):
        raise AssertionError('fused bwd dispatched for a multi-block '
                             'shape')
    monkeypatch.setattr(fa, '_bwd_impl_fused', boom)
    q, k, v = _mk(n=1024)

    def loss(q, k, v):
        return (fa.flash_attention_bhnd(q, k, v, causal=True) ** 2).sum()

    def ref(q, k, v):
        return (fa._ref_bhnd(q, k, v, True, 1.0 / np.sqrt(64)) ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_fused_bwd_dispatched_single_block(monkeypatch):
    # and the complement: a single-tile shape MUST take the fused path
    calls = []
    real = fa._bwd_impl_fused

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)
    monkeypatch.setattr(fa, '_bwd_impl_fused', spy)
    q, k, v = _mk(n=256)
    jax.grad(lambda q: (fa.flash_attention_bhnd(q, k, v) ** 2).sum())(q)
    assert calls
