"""Multi-model serving tests (paddle_tpu/serving/registry/).

The load-bearing assertions of the registry subsystem's contract:
  1. a checkpoint artifact's fingerprint is a pure function of its
     content (same bytes -> same id, any flip -> different id), and
     the serving pointer only ever names a registered version;
  2. weight paging is exact accounting, not heuristics — the
     resident-bytes gauge never exceeds the byte budget, evictions
     follow the LRU oracle exactly, and a model with in-flight
     references is NEVER unloaded (deferred eviction), while a
     double-release is a hard error like a PageAllocator double-free;
  3. a rollout is zero-downtime: every request submitted before,
     during and after the swap completes, and post-swap requests are
     served by the new version.

Engines here are duck-typed stubs (the engine contract: scheduler
.pending/.queue, enqueue, step, generate, shutdown, rebind_perf,
metrics) so the paging/refcount logic is tested without JAX compiles.
"""
import json
import os
import subprocess
import sys

import pytest

from paddle_tpu.monitor import events as _events
from paddle_tpu.serving.gateway import AutoscalePolicy
from paddle_tpu.serving.gateway.gateway import ServingGateway
from paddle_tpu.serving.gateway.router import (LeastLoadedRouter,
                                               ModelAffinityRouter)
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.serving.registry import ModelHost, ModelRegistry
from paddle_tpu.serving.registry.registry import artifact_fingerprint
from paddle_tpu.serving.scheduler import DONE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- duck-typed stub engine ------------------------------------------

class StubEngine:
    """Minimal engine-contract implementation: completes every queued
    request on step(), emitting `max_new_tokens` copies of the version
    digit so tests can tell WHICH weights served a request."""

    max_len = 128
    num_slots = 4
    spec_k = 0
    trace_counts = {'prefill': 1, 'decode': 1}

    def __init__(self, entry):
        self.entry = entry
        self.metrics = ServingMetrics()
        self._reqs = []

    class _Sched:
        def __init__(self, eng):
            self.eng = eng

        @property
        def pending(self):
            return sum(1 for r in self.eng._reqs if not r.done)

        @property
        def queue(self):
            return tuple(r for r in self.eng._reqs if not r.done)

    @property
    def scheduler(self):
        return StubEngine._Sched(self)

    def enqueue(self, req):
        if req._arrival_t is None:
            req._arrival_t = self.metrics.now()
        self._reqs.append(req)
        return req

    def step(self):
        for r in self._reqs:
            if not r.done:
                r.tokens.extend([int(self.entry.version[-1])]
                                * r.max_new_tokens)
                r.state = DONE
                r.outcome = 'ok'
                r._finished.set()
        return self.scheduler.pending

    def generate(self, prompts, max_new_tokens=2, emit_event=True):
        return [[1] * max_new_tokens for _ in prompts]

    def shutdown(self):
        pass

    def rebind_perf(self, registry):
        pass


@pytest.fixture
def registry(tmp_path):
    reg = ModelRegistry(root=str(tmp_path))
    for m, v, scale in [('alpha', 'v1', 1.0), ('alpha', 'v2', 2.0),
                        ('beta', 'v1', 3.0), ('gamma', 'v1', 4.0)]:
        reg.publish(m, v, {'w': [scale] * 64})
    return reg


def make_host(registry, **kw):
    return ModelHost(registry, lambda entry: StubEngine(entry), **kw)


# ---- registry: fingerprints and the serving pointer ------------------

def test_fingerprint_is_content_addressed(tmp_path, registry):
    reg2 = ModelRegistry(root=str(tmp_path / 'other'))
    reg2.publish('alpha', 'v9', {'w': [1.0] * 64})
    # identical content under different (model, version) names -> same id
    assert reg2.entry('alpha', 'v9').fingerprint == \
        registry.entry('alpha', 'v1').fingerprint
    # any content change -> different id
    assert registry.entry('alpha', 'v1').fingerprint != \
        registry.entry('alpha', 'v2').fingerprint
    # recomputing from disk agrees with the registered value
    e = registry.entry('alpha', 'v1')
    assert artifact_fingerprint(e.path) == e.fingerprint


def test_serving_pointer_resolution(registry):
    # the FIRST published version holds the pointer: shipping v2 does
    # not silently change what serves — promotion is set_serving()
    assert registry.resolve('alpha').version == 'v1'
    assert registry.set_serving('alpha', 'v2') == 'v1'
    assert registry.serving_version('alpha') == 'v2'
    assert registry.resolve('alpha').version == 'v2'
    registry.set_serving('alpha', 'v1')
    # explicit version bypasses the pointer
    assert registry.resolve('alpha', 'v2').version == 'v2'
    with pytest.raises(KeyError):
        registry.set_serving('alpha', 'v7')
    with pytest.raises(KeyError):
        registry.resolve('nosuch')
    assert ('alpha', 'v1') in registry
    assert registry.versions('alpha') == ['v1', 'v2']


# ---- weight paging: budget, LRU oracle, refcounts --------------------

def test_byte_budget_holds_k_of_n_with_lru_oracle(registry):
    nbytes = registry.entry('alpha', 'v1').nbytes
    # room for exactly two resident artifacts (all four are equal-sized)
    host = make_host(registry, byte_budget=2 * nbytes + nbytes // 2)
    evicted = []
    resident = []          # LRU oracle: least-recently-used-first order

    def oracle_load(key):
        if key in resident:
            resident.remove(key)
        while len(resident) >= 2:
            evicted.append(resident.pop(0))
        resident.append(key)

    for key in [('alpha', 'v1'), ('beta', 'v1'), ('gamma', 'v1'),
                ('alpha', 'v1'), ('alpha', 'v2'), ('beta', 'v1')]:
        host.load(*key)
        oracle_load(key)
        assert host.resident_bytes <= host.byte_budget
        assert sorted(host.resident_models()) == sorted(resident)

    counts = {m: int(host._m_evictions.labels(model=m).value())
              for m in ('alpha', 'beta', 'gamma')}
    want = {m: sum(1 for k in evicted if k[0] == m)
            for m in ('alpha', 'beta', 'gamma')}
    assert counts == want
    # gauge families agree with the accessors
    assert host._m_resident_bytes.value() == host.resident_bytes
    assert host._m_models.value() == len(host.resident_models())


def test_oversized_artifact_rejected(registry):
    nbytes = registry.entry('alpha', 'v1').nbytes
    host = make_host(registry, byte_budget=nbytes // 2)
    with pytest.raises(RuntimeError, match='budget'):
        host.load('alpha', 'v1')


def test_deferred_eviction_with_inflight_refs(registry):
    nbytes = registry.entry('alpha', 'v1').nbytes
    host = make_host(registry, byte_budget=4 * nbytes)
    host.load('alpha', 'v1')
    host.acquire('alpha', 'v1')
    # eviction with a live reference defers instead of unloading: the
    # weights stay resident (bytes still accounted) but the version
    # stops being routable — no NEW request lands on it
    assert host.evict('alpha', 'v1') is False
    assert ('alpha', 'v1') in host.resident_models()
    assert host.resident_bytes == nbytes
    assert not host.hosts_model('alpha', 'v1')
    assert host.refcount('alpha', 'v1') == 1
    assert host._m_deferred.value() == 1
    # the last release completes the deferred eviction
    host.release('alpha', 'v1')
    assert host.resident_models() == []
    assert host.resident_bytes == 0


def test_double_release_raises(registry):
    host = make_host(registry)
    host.load('alpha', 'v1')
    host.acquire('alpha', 'v1')
    host.release('alpha', 'v1')
    with pytest.raises(ValueError, match='double-release'):
        host.release('alpha', 'v1')
    with pytest.raises(ValueError, match='double-release'):
        host.release('beta', 'v1')   # never acquired at all


def test_pinned_model_cannot_be_evicted(registry):
    host = make_host(registry)
    host.load('alpha', 'v1', pin=True)
    with pytest.raises(ValueError, match='pinned'):
        host.evict('alpha', 'v1')
    host.unpin('alpha', 'v1')
    assert host.evict('alpha', 'v1') is True
    with pytest.raises(KeyError):
        host.evict('alpha', 'v1')    # no longer resident


def test_churn_1k_loads_zero_leak(registry):
    """1000 load/acquire/release/evict cycles across all models leave
    zero residue: no bytes, no models, no refcounts, no parked work."""
    keys = [('alpha', 'v1'), ('alpha', 'v2'), ('beta', 'v1'),
            ('gamma', 'v1')]
    nbytes = registry.entry('alpha', 'v1').nbytes
    host = make_host(registry, byte_budget=2 * nbytes + nbytes // 2)
    for i in range(1000):
        key = keys[i % len(keys)]
        host.load(*key)
        host.acquire(*key)
        host.release(*key)
    for key in list(host.resident_models()):
        assert host.refcount(*key) == 0
        host.evict(*key)
    assert host.resident_models() == []
    assert host.resident_bytes == 0
    assert host._m_resident_bytes.value() == 0
    assert host._m_models.value() == 0
    assert host.step() == 0          # nothing parked, nothing loading


# ---- host as engine: park on miss, serve after async load ------------

def test_request_parks_until_model_loads(registry):
    host = make_host(registry)
    req = host.add_request([1, 2, 3], max_new_tokens=4, model='beta',
                           emit_event=False)
    assert not req.done                 # parked: beta not resident yet
    for _ in range(50):
        if req.done:
            break
        host.step()
    assert req.done and req.outcome == 'ok'
    assert req.tokens == [1, 1, 1, 1]   # beta v1 served it
    assert host.hosts_model('beta', 'v1')
    # the in-flight reference was released on retirement
    assert host.refcount('beta', 'v1') == 0


def test_unknown_model_rejected_at_front_door(registry):
    host = make_host(registry)
    with pytest.raises(KeyError):
        host.add_request([1], max_new_tokens=2, model='nosuch',
                         emit_event=False)


# ---- affinity routing ------------------------------------------------

class _FakeReplica:
    def __init__(self, index, hosts, load):
        self.index = index
        self._hosts = hosts
        self._load = load
        self.engine = self

    def routable(self):
        return True

    def load(self):
        return self._load

    def hosts_model(self, model, version=None):
        return model in self._hosts


def test_model_affinity_router_prefers_hosting_replicas():
    pool = [_FakeReplica(0, {'beta'}, load=5),
            _FakeReplica(1, {'alpha'}, load=3),
            _FakeReplica(2, {'alpha'}, load=1),
            _FakeReplica(3, set(), load=0)]
    r = ModelAffinityRouter()
    # hosting replicas first (by load), then the rest (by load)
    assert [x.index for x in r.candidates_for(pool, 'alpha')] == \
        [2, 1, 3, 0]
    assert [x.index for x in r.candidates_for(pool, 'beta')] == \
        [0, 3, 2, 1]
    # unknown model degrades to plain least-loaded order
    assert [x.index for x in r.candidates_for(pool, 'nosuch')] == \
        [3, 2, 1, 0]
    # the base router interface is intact (gateway fallback path)
    assert isinstance(r, LeastLoadedRouter)
    assert [x.index for x in r.candidates(pool)] == [3, 2, 1, 0]


# ---- gateway: multi-model routing + zero-downtime rollout ------------

def test_gateway_multimodel_rollout_zero_loss(registry):
    log = _events.RequestLog()
    prev = _events.set_default_request_log(log)
    try:
        gw = ServingGateway(lambda: make_host(registry),
                            replicas=2, router=ModelAffinityRouter())
        try:
            registry.set_serving('alpha', 'v1')
            before = [gw.submit([1, 2], max_new_tokens=4,
                                model=('alpha' if i % 2 else 'beta'),
                                tenant='t%d' % (i % 3))
                      for i in range(10)]
            gw.run()
            summary = gw.rollout('alpha', 'v2')
            after = [gw.submit([3], max_new_tokens=4, model='alpha')
                     for _ in range(4)]
            gw.run()
        finally:
            gw.shutdown()
    finally:
        _events.set_default_request_log(prev)

    # zero loss: every request before and after the swap completed
    assert all(r.done and r.error is None for r in before + after)
    assert summary['model'] == 'alpha'
    assert summary['from_version'] == 'v1'
    assert summary['to_version'] == 'v2'
    assert summary['replicas'] == [0, 1]
    # pre-swap alpha requests were served by v1, post-swap by v2
    assert all(r.tokens == [1] * 4 for r in before
               if r.sampling.get('model') == 'alpha')
    assert all(r.tokens == [2] * 4 for r in after)
    # wide events carry the model dimension and filter on it
    evs = log.events(model='alpha')
    assert len(evs) == 5 + 4
    assert {e['model'] for e in log.events()} == {'alpha', 'beta'}
    assert all('model' in e for e in log.events())


def test_gateway_rollout_without_hosts_raises(tmp_path):
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import ContinuousBatchingEngine
    import paddle_tpu as paddle
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_position_embeddings=32, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    gw = ServingGateway(
        lambda: ContinuousBatchingEngine(m, num_slots=2, max_len=16),
        replicas=1)
    try:
        with pytest.raises(ValueError, match='ModelHost-backed'):
            gw.rollout('alpha', 'v2')
    finally:
        gw.shutdown()


# ---- autoscaler: per-tenant premium burn -----------------------------

def test_premium_tenant_burn_scales_before_aggregate():
    """Fake clock: aggregate burn stays at zero while one premium
    tenant burns; the policy must scale up on the tenant signal alone,
    naming the tenant in the reason."""
    pol = AutoscalePolicy(slo_ttft_s=0.5, sustain_s=3.0, cooldown_s=0.0,
                          premium_tenants=('premium',))
    hot = {'premium': 0.9, 'bulk': 0.0}
    assert pol.decide(0.0, 0.0, 0.5, 1, 2, tenant_burns=hot).delta == 0
    assert pol.decide(1.0, 0.0, 0.5, 1, 2, tenant_burns=hot).delta == 0
    d = pol.decide(3.0, 0.0, 0.5, 1, 2, tenant_burns=hot)
    assert d.delta == +1
    assert 'premium' in d.reason and 'burn' in d.reason


def test_non_premium_tenant_burn_is_ignored():
    pol = AutoscalePolicy(slo_ttft_s=0.5, sustain_s=2.0, cooldown_s=0.0,
                          premium_tenants=('premium',))
    cold = {'bulk': 0.9}        # a non-premium tenant burning alone
    for t in (0.0, 2.0, 4.0, 6.0):
        assert pol.decide(t, 0.0, 0.0, 0, 2,
                          tenant_burns=cold).delta <= 0
    # ...and a burning premium tenant suppresses idle scale-down
    pol2 = AutoscalePolicy(slo_ttft_s=0.5, sustain_s=2.0, cooldown_s=0.0,
                           premium_tenants=('premium',))
    hot = {'premium': 0.9}
    assert pol2.decide(0.0, 0.0, 0.0, 0, 2, tenant_burns=hot).delta == 0
    d = pol2.decide(2.0, 0.0, 0.0, 0, 2, tenant_burns=hot)
    assert d.delta == +1        # premium burn wins over idle


def test_policy_without_premium_config_is_positional_compatible():
    """Callers predating tenant_burns keep working unchanged."""
    pol = AutoscalePolicy(slo_ttft_s=0.5, sustain_s=0.0, cooldown_s=0.0)
    assert pol.premium_tenants == ()
    assert pol.decide(0.0, 0.9, 0.9, 4, 2).delta == +1


# ---- workload: model dimension, hash-compat --------------------------

def test_workload_models_deterministic_and_hash_compat():
    from paddle_tpu.capacity.workload import WorkloadSpec
    base = WorkloadSpec(requests=200, seed=5)
    multi = WorkloadSpec(requests=200, seed=5,
                         models={'mode': 'zipf', 'count': 3, 'a': 3.0})
    # the models key is absent-when-unset: pre-change specs hash the same
    assert 'models' not in base.to_dict()
    assert base.hash == WorkloadSpec(requests=200, seed=5).hash
    assert multi.hash != base.hash
    # round-trips through the canonical dict
    assert WorkloadSpec.from_dict(multi.to_dict()).hash == multi.hash

    t1, t2 = multi.generate(), multi.generate()
    assert t1.models() == t2.models()          # seeded determinism
    assert (t1.model_id == t2.model_id).all()
    assert set(t1.models()) <= {'model_000', 'model_001', 'model_002'}
    mix = t1.model_mix()
    assert sum(mix.values()) == 200
    # zipf: the head model dominates
    assert mix['model_000'] == max(mix.values())
    # the model stream is independent: same arrivals/tenants either way
    assert (base.generate().arrival == t1.arrival).all()
    # single-model trace reports no model dimension
    assert base.generate().models() is None
    assert base.generate().model_mix() == {}


def test_workload_models_jsonl_round_trip():
    from paddle_tpu.capacity.workload import Trace, WorkloadSpec
    spec = WorkloadSpec(requests=20, seed=2,
                        models={'mode': 'round_robin',
                                'models': [{'name': 'a'}, {'name': 'b'}]})
    trace = spec.generate()
    back = Trace.from_jsonl(trace.to_jsonl())
    assert back.models() == trace.models()
    assert back.models()[:4] == ['a', 'b', 'a', 'b']
    # single-model traces round-trip without a model column at all
    single = WorkloadSpec(requests=20, seed=2).generate()
    text = single.to_jsonl()
    assert '"model"' not in text
    assert Trace.from_jsonl(text).models() is None


# ---- offline gate: tools/registry_report.py --------------------------

def _run_gate(*args):
    """(exit code, parsed JSON lines) — gate_common emits one JSON
    object per line: findings (regression: true) or the ok-summary."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools',
                                      'registry_report.py')] + list(args),
        capture_output=True, text=True, cwd=REPO)
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip()]
    return proc.returncode, lines


def test_registry_report_exit_codes(tmp_path):
    # nothing to check -> 2
    rc, _ = _run_gate()
    assert rc == 2

    clean = tmp_path / 'clean.json'
    clean.write_text(json.dumps({
        'model': 'alpha', 'from_version': 'v1', 'to_version': 'v2',
        'replicas': 2, 'cache_hits': 3, 'cache_misses': 0,
        'requests': 10, 'completed': 10}))
    rc, out = _run_gate('--rollout', str(clean))
    assert rc == 0
    assert out[-1]['ok'] is True
    assert out[-1]['rollout']['to_version'] == 'v2'

    lossy = tmp_path / 'lossy.json'
    lossy.write_text(json.dumps({
        'model': 'alpha', 'from_version': 'v1', 'to_version': 'v2',
        'requests': 10, 'completed': 8, 'cache_misses': 0}))
    rc, out = _run_gate('--rollout', str(lossy))
    assert rc == 1
    assert out[0]['problem'] == 'rollout_lost_requests'
    assert out[0]['regression'] is True

    cold = tmp_path / 'cold.json'
    cold.write_text(json.dumps({
        'model': 'alpha', 'to_version': 'v2', 'requests': 4,
        'completed': 4, 'cache_hits': 0, 'cache_misses': 2}))
    rc, out = _run_gate('--rollout', str(cold))
    assert rc == 1
    assert out[0]['problem'] == 'rollout_compile_cache_miss'


def test_registry_report_metrics_cross_checks(tmp_path):
    metrics = tmp_path / 'metrics.json'
    metrics.write_text(json.dumps({
        'registry_resident_bytes': {
            'type': 'gauge', 'labels': [],
            'samples': [{'labels': {}, 'value': 900.0}]},
        'registry_models_resident': {
            'type': 'gauge', 'labels': [],
            'samples': [{'labels': {}, 'value': 2.0}]}}))
    rc, out = _run_gate('--metrics', str(metrics), '--byte-budget',
                        '1000')
    assert rc == 0
    assert out[-1]['registry_metrics']['registry_resident_bytes'] == 900.0
    rc, out = _run_gate('--metrics', str(metrics), '--byte-budget', '800')
    assert rc == 1
    assert out[0]['problem'] == 'resident_bytes_over_budget'


def test_registry_report_model_events_gate(tmp_path):
    sink = tmp_path / 'events.jsonl'
    rows = [{'request_id': i, 'model': 'alpha', 'outcome': 'ok',
             'output_tokens': 4} for i in range(3)]
    rows.append({'request_id': 9, 'model': 'alpha', 'outcome': 'error',
                 'output_tokens': 0})
    sink.write_text('\n'.join(json.dumps(r) for r in rows) + '\n')
    rc, out = _run_gate('--jsonl', str(sink))
    assert rc == 0          # no --model gate: report only
    assert out[-1]['models']['alpha']['requests'] == 4
    assert out[-1]['models']['alpha']['errors'] == 1
    rc, out = _run_gate('--jsonl', str(sink), '--model', 'alpha')
    assert rc == 1
    assert out[0]['problem'] == 'model_request_not_ok'
