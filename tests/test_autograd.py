"""Autograd tape tests (reference pattern: unittests/test_imperative_*.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_backward_simple():
    x = paddle.to_tensor([1., 2., 3.], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2., 4., 6.])


def test_grad_accumulation_multi_use():
    x = paddle.to_tensor([2.], stop_gradient=False)
    y = x * x + x * 3
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.])  # 2x + 3


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1., 2.], stop_gradient=False)
    y = paddle.to_tensor([3., 4.], stop_gradient=True)
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3., 4.])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([1., 2.], stop_gradient=False)
    y = x * 2
    d = y.detach()
    assert d.stop_gradient
    z = (y * d).sum()
    z.backward()
    # d is constant: dz/dx = 2*d = [4, 8]
    np.testing.assert_allclose(x.grad.numpy(), [4., 8.])


def test_no_grad_context():
    x = paddle.to_tensor([1.], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    y2 = x * 2
    assert not y2.stop_gradient


def test_paddle_grad_api():
    x = paddle.to_tensor([3.], stop_gradient=False)
    y = x * x
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), [6.])
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_backward_accumulates_across_calls():
    x = paddle.to_tensor([1.], stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.])
    x.clear_grad()
    assert x.grad is None


def test_grad_of_chain():
    x = paddle.to_tensor([0.5], stop_gradient=False)
    y = paddle.tanh(paddle.exp(x))
    y.backward()
    ref = (1 - np.tanh(np.exp(0.5)) ** 2) * np.exp(0.5)
    np.testing.assert_allclose(x.grad.numpy(), [ref], rtol=1e-5)


def test_non_scalar_backward_needs_grad_tensor():
    x = paddle.to_tensor([1., 2.], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        y.backward()
    y2 = x * 2
    y2.backward(paddle.to_tensor([1., 1.]))
    np.testing.assert_allclose(x.grad.numpy(), [2., 2.])


def test_register_hook():
    x = paddle.to_tensor([1.], stop_gradient=False)
    seen = []

    h = x.register_hook(lambda g: seen.append(g.numpy().copy()))
    (x * 5).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [5.])
    h.remove()


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor([3.], stop_gradient=False)
    y = Double.apply(x)
    np.testing.assert_allclose(y.numpy(), [6.])
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.])


def test_double_use_deep_graph():
    # dep-counted traversal must handle diamond graphs
    x = paddle.to_tensor([1.], stop_gradient=False)
    a = x * 2
    b = a + 1
    c = a * 3
    d = (b * c).sum()
    d.backward()
    # d = (2x+1)(6x); dd/dx = 2*6x + (2x+1)*6 = 12x + 12x + 6 = 24x+6 = 30
    np.testing.assert_allclose(x.grad.numpy(), [30.])


def test_no_grad_guard_is_thread_local():
    """Interleaved no_grad_guard enter/exit across threads must not
    corrupt another thread's grad mode. With a process-global flag the
    save/restore pairs race (T1 enter, T2 enter, T1 exit, T2 exit
    restores T1's False) and the whole process loses its tape — the
    serving gateway runs one guard-wrapped driver thread per replica,
    so a full test run used to come out of test_serving_gateway with
    has_grad=False and every later .backward() silently recording
    nothing."""
    import threading

    stop = threading.Event()
    seen_disabled = []

    def churn():
        while not stop.is_set():
            with paddle.no_grad():
                pass

    threads = [threading.Thread(target=churn) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            if not paddle.is_grad_enabled():
                seen_disabled.append(True)
                break
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not seen_disabled
    assert paddle.is_grad_enabled()
    # and the tape still records after the churn
    x = paddle.to_tensor([2.], stop_gradient=False)
    (x * x).backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.])
