"""slim quantization tests (reference pattern:
python/paddle/fluid/contrib/slim/tests/test_imperative_qat.py,
test_post_training_quantization_*.py — quantize a small model, check the
quantized forward stays close and training still converges)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.slim import (ImperativeQuantAware, PostTrainingQuantization,
                             QuantedConv2D, QuantedLinear, cal_kl_threshold,
                             fake_quant_dequant_abs_max,
                             fake_quant_dequant_channel_wise)


def _mlp():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


def _convnet():
    paddle.seed(7)
    return nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(),
                         nn.Flatten(), nn.Linear(4 * 8 * 8, 3))


def test_fake_quant_roundtrip_accuracy():
    import jax.numpy as jnp
    x = jnp.asarray(np.random.RandomState(0).standard_normal((64, 64)),
                    jnp.float32)
    xq = fake_quant_dequant_abs_max(x, bits=8)
    err = float(jnp.max(jnp.abs(x - xq)))
    scale = float(jnp.max(jnp.abs(x)))
    assert err <= scale / 127 + 1e-6  # one quantization step

    w = jnp.asarray(np.random.RandomState(1).standard_normal((16, 8)) *
                    np.linspace(0.1, 10, 8), jnp.float32)
    wq_pc = fake_quant_dequant_channel_wise(w, bits=8, axis=1)
    wq_pt = fake_quant_dequant_abs_max(w, bits=8)
    # per-channel must be more accurate when channel ranges differ wildly
    assert float(jnp.mean((w - wq_pc) ** 2)) < \
        float(jnp.mean((w - wq_pt) ** 2))


def test_fake_quant_ste_gradient():
    import jax
    import jax.numpy as jnp
    x = jnp.linspace(-1.0, 1.0, 16)

    def f(a):
        return jnp.sum(fake_quant_dequant_abs_max(a, bits=8))
    g = jax.grad(f)(x)
    np.testing.assert_allclose(np.asarray(g), np.ones(16), atol=1e-6)


def test_qat_wrap_and_forward_close():
    model = _mlp()
    x = paddle.to_tensor(np.random.RandomState(0).standard_normal(
        (16, 8)).astype(np.float32))
    ref = model(x).numpy()
    quanter = ImperativeQuantAware(
        weight_quantize_type='channel_wise_abs_max')
    quanter.quantize(model)
    kinds = [type(l) for l in model.sublayers()]
    assert kinds.count(QuantedLinear) == 2
    model.train()
    out = model(x).numpy()
    # int8 simulation should track fp32 within a few percent of the range
    assert np.max(np.abs(out - ref)) < 0.05 * np.max(np.abs(ref)) + 0.05


def test_qat_trains_and_updates_scales():
    model = _mlp()
    ImperativeQuantAware().quantize(model)
    model.train()
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.standard_normal((32, 8)).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (32,)).astype(np.int64))
    losses = []
    for _ in range(80):
        loss = nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.7, losses
    # moving-average act scales must have been populated
    for layer in model.sublayers():
        if isinstance(layer, QuantedLinear):
            assert float(layer._act_quanter.scale.numpy()) > 0


def test_qat_save_load_roundtrip(tmp_path):
    model = _mlp()
    quanter = ImperativeQuantAware()
    quanter.quantize(model)
    model.eval()
    x = paddle.to_tensor(np.random.RandomState(0).standard_normal(
        (4, 8)).astype(np.float32))
    ref = model(x).numpy()
    path = str(tmp_path / 'qat_model')
    quanter.save_quantized_model(model, path)
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(loaded(x).numpy(), ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize('algo', ['abs_max', 'avg', 'mse', 'KL', 'hist'])
def test_ptq_calibration_algos(algo):
    model = _convnet()
    rng = np.random.RandomState(0)
    data = [paddle.to_tensor(rng.standard_normal(
        (8, 1, 8, 8)).astype(np.float32)) for _ in range(4)]
    x = data[0]
    ref = model(x).numpy()
    ptq = PostTrainingQuantization(model=model, data_loader=data,
                                   batch_nums=4, algo=algo)
    qmodel = ptq.quantize()
    kinds = [type(l) for l in qmodel.sublayers()]
    assert QuantedConv2D in kinds and QuantedLinear in kinds
    assert ptq.scales and all(s > 0 for s in ptq.scales.values()), ptq.scales
    out = qmodel(x).numpy()
    rel = np.max(np.abs(out - ref)) / (np.max(np.abs(ref)) + 1e-8)
    assert rel < 0.25, (algo, rel)


def test_kl_threshold_prefers_bulk_over_outlier():
    # mass concentrated near 0 with a tiny outlier tail: KL threshold must
    # clip well below abs_max
    hist = np.zeros(2048)
    hist[:256] = 1000.0
    hist[-1] = 1.0
    bin_width = 10.0 / 2048
    t = cal_kl_threshold(hist, bin_width, 8)
    assert t < 10.0 * 0.75, t
    assert t > 256 / 2048 * 10.0 * 0.5


def test_skip_quant_respected():
    model = _mlp()
    model[0].skip_quant = True
    ImperativeQuantAware().quantize(model)
    assert type(model[0]) is nn.Linear
    assert type(model[2]) is QuantedLinear


def test_observer_growing_range_rebins():
    # regression: histogram-based algos must merge batches whose abs ranges
    # differ (early narrow-range mass must not be reinterpreted as spread
    # over the widened range)
    from paddle_tpu.slim.ptq import _Observer
    obs = _Observer('hist', 8, hist_bins=512, hist_percent=0.999)
    rng = np.random.RandomState(0)
    obs.observe(rng.uniform(-1, 1, 4096))      # range ~1
    obs.observe(rng.uniform(-10, 10, 4096))    # range grows to ~10
    s = obs.scale()
    assert 8.0 < s <= 10.0, s  # bulk of combined mass is uniform to 10

    obs2 = _Observer('KL', 8, hist_bins=512)
    obs2.observe(rng.standard_normal(8192) * 0.1)
    obs2.observe(np.asarray([5.0]))            # single extreme outlier
    t = obs2.scale()
    # KL's search floor is half the range (starting_iter = bins//2), so the
    # outlier-driven range of 5.0 must be clipped to ~2.5, not tracked
    assert t < 0.55 * 5.0, t


def test_ptq_hooks_removed_on_failure():
    model = _mlp()
    bad = [paddle.to_tensor(np.zeros((4, 8), np.float32)),
           paddle.to_tensor(np.zeros((4, 3), np.float32))]  # wrong shape
    ptq = PostTrainingQuantization(model=model, data_loader=bad,
                                   batch_nums=2, algo='abs_max')
    with pytest.raises(Exception):
        ptq.quantize()
    for layer in model.sublayers(include_self=True):
        assert not layer._forward_pre_hooks, layer


def test_shared_layer_single_wrapper_and_alias_types():
    # a layer shared at two paths must get ONE wrapper so calibrated scales
    # cover every call site; lowercase reference op names are accepted
    class TwoPath(nn.Layer):
        def __init__(self):
            super().__init__()
            shared = nn.Linear(8, 8)
            self.a = shared
            self.b = shared

        def forward(self, x):
            return self.a(x) + self.b(x)

    model = TwoPath()
    rng = np.random.RandomState(0)
    data = [paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
            for _ in range(2)]
    ptq = PostTrainingQuantization(model=model, data_loader=data,
                                   batch_nums=2, algo='abs_max',
                                   quantizable_op_type=('linear',))
    ptq.quantize()
    assert model.a is model.b
    assert isinstance(model.a, QuantedLinear)
    assert float(model.a._act_quanter.scale.numpy()) > 0

    with pytest.raises(ValueError):
        PostTrainingQuantization(model=model, data_loader=data,
                                 quantizable_op_type=('nope',))
    with pytest.raises(NotImplementedError):
        ImperativeQuantAware(weight_quantize_layer=object())
