"""C inference API end-to-end (reference pattern: the capi_exp tests —
paddle/fluid/inference/tests/api/ exercising the C surface against a
saved model).

Builds libpaddle_tpu_c.so (CPython-embedding shared lib), compiles a
real C client with gcc, runs it in a subprocess against a jit.save'd
model, and compares the printed outputs with the in-process Python
predictor bit-for-bit (same platform, same executable path).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CLIENT_C = r'''
#include <stdio.h>
#include <stdlib.h>
#include "pd_capi.h"

int main(int argc, char** argv) {
  /* argv: repo_root model_dir [--no-init] */
  if (argc < 3) { fprintf(stderr, "usage: client repo model\n"); return 2; }
  if (argc > 3) {
    /* pre-init calls must fail with an error, not crash the process */
    PD_Config* c0 = PD_ConfigCreate();
    PD_ConfigSetModel(c0, argv[2]);
    PD_Predictor* p0 = PD_PredictorCreate(c0);
    PD_ConfigDestroy(c0);
    if (p0 != NULL) { fprintf(stderr, "pre-init create succeeded?\n"); return 10; }
    fprintf(stderr, "pre-init: %s\n", PD_GetLastError());
    return 0;
  }
  if (PD_Init(argv[1]) != 0) {
    fprintf(stderr, "init: %s\n", PD_GetLastError());
    return 3;
  }
  PD_Config* cfg = PD_ConfigCreate();
  PD_ConfigSetModel(cfg, argv[2]);
  PD_ConfigSetDevice(cfg, "cpu");
  PD_Predictor* pred = PD_PredictorCreate(cfg);
  PD_ConfigDestroy(cfg);
  if (!pred) { fprintf(stderr, "create: %s\n", PD_GetLastError()); return 4; }

  int n_in = PD_PredictorGetInputNum(pred);
  char name[128];
  if (n_in < 1 || PD_PredictorGetInputName(pred, 0, name, 128) < 0) {
    fprintf(stderr, "inputs: %s\n", PD_GetLastError());
    return 5;
  }
  float data[2 * 8];
  for (int i = 0; i < 16; ++i) data[i] = 0.125f * (float)(i - 8);
  int64_t shape[2] = {2, 8};
  if (PD_PredictorSetInputFloat(pred, name, data, shape, 2) != 0 ||
      PD_PredictorRun(pred) != 0) {
    fprintf(stderr, "run: %s\n", PD_GetLastError());
    return 6;
  }
  if (PD_PredictorGetOutputNum(pred) < 1) { return 7; }
  int64_t oshape[8];
  int rank = PD_PredictorGetOutputShape(pred, 0, oshape, 8);
  if (rank < 0) { fprintf(stderr, "shape: %s\n", PD_GetLastError()); return 8; }
  printf("rank %d\n", rank);
  for (int i = 0; i < rank; ++i) printf("dim %lld\n", (long long)oshape[i]);
  float out[256];
  int64_t n = PD_PredictorGetOutputFloat(pred, 0, out, 256);
  if (n < 0 || n > 256) { fprintf(stderr, "out: %s\n", PD_GetLastError()); return 9; }
  for (int64_t i = 0; i < n; ++i) printf("%.8e\n", out[i]);
  PD_PredictorDestroy(pred);
  /* error surface: an invalid call after destroy must fail, not crash */
  return 0;
}
'''


@pytest.fixture(scope='module')
def capi_lib():
    from paddle_tpu.capi import build_capi
    try:
        return build_capi()
    except RuntimeError as e:
        pytest.skip('capi build unavailable: %s' % e)


@pytest.fixture(scope='module')
def saved_model(tmp_path_factory):
    paddle.seed(1234)
    model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    model.eval()
    path = str(tmp_path_factory.mktemp('capi') / 'mlp')
    from paddle_tpu.static import InputSpec
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([2, 8], name='features')])
    x = (0.125 * (np.arange(16, dtype=np.float32) - 8)).reshape(2, 8)
    ref = model(paddle.to_tensor(x)).numpy()
    return path, ref


def _build_client(lib, tmpdir):
    from paddle_tpu.capi import header_path
    src = os.path.join(tmpdir, 'client.c')
    with open(src, 'w') as f:
        f.write(CLIENT_C)
    exe = os.path.join(tmpdir, 'client')
    cmd = ['gcc', '-O1', '-o', exe, src,
           '-I', os.path.dirname(header_path()), lib,
           '-Wl,-rpath,' + os.path.dirname(lib)]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    return exe


def test_c_client_matches_python_predictor(capi_lib, saved_model, tmp_path):
    model_path, ref = saved_model
    exe = _build_client(capi_lib, str(tmp_path))
    env = dict(os.environ)
    # the embedded interpreter must resolve the venv's packages AND the
    # repo; the C side only prepends the repo root
    env['PYTHONPATH'] = os.pathsep.join(
        [p for p in sys.path if p and os.path.isdir(p)])
    env.pop('XLA_FLAGS', None)  # no virtual-device mesh inside the client
    proc = subprocess.run([exe, REPO, model_path], capture_output=True,
                          text=True, env=env, timeout=600)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    lines = proc.stdout.strip().splitlines()
    rank = int(lines[0].split()[1])
    dims = [int(l.split()[1]) for l in lines[1:1 + rank]]
    vals = np.array([float(l) for l in lines[1 + rank:]], np.float32)
    assert dims == list(ref.shape)
    np.testing.assert_allclose(vals.reshape(ref.shape), ref,
                               rtol=1e-5, atol=1e-6)


def test_c_client_pre_init_fails_cleanly(capi_lib, tmp_path):
    exe = _build_client(capi_lib, str(tmp_path))
    proc = subprocess.run([exe, REPO, 'ignored', '--no-init'],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.returncode, proc.stderr)
    assert 'PD_Init has not been called' in proc.stderr


def test_c_client_reports_bad_model_path(capi_lib, tmp_path):
    exe = _build_client(capi_lib, str(tmp_path))
    env = dict(os.environ)
    env['PYTHONPATH'] = os.pathsep.join(
        [p for p in sys.path if p and os.path.isdir(p)])
    env.pop('XLA_FLAGS', None)
    proc = subprocess.run([exe, REPO, str(tmp_path / 'nope')],
                          capture_output=True, text=True, env=env,
                          timeout=600)
    # create must fail cleanly through PD_GetLastError, not crash
    assert proc.returncode == 4, (proc.returncode, proc.stderr)
    assert 'create:' in proc.stderr
