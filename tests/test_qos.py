"""Overload-robust multi-tenant QoS (ISSUE 17): admission control at
the gateway front door, priority preemption with exact-parity resume in
the paged engine, and the simulator's million-request policy sweeps.

The load-bearing contracts:

  1. admission is pure policy over an injected clock — token buckets
     and quotas are exact functions of (now, tenant), rejection never
     consumes credit, and tests never sleep;
  2. a shed request is DATA, not an exception: an already-finished
     handle with `error` set and exactly ONE wide event
     (outcome='rejected'), and it never touches an engine;
  3. preempt-and-resume never buys QoS with output drift: a victim's
     delivered stream is token-for-token IDENTICAL to an unpreempted
     run (determinism + the Request._replay swallow), and zero-retrace
     still holds;
  4. the simulator's QoS path makes the same admission decisions in
     virtual time, deterministically.
"""
import json
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.capacity import workload
from paddle_tpu.capacity.qos import (REJECT_REASONS, QosPolicy,
                                     TenantClass, TokenBucket)
from paddle_tpu.capacity.simulator import ServiceModel, simulate, sweep_qos
from paddle_tpu.monitor import events as _events
from paddle_tpu.monitor.registry import MetricRegistry
from paddle_tpu.serving import (ContinuousBatchingEngine,
                                PagedContinuousBatchingEngine,
                                ServingGateway)
from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

MNT = 8


@pytest.fixture(scope='module')
def model():
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=211, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope='module')
def prompts():
    rng = np.random.RandomState(3)
    return [[int(t) for t in rng.randint(0, 211, n)]
            for n in (5, 9, 7, 12, 4, 11, 6, 8)]


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t


def _capture_log():
    """Fresh RequestLog installed as default; caller must restore."""
    log = _events.RequestLog(capacity=256)
    prev = _events.set_default_request_log(log)
    return log, prev


def _events_for(log, req_id):
    return [e for e in log.events() if e['request_id'] == req_id]


# ---- pure policy units (fake clock, no jax) ---------------------------


def test_token_bucket_fake_clock():
    b = TokenBucket(rate=2.0, burst=4.0)
    assert all(b.take(0.0) for _ in range(4))
    assert not b.take(0.0)             # empty; reject leaves level alone
    assert b.level(0.0) == pytest.approx(0.0)
    assert b.take(0.5)                 # 0.5s * 2/s == 1 token refilled
    assert not b.take(0.5)
    assert b.level(10.0) == pytest.approx(4.0)   # capped at burst


def test_policy_quota_checked_before_rate():
    pol = QosPolicy(classes=[
        TenantClass('bg', rate=100.0, burst=1.0, max_concurrent=1)])
    ok, reason = pol.admit(0.0, 'bg')
    assert ok and reason is None
    # in-flight cap hit: quota rejection must NOT spend a bucket token
    lvl = pol.bucket_level('bg', 0.0)
    ok, reason = pol.admit(0.0, 'bg')
    assert (ok, reason) == (False, 'quota')
    assert pol.bucket_level('bg', 0.0) == pytest.approx(lvl)
    pol.finish('bg')
    assert pol.inflight('bg') == 0
    ok, _ = pol.admit(0.0, 'bg')       # slot free again, bucket empty
    assert (ok, _) == (False, 'rate')
    assert reason in REJECT_REASONS


def test_policy_roundtrip_and_priorities():
    pol = QosPolicy(
        classes=[TenantClass('premium', priority=2),
                 TenantClass('bg', rate=5.0, burst=8.0,
                             max_concurrent=3)],
        max_pending=16, max_queue_wait_s=1.5)
    clone = QosPolicy.from_dict(pol.to_dict())
    assert clone.to_dict() == pol.to_dict()
    assert clone.priority_of('premium') == 2
    assert clone.priority_of('bg') == 0
    assert clone.priority_of('unknown') == 0      # default class
    assert clone.max_pending == 16
    assert clone.max_queue_wait_s == pytest.approx(1.5)
    # fresh state: the clone starts with a full bucket
    assert clone.bucket_level('bg', 0.0) == pytest.approx(8.0)


# ---- gateway admission ------------------------------------------------


def _slot_factory(model):
    return lambda: ContinuousBatchingEngine(
        model, num_slots=2, max_len=32, prefill_chunk=8, decode_block=2)


def test_gateway_rate_and_quota_rejections(model, prompts):
    log, prev = _capture_log()
    try:
        clock = FakeClock()
        gw = ServingGateway(
            _slot_factory(model), replicas=1, clock=clock,
            registry=MetricRegistry(),
            admission=QosPolicy(classes=[
                TenantClass('premium', priority=1),
                TenantClass('bg', rate=1.0, burst=1.0),
                TenantClass('q', max_concurrent=1)]))
        ok_h = gw.submit(prompts[0], max_new_tokens=MNT, tenant='bg')
        shed = gw.submit(prompts[1], max_new_tokens=MNT, tenant='bg')
        q1 = gw.submit(prompts[2], max_new_tokens=MNT, tenant='q')
        q2 = gw.submit(prompts[3], max_new_tokens=MNT, tenant='q')
        prem = gw.submit(prompts[4], max_new_tokens=MNT, tenant='premium')

        # bucket empty at the same instant: shed, instantly final
        assert shed.done and 'rate' in str(shed.error)
        assert not shed.tokens
        # concurrency quota: q2 shed while q1 is in flight
        assert q2.done and 'quota' in str(q2.error)
        assert not ok_h.done and not q1.done and not prem.done

        gw.run()
        assert ok_h.done and ok_h.error is None and len(ok_h.tokens) == MNT
        assert q1.error is None and prem.error is None

        rep = gw.report()
        assert rep['rejected'] == 2
        # shed requests never became engine traffic
        assert rep['requests'] == 3 and rep['completed'] == 3
        reg = gw.registry
        assert reg.get('qos_rejected_total').labels('rate', 'bg') \
                  .value() == 1
        assert reg.get('qos_rejected_total').labels('quota', 'q') \
                  .value() == 1
        assert reg.get('qos_admitted_total').labels('premium').value() == 1

        # exactly one wide event per request, correct outcome + priority
        for h, outcome in ((ok_h, 'ok'), (shed, 'rejected'),
                           (q1, 'ok'), (q2, 'rejected'), (prem, 'ok')):
            evs = _events_for(log, h.id)
            assert len(evs) == 1, (h.id, evs)
            assert evs[0]['outcome'] == outcome
        assert _events_for(log, prem.id)[0]['priority'] == 1
        assert _events_for(log, shed.id)[0]['first_token_t'] is None
        # admission slots all released: the policy holds no in-flight
        for t in ('bg', 'q', 'premium'):
            assert gw.admission.inflight(t) == 0
    finally:
        _events.set_default_request_log(prev)


def test_gateway_bounded_queue_and_deadline_shed(model, prompts):
    log, prev = _capture_log()
    try:
        clock = FakeClock()
        gw = ServingGateway(
            _slot_factory(model), replicas=1, clock=clock,
            registry=MetricRegistry(),
            admission=QosPolicy(
                classes=[TenantClass('hi', priority=1),
                         TenantClass('lo', priority=0)],
                max_pending=1, max_queue_wait_s=0.5))
        gw.kill_replica(0)       # nothing routable: everything parks
        lo1 = gw.submit(prompts[0], max_new_tokens=MNT, tenant='lo')
        assert not lo1.done      # parked
        # same class at capacity: the NEWCOMER sheds (queue_full)
        lo2 = gw.submit(prompts[1], max_new_tokens=MNT, tenant='lo')
        assert lo2.done and 'queue_full' in str(lo2.error)
        # higher class at capacity: the parked low request is the victim
        hi = gw.submit(prompts[2], max_new_tokens=MNT, tenant='hi')
        assert lo1.done and 'queue_full' in str(lo1.error)
        assert not hi.done
        # parked past the deadline: shed on the next drain
        clock.t = 1.0
        assert gw.step() == 0
        assert hi.done and 'deadline' in str(hi.error)
        for h in (lo1, lo2, hi):
            evs = _events_for(log, h.id)
            assert len(evs) == 1 and evs[0]['outcome'] == 'rejected'
        assert gw.report()['rejected'] == 3
    finally:
        _events.set_default_request_log(prev)


def test_gateway_fifo_within_priority_class(model, prompts):
    """Parked work drains best-class-first, FIFO inside a class."""
    gw = ServingGateway(
        _slot_factory(model), replicas=1, registry=MetricRegistry(),
        admission=QosPolicy(classes=[TenantClass('hi', priority=1),
                                     TenantClass('lo', priority=0)]))
    gw.kill_replica(0)
    order = []
    handles = [gw.submit(prompts[i], max_new_tokens=MNT, tenant=t)
               for i, t in enumerate(('lo', 'lo', 'hi', 'lo', 'hi'))]
    with gw._lock:
        gw._add_replica_locked()     # capacity returns; next step drains
    while gw.step():
        pass
    for h in handles:
        assert h.error is None and len(h.tokens) == MNT
    # admission order onto the replica == drain order
    order = sorted(range(5), key=lambda i: handles[i]._eng_req._admit_t)
    assert order == [2, 4, 0, 1, 3]


# ---- paged-engine preemption: evict, resume, exact parity -------------


@pytest.fixture(scope='module')
def paged_preempt(model):
    """One preempt-enabled paged engine (and its wide-event log,
    installed BEFORE construction) shared by the preemption tests —
    each compile of the three jitted programs is seconds of suite
    budget. Tests mutate scheduler.max_preempts and must set it."""
    log = _events.RequestLog(capacity=256)
    prev = _events.set_default_request_log(log)
    eng = PagedContinuousBatchingEngine(
        model, num_seqs=2, max_len=32, page_size=8, prefill_chunk=8,
        decode_block=2, preempt=True)
    yield eng, log
    _events.set_default_request_log(prev)


def test_preempt_resume_exact_token_parity(paged_preempt, prompts):
    eng, log = paged_preempt
    eng.scheduler.max_preempts = None
    # uniform priorities never preempt, so the shared engine doubles as
    # its own unpreempted oracle (greedy + seeded == deterministic)
    ref = eng.generate(prompts[:3], max_new_tokens=MNT)

    reg = eng.metrics.registry
    pre0 = reg.get('qos_preempted_total').labels('lo').value()
    res0 = reg.get('qos_resumed_total').labels('lo').value()
    base = eng.scheduler.preempted
    r0 = eng.add_request(prompts[0], max_new_tokens=MNT, tenant='lo',
                         priority=0)
    r1 = eng.add_request(prompts[1], max_new_tokens=MNT, tenant='lo',
                         priority=0)
    while min(len(r0.tokens), len(r1.tokens)) < 2:
        eng.step()       # both residents mid-decode
    r2 = eng.add_request(prompts[2], max_new_tokens=MNT, tenant='hi',
                         priority=1)
    while eng.scheduler.pending:
        eng.step()

    # the high-priority arrival evicted exactly one resident, which
    # then resumed and finished
    assert eng.scheduler.preempted == base + 1
    victim = r1 if r1._preempts else r0
    assert victim._preempts == 1 and victim.outcome == 'ok'
    assert reg.get('qos_preempted_total').labels('lo').value() == pre0 + 1
    assert reg.get('qos_resumed_total').labels('lo').value() == res0 + 1
    # THE invariant: caller-visible streams identical to an
    # unpreempted run — no duplicate, no gap, no drift
    assert [r0.tokens, r1.tokens, r2.tokens] == ref
    # eviction + resume compiled nothing new
    assert set(eng.trace_counts.values()) <= {0, 1}
    # exactly one wide event each; the victim's says ok (it finished)
    for r in (r0, r1, r2):
        evs = _events_for(log, r.id)
        assert len(evs) == 1 and evs[0]['outcome'] == 'ok'
    assert _events_for(log, r2.id)[0]['priority'] == 1


def test_preempt_budget_exhausted_is_terminal(paged_preempt, prompts):
    eng, log = paged_preempt
    eng.scheduler.max_preempts = 0
    base = eng.scheduler.preempted
    r0 = eng.add_request(prompts[0], max_new_tokens=MNT, priority=0)
    r1 = eng.add_request(prompts[1], max_new_tokens=MNT, priority=0)
    while min(len(r0.tokens), len(r1.tokens)) < 2:
        eng.step()
    r2 = eng.add_request(prompts[2], max_new_tokens=MNT, priority=1)
    while eng.scheduler.pending:
        eng.step()
    eng.scheduler.max_preempts = None
    assert eng.scheduler.preempted == base + 1
    victim = r1 if r1._preempts else r0
    survivor = r0 if victim is r1 else r1
    assert victim.done and victim.outcome == 'preempted'
    assert survivor.outcome == 'ok' and r2.outcome == 'ok'
    evs = _events_for(log, victim.id)
    assert len(evs) == 1 and evs[0]['outcome'] == 'preempted'
    # its pages really came back: no resident holds a mapping (what
    # remains ref'd belongs to the prefix cache, not to requests)
    assert not eng.scheduler.resident and not eng.scheduler._nblocks


@pytest.mark.slow
def test_engine_priority_admission_fifo_within_class(model, prompts):
    eng = ContinuousBatchingEngine(model, num_slots=1, max_len=32,
                                   prefill_chunk=8, decode_block=2)
    reqs = [eng.add_request(prompts[i], max_new_tokens=4, priority=p)
            for i, p in enumerate((0, 0, 1, 0))]
    while eng.scheduler.pending:
        eng.step()
    order = sorted(range(4), key=lambda i: reqs[i]._admit_t)
    assert order == [2, 0, 1, 3]


# ---- chaos: failover + shedding compose -------------------------------


@pytest.mark.slow
def test_kill_replica_mid_burst_with_active_shedding(model, prompts):
    """A replica dies while the admission layer is actively shedding:
    failover victims are re-placed and complete (outcome 'ok', counted
    once), shed requests stay shed (outcome 'rejected', counted once) —
    the two outcomes never double-count a request."""
    log, prev = _capture_log()
    try:
        gw = ServingGateway(
            _slot_factory(model), replicas=2, registry=MetricRegistry(),
            admission=QosPolicy(classes=[
                TenantClass('premium', priority=1),
                TenantClass('bg', rate=1.0, burst=2.0)]))
        handles = []
        for i, p in enumerate(prompts):
            handles.append(gw.submit(
                p, max_new_tokens=MNT,
                tenant='premium' if i % 2 == 0 else 'bg'))
        gw.step()
        gw.kill_replica(0)
        while gw.step():
            pass
        shed = [h for h in handles if h.error is not None]
        done_ok = [h for h in handles if h.error is None]
        assert len(shed) == 2      # bg burst 2.0 admits 2 of 4
        assert all('rejected: rate' in str(h.error) for h in shed)
        assert all(h.failovers == 0 for h in shed)
        assert all(len(h.tokens) == MNT for h in done_ok)
        assert any(h.failovers for h in done_ok)   # the kill was real
        rep = gw.report()
        assert rep['rejected'] == len(shed)
        assert rep['completed'] == len(done_ok)
        # one event per request; outcomes partition the burst exactly
        outcomes = {}
        for h in handles:
            evs = _events_for(log, h.id)
            assert len(evs) == 1
            outcomes[h.id] = evs[0]['outcome']
        assert sum(1 for o in outcomes.values() if o == 'rejected') \
            == len(shed)
        assert sum(1 for o in outcomes.values() if o == 'ok') \
            == len(done_ok)
    finally:
        _events.set_default_request_log(prev)


# ---- simulator QoS ----------------------------------------------------

SIM_MODEL = ServiceModel(prefill_chunk_s=0.002, decode_burst_s=0.004)


def _mixed_spec(n=800, mean_gap=0.0005, seed=2):
    return workload.WorkloadSpec(
        requests=n, seed=seed, vocab_size=512,
        arrival={'process': 'poisson', 'mean_gap_s': mean_gap},
        lengths={'dist': 'ladder', 'lens': [8, 16, 24, 32]},
        output={'dist': 'fixed', 'len': 16},
        tenants={'mode': 'round_robin',
                 'tenants': [{'name': 'premium'}, {'name': 'bg'}]})


def _throttle():
    return QosPolicy(classes=[TenantClass('premium', priority=1),
                              TenantClass('bg', rate=120.0, burst=8.0)])


def test_sim_qos_sheds_and_protects_premium():
    tr = workload.generate(_mixed_spec())
    open_res = simulate(tr, SIM_MODEL, replicas=1)
    qos_res = simulate(tr, SIM_MODEL, replicas=1, qos=_throttle())

    summ = qos_res.summary()
    assert summ['rejected'] > 0
    assert 0.0 < summ['shed_rate'] < 1.0
    # premium never sheds (no rate class) and its tail collapses vs the
    # open door: that IS graceful degradation
    prem = np.asarray(tr.tenant_id) == tr.tenant_names.index('premium')
    open_p99 = float(np.percentile(open_res.ttft()[prem], 99))
    by_prio = qos_res.ttft_percentiles_by_priority([99])
    assert by_prio[1][99] < open_p99 * 0.75
    ok = qos_res.ok_mask()
    assert ok[prem].all()

    # shed rows join the wide schema with nothing fabricated
    evs = qos_res.to_events()
    shed_evs = [e for e in evs if e['outcome'] == 'rejected']
    assert len(shed_evs) == summ['rejected']
    assert all(e['first_token_t'] is None and e['output_tokens'] == 0
               for e in shed_evs)
    assert {e['priority'] for e in evs} == {0, 1}


def test_sim_qos_is_deterministic():
    tr = workload.generate(_mixed_spec(n=400))
    pol = _throttle()
    a = simulate(tr, SIM_MODEL, replicas=1,
                 qos=QosPolicy.from_dict(pol.to_dict()))
    b = simulate(tr, SIM_MODEL, replicas=1,
                 qos=QosPolicy.from_dict(pol.to_dict()))
    assert np.array_equal(a.outcome, b.outcome)
    assert np.array_equal(a.first, b.first)
    assert np.array_equal(a.finish, b.finish)


def test_sweep_qos_slo_verdicts():
    tr = workload.generate(_mixed_spec())
    sweep = sweep_qos(tr, SIM_MODEL,
                      [('open', {}), ('throttled', _throttle())],
                      replicas=1, slo_ttft_s=1.0)
    points = {p['policy']: p for p in sweep['points']}
    assert points['open']['shed_rate'] == 0.0
    assert points['throttled']['rejected'] > 0
    assert not points['open']['meets_slo']
    assert points['throttled']['meets_slo']


# ---- the offline gate CLI ---------------------------------------------


def test_capacity_report_qos_policy_protocol(tmp_path):
    spec = {'requests': 300, 'seed': 2, 'vocab_size': 512,
            'arrival': {'process': 'poisson', 'mean_gap_s': 0.0005},
            'lengths': {'dist': 'ladder', 'lens': [8, 16, 24, 32]},
            'output': {'dist': 'fixed', 'len': 16},
            'tenants': {'mode': 'round_robin',
                        'tenants': [{'name': 'premium'},
                                    {'name': 'bg'}]}}
    pol = dict(_throttle().to_dict(), name='throttled')

    def run(*args):
        return subprocess.run(
            [sys.executable, 'tools/capacity_report.py'] + list(args),
            capture_output=True, text=True)

    ok = run('--spec-inline', json.dumps(spec),
             '--qos-policy', json.dumps(pol),
             '--qos-policy', '{"name": "open", "classes": []}',
             '--replicas', '1', '--slo-ms', '1000')
    assert ok.returncode == 0, ok.stdout + ok.stderr
    out = json.loads(ok.stdout.splitlines()[-1])
    points = {p['policy']: p for p in out['qos_sweep']['points']}
    assert points['throttled']['rejected'] > 0
    assert points['open']['shed_rate'] == 0.0
    assert 'by_priority' in points['throttled']

    nothing = run('--qos-policy', json.dumps(pol))
    assert nothing.returncode == 2    # no trace/spec to sweep over
