"""Elastic membership + fault-injection (SURVEY §5.3; reference
fleet/elastic.py ElasticManager + launcher relaunch-on-scale-event).

Drives the file-backed membership protocol directly: heartbeats define
the member set, stale beats drop out, membership changes trip the
relaunch trigger, and a crashing worker under the launch() supervision
loop gets relaunched and completes on its second life.
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                  ELASTIC_EXIT_CODE)


def test_membership_join_leave(tmp_path):
    srv = 'file://' + str(tmp_path)
    a = ElasticManager(srv, 'job1', np=2, host='hostA', ttl=0.5)
    b = ElasticManager(srv, 'job1', np=2, host='hostB', ttl=0.5)
    a.register()
    b.register()
    try:
        assert set(a.hosts()) == {'hostA', 'hostB'}
        a.membership_changed()          # prime the view
        assert not a.membership_changed()
        # B dies: stop its heartbeat, let the lease lapse
        b.unregister()
        deadline = time.time() + 5
        while time.time() < deadline and 'hostB' in a.hosts():
            time.sleep(0.1)
        assert set(a.hosts()) == {'hostA'}
        assert a.membership_changed()   # scale event visible
    finally:
        a.unregister()


def test_stale_heartbeat_expires(tmp_path):
    srv = 'file://' + str(tmp_path)
    a = ElasticManager(srv, 'job2', np=1, host='only', ttl=0.3)
    a.register()
    try:
        assert a.hosts() == ['only']
    finally:
        a.unregister()
    deadline = time.time() + 5
    while time.time() < deadline and a.hosts():
        time.sleep(0.1)
    assert a.hosts() == []


def test_reregister_after_unregister_keeps_lease_fresh(tmp_path):
    """A node that leaves and rejoins must get a LIVE heartbeat thread
    again — if register() saw the dead thread and declined to arm a new
    one, the lease would silently lapse after ttl."""
    srv = 'file://' + str(tmp_path)
    m = ElasticManager(srv, 'rejoin', np=1, host='only', ttl=0.6)
    m.register()
    first = m._hb_thread
    m.unregister()
    assert not first.is_alive()
    assert m.hosts() == []

    m.register()
    try:
        assert m._hb_thread is not first
        assert m._hb_thread.is_alive()
        # outlive the ttl: only a working heartbeat thread keeps the
        # lease fresh past this point
        time.sleep(m.ttl * 2)
        assert m.hosts() == ['only']
    finally:
        m.unregister()
    assert m.hosts() == []


class _StuckStop:
    """Stop-event stand-in for the retirement race: the flag reads as set
    but the loop thread has not exited yet (it is still inside its
    ttl/3 wait). set() releases the thread, as the real Event would."""

    def __init__(self):
        self._release = threading.Event()

    def is_set(self):
        return True

    def set(self):
        self._release.set()

    def wait(self, timeout=None):
        return self._release.wait(timeout)


def test_register_retires_stopping_heartbeat_thread(tmp_path):
    """register() must stop AND join a still-alive thread whose stop flag
    is set before arming a fresh one — otherwise the old loop's last
    heartbeat can land after the new thread's, or two loops beat at
    once."""
    srv = 'file://' + str(tmp_path)
    m = ElasticManager(srv, 'retire', np=1, host='only', ttl=0.5)
    m.register()
    # retire the real thread quietly, then install the stuck stand-in
    m._hb_stop.set()
    m._hb_thread.join()
    stuck = _StuckStop()
    blocker = threading.Thread(target=stuck.wait, daemon=True)
    blocker.start()
    m._hb_stop = stuck
    m._hb_thread = blocker

    m.register()
    try:
        blocker.join(timeout=5)
        assert not blocker.is_alive()       # retired: set + joined
        assert m._hb_thread is not blocker  # fresh thread armed...
        assert m._hb_thread.is_alive()
        assert not m._hb_stop.is_set()      # ...with a clear stop flag
        time.sleep(m.ttl * 1.5)
        assert m.hosts() == ['only']        # and the lease stays fresh
    finally:
        m.unregister()


def test_crash_once_worker_is_relaunched(tmp_path):
    """Fault injection through the real launcher supervision loop: the
    worker exits with ELASTIC_EXIT_CODE on its first life (simulated
    fault), the supervisor relaunches, and the second life succeeds."""
    marker = tmp_path / 'lives.txt'
    script = tmp_path / 'worker.py'
    script.write_text(
        "import os, sys\n"
        "m = %r\n"
        "lives = open(m).read().count('x') if os.path.exists(m) else 0\n"
        "open(m, 'a').write('x')\n"
        "if lives == 0:\n"
        "    sys.exit(%d)\n"           # first life: simulated fault
        "print('WORKER_OK rank', os.environ.get('PADDLE_TRAINER_ID'))\n"
        % (str(marker), ELASTIC_EXIT_CODE))

    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PALLAS_AXON_POOL_IPS'] = ''
    proc = subprocess.run(
        [sys.executable, '-m', 'paddle_tpu.distributed.launch.main',
         '--nproc_per_node', '1',
         '--elastic_server', 'file://' + str(tmp_path / 'kv'),
         '--job_id', 'crashjob', str(script)],
        capture_output=True, text=True, env=env, timeout=180,
        cwd='/root/repo')
    lives = marker.read_text().count('x')
    assert lives == 2, (lives, proc.stdout[-500:], proc.stderr[-500:])
    assert proc.returncode == 0, proc.stderr[-500:]
