"""OpTest harness (reference: python/paddle/fluid/tests/unittests/op_test.py:270).

Same contract, TPU-native mechanics: `check_output` compares the op against
a NumPy reference; `check_grad` compares the tape's analytic grads against
numeric finite differences (the reference's get_numeric_gradient,
op_test.py:110) — plus a jax.jit consistency check standing in for the
reference's dygraph-vs-static check.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.framework.core import Tensor


def numeric_grad(fn_np_scalar, x, delta=1e-3):
    """Central finite differences of a scalar-valued numpy function."""
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + delta
        hi = fn_np_scalar(x)
        flat[i] = orig - delta
        lo = fn_np_scalar(x)
        flat[i] = orig
        gf[i] = (hi - lo) / (2 * delta)
    return g


class OpTest:
    """Subclass contract: set self.fn (paddle op over Tensors), self.inputs
    (dict name -> ndarray), self.ref (numpy reference returning array or
    tuple), optional self.attrs."""

    fn = None
    ref = None
    inputs = None
    attrs = None
    atol = 1e-5
    rtol = 1e-5
    grad_atol = 1e-2
    grad_rtol = 1e-2

    def _run(self, stop_gradient=True):
        attrs = self.attrs or {}
        tensors = {k: paddle.to_tensor(v, stop_gradient=stop_gradient)
                   for k, v in self.inputs.items()}
        out = type(self).fn(*tensors.values(), **attrs)
        return tensors, out

    def check_output(self):
        _, out = self._run()
        ref_out = type(self).ref(*[np.asarray(v) for v in
                                   self.inputs.values()],
                                 **(self.attrs or {}))
        outs = out if isinstance(out, (list, tuple)) else [out]
        refs = ref_out if isinstance(ref_out, (list, tuple)) else [ref_out]
        for o, r in zip(outs, refs):
            np.testing.assert_allclose(
                np.asarray(o.numpy(), np.float64),
                np.asarray(r, np.float64), atol=self.atol, rtol=self.rtol,
                err_msg='output mismatch for %s' % type(self).__name__)

    def check_grad(self, inputs_to_check=None, delta=1e-3):
        attrs = self.attrs or {}
        names = inputs_to_check or [
            k for k, v in self.inputs.items()
            if np.issubdtype(np.asarray(v).dtype, np.floating)]
        tensors, out = self._run(stop_gradient=False)
        outs = out if isinstance(out, (list, tuple)) else [out]
        loss = outs[0].sum() if outs[0].size > 1 else outs[0]
        loss.backward()
        for name in names:
            analytic = tensors[name].grad.numpy().astype(np.float64)

            def scalar_fn(x, name=name):
                vals = {k: np.asarray(v) for k, v in self.inputs.items()}
                vals[name] = x
                # float inputs ride at f32; integer inputs (indices,
                # labels) must keep their dtype or gather-like ops break
                ts = {k: paddle.to_tensor(
                          v.astype(np.float32)
                          if np.issubdtype(v.dtype, np.floating) else v)
                      for k, v in vals.items()}
                o = type(self).fn(*ts.values(), **attrs)
                o0 = o[0] if isinstance(o, (list, tuple)) else o
                return float(np.sum(o0.numpy(), dtype=np.float64))

            numeric = numeric_grad(scalar_fn, self.inputs[name], delta)
            np.testing.assert_allclose(
                analytic, numeric, atol=self.grad_atol, rtol=self.grad_rtol,
                err_msg='grad mismatch for %s input %s'
                        % (type(self).__name__, name))
