"""nn layer tests (reference pattern: unittests/test_layers.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_linear():
    layer = nn.Linear(4, 3)
    x = paddle.randn([2, 4])
    out = layer(x)
    assert out.shape == [2, 3]
    ref = x.numpy() @ layer.weight.numpy() + layer.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


def test_conv2d_shapes():
    layer = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    x = paddle.randn([2, 3, 16, 16])
    assert layer(x).shape == [2, 8, 8, 8]


def test_conv2d_matches_manual():
    import jax.numpy as jnp
    conv = nn.Conv2D(1, 1, 2, bias_attr=False)
    x = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3))
    w = conv.weight.numpy()
    out = conv(x).numpy()
    ref = np.zeros((1, 1, 2, 2), np.float32)
    xv = x.numpy()[0, 0]
    for i in range(2):
        for j in range(2):
            ref[0, 0, i, j] = (xv[i:i+2, j:j+2] * w[0, 0]).sum()
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_conv_transpose_shape():
    layer = nn.Conv2DTranspose(4, 2, 3, stride=2, padding=1)
    x = paddle.randn([1, 4, 8, 8])
    assert layer(x).shape == [1, 2, 15, 15]


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(3)
    x = paddle.randn([4, 3, 5, 5]) * 3 + 1
    bn.train()
    y = bn(x)
    m = y.numpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, np.zeros(3), atol=1e-5)
    # running stats moved toward batch stats
    assert not np.allclose(bn._mean.numpy(), np.zeros(3))
    bn.eval()
    y2 = bn(x)
    assert y2.shape == [4, 3, 5, 5]


def test_layernorm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([2, 8]) * 5
    y = ln(x).numpy()
    np.testing.assert_allclose(y.mean(-1), np.zeros(2), atol=1e-5)
    np.testing.assert_allclose(y.std(-1), np.ones(2), atol=1e-2)


def test_dropout_modes():
    d = nn.Dropout(0.5)
    x = paddle.ones([100, 100])
    d.train()
    y = d(x)
    frac_zero = float((y.numpy() == 0).mean())
    assert 0.3 < frac_zero < 0.7
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_embedding():
    emb = nn.Embedding(10, 4)
    idx = paddle.to_tensor(np.asarray([[1, 2], [3, 4]], np.int64))
    out = emb(idx)
    assert out.shape == [2, 2, 4]
    np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1])


def test_sequential_and_layerlist():
    seq = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.randn([3, 4])
    assert seq(x).shape == [3, 2]
    assert len(seq) == 3
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll) == 3
    assert len(list(ll)) == 3


def test_state_dict_roundtrip():
    m1 = nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 2))
    m2 = nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 2))
    m2.set_state_dict(m1.state_dict())
    x = paddle.randn([2, 4])
    np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_named_parameters_and_buffers():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(2, 2)
            self.bn = nn.BatchNorm1D(2)

        def forward(self, x):
            return self.bn(self.fc(x))

    net = Net()
    names = dict(net.named_parameters())
    assert 'fc.weight' in names and 'bn.weight' in names
    bufs = dict(net.named_buffers())
    assert 'bn._mean' in bufs


def test_parameter_training_via_layer():
    layer = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=layer.parameters())
    x = paddle.randn([16, 4])
    # realizable target: a fixed random linear map
    w_true = paddle.randn([4, 1])
    target = paddle.matmul(x, w_true)
    for _ in range(80):
        loss = ((layer(x) - target) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss.numpy()) < 0.05


def test_rnn_lstm_gru():
    for cls, states in [(nn.SimpleRNN, 1), (nn.LSTM, 2), (nn.GRU, 1)]:
        rnn = cls(input_size=4, hidden_size=8, num_layers=2)
        x = paddle.randn([3, 6, 4])  # batch, time, feat
        out, st = rnn(x)
        assert out.shape == [3, 6, 8]
        if states == 2:
            h, c = st
            assert h.shape == [2, 3, 8] and c.shape == [2, 3, 8]


def test_lstm_backward():
    rnn = nn.LSTM(4, 8)
    x = paddle.randn([2, 5, 4])
    x.stop_gradient = False
    out, _ = rnn(x)
    out.sum().backward()
    assert x.grad is not None
    assert rnn._cells[0].weight_ih.grad is not None


def test_bidirectional_lstm():
    rnn = nn.LSTM(4, 8, direction='bidirect')
    x = paddle.randn([2, 5, 4])
    out, (h, c) = rnn(x)
    assert out.shape == [2, 5, 16]


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 6, 16])
    out = mha(x, x, x)
    assert out.shape == [2, 6, 16]


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 6, 16])
    out = enc(x)
    assert out.shape == [2, 6, 16]
    # layers are independent copies
    p = list(enc.layers[0].named_parameters())
    q = list(enc.layers[1].named_parameters())
    assert p[0][1] is not q[0][1]


def test_full_transformer():
    model = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=32)
    src = paddle.randn([2, 5, 16])
    tgt = paddle.randn([2, 7, 16])
    out = model(src, tgt)
    assert out.shape == [2, 7, 16]


def test_pool_layers():
    x = paddle.randn([2, 3, 8, 8])
    assert nn.MaxPool2D(2, 2)(x).shape == [2, 3, 4, 4]
    assert nn.AvgPool2D(2, 2)(x).shape == [2, 3, 4, 4]
    assert nn.AdaptiveAvgPool2D(1)(x).shape == [2, 3, 1, 1]
    assert nn.AdaptiveAvgPool2D(3)(x).shape == [2, 3, 3, 3]


def test_losses():
    logits = paddle.randn([8, 5])
    labels = paddle.to_tensor(np.random.RandomState(0).randint(0, 5, 8))
    ce = nn.CrossEntropyLoss()(logits, labels)
    assert ce.shape == []
    ref = -np.log(np.exp(logits.numpy() -
                         logits.numpy().max(-1, keepdims=True)) /
                  np.exp(logits.numpy() -
                         logits.numpy().max(-1, keepdims=True)).sum(
                             -1, keepdims=True))
    picked = ref[np.arange(8), labels.numpy()]
    np.testing.assert_allclose(float(ce.numpy()), picked.mean(), rtol=1e-5)

    a, b = paddle.randn([4, 3]), paddle.randn([4, 3])
    np.testing.assert_allclose(nn.MSELoss()(a, b).numpy(),
                               ((a.numpy() - b.numpy()) ** 2).mean(),
                               rtol=1e-5)
    np.testing.assert_allclose(nn.L1Loss()(a, b).numpy(),
                               np.abs(a.numpy() - b.numpy()).mean(),
                               rtol=1e-5)


def test_grad_clip():
    layer = nn.Linear(4, 4)
    clip = nn.ClipGradByGlobalNorm(0.001)
    opt = paddle.optimizer.SGD(learning_rate=1.0,
                               parameters=layer.parameters(),
                               grad_clip=clip)
    x = paddle.randn([8, 4]) * 100
    loss = (layer(x) ** 2).sum()
    loss.backward()
    before = {id(p): p.numpy().copy() for p in layer.parameters()}
    opt.step()
    total_delta = sum(np.abs(p.numpy() - before[id(p)]).sum()
                      for p in layer.parameters())
    assert total_delta < 0.01  # clipped to tiny global norm


def test_weight_norm():
    from paddle_tpu.nn import weight_norm, remove_weight_norm
    layer = nn.Linear(4, 3)
    w0 = layer.weight.numpy().copy()
    weight_norm(layer, 'weight', dim=0)
    assert 'weight_g' in dict(layer.named_parameters())
    x = paddle.randn([2, 4])
    out = layer(x)
    np.testing.assert_allclose(out.numpy(), x.numpy() @ w0 + layer.bias.numpy(),
                               rtol=1e-4)
    remove_weight_norm(layer)
    assert 'weight_g' not in dict(layer.named_parameters())


def test_functional_extension_surface():
    """sequence_mask / diag_embed / affine_grid / grid_sample /
    hsigmoid_loss (reference nn/functional extension+vision ops; the
    last 7 missing names of the functional surface)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    m = F.sequence_mask(paddle.to_tensor(np.asarray([1, 3, 2])), maxlen=4)
    np.testing.assert_array_equal(
        m.numpy(), [[1, 0, 0, 0], [1, 1, 1, 0], [1, 1, 0, 0]])

    d = F.diag_embed(paddle.to_tensor(
        np.asarray([[1., 2.], [3., 4.]], np.float32)))
    np.testing.assert_allclose(d.numpy()[1], [[3, 0], [0, 4]])

    # identity affine theta reproduces the image through grid_sample
    img = paddle.to_tensor(
        np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    theta = paddle.to_tensor(
        np.asarray([[[1., 0, 0], [0, 1., 0]]], np.float32))
    grid = F.affine_grid(theta, [1, 1, 4, 4])
    out = F.grid_sample(img, grid)
    np.testing.assert_allclose(out.numpy(), img.numpy(), atol=1e-4)

    # hsigmoid trains: loss decreases under SGD on a separable problem
    paddle.seed(0)
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(32, 8).astype(np.float32))
    lab = paddle.to_tensor((rng.rand(32) * 4).astype(np.int64))
    from paddle_tpu.framework.core import Parameter
    w = Parameter(rng.randn(7, 8).astype(np.float32) * 0.1)
    opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[w])
    losses = []
    for _ in range(15):
        loss = F.hsigmoid_loss(x, lab, 4, w)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()[0]))
    assert losses[-1] < losses[0]

    assert F.elu_ is not None and F.softmax_ is not None


def test_conv_transpose_output_size():
    """output_size selects among the stride-ambiguous output shapes
    (reference conv2d_transpose semantics); unreachable sizes raise."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(1, 2, 4, 4).astype(np.float32))
    w = paddle.to_tensor(np.random.RandomState(1)
                         .randn(2, 3, 3, 3).astype(np.float32))
    assert tuple(F.conv2d_transpose(x, w, stride=2).shape) == (1, 3, 9, 9)
    assert tuple(F.conv2d_transpose(x, w, stride=2,
                                    output_size=10).shape) == (1, 3, 10, 10)
    # the extra row/col is zero-padding at the end: the common region
    # must agree with the default-output result
    a = F.conv2d_transpose(x, w, stride=2).numpy()
    b = F.conv2d_transpose(x, w, stride=2, output_size=10).numpy()
    np.testing.assert_allclose(b[..., :9, :9], a, rtol=1e-5)
    import pytest
    with pytest.raises(ValueError, match='unreachable'):
        F.conv2d_transpose(x, w, stride=2, output_size=12)
    lyr = paddle.nn.Conv2DTranspose(2, 3, 3, stride=2)
    assert tuple(lyr(x, output_size=[10, 10]).shape) == (1, 3, 10, 10)


def test_rnn_sequence_length_masking():
    """sequence_length semantics (reference rnn.py): steps past a row's
    length emit zeros and freeze the state; reverse direction reverses
    only the valid prefix."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(0)
    b, t, din, h = 3, 6, 4, 5
    lstm = nn.LSTM(din, h)
    x = np.random.RandomState(0).randn(b, t, din).astype(np.float32)
    lens = np.asarray([6, 3, 1], np.int32)
    out, (hn, cn) = lstm(paddle.to_tensor(x),
                         sequence_length=paddle.to_tensor(lens))
    out = out.numpy()
    # past-length steps emit zeros
    assert np.abs(out[1, 3:]).max() == 0 and np.abs(out[2, 1:]).max() == 0
    # each row matches running its truncated prefix alone
    for i, n in enumerate(lens):
        o_i, (h_i, c_i) = lstm(paddle.to_tensor(x[i:i + 1, :n]))
        np.testing.assert_allclose(out[i, :n], o_i.numpy()[0], rtol=1e-5,
                                   atol=1e-6)
        # final state froze at the last valid step
        np.testing.assert_allclose(hn.numpy()[0, i], h_i.numpy()[0, 0],
                                   rtol=1e-5, atol=1e-6)

    # bidirectional: the backward half at t=0 equals running the REVERSED
    # valid prefix, i.e. final-state of reverse pass over row prefix
    bi = nn.LSTM(din, h, direction='bidirect')
    out_bi, _ = bi(paddle.to_tensor(x),
                   sequence_length=paddle.to_tensor(lens))
    out_bi = out_bi.numpy()
    for i, n in enumerate(lens):
        o_i, _ = bi(paddle.to_tensor(x[i:i + 1, :n]))
        np.testing.assert_allclose(out_bi[i, :n], o_i.numpy()[0],
                                   rtol=1e-5, atol=1e-6)
        if n < t:
            assert np.abs(out_bi[i, n:]).max() == 0


def test_dropped_param_fixes():
    """Batch of parameters that were accepted but silently ignored
    (found by AST sweep): instance_norm running stats, interpolate
    align_mode, avg_pool divisor_override, matrix_rank hermitian,
    lu pivot guard, fill_diagonal_ wrap, ctc norm_by_times,
    uniform_ seed."""
    import numpy as np
    import pytest
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    # instance_norm with provided stats (use_input_stats=False)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 3, 4, 4).astype(np.float32))
    rm = paddle.to_tensor(np.asarray([1.0, 2.0, 3.0], np.float32))
    rv = paddle.to_tensor(np.asarray([4.0, 4.0, 4.0], np.float32))
    out = F.instance_norm(x, running_mean=rm, running_var=rv,
                          use_input_stats=False, eps=0.0).numpy()
    want = (x.numpy() - np.asarray([1, 2, 3], np.float32)
            .reshape(1, 3, 1, 1)) / 2.0
    np.testing.assert_allclose(out, want, rtol=1e-5)
    with pytest.raises(ValueError, match='use_input_stats'):
        F.instance_norm(x, use_input_stats=False)

    # interpolate align_mode=1 (asymmetric) differs from half-pixel
    img = paddle.to_tensor(np.arange(4, dtype=np.float32)
                           .reshape(1, 1, 1, 4))
    up0 = F.interpolate(img, size=[1, 8], mode='bilinear',
                        align_mode=0).numpy()
    up1 = F.interpolate(img, size=[1, 8], mode='bilinear',
                        align_mode=1).numpy()
    assert not np.allclose(up0, up1)
    # align_mode=1: src = dst * 0.5 exactly -> first two outputs 0, 0.5
    np.testing.assert_allclose(up1[0, 0, 0, :3], [0.0, 0.5, 1.0],
                               atol=1e-6)

    # avg_pool divisor_override
    a = paddle.to_tensor(np.ones((1, 1, 4, 4), np.float32))
    o = F.avg_pool2d(a, 2, 2, divisor_override=8).numpy()
    np.testing.assert_allclose(o, 0.5)  # sum 4 / 8

    # matrix_rank hermitian
    m = np.diag([5.0, 3.0, 0.0]).astype(np.float32)
    assert int(paddle.linalg.matrix_rank(
        paddle.to_tensor(m), hermitian=True).numpy()) == 2

    with pytest.raises(NotImplementedError):
        paddle.linalg.lu(paddle.to_tensor(np.eye(3, dtype=np.float32)),
                         pivot=False)

    # fill_diagonal_ wrap on a tall matrix
    tall = paddle.to_tensor(np.zeros((7, 3), np.float32))
    paddle.tensor.manipulation.fill_diagonal_(tall, 1.0, wrap=True)
    got = tall.numpy()
    assert got[0, 0] == got[1, 1] == got[2, 2] == 1.0
    assert got[4, 0] == got[5, 1] == got[6, 2] == 1.0
    assert got[3].sum() == 0  # the gap row

    # uniform_ with a fixed seed is reproducible
    t1 = paddle.tensor.random.uniform_(
        paddle.to_tensor(np.zeros(8, np.float32)), seed=5).numpy()
    t2 = paddle.tensor.random.uniform_(
        paddle.to_tensor(np.zeros(8, np.float32)), seed=5).numpy()
    np.testing.assert_array_equal(t1, t2)

    # ctc norm_by_times: loss VALUE unchanged, gradients scaled by 1/T
    # (reference warpctc normalizes only the gradients)
    T, B, C = 4, 1, 3
    lp_np = np.random.RandomState(1).randn(T, B, C).astype(np.float32)
    lab = paddle.to_tensor(np.asarray([[1, 2]], np.int64))
    il = paddle.to_tensor(np.asarray([4], np.int64))
    ll = paddle.to_tensor(np.asarray([2], np.int64))

    lp1 = paddle.to_tensor(lp_np, stop_gradient=False)
    base = F.ctc_loss(lp1, lab, il, ll, reduction='sum')
    base.backward()
    lp2 = paddle.to_tensor(lp_np, stop_gradient=False)
    normed = F.ctc_loss(lp2, lab, il, ll, reduction='sum',
                        norm_by_times=True)
    normed.backward()
    np.testing.assert_allclose(float(normed.numpy()), float(base.numpy()),
                               rtol=1e-6)
    np.testing.assert_allclose(lp2.grad.numpy(), lp1.grad.numpy() / 4.0,
                               rtol=1e-5, atol=1e-7)

    # divisor_override must be positive
    with pytest.raises(ValueError, match='divisor_override'):
        F.avg_pool2d(a, 2, 2, divisor_override=0)


def test_matrix_rank_batched_and_rotate_expand():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision.transforms import functional as TF

    A = np.stack([np.diag([5.0, 3.0, 0.0]), np.eye(3)]).astype(np.float32)
    np.testing.assert_array_equal(
        paddle.linalg.matrix_rank(paddle.to_tensor(A),
                                  hermitian=True).numpy(), [2, 3])

    img = np.ones((10, 20, 3), np.uint8) * 200
    assert TF.rotate(img, 90, expand=True).shape == (20, 10, 3)
    assert TF.rotate(img, 90, expand=False).shape == (10, 20, 3)
    # nearest vs bilinear resize actually differ
    grad_img = np.tile(np.arange(20, dtype=np.uint8)[None, :, None] * 12,
                       (10, 1, 3))
    near = TF.resize(grad_img, (5, 10), interpolation='nearest')
    bil = TF.resize(grad_img, (5, 10), interpolation='bilinear')
    assert not np.array_equal(near, bil)


def test_transformer_decoder_incremental_cache_matches_full():
    """Step-by-step decoding with gen_cache (growing self-attn cache +
    static cross-attn cache) must equal the full-sequence forward
    (reference StaticCache/Cache semantics)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(0)
    d, heads, tl, ml = 16, 4, 5, 7
    layer = nn.TransformerDecoderLayer(d, heads, 32, dropout=0.0)
    dec = nn.TransformerDecoder(layer, 2)
    dec.eval()
    mem = paddle.to_tensor(np.random.RandomState(0)
                           .randn(2, ml, d).astype(np.float32))
    tgt = paddle.to_tensor(np.random.RandomState(1)
                           .randn(2, tl, d).astype(np.float32))
    causal = np.triu(np.full((tl, tl), -1e9, np.float32), 1)
    full = dec(tgt, mem, tgt_mask=paddle.to_tensor(causal)).numpy()

    cache = dec.gen_cache(mem)
    outs = []
    for t in range(tl):
        step_in = paddle.to_tensor(tgt.numpy()[:, t:t + 1])
        out, cache = dec(step_in, mem, cache=cache)
        outs.append(out.numpy())
    inc = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(inc, full, rtol=1e-4, atol=1e-5)


def test_layer_to_device_and_dtype():
    """Layer.to moves params by string, Place, or jax.Device (shared
    resolver with set_device) and casts float dtypes."""
    import jax
    import numpy as np
    import paddle_tpu as paddle

    net = paddle.nn.Linear(4, 3)
    net.to(device='cpu')
    assert list(net.weight._data.devices())[0].platform == 'cpu'
    net.to(device=paddle.CPUPlace())
    assert list(net.weight._data.devices())[0].platform == 'cpu'
    # explicit index: cpu:1 exists under the 8-device test mesh
    net.to(device='cpu:1')
    assert list(net.weight._data.devices())[0].id == 1
    net.to(dtype='bfloat16')
    assert str(net.weight._data.dtype) == 'bfloat16'
    out = net(paddle.to_tensor(np.zeros((2, 4), np.float32)))
    assert tuple(out.shape) == (2, 3)


def test_sparse_attention_masks():
    """key_padding_mask / attn_mask restrict the CSR-allowed positions
    (0 = masked, reference sparse_attention contract)."""
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(0)
    B, H, N, D = 1, 1, 4, 8
    q = paddle.to_tensor(rng.randn(B, H, N, D).astype(np.float32))
    k = paddle.to_tensor(rng.randn(B, H, N, D).astype(np.float32))
    v = paddle.to_tensor(rng.randn(B, H, N, D).astype(np.float32))
    # full CSR: every row attends every column
    offs = paddle.to_tensor(np.broadcast_to(
        np.arange(0, (N + 1) * N, N, dtype=np.int32), (B, H, N + 1)).copy())
    cols = paddle.to_tensor(np.broadcast_to(
        np.tile(np.arange(N, dtype=np.int32), N), (B, H, N * N)).copy())

    base = F.sparse_attention(q, k, v, offs, cols).numpy()
    # mask out the last key everywhere: result must equal dense attention
    # computed over the first N-1 keys
    kpm = paddle.to_tensor(np.asarray([[1, 1, 1, 0]], np.float32))
    got = F.sparse_attention(q, k, v, offs, cols,
                             key_padding_mask=kpm).numpy()
    s = (q.numpy() @ np.swapaxes(k.numpy(), -1, -2)) / np.sqrt(D)
    s = s[..., :N - 1]
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    want = p @ v.numpy()[..., :N - 1, :]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    assert not np.allclose(got, base)

    am = paddle.to_tensor(np.tril(np.ones((N, N), np.float32)))
    causal = F.sparse_attention(q, k, v, offs, cols, attn_mask=am).numpy()
    assert not np.allclose(causal, base)
    # first row attends only itself -> equals v[0]
    np.testing.assert_allclose(causal[0, 0, 0], v.numpy()[0, 0, 0],
                               rtol=1e-5)


def test_lstm_gru_match_numpy_recurrence():
    """Independent numpy gate-math reference (paddle gate order i,f,g,o
    for LSTM; r,z,n with torch/paddle candidate convention for GRU) —
    the recurrence itself, not just self-consistency."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn

    def sig(x):
        return 1.0 / (1.0 + np.exp(-x))

    paddle.seed(3)
    b, t, din, h = 2, 5, 3, 4
    x = np.random.RandomState(1).randn(b, t, din).astype(np.float32)

    lstm = nn.LSTM(din, h)
    out, (hn, cn) = lstm(paddle.to_tensor(x))
    params = dict(lstm.named_parameters())
    wi = params['_cells.0.weight_ih'].numpy()
    wh = params['_cells.0.weight_hh'].numpy()
    bi = params['_cells.0.bias_ih'].numpy()
    bh = params['_cells.0.bias_hh'].numpy()
    hh = np.zeros((b, h), np.float32)
    cc = np.zeros((b, h), np.float32)
    ref = []
    for s in range(t):
        gates = x[:, s] @ wi.T + bi + hh @ wh.T + bh
        i, f, g, o = np.split(gates, 4, axis=-1)
        i, f, o = sig(i), sig(f), sig(o)
        cc = f * cc + i * np.tanh(g)
        hh = o * np.tanh(cc)
        ref.append(hh.copy())
    ref = np.stack(ref, axis=1)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hn.numpy()[0], hh, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(cn.numpy()[0], cc, rtol=1e-5, atol=1e-5)

    gru = nn.GRU(din, h)
    gout, ghn = gru(paddle.to_tensor(x))
    params = dict(gru.named_parameters())
    wi = params['_cells.0.weight_ih'].numpy()
    wh = params['_cells.0.weight_hh'].numpy()
    bi = params['_cells.0.bias_ih'].numpy()
    bh = params['_cells.0.bias_hh'].numpy()
    hh = np.zeros((b, h), np.float32)
    ref = []
    for s in range(t):
        gi = x[:, s] @ wi.T + bi
        gh = hh @ wh.T + bh
        ir, iz, inn = np.split(gi, 3, axis=-1)
        hr, hz, hn_ = np.split(gh, 3, axis=-1)
        r = sig(ir + hr)
        z = sig(iz + hz)
        n = np.tanh(inn + r * hn_)
        hh = (1 - z) * n + z * hh
        ref.append(hh.copy())
    ref = np.stack(ref, axis=1)
    np.testing.assert_allclose(gout.numpy(), ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ghn.numpy()[0], hh, rtol=1e-5, atol=1e-5)
