"""Failure-path coverage: the fault-tolerant RPC layer
(distributed/resilience.py) driven by the chaos harness
(testing/chaos.py), and the hardened checkpoint stack (atomic writes +
CRC32 manifests + newest-valid fallback).

The acceptance scenarios from the reference stack's failure model:
- a killed graph/PS server mid-call surfaces a clean retryable error,
  bounded by the deadline (no hang);
- an idempotent op retried across a server restart returns the correct
  result;
- a truncated latest checkpoint is detected via its manifest and restore
  falls back to the previous valid snapshot.
"""
import os
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed import resilience
from paddle_tpu.distributed.resilience import (
    CircuitBreaker, CircuitOpenError, Deadline, DeadlineExceeded,
    ResilientChannel, RetryPolicy, RetryableError)
from paddle_tpu.distributed.graph_service import GraphPyClient, GraphPyServer
from paddle_tpu.distributed.ps.embedding_service import (EmbeddingClient,
                                                         EmbeddingServer)
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.framework import io_save
from paddle_tpu.incubate.auto_checkpoint import TrainEpochRange
from paddle_tpu import monitor
from paddle_tpu.testing import chaos

# fast-failing policy for tests: whole retry ladder < ~0.5 s
FAST = dict(retry_policy=RetryPolicy(max_attempts=4, base_delay=0.02,
                                     max_delay=0.1),
            call_timeout=2.0)


@pytest.fixture(autouse=True)
def no_leaked_faults():
    yield
    assert chaos.active_faults() == 0, 'a chaos injector leaked'


# -- unit: policy / deadline / breaker --------------------------------------

def test_retry_policy_backoff_and_classification():
    p = RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=0.4,
                    multiplier=2.0, jitter=0.0)
    assert [p.backoff(a) for a in (1, 2, 3, 4)] == [0.1, 0.2, 0.4, 0.4]
    jittered = RetryPolicy(base_delay=0.1, jitter=0.5).backoff(1)
    assert 0.1 <= jittered <= 0.15 + 1e-9
    assert p.is_retryable(ConnectionResetError())
    assert p.is_retryable(TimeoutError())
    assert p.is_retryable(ConnectionRefusedError())
    assert not p.is_retryable(ValueError('app bug'))
    assert not p.is_retryable(RuntimeError('server-side error reply'))


def test_deadline_clamps_and_expires():
    dl = Deadline.after(0.2)
    assert 0.0 < dl.remaining() <= 0.2
    assert dl.clamp(10.0) <= 0.2
    assert dl.clamp(0.05) <= 0.05
    time.sleep(0.25)
    assert dl.expired()
    with pytest.raises(DeadlineExceeded):
        dl.clamp(1.0)


def test_circuit_breaker_half_open_cycle():
    br = CircuitBreaker(failure_threshold=2, reset_timeout=0.15)
    assert br.state == CircuitBreaker.CLOSED
    assert br.allow()
    br.record_failure()
    assert br.allow()
    br.record_failure()                      # hits the threshold
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()
    time.sleep(0.2)                          # reset window elapses
    assert br.state == CircuitBreaker.HALF_OPEN
    assert br.allow()                        # the single probe slot
    assert not br.allow()                    # second caller still blocked
    br.record_failure()                      # probe failed -> reopen
    assert br.state == CircuitBreaker.OPEN
    time.sleep(0.2)
    assert br.allow()
    br.record_success()                      # probe succeeded -> closed
    assert br.state == CircuitBreaker.CLOSED
    assert br.allow()


def test_channel_fails_fast_when_circuit_open():
    # nothing listens on port 1; breaker trips after 2 failed calls
    ch = ResilientChannel('127.0.0.1:1',
                          retry_policy=RetryPolicy(max_attempts=1,
                                                   base_delay=0.01),
                          breaker=CircuitBreaker(failure_threshold=2,
                                                 reset_timeout=30.0))
    for _ in range(2):
        with pytest.raises(RetryableError):
            ch.call({'op': 'stats'})
    t0 = time.monotonic()
    with pytest.raises(CircuitOpenError):
        ch.call({'op': 'stats'})
    assert time.monotonic() - t0 < 0.5      # fast-fail, no connect attempt


# -- graph service under injected faults ------------------------------------

def _graph_cluster():
    srv = GraphPyServer()
    srv.start_server()
    client = GraphPyClient(['127.0.0.1:%d' % srv.port], **FAST)
    client.add_edges('default', [0, 1, 2], [1, 2, 0])
    return srv, client


def test_graph_call_retries_through_dropped_connections():
    srv, client = _graph_cluster()
    try:
        with chaos.drop_connections(point='send', times=2) as fault:
            deg = client.get_degree('default', [0, 1, 2])
        assert fault.fired == 2             # two transport failures eaten
        assert deg.tolist() == [1, 1, 1]
    finally:
        client.stop_server()


def test_graph_call_survives_connect_drops_and_delays():
    srv, client = _graph_cluster()
    try:
        with chaos.drop_connections(point='connect', times=1):
            with chaos.delay_connections(0.05, point='connect', times=1):
                # drop the pooled conn so the call must reconnect
                client._channels[0]._drop_connection()
                deg = client.get_degree('default', [0])
        assert deg.tolist() == [1]
    finally:
        client.stop_server()


def test_killed_graph_server_surfaces_bounded_retryable_error():
    srv, client = _graph_cluster()
    chaos.kill_server(srv)                  # hard kill: listener + conns
    deadline_s = 1.5
    client._op_deadline = deadline_s
    t0 = time.monotonic()
    with pytest.raises(RetryableError):
        client.get_degree('default', [0, 1, 2])
    elapsed = time.monotonic() - t0
    # no hang: bounded by the retry ladder / deadline, with slack for CI
    assert elapsed < deadline_s + 2.0
    client.close()


def test_killed_graph_server_respects_tight_deadline():
    srv, client = _graph_cluster()
    chaos.kill_server(srv)
    # huge attempt budget and a breaker that never trips: the DEADLINE
    # must be what stops the retries
    client._channels[0].policy = RetryPolicy(max_attempts=1000,
                                             base_delay=0.01,
                                             max_delay=0.05)
    client._channels[0].breaker = CircuitBreaker(failure_threshold=10**9)
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        client._channels[0].call({'op': 'degree', 'etype': 'default',
                                  'ids': [0]}, deadline=Deadline(0.4))
    assert time.monotonic() - t0 < 2.0
    client.close()


def test_graph_idempotent_op_retried_across_server_restart():
    srv, client = _graph_cluster()
    before = client.get_degree('default', [0, 1, 2]).tolist()
    port = srv.port
    chaos.kill_server(srv)

    def restart():
        time.sleep(0.15)                     # an outage the retries span
        new_srv = GraphPyServer(port=port)
        # the replacement pod reloads the same shard data
        new_srv._srv.stores['default'].add_edges([0, 1, 2], [1, 2, 0],
                                                 None)
        new_srv.start_server()
        restarted.append(new_srv)

    restarted = []
    t = threading.Thread(target=restart)
    t.start()
    try:
        client._channels[0].policy = RetryPolicy(max_attempts=8,
                                                 base_delay=0.05,
                                                 max_delay=0.2)
        deg = client.get_degree('default', [0, 1, 2])
        assert deg.tolist() == before       # correct result after restart
    finally:
        t.join()
        client.stop_server()


def test_graph_add_edges_is_not_blind_resent():
    """Mutations that append must NOT retry: a resend after an
    applied-but-unacked write would duplicate edges."""
    srv, client = _graph_cluster()
    try:
        with chaos.drop_connections(point='send', times=1) as fault:
            with pytest.raises(RetryableError) as ei:
                client.add_edges('default', [5], [6])
        assert fault.fired == 1             # exactly one attempt
        assert ei.value.attempts == 1
        # and the graph was not corrupted by duplicates
        assert client.get_degree('default', [5]).tolist() == [0]
    finally:
        client.stop_server()


# -- PS embedding service under injected faults ------------------------------

def _ps_cluster(seed=7):
    srv = EmbeddingServer()
    srv.create_table(0, dim=4, seed=seed)
    srv.start()
    client = EmbeddingClient(endpoints=[srv.endpoint], **FAST)
    return srv, client


def test_ps_pull_retried_across_server_restart():
    srv, client = _ps_cluster(seed=7)
    rows = client.pull(0, [1, 2, 3])        # materializes rows (seed 7)
    port = srv.port
    chaos.kill_server(srv)

    def restart():
        time.sleep(0.15)
        new_srv = EmbeddingServer(port=port)
        new_srv.create_table(0, dim=4, seed=7)   # same shard state
        new_srv.start()
        restarted.append(new_srv)

    restarted = []
    t = threading.Thread(target=restart)
    t.start()
    try:
        client._channels[0].policy = RetryPolicy(max_attempts=8,
                                                 base_delay=0.05,
                                                 max_delay=0.2)
        again = client.pull(0, [1, 2, 3])
        np.testing.assert_array_equal(again, rows)
    finally:
        t.join()
        for s in restarted:
            s.stop()


def test_ps_killed_server_bounds_the_error():
    srv, client = _ps_cluster()
    client.pull(0, [1])
    chaos.kill_server(srv)
    t0 = time.monotonic()
    with pytest.raises(RetryableError):
        client.pull(0, [1])
    assert time.monotonic() - t0 < 4.0      # retry ladder, not a hang


def test_ps_push_is_not_blind_resent():
    srv, client = _ps_cluster()
    try:
        client.pull(0, [1])                 # materialize the row
        with chaos.drop_connections(point='send', times=1) as fault:
            with pytest.raises(RetryableError) as ei:
                client.push(0, [1], np.ones((1, 4), np.float32))
        assert fault.fired == 1
        assert ei.value.attempts == 1       # single attempt, no resend
    finally:
        srv.stop()


# -- monitor counters as the chaos oracle ------------------------------------
# The default registry is process-wide and shared with every other test,
# so every assertion here is a DELTA around the faulted section — and the
# deltas must be EXACT: N injected faults means N counted failures, which
# is only true because counter updates are locked (registry design rule 2).

def _counter(name, *labels):
    return monitor.default_registry().get(name).labels(*labels).value()


def test_monitor_failure_counters_match_injected_faults_exactly():
    srv, client = _graph_cluster()
    ep = client._channels[0].endpoint
    f0 = _counter('rpc_attempt_failures_total', ep)
    a0 = _counter('rpc_attempts_total', ep)
    b0 = _counter('rpc_backoff_seconds_total', ep)
    try:
        with chaos.drop_connections(point='send', times=3) as fault:
            deg = client.get_degree('default', [0, 1, 2])
        assert deg.tolist() == [1, 1, 1]
        assert fault.fired == 3
        # the oracle: every injected fault is one counted failure
        assert _counter('rpc_attempt_failures_total', ep) - f0 == fault.fired
        # 3 failures + the final success = 4 attempts begun
        assert _counter('rpc_attempts_total', ep) - a0 == 4
        # 3 backoff sleeps were accounted (FAST ladder: each >= 20 ms)
        slept = _counter('rpc_backoff_seconds_total', ep) - b0
        assert 3 * 0.02 <= slept < 2.0
    finally:
        client.stop_server()


def test_monitor_breaker_transitions_and_fast_fail_counters():
    ep = '127.0.0.1:1'                       # nothing listens here
    t0 = _counter('rpc_breaker_transitions_total', ep, 'open')
    r0 = _counter('rpc_circuit_open_total', ep)
    ch = ResilientChannel(ep,
                          retry_policy=RetryPolicy(max_attempts=1,
                                                   base_delay=0.01),
                          breaker=CircuitBreaker(failure_threshold=2,
                                                 reset_timeout=30.0))
    for _ in range(2):
        with pytest.raises(RetryableError):
            ch.call({'op': 'stats'})
    # threshold hit exactly once -> one closed->open transition, and the
    # state gauge shows open (code 1)
    assert _counter('rpc_breaker_transitions_total', ep, 'open') - t0 == 1
    assert monitor.default_registry().get(
        'rpc_breaker_state').labels(ep).value() == 1
    with pytest.raises(CircuitOpenError):
        ch.call({'op': 'stats'})
    assert _counter('rpc_circuit_open_total', ep) - r0 == 1


def test_monitor_counts_deadline_expirations():
    srv, client = _graph_cluster()
    ep = client._channels[0].endpoint
    chaos.kill_server(srv)
    d0 = _counter('rpc_deadline_expired_total', ep)
    ch = client._channels[0]
    ch.policy = RetryPolicy(max_attempts=1000, base_delay=0.01,
                            max_delay=0.05)
    ch.breaker = CircuitBreaker(failure_threshold=10**9)
    with pytest.raises(DeadlineExceeded):
        ch.call({'op': 'degree', 'etype': 'default', 'ids': [0]},
                deadline=Deadline(0.3))
    assert _counter('rpc_deadline_expired_total', ep) - d0 == 1
    client.close()


def test_monitor_ps_call_counters_per_op():
    srv, client = _ps_cluster()
    c0 = _counter('ps_client_calls_total', 'pull')
    e0 = _counter('ps_client_call_errors_total', 'pull')
    client.pull(0, [1, 2])
    # one data pull + the client's dim-probe pull = exactly 2 RPCs
    assert _counter('ps_client_calls_total', 'pull') - c0 == 2
    assert _counter('ps_client_call_errors_total', 'pull') - e0 == 0
    chaos.kill_server(srv)
    with pytest.raises(RetryableError):
        client.pull(0, [1, 2])
    assert _counter('ps_client_call_errors_total', 'pull') - e0 == 1


# -- checkpoint integrity: manifests, atomicity, fallback --------------------

def test_io_save_writes_manifest_and_detects_truncation(tmp_path):
    path = str(tmp_path / 'state.pdparams')
    io_save.save({'w': np.arange(64, dtype=np.float32)}, path)
    assert os.path.exists(io_save.manifest_path(path))
    assert io_save.verify_checkpoint(path)
    # no temp droppings from the atomic write
    assert [f for f in os.listdir(str(tmp_path)) if '.tmp.' in f] == []

    chaos.truncate_file(path, drop_bytes=16)
    assert not io_save.verify_checkpoint(path)
    with pytest.raises(io_save.CheckpointCorruptError):
        io_save.load(path)


def test_io_save_legacy_file_without_manifest_still_loads(tmp_path):
    path = str(tmp_path / 'legacy.pdparams')
    io_save.save({'x': 1}, path)
    os.remove(io_save.manifest_path(path))  # pre-manifest era snapshot
    assert io_save.verify_checkpoint(path)
    assert io_save.load(path) == {'x': 1}


def test_checkpoint_manager_falls_back_past_truncated_latest(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last=3)
    for step in (1, 2, 3):
        mgr.save(step, {'step': step, 'w': np.full(8, step, np.float32)})
    chaos.truncate_file(os.path.join(str(tmp_path), 'step_3.ckpt'))

    step, state = mgr.restore_latest()
    assert step == 2                        # newest VALID snapshot
    np.testing.assert_array_equal(state['w'], np.full(8, 2, np.float32))

    # all three corrupt -> clean "nothing to restore", not an exception
    for s in (1, 2):
        chaos.truncate_file(os.path.join(str(tmp_path),
                                         'step_%d.ckpt' % s))
    assert mgr.restore_latest() == (None, None)


def test_checkpoint_manager_prunes_manifests_too(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last=2)
    for step in range(5):
        mgr.save(step, {'step': step})
    files = sorted(os.listdir(str(tmp_path)))
    assert files == ['step_3.ckpt', 'step_3.ckpt.manifest',
                     'step_4.ckpt', 'step_4.ckpt.manifest']


def test_auto_checkpoint_restores_previous_epoch_on_truncation(tmp_path):
    """The acceptance scenario end-to-end: epoch snapshots exist, the
    NEWEST one is truncated (preempted writer), and the restart resumes
    from the previous valid epoch instead of crashing or hanging."""
    extra = {}
    r = TrainEpochRange(3, 'jobX', checkpoint_dir=str(tmp_path),
                        extra_state=extra)
    for epoch in r:
        extra['last_epoch_ran'] = epoch
    job_dir = os.path.join(str(tmp_path), 'jobX')
    assert sorted(f for f in os.listdir(job_dir)
                  if f.endswith('.ckpt')) == \
        ['epoch_0.ckpt', 'epoch_1.ckpt', 'epoch_2.ckpt']

    chaos.truncate_file(os.path.join(job_dir, 'epoch_2.ckpt'))

    r2 = TrainEpochRange(5, 'jobX', checkpoint_dir=str(tmp_path))
    assert r2.restored_epoch == 1           # fell back past the torn one
    assert r2.skipped_corrupt == [2]
    assert r2.extra_state['last_epoch_ran'] == 1
    # training resumes where the valid snapshot left off
    assert [e for e in r2] == [2, 3, 4]


def test_auto_checkpoint_all_corrupt_starts_fresh(tmp_path):
    r = TrainEpochRange(2, 'jobY', checkpoint_dir=str(tmp_path))
    for _ in r:
        pass
    job_dir = os.path.join(str(tmp_path), 'jobY')
    for f in os.listdir(job_dir):
        if f.endswith('.ckpt'):
            chaos.truncate_file(os.path.join(job_dir, f), keep_bytes=3)
    r2 = TrainEpochRange(2, 'jobY', checkpoint_dir=str(tmp_path))
    assert r2.restored_epoch == -1          # clean cold start
    assert sorted(r2.skipped_corrupt) == [0, 1]
