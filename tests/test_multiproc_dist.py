"""Real multi-process distributed execution (VERDICT r3 item 2).

Two REAL localhost processes × 4 virtual CPU devices each, bootstrapped
through paddle.distributed.spawn's env contract into
`init_parallel_env` -> `jax.distributed.initialize` (Gloo-backed CPU
collectives), running one data-parallel train step whose gradient/loss
all-reduce spans the process boundary — the reference TestDistBase
capability (test_dist_base.py:743-1135 spawns localhost trainers and
compares losses).
"""
import functools
import importlib
import os

import numpy as np
import pytest

spawn_mod = importlib.import_module('paddle_tpu.distributed.spawn')

_N, _D_IN, _D_OUT, _LR = 16, 8, 4, 0.1


def _problem():
    rng = np.random.RandomState(7)
    x = rng.randn(_N, _D_IN).astype(np.float32)
    y = rng.randn(_N, _D_OUT).astype(np.float32)
    w0 = rng.randn(_D_IN, _D_OUT).astype(np.float32)
    return x, y, w0


def _dp_train_worker(out_dir):
    # child: 4 virtual CPU devices BEFORE the backend initializes
    os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '') +
                               ' --xla_force_host_platform_device_count=4'
                               ).strip()
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu import distributed as dist

    dist.init_parallel_env()   # PADDLE_TRAINER_* -> jax.distributed
    rank = dist.get_rank()
    assert dist.get_world_size() == 2
    assert jax.process_count() == 2
    assert len(jax.devices()) == 8    # global device view

    mesh = Mesh(np.array(jax.devices()), ('dp',))
    data_sh = NamedSharding(mesh, P('dp'))
    rep = NamedSharding(mesh, P())

    x, y, w0 = _problem()
    half = _N // 2
    xg = jax.make_array_from_process_local_data(
        data_sh, x[rank * half:(rank + 1) * half])
    yg = jax.make_array_from_process_local_data(
        data_sh, y[rank * half:(rank + 1) * half])
    w = jax.make_array_from_process_local_data(rep, w0)

    @functools.partial(jax.jit, in_shardings=(rep, data_sh, data_sh),
                       out_shardings=(rep, rep))
    def step(w, xb, yb):
        def loss_fn(w):
            return jnp.mean((xb @ w - yb) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(w)
        return w - _LR * g, loss

    w1, loss = step(w, xg, yg)
    with open(os.path.join(out_dir, 'rank_%d' % rank), 'w') as f:
        f.write('%.8e %.8e' % (float(loss), float(jnp.sum(w1))))


@pytest.mark.skipif(
    os.environ.get('JAX_PLATFORMS', '').startswith('cpu'),
    reason="jaxlib: \"Multiprocess computations aren't implemented on "
           'the CPU backend\"; runs on TPU')
def test_two_process_dp_step_loss_parity(tmp_path):
    spawn_mod.spawn(_dp_train_worker, args=(str(tmp_path),), nprocs=2)
    files = sorted(os.listdir(tmp_path))
    assert files == ['rank_0', 'rank_1']

    # numpy single-process reference over the FULL batch: parity proves
    # the cross-process all-reduce averaged grads/loss globally
    x, y, w0 = _problem()
    pred = x @ w0
    loss_ref = np.mean((pred - y) ** 2)
    g = 2.0 * x.T @ (pred - y) / (_N * _D_OUT)
    w1_ref = w0 - _LR * g

    for f in files:
        loss, wsum = map(float, (tmp_path / f).read_text().split())
        np.testing.assert_allclose(loss, loss_ref, rtol=1e-5)
        np.testing.assert_allclose(wsum, np.sum(w1_ref), rtol=1e-4)
