"""API-surface audit against the reference's own import lists.

Parses the reference package's __init__ files (when the reference tree
is present — skipped elsewhere) and asserts every public name they
import exists on our namespaces. This is the committed, reproducible
form of the round-3 surface audits.
"""
import pathlib
import re

import pytest

REF = pathlib.Path('/root/reference/python/paddle')

pytestmark = pytest.mark.skipif(not REF.exists(),
                                reason='reference tree not available')

# names that are deliberately absent (documented decisions)
WAIVED = {
    # fluid two-level namespace itself is superseded by paddle.static
    'fluid',
    # compiled-proto plumbing with no python-visible behavior
    'core', 'core_avx', 'core_noavx',
}


def _ref_names(init_path):
    txt = init_path.read_text(errors='ignore')
    names = set()
    for m in re.finditer(
            r"^from [.\w]+ import ([\w, #\\\n]+?)(?:  #|$)", txt, re.M):
        for n in m.group(1).replace('\\', ' ').replace('\n', ' ').split(','):
            n = n.strip()
            if n and n.isidentifier() and not n.startswith('_'):
                names.add(n)
    return names - WAIVED


def _missing(ns, names):
    return sorted(n for n in names if not hasattr(ns, n))


def test_paddle_top_level_surface():
    import paddle_tpu as paddle
    missing = _missing(paddle, _ref_names(REF / '__init__.py'))
    assert not missing, missing


def test_paddle_nn_surface():
    import paddle_tpu as paddle
    missing = _missing(paddle.nn, _ref_names(REF / 'nn' / '__init__.py'))
    assert not missing, missing


def test_paddle_nn_functional_surface():
    import paddle_tpu as paddle
    missing = _missing(paddle.nn.functional,
                       _ref_names(REF / 'nn' / 'functional' / '__init__.py'))
    assert not missing, missing


def test_paddle_tensor_surface():
    import paddle_tpu as paddle
    missing = _missing(paddle.tensor,
                       _ref_names(REF / 'tensor' / '__init__.py'))
    assert not missing, missing


def test_paddle_static_surface():
    import paddle_tpu as paddle
    missing = _missing(paddle.static,
                       _ref_names(REF / 'static' / '__init__.py'))
    assert not missing, missing


def test_paddle_vision_and_io_surfaces():
    import paddle_tpu as paddle
    for sub, ns in [('vision', paddle.vision), ('io', paddle.io),
                    ('optimizer', paddle.optimizer),
                    ('metric', paddle.metric), ('amp', paddle.amp)]:
        missing = _missing(ns, _ref_names(REF / sub / '__init__.py'))
        assert not missing, (sub, missing)


def test_paddle_distributed_surface():
    import paddle_tpu as paddle
    missing = _missing(paddle.distributed,
                       _ref_names(REF / 'distributed' / '__init__.py'))
    assert not missing, missing


def test_inplace_fns_and_tensor_array_behavior():
    import numpy as np
    import paddle_tpu as paddle

    x = paddle.to_tensor(np.asarray([1.0, 4.0], np.float32))
    y = paddle.tensor.sqrt_(x)
    assert y is x
    np.testing.assert_allclose(x.numpy(), [1.0, 2.0])

    # paddle parity: inplace on a grad-requiring leaf raises...
    leaf = paddle.to_tensor(np.asarray([1.0], np.float32),
                            stop_gradient=False)
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match='in-place'):
        paddle.tensor.exp_(leaf)
    # ...but a non-leaf keeps full backward history through the rebind
    h = leaf * 4.0
    paddle.tensor.sqrt_(h)
    h.sum().backward()            # d sqrt(4 l) / dl = 2 / (2 sqrt(l)) = 1
    np.testing.assert_allclose(leaf.grad.numpy(), [1.0], rtol=1e-6)

    arr = paddle.tensor.create_array()
    paddle.tensor.array_write(paddle.to_tensor([1.0]), 0, arr)
    paddle.tensor.array_write(paddle.to_tensor([2.0]), 1, arr)
    assert int(paddle.tensor.array_length(arr).numpy()) == 2
    np.testing.assert_allclose(
        paddle.tensor.array_read(arr, 1).numpy(), [2.0])


def test_spectral_norm_normalizes_sigma():
    import numpy as np
    import paddle_tpu as paddle

    paddle.seed(0)
    lin = paddle.nn.Linear(16, 8)
    paddle.nn.utils.spectral_norm(lin, n_power_iterations=20)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 16).astype(np.float32))
    lin(x)
    eff = lin.__dict__['weight'].numpy()
    sigma = np.linalg.svd(eff, compute_uv=False)[0]
    assert abs(sigma - 1.0) < 1e-2, sigma
    paddle.nn.utils.remove_spectral_norm(lin)
    assert 'weight' in dict(lin.named_parameters())


def test_spectral_norm_gradient_has_sigma_term():
    """d(W/sigma)/dW must include the -W (u v^T)/sigma^2 term — compare
    the recorded-op gradient against a numeric one."""
    import numpy as np
    import paddle_tpu as paddle

    paddle.seed(1)
    lin = paddle.nn.Linear(5, 3)
    paddle.nn.utils.spectral_norm(lin, n_power_iterations=30)
    x_np = np.random.RandomState(1).randn(2, 5).astype(np.float32)

    def loss_for(w_np):
        lin._parameters['weight_orig']._data = \
            paddle.to_tensor(w_np)._data
        return float(lin(paddle.to_tensor(x_np)).numpy().sum())

    w0 = lin._parameters['weight_orig'].numpy().copy()
    lin._parameters['weight_orig'].stop_gradient = False
    out = lin(paddle.to_tensor(x_np))
    out.sum().backward()
    analytic = lin._parameters['weight_orig'].grad.numpy()

    h = 1e-3
    i, j = 2, 1
    wp = w0.copy(); wp[i, j] += h
    wm = w0.copy(); wm[i, j] -= h
    numeric = (loss_for(wp) - loss_for(wm)) / (2 * h)
    assert abs(analytic[i, j] - numeric) < 5e-2 * max(1, abs(numeric)), \
        (analytic[i, j], numeric)


def test_paddle_inference_surface():
    import paddle_tpu.inference as inf
    names = _ref_names(REF / 'inference' / '__init__.py')
    missing = _missing(inf, names)
    assert not missing, missing
    assert inf.get_num_bytes_of_data_type(inf.DataType.FLOAT32) == 4


def test_inplace_same_object_second_arg_and_frozen_spectral_norm():
    import numpy as np
    import paddle_tpu as paddle

    # add_(y, y): both branches' grads must survive the handle rebind
    leaf = paddle.to_tensor(np.asarray([3.0], np.float32),
                            stop_gradient=False)
    y = leaf * 2.0
    paddle.tensor.add_(y, y)        # y := 2x + 2x = 4x
    y.sum().backward()
    np.testing.assert_allclose(leaf.grad.numpy(), [4.0])

    # spectral_norm on a frozen layer must not resurrect trainability
    lin = paddle.nn.Linear(4, 3)
    lin.weight.stop_gradient = True
    paddle.nn.utils.spectral_norm(lin)
    assert lin._parameters['weight_orig'].stop_gradient
    paddle.nn.utils.remove_spectral_norm(lin)
    assert lin.weight.stop_gradient
