"""Graph engine tests (reference pattern: distributed/test/graph_node_test.cc
— same-process server+client, load edges, sample neighbors)."""
import os

import numpy as np
import pytest


def test_native_store_weighted_sampling():
    from paddle_tpu.native.graph_store import GraphStore
    gs = GraphStore(seed=3)
    gs.add_edges([0] * 3, [10, 11, 12], weight=[1.0, 2.0, 7.0])
    s = gs.sample_neighbors([0], 2000)[0]
    frac_12 = float(np.mean(s == 12))
    assert 0.6 < frac_12 < 0.8  # ~0.7


def test_native_store_file_load(tmp_path):
    from paddle_tpu.native.graph_store import GraphStore
    p = tmp_path / 'edges.txt'
    p.write_text('1\t2\n1\t3\n2\t4\t0.5\n')
    gs = GraphStore()
    n = gs.load_edge_file(str(p))
    assert n == 3
    assert gs.node_count() == 2
    np.testing.assert_array_equal(gs.degree([1, 2]), [2, 1])


def test_graph_service_cluster():
    from paddle_tpu.distributed.graph_service import GraphPyService
    svc = GraphPyService()
    client = svc.set_up(num_servers=2)
    try:
        src = np.arange(100) % 10
        dst = (np.arange(100) * 7) % 50 + 100
        client.add_edges('default', src, dst)
        deg = client.get_degree('default', np.arange(10))
        assert deg.sum() == 100
        samples = client.random_sample_neighboors('default',
                                                  np.arange(10), 5)
        assert samples.shape == (10, 5)
        assert (samples >= 100).all()
        # features round trip
        ids = np.asarray([3, 7])
        client.set_node_feat('default', ids,
                             np.asarray([[1., 2.], [3., 4.]]))
        feats = client.get_node_feat('default', ids, 2)
        np.testing.assert_allclose(feats, [[1., 2.], [3., 4.]])
        # node listing
        nodes = client.random_sample_nodes('default', 0, 5)
        assert len(nodes) <= 5
    finally:
        svc.stop()


def test_multislot_parser_native_vs_python():
    from paddle_tpu.native.datafeed import parse_multislot
    text = '2 0.5 0.25 3 1 2 3\n1 9.0 2 7 8\nbad line\n1 1.0 1 5\n'
    for force in (False, True):
        slots, n = parse_multislot(text, ['float', 'int'],
                                   force_python=force)
        assert n == 3
        np.testing.assert_allclose(slots[0][0], [0.5, 0.25, 9.0, 1.0])
        np.testing.assert_array_equal(slots[0][1], [0, 2, 3, 4])
        np.testing.assert_array_equal(slots[1][0], [1, 2, 3, 7, 8, 5])
        np.testing.assert_array_equal(slots[1][1], [0, 3, 5, 6])


def test_mixed_weighted_unweighted_edges():
    # regression: a node receiving both weighted and unweighted edges must
    # sample over ALL neighbors (missing weight means 1.0), and native and
    # python fallbacks must agree on the semantics
    from paddle_tpu.native.graph_store import GraphStore
    for force in (False, True):
        gs = GraphStore(seed=7, force_python=force)
        gs.add_edges([0, 0], [10, 11])                 # unweighted first
        gs.add_edges([0], [12], weight=[6.0])          # then weighted
        s = gs.sample_neighbors([0], 4000)[0]
        seen = set(np.unique(s).tolist())
        assert seen == {10, 11, 12}, (force, seen)
        frac_12 = float(np.mean(s == 12))
        assert 0.65 < frac_12 < 0.85, (force, frac_12)  # 6/8 = 0.75


def test_multislot_truncated_line_not_stealing_next(tmp_path):
    # regression: a line declaring more values than it supplies must be
    # dropped without consuming tokens from the following line
    from paddle_tpu.native.datafeed import parse_multislot
    text = '1 0.5 2 7\n2 1.0 2.0 3 1 2 3\n'
    for force in (False, True):
        slots, n = parse_multislot(text, ['float', 'int'], force_python=force)
        assert n == 1, ('force_python=%s' % force)
        np.testing.assert_allclose(slots[0][0], [1.0, 2.0])
        np.testing.assert_array_equal(slots[1][0], [1, 2, 3])


def test_graph_service_restart_cycle():
    # regression: set_up/stop must release listening sockets so repeated
    # cycles in one process don't leak fds
    from paddle_tpu.distributed.graph_service import GraphPyService
    for _ in range(3):
        svc = GraphPyService()
        client = svc.set_up(num_servers=2)
        client.add_edges('default', [1], [2])
        assert client.get_degree('default', [1])[0] == 1
        svc.stop()


def test_remove_nodes_native_and_python():
    from paddle_tpu.native.graph_store import GraphStore
    for force in (False, True):
        gs = GraphStore(force_python=force)
        gs.add_edges([1, 1, 2], [10, 11, 12])
        gs.set_node_feat(1, [1.0, 2.0])
        assert gs.remove_nodes([1, 99]) == 1
        np.testing.assert_array_equal(gs.degree([1, 2]), [0, 1])
        # removed node's feature is gone too
        np.testing.assert_allclose(gs.get_node_feat([1], 2), [[0.0, 0.0]])


def test_service_remove_graph_node():
    from paddle_tpu.distributed.graph_service import GraphPyService
    svc = GraphPyService()
    client = svc.set_up(num_servers=2)
    try:
        client.add_edges('default', [1, 2, 3], [10, 20, 30])
        assert client.remove_graph_node('default', [2, 77]) == 1
        deg = client.get_degree('default', [1, 2, 3])
        np.testing.assert_array_equal(deg, [1, 0, 1])
    finally:
        svc.stop()
