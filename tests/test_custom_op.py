"""Custom-op extension tests (reference pattern:
python/paddle/fluid/tests/custom_op/ — JIT-compile an extension .so then
run it, checking forward, backward, and jit integration)."""
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle

RELU_SRC = textwrap.dedent('''
#include "pd_extension.h"

static int relu_fwd(const PDTensor* ins, int n_in, PDTensor* outs,
                    int n_out) {
  const float* x = (const float*)ins[0].data;
  float* y = (float*)outs[0].data;
  for (int64_t i = 0; i < pd_numel(&ins[0]); i++)
    y[i] = x[i] > 0.f ? x[i] : 0.f;
  return 0;
}

// ins: (x, dy) -> dx
static int relu_bwd(const PDTensor* ins, int n_in, PDTensor* outs,
                    int n_out) {
  const float* x = (const float*)ins[0].data;
  const float* dy = (const float*)ins[1].data;
  float* dx = (float*)outs[0].data;
  for (int64_t i = 0; i < pd_numel(&ins[0]); i++)
    dx[i] = x[i] > 0.f ? dy[i] : 0.f;
  return 0;
}

PD_BUILD_OP(custom_relu, 1, 1, relu_fwd);
PD_BUILD_GRAD_OP(custom_relu, 2, 1, relu_bwd);

// concat-last-dim op with a real infer function: [N,A],[N,B] -> [N,A+B]
static int cat_infer(const PDTensor* ins, int n_in, PDTensor* outs,
                     int n_out) {
  outs[0].ndim = 2;
  outs[0].shape[0] = ins[0].shape[0];
  outs[0].shape[1] = ins[0].shape[1] + ins[1].shape[1];
  outs[0].dtype = ins[0].dtype;
  return 0;
}

static int cat_fwd(const PDTensor* ins, int n_in, PDTensor* outs,
                   int n_out) {
  int64_t n = ins[0].shape[0], a = ins[0].shape[1], b = ins[1].shape[1];
  const float* x = (const float*)ins[0].data;
  const float* y = (const float*)ins[1].data;
  float* o = (float*)outs[0].data;
  for (int64_t r = 0; r < n; r++) {
    for (int64_t i = 0; i < a; i++) o[r * (a + b) + i] = x[r * a + i];
    for (int64_t i = 0; i < b; i++) o[r * (a + b) + a + i] = y[r * b + i];
  }
  return 0;
}

PD_BUILD_OP_INFER(custom_cat2, 2, 1, cat_fwd, cat_infer);
''')


@pytest.fixture(scope='module')
def ext(tmp_path_factory):
    from paddle_tpu.utils.cpp_extension import load
    d = tmp_path_factory.mktemp('ext')
    src = d / 'custom_ops.cc'
    src.write_text(RELU_SRC)
    return load('custom_ops', [str(src)], build_directory=str(d))


def test_custom_relu_forward(ext):
    x = np.random.RandomState(0).standard_normal((4, 5)).astype(np.float32)
    out = ext.custom_relu(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), np.maximum(x, 0))


def test_custom_relu_backward(ext):
    x = paddle.to_tensor(np.asarray([[-1.0, 2.0], [3.0, -4.0]],
                                    np.float32), stop_gradient=False)
    y = ext.custom_relu(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[0.0, 1.0], [1.0, 0.0]])


def test_custom_op_under_jit(ext):
    import jax
    import jax.numpy as jnp
    x = jnp.asarray([[-1.0, 2.0]], jnp.float32)

    @jax.jit
    def f(a):
        return ext._ops['custom_relu']._fn(a) * 2.0

    np.testing.assert_allclose(np.asarray(f(x)), [[0.0, 4.0]])
    g = jax.grad(lambda a: jnp.sum(ext._ops['custom_relu']._fn(a)))(x)
    np.testing.assert_allclose(np.asarray(g), [[0.0, 1.0]])


def test_custom_infer_shape_op(ext):
    a = paddle.to_tensor(np.ones((3, 2), np.float32))
    b = paddle.to_tensor(np.zeros((3, 4), np.float32))
    out = ext.custom_cat2(a, b)
    assert tuple(out.shape) == (3, 6)
    np.testing.assert_allclose(out.numpy()[:, :2], 1.0)
    np.testing.assert_allclose(out.numpy()[:, 2:], 0.0)


def test_load_cache_and_input_validation(ext, tmp_path):
    with pytest.raises(ValueError):
        ext.custom_relu(paddle.to_tensor(np.ones(2, np.float32)),
                        paddle.to_tensor(np.ones(2, np.float32)))
    assert ext.op_names() == ['custom_cat2', 'custom_relu']


def test_gradless_op_forward_ok_backward_errors(tmp_path):
    # an op without a grad kernel must still run FORWARD on inputs that
    # require grad; the error fires only when a gradient is pulled
    from paddle_tpu.utils.cpp_extension import load
    src = tmp_path / 'sq.cc'
    src.write_text(textwrap.dedent('''
    #include "pd_extension.h"
    static int sq(const PDTensor* ins, int n, PDTensor* outs, int m) {
      const float* x = (const float*)ins[0].data;
      float* y = (float*)outs[0].data;
      for (int64_t i = 0; i < pd_numel(&ins[0]); i++) y[i] = x[i] * x[i];
      return 0;
    }
    PD_BUILD_OP(custom_square, 1, 1, sq);
    '''))
    ext2 = load('sq_ext', [str(src)], build_directory=str(tmp_path))
    x = paddle.to_tensor(np.asarray([2.0, 3.0], np.float32),
                         stop_gradient=False)
    y = ext2.custom_square(x)
    np.testing.assert_allclose(y.numpy(), [4.0, 9.0])
    with pytest.raises(Exception):
        y.sum().backward()


def test_bad_grad_arity_rejected(tmp_path):
    from paddle_tpu.utils.cpp_extension import load
    src = tmp_path / 'bad.cc'
    src.write_text(textwrap.dedent('''
    #include "pd_extension.h"
    static int f(const PDTensor* ins, int n, PDTensor* outs, int m) {
      return 0;
    }
    PD_BUILD_OP(custom_bad, 1, 1, f);
    PD_BUILD_GRAD_OP(custom_bad, 3, 1, f);  // wrong: should be 2 inputs
    '''))
    with pytest.raises(RuntimeError, match='grad kernel'):
        load('bad_ext', [str(src)], build_directory=str(tmp_path))
