"""Realistic-shape sharding evidence (VERDICT r3 item 5).

BERT-base dims — hidden 768, 12 heads, vocab 30522, seq 512 — on the
8-device CPU mesh, asserting on the COMPILED (post-SPMD-partitioning)
HLO: the expected collectives are present and the parameters are really
sharded, so a partitioner that silently replicates fails the suite. The
TPU analog of the reference's meta-optimizer program-transform
assertions (test_fleet_sharding_meta_optimizer.py etc., SURVEY §4.2).

Layer count is kept at 2 (CPU compile budget); the dims that surface
realistic sharding bugs — 30k-vocab parallel embedding/head, 12-way
head split over mp, megabyte-scale gathers — are per-layer properties.
"""
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

HIDDEN, HEADS, VOCAB, SEQ = 768, 12, 30522, 512


def _model(seed=0, **overrides):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=HIDDEN, num_layers=2,
                    num_heads=HEADS, max_position_embeddings=SEQ,
                    dropout=0.0, **overrides)
    return GPTForCausalLM(cfg)


def _batch(b=8):
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, VOCAB, (b, SEQ)).astype(np.int32))
    lbl = paddle.to_tensor(rng.randint(0, VOCAB, (b, SEQ)).astype(np.int32))
    return ids, lbl


def _strategy(**hybrid):
    s = fleet.DistributedStrategy()
    cfg = {'dp_degree': 8, 'mp_degree': 1, 'pp_degree': 1,
           'sharding_degree': 1, 'sp_degree': 1}
    cfg.update(hybrid)
    s.hybrid_configs = cfg
    return s


def _step(model, strategy):
    fleet.init(is_collective=True, strategy=strategy)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    return fleet.fleet_train_step(
        model, lambda lg, lb: model.loss(lg, lb), opt, strategy=strategy)


def _collective_counts(hlo):
    return {op: len(re.findall(r'%s' % op, hlo))
            for op in ('all-reduce', 'all-gather', 'reduce-scatter',
                       'all-to-all', 'collective-permute')}


def _shard_of(pshard, name):
    if pshard is None:
        return None
    entry = pshard.get(name) if hasattr(pshard, 'get') else None
    return entry


def test_dp_mp_hlo_has_collectives_and_sharded_params():
    """dp2 x mp4: bwd grad sync (dp all-reduce) + TP activation
    reductions (mp all-reduce) must be in the compiled program, and the
    TP-hinted params must be physically sharded over mp."""
    s = _strategy(dp_degree=2, mp_degree=4)
    model = _model()
    step = _step(model, s)
    ids, lbl = _batch()
    hlo, pshard = step.compiled_hlo(ids, lbl)

    counts = _collective_counts(hlo)
    # TP forward needs >=1 all-reduce per block (out_proj + fc_out rows)
    # plus the dp/mp grad reductions in backward
    assert counts['all-reduce'] >= 4, counts
    assert 'replica_groups' in hlo

    # physically sharded qkv weight: [768, 2304] over mp=4 -> 576 cols
    qkv = [n for n in pshard if 'qkv_proj' in n and 'weight' in n]
    assert qkv, sorted(pshard)[:8]
    spec = pshard[qkv[0]].spec
    assert tuple(spec) == (None, 'mp'), spec
    shape = pshard[qkv[0]].shard_shape((HIDDEN, 3 * HIDDEN))
    assert shape == (HIDDEN, 3 * HIDDEN // 4), shape


def test_zero3_hlo_has_gather_scatter_and_sharded_params():
    """sharding_degree=8 (ZeRO-3): params live sharded; the fwd/bwd
    must gather them and the grad/optimizer state must stay sharded
    (all-gather + reduce-scatter or equivalent dynamic-slice pattern)."""
    s = _strategy(dp_degree=1, sharding_degree=8)
    s.sharding = True
    s.sharding_configs['stage'] = 3
    model = _model(seed=1)
    step = _step(model, s)
    ids, lbl = _batch()
    hlo, pshard = step.compiled_hlo(ids, lbl)

    counts = _collective_counts(hlo)
    assert counts['all-gather'] >= 1, counts
    assert counts['reduce-scatter'] + counts['all-reduce'] >= 1, counts

    # a big 2D param is sharded on its leading dim across the 8 devices
    fc = [n for n in pshard if 'fc_in' in n and 'weight' in n]
    assert fc, sorted(pshard)[:8]
    shape = pshard[fc[0]].shard_shape((HIDDEN, 4 * HIDDEN))
    assert np.prod(shape) == HIDDEN * 4 * HIDDEN // 8, shape


@pytest.mark.slow
def test_dp_only_grad_allreduce_present():
    """Plain dp8: exactly the gradient all-reduce family, nothing else —
    and batch input is sharded over dp (data really parallel)."""
    s = _strategy(dp_degree=8)
    model = _model(seed=2)
    step = _step(model, s)
    ids, lbl = _batch()
    hlo, pshard = step.compiled_hlo(ids, lbl)
    counts = _collective_counts(hlo)
    assert counts['all-reduce'] >= 1, counts
    # params replicated under pure dp
    qkv = [n for n in pshard if 'qkv_proj' in n and 'weight' in n]
    shape = pshard[qkv[0]].shard_shape((HIDDEN, 3 * HIDDEN))
    assert shape == (HIDDEN, 3 * HIDDEN), shape


@pytest.mark.slow
def test_fused_loss_dp_mp_memory_and_collectives():
    """fused_loss at BERT-base dims under dp2 x mp4 runs VOCAB-PARALLEL.

    r4 measured GSPMD gathering the vocab dimension for the CE region
    (f32[2048,30522] tiles per device — the cost model preferred
    replicated-vocab compute). Since r5, fleet_train_step constrains the
    fused logits tiles to [rows@dp, vocab@mp]
    (ops/fused_ce.logits_sharding — the c_softmax_with_cross_entropy
    vocab-parallel pattern), which this test pins: NO per-device
    full-vocab f32 tile may appear anywhere in the fused program, the
    dp/mp collectives are present, and peak TEMP memory is strictly
    below the plain path's (measured 435 MB vs ~1011 MB; the unhinted
    fused path was 769 MB)."""
    ids, lbl = _batch()

    def build(fused):
        model = _model(fused_loss=fused)
        step = _step(model, _strategy(dp_degree=2, mp_degree=4))
        compiled = step.compiled_executable(ids, lbl)
        hlo = compiled.as_text()
        counts = _collective_counts(hlo)
        assert counts['all-reduce'] >= 2, counts
        rows = ids.shape[0] * SEQ
        assert not re.search(r'\[%d,%d\]' % (rows, VOCAB), hlo), \
            'replicated-rows full logits'
        if fused:
            assert step._fce_sharding is not None
            # any rank, vocab as the minor dim: a rank-3 gather
            # (f32[2,2048,30522]) must fail this too
            full_vocab = re.findall(r'f32\[[0-9,]+,%d\]' % VOCAB, hlo)
            assert not full_vocab, (
                'vocab axis gathered in the fused CE region: '
                '%s' % sorted(set(full_vocab)))
        return compiled.memory_analysis().temp_size_in_bytes

    fused_tmp = build(True)
    plain_tmp = build(False)
    assert fused_tmp < plain_tmp, (fused_tmp, plain_tmp)


@pytest.mark.slow
def test_fused_loss_multichunk_stays_dp_balanced(monkeypatch):
    """The STRIDED chunk layout (fused_ce chunk i = rows i::n): with the
    row axis dp-sharded and n > 1 chunks, no chunk may concentrate on
    one dp group — a contiguous-chunk regression would force per-chunk
    redistribution, which under pure dp x mp shows up as
    collective-permutes. This program must have ZERO."""
    monkeypatch.setenv('PADDLE_TPU_FUSED_CE_CHUNK', '512')  # 2048 rows -> 4
    ids, lbl = _batch(b=4)
    model = _model(fused_loss=True)
    step = _step(model, _strategy(dp_degree=2, mp_degree=4))
    hlo, _ = step.compiled_hlo(ids, lbl)
    counts = _collective_counts(hlo)
    assert counts['collective-permute'] == 0, counts
    assert counts['all-reduce'] >= 2, counts
    full_vocab = re.findall(r'f32\[[0-9,]+,%d\]' % VOCAB, hlo)
    assert not full_vocab, sorted(set(full_vocab))
