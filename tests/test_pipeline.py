"""Pipeline parallelism (VERDICT r1 item 2 'done' bar): the GPipe
scan+ppermute schedule trains through fleet_train_step and PipelineEngine,
with loss parity vs the non-pipelined run on the 8-device virtual mesh.

Reference parity targets: framework/section_worker.cc:104 (micro-batch
schedule), fleet/meta_parallel/pipeline_parallel.py:109 (train_batch),
parallel_layers/pp_layers.py:62 (SharedLayerDesc tied weights).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.pipeline import (PipelineEngine, make_pp_state,
                                             pipeline_state)
from paddle_tpu.distributed.meta_parallel.pp_layers import (LayerDesc,
                                                            PipelineLayer)
from paddle_tpu.distributed.topology import HybridCommunicateGroup
from paddle_tpu.text.models import GPTConfig, GPTForCausalLM


def _model(seed=0, layers=4):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=layers,
                    num_heads=4, max_position_embeddings=32, dropout=0.0)
    return GPTForCausalLM(cfg)


def _batch(b=8, s=32, vocab=128):
    rng = np.random.RandomState(3)
    ids = paddle.to_tensor(rng.randint(0, vocab, (b, s)).astype(np.int32))
    lbl = paddle.to_tensor(rng.randint(0, vocab, (b, s)).astype(np.int32))
    return ids, lbl


def _strategy(**hybrid):
    s = fleet.DistributedStrategy()
    cfg = {'dp_degree': 8, 'mp_degree': 1, 'pp_degree': 1,
           'sharding_degree': 1, 'sp_degree': 1}
    cfg.update(hybrid)
    s.hybrid_configs = cfg
    return s


def _fleet_step(model, strategy):
    fleet.init(is_collective=True, strategy=strategy)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    return fleet.fleet_train_step(
        model, lambda lg, lb: model.loss(lg, lb), opt, strategy=strategy)


@pytest.mark.partial_auto
def test_gpt_pp4_uneven_layers_matches_dp():
    """pp=4 over 6 layers (not divisible): ghost identity padding keeps
    loss parity with dp (reference uneven seg_method, pp_layers.py:76)."""
    ids, lbl = _batch()
    ref = _fleet_step(_model(seed=17, layers=6), _strategy())
    ref_losses = [float(ref(ids, lbl).numpy()) for _ in range(2)]
    m = _model(seed=17, layers=6)
    step = _fleet_step(m, _strategy(dp_degree=2, pp_degree=4))
    losses = [float(step(ids, lbl).numpy()) for _ in range(2)]
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)


@pytest.mark.partial_auto
def test_gpt_pp4_matches_dp():
    """pp=4 GPT fleet step: same losses as the plain dp run."""
    ids, lbl = _batch()

    ref = _fleet_step(_model(seed=9), _strategy())
    ref_losses = [float(ref(ids, lbl).numpy()) for _ in range(3)]

    s = _strategy(dp_degree=2, pp_degree=4)
    m_pp = _model(seed=9)
    step = _fleet_step(m_pp, s)
    jaxpr = step.trace_jaxpr(ids, lbl)
    assert 'ppermute' in jaxpr  # the schedule is really in the program
    pp_losses = [float(step(ids, lbl).numpy()) for _ in range(3)]
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-4, atol=2e-5)
    # the context is scoped to the step
    assert pipeline_state() is None


@pytest.mark.partial_auto
def test_gpt_pp2_with_recompute_and_bf16():
    """pp composes with recompute (remat inside the stage scan) and amp."""
    ids, lbl = _batch()
    s = _strategy(dp_degree=4, pp_degree=2)
    s.recompute = True
    s.amp = True
    model = _model(seed=4)
    step = _fleet_step(model, s)
    l0 = float(step(ids, lbl).numpy())
    l1 = float(step(ids, lbl).numpy())
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0


@pytest.mark.partial_auto
def test_pipeline_layer_engine_trains():
    """Declarative PipelineLayer through PipelineEngine: heterogeneous
    stage fns via lax.switch, loss decreases, parity vs sequential."""
    hidden = 32

    def make_descs():
        return [LayerDesc(nn.Linear, hidden, hidden),
                LayerDesc(nn.Tanh),
                LayerDesc(nn.Linear, hidden, hidden),
                LayerDesc(nn.Tanh),
                LayerDesc(nn.Linear, hidden, hidden),
                LayerDesc(nn.Tanh),
                LayerDesc(nn.Linear, hidden, hidden),
                LayerDesc(nn.Tanh)]

    import paddle_tpu.nn.functional as F

    def loss_fn(out, labels):
        return F.mse_loss(out, labels)

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, hidden).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, hidden).astype(np.float32))

    # sequential reference (pp degree 1)
    paddle.seed(21)
    ref_layer = PipelineLayer(make_descs(), num_stages=4, loss_fn=loss_fn)
    hcg1 = HybridCommunicateGroup(dp_degree=8)
    opt_ref = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=ref_layer.parameters())
    eng_ref = PipelineEngine(ref_layer, opt_ref, hcg1)
    ref_losses = [float(eng_ref.step(x, y).numpy()) for _ in range(4)]

    # pipelined (pp=4 over the first mesh axis arrangement dp2xpp4)
    paddle.seed(21)
    layer = PipelineLayer(make_descs(), num_stages=4, loss_fn=loss_fn)
    hcg = HybridCommunicateGroup(dp_degree=2, pp_degree=4)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=layer.parameters())
    eng = PipelineEngine(layer, opt, hcg)
    losses = [float(eng.step(x, y).numpy()) for _ in range(4)]

    assert losses[-1] < losses[0]
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=1e-5)


def test_pipeline_blocks_uneven_split_matches_sequential():
    """4 layers over 3 stages (r3 raised here): ghost identity padding
    keeps the pipelined forward equal to the sequential one."""
    import jax
    from jax.sharding import Mesh
    from paddle_tpu.distributed.pipeline import pipeline_blocks
    model = _model(layers=4)
    model.eval()
    mesh = Mesh(np.array(jax.devices()[:3]), ('pp',))
    st = make_pp_state(mesh, n_stages=3)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(6, 8, 64).astype(np.float32))
    out = pipeline_blocks(model.gpt.h, x, st).numpy()
    ref = x
    for blk in model.gpt.h:
        ref = blk(ref)
    np.testing.assert_allclose(out, ref.numpy(), rtol=2e-4, atol=2e-5)
