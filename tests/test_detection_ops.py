"""Detection-op tranche (VERDICT r2 item 8) through the OpTest pattern:
numpy references written independently of the jnp implementations.

Reference parity targets: operators/detection/{matrix_nms_op.cc,
multiclass_nms_op.cc, iou_similarity_op.cc, box_clip_op.cc,
sigmoid_focal_loss_op.cc, anchor_generator_op.cc, bipartite_match_op.cc}.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import detection as D

from op_test import OpTest


def _np_iou(a, b):
    area = lambda x: np.maximum(x[..., 2] - x[..., 0], 0) * \
        np.maximum(x[..., 3] - x[..., 1], 0)
    out = np.zeros((len(a), len(b)), np.float64)
    for i in range(len(a)):
        for j in range(len(b)):
            lt = np.maximum(a[i, :2], b[j, :2])
            rb = np.minimum(a[i, 2:], b[j, 2:])
            wh = np.maximum(rb - lt, 0)
            inter = wh[0] * wh[1]
            u = area(a[i]) + area(b[j]) - inter
            out[i, j] = inter / max(u, 1e-10)
    return out


class TestIouSimilarity(OpTest):
    fn = staticmethod(D.iou_similarity)

    def setup(self):
        rng = np.random.RandomState(0)
        a = rng.rand(5, 4).astype(np.float32)
        b = rng.rand(7, 4).astype(np.float32)
        a[:, 2:] += a[:, :2]
        b[:, 2:] += b[:, :2]
        self.inputs = {'x': a, 'y': b}

    @staticmethod
    def ref(x, y):
        return _np_iou(x, y)

    def test(self):
        self.setup()
        self.check_output()


class TestBoxClip(OpTest):
    fn = staticmethod(D.box_clip)

    def setup(self):
        rng = np.random.RandomState(1)
        boxes = (rng.rand(2, 6, 4) * 60 - 10).astype(np.float32)
        im = np.asarray([[40.0, 50.0], [30.0, 30.0]], np.float32)
        self.inputs = {'input': boxes, 'im_shape': im}

    @staticmethod
    def ref(input, im_shape):
        out = np.empty_like(input)
        for b in range(input.shape[0]):
            h, w = im_shape[b]
            out[b, :, 0] = np.clip(input[b, :, 0], 0, w - 1)
            out[b, :, 1] = np.clip(input[b, :, 1], 0, h - 1)
            out[b, :, 2] = np.clip(input[b, :, 2], 0, w - 1)
            out[b, :, 3] = np.clip(input[b, :, 3], 0, h - 1)
        return out

    def test(self):
        self.setup()
        self.check_output()


class TestSigmoidFocalLoss(OpTest):
    fn = staticmethod(D.sigmoid_focal_loss)
    attrs = {'alpha': 0.25, 'gamma': 2.0, 'reduction': 'sum'}

    def setup(self):
        rng = np.random.RandomState(2)
        self.inputs = {
            'logit': rng.randn(8, 5).astype(np.float32),
            'label': (rng.rand(8, 5) < 0.2).astype(np.float32),
        }

    @staticmethod
    def ref(logit, label, alpha=0.25, gamma=2.0, reduction='sum'):
        p = 1.0 / (1.0 + np.exp(-logit))
        ce = -(label * np.log(p) + (1 - label) * np.log(1 - p))
        p_t = p * label + (1 - p) * (1 - label)
        a_t = alpha * label + (1 - alpha) * (1 - label)
        return np.sum(a_t * (1 - p_t) ** gamma * ce)

    def test(self):
        self.setup()
        self.check_output()
        self.check_grad(['logit'])


def test_anchor_generator_shapes_and_values():
    x = paddle.to_tensor(np.zeros((1, 8, 3, 4), np.float32))
    anchors, variances = D.anchor_generator(
        x, anchor_sizes=[32, 64], aspect_ratios=[1.0],
        stride=[16.0, 16.0], offset=0.5)
    assert anchors.shape == [3, 4, 2, 4]
    a = anchors.numpy()
    # first pixel center = (0.5*16, 0.5*16) = (8, 8); size-32 square anchor
    np.testing.assert_allclose(a[0, 0, 0], [8 - 16, 8 - 16, 8 + 16, 8 + 16])
    v = variances.numpy()
    np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


def test_bipartite_match_greedy():
    d = np.asarray([[[0.9, 0.1, 0.3],
                     [0.8, 0.7, 0.2]]], np.float32)  # [1, 2 rows, 3 cols]
    idx, dist = D.bipartite_match(d)
    idx = idx.numpy()[0]
    dist = dist.numpy()[0]
    # greedy: (row0,col0,0.9) then row1's best remaining col1 (0.7)
    assert idx[0] == 0 and idx[1] == 1 and idx[2] == -1
    np.testing.assert_allclose(dist[:2], [0.9, 0.7])


def _nms_numpy(boxes, scores, score_th, iou_th, keep_top_k):
    """Independent per-class hard NMS reference."""
    C, M = scores.shape
    results = []
    for c in range(1, C):  # 0 = background
        order = np.argsort(-scores[c])
        kept = []
        for i in order:
            if scores[c, i] <= score_th:
                continue
            ok = True
            for j in kept:
                if _np_iou(boxes[i:i + 1], boxes[j:j + 1])[0, 0] > iou_th:
                    ok = False
                    break
            if ok:
                kept.append(i)
        for i in kept:
            results.append((c, scores[c, i], *boxes[i]))
    results.sort(key=lambda r: -r[1])
    return results[:keep_top_k]


def test_multiclass_nms_matches_reference():
    rng = np.random.RandomState(3)
    M = 12
    boxes = rng.rand(1, M, 4).astype(np.float32)
    boxes[..., 2:] = boxes[..., :2] + 0.3 * rng.rand(1, M, 2)
    scores = rng.rand(1, 3, M).astype(np.float32)

    out, rois_num = D.multiclass_nms(
        paddle.to_tensor(boxes), paddle.to_tensor(scores),
        score_threshold=0.2, nms_threshold=0.4, nms_top_k=M, keep_top_k=10)
    out = out.numpy()
    n = int(rois_num.numpy()[0])

    ref = _nms_numpy(boxes[0], scores[0], 0.2, 0.4, 10)
    assert n == len(ref)
    for row, (c, s, x1, y1, x2, y2) in zip(out[:n], ref):
        assert int(row[0]) == c
        np.testing.assert_allclose(row[1], s, rtol=1e-5)
        np.testing.assert_allclose(row[2:], [x1, y1, x2, y2], rtol=1e-5)


def _matrix_nms_numpy(boxes, scores, score_th, post_th, keep_top_k,
                      use_gaussian, sigma):
    """Independent matrix-NMS reference (SOLOv2 decay)."""
    C, M = scores.shape
    results = []
    for c in range(1, C):
        idx = [i for i in range(M) if scores[c, i] > score_th]
        idx.sort(key=lambda i: -scores[c, i])
        if not idx:
            continue
        ious = _np_iou(boxes[idx], boxes[idx])
        for jj, j in enumerate(idx):
            decay = 1.0
            for ii in range(jj):
                comp = max((ious[ll, ii] for ll in range(ii)), default=0.0)
                if use_gaussian:
                    d = np.exp(-(ious[jj, ii] ** 2 - comp ** 2) / sigma)
                else:
                    d = (1 - ious[jj, ii]) / (1 - comp)
                decay = min(decay, d)
            s = scores[c, j] * decay
            if s > post_th:
                results.append((c, s, *boxes[j]))
    results.sort(key=lambda r: -r[1])
    return results[:keep_top_k]


@pytest.mark.parametrize('use_gaussian', [False, True])
def test_matrix_nms_matches_reference(use_gaussian):
    rng = np.random.RandomState(4)
    M = 10
    boxes = rng.rand(1, M, 4).astype(np.float32)
    boxes[..., 2:] = boxes[..., :2] + 0.4 * rng.rand(1, M, 2)
    scores = rng.rand(1, 3, M).astype(np.float32)

    out, index, rois_num = D.matrix_nms(
        paddle.to_tensor(boxes), paddle.to_tensor(scores),
        score_threshold=0.2, post_threshold=0.1, nms_top_k=M, keep_top_k=8,
        use_gaussian=use_gaussian, gaussian_sigma=2.0, return_index=True)
    out = out.numpy()
    n = int(rois_num.numpy()[0])

    ref = _matrix_nms_numpy(boxes[0], scores[0], 0.2, 0.1, 8,
                            use_gaussian, 2.0)
    assert n == len(ref)
    for row, (c, s, x1, y1, x2, y2) in zip(out[:n], ref):
        assert int(row[0]) == c
        np.testing.assert_allclose(row[1], s, rtol=1e-4)
        np.testing.assert_allclose(row[2:], [x1, y1, x2, y2], rtol=1e-5)
    # padded rows carry label -1
    assert np.all(out[n:, 0] == -1)


def test_nms_categories_filter():
    """`categories` restricts which class ids may appear in the kept set
    (reference vision/ops.py nms contract)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision.ops import nms

    boxes = paddle.to_tensor(np.asarray(
        [[0, 0, 10, 10], [20, 20, 30, 30], [40, 40, 50, 50]], np.float32))
    scores = paddle.to_tensor(np.asarray([0.9, 0.8, 0.7], np.float32))
    cats = paddle.to_tensor(np.asarray([0, 1, 2], np.int64))
    keep = nms(boxes, 0.5, scores=scores, category_idxs=cats,
               categories=[0, 2]).numpy()
    assert set(keep.tolist()) == {0, 2}


def test_nms_categories_requires_idxs():
    import numpy as np
    import pytest
    import paddle_tpu as paddle
    from paddle_tpu.vision.ops import nms

    boxes = paddle.to_tensor(np.asarray([[0, 0, 1, 1]], np.float32))
    with pytest.raises(ValueError, match='category_idxs'):
        nms(boxes, 0.5, scores=paddle.to_tensor(
            np.asarray([0.5], np.float32)), categories=[0])


def test_roi_align_sampling_ratio_and_yolo_box_iou_aware():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision.ops import roi_align, yolo_box

    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(1, 2, 8, 8).astype(np.float32))
    boxes = paddle.to_tensor(np.asarray([[1.0, 1.0, 6.0, 6.0]], np.float32))
    num = paddle.to_tensor(np.asarray([1], np.int32))
    o1 = roi_align(x, boxes, num, 2, sampling_ratio=1).numpy()
    o2 = roi_align(x, boxes, num, 2, sampling_ratio=4).numpy()
    assert o1.shape == o2.shape == (1, 2, 2, 2)
    assert not np.allclose(o1, o2)  # denser sampling changes the average
    # averaging many samples approaches the analytic bin mean: compare
    # s=4 and s=8 are closer together than s=1 and s=8
    o3 = roi_align(x, boxes, num, 2, sampling_ratio=8).numpy()
    assert np.abs(o2 - o3).mean() < np.abs(o1 - o3).mean()

    na, cls, h = 2, 3, 4
    head = np.random.RandomState(1).randn(
        1, na * (5 + cls) + na, h, h).astype(np.float32)
    img_size = paddle.to_tensor(np.asarray([[64, 64]], np.int32))
    kw = dict(anchors=[10, 13, 16, 30], class_num=cls, conf_thresh=0.0,
              downsample_ratio=16)
    b_plain, s_plain = yolo_box(paddle.to_tensor(head[:, na:]), img_size,
                                **kw)
    b_iou, s_iou = yolo_box(paddle.to_tensor(head), img_size,
                            iou_aware=True, iou_aware_factor=0.5, **kw)
    np.testing.assert_allclose(b_iou.numpy(), b_plain.numpy(), rtol=1e-5)
    assert not np.allclose(s_iou.numpy(), s_plain.numpy())


def test_set_state_dict_unstructured_names():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn

    src = nn.Linear(3, 2)
    dst = nn.Linear(3, 2)
    ckpt = {getattr(p, 'name', None) or k: p
            for k, p in src.state_dict().items()}
    missing, unexpected = dst.set_state_dict(ckpt,
                                             use_structured_name=False)
    assert not missing, missing
    np.testing.assert_allclose(dst.weight.numpy(), src.weight.numpy())


def test_generate_proposals_clips_to_image():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision.ops import generate_proposals

    rng = np.random.RandomState(0)
    n, a_count, h, w = 1, 3, 4, 4
    scores = paddle.to_tensor(rng.rand(n, a_count, h, w).astype(np.float32))
    # large positive deltas push raw boxes far outside the image
    deltas = paddle.to_tensor(
        np.full((n, a_count * 4, h, w), 2.0, np.float32))
    anchors = rng.rand(h, w, a_count, 4).astype(np.float32) * 8
    anchors[..., 2:] += 16
    variances = np.ones_like(anchors)
    img = paddle.to_tensor(np.asarray([[20.0, 24.0]], np.float32))  # H, W
    rois, roi_scores = generate_proposals(
        scores, deltas, img, paddle.to_tensor(anchors),
        paddle.to_tensor(variances), min_size=0.0)
    r = rois.numpy()
    assert (r[:, 0] >= 0).all() and (r[:, 1] >= 0).all()
    assert (r[:, 2] <= 24.0).all() and (r[:, 3] <= 20.0).all()

    # pixel_offset tightens the clip bound to dim-1
    rois_po, _ = generate_proposals(
        scores, deltas, img, paddle.to_tensor(anchors),
        paddle.to_tensor(variances), min_size=0.0, pixel_offset=True)
    rp = rois_po.numpy()
    assert (rp[:, 2] <= 23.0).all() and (rp[:, 3] <= 19.0).all()

    # eta < 1 decays the NMS threshold -> at most as many survivors
    base_n = len(r)
    rois_eta, _ = generate_proposals(
        scores, deltas, img, paddle.to_tensor(anchors),
        paddle.to_tensor(variances), min_size=0.0, nms_thresh=0.9,
        eta=0.5)
    rois_90, _ = generate_proposals(
        scores, deltas, img, paddle.to_tensor(anchors),
        paddle.to_tensor(variances), min_size=0.0, nms_thresh=0.9)
    assert len(rois_eta.numpy()) <= len(rois_90.numpy())


def test_distribute_fpn_proposals_batched_counts_and_offset():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision.ops import distribute_fpn_proposals

    # two images: img0 has a small + a large roi, img1 one small roi
    rois = paddle.to_tensor(np.asarray(
        [[0, 0, 20, 20], [0, 0, 200, 200], [0, 0, 16, 16]], np.float32))
    rn = paddle.to_tensor(np.asarray([2, 1], np.int32))
    outs, restore, per_level = distribute_fpn_proposals(
        rois, 2, 5, 4, 224, rois_num=rn)
    counts = [n.numpy() for n in per_level]
    # per-image counts per level: each entry has len == n_images
    assert all(len(c) == 2 for c in counts)
    total = np.stack(counts).sum(0)
    np.testing.assert_array_equal(total, [2, 1])
    # restore index is a permutation of all rois
    assert sorted(restore.numpy().reshape(-1).tolist()) == [0, 1, 2]

    # pixel_offset shifts the level split for boxes near a threshold
    edge = paddle.to_tensor(np.asarray([[0, 0, 111.5, 111.5]], np.float32))
    a = distribute_fpn_proposals(edge, 2, 5, 4, 112)[0]
    b = distribute_fpn_proposals(edge, 2, 5, 4, 112, pixel_offset=True)[0]
    sizes_a = [len(t.numpy()) for t in a]
    sizes_b = [len(t.numpy()) for t in b]
    assert sizes_a != sizes_b


def test_box_coder_axis_and_prior_box_order():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision.ops import box_coder, prior_box

    priors = np.asarray([[0, 0, 10, 10], [10, 10, 30, 30]], np.float32)
    var = [1.0, 1.0, 1.0, 1.0]
    # reference axis semantics: axis=0 with target [N, M, 4] means the
    # M priors broadcast ALONG axis 0 (priors ride dim 1)
    deltas = np.zeros((3, 2, 4), np.float32)
    out0 = box_coder(paddle.to_tensor(priors), var,
                     paddle.to_tensor(deltas),
                     code_type='decode_center_size', axis=0).numpy()
    for i in range(3):
        np.testing.assert_allclose(out0[i], priors, rtol=1e-5)
    # axis=1: priors ride dim 0 of a [M, N, 4] target
    deltas1 = np.zeros((2, 3, 4), np.float32)
    out1 = box_coder(paddle.to_tensor(priors), var,
                     paddle.to_tensor(deltas1),
                     code_type='decode_center_size', axis=1).numpy()
    for j in range(3):
        np.testing.assert_allclose(out1[:, j], priors, rtol=1e-5)

    # encode: every target against every prior -> [N, M, 4]; zero offset
    # exactly when the target IS that prior
    targets = np.asarray([[0, 0, 10, 10], [10, 10, 30, 30],
                          [5, 5, 15, 15]], np.float32)
    enc = box_coder(paddle.to_tensor(priors), var,
                    paddle.to_tensor(targets),
                    code_type='encode_center_size').numpy()
    assert enc.shape == (3, 2, 4)
    np.testing.assert_allclose(enc[0, 0], 0.0, atol=1e-6)
    np.testing.assert_allclose(enc[1, 1], 0.0, atol=1e-6)
    assert np.abs(enc[2]).sum() > 0

    feat = paddle.to_tensor(np.zeros((1, 8, 2, 2), np.float32))
    img = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
    kw = dict(min_sizes=[16.0], max_sizes=[32.0],
              aspect_ratios=[1.0, 2.0])
    b_def, _ = prior_box(feat, img, **kw)
    b_mm, _ = prior_box(feat, img, min_max_aspect_ratios_order=True, **kw)
    d, m = b_def.numpy().reshape(-1, 4), b_mm.numpy().reshape(-1, 4)
    assert d.shape == m.shape
    assert not np.allclose(d, m)          # ordering differs
    # same box SET either way
    np.testing.assert_allclose(np.sort(d, axis=0), np.sort(m, axis=0),
                               rtol=1e-5)
