"""PP-YOLOv2 forward path (BASELINE config 4 / VERDICT r2 item 8): the
detector runs eager, decodes through yolo_box, post-processes with
matrix_nms, and round-trips through the AnalysisPredictor facade.
"""
import pytest
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision.models import yolo as yolo_mod


def _model():
    paddle.seed(0)
    return yolo_mod.ppyolov2(num_classes=6, width=8, img_size=64)


@pytest.mark.slow
def test_ppyolov2_train_mode_shapes():
    model = _model()
    model.train()
    x = paddle.to_tensor(
        np.random.RandomState(0).rand(1, 3, 64, 64).astype(np.float32))
    outs = model(x)
    assert len(outs) == 3
    # 3 anchors * (5 + 6 classes) = 33 channels; strides 8/16/32
    assert outs[0].shape == [1, 33, 8, 8]
    assert outs[1].shape == [1, 33, 4, 4]
    assert outs[2].shape == [1, 33, 2, 2]


@pytest.mark.slow
def test_ppyolov2_eval_decode_and_matrix_nms():
    model = _model()
    model.eval()
    x = paddle.to_tensor(
        np.random.RandomState(1).rand(2, 3, 64, 64).astype(np.float32))
    boxes, scores = model(x)
    m = (8 * 8 + 4 * 4 + 2 * 2) * 3
    assert boxes.shape == [2, m, 4]
    assert scores.shape == [2, 6, m]
    out, rois_num = model.postprocess(boxes, scores, keep_top_k=20)
    assert out.shape == [2 * 20, 6]
    assert rois_num.shape == [2]
    o = out.numpy()
    n0 = int(rois_num.numpy()[0])
    # valid rows carry a real label and in-bounds boxes
    if n0:
        assert np.all(o[:n0, 0] >= 0)
        assert np.all(o[:n0, 2:] >= 0) and np.all(o[:n0, 2:] <= 63)
    assert np.all(o[n0:20, 0] == -1)


@pytest.mark.slow
def test_ppyolov2_through_predictor(tmp_path):
    from paddle_tpu import jit
    from paddle_tpu import inference

    model = _model()
    model.eval()
    path = str(tmp_path / 'ppyolov2')
    jit.save(model, path)

    config = inference.Config(path)
    pred = inference.create_predictor(config)
    x = np.random.RandomState(2).rand(1, 3, 64, 64).astype(np.float32)
    names = pred.get_input_names()
    pred.get_input_handle(names[0]).copy_from_cpu(x)
    pred.run()
    boxes = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    m = (8 * 8 + 4 * 4 + 2 * 2) * 3
    assert boxes.shape == (1, m, 4)

    # predictor output matches the eager forward
    eb, _ = model(paddle.to_tensor(x))
    np.testing.assert_allclose(boxes, eb.numpy(), rtol=2e-4, atol=2e-4)


def test_yolo_loss_ignore_thresh_and_scale():
    """ignore_thresh masks high-IoU negatives out of the objectness loss
    (loss must be <= the fully-counted ignore_thresh=1.01 variant), and
    gt_score weights positive terms."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision.ops import yolo_loss

    rng = np.random.RandomState(0)
    n, na, cls, h = 2, 3, 4, 5
    x = paddle.to_tensor(rng.randn(n, na * (5 + cls), h, h)
                         .astype(np.float32))
    gtb = paddle.to_tensor(
        np.asarray([[[0.5, 0.5, 0.3, 0.4]], [[0.3, 0.6, 0.2, 0.2]]],
                   np.float32))
    gtl = paddle.to_tensor(np.zeros((n, 1), np.int64))
    anchors = [10, 13, 16, 30, 33, 23]
    kw = dict(anchors=anchors, anchor_mask=[0, 1, 2], class_num=cls,
              downsample_ratio=32)
    full = float(yolo_loss(x, gtb, gtl, ignore_thresh=1.01, **kw)
                 .numpy().sum())
    lenient = float(yolo_loss(x, gtb, gtl, ignore_thresh=0.0, **kw)
                    .numpy().sum())
    assert lenient < full  # thresh 0 drops every negative's obj term

    half = paddle.to_tensor(np.full((n, 1), 0.5, np.float32))
    weighted = float(yolo_loss(x, gtb, gtl, ignore_thresh=1.01,
                               gt_score=half, **kw).numpy().sum())
    assert weighted < full
