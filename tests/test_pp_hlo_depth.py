"""1F1B pipeline at realistic depth gets HLO-level assertions
(VERDICT r4 weak #8: the 2-layer budget choice in test_hlo_collectives
never exercised pp structure at depth).

dp2 x pp4 over 8 BERT-width layers, 1F1B with 8 microbatches: the
compiled (post-SPMD) program must contain the pipeline's stage-boundary
transfers (collective-permute per microbatch per boundary) and the dp
gradient reduction, and the step must train. The reference analog is
the 1F1B program-transform assertions
(test_fleet_pipeline_meta_optimizer.py family, SURVEY §4.2)."""
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

# dp2 x pp4 takes the legacy partial-auto shard_map path
pytestmark = pytest.mark.partial_auto

HIDDEN, HEADS, VOCAB, SEQ = 768, 12, 30522, 256
LAYERS, PP, MICRO = 8, 4, 8


def test_1f1b_depth_hlo_structure():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=HIDDEN,
                    num_layers=LAYERS, num_heads=HEADS,
                    max_position_embeddings=SEQ, dropout=0.0)
    model = GPTForCausalLM(cfg)
    s = fleet.DistributedStrategy()
    s.hybrid_configs = {'dp_degree': 2, 'mp_degree': 1, 'pp_degree': PP,
                        'sharding_degree': 1, 'sp_degree': 1}
    s.pipeline = True
    s.pipeline_configs = {'accumulate_steps': MICRO,
                          'schedule_mode': '1F1B'}
    fleet.init(is_collective=True, strategy=s)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = fleet.fleet_train_step(
        model, lambda lg, lb: model.loss(lg, lb), opt, strategy=s)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, VOCAB, (8, SEQ)).astype(np.int32))
    lbl = paddle.to_tensor(rng.randint(0, VOCAB, (8, SEQ)).astype(np.int32))
    compiled = step.compiled_executable(ids, lbl)
    hlo = compiled.as_text()

    cp = len(re.findall('collective-permute', hlo))
    ar = len(re.findall('all-reduce', hlo))
    # fwd sends one boundary activation per microbatch per stage
    # boundary, bwd sends the cotangent back: >= MICRO * (PP - 1)
    # collective-permutes must survive into the partitioned program (a
    # schedule that silently serializes on gathered activations loses
    # them; measured 218 at the 8-layer/8-micro shape)
    assert cp >= MICRO * (PP - 1), cp
    assert ar >= 1, ar  # dp grad reduction
    loss = float(step(ids, lbl).numpy())
    assert np.isfinite(loss), loss
