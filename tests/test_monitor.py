"""Observability subsystem tests (paddle_tpu/monitor/).

The load-bearing assertions:
  1. counters are EXACT under heavy thread contention (the chaos
     harness uses them as a correctness oracle, so ~N is a fail);
  2. the /metrics body is valid Prometheus text exposition, verified by
     an independent parser in this file, not by string-matching what the
     exporter happens to emit;
  3. the disabled-registry fast path adds no measurable overhead to
     ResilientChannel.call (generous bound — this guards the design,
     not a microbenchmark number);
  4. the dryrun telemetry snapshot round-trips through
     tools/check_metrics_snapshot.py against the committed baseline.
"""
import json
import math
import re
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from paddle_tpu import monitor
from paddle_tpu.monitor import (MetricRegistry, MetricsServer,
                                RuntimeSampler, exponential_buckets,
                                schema_of, to_dict, to_prometheus)

REPO = __file__.rsplit('/tests/', 1)[0]


# -- registry semantics ------------------------------------------------------

def test_counter_gauge_histogram_basics():
    r = MetricRegistry()
    c = r.counter('ops_total', 'ops', ('kind',))
    c.labels('read').inc()
    c.labels('read').inc(2.5)
    c.labels(kind='write').inc()
    assert c.labels('read').value() == 3.5
    assert c.labels('write').value() == 1.0
    with pytest.raises(ValueError):
        c.labels('read').inc(-1)          # counters only go up

    g = r.gauge('depth')                  # unlabeled: family IS the child
    g.set(4)
    g.dec()
    assert g.value() == 3.0

    h = r.histogram('lat', 'x', buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    count, total = h.value()
    assert count == 3 and total == pytest.approx(5.55)


def test_registry_get_or_create_and_conflicts():
    r = MetricRegistry()
    a = r.counter('x_total', 'x', ('k',))
    assert r.counter('x_total', 'x', ('k',)) is a     # same family back
    with pytest.raises(ValueError):
        r.gauge('x_total')                            # type conflict
    with pytest.raises(ValueError):
        r.counter('x_total', 'x', ('other',))         # labelname conflict
    with pytest.raises(ValueError):
        r.counter('bad name!')                        # invalid chars
    with pytest.raises(ValueError):
        a.labels('v1', 'v2')                          # label arity


def test_disabled_registry_freezes_all_updates():
    r = MetricRegistry(enabled=False)
    c = r.counter('n_total')
    h = r.histogram('h', buckets=(1.0,))
    g = r.gauge('g')
    c.inc(); g.set(9); h.observe(0.5)
    assert c.value() == 0.0
    assert g.value() == 0.0
    assert h.value() == (0, 0.0)
    r.enable()
    c.inc()
    assert c.value() == 1.0


def test_exponential_buckets():
    assert exponential_buckets(0.001, 2, 4) == (0.001, 0.002, 0.004, 0.008)
    with pytest.raises(ValueError):
        exponential_buckets(0, 2, 4)
    with pytest.raises(ValueError):
        exponential_buckets(0.1, 1.0, 4)


def test_counter_exact_totals_under_thread_contention():
    """8 threads x 10k labeled increments: totals must be EXACT — the
    chaos oracle in test_resilience.py depends on it."""
    r = MetricRegistry()
    fam = r.counter('stress_total', 'x', ('worker_mod',))
    n_threads, n_incs = 8, 10_000
    start = threading.Barrier(n_threads)

    def worker(w):
        child = fam.labels(str(w % 2))    # contended: 2 children, 8 threads
        start.wait()
        for _ in range(n_incs):
            child.inc()

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert fam.labels('0').value() == n_threads // 2 * n_incs
    assert fam.labels('1').value() == n_threads // 2 * n_incs


# -- Prometheus text exposition, validated by an independent parser ----------

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})? '
    r'(?P<value>[0-9.eE+-]+|\+Inf|-Inf|NaN)$')
_LABEL_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"$')


def _parse_exposition(text):
    """Minimal strict parser: returns {name: type} and
    [(name, {label: value}, float)] samples; raises on malformed lines."""
    types = {}
    samples = []
    for line in text.strip().splitlines():
        if line.startswith('# HELP '):
            continue
        if line.startswith('# TYPE '):
            _, _, name, kind = line.split(' ', 3)
            assert kind in ('counter', 'gauge', 'histogram'), line
            types[name] = kind
            continue
        m = _SAMPLE_RE.match(line)
        assert m, 'malformed sample line: %r' % line
        labels = {}
        if m.group('labels'):
            for pair in m.group('labels').split(','):
                assert _LABEL_RE.match(pair), 'bad label pair: %r' % pair
                k, v = pair.split('=', 1)
                labels[k] = v.strip('"')
        v = m.group('value')
        val = math.inf if v == '+Inf' else \
            -math.inf if v == '-Inf' else float(v)
        samples.append((m.group('name'), labels, val))
    return types, samples


def test_prometheus_exposition_is_valid_and_consistent():
    r = MetricRegistry()
    c = r.counter('req_total', 'requests\nwith newline', ('ep', 'op'))
    c.labels('h:1', 'get').inc(3)
    r.gauge('temp', 'has "quotes" \\ backslash').set(-1.5)
    h = r.histogram('lat_seconds', 'latency', ('ep',),
                    buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.7, 20.0):
        h.labels('h:1').observe(v)

    text = to_prometheus(r)
    types, samples = _parse_exposition(text)
    assert types == {'req_total': 'counter', 'temp': 'gauge',
                     'lat_seconds': 'histogram'}
    by = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
    assert by[('req_total', (('ep', 'h:1'), ('op', 'get')))] == 3
    assert by[('temp', ())] == -1.5
    # histogram: cumulative buckets, +Inf == count, sum matches
    buckets = [(l['le'], v) for n, l, v in samples
               if n == 'lat_seconds_bucket']
    assert [v for _, v in buckets] == sorted(v for _, v in buckets)
    assert buckets[-1] == ('+Inf', 4)
    assert by[('lat_seconds_count', (('ep', 'h:1'),))] == 4
    assert by[('lat_seconds_sum', (('ep', 'h:1'),))] == \
        pytest.approx(21.25)
    # le values in ascending numeric order
    les = [float(le) for le, _ in buckets[:-1]]
    assert les == sorted(les) == [0.1, 1.0, 10.0]


def test_metrics_server_scrape_and_healthz():
    r = MetricRegistry()
    r.counter('pings_total').inc(7)
    with MetricsServer(registry=r) as srv:
        body = urllib.request.urlopen(srv.url + '/metrics',
                                      timeout=5).read().decode()
        types, samples = _parse_exposition(body)
        assert ('pings_total', {}, 7.0) in samples

        health = json.loads(urllib.request.urlopen(
            srv.url + '/healthz', timeout=5).read().decode())
        assert health['status'] == 'ok'
        assert health['uptime_s'] >= 0

        snap = json.loads(urllib.request.urlopen(
            srv.url + '/metrics.json', timeout=5).read().decode())
        assert snap['pings_total']['samples'][0]['value'] == 7.0

        # HEAD (load-balancer probes) must get 200 + headers, not 501
        for path in ('/healthz', '/metrics'):
            req = urllib.request.Request(srv.url + path, method='HEAD')
            resp = urllib.request.urlopen(req, timeout=5)
            assert resp.status == 200
            assert int(resp.headers['Content-Length']) > 0
            assert resp.read() == b''

        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(srv.url + '/nope', timeout=5)
    with pytest.raises(RuntimeError):
        srv.port                           # stopped server has no port


def test_metrics_server_readyz_tracks_readiness_callable():
    """/healthz is liveness (always 200 while serving); /readyz is
    readiness and flips to 503 when the injected callable says the
    process is draining — without taking /healthz down with it."""
    ready = {'ok': True}
    r = MetricRegistry()
    with MetricsServer(registry=r, readiness=lambda: ready['ok']) as srv:
        body = json.loads(urllib.request.urlopen(
            srv.url + '/readyz', timeout=5).read().decode())
        assert body['status'] == 'ready'

        ready['ok'] = False
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + '/readyz', timeout=5)
        assert ei.value.code == 503
        assert json.loads(ei.value.read().decode())['status'] == 'draining'
        # liveness unaffected: LB must keep the pod, only unrouting it
        health = json.loads(urllib.request.urlopen(
            srv.url + '/healthz', timeout=5).read().decode())
        assert health['status'] == 'ok'

        # HEAD probes mirror GET status on the new route
        req = urllib.request.Request(srv.url + '/readyz', method='HEAD')
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 503
        ready['ok'] = True
        resp = urllib.request.urlopen(req, timeout=5)
        assert resp.status == 200
        assert resp.read() == b''

    # no readiness callable configured: /readyz degenerates to liveness
    with MetricsServer(registry=r) as srv:
        body = json.loads(urllib.request.urlopen(
            srv.url + '/readyz', timeout=5).read().decode())
        assert body['status'] == 'ready'


# -- runtime sampler ---------------------------------------------------------

def test_runtime_sampler_populates_gauges():
    r = MetricRegistry()
    s = RuntimeSampler(registry=r, interval=3600)
    s.sample_once()
    snap = to_dict(r)
    assert snap['process_resident_bytes']['samples'][0]['value'] > 1e6
    assert snap['jax_device_count']['samples'][0]['value'] == 8  # conftest
    assert snap['jax_live_array_count']['samples'][0]['value'] >= 0
    assert snap['runtime_samples_total']['samples'][0]['value'] == 1

    calls = []
    s.add_source(lambda reg: calls.append(reg))
    s.add_source(lambda reg: 1 / 0)        # broken probe must not kill it
    s.sample_once()
    assert calls == [r]
    assert snap != to_dict(r)              # samples counter advanced


def test_runtime_sampler_thread_start_stop():
    r = MetricRegistry()
    s = RuntimeSampler(registry=r, interval=0.05)
    s.start()
    deadline = time.monotonic() + 5.0
    fam = r.get('runtime_samples_total')
    while fam.value() < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    s.stop()
    assert fam.value() >= 2


# -- disabled-path overhead guard (acceptance criterion) ---------------------

def test_disabled_registry_adds_no_measurable_channel_overhead():
    """ResilientChannel.call against a loopback embedding server, with
    the default registry disabled vs enabled. Disabled does strictly
    less work per call, so its mean must not exceed enabled + a generous
    slack (this is a guard against accidentally putting allocation or
    locking on the disabled path, not a benchmark)."""
    from paddle_tpu.distributed.ps.embedding_service import EmbeddingServer
    from paddle_tpu.distributed.resilience import ResilientChannel

    srv = EmbeddingServer()
    srv.create_table(0, dim=4, seed=0)
    srv.start()
    reg = monitor.default_registry()
    ch = ResilientChannel(srv.endpoint)
    msg = {'op': 'dims', 'table_id': 0}

    def mean_call_s(n=60):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            ch.call(msg)
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return sum(ts[:n // 2]) / (n // 2)   # trimmed: drop GC/sched noise

    try:
        assert reg.enabled
        mean_call_s(10)                      # warm both paths
        enabled = mean_call_s()
        reg.disable()
        try:
            disabled = mean_call_s()
        finally:
            reg.enable()
    finally:
        ch.close()
        srv.stop()
    # generous: 2 ms absolute slack on a loopback call that takes ~100 us
    assert disabled <= enabled + 2e-3, (disabled, enabled)

    # and the disabled single-child fast path is branch-cheap in absolute
    # terms: 100k no-op incs well under a second
    c = MetricRegistry(enabled=False).counter('noop_total')
    t0 = time.perf_counter()
    for _ in range(100_000):
        c.inc()
    assert time.perf_counter() - t0 < 1.0


# -- telemetry snapshot line + schema gate (acceptance criterion) ------------

def test_dryrun_snapshot_passes_committed_baseline(tmp_path):
    """The same helper __graft_entry__ uses produces a line that the CI
    gate accepts against the COMMITTED baseline — so the dryrun and this
    test can only drift together with the baseline file."""
    reg = monitor.telemetry.dryrun_registry(0.25, 2.5, batch=16)
    lines = '\n'.join([
        'dryrun_multichip(8)[dp/mp]: mp=2 loss=2.5000',
        monitor.telemetry.snapshot_line(reg, 8, '[dp/mp]'),
        monitor.telemetry.snapshot_line(reg, 8, '[dp/sp]'),
    ])
    p = tmp_path / 'out.txt'
    p.write_text(lines + '\n')
    proc = subprocess.run(
        [sys.executable, REPO + '/tools/check_metrics_snapshot.py',
         '--text', str(p)], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary['ok'] and summary['configs'] == ['dp/mp', 'dp/sp']
    assert summary['new_unbaselined'] == []

    # a capture-file form works too (the MULTICHIP_r*.json shape)
    cap = tmp_path / 'cap.json'
    cap.write_text(json.dumps({'n_devices': 8, 'tail': lines}))
    proc = subprocess.run(
        [sys.executable, REPO + '/tools/check_metrics_snapshot.py',
         '--new', str(cap)], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_snapshot_gate_fails_when_metric_disappears(tmp_path):
    reg = monitor.telemetry.dryrun_registry(0.25, 2.5, batch=16)
    reg.unregister('train_loss')           # the silent de-instrumentation
    p = tmp_path / 'out.txt'
    p.write_text(monitor.telemetry.snapshot_line(reg, 8, '[dp/mp]') + '\n')
    proc = subprocess.run(
        [sys.executable, REPO + '/tools/check_metrics_snapshot.py',
         '--text', str(p)], capture_output=True, text=True)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    findings = [json.loads(l) for l in proc.stdout.strip().splitlines()]
    assert any(f.get('metric') == 'train_loss'
               and f.get('problem') == 'missing' for f in findings)


def test_snapshot_gate_nothing_to_compare(tmp_path):
    p = tmp_path / 'empty.txt'
    p.write_text('no telemetry here\n')
    proc = subprocess.run(
        [sys.executable, REPO + '/tools/check_metrics_snapshot.py',
         '--text', str(p)], capture_output=True, text=True)
    assert proc.returncode == 2


def test_schema_of_ignores_values_and_label_values():
    r = MetricRegistry()
    c = r.counter('a_total', 'x', ('ep',))
    c.labels('one').inc()
    s1 = schema_of(to_dict(r))
    c.labels('two').inc(99)                # new series, same schema
    assert schema_of(to_dict(r)) == s1


# -- hapi TelemetryCallback --------------------------------------------------

def test_telemetry_callback_records_fit_metrics():
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.hapi.callbacks import TelemetryCallback
    from paddle_tpu.io import Dataset

    class Toy(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            x = np.full((4,), i, np.float32)
            return x, np.zeros((1,), np.float32)

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 1))
    model = paddle.Model(net)
    model.prepare(optimizer=paddle.optimizer.SGD(
        learning_rate=0.01, parameters=net.parameters()),
        loss=nn.MSELoss())
    reg = MetricRegistry()
    cb = TelemetryCallback(registry=reg, sample_every=3)
    model.fit(Toy(), batch_size=4, epochs=2, verbose=0, callbacks=[cb])

    snap = to_dict(reg)
    assert snap['train_steps_total']['samples'][0]['value'] == 4   # 2x2
    assert snap['train_examples_total']['samples'][0]['value'] == 16
    assert snap['train_step_duration_seconds']['samples'][0]['count'] == 4
    assert snap['train_epoch']['samples'][0]['value'] == 1
    assert math.isfinite(snap['train_loss']['samples'][0]['value'])
    # sampler fired (on_train_end guarantees at least one capture)
    assert snap['runtime_samples_total']['samples'][0]['value'] >= 1
    assert snap['process_resident_bytes']['samples'][0]['value'] > 0
