"""Dropout under pipeline and sequence parallelism (VERDICT r3 item 4).

The schedules thread per-step base keys folded with (microbatch, stage,
layer) indices (framework/random.key_scope), so:
  (a) masks differ across microbatches within a step,
  (b) eval mode stays bit-parity with the sequential forward,
  (c) the 1F1B backward's stage recompute rederives identical masks
      (training converges instead of silently corrupting grads).
Reference capability: fleet/meta_parallel/parallel_layers/random.py
(Megatron-style RNG state isolation under pp/mp).
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.pipeline import make_pp_state, pipeline_blocks
from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

# the pp schedules read the stage index via PartitionId inside the
# GSPMD-partitioned step; XLA:CPU's SPMD partitioner rejects it
# ("UNIMPLEMENTED: PartitionId instruction is not supported for SPMD
# partitioning"). Real-TPU runs are unaffected.
_CPU_NO_PARTITION_ID = pytest.mark.skipif(
    jax.default_backend() == 'cpu',
    reason='XLA:CPU SPMD partitioner lacks PartitionId (UNIMPLEMENTED); '
           'runs on TPU')


def _gpt(seed=0, layers=4, dropout=0.1, **kw):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=layers,
                    num_heads=4, max_position_embeddings=32,
                    dropout=dropout, **kw)
    return GPTForCausalLM(cfg)


def _batch(b=8, s=32, vocab=128, seed=3):
    rng = np.random.RandomState(seed)
    ids = paddle.to_tensor(rng.randint(0, vocab, (b, s)).astype(np.int32))
    lbl = paddle.to_tensor(rng.randint(0, vocab, (b, s)).astype(np.int32))
    return ids, lbl


def _strategy(**hybrid):
    s = fleet.DistributedStrategy()
    cfg = {'dp_degree': 8, 'mp_degree': 1, 'pp_degree': 1,
           'sharding_degree': 1, 'sp_degree': 1}
    cfg.update(hybrid)
    s.hybrid_configs = cfg
    return s


def _fleet_step(model, strategy, schedule=None):
    if schedule is not None:
        strategy.pipeline = True
        strategy.pipeline_configs['schedule_mode'] = schedule
    fleet.init(is_collective=True, strategy=strategy)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    return fleet.fleet_train_step(
        model, lambda lg, lb: model.loss(lg, lb), opt, strategy=strategy)


class _DropBlock(nn.Layer):
    """Homogeneous block whose only nondeterminism is dropout."""

    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(16, 16)
        self.drop = nn.Dropout(0.5)

    def forward(self, x):
        return self.drop(self.lin(x))


def _pp_mesh(pp=2):
    import jax
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:pp])
    return Mesh(devs, ('pp',))


class _FnDropBlock(nn.Layer):
    """Dropout via a DIRECT functional call — no nn.Dropout module, no
    float attr. The key threading must not depend on detecting dropout
    structurally (r4 review regression)."""

    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(16, 16)

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        return F.dropout(self.lin(x), p=0.5,
                         training=self.training)


def test_gpipe_functional_dropout_masks_differ_per_microbatch():
    """F.dropout called directly inside a pp block still gets per-
    microbatch masks (keys thread unconditionally, not by heuristic)."""
    paddle.seed(13)
    blocks = [_FnDropBlock() for _ in range(2)]
    for b in blocks:
        b.train()
    state = make_pp_state(_pp_mesh(2), n_stages=2, n_micro=4)
    rng = np.random.RandomState(2)
    row = rng.randn(2, 16).astype(np.float32)
    x = paddle.to_tensor(np.tile(row, (4, 1)))
    out = pipeline_blocks(blocks, x, state).numpy()
    mbs = out.reshape(4, 2, 16)
    assert all(not np.allclose(mbs[i], mbs[j])
               for i in range(4) for j in range(i + 1, 4)), \
        'functional dropout repeated masks across microbatches'


def test_gpipe_dropout_masks_differ_per_microbatch():
    """Identical microbatch contents -> different outputs per microbatch
    iff the mask is folded per microbatch (the r3 behavior repeated one
    mask for every tick)."""
    paddle.seed(11)
    blocks = [_DropBlock() for _ in range(2)]
    for b in blocks:
        b.train()
    state = make_pp_state(_pp_mesh(2), n_stages=2, n_micro=4)
    rng = np.random.RandomState(0)
    row = rng.randn(2, 16).astype(np.float32)
    x = paddle.to_tensor(np.tile(row, (4, 1)))  # 4 identical microbatches
    out = pipeline_blocks(blocks, x, state).numpy()
    mbs = out.reshape(4, 2, 16)
    diffs = [not np.allclose(mbs[i], mbs[j])
             for i in range(4) for j in range(i + 1, 4)]
    assert all(diffs), 'dropout masks repeated across microbatches'


def test_gpipe_dropout_step_dependent_and_deterministic():
    """Same seed -> same masks; advancing the stream -> different masks."""
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))

    def run(seed):
        paddle.seed(seed)
        blocks = [_DropBlock() for _ in range(2)]
        for b in blocks:
            b.train()
        state = make_pp_state(_pp_mesh(2), n_stages=2, n_micro=4)
        first = pipeline_blocks(blocks, x, state).numpy()
        second = pipeline_blocks(blocks, x, state).numpy()
        return first, second

    a1, a2 = run(5)
    b1, b2 = run(5)
    np.testing.assert_array_equal(a1, b1)   # deterministic per seed
    np.testing.assert_array_equal(a2, b2)
    assert not np.allclose(a1, a2)          # masks advance per call/step


def test_gpipe_dropout_eval_parity():
    """eval() blocks: pipelined forward == sequential forward exactly."""
    paddle.seed(7)
    blocks = [_DropBlock() for _ in range(2)]
    for b in blocks:
        b.eval()
    state = make_pp_state(_pp_mesh(2), n_stages=2, n_micro=4)
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
    out_pp = pipeline_blocks(blocks, x, state).numpy()
    ref = x
    for b in blocks:
        ref = b(ref)
    np.testing.assert_allclose(out_pp, ref.numpy(), rtol=1e-6, atol=1e-6)


@_CPU_NO_PARTITION_ID
def test_gpt_pp2_gpipe_dropout_trains():
    """GPipe pp=2 with full dropout (residual + attention-prob) trains:
    finite losses, loss moves, and the run is seed-deterministic."""
    ids, lbl = _batch()

    def run():
        model = _gpt(seed=3, dropout=0.2)
        step = _fleet_step(model, _strategy(dp_degree=4, pp_degree=2))
        return [float(step(ids, lbl).numpy()) for _ in range(3)]

    losses = run()
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    # dropout varies per step: consecutive losses must not be identical
    assert len({round(l, 9) for l in losses}) == 3
    np.testing.assert_allclose(run(), losses, rtol=1e-6)


@_CPU_NO_PARTITION_ID
def test_gpt_pp2_1f1b_dropout_trains():
    """1F1B pp=2 with dropout: the build-time raise is gone, masks are
    recompute-consistent (loss decreases over steps), deterministic."""
    ids, lbl = _batch()

    def run():
        model = _gpt(seed=3, dropout=0.2)
        step = _fleet_step(model, _strategy(dp_degree=4, pp_degree=2),
                           schedule='1F1B')
        return [float(step(ids, lbl).numpy()) for _ in range(4)]

    losses = run()
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert len({round(l, 9) for l in losses}) == 4
    np.testing.assert_allclose(run(), losses, rtol=1e-6)


def test_gpt_pp2_1f1b_dropout_eval_matches_dropout_free_train_shape():
    """With dropout config present, eval/generation outside the step is
    the plain sequential forward (pp_scope is step-scoped) and must be
    deterministic — two eval calls agree exactly."""
    model = _gpt(seed=3, dropout=0.2)
    _fleet_step(model, _strategy(dp_degree=4, pp_degree=2),
                schedule='1F1B')
    model.eval()
    ids, _ = _batch(b=2)
    a = model(ids).numpy()
    b = model(ids).numpy()
    np.testing.assert_array_equal(a, b)


def test_ring_attention_dropout_unbiased():
    """Attention-prob dropout in the ring must be UNBIASED: the value
    accumulation sees the mask but the softmax denominator uses the
    undropped weights, so E[out] over masks equals undropped attention
    (the dropout-after-softmax identity)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from paddle_tpu.ops import ring_attention as ra

    mesh = Mesh(np.array(jax.devices()[:2]), ('sp',))
    spec = P(None, 'sp', None, None)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 8, 2, 4), jnp.float32)
    k = jnp.asarray(rng.randn(1, 8, 2, 4), jnp.float32)
    v = jnp.asarray(rng.randn(1, 8, 2, 4), jnp.float32)

    def body(qq, kk, vv, key):
        rank_key = jax.random.fold_in(key, lax.axis_index('sp'))
        return ra.ring_attention(qq, kk, vv, axis_name='sp', causal=True,
                                 dropout_p=0.3, dropout_key=rank_key)

    dropped = jax.jit(shard_map(body, mesh=mesh,
                                in_specs=(spec, spec, spec, P()),
                                out_specs=spec, check_rep=False))

    def ref_body(qq, kk, vv):
        return ra.ring_attention(qq, kk, vv, axis_name='sp', causal=True)
    ref = shard_map(ref_body, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_rep=False)(q, k, v)

    n = 400
    acc = np.zeros(q.shape, np.float32)
    base = jax.random.PRNGKey(7)
    for i in range(n):
        acc += np.asarray(dropped(q, k, v, jax.random.fold_in(base, i)))
    mean = acc / n
    # SE of the mean ~ |v|*sqrt(p/(1-p))/sqrt(n); loose 4-sigma-ish band
    np.testing.assert_allclose(mean, np.asarray(ref), atol=0.35)
    # and a single draw really differs from the undropped output
    one = np.asarray(dropped(q, k, v, base))
    assert not np.allclose(one, np.asarray(ref), atol=1e-3)


@pytest.mark.slow
def test_sp_dropout_trains():
    """sp=4 ring attention with dropout (attention-prob + residual):
    builds (the r3 ValueError is gone) and trains with finite losses."""
    ids, lbl = _batch()
    s = _strategy(dp_degree=2, sp_degree=4)
    s.sequence_parallel = True
    model = _gpt(seed=5, dropout=0.2)
    step = _fleet_step(model, s)
    losses = [float(step(ids, lbl).numpy()) for _ in range(3)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert len({round(l, 9) for l in losses}) == 3


def test_sp_dropout_eval_parity_with_dp():
    """eval forward of the sp-built model == eval forward of a dp model
    with identical weights (dropout off, no sp context outside steps)."""
    s = _strategy(dp_degree=2, sp_degree=4)
    s.sequence_parallel = True
    model = _gpt(seed=5, dropout=0.2)
    _fleet_step(model, s)
    ref = _gpt(seed=5, dropout=0.2)  # same seed -> same init weights
    model.eval()
    ref.eval()
    ids, _ = _batch(b=2)
    np.testing.assert_allclose(model(ids).numpy(), ref(ids).numpy(),
                               rtol=1e-5, atol=1e-5)
