"""Paged-KV host bookkeeping + engine lifecycle tests.

Parity of the paged/prefix/speculative MODEL paths lives in
tests/test_serving.py next to the slot engine's; this file covers the
host side the paged engine stands on — page refcounts, prefix-cache
hashing/eviction, page-aware admission — plus the lifecycle edges:
allocator double-free strictness, FIFO fairness under sustained full
occupancy, shutdown semantics, and page-leak-free churn.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.serving import (ContinuousBatchingEngine, NGramProposer,
                                PagedContinuousBatchingEngine,
                                PagedScheduler, SlotAllocator)
from paddle_tpu.serving.kv_cache import (SCRATCH_PAGE, PageAllocator,
                                         PrefixCache)
from paddle_tpu.serving.scheduler import Request
from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM


@pytest.fixture(scope='module')
def model():
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=211, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


# ---- allocators -------------------------------------------------------


def test_slot_allocator_double_free_raises():
    a = SlotAllocator(2)
    s = a.alloc('r0')
    a.free(s)
    with pytest.raises(ValueError, match='double-free'):
        a.free(s)
    with pytest.raises(ValueError, match='not allocated'):
        a.free(1)                       # never allocated
    # the raise must not corrupt the free list: both slots still usable
    assert sorted([a.alloc('r1'), a.alloc('r2')]) == [0, 1]
    assert a.alloc('r3') is None


def test_page_allocator_basics():
    a = PageAllocator(5)                # pages 1..4 allocatable
    assert a.alloc() == 1               # lowest-first, page 0 reserved
    assert a.alloc() == 2
    assert a.refcount(1) == 1
    assert a.in_use == 2 and a.available == 2
    assert a.occupancy == pytest.approx(0.5)
    assert a.decref(1) is True          # freed at zero
    assert a.alloc() == 1               # reuses the lowest freed page
    with pytest.raises(ValueError, match='num_pages'):
        PageAllocator(1)                # no room beyond the scratch page


def test_page_allocator_refcounts_and_double_free():
    a = PageAllocator(4)
    p = a.alloc()
    a.incref(p)                         # second owner (e.g. prefix cache)
    assert a.refcount(p) == 2
    assert a.decref(p) is False         # still held
    assert a.decref(p) is True          # last owner: back on free list
    with pytest.raises(ValueError, match='double-free'):
        a.decref(p)
    with pytest.raises(ValueError, match='not allocated'):
        a.incref(p)
    with pytest.raises(ValueError, match='scratch'):
        a.decref(SCRATCH_PAGE)
    with pytest.raises(ValueError, match='not allocated'):
        a.free(3)                       # never allocated


# ---- prefix cache -----------------------------------------------------


def test_prefix_cache_chain_match_and_publish():
    a = PageAllocator(16)
    pc = PrefixCache(4, a)
    prompt = list(range(11))            # blocks [0-3], [4-7]; tail 8-10
    assert pc.match(prompt) == []       # cold: both full blocks miss
    assert (pc.hits, pc.misses) == (0, 2)
    p0, p1 = a.alloc(), a.alloc()
    assert pc.publish(prompt, 0, p0)
    assert pc.publish(prompt, 1, p1)
    assert a.refcount(p0) == 2          # cache holds its own reference
    assert pc.match(prompt) == [p0, p1]
    # a whole-prompt-covering match is forbidden: >= 1 token must
    # prefill so the final chunk's logits can seed generation
    assert pc.match(prompt[:8]) == [p0]
    # chain hashing: same block content after a DIFFERENT prefix is a
    # different key — block 1's page must not leak to a mismatched head
    other = [99, 99, 99, 99] + prompt[4:]
    assert pc.match(other) == []
    # duplicate publish is a no-op and takes no extra reference
    assert not pc.publish(prompt, 0, p0)
    assert a.refcount(p0) == 2


def test_prefix_cache_evicts_lru_and_skips_referenced_pages():
    a = PageAllocator(16)
    pc = PrefixCache(2, a)
    prompts = [[i, i, 0, 0, 0] for i in range(3)]
    pages = []
    for pr in prompts:
        p = a.alloc()
        pc.publish(pr, 0, p)
        a.decref(p)                     # publisher retired: cache-only ref
        pages.append(p)
    pc.match(prompts[0])                # refresh entry 0: now most-recent
    a.incref(pages[1])                  # a live sequence maps entry 1
    assert pc.evict(2) == 2             # entry 2 (LRU) + entry 0
    assert len(pc) == 1                 # the referenced entry survived
    assert a.refcount(pages[1]) == 2
    assert pc.match(prompts[1]) == [pages[1]]
    pc.clear()
    a.decref(pages[1])
    assert a.in_use == 0 and a.available == 15


# ---- page-aware scheduling --------------------------------------------


def _mk_sched(num_seqs=2, num_pages=9, max_len=32, chunk=4, page=4,
              prefix=True):
    pages = PageAllocator(num_pages)
    pc = PrefixCache(page, pages) if prefix else None
    sched = PagedScheduler(SlotAllocator(num_seqs), pages, max_len, chunk,
                           page, pc)
    return sched, pages


def test_paged_scheduler_reserves_all_pages_up_front():
    sched, pages = _mk_sched()
    r = Request(list(range(6)), max_new_tokens=4)   # needs 9 rows -> 3 pages
    sched.submit(r)
    assert [req for _, req in sched.admit()] == [r]
    assert pages.in_use == 3
    row = sched.block_tables[r.slot]
    assert (row[:3] > SCRATCH_PAGE).all()
    assert (row[3:] == SCRATCH_PAGE).all()
    sched.mark_prefilled(r, 6)
    sched.retire(r)
    # full release: pages either free or held ONLY by the prefix cache
    assert pages.in_use == len(sched.prefix)
    assert (sched.block_tables[0] == SCRATCH_PAGE).all()


def test_paged_scheduler_head_blocking_keeps_fifo():
    sched, pages = _mk_sched(num_seqs=2, num_pages=9)
    big = Request(list(range(20)), max_new_tokens=9)    # 7 pages
    small = Request([1, 2], max_new_tokens=2)           # 1 page
    hog = Request(list(range(12)), max_new_tokens=5)    # 4 pages
    sched.submit(hog)
    assert len(sched.admit()) == 1
    sched.submit(big)
    sched.submit(small)
    # 5 pages remain: big (7) cannot reserve — and small must NOT jump
    # the queue past it, or big could starve behind a stream of smalls
    assert sched.admit() == []
    assert pages.in_use == 4
    sched.mark_prefilled(hog, 12)
    sched.retire(hog)
    admitted = [req for _, req in sched.admit()]
    assert admitted[0] is big                           # FIFO restored
    assert small in admitted


def test_paged_scheduler_submit_validation():
    sched, _ = _mk_sched(max_len=16, num_pages=5)
    with pytest.raises(ValueError, match='empty prompt'):
        sched.submit(Request([], max_new_tokens=2))
    with pytest.raises(ValueError, match='max_new_tokens'):
        sched.submit(Request([1], max_new_tokens=0))
    with pytest.raises(ValueError, match='cache rows'):
        sched.submit(Request(list(range(14)), max_new_tokens=8))
    with pytest.raises(ValueError, match='pages'):
        # fits max_len rows but not the 4-page pool
        _mk_sched(max_len=32, num_pages=5)[0].submit(
            Request(list(range(15)), max_new_tokens=14))


def test_paged_scheduler_1k_churn_leaks_no_pages():
    """The page-leak satellite, at the bookkeeping layer where 1000
    requests are cheap: after arbitrary admit/prefill/retire churn with
    prefix publishing on, every page is back on the free list except
    the prefix cache's own bounded references."""
    rng = np.random.RandomState(5)
    sched, pages = _mk_sched(num_seqs=4, num_pages=33, max_len=32)
    system = [7, 8, 9, 10]                      # one shareable block
    live = []
    for i in range(1000):
        n0 = int(rng.randint(1, 10))
        r = Request(system + [int(t) for t in rng.randint(0, 99, n0)],
                    max_new_tokens=int(rng.randint(1, 8)))
        sched.submit(r)
        for _, req in sched.admit():
            live.append(req)
        if live and rng.rand() < 0.7:
            req = live.pop(int(rng.randint(len(live))))
            sched.mark_prefilled(req, len(req.prompt))
            sched.retire(req)
    for req in live:
        sched.mark_prefilled(req, len(req.prompt))
        sched.retire(req)
    while sched.queue:
        for _, req in sched.admit():
            sched.mark_prefilled(req, len(req.prompt))
            sched.retire(req)
    assert pages.in_use == len(sched.prefix)
    sched.prefix.clear()
    assert pages.in_use == 0
    assert pages.available == 32
    assert (sched.block_tables == SCRATCH_PAGE).all()


# ---- engine lifecycle -------------------------------------------------


def test_engine_fifo_fairness_under_full_occupancy(model):
    """Sustained full occupancy with Poisson arrivals: admission is
    FIFO (no request overtakes an earlier one) and nobody starves —
    every request finishes within a wait bounded by the generation
    lengths ahead of it."""
    rng = np.random.RandomState(4)
    eng = PagedContinuousBatchingEngine(model, num_seqs=2, max_len=32,
                                        page_size=8, prefill_chunk=8,
                                        decode_block=4)
    admitted = []
    orig = eng.scheduler.admit
    eng.scheduler.admit = lambda: [
        (s, (admitted.append(r.id), r)[1]) for s, r in orig()]
    n_req, due = 12, [0] + list(np.cumsum(
        rng.poisson(1.0, size=11)))       # arrival step of each request
    prompts = [[int(t) for t in rng.randint(0, 211, 1 + i % 5)]
               for i in range(n_req)]
    reqs, i, steps = [], 0, 0
    while i < n_req or eng.scheduler.pending:
        while i < n_req and due[i] <= steps:
            reqs.append(eng.add_request(prompts[i], max_new_tokens=6))
            i += 1
        eng.step()
        steps += 1
        assert steps < 300              # no starvation: bounded total
    assert admitted == [r.id for r in reqs]          # FIFO, no overtakes
    assert all(len(r.tokens) == 6 for r in reqs)
    # load was sustained: most steps ran with some occupancy
    assert eng.metrics.report()['occupancy_mean'] > 0.25


@pytest.mark.parametrize('make', [
    lambda m: ContinuousBatchingEngine(m, num_slots=2, max_len=32,
                                       prefill_chunk=8, decode_block=2),
    lambda m: PagedContinuousBatchingEngine(m, num_seqs=2, max_len=32,
                                            page_size=8, prefill_chunk=8,
                                            decode_block=2),
], ids=['slot', 'paged'])
def test_shutdown_rejects_new_requests_but_drains(model, make):
    eng = make(model)
    req = eng.add_request([1, 2, 3], max_new_tokens=3)
    eng.shutdown()
    with pytest.raises(RuntimeError, match='shut down'):
        eng.add_request([4, 5], max_new_tokens=2)
    eng.run()                           # in-flight work still completes
    assert len(req.tokens) == 3
    assert eng.scheduler.pending == 0


@pytest.mark.parametrize('make', [
    lambda m: ContinuousBatchingEngine(m, num_slots=2, max_len=32,
                                       prefill_chunk=8, decode_block=2),
    lambda m: PagedContinuousBatchingEngine(m, num_seqs=2, max_len=32,
                                            page_size=8, prefill_chunk=8,
                                            decode_block=2),
], ids=['slot', 'paged'])
def test_shutdown_races_active_stream_consumers(model, make):
    """shutdown() lands WHILE stream() consumers are cooperatively
    driving the engine: the front door closes, but every consumer's
    stream still terminates cleanly with its full token budget (the
    retire/churn half of this contract is covered above)."""
    import threading
    eng = make(model)
    reqs = [eng.add_request(p, max_new_tokens=6, stream=True)
            for p in ([1, 2, 3], [4, 5], [6, 7, 8, 9])]
    got = {i: [] for i in range(len(reqs))}
    errs = []

    def consume(i):
        try:
            for tok in eng.stream(reqs[i]):
                got[i].append(tok)
        except Exception as e:        # noqa: BLE001 — the assertion
            errs.append(e)

    threads = [threading.Thread(target=consume, args=(i,))
               for i in range(len(reqs))]
    for t in threads:
        t.start()
    eng.shutdown()                    # races the consumers' step() calls
    with pytest.raises(RuntimeError, match='shut down'):
        eng.add_request([1], max_new_tokens=2)
    for t in threads:
        t.join(120)
    assert not any(t.is_alive() for t in threads)
    assert errs == []
    for i, r in enumerate(reqs):
        assert got[i] == r.tokens
        assert len(got[i]) == 6
    assert eng.scheduler.pending == 0


def test_engine_retire_releases_pages(model):
    """Engine-level leak check: after churning many requests through few
    sequences, only the prefix cache still references pages, and
    disabling it drains the pool to empty."""
    rng = np.random.RandomState(9)
    prompts = [[int(t) for t in rng.randint(0, 211, 1 + i % 7)]
               for i in range(12)]
    eng = PagedContinuousBatchingEngine(model, num_seqs=2, max_len=32,
                                        page_size=8, prefill_chunk=8,
                                        decode_block=2, prefix_cache=False)
    eng.generate(prompts, max_new_tokens=4)
    assert eng.pages.in_use == 0
    assert eng.pages.available == eng.num_pages - 1
    assert (eng.scheduler.block_tables == SCRATCH_PAGE).all()


# ---- speculative proposer ---------------------------------------------


def test_ngram_proposer():
    p = NGramProposer(2)
    # trailing bigram (3, 4) occurred earlier: propose its continuation
    assert p.propose([1, 3, 4, 7, 8, 3, 4], 3) == [7, 8, 3]
    # no earlier occurrence: repeat the last token
    assert p.propose([1, 2, 3], 2) == [3, 3]
    # continuation shorter than k: pad by repeating its last token
    assert p.propose([5, 6, 9, 5, 6], 4) == [9, 5, 6, 6]
    # single-token history cannot form an n-gram; still drafts k tokens
    assert p.propose([4], 3) == [4, 4, 4]
    with pytest.raises(ValueError):
        NGramProposer(0)


def test_paged_capacity_validation(model):
    with pytest.raises(ValueError, match='max_position_embeddings'):
        PagedContinuousBatchingEngine(model, num_seqs=2, max_len=4096)
    eng = PagedContinuousBatchingEngine(model, num_seqs=2, max_len=32,
                                        page_size=8, prefill_chunk=8,
                                        decode_block=2)
    with pytest.raises(ValueError, match='cache rows'):
        eng.add_request(list(range(30)), max_new_tokens=8)
    # capacity errors must not wedge later valid requests
    req = eng.add_request([1, 2, 3], max_new_tokens=2)
    eng.run()
    assert len(req.tokens) == 2
