"""Concurrent serving over the inference front doors.

Two concurrency surfaces, each asserting output parity AND no
cross-talk between simultaneous users:
  1. a Python thread pool where every worker serves its own
     predictor.clone() (the AnalysisPredictor::Clone serving pattern —
     clones share the artifact, not mutable run state);
  2. the C ABI with TWO predictor handles driven from two pthreads in
     one client process (every entry point is GIL-guarded, so
     interleaved Run calls must not mix handles' buffers).
"""
import os
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope='module')
def saved_mlp(tmp_path_factory):
    paddle.seed(2024)
    model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    model.eval()
    path = str(tmp_path_factory.mktemp('concurrent') / 'mlp')
    from paddle_tpu.static import InputSpec
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([2, 8], name='features')])
    return path, model


def _inputs_for(worker):
    # distinct per worker so cross-talk shows up as wrong VALUES, not
    # just races
    return (0.1 * (worker + 1)
            * (np.arange(16, dtype=np.float32) - 8)).reshape(2, 8)


def test_thread_pool_over_predictor_clones(saved_mlp):
    path, model = saved_mlp
    from paddle_tpu import inference
    root = inference.create_predictor(inference.Config(path))
    n_workers, iters = 4, 6
    expect = [model(paddle.to_tensor(_inputs_for(w))).numpy()
              for w in range(n_workers)]

    def worker(w):
        p = root.clone()           # own run state, shared artifact
        x = _inputs_for(w)
        outs = []
        for _ in range(iters):
            outs.append(p.run([x])[0])
        return outs

    with ThreadPoolExecutor(n_workers) as ex:
        results = list(ex.map(worker, range(n_workers)))
    for w, outs in enumerate(results):
        for out in outs:           # every iteration, not just the last:
            # an interleaved write from another clone would corrupt a
            # middle run
            np.testing.assert_allclose(out, expect[w], rtol=1e-5,
                                       atol=1e-6)
    # sanity: the workloads really were distinct
    assert not np.allclose(expect[0], expect[1])


def test_live_metrics_scrape_during_concurrent_serving():
    """Scrape /metrics WHILE a continuous-batching engine serves
    concurrent requests: every mid-flight scrape must be valid
    Prometheus text (the exporter reads under the family locks), and the
    final counters must account for exactly the work done."""
    import threading
    import urllib.request

    from paddle_tpu import monitor
    from paddle_tpu.serving import ContinuousBatchingEngine
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM
    from test_monitor import _parse_exposition

    # counters and scrape validity are the subject here, not parity, so
    # the model is as small as the engine accepts
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=1,
                    num_heads=2, max_position_embeddings=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(3)
    prompts = [[int(t) for t in rng.randint(0, 97, n)]
               for n in (3, 11, 7, 9, 5, 13)]
    mnt = 4

    reg = monitor.default_registry()

    def counter(name):
        return reg.get(name).labels().value() if reg.get(name) else 0.0

    # engine construction registers the families; baselines AFTER it
    eng = ContinuousBatchingEngine(model, num_slots=3, max_len=64,
                                   prefill_chunk=8, decode_block=4)
    base = {n: counter(n) for n in
            ('serving_requests_total', 'serving_requests_admitted_total',
             'serving_requests_retired_total', 'serving_tokens_total')}

    results = [None] * 3
    bodies = []
    done = threading.Event()

    def worker(i):
        results[i] = eng.generate(prompts[2 * i:2 * i + 2],
                                  max_new_tokens=mnt)

    with monitor.MetricsServer(registry=reg) as srv:
        def scraper():
            while not done.is_set():
                bodies.append(urllib.request.urlopen(
                    srv.url + '/metrics', timeout=5).read().decode())

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        s = threading.Thread(target=scraper)
        s.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        done.set()
        s.join()
        final = urllib.request.urlopen(srv.url + '/metrics',
                                       timeout=5).read().decode()

    # every scrape taken mid-serving parses as valid exposition
    assert bodies, 'scraper never ran'
    for body in bodies:
        _parse_exposition(body)
    types, samples = _parse_exposition(final)
    assert types['serving_tokens_total'] == 'counter'
    assert types['serving_ttft_seconds'] == 'histogram'

    # outputs are untouched by the scraping, and the counters account
    # for exactly the work done
    assert all(len(toks) == mnt for pair in results for toks in pair)
    assert counter('serving_requests_total') - \
        base['serving_requests_total'] == len(prompts)
    assert counter('serving_requests_admitted_total') - \
        base['serving_requests_admitted_total'] == len(prompts)
    assert counter('serving_requests_retired_total') - \
        base['serving_requests_retired_total'] == len(prompts)
    assert counter('serving_tokens_total') - \
        base['serving_tokens_total'] == len(prompts) * mnt
    assert eng.compiled_sizes() == {'prefill': 1, 'decode': 1}
    # the zero-retrace invariant is itself scrapeable
    trace = {(l['program'], v) for n, l, v in samples
             if n == 'serving_trace_count'}
    assert trace >= {('prefill', 1.0), ('decode', 1.0)}


CLIENT_MT_C = r'''
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include "pd_capi.h"

typedef struct {
  PD_Predictor* pred;
  float scale;
  int iters;
  float out[64];
  int64_t n;
  int rc;
} Job;

static void* worker(void* arg) {
  Job* j = (Job*)arg;
  char name[128];
  if (PD_PredictorGetInputName(j->pred, 0, name, 128) < 0) {
    j->rc = 1;
    return NULL;
  }
  float data[16];
  int64_t shape[2] = {2, 8};
  for (int i = 0; i < 16; ++i) data[i] = j->scale * (float)(i - 8);
  for (int it = 0; it < j->iters; ++it) {
    if (PD_PredictorSetInputFloat(j->pred, name, data, shape, 2) != 0 ||
        PD_PredictorRun(j->pred) != 0) {
      j->rc = 2;
      return NULL;
    }
    j->n = PD_PredictorGetOutputFloat(j->pred, 0, j->out, 64);
    if (j->n < 0 || j->n > 64) {
      j->rc = 3;
      return NULL;
    }
  }
  j->rc = 0;
  return NULL;
}

int main(int argc, char** argv) {
  if (argc < 3) { fprintf(stderr, "usage: client repo model\n"); return 2; }
  if (PD_Init(argv[1]) != 0) {
    fprintf(stderr, "init: %s\n", PD_GetLastError());
    return 3;
  }
  PD_Config* cfg = PD_ConfigCreate();
  PD_ConfigSetModel(cfg, argv[2]);
  PD_ConfigSetDevice(cfg, "cpu");
  PD_Predictor* p1 = PD_PredictorCreate(cfg);
  PD_Predictor* p2 = PD_PredictorCreate(cfg);
  PD_ConfigDestroy(cfg);
  if (p1 == NULL || p2 == NULL) {
    fprintf(stderr, "create: %s\n", PD_GetLastError());
    return 4;
  }
  Job jobs[2] = {{p1, 0.125f, 8, {0}, 0, -1}, {p2, -0.25f, 8, {0}, 0, -1}};
  pthread_t threads[2];
  pthread_create(&threads[0], NULL, worker, &jobs[0]);
  pthread_create(&threads[1], NULL, worker, &jobs[1]);
  pthread_join(threads[0], NULL);
  pthread_join(threads[1], NULL);
  for (int w = 0; w < 2; ++w) {
    if (jobs[w].rc != 0) {
      fprintf(stderr, "worker %d rc=%d: %s\n", w, jobs[w].rc,
              PD_GetLastError());
      return 6;
    }
    printf("worker %d n %lld\n", w, (long long)jobs[w].n);
    for (int64_t i = 0; i < jobs[w].n; ++i)
      printf("w%d %.8e\n", w, jobs[w].out[i]);
  }
  PD_PredictorDestroy(p1);
  PD_PredictorDestroy(p2);
  return 0;
}
'''


@pytest.fixture(scope='module')
def capi_lib():
    from paddle_tpu.capi import build_capi
    try:
        return build_capi()
    except RuntimeError as e:
        pytest.skip('capi build unavailable: %s' % e)


def test_c_abi_two_handles_concurrent_run(capi_lib, saved_mlp, tmp_path):
    path, model = saved_mlp
    from paddle_tpu.capi import header_path
    src = os.path.join(str(tmp_path), 'client_mt.c')
    with open(src, 'w') as f:
        f.write(CLIENT_MT_C)
    exe = os.path.join(str(tmp_path), 'client_mt')
    proc = subprocess.run(
        ['gcc', '-O1', '-pthread', '-o', exe, src,
         '-I', os.path.dirname(header_path()), capi_lib,
         '-Wl,-rpath,' + os.path.dirname(capi_lib)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    env = dict(os.environ)
    env['PYTHONPATH'] = os.pathsep.join(
        [p for p in sys.path if p and os.path.isdir(p)])
    env.pop('XLA_FLAGS', None)  # no virtual-device mesh inside the client
    proc = subprocess.run([exe, REPO, path], capture_output=True,
                          text=True, env=env, timeout=600)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    got = {0: [], 1: []}
    for line in proc.stdout.strip().splitlines():
        if line.startswith('w0 '):
            got[0].append(float(line.split()[1]))
        elif line.startswith('w1 '):
            got[1].append(float(line.split()[1]))
    for w, scale in ((0, 0.125), (1, -0.25)):
        x = (scale * (np.arange(16, dtype=np.float32) - 8)).reshape(2, 8)
        ref = model(paddle.to_tensor(x)).numpy()
        assert len(got[w]) == ref.size
        np.testing.assert_allclose(
            np.array(got[w], np.float32).reshape(ref.shape), ref,
            rtol=1e-5, atol=1e-6,
            err_msg='worker %d output drifted under concurrency' % w)
    assert not np.allclose(got[0], got[1])   # two jobs, two answers
