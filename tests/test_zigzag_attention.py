"""Zigzag (load-balanced) causal ring attention.

Parity bar: must match the quadratic causal reference exactly (fwd and
grads) through the sp_attention entry, like the plain ring. Balance bar:
per-rank matmul flops must be the lower-triangle schedule — (2P+1)/(4P)
of the plain ring's compute-then-mask — asserted on the shard_map body's
jaxpr with scan trip counts weighted in.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

import paddle_tpu  # noqa: F401  (forces the 8-device CPU mesh via conftest)
from paddle_tpu.distributed import sp as sp_mod
from paddle_tpu.ops import ring_attention as ra

from test_blockwise_attention import _weighted_dot_flops


def _mesh(n):
    devs = np.array(jax.devices()[:n])
    return Mesh(devs, ('sp',))


def _ref_causal(q, k, v, scale):
    s = jnp.einsum('bqhd,bkhd->bhqk', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    n = s.shape[-1]
    s = jnp.where(jnp.tril(jnp.ones((n, n), bool))[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhqk,bkhd->bqhd', p,
                      v.astype(jnp.float32)).astype(q.dtype)


@pytest.mark.parametrize('sp,n', [(2, 8), (4, 16), (8, 32), (4, 64)])
def test_zigzag_matches_reference_fwd(sp, n):
    rng = np.random.RandomState(0)
    b, h, d = 2, 2, 16
    q = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    mesh = _mesh(sp)
    st = sp_mod.make_sp_state(mesh, axis='sp', mode='zigzag')
    out = sp_mod.sp_attention(q, k, v, causal=True, scale=scale, state=st)
    ref = _ref_causal(q, k, v, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_zigzag_matches_reference_grads():
    rng = np.random.RandomState(1)
    b, n, h, d = 1, 16, 2, 8
    q = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    mesh = _mesh(4)
    st = sp_mod.make_sp_state(mesh, axis='sp', mode='zigzag')

    def loss_z(q, k, v):
        return jnp.sum(sp_mod.sp_attention(q, k, v, causal=True,
                                           scale=scale, state=st) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_causal(q, k, v, scale) ** 2)

    gz = jax.grad(loss_z, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gz, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-5, atol=5e-5)


def test_zigzag_flops_are_lower_triangle():
    """Per-rank matmul flops: plain causal ring computes all 4 quadrants
    per ring step (then masks); zigzag computes 2P+1 quadrants total vs
    the ring's 4P."""
    sp, n, b, h, d = 4, 32, 1, 2, 16
    mesh = _mesh(sp)
    x = jnp.zeros((b, n, h, d), jnp.float32)
    spec = P(None, 'sp', None, None)

    def count(fn, **kw):
        import functools
        wrapped = shard_map(
            functools.partial(fn, axis_name='sp', **kw), mesh=mesh,
            in_specs=(spec, spec, spec), out_specs=spec, check_rep=False)
        return _weighted_dot_flops(jax.make_jaxpr(wrapped)(x, x, x).jaxpr)

    ring = count(ra.ring_attention, causal=True)
    zig = count(ra.zigzag_ring_attention)
    assert zig == ring * (2 * sp + 1) // (4 * sp), (zig, ring)


@pytest.mark.slow
def test_zigzag_dropout_deterministic_and_varying():
    rng = np.random.RandomState(3)
    b, n, h, d = 1, 16, 2, 8
    q = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    mesh = _mesh(4)
    st = sp_mod.make_sp_state(mesh, axis='sp', mode='zigzag')
    key = jax.random.PRNGKey(7)

    def run(key):
        return np.asarray(sp_mod.sp_attention(
            q, q, q, causal=True, scale=0.35, state=st,
            dropout_p=0.5, dropout_key=key))

    a, b_ = run(key), run(key)
    np.testing.assert_array_equal(a, b_)          # same key -> same masks
    c = run(jax.random.PRNGKey(8))
    assert np.abs(a - c).max() > 0                # new key -> new masks
    # p=0 path equals the no-dropout path
    nd = np.asarray(sp_mod.sp_attention(q, q, q, causal=True, scale=0.35,
                                        state=st))
    z = np.asarray(sp_mod.sp_attention(q, q, q, causal=True, scale=0.35,
                                       state=st, dropout_p=0.0,
                                       dropout_key=key))
    np.testing.assert_allclose(nd, z, rtol=1e-6)


def test_zigzag_falls_back_when_not_applicable():
    rng = np.random.RandomState(5)
    b, n, h, d = 1, 16, 2, 8
    q = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    mesh = _mesh(4)
    st = sp_mod.make_sp_state(mesh, axis='sp', mode='zigzag')
    # non-causal: falls back to the plain ring and stays correct
    out = sp_mod.sp_attention(q, q, q, causal=False, scale=0.35, state=st)
    s = jnp.einsum('bqhd,bkhd->bhqk', q, q) * 0.35
    p = jax.nn.softmax(s, axis=-1)
    ref = jnp.einsum('bhqk,bkhd->bqhd', p, q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # causal but N not divisible by 2P (24 % 8 != 0): same downgrade,
    # must still match the quadratic causal reference
    n2 = 24
    q2 = jnp.asarray(rng.randn(b, n2, h, d), jnp.float32)
    out2 = sp_mod.sp_attention(q2, q2, q2, causal=True, scale=0.35,
                               state=st)
    ref2 = _ref_causal(q2, q2, q2, 0.35)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_ulysses_long_causal_uses_blockwise_skip():
    """Ulysses' local full-sequence attention routes through the causal
    block-skip path at long N: parity with the quadratic reference AND
    fewer matmul flops than the compute-then-mask program."""
    import functools
    sp, n, b, h, d = 4, 2048, 1, 4, 8
    mesh = _mesh(sp)
    rng = np.random.RandomState(6)
    q = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    spec = P(None, 'sp', None, None)

    wrapped = shard_map(
        functools.partial(ra.ulysses_attention, axis_name='sp',
                          causal=True, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    out = wrapped(q, q, q)
    ref = _ref_causal(q, q, q, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=3e-5)

    flops_causal = _weighted_dot_flops(
        jax.make_jaxpr(wrapped)(q, q, q).jaxpr)
    wrapped_full = shard_map(
        functools.partial(ra.ulysses_attention, axis_name='sp',
                          causal=False, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    flops_full = _weighted_dot_flops(
        jax.make_jaxpr(wrapped_full)(q, q, q).jaxpr)
    assert flops_causal < 0.7 * flops_full, (flops_causal, flops_full)


@pytest.mark.slow
def test_ulysses_long_causal_grads_match():
    """The blockwise-skip route swaps the BACKWARD program too — grad
    parity vs the quadratic reference through the composed
    all_to_all + causal-skip path."""
    import functools
    sp, n, b, h, d = 4, 1024, 1, 4, 8
    mesh = _mesh(sp)
    rng = np.random.RandomState(13)
    q = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, n, h, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    spec = P(None, 'sp', None, None)
    wrapped = shard_map(
        functools.partial(ra.ulysses_attention, axis_name='sp',
                          causal=True, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)

    def loss_u(q, k, v):
        return jnp.sum(wrapped(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_causal(q, k, v, scale) ** 2)

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gu, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_zigzag_dropout_unbiased():
    """Zigzag's quadrant-level dropout keys must preserve the dropout-
    after-softmax identity: averaging many masked draws recovers the
    undropped attention (the same unbiasedness bar the plain ring
    holds)."""
    mesh = _mesh(2)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 8, 2, 4), jnp.float32)
    k = jnp.asarray(rng.randn(1, 8, 2, 4), jnp.float32)
    v = jnp.asarray(rng.randn(1, 8, 2, 4), jnp.float32)
    st = sp_mod.make_sp_state(mesh, axis='sp', mode='zigzag')

    ref = np.asarray(sp_mod.sp_attention(q, k, v, causal=True, scale=0.5,
                                         state=st))

    @jax.jit
    def one(key):
        return sp_mod.sp_attention(q, k, v, causal=True, scale=0.5,
                                   state=st, dropout_p=0.3,
                                   dropout_key=key)

    n = 400
    acc = np.zeros(np.asarray(ref).shape, np.float32)
    base = jax.random.PRNGKey(11)
    for i in range(n):
        acc += np.asarray(one(jax.random.fold_in(base, i)))
    mean = acc / n
    np.testing.assert_allclose(mean, ref, atol=0.35)
