"""PS table + communicator tests (reference pattern:
distributed/test/brpc_service_dense_sgd_test.cc, sparse_table_test.cc,
barrier_table_test.cc — real server+client in one process)."""
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.ps import (AsyncCommunicator, BarrierTable,
                                       Communicator, DenseTable,
                                       EmbeddingClient, EmbeddingServer,
                                       GeoCommunicator, GeoSparseTable,
                                       SsdSparseTable, SyncCommunicator,
                                       TensorTable)
from paddle_tpu.distributed.ps.communicator import _merge_by_id


def test_dense_table_sgd_and_adam():
    t = DenseTable((4,), optimizer='sgd', lr=0.1)
    t.set(np.ones(4, np.float32))
    t.push(np.full(4, 2.0, np.float32))
    np.testing.assert_allclose(t.pull(), 0.8 * np.ones(4))

    ta = DenseTable((2,), optimizer='adam', lr=0.01)
    v0 = ta.pull()
    for _ in range(3):
        ta.push(np.ones(2, np.float32))
    assert np.all(ta.pull() < v0)


def test_barrier_table_blocks_until_full():
    bt = BarrierTable(3)
    arrived = []

    def worker(i):
        bt.barrier(i, timeout=5.0)
        arrived.append(i)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    assert arrived == []          # 2 of 3: still blocked
    bt.barrier(2, timeout=5.0)    # third arrival releases everyone
    for t in threads:
        t.join(timeout=5)
    assert sorted(arrived) == [0, 1]
    # reusable: next round works
    round2 = [threading.Thread(target=worker, args=(i,)) for i in (7, 9)]
    for t in round2:
        t.start()
    bt.barrier(8, timeout=5.0)
    for t in round2:
        t.join(timeout=5)
    assert 7 in arrived and 9 in arrived


def test_barrier_timeout():
    bt = BarrierTable(2)
    with pytest.raises(TimeoutError):
        bt.barrier(0, timeout=0.2)


def test_tensor_table():
    tt = TensorTable()
    tt.set('step', 0.0)
    assert tt.increment('step', 1.0) == 1.0
    assert tt.increment('step', 2.0) == 3.0
    np.testing.assert_allclose(tt.get('step'), 3.0)
    assert tt.get('missing') is None


def test_geo_sparse_table_delta_semantics():
    t = GeoSparseTable(4, initializer='zeros')
    base = t.pull([1, 2])
    t.push_delta([1], np.full((1, 4), 0.5, np.float32))
    t.push_delta([1], np.full((1, 4), 0.25, np.float32))
    out = t.pull([1])
    np.testing.assert_allclose(out[0], base[0] + 0.75)


def test_ssd_sparse_table_spills_and_promotes():
    t = SsdSparseTable(8, max_mem_rows=10, initializer='uniform',
                       optimizer='adagrad', lr=0.1, seed=1)
    ids = list(range(25))
    first = t.pull(ids)
    assert t.mem_rows() <= 10
    assert t.disk_rows() >= 15
    assert len(t) == 25
    # promoted rows keep their values
    again = t.pull(ids[:5])
    np.testing.assert_allclose(again, first[:5])
    # push on a spilled row: promote, apply optimizer, value changes
    g = np.ones((1, 8), np.float32)
    before = t.pull([7]).copy()
    t.push([7], g)
    after = t.pull([7])
    assert not np.allclose(before, after)
    # optimizer slots survived the spill round trip: a second identical
    # push with adagrad must move LESS than the first
    d1 = np.abs(after - before).mean()
    t.push([7], g)
    final = t.pull([7])
    d2 = np.abs(final - after).mean()
    assert d2 < d1


def test_merge_by_id():
    ids = [3, 1, 3, 2, 1]
    grads = np.ones((5, 2), np.float32)
    uniq, merged = _merge_by_id(ids, grads)
    np.testing.assert_array_equal(uniq, [1, 2, 3])
    np.testing.assert_allclose(merged, [[2, 2], [1, 1], [2, 2]])


def _local_cluster(dim=4, optimizer='sgd', lr=0.1, table_class=None):
    servers = [EmbeddingServer() for _ in range(2)]
    for s in servers:
        s.create_table(0, dim, table_class=table_class,
                       initializer='zeros', optimizer=optimizer, lr=lr)
    client = EmbeddingClient(servers=servers)
    return servers, client


def test_sync_communicator_immediate():
    servers, client = _local_cluster()
    comm = SyncCommunicator(client)
    comm.start()          # no-op in sync mode
    rows0 = client.pull(0, [1, 2, 3])
    comm.push_sparse_grad(0, [1, 1, 2], np.ones((3, 4), np.float32))
    rows = client.pull(0, [1, 2, 3])
    # sgd lr=0.1: id1 got merged grad 2 -> -0.2; id2 grad 1 -> -0.1
    np.testing.assert_allclose(rows[0], rows0[0] - 0.2)
    np.testing.assert_allclose(rows[1], rows0[1] - 0.1)
    np.testing.assert_allclose(rows[2], rows0[2])


def test_async_communicator_background_merge():
    servers, client = _local_cluster()
    comm = AsyncCommunicator(client, merge_size=4)
    comm.start()
    client.pull(0, [5])
    for _ in range(8):
        comm.push_sparse_grad(0, [5], np.ones((1, 4), np.float32))
    comm.flush()
    rows = client.pull(0, [5])
    np.testing.assert_allclose(rows[0], -0.1 * 8 * np.ones(4), rtol=1e-5)
    comm.stop()
    assert not comm.is_running


def test_geo_communicator_batches_deltas():
    from paddle_tpu.distributed.ps.tables import GeoSparseTable
    servers, client = _local_cluster(table_class=GeoSparseTable)
    comm = GeoCommunicator(client, geo_need_push_nums=4)
    base = client.pull(0, [1, 2])
    comm.push_sparse_param(0, [1], np.full((1, 4), 0.5, np.float32))
    comm.push_sparse_param(0, [2], np.full((1, 4), 0.5, np.float32))
    # threshold (4) not reached: server unchanged
    np.testing.assert_allclose(client.pull(0, [1, 2]), base)
    comm.push_sparse_param(0, [1, 2], np.full((2, 4), 0.5, np.float32))
    # 4 accumulated rows -> flushed: each id got 2 deltas of 0.5
    np.testing.assert_allclose(client.pull(0, [1, 2]), base + 1.0)


def test_remote_dense_barrier_tensor_ops():
    servers = [EmbeddingServer() for _ in range(2)]
    for s in servers:
        s.start()
    try:
        servers[0].create_dense_table(0, (3,), optimizer='sgd', lr=0.5)
        servers[1].create_tensor_table(1)
        servers[0].create_barrier_table(2, trigger_count=2)
        eps = ['127.0.0.1:%d' % s.port for s in servers]
        c1 = EmbeddingClient(endpoints=eps)
        c2 = EmbeddingClient(endpoints=eps)

        c1.set_dense(0, np.asarray([1.0, 2.0, 3.0]))
        c1.push_dense(0, np.ones(3, np.float32))
        np.testing.assert_allclose(c2.pull_dense(0), [0.5, 1.5, 2.5])

        c1.tensor(1, 'set', 'epoch', 5.0)
        np.testing.assert_allclose(c2.tensor(1, 'increment', 'epoch', 1.0),
                                   6.0)

        # remote barrier across two clients
        done = []

        def wait():
            c2.barrier(2, worker_id=1, timeout=5.0)
            done.append(1)
        th = threading.Thread(target=wait)
        th.start()
        time.sleep(0.1)
        assert done == []
        c1.barrier(2, worker_id=0, timeout=5.0)
        th.join(timeout=5)
        assert done == [1]
    finally:
        for s in servers:
            s.stop()


def test_ssd_table_save_load_includes_cold_tier(tmp_path):
    t = SsdSparseTable(4, max_mem_rows=5, initializer='uniform', seed=2)
    ids = list(range(12))
    orig = t.pull(ids).copy()
    p = str(tmp_path / 'ssd_shard')
    t.save(p)
    t2 = SsdSparseTable(4, max_mem_rows=5, initializer='zeros', seed=3)
    t2.load(p)
    assert len(t2) == 12
    np.testing.assert_allclose(t2.pull(ids), orig)


def test_barrier_timeout_withdraws_arrival():
    bt = BarrierTable(2)
    with pytest.raises(TimeoutError):
        bt.barrier(0, timeout=0.2)
    # the failed arrival must NOT count toward the next round
    with pytest.raises(TimeoutError):
        bt.barrier(1, timeout=0.2)


def test_remote_error_reply_and_concurrent_barrier():
    server = EmbeddingServer()
    server.create_table(0, 4, initializer='zeros')
    server.create_barrier_table(9, trigger_count=2)
    server.start()
    try:
        eps = ['127.0.0.1:%d' % server.port]
        c = EmbeddingClient(endpoints=eps)
        with pytest.raises(RuntimeError):
            c.pull_dense(42)  # no such table: server must reply, not die
        # connection still usable after the error
        assert c.pull(0, [1]).shape == (1, 4)
        # a blocking barrier on this client must not stall its pulls
        done = []

        def wait():
            c.barrier(9, timeout=5.0)
            done.append(1)
        th = threading.Thread(target=wait)
        th.start()
        time.sleep(0.2)
        assert c.pull(0, [2]).shape == (1, 4)  # not blocked by barrier
        c.barrier(9, timeout=5.0)              # second arrival releases
        th.join(timeout=5)
        assert done == [1]
    finally:
        server.stop()
