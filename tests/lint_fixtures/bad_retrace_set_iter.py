"""Trigger: retrace-set-iter (set iteration feeding a trace).

Also exercises the exemptions: `shapes` is static (static_argnames), so
branching on it is fine, and dict iteration is insertion-ordered so
`table.items()` must stay quiet — only the set iterations fire.
"""
import jax


def build(table, shapes):
    total = 0
    for _, v in table.items():     # dict views are insertion-ordered: OK
        total = total + v
    names = set(shapes)
    for name in names:             # set order is process-dependent
        total = total + name
    for item in {3, 4}:            # set literal iterated directly
        total = total + item
    if shapes:                     # static arg: no finding
        total = total + 1
    return total


build_jit = jax.jit(build, static_argnames='shapes')
