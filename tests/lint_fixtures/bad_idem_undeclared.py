"""Trigger: idem-undeclared-op (retried op with no OP_SEMANTICS entry)."""


class Client:
    def __init__(self, channel):
        self._channel = channel

    def mystery(self, key):
        # retried by default, declared nowhere in this project
        return self._channel.call({'op': 'mystery_op', 'key': key})
