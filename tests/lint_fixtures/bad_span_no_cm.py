"""Trigger: span-no-cm (leakable tracer spans).

``good`` shows the three accepted shapes: context manager, explicit
finish, and escape (stored on the request).
"""


def leak_discarded(tracer):
    tracer.start_span('decode')          # result dropped: leaks open


def leak_bound(tracer):
    span = tracer.start_span('prefill')  # bound but never finished
    return 1


def good(tracer, req):
    with tracer.start_span('route'):
        pass
    s = tracer.server_span('handle', {})
    s.finish()
    req._span = tracer.start_span('stream')
    return req
