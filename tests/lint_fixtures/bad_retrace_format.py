"""Trigger: retrace-format (f-string / str() of a traced value)."""
import jax


@jax.jit
def step(x):
    msg = f"x is now {x}"      # implicit host sync to render
    label = str(x)             # and explicitly
    return x, msg, label
