"""Fixture: wide-event schema violations the events checker must catch.

Two shapes of the same mistake — a field name the committed baseline
(tools/request_event_baseline.json) does not know:

  1. an emit(...) keyword typo'd at an emission site (RequestLog.emit
     would raise at runtime, but only when that path runs);
  2. a REQUEST_EVENT_FIELDS table declaring a field the baseline was
     never taught.
"""
from paddle_tpu.monitor.events import default_request_log

# a vendored/forked schema table drifting from the baseline
REQUEST_EVENT_FIELDS = (
    ('request_id', 'engine- or gateway-level request id'),
    ('tenant_id', 'BAD: the canonical field is named `tenant`'),
)


def emit_event(req):
    log = default_request_log()
    # `tennant` is a typo of `tenant`; the checker flags it statically
    log.emit(request_id=req.id, tennant='acme', outcome='ok')
