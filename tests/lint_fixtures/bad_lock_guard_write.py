"""Trigger: lock-guard-write (guarded attribute written bare).

Also exercises the conventions the checker must honour: writes in
``__init__`` and in ``*_locked`` methods are fine.
"""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0          # construction: no finding

    def add(self, n):
        with self._lock:
            self.total += n     # establishes: total is lock-guarded

    def _bump_locked(self):
        self.total += 1         # caller holds the lock: no finding

    def reset(self):
        self.total = 0          # BARE write of a guarded attribute
