"""Fixture: checkpoint artifacts written raw — every write here can be
torn by a preempted pod and leaves no manifest to flag it. graftlint's
atomic-write rule must fire on each bad_* site and stay quiet on the
good_* ones."""
import os
import pickle

import numpy as np


def bad_literal_path(state):
    with open('model.ckpt', 'wb') as f:       # atomic-write
        f.write(state)


def bad_named_variable(checkpoint_path, payload):
    f = open(checkpoint_path, 'w')            # atomic-write
    f.write(payload)
    f.close()


def bad_pickle_dump(state, ckpt_path):
    pickle.dump(state, ckpt_path)             # atomic-write


def bad_handrolled_commit(tmp, ckpt_target):
    os.replace(tmp, ckpt_target)              # atomic-write


def good_read_side(ckpt_path):
    # read mode never tears anything
    with open(ckpt_path, 'rb') as f:
        return f.read()


def good_unnamed_write(path, payload):
    # generic writer with no checkpoint evidence: out of scope
    with open(path, 'w') as f:
        f.write(payload)


def good_sanctioned(state, path):
    from paddle_tpu.framework import io_save
    io_save.save(state, path + '.ckpt')


def good_suppressed(state):
    with open('debug.ckpt', 'wb') as f:  # graftlint: disable=atomic-write  forensics dump, torn is fine
        f.write(state)


def good_numpy_elsewhere(arr, path):
    # no checkpoint evidence in the args
    np.save(path, arr)
