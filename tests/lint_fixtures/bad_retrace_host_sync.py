"""Trigger: retrace-host-sync (coercions that pull a traced value to
host), including taint through an assignment and a same-module helper."""
import jax
import numpy as np


def _helper(v):
    return float(v)        # tainted via the call below


@jax.jit
def loss_fn(logits, target):
    err = logits - target
    scale = float(err)     # direct coercion
    n = int(target)        # and again
    host = np.asarray(err)     # device -> host copy
    item = err.item()          # forces a sync
    return _helper(err) + scale + n + host + item
