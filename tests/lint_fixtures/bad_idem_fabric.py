"""Trigger: every idempotency rule, in the serving-fabric op shapes.

The OP_SEMANTICS table mirrors the fabric worker's wire surface
(serving/fabric/worker.py); each send below breaks the declared
discipline the real SocketReplica upholds, and the table carries one
stale entry the handler never dispatches — the two-way check's other
direction.
"""

OP_SEMANTICS = {
    'submit': 'conditional',      # idempotent iff journaled
    'poll': 'idempotent',
    'drain': 'idempotent',        # STALE: the handler below lost it
    'stop': 'non_idempotent',
}


def handle(msg):
    op = msg.get('op')
    if op == 'submit':
        return 1
    elif op == 'poll':
        return 2
    elif op == 'stop':
        return 3


class BadFabricClient:
    def __init__(self, channel):
        self._channel = channel

    def submit(self, prompt, seq):
        # conditional op with the retrying default: an unjournaled
        # retried submit admits twice
        return self._channel.call({'op': 'submit', 'prompt': prompt,
                                   'seq': seq})

    def stop(self):
        # non_idempotent op with retries enabled: a retried stop hits
        # a dead server
        return self._channel.call({'op': 'stop'})

    def probe(self):
        # 'status' is sent through a retrying channel but declared in
        # no OP_SEMANTICS table
        return self._channel.call({'op': 'status'})
