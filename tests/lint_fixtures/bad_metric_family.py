"""Trigger: metric-unknown-family + metric-label-arity."""


class Worker:
    def __init__(self, registry):
        # not in tools/metrics_schema_baseline.json
        self._m_bogus = registry.counter(
            'lintfix_bogus_total', 'a family the schema never heard of',
            ('shard',))

    def tick(self, shard, kind):
        # family declares one label, call passes two
        self._m_bogus.labels(shard, kind).inc()
