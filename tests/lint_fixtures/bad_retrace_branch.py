"""Trigger: retrace-branch (python control flow on a traced value)."""
import jax


@jax.jit
def decode_step(x, limit):
    if x > limit:          # traced comparison -> ConcretizationTypeError
        return x - limit
    while x < limit:       # traced loop condition
        x = x + 1
    return x if x > 0 else -x   # traced ternary
