"""Trigger: idem-unknown-op, both directions — the handler dispatches
an op the table misses, and the table declares an op the handler never
dispatches."""

OP_SEMANTICS = {
    'declared_only': 'idempotent',     # stale: never dispatched
}


def handle(msg):
    op = msg['op']
    if op == 'dispatched_only':        # handled but undeclared
        return 1
    return None
