"""Trigger: idem-retry-unsafe + idem-conditional-literal.

The OP_SEMANTICS table here stands in for the server module; the sends
below violate each declared semantic. The handler dispatch keeps the
two-way idem-unknown-op rule quiet for these ops.
"""

OP_SEMANTICS = {
    'accumulate': 'accumulating',
    'maybe': 'conditional',
}


def handle(msg):
    op = msg['op']
    if op == 'accumulate':
        return 1
    elif op == 'maybe':
        return 2


class Client:
    def __init__(self, channel):
        self._channel = channel

    def accumulate(self, delta):
        # accumulating op sent with the retrying default: double-apply
        return self._channel.call({'op': 'accumulate', 'delta': delta})

    def maybe(self, payload):
        # conditional op with a constant idempotent=: a lie waiting
        return self._channel.call({'op': 'maybe', 'p': payload},
                                  idempotent=True)
