# Known-bad fixture corpus for graftlint: one minimal trigger file per
# rule, asserted rule-by-rule in tests/test_lint.py. These files are
# intentionally wrong — never import them from product code.
