"""Trigger: lock-order-cycle (same pair of locks, opposite orders)."""
import threading


class Router:
    def __init__(self):
        self._lock = threading.Lock()
        self._table_lock = threading.Lock()

    def route(self):
        with self._lock:
            with self._table_lock:       # order: _lock -> _table_lock
                return 1

    def rebuild(self):
        with self._table_lock:
            with self._lock:             # order: _table_lock -> _lock
                return 2
