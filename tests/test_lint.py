"""graftlint gate: fixture corpus proves every rule fires; the full
repo stays clean; fixed files are pinned at zero findings; the gates
share one exit-code/JSON convention; the lockwatch runtime witness
agrees with the static lock-order graph.
"""
import io
import json
import os
import subprocess
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import gate_common
from tools.graftlint import cli
from tools.graftlint.checkers import all_checkers
from tools.graftlint.checkers.locks import acquisition_order
from tools.graftlint.checkers.metrics import MetricsChecker
from tools.graftlint.core import (Project, apply_baseline, load_baseline,
                                  run_checkers, write_baseline)

FIXTURES = os.path.join('tests', 'lint_fixtures')


def _lint(paths):
    project = Project.load(paths, root=REPO)
    return run_checkers(project, all_checkers())


# ---------------------------------------------------------------- fixtures

# one known-bad file per rule: (fixture, {rule: expected count}).
# Counts are exact — a checker that silently stops firing OR starts
# over-firing on the same code both break the gate.
CORPUS = [
    ('bad_retrace_branch.py', {'retrace-branch': 3}),
    ('bad_retrace_host_sync.py', {'retrace-host-sync': 5}),
    ('bad_retrace_format.py', {'retrace-format': 2}),
    ('bad_retrace_set_iter.py', {'retrace-set-iter': 2}),
    ('bad_lock_order_cycle.py', {'lock-order-cycle': 1}),
    ('bad_lock_guard_write.py', {'lock-guard-write': 1}),
    ('bad_idem_undeclared.py', {'idem-undeclared-op': 1}),
    ('bad_idem_retry_unsafe.py', {'idem-retry-unsafe': 1,
                                  'idem-conditional-literal': 1}),
    ('bad_idem_unknown_op.py', {'idem-unknown-op': 2}),
    ('bad_idem_fabric.py', {'idem-unknown-op': 1,
                            'idem-conditional-literal': 1,
                            'idem-retry-unsafe': 1,
                            'idem-undeclared-op': 1}),
    ('bad_metric_family.py', {'metric-unknown-family': 1,
                              'metric-label-arity': 1}),
    ('bad_span_no_cm.py', {'span-no-cm': 2}),
    ('bad_atomic_write.py', {'atomic-write': 4}),
    ('bad_event_field.py', {'event-unknown-field': 2}),
]


@pytest.mark.parametrize('fixture,expected',
                         CORPUS, ids=[c[0] for c in CORPUS])
def test_fixture_triggers_rule(fixture, expected):
    findings = _lint([os.path.join(FIXTURES, fixture)])
    got = {}
    for f in findings:
        got[f.rule] = got.get(f.rule, 0) + 1
    assert got == expected, [str(f) for f in findings]


def test_fixture_exemptions_stay_quiet():
    """The corpus encodes negative space too: static_argnames branches,
    __init__ writes, *_locked helpers, dict-view iteration and the
    well-formed span shapes in bad_span_no_cm.good() must NOT fire.
    The exact-count assertions above already pin this; spot-check the
    two subtlest ones by symbol."""
    findings = _lint([os.path.join(FIXTURES, 'bad_retrace_set_iter.py')])
    assert all(f.rule == 'retrace-set-iter' for f in findings)
    findings = _lint([os.path.join(FIXTURES, 'bad_span_no_cm.py')])
    assert all('good' not in f.symbol for f in findings)


# ---------------------------------------------------------------- the gate

def test_repo_is_clean_under_graftlint():
    """The tier-1 gate itself: paddle_tpu/ and tools/ lint clean modulo
    the committed baseline. A new finding fails this test with the
    finding text in the assertion message."""
    findings = _lint(['paddle_tpu', 'tools'])
    new, pinned = apply_baseline(findings, load_baseline())
    assert new == [], '\n'.join(str(f) for f in new)


def test_cli_exit_codes(tmp_path):
    out = io.StringIO()
    assert cli.main(['--json', '--no-baseline', 'paddle_tpu', 'tools'],
                    stream=out) == gate_common.OK
    summary = json.loads(out.getvalue().splitlines()[-1])
    assert summary['ok'] is True and summary['modules'] > 150

    out = io.StringIO()
    assert cli.main(['--json', '--no-baseline', FIXTURES],
                    stream=out) == gate_common.FAIL
    lines = [json.loads(x) for x in out.getvalue().splitlines()]
    assert lines and all(d.get('regression') for d in lines)

    # --fix-baseline pins the corpus; a rerun against that baseline is OK
    bl = tmp_path / 'baseline.json'
    out = io.StringIO()
    assert cli.main(['--json', '--fix-baseline', '--baseline', str(bl),
                     FIXTURES], stream=out) == gate_common.OK
    out = io.StringIO()
    assert cli.main(['--json', '--baseline', str(bl), FIXTURES],
                    stream=out) == gate_common.OK
    summary = json.loads(out.getvalue().splitlines()[-1])
    assert summary['pinned'] == summary['findings'] > 0


FIXED_FILES = [
    'paddle_tpu/serving/gateway/replica.py',
    'paddle_tpu/serving/metrics.py',
    'paddle_tpu/distributed/resilience.py',
    'paddle_tpu/distributed/ps/embedding_service.py',
    'paddle_tpu/distributed/graph_service.py',
    'paddle_tpu/hapi/callbacks.py',
]


def test_fixed_files_stay_clean():
    """Regression pins for the violations this lint originally surfaced
    and we fixed (bare replica-state writes racing the driver's
    condvar-guarded transition; metric families registered off-baseline;
    undeclared RPC op semantics). Zero findings, forever."""
    findings = _lint(FIXED_FILES)
    assert findings == [], '\n'.join(str(f) for f in findings)


def test_idempotency_is_cross_module():
    """The client send-sites in embedding_service/graph_service must
    judge against OP_SEMANTICS declared in the same files — removing a
    declaration has to surface as a finding even when linting the whole
    package (whole-program, not per-file)."""
    import re
    path = os.path.join(REPO, 'paddle_tpu/distributed/ps/'
                              'embedding_service.py')
    with open(path) as f:
        src = f.read()
    mutated = re.sub(r"^\s*'push':.*$", '', src, count=1, flags=re.M)
    assert mutated != src
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        mpath = os.path.join(td, 'embedding_service.py')
        with open(mpath, 'w') as f:
            f.write(mutated)
        findings = _lint([mpath])
    assert any(f.rule == 'idem-undeclared-op' and "'push'" in f.message
               for f in findings), [str(f) for f in findings]


def test_metric_baseline_is_two_way(tmp_path):
    """Code->baseline: an unknown family fails (fixture corpus).
    Baseline->code: a family present in the schema but registered
    nowhere fails too — checked with a doctored schema so the committed
    one stays clean."""
    with open(os.path.join(REPO, 'tools/metrics_schema_baseline.json')) as f:
        schema = json.load(f)
    schema['bogus_family_total'] = {'labels': [], 'type': 'counter'}
    doctored = tmp_path / 'schema.json'
    doctored.write_text(json.dumps(schema))
    project = Project.load(['paddle_tpu'], root=REPO)
    findings = MetricsChecker(schema_path=str(doctored)).check(project)
    assert any(f.rule == 'metric-stale-family'
               and f.symbol == 'bogus_family_total' for f in findings), \
        [str(f) for f in findings]


def test_event_baseline_is_two_way(tmp_path):
    """Same discipline for the wide-event schema: code->baseline is the
    fixture corpus; baseline->code is checked with a doctored baseline
    listing a field no REQUEST_EVENT_FIELDS table declares."""
    from tools.graftlint.checkers.events import EventsChecker
    with open(os.path.join(REPO, 'tools/request_event_baseline.json')) as f:
        baseline = json.load(f)
    baseline['fields'].append('bogus_field')
    doctored = tmp_path / 'events.json'
    doctored.write_text(json.dumps(baseline))
    project = Project.load(['paddle_tpu'], root=REPO)
    findings = EventsChecker(baseline_path=str(doctored)).check(project)
    assert any(f.rule == 'event-stale-field'
               and f.symbol == 'bogus_field' for f in findings), \
        [str(f) for f in findings]
    # the stale check is anchored on the events module: a fixture-only
    # run must not drown in repo-wide stale noise
    fixture_only = Project.load([os.path.join(FIXTURES,
                                              'bad_event_field.py')],
                                root=REPO)
    assert all(f.rule != 'event-stale-field'
               for f in EventsChecker(
                   baseline_path=str(doctored)).check(fixture_only))


def test_baseline_roundtrip(tmp_path):
    findings = _lint([FIXTURES])
    assert findings
    path = write_baseline(findings, str(tmp_path / 'bl.json'))
    new, pinned = apply_baseline(findings, load_baseline(path))
    assert new == [] and len(pinned) == len(findings)
    # one extra occurrence of a pinned fingerprint is NOT absorbed
    new, _ = apply_baseline(findings + [findings[0]], load_baseline(path))
    assert len(new) == 1


# ------------------------------------------------------------- gate_common

def test_gate_common_convention():
    out = io.StringIO()
    assert gate_common.finish([], {'n': 1}, stream=out) == 0
    assert json.loads(out.getvalue())['ok'] is True
    out = io.StringIO()
    assert gate_common.finish([{'metric': 'm'}], stream=out) == 1
    assert json.loads(out.getvalue())['regression'] is True
    out = io.StringIO()
    assert gate_common.nothing_to_check('empty', stream=out) == 2
    assert json.loads(out.getvalue())['checked'] == 0


@pytest.mark.parametrize('argv', [
    [sys.executable, 'tools/check_metrics_snapshot.py', '--text', '-'],
    [sys.executable, 'tools/check_bench_regression.py',
     '--new', os.devnull, '--baseline', os.devnull],
    [sys.executable, '-m', 'tools.graftlint'],
    [sys.executable, 'tools/request_report.py', '--text', '-'],
], ids=['metrics', 'bench', 'graftlint', 'request_report'])
def test_gates_share_nothing_to_check_shape(argv):
    """Every gate speaks the same protocol: empty input -> exit 2 with a
    single {'checked': 0, ...} JSON line."""
    proc = subprocess.run(argv, cwd=REPO, input='', capture_output=True,
                          text=True)
    assert proc.returncode == gate_common.NOTHING, proc.stderr
    note = json.loads(proc.stdout.splitlines()[-1])
    assert note['checked'] == 0 and note['note']


# --------------------------------------------------------------- lockwatch

def test_lockwatch_consistent_order_passes():
    from paddle_tpu.testing.lockwatch import LockWatch
    watch = LockWatch()
    a = watch.wrap('a', threading.Lock())
    b = watch.wrap('b', threading.Lock())

    def worker():
        for _ in range(50):
            with a:
                with b:
                    pass

    ts = [threading.Thread(target=worker) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    watch.assert_acyclic()
    assert watch.edges() == {('a', 'b'): 200}


def test_lockwatch_inversion_detected():
    from paddle_tpu.testing.lockwatch import LockOrderError, LockWatch
    watch = LockWatch()
    a = watch.wrap('a', threading.Lock())
    b = watch.wrap('b', threading.Lock())
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    with pytest.raises(LockOrderError) as exc:
        watch.assert_acyclic()
    assert ' -> '.join(['a', 'b', 'a']) in str(exc.value)


def test_lockwatch_strict_raises_at_acquire():
    from paddle_tpu.testing.lockwatch import LockOrderError, LockWatch
    watch = LockWatch(strict=True)
    a = watch.wrap('a', threading.Lock())
    b = watch.wrap('b', threading.Lock())
    with a:
        with b:
            pass
    with b:
        with pytest.raises(LockOrderError):
            a.acquire()


def test_lockwatch_condition_passthrough():
    """A wrapped Condition still behaves like one (wait/notify ride the
    same underlying lock), and re-entrant RLock acquires add no edges."""
    from paddle_tpu.testing.lockwatch import LockWatch
    watch = LockWatch()
    cv = watch.wrap('cv', threading.Condition())
    state = []

    def setter():
        with cv:
            state.append(1)
            cv.notify_all()

    t = threading.Thread(target=setter)
    with cv:
        t.start()
        while not state:
            cv.wait(timeout=5)
    t.join()
    assert state == [1]
    r = watch.wrap('r', threading.RLock())
    with r:
        with r:
            pass
    watch.assert_acyclic()
    assert watch.edges() == {}


def test_lockwatch_agrees_with_static_graph():
    """The cross-check the ISSUE asks for: runtime-observed edges from a
    live threaded interaction union the statically derived acquisition
    order, and the combined graph must stay acyclic. Uses the serving
    replica's real condvar protocol (the component whose bare-write race
    this PR fixed)."""
    from paddle_tpu.testing.lockwatch import LockWatch
    project = Project.load(['paddle_tpu/serving', 'paddle_tpu/monitor',
                            'paddle_tpu/distributed'], root=REPO)
    static_edges = [(a, b) for a, b, _, _ in acquisition_order(project)]

    watch = LockWatch()
    outer = watch.wrap('paddle_tpu.serving.gateway.replica:Replica._cv',
                       threading.Condition())
    inner = watch.wrap('paddle_tpu.monitor.registry:Registry._lock',
                       threading.Lock())

    def worker():
        with outer:
            with inner:
                pass

    ts = [threading.Thread(target=worker) for _ in range(2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    watch.assert_acyclic(extra_edges=static_edges)
