"""paddle.distributed.spawn multi-process path (VERDICT r2: 'multi-proc
branch untested'). Real subprocesses on localhost — the reference
TestDistBase spawn pattern (test_dist_base.py:866)."""
import os

import numpy as np
import pytest

import importlib
import functools
import subprocess
import sys

# the package re-exports the spawn FUNCTION under the same name; fetch
# the module itself
spawn_mod = importlib.import_module('paddle_tpu.distributed.spawn')


@functools.lru_cache(None)
def _children_can_import():
    """A spawned child re-imports paddle_tpu at interpreter startup.
    Since r4 the spawn bootstrap forces the CPU backend into child env
    (spawn._platform_env) so the axon TPU claim cannot wedge the import;
    probe with the same env the children get."""
    env = dict(os.environ)
    env.update(spawn_mod._platform_env())
    try:
        proc = subprocess.run(
            [sys.executable, '-c',
             'import sys; sys.path.insert(0, %r); import paddle_tpu'
             % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))],
            timeout=60, capture_output=True, env=env)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


# r3 skipped here (children wedged importing under the axon shim); the
# guard stays as a tripwire but must not fire — test_children_import_probe
# fails loudly if the bootstrap regresses
needs_spawn = pytest.mark.skipif(
    not _children_can_import(),
    reason='spawned children cannot import the framework in this '
           'environment (TPU claim wedges at child startup)')


def test_children_import_probe():
    """The r3 skip condition is fixed, not worked around: children must
    import the framework under the spawn bootstrap env."""
    assert _children_can_import()


def _rank_worker(out_dir):
    # child process: record the env contract
    rank = os.environ['PADDLE_TRAINER_ID']
    n = os.environ['PADDLE_TRAINERS_NUM']
    ep = os.environ['PADDLE_CURRENT_ENDPOINT']
    eps = os.environ['PADDLE_TRAINER_ENDPOINTS'].split(',')
    assert ep in eps and len(eps) == int(n)
    with open(os.path.join(out_dir, 'rank_%s' % rank), 'w') as f:
        f.write('%s/%s %s' % (rank, n, ep))


def _failing_worker():
    raise ValueError('rank exploded on purpose')


@needs_spawn
def test_spawn_two_processes_env_contract(tmp_path):
    spawn_mod.spawn(_rank_worker, args=(str(tmp_path),), nprocs=2)
    files = sorted(os.listdir(tmp_path))
    assert files == ['rank_0', 'rank_1']
    body0 = (tmp_path / 'rank_0').read_text()
    body1 = (tmp_path / 'rank_1').read_text()
    assert body0.startswith('0/2') and body1.startswith('1/2')
    # distinct endpoints per rank
    assert body0.split()[1] != body1.split()[1]


@needs_spawn
def test_spawn_propagates_child_failure():
    with pytest.raises(RuntimeError, match='exploded on purpose'):
        spawn_mod.spawn(_failing_worker, nprocs=2)


@needs_spawn
def test_spawn_nonjoin_returns_context(tmp_path):
    ctx = spawn_mod.spawn(_rank_worker, args=(str(tmp_path),), nprocs=2,
                          join=False)
    assert ctx is not None and len(ctx.processes) == 2
    ctx.join()
    assert sorted(os.listdir(tmp_path)) == ['rank_0', 'rank_1']


def test_spawn_single_proc_inline():
    called = []
    spawn_mod.spawn(lambda: called.append(1), nprocs=1)
    assert called == [1]
