"""Expert-parallel MoE (beyond-reference; SURVEY.md §2.2 notes its absence
from the snapshot — expert parallelism is in the capability bar and the
driver contract's tp/pp/dp/sp/ep axes).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.moe import SwitchMoE
from paddle_tpu.distributed import fleet
from paddle_tpu.text.models import GPTConfig, GPTForCausalLM


def test_switch_moe_routes_to_argmax_expert():
    """With generous capacity, each token's output must equal its top-1
    expert's FFN applied to it, scaled by the gate prob (python-loop
    reference over the layer's own weights)."""
    paddle.seed(0)
    moe = SwitchMoE(hidden_size=8, ffn_size=16, num_experts=4,
                    capacity_factor=4.0)
    rng = np.random.RandomState(1)
    x = rng.randn(6, 8).astype(np.float32)
    y = moe(paddle.to_tensor(x)).numpy()

    import math as _m

    def gelu_np(v):
        return np.asarray([0.5 * t * (1 + _m.erf(t / _m.sqrt(2)))
                           for t in v.ravel()]).reshape(v.shape)

    gw = moe.gate.weight.numpy()
    gb = moe.gate.bias.numpy()
    w1, b1 = moe.w1.numpy(), moe.b1.numpy()
    w2, b2 = moe.w2.numpy(), moe.b2.numpy()
    logits = x @ gw + gb
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    for t in range(x.shape[0]):
        e = int(np.argmax(probs[t]))
        h = gelu_np(x[t] @ w1[e] + b1[e])
        expect = (h @ w2[e] + b2[e]) * probs[t, e]
        np.testing.assert_allclose(y[t], expect, rtol=2e-4, atol=2e-4)
    assert moe.aux_loss is not None
    assert float(moe.aux_loss.numpy()) > 0


def test_switch_moe_capacity_drops_to_residual_zero():
    """capacity 1 token/expert: overflowing tokens produce zero output
    (the residual connection outside the layer keeps them alive)."""
    paddle.seed(1)
    moe = SwitchMoE(hidden_size=4, ffn_size=8, num_experts=2,
                    capacity_factor=0.26)  # cap = 1 for 8 tokens
    x = paddle.to_tensor(np.ones((8, 4), np.float32))
    y = moe(x).numpy()
    # identical tokens all route to one expert; only 1 fits capacity
    nonzero_rows = np.abs(y).sum(-1) > 1e-9
    assert nonzero_rows.sum() == 1


@pytest.mark.slow
def test_moe_gpt_trains_on_ep_mesh():
    """GPT with SwitchMoE blocks under dp2 x ep4: fleet step runs, loss
    decreases, expert params sharded over ep in the step's shardings."""
    paddle.seed(5)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=16, dropout=0.0,
                    num_experts=4, intermediate_size=64)
    model = GPTForCausalLM(cfg)

    s = fleet.DistributedStrategy()
    s.hybrid_configs = {'dp_degree': 2, 'mp_degree': 1, 'pp_degree': 1,
                        'sharding_degree': 1, 'sp_degree': 1,
                        'ep_degree': 4}
    fleet.init(is_collective=True, strategy=s)
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
    step = fleet.fleet_train_step(
        model, lambda lg, lb: model.loss(lg, lb), opt, strategy=s)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype(np.int32))
    lbl = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype(np.int32))
    losses = [float(step(ids, lbl).numpy()) for _ in range(5)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]

    # expert-stacked params actually got ep shardings
    from paddle_tpu.distributed import strategy as strat
    shards = strat.build_shardings(model, opt, fleet._FLEET['hcg'].mesh,
                                   fleet._strategy_dict(s))
    w1_name = [n for n in shards['param_shardings'] if n.endswith('.w1')][0]
    assert 'ep' in str(shards['param_shardings'][w1_name].spec)


def test_moe_matches_dense_when_single_expert():
    """num_experts=1, ample capacity: MoE degenerates to one FFN — loss
    parity with direct expert application confirms dispatch/combine."""
    paddle.seed(2)
    moe = SwitchMoE(hidden_size=8, ffn_size=16, num_experts=1,
                    capacity_factor=2.0)
    rng = np.random.RandomState(3)
    x = rng.randn(2, 5, 8).astype(np.float32)
    y = moe(paddle.to_tensor(x)).numpy()
    assert y.shape == (2, 5, 8)
    assert np.all(np.isfinite(y))


def test_gshard_top2_matches_dense_reference():
    """Top-2 routing with ample capacity equals the dense two-expert
    mixture: y = sum_k gate_k * FFN_{e_k}(x), gates renormalized over
    the chosen pair."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.incubate.moe import GShardMoE

    paddle.seed(0)
    h, e, t = 8, 4, 10
    moe = GShardMoE(h, ffn_size=16, num_experts=e, capacity_factor=4.0)
    x_np = np.random.RandomState(0).randn(1, t, h).astype(np.float32)
    y = moe(paddle.to_tensor(x_np)).numpy()[0]

    # dense reference
    gl = (x_np[0] @ moe.gate.weight.numpy() + moe.gate.bias.numpy())
    probs = np.exp(gl) / np.exp(gl).sum(-1, keepdims=True)
    w1, b1 = moe.w1.numpy(), moe.b1.numpy()
    w2, b2 = moe.w2.numpy(), moe.b2.numpy()

    def ffn(tok, ei):
        h1 = np.asarray(jax.nn.gelu(tok @ w1[ei] + b1[ei]))
        return h1 @ w2[ei] + b2[ei]

    want = np.zeros_like(y)
    for i in range(t):
        top2 = np.argsort(-probs[i])[:2]
        g = probs[i][top2]
        g = g / g.sum()
        want[i] = g[0] * ffn(x_np[0, i], top2[0]) + \
            g[1] * ffn(x_np[0, i], top2[1])
    np.testing.assert_allclose(y, want, rtol=2e-4, atol=2e-5)
    assert moe.aux_loss is not None


def test_top2_capacity_overflow_drops_second_choice_first():
    """With capacity 1 per expert, top-1 assignments win the slots; an
    overflowing token's contribution is partially dropped (its kept
    gates sum to < 1)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.moe import SwitchMoE

    paddle.seed(1)
    h, e, t = 4, 2, 6
    moe = SwitchMoE(h, ffn_size=8, num_experts=e, top_k=2,
                    capacity_factor=0.34)  # cap = 1 slot per expert
    x = paddle.to_tensor(np.random.RandomState(1)
                         .randn(1, t, h).astype(np.float32))
    y = moe(x)
    assert np.isfinite(y.numpy()).all()
    # overflow is real: with 2 slots total for 6 tokens x 2 choices,
    # some token's kept gate mass must fall below ~1, so its output
    # norm shrinks vs the ample-capacity run
    moe_ample = SwitchMoE(h, ffn_size=8, num_experts=e, top_k=2,
                          capacity_factor=8.0)
    moe_ample.set_state_dict(moe.state_dict())
    y_full = moe_ample(x).numpy()
    norms = np.linalg.norm(y.numpy()[0], axis=-1)
    norms_full = np.linalg.norm(y_full[0], axis=-1)
    assert (norms < norms_full - 1e-6).any()


def test_switch_moe_bf16_close_to_f32():
    """The low-precision expert path (native-dtype contractions with f32
    MXU accumulation) must track the f32 layer within bf16 resolution —
    locks the dtype contract the f32-matmul audit installed."""
    paddle.seed(0)
    moe = SwitchMoE(hidden_size=16, ffn_size=32, num_experts=4,
                    capacity_factor=4.0)
    rng = np.random.RandomState(2)
    x = rng.randn(10, 16).astype(np.float32)
    y32 = moe(paddle.to_tensor(x)).numpy()

    moe.bfloat16()
    yb = moe(paddle.to_tensor(x).astype('bfloat16'))
    assert str(yb.dtype).endswith('bfloat16')
    yb = np.asarray(yb.numpy(), np.float32)
    denom = max(float(np.abs(y32).max()), 1e-6)
    assert float(np.abs(yb - y32).max()) / denom < 0.05
