"""Optimizer-op variants (VERDICT r2 missing #3: operators/optimizers/
ftrl_op.cc, dpsgd_op.cc, proximal_gd_op.cc, proximal_adagrad_op.cc,
adam lazy_mode), encrypted save/load (framework/io/crypto/cipher.cc),
and the op micro-benchmark harness (operators/benchmark/op_tester.cc).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _quadratic_setup(opt_cls, seed=3, **kw):
    paddle.seed(seed)
    lin = nn.Linear(4, 1)
    opt = opt_cls(parameters=lin.parameters(), **kw)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 4).astype(np.float32))
    w_true = np.asarray([[1.0], [-2.0], [0.5], [0.0]], np.float32)
    y = paddle.to_tensor((rng.randn(16, 4).astype(np.float32) @ w_true))
    return lin, opt, x, y


@pytest.mark.parametrize('opt_cls,kw', [
    # lr 0.2 for the two slowest-converging variants: at 0.1 they land
    # at ~0.708x in 30 steps, a hair over the 0.7 gate
    (paddle.optimizer.Ftrl, {'learning_rate': 0.2, 'l1': 0.001}),
    (paddle.optimizer.Dpsgd, {'learning_rate': 0.05, 'clip': 5.0,
                              'batch_size': 16.0, 'sigma': 0.01}),
    (paddle.optimizer.ProximalGD, {'learning_rate': 0.05, 'l1': 1e-4,
                                   'l2': 1e-4}),
    (paddle.optimizer.ProximalAdagrad, {'learning_rate': 0.2, 'l1': 1e-4}),
    (paddle.optimizer.SparseAdam, {'learning_rate': 0.05}),
])
def test_variant_reduces_loss(opt_cls, kw):
    import paddle_tpu.nn.functional as F
    lin, opt, x, y = _quadratic_setup(opt_cls, **kw)
    losses = []
    for _ in range(30):
        out = lin(x)
        loss = F.mse_loss(out, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_ftrl_l1_produces_sparsity():
    # strong L1 should drive weights toward exact zeros
    paddle.seed(0)
    p = paddle.to_tensor(np.asarray([0.01, -0.02, 0.5], np.float32),
                         stop_gradient=False)
    from paddle_tpu.framework.core import Parameter
    import jax.numpy as jnp
    param = Parameter(p._data)
    opt = paddle.optimizer.Ftrl(learning_rate=0.5, l1=5.0,
                                parameters=[param])
    slots = opt._get_slots(param)
    g = jnp.asarray([0.001, 0.001, 0.001], jnp.float32)
    new_p, _ = opt._apply(param._data, g, slots, 0.5, 1)
    assert np.count_nonzero(np.asarray(new_p)) == 0  # shrunk to zero


def test_sparse_adam_freezes_untouched_rows():
    from paddle_tpu.framework.core import Parameter
    import jax.numpy as jnp
    param = Parameter(np.ones((4, 3), np.float32))
    opt = paddle.optimizer.SparseAdam(learning_rate=0.1,
                                      parameters=[param])
    slots = opt._get_slots(param)
    g = np.zeros((4, 3), np.float32)
    g[1] = 0.5  # only row 1 touched
    new_p, new_slots = opt._apply(param._data, jnp.asarray(g), slots,
                                  0.1, 1)
    new_p = np.asarray(new_p)
    np.testing.assert_array_equal(new_p[0], param.numpy()[0])  # frozen
    assert not np.allclose(new_p[1], param.numpy()[1])          # updated
    assert np.all(np.asarray(new_slots['moment1'])[0] == 0)


def test_encrypted_save_load_roundtrip(tmp_path):
    from paddle_tpu.framework import crypto
    key = crypto.generate_key()
    state = {'w': paddle.to_tensor(np.arange(6, dtype=np.float32))}
    path = str(tmp_path / 'enc.pdparams')
    paddle.save(state, path, encryption_key=key)

    raw = open(path, 'rb').read()
    assert raw.startswith(b'PTCRYPT1')
    assert b'numpy' not in raw  # pickle bytes are not in the clear

    loaded = paddle.load(path, encryption_key=key)
    np.testing.assert_array_equal(loaded['w'].numpy(),
                                  np.arange(6, dtype=np.float32))

    with pytest.raises(ValueError, match='encrypted'):
        paddle.load(path)
    with pytest.raises(ValueError, match='wrong key|corrupted'):
        paddle.load(path, encryption_key='not-the-key')


def test_cipher_api_and_fallback(tmp_path):
    from paddle_tpu.framework import crypto
    c = crypto.CipherFactory.create_cipher()
    blob = c.encrypt(b'secret weights', 'k1')
    assert c.decrypt(blob, 'k1') == b'secret weights'
    # HMAC-CTR fallback scheme decrypts its own output too
    k = crypto._norm_key('k2')
    nonce = b'\x00' * 12
    ct = crypto._hmac_ctr(k, nonce, b'payload')
    assert crypto._hmac_ctr(k, nonce, ct) == b'payload'


def test_op_benchmark_harness_and_gate():
    from paddle_tpu.utils import op_benchmark as ob
    results = ob.run_benchmarks(
        configs=[('matmul_tiny', lambda: ob._matmul(64, 64, 64,
                                                    'float32'))],
        repeat=3, warmup=1)
    assert results[0]['ok'] and results[0]['mean_ms'] > 0
    base = [{'op': 'matmul_tiny', 'mean_ms': results[0]['mean_ms'] / 10,
             'ok': True}]
    regs = ob.compare(base, results, threshold=0.15)
    assert regs and regs[0]['op'] == 'matmul_tiny'
    assert ob.compare(results, results, threshold=0.15) == []


def test_lookahead_slow_weights_pull():
    """k fast steps then slow<-slow+alpha*(fast-slow) (reference
    LookaheadOptimizer :5969)."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.incubate.optimizer import LookAhead
    lin, inner, x, y = _quadratic_setup(paddle.optimizer.SGD,
                                        learning_rate=0.1)
    la = LookAhead(inner, alpha=0.5, k=3)
    w0 = lin.weight.numpy().copy()
    trace = []
    for i in range(6):
        loss = F.mse_loss(lin(x), y)
        loss.backward()
        la.step()
        la.clear_grad()
        trace.append(lin.weight.numpy().copy())
    # after step 3 (k reached) the weights jumped back toward w0
    # (interpolation), so ||w3 - w0|| < ||w2 - w0||
    d2 = np.linalg.norm(trace[1] - w0)
    d3 = np.linalg.norm(trace[2] - w0)
    assert d3 < d2 * 0.75  # pullback happened at the k-th step


def test_model_average_apply_restore():
    from paddle_tpu.incubate.optimizer import ModelAverage
    from paddle_tpu.framework.core import Parameter
    p = Parameter(np.zeros(3, np.float32))
    ma = ModelAverage(parameters=[p])
    for v in (1.0, 2.0, 3.0):
        p._data = np.full(3, v, np.float32) * 1.0
        import jax.numpy as jnp
        p._data = jnp.asarray(p._data)
        ma.step()
    cur = p.numpy().copy()
    with ma.apply():
        np.testing.assert_allclose(p.numpy(), np.full(3, 2.0), atol=1e-6)
    np.testing.assert_allclose(p.numpy(), cur)  # restored


def test_ema_tracks_and_restores():
    from paddle_tpu.incubate.optimizer import ExponentialMovingAverage
    from paddle_tpu.framework.core import Parameter
    import jax.numpy as jnp
    p = Parameter(np.ones(2, np.float32))
    ema = ExponentialMovingAverage(decay=0.5, parameters=[p])
    p._data = jnp.asarray(np.full(2, 3.0, np.float32))
    ema.update()   # shadow = 0.5*1 + 0.5*3 = 2
    cur = p.numpy().copy()
    ema.apply(need_restore=False)
    np.testing.assert_allclose(p.numpy(), np.full(2, 2.0))
    ema.restore()
    np.testing.assert_allclose(p.numpy(), cur)
