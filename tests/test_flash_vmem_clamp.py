"""Scoped-VMEM footprint gate for the flash-attention block clamp.

The r5 in-window failure this hardens against: the STANDARD kernels at
seq 4096 with 512/1024 blocks died compiling with
"kernel-vmem-stack-oom" (docs/bench_inwindow_r5.jsonl 09:32:35Z) — the
divisibility clamp launched a config Mosaic could not hold. The gate
must refuse exactly that config with a clear error, while keeping every
configuration the captures show running on hardware: 2048 at the same
blocks, 4096 at 256/512, the seq-512 fused-backward headline, and the
long-kernel rungs.
"""
import jax.numpy as jnp
import pytest

from paddle_tpu.ops import flash_attention as fa


def _force_std(monkeypatch, bq, bk):
    """Emulate the capture's env: long path off, fwd+bwd blocks pinned.
    (bench children re-import with the env set; in-process tests pin the
    import-latched module constants instead.)"""
    monkeypatch.setattr(fa, '_LONG_SEQ', 10 ** 9)
    monkeypatch.setattr(fa, '_DEFAULT_BLOCK_Q', bq)
    monkeypatch.setattr(fa, '_DEFAULT_BLOCK_K', bk)
    monkeypatch.setattr(fa, '_BLOCK_Q_BWD', bq)
    monkeypatch.setattr(fa, '_BLOCK_K_BWD', bk)
    monkeypatch.delenv('PADDLE_TPU_FLASH_INTERPRET', raising=False)
    monkeypatch.delenv('PADDLE_TPU_FLASH_VMEM_BUDGET_MB', raising=False)


def _mk(n, dtype=jnp.bfloat16):
    return jnp.zeros((1, 1, n, 64), dtype)


def test_rejects_the_r5_vmem_oom_config(monkeypatch):
    _force_std(monkeypatch, 512, 1024)
    q = _mk(4096)
    reason = fa._supported(q, q, q)
    assert reason is not None
    assert 'VMEM' in reason and 'dk/dv' in reason
    assert 'PADDLE_TPU_FLASH_VMEM_BUDGET_MB' in reason
    # strict mode (the bench-honesty contract): refuse loudly instead of
    # handing Mosaic a config it cannot compile
    monkeypatch.setenv('PADDLE_TPU_FLASH_STRICT', '1')
    with pytest.raises(RuntimeError, match='scoped VMEM'):
        fa.flash_attention_bhnd(q, q, q)


def test_accepts_every_config_that_ran_on_hardware(monkeypatch):
    # std 2048 @ 512/1024 (longseq2048_flash_bq512_bk1024: 148 ms)
    _force_std(monkeypatch, 512, 1024)
    q = _mk(2048)
    assert fa._supported(q, q, q) is None
    # std 4096 @ 256/512 (fused_flash_seq4096_b4_scan2)
    _force_std(monkeypatch, 256, 512)
    q = _mk(4096)
    assert fa._supported(q, q, q) is None
    # the seq-512 fused-backward headline config
    _force_std(monkeypatch, 512, 512)
    q = _mk(512)
    assert fa._supported(q, q, q) is None
    # stock knobs route 4096 to the LONG kernels, which stage O(block)
    # and ran at 197.8 ms (longseq4096_longkern_bq512_bk1024)
    monkeypatch.setattr(fa, '_LONG_SEQ', 4096)
    q = _mk(4096)
    assert fa._supported(q, q, q) is None
    # and the 8k long rung at the wide 512/2048 KV block
    monkeypatch.setattr(fa, '_BLOCK_K_LONG', 2048)
    q = _mk(8192)
    assert fa._supported(q, q, q) is None


def test_budget_knob_moves_the_gate(monkeypatch):
    _force_std(monkeypatch, 512, 1024)
    q = _mk(4096)
    assert fa._supported(q, q, q) is not None
    # a v6-sized budget admits the config the v5e budget refuses
    monkeypatch.setenv('PADDLE_TPU_FLASH_VMEM_BUDGET_MB', '64')
    assert fa._supported(q, q, q) is None
    # a starved budget rejects even the headline config
    monkeypatch.setenv('PADDLE_TPU_FLASH_VMEM_BUDGET_MB', '1')
    _force_std(monkeypatch, 512, 512)
    monkeypatch.setenv('PADDLE_TPU_FLASH_VMEM_BUDGET_MB', '1')
    q = _mk(512)
    assert fa._supported(q, q, q) is not None


def test_interpreter_mode_skips_the_gate(monkeypatch):
    """The CPU interpreter has no VMEM: the correctness tests must keep
    running shapes the hardware budget would refuse."""
    _force_std(monkeypatch, 512, 1024)
    monkeypatch.setenv('PADDLE_TPU_FLASH_INTERPRET', '1')
    q = _mk(4096)
    assert fa._supported(q, q, q) is None
