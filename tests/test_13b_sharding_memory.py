"""BASELINE config 5 evidence at 13B dims WITHOUT hardware
(VERDICT r4 missing #3 / next #4).

GPT-3-13B dimensions — hidden 5120, 40 heads, vocab 50304 — compiled
under sharding_stage3 (ZeRO-3) x pipeline-parallel on the 8-device CPU
mesh. Lowering + compiling allocates no device buffers for the step, so
the 13B-scale partitioning claims are checkable on CPU: the compiled
executable's per-device argument bytes prove params+optimizer state are
REALLY sharded (silent replication fails the assertion by an order of
magnitude), and a two-point layer-count fit projects the full 40-layer
model against the v5p HBM budget.

Layer count is reduced for the CPU compile budget (the per-LAYER
partitioning behavior is what ZeRO-3+pp decides; layers are homogeneous,
so bytes scale affinely in depth — the two-point fit measures exactly
that affine law and the projection documents it). Reference bar:
python/paddle/distributed/fleet/meta_optimizers/sharding_optimizer.py:97
(the 1436-line program rewrite that exists precisely for this scale).
"""
import re

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

HIDDEN, HEADS, VOCAB = 5120, 40, 50304
SEQ, BATCH = 512, 4
V5P_HBM = 95e9               # bytes per chip
SHARDING, PP = 4, 2          # sharding_stage3 x pp over the 8-dev mesh


def _arg_bytes(num_layers):
    """Per-device argument bytes of the compiled ZeRO-3 x pp train step
    at 13B dims with `num_layers` layers."""
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=HIDDEN,
                    num_layers=num_layers, num_heads=HEADS,
                    max_position_embeddings=SEQ, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.bfloat16()           # the config-5 training dtype

    s = fleet.DistributedStrategy()
    s.hybrid_configs = {'dp_degree': 1, 'mp_degree': 1, 'pp_degree': PP,
                        'sharding_degree': SHARDING, 'sp_degree': 1}
    s.sharding = True
    s.sharding_configs['stage'] = 3
    fleet.init(is_collective=True, strategy=s)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=True)
    step = fleet.fleet_train_step(
        model, lambda lg, lb: model.loss(lg, lb), opt, strategy=s)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, VOCAB, (BATCH, SEQ)).astype(np.int32))
    lbl = paddle.to_tensor(
        rng.randint(0, VOCAB, (BATCH, SEQ)).astype(np.int32))
    compiled = step.compiled_executable(ids, lbl)
    ma = compiled.memory_analysis()
    n_params = model.num_params()
    hlo = compiled.as_text()
    return (int(ma.argument_size_in_bytes), int(ma.temp_size_in_bytes),
            n_params, hlo)


@pytest.fixture(scope='module')
def two_point():
    b2 = _arg_bytes(2)
    b4 = _arg_bytes(4)
    return b2, b4


def test_13b_dims_zero3_pp_actually_shards(two_point):
    (arg2, _, n2, hlo), (arg4, _, n4, _) = two_point
    # bf16 params + f32 master/m/v AdamW state = 14 bytes/param if fully
    # replicated on every device. Coarse guard: the whole argument set
    # must be well under replicated (measured: 3.11 GB vs 12.45 GB at
    # L=2 — embedding+head shard over sharding=4 only, transformer
    # layers over sharding x pp = 8).
    replicated4 = 14.0 * n4
    assert arg4 < replicated4 / 3.0, (
        'per-device argument bytes %.2f GB vs replicated %.2f GB — '
        'ZeRO-3+pp is not sharding at 13B dims' %
        (arg4 / 1e9, replicated4 / 1e9))
    # the sharp catcher: the MARGINAL per-layer bytes (what config 5
    # scales in depth) must divide by ~sharding_degree (ZeRO-3 carries
    # param+opt residency; pp splits COMPUTE across stages — the stacked
    # layer params stay sharding-sharded, not stage-local, in the GSPMD
    # formulation). Require > 3x under replicated (measured ~4x): a
    # partitioner that replicates layer params or opt state fails wide.
    per_layer = (arg4 - arg2) / 2.0
    per_layer_repl = 14.0 * (n4 - n2) / 2.0
    assert per_layer < per_layer_repl / 3.0, (
        'per-device marginal layer bytes %.0f MB vs replicated %.0f MB' %
        (per_layer / 1e6, per_layer_repl / 1e6))
    # ZeRO-3 signature collectives must be in the partitioned program
    counts = {op: len(re.findall(op, hlo))
              for op in ('all-gather', 'reduce-scatter', 'all-reduce',
                         'collective-permute', 'all-to-all')}
    assert counts['all-gather'] >= 1, counts
    assert counts['reduce-scatter'] + counts['all-reduce'] >= 1, counts


def test_13b_40layer_projection_fits_v5p(two_point):
    (arg2, tmp2, _, _), (arg4, tmp4, _, _) = two_point
    # affine fit over homogeneous layers: bytes(L) = base + L * per_layer
    per_layer = (arg4 - arg2) / 2.0
    base = arg2 - 2 * per_layer
    assert per_layer > 0, (arg2, arg4)
    proj40 = base + 40 * per_layer
    tmp_per_layer = max(0.0, (tmp4 - tmp2) / 2.0)
    tmp40 = max(tmp2, tmp4) + 36 * tmp_per_layer
    # the claimed config-5 sharding must leave headroom on a v5p chip:
    # params+opt+activation-temp under 90% of HBM. (A v5p-64 run also
    # scales sharding_degree with the pod — this is the CONSERVATIVE
    # single-slice-8 check; more chips only shrink the per-device share.)
    assert proj40 + tmp40 < 0.9 * V5P_HBM, (
        'projected 40-layer per-device bytes %.1f GB args + %.1f GB temp '
        'exceed the v5p budget' % (proj40 / 1e9, tmp40 / 1e9))
