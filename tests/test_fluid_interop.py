"""Reference-artifact inference interop (VERDICT r3 item 3).

Builds byte-genuine reference-format model directories — `__model__`
ProgramDesc protobuf (framework.proto:202) + LoDTensor param files
(lod_tensor.cc:244 SerializeToStream layout) — with an INDEPENDENT
hand-rolled encoder, then serves them through inference.create_predictor
and checks the forward against numpy. Covers the book-test model shapes
(fit_a_line: mul+elementwise_add; recognize_digits: conv2d+batch_norm+
pool2d+fc+softmax), both separate-param-files and combined layouts.
"""
import os
import struct

import numpy as np
import pytest

from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.inference.fluid_program import (load_fluid_model,
                                                parse_program_desc,
                                                read_lod_tensor)


# -- independent proto2 wire writer ------------------------------------------

def _varint(v):
    if v < 0:
        v += 1 << 64  # two's complement (proto2 int32/int64 negatives)
    out = b''
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tag(field, wire):
    return _varint((field << 3) | wire)


def _len_field(field, payload):
    return _tag(field, 2) + _varint(len(payload)) + payload


def _vint_field(field, v):
    return _tag(field, 0) + _varint(v)


def _f32_field(field, v):
    return _tag(field, 5) + struct.pack('<f', v)


def _str_field(field, s):
    return _len_field(field, s.encode())


def _attr(name, atype, value):
    out = _str_field(1, name) + _vint_field(2, atype)
    if atype == 0:      # INT
        out += _vint_field(3, value)
    elif atype == 1:    # FLOAT
        out += _f32_field(4, value)
    elif atype == 2:    # STRING
        out += _str_field(5, value)
    elif atype == 3:    # INTS (proto2 default: unpacked)
        for v in value:
            out += _vint_field(6, v)
    elif atype == 6:    # BOOLEAN
        out += _vint_field(10, 1 if value else 0)
    elif atype == 11:   # LONGS
        for v in value:
            out += _vint_field(15, v)
    else:
        raise ValueError(atype)
    return out


def _op(op_type, inputs, outputs, attrs=()):
    out = b''
    for param, args in inputs:
        var = _str_field(1, param)
        for a in args:
            var += _str_field(2, a)
        out += _len_field(1, var)
    for param, args in outputs:
        var = _str_field(1, param)
        for a in args:
            var += _str_field(2, a)
        out += _len_field(2, var)
    out += _str_field(3, op_type)
    for a in attrs:
        out += _len_field(4, _attr(*a))
    return out


_FP32 = 5


def _tensor_desc(dtype, dims):
    out = _vint_field(1, dtype)
    for d in dims:
        out += _vint_field(2, d)
    return out


def _var(name, dims=None, vtype=7, dtype=_FP32, persistable=False):
    """vtype 7 = LOD_TENSOR, 9 = FEED_MINIBATCH, 10 = FETCH_LIST."""
    vt = _vint_field(1, vtype)
    if dims is not None:
        lod = _len_field(1, _tensor_desc(dtype, dims)) + _vint_field(2, 0)
        vt += _len_field(3, lod)
    out = _str_field(1, name) + _len_field(2, vt)
    if persistable:
        out += _vint_field(3, 1)
    return out


def _block(variables, ops, idx=0, parent=-1):
    out = _vint_field(1, idx) + _vint_field(2, parent)
    for v in variables:
        out += _len_field(3, v)
    for o in ops:
        out += _len_field(4, o)
    return out


def _program(blocks):
    out = b''
    for b in blocks:
        out += _len_field(1, b)
    out += _len_field(4, _vint_field(1, 0))  # Version{version=0}
    return out


def _write_lod_tensor(f, arr):
    """lod_tensor.cc SerializeToStream: u32 ver, u64 lod levels, then
    tensor_util.cc TensorToStream: u32 ver, i32 desc size, desc, data."""
    f.write(struct.pack('<I', 0))
    f.write(struct.pack('<Q', 0))
    desc = _tensor_desc(_FP32, arr.shape)
    f.write(struct.pack('<I', 0))
    f.write(struct.pack('<i', len(desc)))
    f.write(desc)
    f.write(np.ascontiguousarray(arr, np.float32).tobytes())


# -- model builders -----------------------------------------------------------

def _fit_a_line_dir(tmp_path, combined):
    rng = np.random.RandomState(0)
    w = rng.randn(13, 1).astype(np.float32)
    b = rng.randn(1).astype(np.float32)

    variables = [
        _var('feed', vtype=9, persistable=True),
        _var('fetch', vtype=10, persistable=True),
        _var('x', dims=[-1, 13]),
        _var('fc_w', dims=[13, 1], persistable=True),
        _var('fc_b', dims=[1], persistable=True),
        _var('fc_tmp', dims=[-1, 1]),
        _var('out', dims=[-1, 1]),
    ]
    ops = [
        _op('feed', [('X', ['feed'])], [('Out', ['x'])],
            [('col', 0, 0)]),
        _op('mul', [('X', ['x']), ('Y', ['fc_w'])],
            [('Out', ['fc_tmp'])],
            [('x_num_col_dims', 0, 1), ('y_num_col_dims', 0, 1)]),
        _op('elementwise_add', [('X', ['fc_tmp']), ('Y', ['fc_b'])],
            [('Out', ['out'])], [('axis', 0, 1)]),
        _op('fetch', [('X', ['out'])], [('Out', ['fetch'])],
            [('col', 0, 0)]),
    ]
    d = tmp_path / ('fit_a_line_comb' if combined else 'fit_a_line')
    d.mkdir()
    (d / '__model__').write_bytes(_program([_block(variables, ops)]))
    params = {'fc_w': w, 'fc_b': b}
    if combined:
        with open(d / '__params__', 'wb') as f:
            for name in sorted(params):
                _write_lod_tensor(f, params[name])
    else:
        for name, arr in params.items():
            with open(d / name, 'wb') as f:
                _write_lod_tensor(f, arr)
    return d, w, b


def _digits_cnn_dir(tmp_path):
    """recognize_digits-style: conv2d -> batch_norm -> relu -> pool2d ->
    flatten -> fc(mul+add) -> softmax."""
    rng = np.random.RandomState(1)
    conv_w = (rng.randn(4, 1, 3, 3) * 0.5).astype(np.float32)
    bn_scale = rng.rand(4).astype(np.float32) + 0.5
    bn_bias = rng.randn(4).astype(np.float32)
    bn_mean = rng.randn(4).astype(np.float32) * 0.1
    bn_var = rng.rand(4).astype(np.float32) + 0.5
    fc_w = (rng.randn(4 * 13 * 13, 10) * 0.1).astype(np.float32)
    fc_b = rng.randn(10).astype(np.float32)

    variables = [
        _var('feed', vtype=9, persistable=True),
        _var('fetch', vtype=10, persistable=True),
        _var('img', dims=[-1, 1, 28, 28]),
        _var('conv_w', dims=[4, 1, 3, 3], persistable=True),
        _var('bn_scale', dims=[4], persistable=True),
        _var('bn_bias', dims=[4], persistable=True),
        _var('bn_mean', dims=[4], persistable=True),
        _var('bn_var', dims=[4], persistable=True),
        _var('fc_w', dims=[4 * 13 * 13, 10], persistable=True),
        _var('fc_b', dims=[10], persistable=True),
        _var('conv_out', dims=[-1, 4, 26, 26]),
        _var('bn_out', dims=[-1, 4, 26, 26]),
        _var('relu_out', dims=[-1, 4, 26, 26]),
        _var('pool_out', dims=[-1, 4, 13, 13]),
        _var('flat_out', dims=[-1, 4 * 13 * 13]),
        _var('fc_tmp', dims=[-1, 10]),
        _var('fc_out', dims=[-1, 10]),
        _var('prob', dims=[-1, 10]),
    ]
    ops = [
        _op('feed', [('X', ['feed'])], [('Out', ['img'])], [('col', 0, 0)]),
        _op('conv2d', [('Input', ['img']), ('Filter', ['conv_w'])],
            [('Output', ['conv_out'])],
            [('strides', 3, [1, 1]), ('paddings', 3, [0, 0]),
             ('dilations', 3, [1, 1]), ('groups', 0, 1)]),
        _op('batch_norm',
            [('X', ['conv_out']), ('Scale', ['bn_scale']),
             ('Bias', ['bn_bias']), ('Mean', ['bn_mean']),
             ('Variance', ['bn_var'])],
            [('Y', ['bn_out'])],
            [('epsilon', 1, 1e-5), ('is_test', 6, True)]),
        _op('relu', [('X', ['bn_out'])], [('Out', ['relu_out'])]),
        _op('pool2d', [('X', ['relu_out'])], [('Out', ['pool_out'])],
            [('pooling_type', 2, 'max'), ('ksize', 3, [2, 2]),
             ('strides', 3, [2, 2]), ('paddings', 3, [0, 0])]),
        _op('flatten_contiguous_range', [('X', ['pool_out'])],
            [('Out', ['flat_out'])],
            [('start_axis', 0, 1), ('stop_axis', 0, -1)]),
        _op('mul', [('X', ['flat_out']), ('Y', ['fc_w'])],
            [('Out', ['fc_tmp'])],
            [('x_num_col_dims', 0, 1), ('y_num_col_dims', 0, 1)]),
        _op('elementwise_add', [('X', ['fc_tmp']), ('Y', ['fc_b'])],
            [('Out', ['fc_out'])], [('axis', 0, 1)]),
        _op('softmax', [('X', ['fc_out'])], [('Out', ['prob'])],
            [('axis', 0, -1)]),
        _op('fetch', [('X', ['prob'])], [('Out', ['fetch'])],
            [('col', 0, 0)]),
    ]
    d = tmp_path / 'digits'
    d.mkdir()
    (d / '__model__').write_bytes(_program([_block(variables, ops)]))
    params = {'conv_w': conv_w, 'bn_scale': bn_scale, 'bn_bias': bn_bias,
              'bn_mean': bn_mean, 'bn_var': bn_var, 'fc_w': fc_w,
              'fc_b': fc_b}
    for name, arr in params.items():
        with open(d / name, 'wb') as f:
            _write_lod_tensor(f, arr)
    return d, params


def _np_conv2d(x, w):
    n, cin, h, ww = x.shape
    cout, _, kh, kw = w.shape
    oh, ow = h - kh + 1, ww - kw + 1
    out = np.zeros((n, cout, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, :, i:i + kh, j:j + kw]          # n,cin,kh,kw
            out[:, :, i, j] = np.einsum('ncij,ocij->no', patch, w)
    return out


def _np_maxpool2(x):
    n, c, h, w = x.shape
    return x.reshape(n, c, h // 2, 2, w // 2, 2).max(axis=(3, 5))


# -- tests --------------------------------------------------------------------

@pytest.mark.parametrize('combined', [False, True])
def test_fit_a_line_reference_model_serves(tmp_path, combined):
    d, w, b = _fit_a_line_dir(tmp_path, combined)
    cfg = Config(str(d))
    if combined:
        cfg.set_model(str(d / '__model__'), str(d / '__params__'))
    pred = create_predictor(cfg)
    assert pred.get_input_names() == ['x']
    rng = np.random.RandomState(2)
    x = rng.randn(5, 13).astype(np.float32)
    out, = pred.run([x])
    np.testing.assert_allclose(out, x @ w + b, rtol=1e-5, atol=1e-6)


def test_digits_cnn_reference_model_serves(tmp_path):
    d, p = _digits_cnn_dir(tmp_path)
    pred = create_predictor(Config(str(d)))
    rng = np.random.RandomState(3)
    x = rng.rand(2, 1, 28, 28).astype(np.float32)
    out, = pred.run([x])

    conv = _np_conv2d(x, p['conv_w'])
    sh = (1, -1, 1, 1)
    bn = ((conv - p['bn_mean'].reshape(sh)) /
          np.sqrt(p['bn_var'].reshape(sh) + 1e-5) *
          p['bn_scale'].reshape(sh) + p['bn_bias'].reshape(sh))
    act = np.maximum(bn, 0)
    pool = _np_maxpool2(act)
    flat = pool.reshape(2, -1)
    logits = flat @ p['fc_w'] + p['fc_b']
    e = np.exp(logits - logits.max(-1, keepdims=True))
    ref = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_loader_direct_api_and_trailing_byte_guard(tmp_path):
    d, w, b = _fit_a_line_dir(tmp_path, combined=True)
    prog = load_fluid_model(str(d / '__model__'), str(d / '__params__'))
    assert prog.feed_names == ['x'] and len(prog.params) == 2
    np.testing.assert_array_equal(prog.params['fc_w'], w)
    # corrupt: append a byte -> loader must refuse (ordering mismatch
    # would otherwise silently misassign tensors)
    with open(d / '__params__', 'ab') as f:
        f.write(b'\x00')
    with pytest.raises(ValueError, match='trailing'):
        load_fluid_model(str(d / '__model__'), str(d / '__params__'))


def test_executor_load_inference_model_serves_reference_dir(tmp_path):
    """The fluid-era path: static.load_inference_model on a reference
    model dir + Executor.run (the reference book tests' serving idiom)."""
    import paddle_tpu as paddle
    from paddle_tpu import static

    d, w, b = _fit_a_line_dir(tmp_path, combined=False)
    exe = static.Executor()
    prog, feeds, fetches = static.load_inference_model(str(d), exe)
    assert feeds == ['x']
    rng = np.random.RandomState(4)
    x = rng.randn(3, 13).astype(np.float32)
    out, = exe.run(prog, feed={'x': x}, fetch_list=fetches)
    np.testing.assert_allclose(out, x @ w + b, rtol=1e-5, atol=1e-6)


def test_extended_op_table_executes(tmp_path):
    """CNN-era ops beyond the book models: leaky_relu(alpha),
    layer_norm, nearest_interp_v2, pad2d, split + stack — vs numpy."""
    variables = [
        _var('feed', vtype=9, persistable=True),
        _var('fetch', vtype=10, persistable=True),
        _var('x', dims=[-1, 2, 4, 4]),
        _var('lr_out', dims=[-1, 2, 4, 4]),
        _var('up', dims=[-1, 2, 8, 8]),
        _var('padded', dims=[-1, 2, 10, 10]),
        _var('s0', dims=[-1, 1, 10, 10]),
        _var('s1', dims=[-1, 1, 10, 10]),
        _var('stacked', dims=[-1, 2, 1, 10, 10]),
    ]
    ops = [
        _op('feed', [('X', ['feed'])], [('Out', ['x'])], [('col', 0, 0)]),
        _op('leaky_relu', [('X', ['x'])], [('Out', ['lr_out'])],
            [('alpha', 1, 0.1)]),
        _op('nearest_interp_v2', [('X', ['lr_out'])], [('Out', ['up'])],
            [('out_h', 0, 8), ('out_w', 0, 8),
             ('align_corners', 6, False)]),
        _op('pad2d', [('X', ['up'])], [('Out', ['padded'])],
            [('paddings', 3, [1, 1, 1, 1]), ('mode', 2, 'constant'),
             ('pad_value', 1, 0.0)]),
        _op('split', [('X', ['padded'])], [('Out', ['s0', 's1'])],
            [('axis', 0, 1), ('num', 0, 2)]),
        _op('stack', [('X', ['s0', 's1'])], [('Y', ['stacked'])],
            [('axis', 0, 1)]),
        _op('fetch', [('X', ['stacked'])], [('Out', ['fetch'])],
            [('col', 0, 0)]),
    ]
    d = tmp_path / 'ext_ops'
    d.mkdir()
    (d / '__model__').write_bytes(_program([_block(variables, ops)]))
    prog = load_fluid_model(str(d))
    rng = np.random.RandomState(6)
    x = rng.randn(2, 2, 4, 4).astype(np.float32)
    out, = prog.run({'x': x})

    ref = np.where(x > 0, x, 0.1 * x)
    ref = ref.repeat(2, axis=2).repeat(2, axis=3)      # nearest 2x
    ref = np.pad(ref, [(0, 0), (0, 0), (1, 1), (1, 1)])
    parts = np.split(ref, 2, axis=1)
    ref = np.stack(parts, axis=1)
    assert out.shape == (2, 2, 1, 10, 10)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_yolo_detection_ops_serve(tmp_path):
    """The real exported PP-YOLO tail — yolo_box (scores [N,M,C]) ->
    transpose2 ([N,C,M]) -> multiclass_nms3 — through the fluid table,
    matching the native vision implementations (themselves
    reference-validated in test_yolo.py)."""
    na, cls, h = 3, 4, 4
    c = na * (5 + cls)
    anchors = [10, 13, 16, 30, 33, 23]
    variables = [
        _var('feed', vtype=9, persistable=True),
        _var('fetch', vtype=10, persistable=True),
        _var('head', dims=[-1, c, h, h]),
        _var('imgsz', dims=[-1, 2], dtype=2),       # int32
        _var('boxes', dims=[-1, na * h * h, 4]),
        _var('scores_mc', dims=[-1, na * h * h, cls]),
        _var('scores', dims=[-1, cls, na * h * h]),
        _var('dets', dims=[-1, 6]),
        _var('rois_n', dims=[-1], dtype=2),
    ]
    ops = [
        _op('feed', [('X', ['feed'])], [('Out', ['head'])],
            [('col', 0, 0)]),
        _op('feed', [('X', ['feed'])], [('Out', ['imgsz'])],
            [('col', 0, 1)]),
        _op('yolo_box', [('X', ['head']), ('ImgSize', ['imgsz'])],
            [('Boxes', ['boxes']), ('Scores', ['scores_mc'])],
            [('anchors', 3, anchors), ('class_num', 0, cls),
             ('conf_thresh', 1, 0.01), ('downsample_ratio', 0, 32),
             ('clip_bbox', 6, True), ('scale_x_y', 1, 1.0)]),
        _op('transpose2', [('X', ['scores_mc'])], [('Out', ['scores'])],
            [('axis', 3, [0, 2, 1])]),
        _op('multiclass_nms3',
            [('BBoxes', ['boxes']), ('Scores', ['scores'])],
            [('Out', ['dets']), ('NmsRoisNum', ['rois_n'])],
            [('score_threshold', 1, 0.01), ('nms_top_k', 0, 10),
             ('keep_top_k', 0, 5), ('nms_threshold', 1, 0.45),
             ('normalized', 6, True), ('background_label', 0, -1)]),
        _op('fetch', [('X', ['dets'])], [('Out', ['fetch'])],
            [('col', 0, 0)]),
        _op('fetch', [('X', ['rois_n'])], [('Out', ['fetch'])],
            [('col', 0, 1)]),
    ]
    d = tmp_path / 'yolo_tail'
    d.mkdir()
    (d / '__model__').write_bytes(_program([_block(variables, ops)]))
    prog = load_fluid_model(str(d))
    rng = np.random.RandomState(8)
    head = rng.randn(1, c, h, h).astype(np.float32)
    imgsz = np.array([[128, 128]], np.int32)
    dets, rois_n = prog.run({'head': head, 'imgsz': imgsz})

    import paddle_tpu as paddle
    from paddle_tpu.vision.ops import yolo_box
    from paddle_tpu.vision.detection import multiclass_nms
    b_ref, s_ref = yolo_box(paddle.to_tensor(head),
                            paddle.to_tensor(imgsz), anchors=anchors,
                            class_num=cls, conf_thresh=0.01,
                            downsample_ratio=32)
    s_ref_cm = paddle.transpose(s_ref, [0, 2, 1])  # [N,M,C] -> [N,C,M]
    out_ref, rois_ref = multiclass_nms(
        b_ref, s_ref_cm, score_threshold=0.01, nms_top_k=10, keep_top_k=5,
        nms_threshold=0.45, background_label=-1, return_rois_num=True)
    np.testing.assert_allclose(dets, out_ref.numpy(), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(rois_n, rois_ref.numpy())


def test_optim_cache_dir_persists_executables(tmp_path):
    """Config.set_optim_cache_dir -> jax persistent compilation cache:
    running the predictor populates the directory with compiled
    executables (restart-warm serving)."""
    import jax
    d, w, b = _fit_a_line_dir(tmp_path, combined=False)
    cache = tmp_path / 'optim_cache'
    cfg = Config(str(d))
    cfg.set_optim_cache_dir(str(cache))
    try:
        pred = create_predictor(cfg)
        x = np.random.RandomState(1).randn(2, 13).astype(np.float32)
        out, = pred.run([x])
        np.testing.assert_allclose(out, x @ w + b, rtol=1e-5, atol=1e-6)
        assert cache.exists() and any(cache.iterdir()), \
            'persistent cache dir not populated'
    finally:
        # the knob is process-global; later tests must not write compile
        # artifacts into this (soon-deleted) tmp dir
        jax.config.update('jax_compilation_cache_dir', None)


def test_rcnn_family_ops_serve(tmp_path):
    """roi_align (RoisNum batching) + box_coder via the fluid table match
    the native vision implementations."""
    variables = [
        _var('feed', vtype=9, persistable=True),
        _var('fetch', vtype=10, persistable=True),
        _var('feat', dims=[-1, 3, 8, 8]),
        _var('rois', dims=[-1, 4]),
        _var('rois_num', dims=[-1], dtype=2),
        _var('pooled', dims=[-1, 3, 2, 2]),
    ]
    ops = [
        _op('feed', [('X', ['feed'])], [('Out', ['feat'])],
            [('col', 0, 0)]),
        _op('feed', [('X', ['feed'])], [('Out', ['rois'])],
            [('col', 0, 1)]),
        _op('feed', [('X', ['feed'])], [('Out', ['rois_num'])],
            [('col', 0, 2)]),
        _op('roi_align',
            [('X', ['feat']), ('ROIs', ['rois']),
             ('RoisNum', ['rois_num'])],
            [('Out', ['pooled'])],
            [('pooled_height', 0, 2), ('pooled_width', 0, 2),
             ('spatial_scale', 1, 0.5), ('sampling_ratio', 0, 2),
             ('aligned', 6, True)]),
        _op('fetch', [('X', ['pooled'])], [('Out', ['fetch'])],
            [('col', 0, 0)]),
    ]
    d = tmp_path / 'rcnn'
    d.mkdir()
    (d / '__model__').write_bytes(_program([_block(variables, ops)]))
    prog = load_fluid_model(str(d))
    rng = np.random.RandomState(9)
    feat = rng.randn(2, 3, 8, 8).astype(np.float32)
    rois = np.abs(rng.randn(4, 4)).astype(np.float32) * 4
    rois[:, 2:] += rois[:, :2] + 2
    rois_num = np.array([3, 1], np.int32)
    out, = prog.run({'feat': feat, 'rois': rois, 'rois_num': rois_num})

    import paddle_tpu as paddle
    from paddle_tpu.vision.ops import roi_align
    ref = roi_align(paddle.to_tensor(feat), paddle.to_tensor(rois),
                    paddle.to_tensor(rois_num), output_size=2,
                    spatial_scale=0.5, sampling_ratio=2, aligned=True)
    np.testing.assert_allclose(out, ref.numpy(), rtol=1e-5, atol=1e-5)


def test_parser_roundtrips_negative_and_attr_types(tmp_path):
    blk = _block([_var('v', dims=[-1, 7])],
                 [_op('scale', [('X', ['v'])], [('Out', ['v2'])],
                      [('scale', 1, 2.5), ('bias', 1, -1.0),
                       ('bias_after_scale', 6, True)]),
                  _op('reshape2', [('X', ['v2'])], [('Out', ['v3'])],
                      [('shape', 3, [-1, 7])]),
                  _op('slice', [('Input', ['v3'])], [('Out', ['v4'])],
                      [('axes', 3, [0]), ('starts', 3, [0]),
                       ('ends', 3, [1]), ('decrease_axis', 3, [0])])])
    blocks = parse_program_desc(_program([blk]))
    v = blocks[0].vars['v']
    assert v.shape == [-1, 7]
    op = blocks[0].ops[0]
    assert op.type == 'scale'
    assert op.attr('scale') == pytest.approx(2.5)
    assert op.attr('bias') == pytest.approx(-1.0)
    assert op.attr('bias_after_scale') is True
    # negative INTS arrive sign-extended as 64-bit varints (proto2):
    # the common reshape2(shape=[-1, C]) case must decode to -1
    assert blocks[0].ops[1].attr('shape') == [-1, 7]
    assert blocks[0].ops[2].attr('decrease_axis') == [0]


def test_reshape_neg1_and_decrease_axis_execute(tmp_path):
    """End-to-end: a program using reshape2([-1, C]) and a
    decrease_axis slice runs and matches numpy."""
    variables = [
        _var('feed', vtype=9, persistable=True),
        _var('fetch', vtype=10, persistable=True),
        _var('x', dims=[-1, 2, 6]),
        _var('r', dims=[-1, 6]),
        _var('row', dims=[6]),
    ]
    ops = [
        _op('feed', [('X', ['feed'])], [('Out', ['x'])], [('col', 0, 0)]),
        _op('reshape2', [('X', ['x'])], [('Out', ['r'])],
            [('shape', 3, [-1, 6])]),
        _op('slice', [('Input', ['r'])], [('Out', ['row'])],
            [('axes', 3, [0]), ('starts', 3, [0]), ('ends', 3, [1]),
             ('decrease_axis', 3, [0])]),
        _op('fetch', [('X', ['row'])], [('Out', ['fetch'])],
            [('col', 0, 0)]),
    ]
    d = tmp_path / 'negshape'
    d.mkdir()
    (d / '__model__').write_bytes(_program([_block(variables, ops)]))
    prog = load_fluid_model(str(d))
    rng = np.random.RandomState(5)
    x = rng.randn(2, 2, 6).astype(np.float32)
    out, = prog.run({'x': x})
    assert out.shape == (6,)
    np.testing.assert_allclose(out, x.reshape(-1, 6)[0], rtol=1e-6)


def _word2vec_dir(tmp_path):
    """The word2vec book-test graph (test_word2vec_book.py shape): four
    context words share ONE embedding table (lookup_table_v2), concat,
    fc, softmax over the vocab."""
    rng = np.random.RandomState(7)
    vocab, emb, n_ctx = 50, 8, 4
    table = rng.randn(vocab, emb).astype(np.float32)
    fc_w = rng.randn(n_ctx * emb, vocab).astype(np.float32)
    fc_b = rng.randn(vocab).astype(np.float32)

    int64 = 3
    variables = [
        _var('feed', vtype=9, persistable=True),
        _var('fetch', vtype=10, persistable=True),
        _var('emb_table', dims=[vocab, emb], persistable=True),
        _var('fc_w', dims=[n_ctx * emb, vocab], persistable=True),
        _var('fc_b', dims=[vocab], persistable=True),
        _var('cat', dims=[-1, n_ctx * emb]),
        _var('fc_tmp', dims=[-1, vocab]),
        _var('logits', dims=[-1, vocab]),
        _var('prob', dims=[-1, vocab]),
    ]
    ops = []
    for i in range(n_ctx):
        variables.append(_var('w%d' % i, dims=[-1], dtype=int64))
        variables.append(_var('emb%d' % i, dims=[-1, emb]))
        ops.append(_op('feed', [('X', ['feed'])], [('Out', ['w%d' % i])],
                       [('col', 0, i)]))
    for i in range(n_ctx):
        ops.append(_op('lookup_table_v2',
                       [('Ids', ['w%d' % i]), ('W', ['emb_table'])],
                       [('Out', ['emb%d' % i])]))
    ops += [
        _op('concat', [('X', ['emb%d' % i for i in range(n_ctx)])],
            [('Out', ['cat'])], [('axis', 0, 1)]),
        _op('mul', [('X', ['cat']), ('Y', ['fc_w'])],
            [('Out', ['fc_tmp'])],
            [('x_num_col_dims', 0, 1), ('y_num_col_dims', 0, 1)]),
        _op('elementwise_add', [('X', ['fc_tmp']), ('Y', ['fc_b'])],
            [('Out', ['logits'])], [('axis', 0, 1)]),
        _op('softmax', [('X', ['logits'])], [('Out', ['prob'])],
            [('axis', 0, -1)]),
        _op('fetch', [('X', ['prob'])], [('Out', ['fetch'])],
            [('col', 0, 0)]),
    ]
    d = tmp_path / 'word2vec'
    d.mkdir()
    (d / '__model__').write_bytes(_program([_block(variables, ops)]))
    for name, arr in (('emb_table', table), ('fc_w', fc_w),
                      ('fc_b', fc_b)):
        with open(d / name, 'wb') as f:
            _write_lod_tensor(f, arr)
    return d, table, fc_w, fc_b


def test_word2vec_reference_model_serves(tmp_path):
    d, table, fc_w, fc_b = _word2vec_dir(tmp_path)
    pred = create_predictor(Config(str(d)))
    assert pred.get_input_names() == ['w0', 'w1', 'w2', 'w3']
    rng = np.random.RandomState(8)
    ids = [rng.randint(0, 50, (6,)).astype(np.int64) for _ in range(4)]
    out, = pred.run(ids)
    cat = np.concatenate([table[i] for i in ids], axis=1)
    logits = cat @ fc_w + fc_b
    e = np.exp(logits - logits.max(-1, keepdims=True))
    ref = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
