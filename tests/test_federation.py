"""Fleet telemetry plane tests (monitor/federation.py + monitor/alerts.py).

The load-bearing assertions:
  1. merged counter totals are EXACT — across in-proc registries, a real
     child process scraped over HTTP, and a target killed mid-scrape
     (stale data is held, so totals stay monotone and never shrink);
  2. histogram bucket counts match an independent numpy computation, and
     the merged exposition renders through the same cumulative-`le`
     contract as a single registry's /metrics body;
  3. every alert lifecycle edge lands at an analytically exact tick of
     an injected clock (pending -> firing -> resolved), a firing edge
     writes EXACTLY ONE flight dump, and hysteresis keeps a sawtoothing
     signal from flapping;
  4. the disabled path is inert: a disabled collector fetches nothing
     (even from an unreachable target) and alerting off the evaluate()
     path costs the serving loops nothing.
"""
import glob
import json
import os
import subprocess
import sys
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu.monitor import (FleetCollector, MetricRegistry,
                                MetricsServer, ScrapeTarget, alerts,
                                merge_snapshots, to_dict, to_prometheus)
from paddle_tpu.monitor.alerts import (AlertManager, BurnRateRule,
                                       HistogramWindow, ThresholdRule,
                                       federated_burn_source)
from paddle_tpu.monitor.export import snapshot_to_prometheus
from paddle_tpu.monitor.federation import fleet_snapshot_line, FLEET_LINE_RE
from paddle_tpu.monitor.tracing import FlightRecorder, Tracer
from paddle_tpu.testing import chaos

REPO = __file__.rsplit('/tests/', 1)[0]


def _reg(counter=0.0, gauge=None, hist=()):
    """A registry with one family of each kind (fixed shared names)."""
    r = MetricRegistry()
    c = r.counter('fed_tokens_total', 'tokens')
    if counter:
        c.inc(counter)
    if gauge is not None:
        r.gauge('fed_occupancy', 'occ').set(gauge)
    h = r.histogram('fed_lat_seconds', 'lat', buckets=(0.1, 1.0, 10.0))
    for v in hist:
        h.observe(v)
    return r


# -- histogram cumulative view ----------------------------------------------

def test_histogram_cumulative_numpy_parity():
    """The mergeable cumulative() view agrees with an independent numpy
    cumsum over the same bounds — the federation merge and the
    Prometheus `le` lines both stand on this."""
    rng = np.random.RandomState(7)
    values = rng.lognormal(mean=-1.0, sigma=1.5, size=500)
    bounds = (0.05, 0.2, 1.0, 5.0)
    r = MetricRegistry()
    h = r.histogram('lat_seconds', 'lat', buckets=bounds)
    for v in values:
        h.observe(float(v))
    cum = h.cumulative()
    assert cum['bounds'] == list(bounds) + [float('inf')]
    # numpy oracle: observations <= bound, cumulatively (le semantics)
    expect = [int(np.sum(values <= b)) for b in bounds] + [len(values)]
    assert cum['cumulative'] == expect
    assert cum['count'] == len(values)
    assert cum['sum'] == pytest.approx(float(np.sum(values)))
    # the snapshot's buckets are per-bucket increments of the same
    # distribution: their running sum IS the cumulative view
    sample = to_dict(r)['lat_seconds']['samples'][0]
    assert list(np.cumsum(list(sample['buckets'].values()))) == expect


def test_snapshot_exposition_matches_registry_exposition():
    """snapshot_to_prometheus(to_dict(r)) and to_prometheus(r) agree on
    every sample line — the /fleet?format=prom body speaks the same
    dialect as a single process's /metrics."""
    r = _reg(counter=3, gauge=0.5, hist=(0.05, 0.5, 50.0))
    c = r.counter('fed_ops_total', 'ops', ('kind',))
    c.labels('read').inc(2)
    c.labels('write').inc(5)
    direct = to_prometheus(r)
    via_snapshot = snapshot_to_prometheus(to_dict(r))
    # compare as line sets: family ordering may differ, samples may not
    assert set(l for l in direct.splitlines() if not l.startswith('#')) \
        == set(l for l in via_snapshot.splitlines()
               if not l.startswith('#'))


# -- pure merge semantics ----------------------------------------------------

def test_merge_counters_exact_per_labelset():
    a = MetricRegistry()
    b = MetricRegistry()
    for r, n in ((a, 3), (b, 39)):
        fam = r.counter('ops_total', 'ops', ('kind',))
        fam.labels('read').inc(n)
    a.get('ops_total').labels('write').inc(7)
    merged = merge_snapshots([('a', to_dict(a)), ('b', to_dict(b))])
    by_kind = {s['labels']['kind']: s['value']
               for s in merged['ops_total']['samples']}
    assert by_kind == {'read': 42.0, 'write': 7.0}
    assert merged['ops_total']['labels'] == ['kind']


def test_merge_gauges_get_instance_label():
    a = _reg(gauge=0.25)
    b = _reg(gauge=0.75)
    merged = merge_snapshots([('a', to_dict(a)), ('b', to_dict(b))])
    fam = merged['fed_occupancy']
    assert fam['labels'] == ['instance']
    vals = {s['labels']['instance']: s['value'] for s in fam['samples']}
    assert vals == {'a': 0.25, 'b': 0.75}
    # federation of federations: a family already carrying `instance`
    # passes through instead of growing instance twice
    again = merge_snapshots([('meta', merged)])
    fam2 = again['fed_occupancy']
    assert fam2['labels'] == ['instance']
    assert {s['labels']['instance'] for s in fam2['samples']} == {'a', 'b'}


def test_merge_histograms_bucketwise_numpy_parity():
    rng = np.random.RandomState(3)
    va = rng.exponential(1.0, size=200)
    vb = rng.exponential(3.0, size=300)
    a = _reg(hist=[float(v) for v in va])
    b = _reg(hist=[float(v) for v in vb])
    merged = merge_snapshots([('a', to_dict(a)), ('b', to_dict(b))])
    s = merged['fed_lat_seconds']['samples'][0]
    both = np.concatenate([va, vb])
    assert s['count'] == 500
    assert s['sum'] == pytest.approx(float(np.sum(both)))
    # per-bucket increments: difference the numpy cumulative counts
    cum = [int(np.sum(both <= b)) for b in (0.1, 1.0, 10.0, np.inf)]
    expect = dict(zip(('0.1', '1', '10', '+Inf'),
                      np.diff([0] + cum).tolist()))
    assert s['buckets'] == expect


def test_merge_conflicting_families_dropped_not_wrong():
    a = MetricRegistry()
    a.counter('x_total', 'x').inc(1)
    b = MetricRegistry()
    b.gauge('x_total', 'x').set(5)            # same name, other kind
    c = MetricRegistry()
    c.counter('ok_total', 'ok').inc(2)
    conflicts = []
    merged = merge_snapshots(
        [('a', to_dict(a)), ('b', to_dict(b)), ('c', to_dict(c))],
        conflicts=conflicts)
    assert 'x_total' not in merged            # dropped, never guessed
    assert merged['ok_total']['samples'][0]['value'] == 2.0
    assert conflicts and conflicts[0]['family'] == 'x_total'

    # histogram bucket-bound mismatch is the same story
    ha = MetricRegistry()
    ha.histogram('h_seconds', 'h', buckets=(0.1, 1.0)).observe(0.5)
    hb = MetricRegistry()
    hb.histogram('h_seconds', 'h', buckets=(0.2, 2.0)).observe(0.5)
    conflicts = []
    merged = merge_snapshots([('a', to_dict(ha)), ('b', to_dict(hb))],
                             conflicts=conflicts)
    assert 'h_seconds' not in merged
    assert any(c['problem'] == 'bucket_bounds' for c in conflicts)


def test_scrape_target_validation():
    with pytest.raises(ValueError):
        ScrapeTarget('x')                     # neither registry nor url
    with pytest.raises(ValueError):
        ScrapeTarget('x', registry=MetricRegistry(),
                     url='http://127.0.0.1:1/')
    t = ScrapeTarget('x', url='http://127.0.0.1:1')
    assert t.url.endswith('/metrics.json')


# -- the federation oracle ---------------------------------------------------

_CHILD = r'''
import os, sys, types
sys.path.insert(0, %(repo)r)
pkg = types.ModuleType('paddle_tpu')
pkg.__path__ = [os.path.join(%(repo)r, 'paddle_tpu')]
sys.modules['paddle_tpu'] = pkg        # monitor/ must load without jax
from paddle_tpu.monitor.registry import MetricRegistry
from paddle_tpu.monitor.server import MetricsServer
r = MetricRegistry()
r.counter('fed_tokens_total', 'tokens').inc(int(sys.argv[1]))
r.gauge('fed_occupancy', 'occ').set(0.5)
h = r.histogram('fed_lat_seconds', 'lat', buckets=(0.1, 1.0, 10.0))
for v in (0.05, 0.5, 50.0):
    h.observe(v)
srv = MetricsServer(registry=r).start()
print(srv.port, flush=True)
sys.stdin.read()                       # live until the parent kills us
'''


def test_federation_oracle_http_child_process_and_death():
    """THE acceptance test: three targets — two in-proc registries plus
    a REAL child process scraped over HTTP — merge to exact totals;
    killing the child degrades to stale last-known data (totals
    monotone, never wrong) with fleet_target_up{child}=0."""
    meta = MetricRegistry()
    fc = FleetCollector(registry=meta, clock=time.monotonic)
    fc.add_target('a', registry=_reg(counter=10, gauge=0.25,
                                     hist=(0.05, 0.5, 50.0)))
    fc.add_target('b', registry=_reg(counter=20, gauge=0.75,
                                     hist=(0.05, 0.5, 50.0)))
    proc = subprocess.Popen(
        [sys.executable, '-c', _CHILD % {'repo': REPO}, '12'],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    try:
        port = int(proc.stdout.readline())
        fc.add_target('child', url='http://127.0.0.1:%d' % port)
        assert fc.scrape() == {'ok': 3, 'down': 0}
        merged = fc.merged()
        assert merged['fed_tokens_total']['samples'][0]['value'] == 42.0
        occ = {s['labels']['instance']: s['value']
               for s in merged['fed_occupancy']['samples']}
        assert occ == {'a': 0.25, 'b': 0.75, 'child': 0.5}
        lat = merged['fed_lat_seconds']['samples'][0]
        assert lat['count'] == 9
        assert lat['buckets'] == {'0.1': 3, '1': 3, '10': 0, '+Inf': 3}

        proc.kill()
        proc.wait(timeout=10)
        assert fc.scrape() == {'ok': 2, 'down': 1}
        st = fc.fleet_status()
        assert st['up'] == 2
        assert st['targets']['child']['up'] is False
        assert st['targets']['child']['stale'] is True
        assert st['targets']['child']['last_error']
        # monotone: the dead child's counted work is still in the total
        merged = fc.merged()
        assert merged['fed_tokens_total']['samples'][0]['value'] == 42.0
        up = {s['labels']['instance']: s['value']
              for s in to_dict(meta)['fleet_target_up']['samples']}
        assert up == {'a': 1.0, 'b': 1.0, 'child': 0.0}
        errs = to_dict(meta)['fleet_scrape_errors_total']['samples']
        assert {s['labels']['instance']: s['value']
                for s in errs} == {'child': 1.0}
    finally:
        proc.kill()
        proc.wait(timeout=10)


@pytest.mark.chaos
def test_chaos_partition_mid_scrape_single_trace():
    """A chaos partition on one target's endpoint mid-scrape: the cycle
    completes, the partitioned target goes stale (data held, totals
    exact), and every span of the cycle shares ONE trace_id."""
    tracer = Tracer(registry=MetricRegistry())
    fc = FleetCollector(registry=MetricRegistry(), tracer=tracer,
                        clock=time.monotonic)
    fc.add_target('a', registry=_reg(counter=5))
    fc.add_target('b', registry=_reg(counter=37))
    assert fc.scrape() == {'ok': 2, 'down': 0}
    tracer.recorder.clear()

    endpoint = 'inproc://b'
    with chaos.partition(endpoint) as fault:
        res = fc.scrape()
    assert res == {'ok': 1, 'down': 1}
    assert fault.fired >= 1
    assert chaos.active_faults() == 0
    st = fc.fleet_status()
    assert st['targets']['b']['up'] is False
    assert st['targets']['b']['stale'] is True
    # totals monotone through the partition (stale data held)
    assert fc.merged()['fed_tokens_total']['samples'][0]['value'] == 42.0

    spans = tracer.recorder.spans()
    cycle = [s for s in spans if s['name'] == 'fleet.scrape']
    targets = [s for s in spans if s['name'] == 'fleet.scrape.target']
    assert len(cycle) == 1 and len(targets) == 2
    assert {s['trace_id'] for s in spans} \
        == {cycle[0]['trace_id']}                  # one trace per cycle
    assert all(s['parent_id'] == cycle[0]['span_id'] for s in targets)
    by_inst = {s['tags']['instance']: s for s in targets}
    assert by_inst['b']['status'] == 'error'
    assert by_inst['a']['status'] == 'ok'
    assert cycle[0]['tags']['ok'] == 1 and cycle[0]['tags']['down'] == 1

    # partition lifted: next cycle recovers the target
    assert fc.scrape() == {'ok': 2, 'down': 0}
    assert fc.fleet_status()['targets']['b']['stale'] is False


def test_disabled_collector_fetches_nothing():
    """Disabled federation is inert: scrape() skips even unreachable
    targets (nothing to time out on) and merged() serves the last
    view — the plane costs nothing unless someone pulls."""
    fc = FleetCollector(registry=MetricRegistry(), enabled=True,
                        clock=time.monotonic)
    fc.add_target('a', registry=_reg(counter=8))
    fc.scrape()
    fc.disable()
    # an unreachable HTTP target would raise/timeout if fetched
    fc.add_target('dead', url='http://127.0.0.1:9/', timeout=0.05)
    t0 = time.monotonic()
    assert fc.scrape() == {'ok': 0, 'down': 0, 'skipped': True}
    assert time.monotonic() - t0 < 0.05
    assert fc.merged()['fed_tokens_total']['samples'][0]['value'] == 8.0
    fc.enable()
    assert fc.scrape() == {'ok': 1, 'down': 1}


def test_fleet_snapshot_line_roundtrip():
    fc = FleetCollector(registry=MetricRegistry(), clock=time.monotonic)
    fc.add_target('a', registry=_reg(counter=6, hist=(0.5,)))
    fc.scrape()
    line = fleet_snapshot_line(fc, 8, '[dp/mp]')
    m = FLEET_LINE_RE.search(line)
    assert m and m.group('n') == '8' and m.group('tag') == 'dp/mp'
    status = json.loads(m.group('json'))
    assert status['up'] == 1
    fam = status['merged']['fed_tokens_total']
    assert fam['samples'][0]['value'] == 6.0
    # bucket detail is trimmed from the one-line form (count/sum stay)
    lat = status['merged']['fed_lat_seconds']['samples'][0]
    assert lat['count'] == 1 and 'buckets' not in lat


# -- /fleet and /alerts routes -----------------------------------------------

def test_server_fleet_and_alerts_routes():
    fc = FleetCollector(registry=MetricRegistry(), clock=time.monotonic)
    fc.add_target('a', registry=_reg(counter=11, hist=(0.05,)))
    mgr = AlertManager(
        [ThresholdRule('hot', 'fed_tokens_total', 10.0)],
        source=fc.merged, registry=MetricRegistry(),
        recorder=None, clock=time.monotonic)
    with MetricsServer(registry=MetricRegistry(), collector=fc,
                       alerts=mgr) as srv:
        # ?scrape=1 forces a cycle, so the JSON body is fresh
        body = json.loads(urllib.request.urlopen(
            srv.url + '/fleet?scrape=1', timeout=5).read().decode())
        assert body['up'] == 1
        assert body['merged']['fed_tokens_total']['samples'][0]['value'] \
            == 11.0
        # the merged view renders as Prometheus text exposition too
        prom = urllib.request.urlopen(
            srv.url + '/fleet?format=prom', timeout=5).read().decode()
        assert 'fed_tokens_total 11' in prom
        assert 'fed_lat_seconds_bucket{le="+Inf"} 1' in prom

        body = json.loads(urllib.request.urlopen(
            srv.url + '/alerts?evaluate=1', timeout=5).read().decode())
        assert body['firing'] == ['hot']
        assert body['alerts'][0]['state'] == 'firing'

        # HEAD parity on the new routes (LB probes must not see 501)
        for path in ('/fleet', '/alerts'):
            req = urllib.request.Request(srv.url + path, method='HEAD')
            resp = urllib.request.urlopen(req, timeout=5)
            assert resp.status == 200
            assert int(resp.headers['Content-Length']) > 0
            assert resp.read() == b''


def test_server_routes_404_when_unattached():
    with MetricsServer(registry=MetricRegistry()) as srv:
        for path in ('/fleet', '/alerts'):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url + path, timeout=5)
            assert ei.value.code == 404
            req = urllib.request.Request(srv.url + path, method='HEAD')
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 404


def test_server_fleet_route_respects_draining_readyz():
    """The new routes ride the same server as /readyz: a draining
    process keeps answering /fleet (debugging a drain needs data) while
    /readyz 503s — route-level, not server-level, drain semantics."""
    fc = FleetCollector(registry=MetricRegistry(), clock=time.monotonic)
    fc.add_target('a', registry=_reg(counter=1))
    fc.scrape()
    ready = {'ok': False}
    with MetricsServer(registry=MetricRegistry(), collector=fc,
                       readiness=lambda: ready['ok']) as srv:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + '/readyz', timeout=5)
        assert ei.value.code == 503
        body = json.loads(urllib.request.urlopen(
            srv.url + '/fleet', timeout=5).read().decode())
        assert body['up'] == 1


# -- alert lifecycle at analytic ticks ---------------------------------------

def _mgr(rules, source_reg, tmp_path=None, cooldown=1e9):
    """AlertManager over a fake clock; returns (mgr, tick). The huge
    recorder cooldown proves firing-edge dumps bypass maybe_dump's
    throttle (the rule lifecycle IS the throttle)."""
    clock = {'t': 0.0}
    rec = None
    if tmp_path is not None:
        rec = FlightRecorder(dump_dir=str(tmp_path), cooldown=cooldown,
                             registry=MetricRegistry(),
                             clock=lambda: clock['t'])
        rec.record({'name': 'ctx', 'start': 0.0, 'end': 0.1})
    mgr = AlertManager(rules, source=lambda: to_dict(source_reg),
                       registry=MetricRegistry(), recorder=rec,
                       clock=lambda: clock['t'])

    def tick(t):
        clock['t'] = t
        return mgr.evaluate()
    return mgr, tick


def test_threshold_rule_lifecycle_exact_ticks(tmp_path):
    reg = MetricRegistry()
    g = reg.gauge('occ', 'occupancy')
    g.set(0.1)
    rule = ThresholdRule('hot', 'occ', 0.8, op='>', for_duration=10.0,
                         resolve_after=5.0)
    mgr, tick = _mgr([rule], reg, tmp_path)
    assert tick(0.0) == []                      # below threshold
    g.set(0.9)
    assert tick(1.0) == [('hot', 'pending')]
    assert tick(10.9) == []                     # 9.9s held < 10s
    assert tick(11.0) == [('hot', 'firing')]    # exactly at for_duration
    assert mgr.firing() == ['hot']
    # exactly one dump on the edge, regardless of later evaluations
    dumps = lambda: glob.glob(  # noqa: E731
        os.path.join(str(tmp_path), 'flight_alert_firing_*.json'))
    assert len(dumps()) == 1
    assert tick(12.0) == []
    assert len(dumps()) == 1
    # hysteresis: a brief clear + re-assert does NOT resolve
    g.set(0.1)
    assert tick(13.0) == []
    g.set(0.9)
    assert tick(14.0) == []                     # clear_since reset
    g.set(0.1)
    assert tick(20.0) == []
    assert tick(24.9) == []                     # 4.9s clear < 5s
    assert tick(25.0) == [('hot', 'resolved')]
    assert mgr.firing() == []
    st = mgr.state()[0]
    assert st['state'] == 'inactive'
    assert st['fired_count'] == 1 and st['resolved_count'] == 1
    # a second incident fires again -> a SECOND dump (one per edge)
    g.set(0.9)
    tick(30.0)
    assert tick(40.0) == [('hot', 'firing')]
    assert len(dumps()) == 2
    # pending that clears before for_duration never fires
    g2 = reg.gauge('occ2', 'occupancy2')
    g2.set(0.9)
    rule2 = ThresholdRule('warm', 'occ2', 0.8, for_duration=10.0)
    mgr2, tick2 = _mgr([rule2], reg)
    assert tick2(0.0) == [('warm', 'pending')]
    g2.set(0.1)
    assert tick2(5.0) == [('warm', 'inactive')]
    assert mgr2.state()[0]['fired_count'] == 0


def test_alert_gauges_and_transition_counters():
    reg = MetricRegistry()
    reg.gauge('occ', 'occupancy').set(1.0)
    rule = ThresholdRule('hot', 'occ', 0.5, for_duration=2.0)
    mgr, tick = _mgr([rule], reg)
    areg = mgr.registry
    tick(0.0)
    snap = to_dict(areg)
    assert snap['alerts_pending']['samples'][0]['value'] == 1.0
    assert snap['alerts_firing']['samples'][0]['value'] == 0.0
    tick(2.0)
    snap = to_dict(areg)
    assert snap['alerts_pending']['samples'][0]['value'] == 0.0
    assert snap['alerts_firing']['samples'][0]['value'] == 1.0
    trans = {tuple(sorted(s['labels'].items())): s['value']
             for s in snap['alerts_transitions_total']['samples']}
    assert trans[(('rule', 'hot'), ('to', 'pending'))] == 1.0
    assert trans[(('rule', 'hot'), ('to', 'firing'))] == 1.0
    assert snap['alerts_evaluations_total']['samples'][0]['value'] == 2.0


def test_alert_manager_rejects_duplicate_rule_names():
    with pytest.raises(ValueError):
        AlertManager([ThresholdRule('x', 'm', 1.0),
                      ThresholdRule('x', 'm', 2.0)],
                     source=dict, registry=MetricRegistry(),
                     recorder=None)


# -- burn-rate rules ---------------------------------------------------------

def test_histogram_window_fraction_differencing():
    w = HistogramWindow(slo_le=0.1)
    s = {'count': 10, 'buckets': {'0.1': 10, '1': 10, '+Inf': 10}}
    w.update(s, now=0.0)
    assert w.fraction(60.0, now=0.0) == 0.0     # no delta yet
    s = {'count': 20, 'buckets': {'0.1': 10, '1': 15, '+Inf': 20}}
    w.update(s, now=30.0)
    # 10 new observations, 10 of them over 0.1s
    assert w.fraction(60.0, now=30.0) == 1.0
    s = {'count': 40, 'buckets': {'0.1': 20, '1': 15, '+Inf': 5}}
    w.update(s, now=60.0)
    # window 30: vs the t=30 entry -> 20 new, 10 over
    assert w.fraction(30.0, now=60.0) == 0.5
    # full horizon: 30 new since t=0, 20 over
    assert w.fraction(3600.0, now=60.0) == pytest.approx(2 / 3)


def test_histogram_window_rejects_non_bucket_slo():
    w = HistogramWindow(slo_le=0.15)    # not a bound of this histogram
    with pytest.raises(ValueError):
        w.update({'count': 1, 'buckets': {'0.1': 1, '+Inf': 1}}, now=0.0)
    # and a bucketless sample (snapshot taken with buckets=False) raises
    # instead of silently alerting on garbage
    w2 = HistogramWindow(slo_le=0.1)
    with pytest.raises(ValueError):
        w2.update({'count': 1}, now=0.0)


def test_burn_rate_rule_fires_and_resolves_at_analytic_ticks(tmp_path):
    """objective=0.9 (budget 0.1), one (60s, 10s, 5.0) window pair:
    firing requires >= 50% of windowed observations over the SLO in
    BOTH windows. Drive the histogram to cross exactly that line."""
    reg = MetricRegistry()
    h = reg.histogram('lat_seconds', 'lat', buckets=(0.1, 1.0))
    rule = BurnRateRule('slo-burn', 'lat_seconds', slo_le=0.1,
                        objective=0.9, windows=((60.0, 10.0, 5.0),),
                        resolve_after=0.0)
    mgr, tick = _mgr([rule], reg, tmp_path)
    for _ in range(10):
        h.observe(0.05)                          # 10 good
    assert tick(0.0) == []                       # first sample: no delta
    for _ in range(10):
        h.observe(5.0)                           # 10 bad
    edges = tick(5.0)
    # long: 10 new / 10 over -> frac 1.0 -> burn 10 >= 5; short: same
    assert edges == [('slo-burn', 'firing')]
    st = mgr.state()[0]
    assert st['value'] == pytest.approx(10.0)    # min(long, short) burn
    # recovery: a flood of good observations dilutes both windows
    for _ in range(80):
        h.observe(0.05)
    edges = tick(12.0)
    # short window (10s) covers [2, 12] -> only the t=12 delta: 80 new,
    # 0 over -> burn 0 < 5 -> resolved (resolve_after=0)
    assert edges == [('slo-burn', 'resolved')]
    dumps = glob.glob(os.path.join(str(tmp_path),
                                   'flight_alert_firing_*.json'))
    assert len(dumps) == 1
    payload = json.load(open(dumps[0]))
    assert payload['reason'] == 'alert_firing'


def test_burn_rate_needs_both_windows():
    """An old burst keeps the long window hot while the short window is
    clean: must NOT fire (the incident is over — SRE workbook rule)."""
    reg = MetricRegistry()
    h = reg.histogram('lat_seconds', 'lat', buckets=(0.1, 1.0))
    rule = BurnRateRule('slo-burn', 'lat_seconds', slo_le=0.1,
                        objective=0.9, windows=((600.0, 10.0, 5.0),))
    mgr, tick = _mgr([rule], reg)
    for _ in range(10):
        h.observe(5.0)                           # burst, all bad
    assert tick(0.0) == []
    tick(1.0)                                    # ring: burst visible
    for _ in range(10):
        h.observe(0.05)                          # recovery, all good
    edges = tick(100.0)
    # long (600s): 20 obs, 10 over -> burn 5.0 >= 5;
    # short (10s): only the recovery delta -> 10 obs, 0 over -> burn 0
    assert edges == []
    assert mgr.firing() == []


def test_federated_burn_source_reads_merged_view():
    reg_a, reg_b = MetricRegistry(), MetricRegistry()
    for r in (reg_a, reg_b):
        r.histogram('gateway_ttft_seconds', 'ttft', buckets=(0.1, 1.0))
    fc = FleetCollector(registry=MetricRegistry(), clock=time.monotonic)
    fc.add_target('gw-a', registry=reg_a)
    fc.add_target('gw-b', registry=reg_b)
    burn = federated_burn_source(fc, slo_ttft_s=0.1,
                                 window_s=30.0)
    fc.scrape()
    assert burn(0.0) == 0.0
    # replica B alone burns the fleet SLO; a local-only autoscaler on A
    # would never see it
    for _ in range(10):
        reg_a.get('gateway_ttft_seconds').observe(0.05)
        reg_b.get('gateway_ttft_seconds').observe(5.0)
    fc.scrape()
    assert burn(10.0) == 0.5                     # 20 new, 10 over


# -- gateway wiring ----------------------------------------------------------

class _FakeEngine:
    """The InprocReplica-facing engine surface, no jax: add_request /
    step / scheduler.queue / trace_counts — one deterministic token per
    request per step."""
    num_slots = 4
    spec_k = 0

    def __init__(self):
        self.trace_counts = {}               # nothing left to trace
        self.scheduler = types.SimpleNamespace(queue=[], pending=[])
        self._live = []
        self.metrics = None                  # InprocReplica rebinds

    def rebind_perf(self, registry):
        pass

    def add_request(self, prompt, max_new_tokens=4, **sampling):
        req = types.SimpleNamespace(prompt=prompt, tokens=[],
                                    _n=int(max_new_tokens), done=False)
        self._live.append(req)
        self.scheduler.pending.append(req)
        return req

    def step(self):
        moved = 0
        for req in list(self._live):
            req.tokens.append(len(req.tokens))
            moved += 1
            if len(req.tokens) >= req._n:
                req.done = True
                self._live.remove(req)
                self.scheduler.pending.remove(req)
        return moved


def test_gateway_attach_fleet_and_federated_burn_override():
    from paddle_tpu.serving.gateway import AutoscalePolicy, ServingGateway
    gw = ServingGateway(_FakeEngine, replicas=2,
                        registry=MetricRegistry(),
                        autoscaler=AutoscalePolicy(
                            slo_ttft_s=0.1, max_replicas=4,
                            sustain_s=0.0, cooldown_s=0.0))
    fc = FleetCollector(registry=MetricRegistry(), clock=time.monotonic)
    gw.attach_fleet(fc)
    assert sorted(t.instance for t in fc.targets()) \
        == ['gw-replica-0', 'gw-replica-1']
    reqs = [gw.submit([1, 2], max_new_tokens=3) for _ in range(4)]
    gw.run()
    assert all(r.done and r.tokens == [0, 1, 2] for r in reqs)
    fc.scrape()
    merged = fc.merged()
    # per-replica serving gauges survive the merge under `instance`
    assert 'serving_queue_depth' in merged
    insts = {s['labels']['instance']
             for s in merged['serving_queue_depth']['samples']}
    assert insts == {'gw-replica-0', 'gw-replica-1'}

    # the autoscaler reads the FEDERATED burn when overridden
    gw.burn_source = lambda now: 0.9
    decision = gw.autoscale_tick(now=100.0)
    assert decision.delta == 1               # burn 0.9 >= threshold 0.5
    assert gw.registry.get('gateway_slo_burn_rate').value() == 0.9
    # ...and the scaled-up replica self-registered as a target
    assert sorted(t.instance for t in fc.targets()) \
        == ['gw-replica-0', 'gw-replica-1', 'gw-replica-2']
