"""Fleet FS utils: LocalFS behavior + the DECLARED HDFS shim (VERDICT r3
item 9 — it must announce itself and refuse hdfs:// URIs, not silently
treat them as local paths)."""
import warnings

import pytest

from paddle_tpu.distributed.fleet.utils import HDFSClient, LocalFS


def test_localfs_roundtrip(tmp_path):
    fs = LocalFS()
    p = tmp_path / 'a.txt'
    fs.touch(str(p))
    assert fs.is_exist(str(p)) and fs.is_file(str(p))
    fs.mv(str(p), str(tmp_path / 'b.txt'))
    assert fs.is_exist(str(tmp_path / 'b.txt'))
    fs.delete(str(tmp_path / 'b.txt'))
    assert not fs.is_exist(str(tmp_path / 'b.txt'))


def test_hdfs_client_declares_itself_and_refuses_hdfs_uris(tmp_path):
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        client = HDFSClient(hadoop_home='/opt/hadoop', configs={})
    assert any('LocalFS-backed' in str(x.message) for x in w)

    # local paths still work through the LocalFS API
    p = tmp_path / 'c.txt'
    client.touch(str(p))
    assert client.is_exist(str(p))

    with pytest.raises(NotImplementedError, match='hdfs'):
        client.is_exist('hdfs://namenode:9000/user/data')
    with pytest.raises(NotImplementedError, match='hdfs'):
        client.download('hdfs://nn/user/x', str(tmp_path / 'x'))
