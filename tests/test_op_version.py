"""Op-version compatibility registry (VERDICT r3 missing #6; reference:
framework/op_version_registry.h): artifacts embed per-op semantic
versions; loads refuse newer-than-runtime ops and warn on older."""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import op_version as opv
from paddle_tpu.jit import InputSpec


def test_registry_defaults_and_snapshot():
    assert opv.get_op_version('some_unregistered_op') == 1
    snap = opv.snapshot()
    assert snap.get('flash_attention', 0) >= 2
    opv.check_compatible(snap)  # identity snapshot always compatible


def test_newer_saved_version_refused_older_warns():
    snap = {'flash_attention': opv.get_op_version('flash_attention') + 1}
    with pytest.raises(opv.OpVersionError, match='newer|upgrade'):
        opv.check_compatible(snap, artifact='m')
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        opv.check_compatible({'flash_attention': 1}, artifact='m')
    assert any('version' in str(x.message) for x in w)


class _M(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.lin = paddle.nn.Linear(4, 2)

    def forward(self, x):
        return self.lin(x)


def test_jit_artifact_embeds_and_checks_op_versions(tmp_path):
    m = _M()
    path = str(tmp_path / 'm')
    paddle.jit.save(m, path,
                    input_spec=[InputSpec([None, 4], 'float32', 'x')])

    import pickle
    with open(path + '.pdmodel', 'rb') as f:
        payload = pickle.load(f)
    assert payload['meta']['op_versions'] == opv.snapshot()

    loaded = paddle.jit.load(path)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    np.testing.assert_allclose(loaded(x).numpy(), m(x).numpy(), rtol=1e-6)

    # tamper: claim a future op version -> load must refuse
    payload['meta']['op_versions'] = dict(
        payload['meta']['op_versions'],
        flash_attention=opv.get_op_version('flash_attention') + 5)
    with open(path + '.pdmodel', 'wb') as f:
        pickle.dump(payload, f, protocol=4)
    with pytest.raises(opv.OpVersionError):
        paddle.jit.load(path)
