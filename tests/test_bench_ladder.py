"""bench.py self-tuning replay: the driver's end-of-round bench must
replay the best warmer-measured config verbatim (capture row -> child
env, EVERY knob pinned both ways so stray operator env can't leak),
ranked in the 6N convention with suspect samples excluded, restricted
to the headline seq-512 workload, and deduplicated against the fixed
ladder."""
import importlib.util
import json
import os


def _bench():
    spec = importlib.util.spec_from_file_location(
        'bench_mod', os.path.join(os.path.dirname(__file__), os.pardir,
                                  'bench.py'))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_capture_replay_env_fully_pinned():
    b = _bench()
    env = b._capture_replay_env({
        'scan_steps': 8, 'fused_ce': True, 'flash_in_program': True,
        'qkv_split': 'last', 'attn_impl': 'auto', 'fused_ce_chunk': 8192,
        'flash_block_q': 128, 'flash_block_k': 128,
        'flash_block_q_bwd': 256, 'flash_block_k_bwd': 128,
        'flash_block_q_long': 512, 'flash_block_k_long': 2048,
        'flash_long_seq': 2048, 'batch': 32, 'seq': 512})
    assert env['PADDLE_TPU_BENCH_SCAN_STEPS'] == '8'
    assert env['PADDLE_TPU_FUSED_CE'] == '1'
    assert env['PADDLE_TPU_QKV_SPLIT'] == 'last'
    assert env['PADDLE_TPU_FUSED_CE_CHUNK'] == '8192'
    assert env['PADDLE_TPU_FLASH_BLOCK_Q'] == '128'
    assert env['PADDLE_TPU_FLASH_BLOCK_K'] == '128'
    assert env['PADDLE_TPU_FLASH_BLOCK_Q_BWD'] == '256'
    assert env['PADDLE_TPU_FLASH_BLOCK_K_BWD'] == '128'
    assert env['PADDLE_TPU_FLASH_BLOCK_Q_LONG'] == '512'
    assert env['PADDLE_TPU_FLASH_BLOCK_K_LONG'] == '2048'
    assert env['PADDLE_TPU_FLASH_LONG_SEQ'] == '2048'
    # flash ran: disable pinned OFF and strict pinned ON — an inherited
    # FLASH_DISABLE=1 or STRICT=0 must not survive the replay
    assert env['PADDLE_TPU_FLASH_DISABLE'] == '0'
    assert env['PADDLE_TPU_FLASH_STRICT'] == '1'
    assert env['PADDLE_TPU_BENCH_BATCH'] == '32'
    assert env['PADDLE_TPU_BENCH_SEQ'] == '512'

    env = b._capture_replay_env({
        'scan_steps': 0, 'fused_ce': False, 'flash_in_program': False,
        'attn_impl': 'blockwise', 'blockwise_block': 128,
        'batch': 32, 'seq': 512})
    assert env['PADDLE_TPU_FLASH_DISABLE'] == '1'
    assert env['PADDLE_TPU_FLASH_STRICT'] == '0'
    assert env['PADDLE_TPU_FUSED_CE'] == '0'
    assert env['PADDLE_TPU_ATTN_IMPL'] == 'blockwise'
    assert env['PADDLE_TPU_BLOCKWISE_BLOCK'] == '128'
    assert env['PADDLE_TPU_BENCH_SCAN_STEPS'] == '0'
    # knobs the row never recorded still get pinned — at the ERA
    # values (this row has no block fields, so it predates them:
    # 256/512 was that code's default)
    assert env['PADDLE_TPU_QKV_SPLIT'] == 'headaxis'
    assert env['PADDLE_TPU_FLASH_BLOCK_Q'] == '256'
    assert env['PADDLE_TPU_FLASH_BLOCK_Q_BWD'] == '256'
    assert env['PADDLE_TPU_FLASH_BLOCK_K_LONG'] == '512'


def test_capture_replay_env_legacy_rows_pin_era_values():
    b = _bench()
    env = b._capture_replay_env({
        'scan_steps': 8, 'fused_ce': False, 'flash_in_program': True,
        'batch': 32, 'seq': 512})  # r4-era row: block knobs predate it
    assert env['PADDLE_TPU_FLASH_BLOCK_Q'] == '256'
    assert env['PADDLE_TPU_FLASH_BLOCK_K'] == '512'
    assert env['PADDLE_TPU_FLASH_BLOCK_Q_BWD'] == '256'
    assert env['PADDLE_TPU_FLASH_BLOCK_Q_LONG'] == '256'
    assert env['PADDLE_TPU_FLASH_BLOCK_K_LONG'] == '512'
    # legacy router was '> 4096', i.e. today's '>= 4097'
    assert env['PADDLE_TPU_FLASH_LONG_SEQ'] == '4097'
    # the fused backward kernel postdates this row: two-pass pinned
    assert env['PADDLE_TPU_FLASH_FUSED_BWD'] == '0'


def test_effective_env_dedup():
    b = _bench()
    # the fixed ladder's head rung and a replay of a capture it produced
    # must compare EQUAL as effective configs (the driver must not burn
    # two child timeouts on one config)
    ladder_head = {'PADDLE_TPU_BENCH_SCAN_STEPS': '8'}
    replay = b._capture_replay_env({
        'scan_steps': 8, 'fused_ce': True, 'flash_in_program': True,
        'qkv_split': 'headaxis', 'attn_impl': 'auto',
        'fused_ce_chunk': 4096, 'flash_block_q': 512,
        'flash_block_k': 512, 'flash_block_q_bwd': 512,
        'flash_block_k_bwd': 512, 'flash_block_q_long': 512,
        'flash_block_k_long': 1024, 'flash_long_seq': 4096,
        'flash_fused_bwd': True, 'batch': 32, 'seq': 512})
    assert b._effective_env(ladder_head) == b._effective_env(replay)
    # but a genuinely different config (qkv last) stays distinct
    replay2 = dict(replay, PADDLE_TPU_QKV_SPLIT='last')
    assert b._effective_env(ladder_head) != b._effective_env(replay2)


def test_best_capture_ranking_suspect_and_headline(tmp_path, monkeypatch):
    b = _bench()
    log = tmp_path / 'inwindow.jsonl'
    rows = [
        # higher mfu but suspect: must lose
        {'platform': 'tpu', 'mfu_6n': 0.52, 'suspect': True, 'seq': 512,
         'label': 'throttle-adjacent'},
        # higher mfu but long-context: must lose the HEADLINE ranking
        {'platform': 'tpu', 'mfu_6n': 0.60, 'seq': 8192, 'label': 'long'},
        {'platform': 'tpu', 'mfu_6n': 0.42, 'seq': 512, 'label': 'good'},
        {'platform': 'cpu', 'mfu_6n': 0.9, 'degraded': True},
        {'platform': 'tpu', 'mfu_6n': 0.40, 'seq': 512, 'label': 'worse'},
    ]
    log.write_text('\n'.join(json.dumps(r) for r in rows) + '\n')
    monkeypatch.setenv('PADDLE_TPU_BENCH_INWINDOW_LOG', str(log))
    assert b._best_capture(headline_seq=512)['label'] == 'good'
    # the unfiltered rank (the attached-evidence rule) may pick the
    # long-context row — it carries its own batch/seq labeling
    assert b._best_capture()['label'] == 'long'


def test_best_capture_missing_log(monkeypatch, tmp_path):
    b = _bench()
    monkeypatch.setenv('PADDLE_TPU_BENCH_INWINDOW_LOG',
                       str(tmp_path / 'nope.jsonl'))
    assert b._best_capture() is None


def test_replay_plus_head_rung_reports_the_faster(tmp_path, monkeypatch,
                                                  capsys):
    """When the fixed ladder's head config differs from the best logged
    capture (a newer optimum landed between windows), the driver must run
    BOTH and report the faster — a stale replay may not preempt it."""
    b = _bench()
    log = tmp_path / 'inwindow.jsonl'
    log.write_text(json.dumps({
        'platform': 'tpu', 'mfu_6n': 0.50, 'seq': 512, 'batch': 32,
        'scan_steps': 8, 'fused_ce': True, 'flash_in_program': True,
        'qkv_split': 'last', 'attn_impl': 'auto', 'fused_ce_chunk': 4096,
        'flash_block_q': 512, 'flash_block_k': 512,
        'label': 'old_best'}) + '\n')  # legacy row: two-pass bwd pinned
    monkeypatch.setenv('PADDLE_TPU_BENCH_INWINDOW_LOG', str(log))

    spawned = []

    def fake_spawn(extra_env=None, timeout=None):
        spawned.append(dict(extra_env or {}))
        if extra_env and extra_env.get('PADDLE_TPU_FLASH_FUSED_BWD') == '0':
            return {'mfu_6n': 0.50, 'metric': 'm', 'value': 1.0}, None
        return {'mfu_6n': 0.53, 'metric': 'm', 'value': 2.0}, None

    monkeypatch.setattr(b, '_spawn_child', fake_spawn)
    monkeypatch.setattr(b, '_probe_backend', lambda: ('tpu', None))
    monkeypatch.setattr(b, '_probe_pallas', lambda: (True, None))
    monkeypatch.setenv('PADDLE_TPU_BENCH_FAST_PROBE', '1')
    b._orchestrate([])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    res = json.loads(out)
    # two children ran (replay + head) and the faster one was reported
    assert len(spawned) == 2
    assert res['mfu_6n'] == 0.53
    assert res['retry'] == 'fused_flash_scan8_qkvlast'


def test_probe_fail_fast_short_then_one_long_retry(monkeypatch):
    """A hung backend costs one SHORT probe plus exactly ONE long retry
    (not three serial full-length timeouts), and a healthy backend is
    decided by the short probe alone."""
    b = _bench()
    calls = []

    def fake_once(timeout):
        calls.append(timeout)
        return None, 'backend probe hung (>%ds)' % timeout

    monkeypatch.setattr(b, '_probe_backend_once', fake_once)
    monkeypatch.delenv('PADDLE_TPU_BENCH_FAST_PROBE', raising=False)
    platform, err = b._probe_backend()
    assert platform is None
    assert calls == [30, 240]            # short first, one long retry
    assert 'short probe' in err and 'long retry' in err

    # healthy backend: the short probe decides, no retry
    calls.clear()
    monkeypatch.setattr(b, '_probe_backend_once',
                        lambda t: (calls.append(t), ('tpu', None))[1])
    assert b._probe_backend() == ('tpu', None)
    assert calls == [30]

    # the retry rescues a slow-but-alive tunnel, reporting success clean
    calls.clear()

    def flaky_once(timeout):
        calls.append(timeout)
        if timeout == 30:
            return None, 'backend probe hung (>30s)'
        return 'tpu', None

    monkeypatch.setattr(b, '_probe_backend_once', flaky_once)
    assert b._probe_backend() == ('tpu', None)
    assert calls == [30, 240]


def test_probe_fast_mode_and_explicit_timeout(monkeypatch):
    """FAST_PROBE=1 keeps its semantics (single short attempt, no long
    retry — CI must not stall 240s) and an explicit timeout is a single
    bounded attempt at exactly that bound."""
    b = _bench()
    calls = []

    def fake_once(timeout):
        calls.append(timeout)
        return None, 'down'

    monkeypatch.setattr(b, '_probe_backend_once', fake_once)
    monkeypatch.setenv('PADDLE_TPU_BENCH_FAST_PROBE', '1')
    assert b._probe_backend() == (None, 'down')
    assert calls == [30]

    calls.clear()
    monkeypatch.delenv('PADDLE_TPU_BENCH_FAST_PROBE', raising=False)
    monkeypatch.setenv('PADDLE_TPU_BENCH_PROBE_SHORT_TIMEOUT', '5')
    monkeypatch.setenv('PADDLE_TPU_BENCH_PROBE_TIMEOUT', '60')
    b._probe_backend()
    assert calls == [5, 60]              # both knobs respected

    calls.clear()
    assert b._probe_backend(timeout=7) == (None, 'down')
    assert calls == [7]                  # explicit bound: one attempt
